"""Load-factor policy: grow-and-rehash with exact counts across growth.

The reference's Redis table never fills (Redis grows; OOM is fatal,
/root/reference/storage/rediscache.go:57-65). The HBM table DOES fill,
and insert cost rises with load factor, so the aggregator grows to the
next power of two at ``grow_at`` load (re-hashing every occupied row —
home slots and probe chains depend on capacity) and spills to the exact
host lane past ``max_capacity``. Either way the drained counts must
stay exact.
"""

import datetime

import jax
import numpy as np
from jax.sharding import Mesh

from ct_mapreduce_tpu.agg import TpuAggregator
from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator
from ct_mapreduce_tpu.telemetry import metrics as tmetrics

from certgen import make_cert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2024, 6, 1, tzinfo=UTC)


def leaf(serial, issuer_cn="Grow CA", **kw):
    kw.setdefault("is_ca", False)
    kw.setdefault("subject_cn", f"g{serial}.example.com")
    return make_cert(serial=serial, issuer_cn=issuer_cn, **kw)


def entries(n, issuer_cn="Grow CA"):
    ca = make_cert(issuer_cn=issuer_cn)
    return [(leaf(7000 + i, issuer_cn=issuer_cn), ca) for i in range(n)]


def test_auto_grow_preserves_dedup_and_counts():
    sink = tmetrics.InMemSink()
    tmetrics.set_sink(sink)
    a = TpuAggregator(capacity=256, batch_size=64, now=NOW,
                      grow_at=0.6, max_capacity=(1 << 12) + 7)
    # A ragged ceiling rounds DOWN to a power of two, then to the
    # layout-achievable capacity (bucket: 24·2^k — the r05 grow-
    # livelock fix; see tests/test_growth_ceiling.py).
    assert a.max_capacity == a._layout_capacity_floor(1 << 12)
    assert a.max_capacity <= 1 << 12
    start_cap = a.capacity  # layout may round the requested 256 up
    assert 300 > a.grow_at * start_cap  # growth must trigger below
    ents = entries(300)
    res = a.ingest(ents)
    assert res.was_unknown.all()
    # The policy grew the table (300 uniques ≫ 0.6 × start) and kept
    # it under the ceiling (bucket layouts round to whole buckets, so
    # the exact value is layout-dependent).
    assert start_cap < a.capacity <= 1 << 12
    # Growth must never cost probe overflow into the host lane (every
    # entry here is device-sized, so ANY host-lane traffic would mean
    # spilled probes).
    assert a.metrics["host_lane"] == 0
    assert a.metrics["overflow"] == 0
    # Device membership survived the re-hash: everything is now known.
    res2 = a.ingest(ents)
    assert not res2.was_unknown.any()
    snap = a.drain()
    assert snap.total == 300
    # The gauge tracks fill/capacity.
    load = sink.snapshot()["gauges"]["aggregator.table_load"]
    assert 0 < load <= a.grow_at + 64 / a.capacity
    tmetrics.set_sink(tmetrics.InMemSink())  # reset global for other tests


def test_grow_disabled_spills_to_host_lane_exactly():
    a = TpuAggregator(capacity=256, batch_size=64, now=NOW, grow_at=0)
    start_cap = a.capacity
    n = start_cap + 116  # strictly more uniques than the table holds
    ents = entries(n, issuer_cn="NoGrow CA")
    res = a.ingest(ents)
    assert a.capacity == start_cap  # never grew
    assert res.was_unknown.all()  # host lane is exact for spilled lanes
    assert a.metrics["host_lane"] > 0  # something really spilled
    assert a.metrics["overflow"] > 0  # ... and the metric names the cause
    res2 = a.ingest(ents)
    assert not res2.was_unknown.any()
    assert a.drain().total == n


def test_max_capacity_caps_growth():
    a = TpuAggregator(capacity=256, batch_size=64, now=NOW,
                      grow_at=0.6, max_capacity=256)
    start_cap = a.capacity  # >= the requested 256 = the growth ceiling
    ents = entries(300, issuer_cn="Capped CA")
    a.ingest(ents)
    assert a.capacity == start_cap  # the cap held
    assert a.drain().total == 300


def test_explicit_grow_rehashes_members():
    a = TpuAggregator(capacity=1 << 10, batch_size=64, now=NOW)
    ents = entries(100, issuer_cn="Explicit CA")
    a.ingest(ents)
    a.grow(1 << 12)
    assert a.capacity >= 1 << 12  # layouts may round up, never down
    res = a.ingest(ents)
    assert not res.was_unknown.any()
    assert a.drain().total == 100


def test_sharded_grow_on_virtual_mesh():
    devs = np.array(jax.devices()[:8])
    assert devs.size == 8, "conftest must provide 8 virtual devices"
    mesh = Mesh(devs, ("shard",))
    a = ShardedAggregator(mesh, capacity=512, batch_size=64, now=NOW,
                          grow_at=0.6, max_capacity=1 << 13)
    ents = entries(400, issuer_cn="Sharded Grow CA")  # > 0.6 × 512
    res = a.ingest(ents)
    assert res.was_unknown.all()
    assert a.capacity > 512
    res2 = a.ingest(ents)
    assert not res2.was_unknown.any()
    assert a.drain().total == 400


def test_growth_survives_checkpoint_resume():
    """Grow → snapshot → restore into a SMALLER-configured aggregator →
    continue ingesting until it grows again: counts stay exact across
    the whole life cycle (the checkpoint carries the grown capacity;
    the restored table must keep growing from there)."""
    import os
    import tempfile

    a = TpuAggregator(capacity=256, batch_size=64, now=NOW,
                      grow_at=0.6, max_capacity=1 << 13)
    first = entries(300, issuer_cn="Ckpt Grow CA")
    a.ingest(first)
    grown_cap = a.capacity
    assert grown_cap > 256
    fd, path = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    try:
        a.save_checkpoint(path)

        b = TpuAggregator(capacity=256, batch_size=64, now=NOW,
                          grow_at=0.6, max_capacity=1 << 13)
        b.load_checkpoint(path)
        assert b.capacity == grown_cap  # checkpoint capacity wins
        # Everything from before the restart is known.
        res = b.ingest(first)
        assert not res.was_unknown.any()
        # Keep going until growth fires again on the restored table.
        second = [(leaf(9000 + i, issuer_cn="Ckpt Grow CA"),
                   first[0][1]) for i in range(400)]
        res2 = b.ingest(second)
        assert res2.was_unknown.all()
        assert b.capacity > grown_cap
        assert b.drain().total == 700
    finally:
        os.unlink(path)
