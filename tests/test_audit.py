"""Real-log audit subsystem (round 24): RFC 6962 §3.2 TBS
reconstruction (KAT + poison-placement edges + mutation fuzz),
production log-list loading/routing, the quarantine lane's exclusion
property, and the recorded-shard driver feeding every existing
downstream surface.

The reconstruction contract under test: the digest convention is the
REAL precert signing digest — TBSCertificate with every SCT-list and
poison extension stripped and outer lengths re-encoded — computed
bit-identically by the native streaming scanner and the pure-python
mirror. Any lane where they disagree is quarantined and provably
excluded from aggregates (counts identical with the lane spooled or
the entry dropped).
"""

import base64
import datetime
import hashlib
import json
import os

import numpy as np
import pytest

from ct_mapreduce_tpu.audit import driver as drvlib
from ct_mapreduce_tpu.audit import fixture as fxlib
from ct_mapreduce_tpu.audit import loglist as loglistlib
from ct_mapreduce_tpu.audit import quarantine as quarlib
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.verify import host as vhost
from ct_mapreduce_tpu.verify import sct as sctlib

from tests import certgen

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)

TS_KAT = 1_710_000_000_000


def _native_sct_available() -> bool:
    try:
        from ct_mapreduce_tpu.native import load as load_native

        if os.environ.get("CTMR_NATIVE", "1") == "0":
            return False
        lib = load_native()
        return lib is not None and getattr(lib, "has_sct", False)
    except Exception:
        return False


needs_native = pytest.mark.skipif(
    not _native_sct_available(),
    reason="native SCT extractor unavailable")


# -- DER surgery helpers -----------------------------------------------------

# A poison extension (RFC 6962 §3.1): critical, extnValue = DER NULL.
POISON_EXT = sctlib._wrap_tlv(
    0x30,
    sctlib._wrap_tlv(0x06, sctlib.POISON_OID)
    + b"\x01\x01\xff"
    + sctlib._wrap_tlv(0x04, b"\x05\x00"),
)


def _with_exts(der: bytes, fn) -> bytes:
    """Rebuild ``der`` with its [3] extension list transformed by
    ``fn(list[raw_ext_tlv]) -> list[raw_ext_tlv]`` (empty result omits
    [3] entirely). Signature bytes ride along unchanged — reconstruction
    never looks at them."""
    t = sctlib._tlv(der, 0, len(der))
    _, cert_off, cert_len = t
    tbs = sctlib._tlv(der, cert_off, cert_off + cert_len)
    tbs_off, tbs_len = tbs[1], tbs[2]
    tbs_end = tbs_off + tbs_len
    rest = der[tbs_end:]
    off = tbs_off
    t2 = sctlib._tlv(der, off, tbs_end)
    if t2[0] == 0xA0:
        off = t2[1] + t2[2]
    for _ in range(6):
        t2 = sctlib._tlv(der, off, tbs_end)
        off = t2[1] + t2[2]
    head = der[tbs_off:off]
    exts: list[bytes] = []
    while off < tbs_end:
        t2 = sctlib._tlv(der, off, tbs_end)
        if t2[0] == 0xA3:
            seq = sctlib._tlv(der, t2[1], t2[1] + t2[2])
            p, p_end = seq[1], seq[1] + seq[2]
            while p < p_end:
                e = sctlib._tlv(der, p, p_end)
                exts.append(der[p:e[1] + e[2]])
                p = e[1] + e[2]
            off = t2[1] + t2[2]
            break
        head += der[off:t2[1] + t2[2]]
        off = t2[1] + t2[2]
    head += der[off:tbs_end]
    new = list(fn(exts))
    body = head
    if new:
        body += sctlib._wrap_tlv(0xA3, sctlib._wrap_tlv(0x30, b"".join(new)))
    return sctlib._wrap_tlv(
        0x30, sctlib._wrap_tlv(0x30, body) + rest)


def _kat_materials():
    issuer = certgen.make_cert(
        serial=1, issuer_cn="KAT CA", is_ca=True, not_after=FUTURE)
    leaf = certgen.make_cert(
        serial=7, issuer_cn="KAT CA", subject_cn="kat.example",
        is_ca=False, not_after=FUTURE)
    signer = loglistlib.adopt_production_id(
        sctlib.EcSctSigner("audit-kat"))
    der = sctlib.attach_sct(leaf, signer, TS_KAT, issuer_der=issuer)
    return issuer, leaf, signer, der


# -- §3.2 TBS reconstruction -------------------------------------------------


def test_reconstruct_tbs_kat():
    """Known-answer pin for the reconstruction and the full signing
    digest: the fixture generators are RNG-free, so these values are
    stable across processes and boxes. A change here is a digest-
    convention change and must be deliberate (MIGRATING.md)."""
    issuer, leaf, _signer, der = _kat_materials()
    assert hashlib.sha256(der).hexdigest() == (
        "9be022a7e05cd26c7e235e761a4144905e9bc226"
        "abe832cb6b2dfcd488dc1f2a")
    tbs = sctlib.reconstruct_precert_tbs(der)
    assert hashlib.sha256(tbs).hexdigest() == (
        "6dc9519f8e53f57e38d6281fc50189b11bc08453"
        "1dbd684bc01d9d57bb9ff8cc")
    ikh = sctlib.issuer_key_hash_of(issuer)
    assert ikh.hex() == (
        "de101f1aaab1fc2e96277e9d0dbcd8b5f7046d8f"
        "90bccb328889c3accfd6187f")
    digest = sctlib.sct_digest(der, 0, 0, TS_KAT, b"", ikh)
    assert digest.hex() == (
        "85cc8981d21673ad6c1820e1421d45a5bd42ed12"
        "ae93262272d95975d7212cbf")
    # The reconstruction of the ORIGINAL (no SCT) leaf is identical —
    # stripping the embedded list recovers what the log signed.
    assert tbs == sctlib.reconstruct_precert_tbs(leaf)


def test_digest_structure_independently_rederived():
    """The §3.2 digitally-signed payload, rebuilt by hand from its
    documented layout, hashes to what sct_digest returns."""
    issuer, _leaf, _signer, der = _kat_materials()
    ikh = sctlib.issuer_key_hash_of(issuer)
    tbs = sctlib.reconstruct_precert_tbs(der)
    payload = (
        b"\x00"                       # version v1
        + b"\x00"                     # signature_type certificate_timestamp
        + TS_KAT.to_bytes(8, "big")   # timestamp
        + b"\x00\x01"                 # entry_type precert_entry
        + ikh                         # issuer_key_hash
        + len(tbs).to_bytes(3, "big") + tbs   # opaque TBSCertificate<1..2^24-1>
        + b"\x00\x00"                 # CtExtensions (empty)
    )
    assert hashlib.sha256(payload).digest() == sctlib.sct_digest(
        der, 0, 0, TS_KAT, b"", ikh)
    # issuer_key_hash really is SHA-256 over the issuer's SPKI TLV.
    win = sctlib.find_spki(issuer)
    assert ikh == hashlib.sha256(issuer[win[0]:win[1]]).digest()


def test_reconstruct_strips_poison_at_every_placement():
    """Poison extensions are stripped wherever they sit: first, every
    interior slot, last, and multiply — the reconstruction always
    equals the SCT-certificate's own reconstruction."""
    _issuer, _leaf, _signer, der = _kat_materials()
    expected = sctlib.reconstruct_precert_tbs(der)
    exts: list = []
    _with_exts(der, lambda e: exts.extend(e) or e)
    assert len(exts) >= 2  # base extensions + the SCT list
    for k in range(len(exts) + 1):
        poisoned = _with_exts(
            der, lambda e, k=k: e[:k] + [POISON_EXT] + e[k:])
        assert sctlib.reconstruct_precert_tbs(poisoned) == expected, k
    # Multiple poisons, both edges at once.
    double = _with_exts(
        der, lambda e: [POISON_EXT] + e + [POISON_EXT])
    assert sctlib.reconstruct_precert_tbs(double) == expected


def test_reconstruct_omits_empty_extension_list():
    """When stripping leaves no extensions, [3] is omitted entirely
    (§3.2: 'the Precertificate's TBSCertificate ... without the
    poison extension')."""
    _issuer, leaf, signer, _der = _kat_materials()
    bare = _with_exts(leaf, lambda e: [])  # no [3] at all
    assert sctlib.find_sct_extension(bare) is None
    only_poison = _with_exts(bare, lambda e: [POISON_EXT])
    tbs = sctlib.reconstruct_precert_tbs(only_poison)
    assert tbs == sctlib.reconstruct_precert_tbs(bare)
    # ... and the stripped TBS carries no [3] element at the tail.
    t = sctlib._tlv(tbs, 0, len(tbs))
    content = tbs[t[1]:t[1] + t[2]]
    assert b"\xa3" not in content[-4:]
    # SCT as the ONLY extension: same omission.
    only_sct = sctlib.attach_sct(bare, signer, TS_KAT)
    assert sctlib.reconstruct_precert_tbs(only_sct) \
        == sctlib.reconstruct_precert_tbs(bare)


def _pack_rows(ders: list) -> tuple:
    pad = max(len(d) for d in ders)
    data = np.zeros((len(ders), pad), np.uint8)
    length = np.zeros((len(ders),), np.int32)
    for j, d in enumerate(ders):
        data[j, :len(d)] = np.frombuffer(d, np.uint8)
        length[j] = len(d)
    return data, length


def _placement_variants() -> list:
    issuer, leaf, signer, der = _kat_materials()
    variants = [der]
    exts = []
    _with_exts(der, lambda e: exts.extend(e) or e)
    for k in range(len(exts) + 1):
        variants.append(_with_exts(
            der, lambda e, k=k: e[:k] + [POISON_EXT] + e[k:]))
    variants.append(_with_exts(
        der, lambda e: [POISON_EXT] + e + [POISON_EXT]))
    bare = _with_exts(leaf, lambda e: [])
    variants.append(sctlib.attach_sct(bare, signer, TS_KAT))
    variants.append(_with_exts(bare, lambda e: [POISON_EXT]))
    variants.append(leaf)  # no SCT at all
    return variants


@needs_native
def test_native_mirror_bit_identical_on_poison_edges():
    """The acceptance pin: the native streaming scanner and the
    Python mirror produce byte-identical extractions (digest included)
    across every poison-placement edge."""
    from ct_mapreduce_tpu.native import leafpack

    issuer, _leaf, _signer, _der = _kat_materials()
    variants = _placement_variants()
    data, length = _pack_rows(variants)
    ikh = np.tile(
        np.frombuffer(sctlib.issuer_key_hash_of(issuer), np.uint8),
        (len(variants), 1))
    native = leafpack.extract_scts(data, length, issuer_key_hash=ikh)
    mirror = sctlib.extract_scts_np(data, length, issuer_key_hash=ikh)
    chk = quarlib.compare_extractions(native, mirror)
    assert chk.measured and chk.count == 0, chk.reasons
    # The SCT-bearing variants all carry the SAME digest (poison and
    # placement never change what the log signed) — and it is the KAT.
    ok_rows = np.flatnonzero(mirror.ok == sctlib.SCT_OK)
    assert len(ok_rows) >= len(variants) - 3
    kat = sctlib.sct_digest(variants[0], 0, 0, TS_KAT, b"",
                            sctlib.issuer_key_hash_of(issuer))
    for j in ok_rows[:-1]:
        assert bytes(mirror.digest[j]) == kat, int(j)


@needs_native
def test_mutation_fuzz_native_mirror_agreement():
    """Byte-flip fuzz over the placement variants: whatever each
    extractor decides (accept, fallback, reject), they must decide it
    IDENTICALLY — the quarantine lane's steady-state-empty claim."""
    from ct_mapreduce_tpu.native import leafpack

    rng = np.random.default_rng(20260807)
    bases = _placement_variants()
    mutants = []
    for _ in range(240):
        base = bytearray(bases[int(rng.integers(len(bases)))])
        for _ in range(int(rng.integers(1, 4))):
            base[int(rng.integers(len(base)))] ^= int(
                rng.integers(1, 256))
        mutants.append(bytes(base))
    data, length = _pack_rows(mutants)
    native = leafpack.extract_scts(data, length)
    mirror = sctlib.extract_scts_np(data, length)
    chk = quarlib.compare_extractions(native, mirror)
    assert chk.measured and chk.count == 0, chk.reasons


# -- log-list schema ---------------------------------------------------------


def _fixture_list():
    signers = fxlib.fixture_signers()
    return signers, loglistlib.parse_log_list(
        fxlib.fixture_log_list_doc(signers))


def test_loglist_parses_production_shape():
    signers, ll = _fixture_list()
    assert len(ll) == 3  # p256 + p384(retired) + rsa; unknown unlisted
    assert ll.version == "3.99"
    p256 = ll.shards[signers["p256"].log_id]
    assert p256.state == "usable"
    assert p256.operator == "Audit Fixture Op"
    assert p256.entry["alg"] == "p256"
    assert p256.entry["log_id"] == signers["p256"].log_id.hex()
    assert ll.shards[signers["p384"].log_id].state == "retired"
    assert ll.shards[signers["rsa"].log_id].entry["alg"] == "rsa"
    # The registry the verify lane consumes resolves every listed id.
    reg = ll.registry()
    for name in ("p256", "p384", "rsa"):
        assert reg.get(signers[name].log_id) is not None
    assert reg.get(signers["unknown"].log_id) is None


def test_loglist_temporal_interval_boundaries():
    signers, ll = _fixture_list()
    start = loglistlib.parse_rfc3339_ms(fxlib.INTERVAL[0])
    end = loglistlib.parse_rfc3339_ms(fxlib.INTERVAL[1])
    shard = ll.shards[signers["p256"].log_id]
    assert shard.accepts_at(start)          # start is inclusive
    assert not shard.accepts_at(start - 1)
    assert shard.accepts_at(end - 1)
    assert not shard.accepts_at(end)        # end is exclusive
    v = ll.route(signers["p256"].log_id, end)
    assert v.known and not v.in_interval and not v.retired
    # Unsharded logs accept any timestamp.
    assert ll.route(signers["rsa"].log_id, 1).in_interval
    assert ll.route(signers["rsa"].log_id, 1 << 62).in_interval


def test_loglist_retired_is_verify_but_flag():
    signers, ll = _fixture_list()
    v = ll.route(signers["p384"].log_id, fxlib.TS_IN_INTERVAL)
    assert v.known and v.retired and v.state == "retired"
    # ... and its key still loads into the registry (verifiable).
    assert ll.registry().get(signers["p384"].log_id) is not None


def test_loglist_unknown_log_id():
    signers, ll = _fixture_list()
    v = ll.route(signers["unknown"].log_id, fxlib.TS_IN_INTERVAL)
    assert not v.known and v.state == ""


def test_loglist_key_logid_mismatch_is_loud():
    signers, _ = _fixture_list()
    doc = fxlib.fixture_log_list_doc(signers)
    raw = doc["operators"][0]["logs"][0]
    wrong = hashlib.sha256(b"not the key").digest()
    raw["log_id"] = base64.b64encode(wrong).decode()
    with pytest.raises(ValueError, match="SHA-256"):
        loglistlib.parse_log_list(doc)


def test_loglist_rejected_and_pending_skipped():
    s1 = loglistlib.adopt_production_id(
        sctlib.EcSctSigner("audit-rejected"))
    s2 = loglistlib.adopt_production_id(
        sctlib.EcSctSigner("audit-pending"))
    s3 = loglistlib.adopt_production_id(
        sctlib.EcSctSigner("audit-readonly"))
    doc = loglistlib.fixture_log_list([
        {"signer": s1, "state": "rejected"},
        {"signer": s2, "state": "pending"},
        {"signer": s3, "state": "readonly"},
    ])
    ll = loglistlib.parse_log_list(doc)
    assert len(ll) == 1
    assert ll.route(s3.log_id, 0).known
    assert not ll.route(s1.log_id, 0).known
    assert not ll.route(s2.log_id, 0).known


def test_spki_codec_roundtrip_and_rejection():
    for curve in (vhost.P256, vhost.P384):
        s = sctlib.EcSctSigner(f"audit-spki-{curve.name}", curve)
        spki = loglistlib.spki_from_signer(s)
        key = loglistlib.parse_spki(spki)
        assert key["alg"] == curve.name
        assert int(key["x"], 16) == s.q[0]
        assert int(key["y"], 16) == s.q[1]
    r = sctlib.RsaSctSigner()
    key = loglistlib.parse_spki(loglistlib.spki_from_signer(r))
    assert key == {"alg": "rsa", "n": hex(r.n), "e": hex(r.e)}
    with pytest.raises(ValueError, match="algorithm OID"):
        # Ed25519 OID — present in the wild, not in the CT ecosystem.
        loglistlib.parse_spki(bytes.fromhex(
            "302a300506032b6570032100") + bytes(32))
    with pytest.raises(ValueError):
        loglistlib.parse_spki(b"\x30\x03\x02\x01\x01")


# -- quarantine lane ---------------------------------------------------------


def test_quarantine_spool_file_and_replay(tmp_path):
    spool = quarlib.QuarantineSpool(str(tmp_path / "spool"))
    a, b = b"\x30\x03\x02\x01\x01", b"\x30\x03\x02\x01\x02"
    spool.file(a, index=5, log_url="l", reasons=["digest"])
    spool.file(b, index=6, log_url="l", reasons=["ok", "r"])
    spool.file(a, index=7, log_url="l", reasons=["digest"])  # re-filed
    assert spool.count == 3
    recs = spool.replay()
    assert len(recs) == 2  # content-addressed: same DER, same file
    assert sorted(r["sha256"] for r in recs) == sorted(
        hashlib.sha256(x).hexdigest() for x in (a, b))
    assert set(spool.replay_ders()) == {a, b}
    for r in recs:
        assert r["format"] == quarlib.SPOOL_FORMAT
    # Unknown record formats refuse to replay.
    bad = tmp_path / "spool" / "zzzz.json"
    bad.write_text(json.dumps({"format": "NOPE", "der": ""}))
    with pytest.raises(ValueError, match="NOPE"):
        spool.replay()
    # In-memory posture: no directory, records still held and counted.
    mem = quarlib.QuarantineSpool("")
    mem.file(a, index=0)
    assert mem.count == 1 and mem.replay_ders() == [a]


def test_check_batch_unmeasured_without_native(monkeypatch):
    monkeypatch.setenv("CTMR_NATIVE", "0")
    data, length = _pack_rows([b"\x30\x00"])
    chk = quarlib.check_batch(data, length)
    assert not chk.measured and chk.count == 0


# -- recorded-shard driver ---------------------------------------------------

SMALL_KINDS = (
    ["p256_valid"] * 6 + ["p256_corrupt"] * 2 + ["p384_retired"] * 2
    + ["rsa"] * 2 + ["unknown_log"] * 2 + ["out_of_interval"] * 2
    + ["no_sct"] * 8
)

SMALL_EXPECT = {
    "entries": 24, "sct_lanes": 16, "no_sct": 8,
    "verified": 12, "failed": 2, "no_key": 2,
    "device_lanes": 12, "host_lanes": 2,
    "retired": 2, "out_of_interval": 2, "unknown_log": 2,
}


def _small_doc() -> dict:
    """A 24-entry single-page CTMRAU01 doc with every lane class —
    the cheap stand-in for the checked-in 1024-entry shard."""
    from ct_mapreduce_tpu.utils import minicert

    signers = fxlib.fixture_signers()
    issuers = [
        minicert.make_cert(serial=100 + i,
                           issuer_cn=f"Small Audit CA {i}",
                           is_ca=True, not_after=FUTURE)
        for i in range(2)
    ]
    entries = []
    for idx, kind in enumerate(SMALL_KINDS):
        issuer = issuers[idx % 2]
        base = minicert.make_cert(
            serial=9000 + idx, issuer_cn=f"Small Audit CA {idx % 2}",
            subject_cn=f"small-{idx}.example", is_ca=False,
            not_after=FUTURE)
        ts = fxlib.TS_IN_INTERVAL + idx
        if kind == "no_sct":
            der = base
        else:
            signer = {
                "p256_valid": signers["p256"],
                "p256_corrupt": signers["p256"],
                "out_of_interval": signers["p256"],
                "p384_retired": signers["p384"],
                "rsa": signers["rsa"],
                "unknown_log": signers["unknown"],
            }[kind]
            if kind == "out_of_interval":
                ts = fxlib.TS_OUTSIDE + idx
            der = sctlib.attach_sct(
                base, signer, ts,
                corrupt_signature=(kind == "p256_corrupt"),
                issuer_der=issuer)
        li = leaflib.encode_leaf_input(der, timestamp_ms=ts)
        ed = leaflib.encode_extra_data([issuer])
        entries.append({
            "leaf_input": base64.b64encode(li).decode(),
            "extra_data": base64.b64encode(ed).decode(),
        })
    return {
        "format": drvlib.RECORDED_FORMAT,
        "log_url": "https://small.audit.example/",
        "log_list": fxlib.fixture_log_list_doc(signers),
        "pages": [{"start": 0, "entries": entries}],
    }


def _small_driver(doc, quarantine_dir=""):
    # Default capacity + the CLI's --batch-size/--flush-size values so
    # every driver in this module (and the CLI test) shares ONE set of
    # compiled dispatch shapes.
    ll = loglistlib.parse_log_list(doc["log_list"])
    return drvlib.AuditDriver(
        ll, quarantine_dir=quarantine_dir,
        batch_size=16, flush_size=16, batch_width=32)


@pytest.fixture(scope="module")
def small_run():
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    doc = _small_doc()
    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        drv = _small_driver(doc)
        rep = drv.run_recorded(doc)
        snap = sink.snapshot()
    finally:
        tmetrics.set_sink(prev)
    return doc, drv, rep, snap


def test_driver_small_doc_tallies(small_run):
    _doc, _drv, rep, snap = small_run
    e = SMALL_EXPECT
    assert rep.entries == e["entries"]
    assert rep.sct_lanes == e["sct_lanes"]
    assert rep.no_sct == e["no_sct"]
    assert rep.verified == e["verified"]
    assert rep.failed == e["failed"]
    assert rep.verifier_no_key == e["no_key"]
    assert rep.device_lanes == e["device_lanes"]
    assert rep.host_lanes == e["host_lanes"]
    assert rep.retired == e["retired"]
    assert rep.out_of_interval == e["out_of_interval"]
    assert rep.unknown_log == e["unknown_log"]
    assert rep.quarantined == 0
    assert rep.decode_failed == 0
    if _native_sct_available():
        assert rep.divergence_measured
    # Per-issuer folds: two CAs, each with half the verifiable mass.
    assert len(rep.per_issuer) == 2
    assert sum(v for v, _ in rep.per_issuer.values()) == e["verified"]
    assert sum(f for _, f in rep.per_issuer.values()) == e["failed"]
    # Audit metrics really published.
    c = snap["counters"]
    assert c["audit.entries"] == float(e["entries"])
    assert c["audit.verified"] == float(e["verified"])
    assert c["audit.failed"] == float(e["failed"])
    assert c["audit.unknown_log"] == float(e["unknown_log"])
    assert c["audit.retired_sct"] == float(e["retired"])
    assert c["audit.out_of_interval"] == float(e["out_of_interval"])
    assert "audit.quarantined" not in c
    # Report serializes.
    j = rep.to_json()
    json.dumps(j)
    assert j["verified"] == e["verified"]
    assert len(j["perIssuer"]) == 2


def test_driver_tile_scaling():
    doc = _small_doc()
    drv = _small_driver(doc)
    rep = drv.run_recorded(doc, tile=3)
    e = SMALL_EXPECT
    assert rep.entries == 3 * e["entries"]
    assert rep.verified == 3 * e["verified"]
    assert rep.failed == 3 * e["failed"]
    assert rep.retired == 3 * e["retired"]
    assert rep.unknown_log == 3 * e["unknown_log"]
    assert sum(rep.per_log.values()) == 3 * e["sct_lanes"]
    assert sum(v for v, _ in rep.per_issuer.values()) == 3 * e["verified"]


def test_driver_emits_filter_artifact(tmp_path):
    """The last leg of the acceptance flow: decode → verify →
    aggregate → FILTER. A driver armed with ``filter_path`` captures
    every inserted serial and checkpoint-save compiles the versioned
    artifact; every audited serial queries positive in its (issuer,
    expDate) group (no false negatives by contract)."""
    from ct_mapreduce_tpu.filter import artifact as fartifact

    doc = _small_doc()
    ll = loglistlib.parse_log_list(doc["log_list"])
    fpath = str(tmp_path / "audited.filter")
    drv = drvlib.AuditDriver(
        ll, batch_size=16, flush_size=16, batch_width=32,
        filter_path=fpath)
    rep = drv.run_recorded(doc)
    assert rep.entries == SMALL_EXPECT["entries"]
    drv.aggregator.save_checkpoint(str(tmp_path / "audited.npz"))

    art = fartifact.read_artifact(fpath)
    # Every decoded entry was inserted (no CA/expiry drops in the
    # fixture), so the artifact covers all 24 serials in 2 groups.
    assert art.n_serials == SMALL_EXPECT["entries"]
    assert len({iss for iss, _ in art.groups}) == 2

    reg = drv.aggregator.registry
    cap = drv.aggregator.filter_capture
    assert cap is not None and sum(len(s) for s in cap.values()) == 24
    for (idx, eh), serials in sorted(cap.items()):
        iss = reg.issuer_at(idx).id()
        for serial in sorted(serials):
            assert art.query(iss, eh, serial), (iss, eh, serial.hex())
    # An absent serial resolves negative in this (deterministic)
    # build — unknown serials only FP at the target rate.
    some_idx, some_eh = sorted(cap)[0]
    assert not art.query(reg.issuer_at(some_idx).id(), some_eh,
                         b"\x99" * 9)


@needs_native
def test_quarantine_exclusion_property(tmp_path, monkeypatch):
    """The acceptance property: a diverging lane is spooled and the
    aggregate outcome is IDENTICAL to a run where that entry never
    existed — quarantine is exclusion, never a third verdict."""
    from ct_mapreduce_tpu.native import leafpack
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    doc = _small_doc()
    n = len(doc["pages"][0]["entries"])
    target = 0  # a p256_valid lane

    real = leafpack.extract_scts
    fired = {"n": 0}

    def tampered(data, length, issuer_key_hash=None, **kw):
        out = real(data, length, issuer_key_hash=issuer_key_hash, **kw)
        # Only the pre-pass batch (full page width) is tampered, and
        # only once — the sink's own extraction stays honest.
        if fired["n"] == 0 and out.ok.shape[0] == n:
            fired["n"] = 1
            out.timestamp_ms = np.array(out.timestamp_ms, copy=True)
            out.timestamp_ms[target] += 1
        return out

    monkeypatch.setattr(leafpack, "extract_scts", tampered)
    qdir = str(tmp_path / "spool")
    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        drv = _small_driver(doc, quarantine_dir=qdir)
        rep = drv.run_recorded(doc)
        snap = sink.snapshot()
    finally:
        tmetrics.set_sink(prev)
    assert fired["n"] == 1
    assert rep.quarantined == 1 and rep.divergence_measured
    assert snap["counters"]["audit.quarantined"] == 1.0
    assert rep.entries == SMALL_EXPECT["entries"] - 1
    # The spool holds the offending DER with the disagreeing field.
    recs = drv.spool.replay()
    assert len(recs) == 1
    assert recs[0]["reasons"] == ["timestamp_ms"]
    assert recs[0]["index"] == target
    dec = leaflib.decode_json_entry(
        target, doc["pages"][0]["entries"][target])
    assert drv.spool.replay_ders() == [dec.cert_der]

    # Control: the same doc with the entry REMOVED, no tamper.
    monkeypatch.setattr(leafpack, "extract_scts", real)
    doc2 = _small_doc()
    del doc2["pages"][0]["entries"][target]
    drv2 = _small_driver(doc2)
    rep2 = drv2.run_recorded(doc2)
    assert rep2.quarantined == 0
    for f in ("verified", "failed", "verifier_no_key", "device_lanes",
              "host_lanes", "entries", "no_sct"):
        assert getattr(rep, f) == getattr(rep2, f), f
    assert sorted(rep.per_issuer.values()) \
        == sorted(rep2.per_issuer.values())


def test_driver_feeds_statistics_serve_and_checkpoint(
        small_run, tmp_path):
    """The audit aggregate flows through every EXISTING surface: the
    storage_statistics text + JSON totals, the serve plane's /issuer
    meta, and checkpoint round-trips — no parallel bookkeeping."""
    import io

    from ct_mapreduce_tpu.agg.aggregator import HostSnapshotAggregator
    from ct_mapreduce_tpu.cmd import storage_statistics as stats
    from ct_mapreduce_tpu.config import CTConfig
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    _doc, drv, rep, _snap = small_run
    agg = drv.aggregator
    path = str(tmp_path / "audit-agg.npz")
    agg.save_checkpoint(path)

    cfg = CTConfig()
    cfg.backend = "tpu"
    cfg.agg_state_path = path
    out = io.StringIO()
    assert stats.report_from_tpu_snapshot(cfg, out) == 0
    text = out.getvalue()
    assert f"{rep.verified} scts verified" in text
    assert f"{rep.failed} scts failed" in text
    report = stats.collect_tpu_report(cfg)
    assert report["totals"]["sctsVerified"] == rep.verified
    assert report["totals"]["sctsFailed"] == rep.failed

    h = HostSnapshotAggregator(capacity=1 << 10)
    h.load_checkpoint(path)
    assert h.verify_counts() == rep.per_issuer

    oracle = MembershipOracle(agg, replicas=1, device=False,
                              cache_size=-1)
    try:
        total_v = total_f = 0
        for iss_id in rep.per_issuer:
            meta = oracle.issuer_meta(iss_id)
            total_v += meta["verified"]
            total_f += meta["failed"]
        assert (total_v, total_f) == (rep.verified, rep.failed)
    finally:
        oracle.close()


def test_resolve_audit_knob_ladder(monkeypatch):
    from ct_mapreduce_tpu import audit as auditpkg

    for var in ("CTMR_AUDIT_LOG_LIST", "CTMR_AUDIT_QUARANTINE_DIR"):
        monkeypatch.delenv(var, raising=False)
    assert auditpkg.resolve_audit() == ("", "")
    monkeypatch.setenv("CTMR_AUDIT_LOG_LIST", "/tmp/list.json")
    monkeypatch.setenv("CTMR_AUDIT_QUARANTINE_DIR", "/tmp/spool")
    assert auditpkg.resolve_audit() == ("/tmp/list.json", "/tmp/spool")
    # Explicit beats env; an EMPTY explicit is "unset" on the ladder
    # (is_set = nonempty_str), so the spool knob still reads the env.
    assert auditpkg.resolve_audit("x.json", "") \
        == ("x.json", "/tmp/spool")
    assert auditpkg.resolve_audit("x.json", "/spool2") \
        == ("x.json", "/spool2")
    with pytest.raises(ValueError, match="log list"):
        monkeypatch.delenv("CTMR_AUDIT_LOG_LIST")
        drvlib.load_driver()


def test_audit_cli_recorded_json(tmp_path, capsys, monkeypatch):
    from tools import audit as audit_cli

    monkeypatch.delenv("CTMR_AUDIT_LOG_LIST", raising=False)
    monkeypatch.delenv("CTMR_AUDIT_QUARANTINE_DIR", raising=False)
    # Pin the verifier to the suite's shared compiled width — the CLI
    # builds its sink through the env ladder, and a fresh width would
    # cost a whole extra kernel compile inside the tier-1 budget.
    monkeypatch.setenv("CTMR_VERIFY_BATCH", "32")
    doc = _small_doc()
    path = str(tmp_path / "small.json.gz")
    drvlib.write_recorded(path, doc)
    rc = audit_cli.main(["--recorded", path, "--json",
                         "--flush-size", "16", "--batch-size", "16"])
    captured = capsys.readouterr()
    rep = json.loads(captured.out)
    assert rc == 0  # nothing quarantined
    assert rep["entries"] == SMALL_EXPECT["entries"]
    assert rep["verified"] == SMALL_EXPECT["verified"]
    assert rep["failed"] == SMALL_EXPECT["failed"]
    assert len(rep["perIssuer"]) == 2
    # Human-readable mode renders without crashing.
    rc = audit_cli.main(["--recorded", path])
    assert rc == 0
    assert "per-issuer" in capsys.readouterr().out


def test_recorded_format_rejected_loudly(tmp_path):
    path = str(tmp_path / "bad.json.gz")
    drvlib.write_recorded(path, {"pages": []})
    good = drvlib.load_recorded(path)
    assert good["format"] == drvlib.RECORDED_FORMAT
    import gzip

    with gzip.open(path, "wt", encoding="utf-8") as fh:
        json.dump({"format": "CTMRXX99", "pages": []}, fh)
    with pytest.raises(ValueError, match="CTMRXX99"):
        drvlib.load_recorded(path)


def test_checked_in_shard_matches_generator():
    """The checked-in corpus is EXACTLY what the generator emits —
    byte-stable regeneration is the tamper/drift guard for a fixture
    that test gates trust."""
    root = os.path.join(os.path.dirname(__file__), "..")
    path = os.path.join(root, "tests", "data",
                        "recorded_shard.json.gz")
    doc = drvlib.load_recorded(path)
    assert doc["mix"] == dict(fxlib.MIX, no_sct=816)
    n = sum(len(p["entries"]) for p in doc["pages"])
    assert n == fxlib.PAGE_SIZE * fxlib.N_PAGES == 1024
    # The embedded list is the fixture signers' production publication.
    ll = loglistlib.parse_log_list(doc["log_list"])
    signers = fxlib.fixture_signers()
    assert set(ll.shards) == {signers["p256"].log_id,
                              signers["p384"].log_id,
                              signers["rsa"].log_id}
