"""Ingest-side signature-verification lane (round 13).

End-to-end through AggregatorSink: extraction → classification →
batched device ECDSA + pure-python host fallback → per-issuer fold,
under both the serial per-chunk dispatch and the staged device queue,
with verdict truth recomputed independently per lane. Budget
discipline: device batches pad to width 32 (the compile the ECDSA
parity suite already paid), ONE serial sink run is shared module-wide
by every read-side assertion (checkpoint / issuer meta / reports),
and the walker compiles reuse one batch shape.
"""

import base64
import datetime
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.agg.aggregator import (  # noqa: E402
    HostSnapshotAggregator,
    TpuAggregator,
)
from ct_mapreduce_tpu.ingest import leaf as leaflib  # noqa: E402
from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch  # noqa: E402
from ct_mapreduce_tpu.utils import minicert  # noqa: E402
from ct_mapreduce_tpu.verify import host, sct as sctlib  # noqa: E402
from ct_mapreduce_tpu.verify.lane import (  # noqa: E402
    LogKeyRegistry,
    SignatureVerifier,
    resolve_verify,
)

FUTURE = datetime.datetime(2031, 6, 15, tzinfo=datetime.timezone.utc)


def _signers():
    return (sctlib.EcSctSigner("vl-a"),
            sctlib.EcSctSigner("vl-b", host.P384),
            sctlib.RsaSctSigner())


def _corpus(n=24):
    """[(leaf_der, issuer_der)] + expected outcome totals."""
    issuer = minicert.make_cert(serial=1, issuer_cn="Verify CA",
                                is_ca=True, not_after=FUTURE)
    p256, p384, rsa = _signers()
    unknown = sctlib.EcSctSigner("vl-unknown")
    pairs, expect = [], dict(verified=0, failed=0, no_sct=0, no_key=0,
                             host=0, device=0)
    for s in range(n):
        base = minicert.make_cert(
            serial=1000 + s, issuer_cn="Verify CA", subject_cn=f"l{s}",
            is_ca=False, not_after=FUTURE)
        kind = s % 6
        if kind == 0:
            der = sctlib.attach_sct(base, p256, 10**12 + s)
            expect["verified"] += 1
            expect["device"] += 1
        elif kind == 1:
            der = sctlib.attach_sct(base, p256, 10**12 + s,
                                    corrupt_signature=True)
            expect["failed"] += 1
            expect["device"] += 1
        elif kind == 2:
            der = sctlib.attach_sct(base, p384, 10**12 + s)
            expect["verified"] += 1
            expect["host"] += 1
        elif kind == 3:
            der = sctlib.attach_sct(base, rsa, 10**12 + s,
                                    corrupt_signature=True)
            expect["failed"] += 1
            expect["host"] += 1
        elif kind == 4:
            der = base
            expect["no_sct"] += 1
        else:
            der = sctlib.attach_sct(base, unknown, 10**12 + s)
            expect["no_key"] += 1
        pairs.append((der, issuer))
    return pairs, expect


def _wire(pairs):
    lis = [base64.b64encode(leaflib.encode_leaf_input(
        leaf, timestamp_ms=1_700_000_000_000 + j)).decode()
        for j, (leaf, _) in enumerate(pairs)]
    eds = [base64.b64encode(leaflib.encode_extra_data([iss])).decode()
           for _, iss in pairs]
    return lis, eds


def _run_sink(pairs, chunks_per_dispatch=1, flush=16):
    agg = TpuAggregator(capacity=1 << 12, batch_size=flush)
    sink = AggregatorSink(agg, flush_size=flush, device_queue_depth=0,
                          verify_signatures=True,
                          chunks_per_dispatch=chunks_per_dispatch)
    sink.verifier.batch_width = 32  # the parity suite's compiled width
    for s in _signers():
        sink.verifier.keys.register_signer(s)
    lis, eds = _wire(pairs)
    sink.store_raw_batch(RawBatch(lis, eds, 0, "v-log"))
    sink.flush()
    return agg, sink


@pytest.fixture(scope="module")
def serial_run():
    """One serial-dispatch sink run, shared by every read-side test."""
    pairs, expect = _corpus()
    agg, sink = _run_sink(pairs)
    return pairs, expect, agg, sink


def _check_outcomes(agg, sink, expect, n_pairs):
    st = sink.verifier.stats
    assert st["verified"] == expect["verified"]
    assert st["failed"] == expect["failed"]
    assert st["no_sct"] == expect["no_sct"]
    assert st["no_key"] == expect["no_key"]
    assert st["host_lanes"] == expect["host"]
    assert st["device_lanes"] == expect["device"]
    vc = agg.verify_counts()
    assert sum(v for v, _ in vc.values()) == expect["verified"]
    assert sum(f for _, f in vc.values()) == expect["failed"]
    # The dedup side is untouched by the lane: every lane still counts.
    assert agg.metrics["inserted"] == n_pairs


def test_sink_lane_outcomes_serial(serial_run):
    pairs, expect, agg, sink = serial_run
    _check_outcomes(agg, sink, expect, len(pairs))


def test_sink_lane_outcomes_staged():
    pairs, expect = _corpus()
    agg, sink = _run_sink(pairs, chunks_per_dispatch=2)
    _check_outcomes(agg, sink, expect, len(pairs))


def test_lane_python_extraction_parity(serial_run, monkeypatch):
    """CTMR_NATIVE=0 (pure-python decode AND extraction) produces the
    exact same verify outcomes — the degradation contract end to end."""
    pairs, expect, _agg, native_sink = serial_run
    monkeypatch.setenv("CTMR_NATIVE", "0")
    agg, sink = _run_sink(pairs)
    assert sink.verifier.stats == native_sink.verifier.stats


def test_verify_off_means_no_verifier():
    agg = TpuAggregator(capacity=1 << 12, batch_size=16)
    sink = AggregatorSink(agg, flush_size=16, device_queue_depth=0)
    assert sink.verifier is None
    assert not agg.verify_counts()
    assert not agg.drain().verified


def test_checkpoint_roundtrip(serial_run, tmp_path):
    _pairs, expect, agg, _sink = serial_run
    path = str(tmp_path / "agg.npz")
    agg.save_checkpoint(path)
    h = HostSnapshotAggregator(capacity=1 << 10)
    h.load_checkpoint(path)
    assert np.array_equal(h.verify_verified, agg.verify_verified)
    snap = h.drain()
    assert sum(snap.verified.values()) == expect["verified"]
    assert sum(snap.failed.values()) == expect["failed"]
    # Pre-round-13 snapshots (no verify arrays) load as zeros.
    z = dict(np.load(path, allow_pickle=True))
    z.pop("verify_verified")
    z.pop("verify_failed")
    legacy = str(tmp_path / "legacy.npz")
    with open(legacy, "wb") as fh:
        np.savez_compressed(fh, **z)
    h2 = HostSnapshotAggregator(capacity=1 << 10)
    h2.load_checkpoint(legacy)
    assert not h2.verify_counts()
    assert not h2.drain().verified


def test_issuer_meta_carries_verify_counts(serial_run):
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    _pairs, expect, agg, _sink = serial_run
    oracle = MembershipOracle(agg, replicas=1, device=False,
                              cache_size=-1)
    try:
        iss_id = next(iter(agg.verify_counts()))
        meta = oracle.issuer_meta(iss_id)
        assert meta["verified"] == expect["verified"]
        assert meta["failed"] == expect["failed"]
    finally:
        oracle.close()


def test_storage_statistics_verify_totals(serial_run, tmp_path):
    import io
    import json

    from ct_mapreduce_tpu.cmd import storage_statistics as stats
    from ct_mapreduce_tpu.config import CTConfig

    _pairs, expect, agg, _sink = serial_run
    path = str(tmp_path / "agg.npz")
    agg.save_checkpoint(path)
    cfg = CTConfig()
    cfg.backend = "tpu"
    cfg.agg_state_path = path
    out = io.StringIO()
    assert stats.report_from_tpu_snapshot(cfg, out) == 0
    text = out.getvalue()
    assert f"{expect['verified']} scts verified" in text
    assert f"{expect['failed']} scts failed" in text
    report = stats.collect_tpu_report(cfg)
    assert report["totals"]["sctsVerified"] == expect["verified"]
    assert report["totals"]["sctsFailed"] == expect["failed"]
    json.dumps(report)  # stays serializable


def test_resolve_verify_env_layering(monkeypatch):
    monkeypatch.delenv("CTMR_VERIFY", raising=False)
    monkeypatch.delenv("CTMR_VERIFY_KEYS", raising=False)
    monkeypatch.delenv("CTMR_VERIFY_BATCH", raising=False)
    assert resolve_verify() == (False, "", 1024)
    monkeypatch.setenv("CTMR_VERIFY", "1")
    monkeypatch.setenv("CTMR_VERIFY_KEYS", "/tmp/k.json")
    monkeypatch.setenv("CTMR_VERIFY_BATCH", "256")
    assert resolve_verify() == (True, "/tmp/k.json", 256)
    # explicit beats env; junk batch env is ignored
    monkeypatch.setenv("CTMR_VERIFY_BATCH", "zap")
    assert resolve_verify(False, "x.json", 64) == (False, "x.json", 64)
    assert resolve_verify(True) == (True, "/tmp/k.json", 1024)


def test_sink_loads_keys_from_file(tmp_path):
    reg = LogKeyRegistry()
    p256, p384, rsa = _signers()
    for s in (p256, p384, rsa):
        reg.register_signer(s)
    keys_path = tmp_path / "keys.json"
    keys_path.write_text(reg.to_json())
    agg = TpuAggregator(capacity=1 << 12, batch_size=16)
    sink = AggregatorSink(agg, flush_size=16, device_queue_depth=0,
                          verify_signatures=True,
                          verify_log_keys=str(keys_path))
    assert isinstance(sink.verifier, SignatureVerifier)
    assert len(sink.verifier.keys) == 3
    assert sink.verifier.keys.is_p256(p256.log_id)
