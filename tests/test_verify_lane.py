"""Ingest-side signature-verification lane (round 13).

End-to-end through AggregatorSink: extraction → classification →
batched device ECDSA + pure-python host fallback → per-issuer fold,
under both the serial per-chunk dispatch and the staged device queue,
with verdict truth recomputed independently per lane. Budget
discipline: device batches pad to width 32 (the compile the ECDSA
parity suite already paid), ONE serial sink run is shared module-wide
by every read-side assertion (checkpoint / issuer meta / reports),
and the walker compiles reuse one batch shape.
"""

import base64
import datetime
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.agg.aggregator import (  # noqa: E402
    HostSnapshotAggregator,
    TpuAggregator,
)
from ct_mapreduce_tpu.ingest import leaf as leaflib  # noqa: E402
from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch  # noqa: E402
from ct_mapreduce_tpu.utils import minicert  # noqa: E402
from ct_mapreduce_tpu.verify import host, sct as sctlib  # noqa: E402
from ct_mapreduce_tpu.verify.lane import (  # noqa: E402
    LogKeyRegistry,
    SignatureVerifier,
    resolve_verify,
)

FUTURE = datetime.datetime(2031, 6, 15, tzinfo=datetime.timezone.utc)


def _signers():
    return (sctlib.EcSctSigner("vl-a"),
            sctlib.EcSctSigner("vl-b", host.P384),
            sctlib.RsaSctSigner())


def _corpus(n=24):
    """[(leaf_der, issuer_der)] + expected outcome totals."""
    issuer = minicert.make_cert(serial=1, issuer_cn="Verify CA",
                                is_ca=True, not_after=FUTURE)
    p256, p384, rsa = _signers()
    unknown = sctlib.EcSctSigner("vl-unknown")
    pairs, expect = [], dict(verified=0, failed=0, no_sct=0, no_key=0,
                             host=0, device=0, p384=0)
    for s in range(n):
        base = minicert.make_cert(
            serial=1000 + s, issuer_cn="Verify CA", subject_cn=f"l{s}",
            is_ca=False, not_after=FUTURE)
        kind = s % 6
        if kind == 0:
            der = sctlib.attach_sct(base, p256, 10**12 + s,
                                    issuer_der=issuer)
            expect["verified"] += 1
            expect["device"] += 1
        elif kind == 1:
            der = sctlib.attach_sct(base, p256, 10**12 + s,
                                    corrupt_signature=True,
                                    issuer_der=issuer)
            expect["failed"] += 1
            expect["device"] += 1
        elif kind == 2:
            # P-384 lanes ride the DEVICE since round 17 (re-extracted
            # from row bytes, verified by the windowed P-384 kernel).
            der = sctlib.attach_sct(base, p384, 10**12 + s,
                                    issuer_der=issuer)
            expect["verified"] += 1
            expect["device"] += 1
            expect["p384"] += 1
        elif kind == 3:
            der = sctlib.attach_sct(base, rsa, 10**12 + s,
                                    corrupt_signature=True,
                                    issuer_der=issuer)
            expect["failed"] += 1
            expect["host"] += 1
        elif kind == 4:
            der = base
            expect["no_sct"] += 1
        else:
            der = sctlib.attach_sct(base, unknown, 10**12 + s,
                                    issuer_der=issuer)
            expect["no_key"] += 1
        pairs.append((der, issuer))
    return pairs, expect


def _wire(pairs):
    lis = [base64.b64encode(leaflib.encode_leaf_input(
        leaf, timestamp_ms=1_700_000_000_000 + j)).decode()
        for j, (leaf, _) in enumerate(pairs)]
    eds = [base64.b64encode(leaflib.encode_extra_data([iss])).decode()
           for _, iss in pairs]
    return lis, eds


def _run_sink(pairs, chunks_per_dispatch=1, flush=16):
    agg = TpuAggregator(capacity=1 << 12, batch_size=flush)
    sink = AggregatorSink(agg, flush_size=flush, device_queue_depth=0,
                          verify_signatures=True,
                          chunks_per_dispatch=chunks_per_dispatch)
    sink.verifier.batch_width = 32  # the parity suite's compiled width
    for s in _signers():
        sink.verifier.keys.register_signer(s)
    lis, eds = _wire(pairs)
    sink.store_raw_batch(RawBatch(lis, eds, 0, "v-log"))
    sink.flush()
    return agg, sink


@pytest.fixture(scope="module")
def serial_run():
    """One serial-dispatch sink run, shared by every read-side test."""
    pairs, expect = _corpus()
    agg, sink = _run_sink(pairs)
    return pairs, expect, agg, sink


def _check_outcomes(agg, sink, expect, n_pairs):
    st = sink.verifier.stats
    assert st["verified"] == expect["verified"]
    assert st["failed"] == expect["failed"]
    assert st["no_sct"] == expect["no_sct"]
    assert st["no_key"] == expect["no_key"]
    assert st["host_lanes"] == expect["host"]
    assert st["device_lanes"] == expect["device"]
    assert st["p384_lanes"] == expect["p384"]
    # Q-table accounting: one lookup per device lane, one miss per
    # distinct (log key, registry epoch) — steady state is all hits.
    assert st["qtable_hits"] + st["qtable_misses"] == expect["device"]
    assert st["qtable_misses"] == 2  # one P-256 key + one P-384 key
    vc = agg.verify_counts()
    assert sum(v for v, _ in vc.values()) == expect["verified"]
    assert sum(f for _, f in vc.values()) == expect["failed"]
    # The dedup side is untouched by the lane: every lane still counts.
    assert agg.metrics["inserted"] == n_pairs


def test_sink_lane_outcomes_serial(serial_run):
    pairs, expect, agg, sink = serial_run
    _check_outcomes(agg, sink, expect, len(pairs))


def test_sink_lane_outcomes_staged():
    pairs, expect = _corpus()
    agg, sink = _run_sink(pairs, chunks_per_dispatch=2)
    _check_outcomes(agg, sink, expect, len(pairs))


def test_lane_python_extraction_parity(serial_run, monkeypatch):
    """CTMR_NATIVE=0 (pure-python decode AND extraction) produces the
    exact same verify outcomes — the degradation contract end to end."""
    pairs, expect, _agg, native_sink = serial_run
    monkeypatch.setenv("CTMR_NATIVE", "0")
    agg, sink = _run_sink(pairs)
    assert sink.verifier.stats == native_sink.verifier.stats


def test_verify_off_means_no_verifier():
    agg = TpuAggregator(capacity=1 << 12, batch_size=16)
    sink = AggregatorSink(agg, flush_size=16, device_queue_depth=0)
    assert sink.verifier is None
    assert not agg.verify_counts()
    assert not agg.drain().verified


def test_checkpoint_roundtrip(serial_run, tmp_path):
    _pairs, expect, agg, _sink = serial_run
    path = str(tmp_path / "agg.npz")
    agg.save_checkpoint(path)
    h = HostSnapshotAggregator(capacity=1 << 10)
    h.load_checkpoint(path)
    assert np.array_equal(h.verify_verified, agg.verify_verified)
    snap = h.drain()
    assert sum(snap.verified.values()) == expect["verified"]
    assert sum(snap.failed.values()) == expect["failed"]
    # Pre-round-13 snapshots (no verify arrays) load as zeros.
    z = dict(np.load(path, allow_pickle=True))
    z.pop("verify_verified")
    z.pop("verify_failed")
    legacy = str(tmp_path / "legacy.npz")
    with open(legacy, "wb") as fh:
        np.savez_compressed(fh, **z)
    h2 = HostSnapshotAggregator(capacity=1 << 10)
    h2.load_checkpoint(legacy)
    assert not h2.verify_counts()
    assert not h2.drain().verified


def test_issuer_meta_carries_verify_counts(serial_run):
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    _pairs, expect, agg, _sink = serial_run
    oracle = MembershipOracle(agg, replicas=1, device=False,
                              cache_size=-1)
    try:
        iss_id = next(iter(agg.verify_counts()))
        meta = oracle.issuer_meta(iss_id)
        assert meta["verified"] == expect["verified"]
        assert meta["failed"] == expect["failed"]
    finally:
        oracle.close()


def test_storage_statistics_verify_totals(serial_run, tmp_path):
    import io
    import json

    from ct_mapreduce_tpu.cmd import storage_statistics as stats
    from ct_mapreduce_tpu.config import CTConfig

    _pairs, expect, agg, _sink = serial_run
    path = str(tmp_path / "agg.npz")
    agg.save_checkpoint(path)
    cfg = CTConfig()
    cfg.backend = "tpu"
    cfg.agg_state_path = path
    out = io.StringIO()
    assert stats.report_from_tpu_snapshot(cfg, out) == 0
    text = out.getvalue()
    assert f"{expect['verified']} scts verified" in text
    assert f"{expect['failed']} scts failed" in text
    report = stats.collect_tpu_report(cfg)
    assert report["totals"]["sctsVerified"] == expect["verified"]
    assert report["totals"]["sctsFailed"] == expect["failed"]
    json.dumps(report)  # stays serializable


def test_resolve_verify_env_layering(monkeypatch):
    for var in ("CTMR_VERIFY", "CTMR_VERIFY_KEYS", "CTMR_VERIFY_BATCH",
                "CTMR_VERIFY_PRECOMP_WINDOW", "CTMR_VERIFY_QTABLE_SIZE"):
        monkeypatch.delenv(var, raising=False)
    assert resolve_verify() == (False, "", 1024, 8, 32)
    monkeypatch.setenv("CTMR_VERIFY", "1")
    monkeypatch.setenv("CTMR_VERIFY_KEYS", "/tmp/k.json")
    monkeypatch.setenv("CTMR_VERIFY_BATCH", "256")
    monkeypatch.setenv("CTMR_VERIFY_PRECOMP_WINDOW", "4")
    monkeypatch.setenv("CTMR_VERIFY_QTABLE_SIZE", "7")
    assert resolve_verify() == (True, "/tmp/k.json", 256, 4, 7)
    # explicit beats env; junk batch env is ignored
    monkeypatch.setenv("CTMR_VERIFY_BATCH", "zap")
    assert resolve_verify(False, "x.json", 64, 2, 3) \
        == (False, "x.json", 64, 2, 3)
    assert resolve_verify(True) == (True, "/tmp/k.json", 1024, 4, 7)
    # explicit window 0 (the legacy ladder) beats a set env var —
    # 0 is a REAL value, the parity fallback.
    assert resolve_verify(True, window=0)[3] == 0
    # invalid windows (must divide 16) fall back to the default 8.
    monkeypatch.setenv("CTMR_VERIFY_PRECOMP_WINDOW", "5")
    assert resolve_verify(True)[3] == 8
    monkeypatch.setenv("CTMR_VERIFY_QTABLE_SIZE", "junk")
    assert resolve_verify(True)[4] == 32


def test_sink_loads_keys_from_file(tmp_path):
    reg = LogKeyRegistry()
    p256, p384, rsa = _signers()
    for s in (p256, p384, rsa):
        reg.register_signer(s)
    keys_path = tmp_path / "keys.json"
    keys_path.write_text(reg.to_json())
    agg = TpuAggregator(capacity=1 << 12, batch_size=16)
    sink = AggregatorSink(agg, flush_size=16, device_queue_depth=0,
                          verify_signatures=True,
                          verify_log_keys=str(keys_path))
    assert isinstance(sink.verifier, SignatureVerifier)
    assert len(sink.verifier.keys) == 3
    assert sink.verifier.keys.is_p256(p256.log_id)


# -- round 17: Q-table cache, routing, legacy-window parity --------------

def _sct_rows(certs):
    pad = max(len(c) for c in certs) + 16
    data = np.zeros((len(certs), pad), np.uint8)
    length = np.zeros((len(certs),), np.int32)
    for i, c in enumerate(certs):
        data[i, : len(c)] = np.frombuffer(c, np.uint8)
        length[i] = len(c)
    return data, length


def _submit(verifier, certs):
    data, length = _sct_rows(certs)
    scts = sctlib.extract_scts_np(data, length)
    verifier.submit_chunk(
        scts, np.zeros((len(certs),), np.int64),
        np.ones((len(certs),), bool), data, length)


def _sct_cert(signer, serial, ts=10**12):
    base = minicert.make_cert(serial=serial, issuer_cn="QT CA",
                              subject_cn=f"qt{serial}", is_ca=False,
                              not_after=FUTURE)
    return sctlib.attach_sct(base, signer, ts)


def test_qtable_lru_eviction_and_epoch_invalidation():
    """The per-log-key Q-table LRU: one miss per distinct (key,
    registry epoch), hits afterwards, eviction under a 1-slot cap,
    and re-registration (epoch bump) invalidating exactly that key.
    Width 32 — the compile the parity suite already paid."""
    ka, kb = sctlib.EcSctSigner("qt-a"), sctlib.EcSctSigner("qt-b")
    ca, cb = _sct_cert(ka, 1), _sct_cert(kb, 2)

    agg = TpuAggregator(capacity=1 << 12, batch_size=16)
    tight = SignatureVerifier(agg, batch_width=32, qtable_size=1)
    for s in (ka, kb):
        tight.keys.register_signer(s)
    _submit(tight, [ca, cb])
    tight.drain()
    st = tight.stats
    assert (st["qtable_misses"], st["qtable_hits"]) == (2, 0)
    _submit(tight, [ca])  # a was evicted by b under the 1-slot cap
    tight.drain()
    assert (st["qtable_misses"], st["qtable_hits"]) == (3, 0)
    assert tight.health()["qtable"]["p256"]["occupancy"] == 1
    assert st["verified"] == 3 and st["failed"] == 0

    roomy = SignatureVerifier(agg, batch_width=32, qtable_size=4)
    for s in (ka, kb):
        roomy.keys.register_signer(s)
    _submit(roomy, [ca, cb])
    roomy.drain()
    _submit(roomy, [ca, cb])  # steady state: 100% hits
    roomy.drain()
    st = roomy.stats
    assert (st["qtable_misses"], st["qtable_hits"]) == (2, 2)
    # Epoch bump: re-registering ka invalidates ONLY ka's slot.
    roomy.keys.register_signer(ka)
    _submit(roomy, [ca, cb])
    roomy.drain()
    assert (st["qtable_misses"], st["qtable_hits"]) == (3, 3)
    h = roomy.health()
    assert h["window"] == roomy.window > 0
    assert h["qtable"]["p256"]["capacity"] == 4
    assert h["qtable"]["p256"]["occupancy"] == 3  # stale ka slot + 2
    assert h["stats"]["verified"] == st["verified"] == 6


def test_p384_host_fallback_routing():
    """The third routing leg: a lane keyed to a P-384 entry whose SCT
    is NOT device-decidable (RSA algorithm bytes under the key's
    log id) replays through the host verifier and fails closed —
    P-256 device / P-384 device / host fallback all pinned."""
    rsa = sctlib.RsaSctSigner()
    cert = _sct_cert(rsa, 3)
    p384k = sctlib.EcSctSigner("fb-384", host.P384)
    agg = TpuAggregator(capacity=1 << 12, batch_size=16)
    v = SignatureVerifier(agg, batch_width=32)
    v.keys.register({
        "log_id": rsa.log_id.hex(), "alg": "p384",
        "x": hex(p384k.q[0]), "y": hex(p384k.q[1]),
    })
    _submit(v, [cert])
    v.drain()
    st = v.stats
    assert st["host_lanes"] == 1 and st["device_lanes"] == 0
    assert st["p384_lanes"] == 0
    assert st["failed"] == 1 and st["verified"] == 0


def test_lane_window0_legacy_parity():
    """verifyPrecompWindow = 0 routes the lane down the round-13
    Jacobian ladder (the parity fallback) — same outcomes as the
    windowed default on the same lanes. P-256 only: the legacy P-384
    compile is slow-tier (test_ecdsa), and the lane shares kernels
    with it."""
    ka = sctlib.EcSctSigner("w0-a")
    certs = [_sct_cert(ka, 10), _sct_cert(ka, 11)]
    bad = sctlib.attach_sct(
        minicert.make_cert(serial=12, issuer_cn="QT CA",
                           subject_cn="qt12", is_ca=False,
                           not_after=FUTURE),
        ka, 10**12, corrupt_signature=True)
    certs.append(bad)

    outcomes = []
    for window in (0, None):
        agg = TpuAggregator(capacity=1 << 12, batch_size=16)
        v = SignatureVerifier(agg, batch_width=32, window=window)
        v.keys.register_signer(ka)
        _submit(v, certs)
        v.drain()
        outcomes.append((v.stats["verified"], v.stats["failed"],
                         v.stats["device_lanes"]))
    assert outcomes[0] == outcomes[1] == (2, 1, 3)
    # window 0 builds no tables: the Q-table stats stay zero.
    assert outcomes[0] == (2, 1, 3)
