"""CI gate for bench.py's CPU smoke path (CT_BENCH_SMOKE=1).

Locks the overlapped-ingest pipeline into tier-1: run_smoke() asserts
serial/overlap parity (table_count, host_lane, drained counts), the
rediscache serial sets, AND the overlap inequality — overlapped wall
< 0.85 × (decode + device_wait + drain) on the same run — so the
pipeline cannot silently regress to serialized stages without failing
the suite.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(120)
def test_bench_smoke_overlap_gate(monkeypatch):
    # Same ambient-sitecustomize workaround as bench.main(): keep the
    # smoke on CPU even outside pytest/conftest (run_smoke also forces
    # the cpu platform itself).
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_smoke()  # raises BenchError on any parity/gate miss
    assert out["metric"] == "ct_e2e_smoke"
    assert out["smoke_entries"] == out["smoke_table_count"]
    assert out["smoke_overlap_ratio"] < 0.85
    assert out["value"] > 0
    # The stage budget really was measured (not zeroed by a silent
    # metrics-sink regression). Since PR 4 it is SPAN-derived: the
    # smoke traces itself and sums the ingest.decode/submit/drain
    # spans, so a tracer regression zeroes these and fails here.
    assert out["smoke_decode_s"] > 0 and out["smoke_device_wait_s"] > 0
    # The trace artifact exists and tools/traceview.py parses it into
    # per-stage occupancy that shows decode/device/drain overlapping
    # (stage occupancies summing past the overlap ratio's complement
    # is what the 0.85 gate measures; here we pin the artifact path).
    from tools import traceview

    events = traceview.load(out["smoke_trace_path"])
    summary = traceview.stage_summary(
        events, stages=("ingest.decode", "ingest.submit", "ingest.drain"))
    wall = summary.pop("_wall_s")
    assert set(summary) == {"ingest.decode", "ingest.submit",
                            "ingest.drain"}
    assert all(s["busy_s"] > 0 for s in summary.values())
    assert wall > 0
    # Serve leg (ISSUE 5): run_smoke itself gates parity-under-ingest,
    # the span-derived p99 wait budget, and the shed behavior; here we
    # pin that the leg RAN and its numbers are sane — dynamic batching
    # really formed batches (mean lanes/batch > 1, some batch merged
    # several requests), occupancy came from serve.batch spans (a
    # tracer regression zeroes the batch count and fails here), and
    # overload shed explicitly.
    assert out["smoke_serve_parity"] == 1
    assert out["smoke_serve_batches"] > 0
    assert out["smoke_serve_mean_batch_lanes"] > 1.0
    assert out["smoke_serve_max_batch_requests"] > 1
    assert out["smoke_serve_lanes_per_s"] > 0
    assert 0 < out["smoke_serve_wait_p50_ms"] <= out["smoke_serve_wait_p99_ms"]
    assert out["smoke_serve_shed"] > 0
    # Serve-device leg (ISSUE 7): run_smoke gates exact parity under
    # concurrent ingest on the replicated device tier; here we pin the
    # structural numbers — the jitted device contains really executed
    # (span-counted), >=2 replicas answered batches round-robin, the
    # hot-serial cache served hits on the zipf-ish mix, and misses
    # still coalesced into multi-lane batches.
    assert out["smoke_serve_dev_parity"] == 1
    assert out["smoke_serve_dev_replicas"] >= 2
    assert out["smoke_serve_dev_lookups"] > 0
    assert out["smoke_serve_dev_contains_spans"] > 0
    assert out["smoke_serve_dev_cache_hits"] > 0
    assert 0 < out["smoke_serve_dev_cache_hit_rate"] <= 1
    assert out["smoke_serve_dev_mean_batch_lanes"] > 1.0
    assert out["smoke_serve_dev_fallbacks"] == 0
    # Pre-parsed leg: run_smoke itself asserts exact parity with the
    # walker lanes AND that D2H flag traffic stays O(flagged); here we
    # only pin that the leg ran when the native extractor exists (its
    # absence would silently drop the gate).
    from ct_mapreduce_tpu.native import available

    if available():
        # Staged leg (round 11): run_smoke itself gates exact parity
        # with the serial lane, the mean chunks/dispatch hitting K,
        # the ingest.h2d span/bytes instrumentation, H2D hidden behind
        # the envelope's compute, the span-counted execution-fusion
        # structure, and the tunneled-toll-modeled >=1.3x acceptance
        # inequality (raw walls are parity-neutral on the 1-core CI
        # box — see the honesty note in run_smoke / BENCHLOG round
        # 11); here we pin those numbers.
        assert out["smoke_staged_modeled_vs_overlap"] >= 1.3
        assert (out["smoke_staged_execs"]
                * out["smoke_staged_chunks_per_dispatch"]
                <= out["smoke_overlap_execs"])
        assert out["smoke_staged_wall_s"] <= 1.15 * out["smoke_overlap_wall_s"]
        assert out["smoke_staged_chunks_per_dispatch"] > 1
        assert out["smoke_staged_h2d_bytes"] > 0
        assert 0 < out["smoke_staged_h2d_s"] < 0.1 * out["smoke_staged_wall_s"]
        assert out["smoke_preparsed_flag_bytes"] > 0
        # Far below one int32 status row per chunk (the old readback).
        assert out["smoke_preparsed_flag_bytes"] < 4 * out["smoke_entries"]
        # The sharded-preparsed leg ran (host-routed mesh path) with
        # the same O(flagged) compact-readback budget, and the
        # intra-chunk decode-thread parity leg passed.
        assert out["smoke_sharded_preparsed_flag_bytes"] > 0
        assert (out["smoke_sharded_preparsed_flag_bytes"]
                < 4 * out["smoke_entries"])
        assert out["smoke_decode_threads_parity"] == 1


@pytest.mark.timeout(340)
def test_bench_smoke_fleet_gate(tmp_path_factory, monkeypatch):
    """Fleet leg (ISSUE 9): run_fleet_smoke itself gates merged-vs-
    serial parity for W∈{1,2} local worker processes and the
    disjoint+covering partition structure; here we pin that both
    fleets ran with real work and the throughput numbers were
    recorded (honestly — the 1-core box carries no scaling claim;
    parity + structure carry it)."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    # Shared persistent compile cache for the worker subprocesses —
    # all compile identical tiny CPU programs.
    monkeypatch.setenv("CT_COMPILE_CACHE", str(
        tmp_path_factory.getbasetemp().parent / "fleet-xla-cache"))
    import bench

    out = bench.run_fleet_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_fleet_smoke"
    assert out["smoke_fleet_parity"] == 1
    assert out["smoke_fleet_entries"] > 0
    assert out["smoke_fleet_ref_total"] > 0
    assert out["value"] > 0
    assert out["smoke_fleet_w1_entries_per_s"] > 0
    assert out["smoke_fleet_w2_entries_per_s"] > 0
    # The W=1 leg also served the fleet /healthz section live (role,
    # membership, partition map) and observed leader-published
    # checkpoint epochs mid-run.
    assert out["smoke_fleet_healthz_epoch"] >= 1


@pytest.mark.timeout(420)
def test_bench_smoke_obs_gate(tmp_path_factory, monkeypatch):
    """Observability leg (round 23): run_obs_smoke itself gates the
    live W=2 fleet-observability plane — one merged timeline with the
    client's trace_id crossing the process boundary, exact in-body
    AND cross-scrape /metrics/fleet counter parity, the SIGSTOP ->
    /healthz/fleet 503 flip landing within the heartbeat TTL, and the
    modeled tracer+fan-in overhead under 2% of the workers' wall
    (rounds-11/14 convention: raw 1-core walls carry no timing
    claim); here we pin that every sub-leg ran with real work and the
    BENCHLOG numbers were recorded."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    # Shared persistent compile cache for the worker subprocesses —
    # safe here (no SIGKILL/restart sequence; SIGSTOP/SIGCONT and a
    # clean SIGTERM only — see the spawn_worker cache caveat).
    monkeypatch.setenv("CT_COMPILE_CACHE", str(
        tmp_path_factory.getbasetemp().parent / "fleet-xla-cache"))
    import bench

    out = bench.run_obs_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_obs_smoke"
    assert out["value"] > 0
    assert out["smoke_obs_workers"] == 2
    # One timeline, three processes (client + both workers), labeled
    # worker tracks, and at least one request's trace_id observed on
    # both sides of the process boundary.
    assert out["smoke_obs_merged_pids"] >= 3
    assert out["smoke_obs_merged_events"] > 0
    assert 1 <= out["smoke_obs_correlated"] <= out["smoke_obs_trace_ids"]
    # Fan-in parity: exact within the body and across live scrapes.
    assert out["smoke_obs_parity"] == 1
    assert out["smoke_obs_cross_scrape_parity"] == 1
    assert out["smoke_obs_parity_counters"] > 0
    assert out["smoke_obs_insert_total"] > 0
    # The SIGSTOP'd worker degraded the rollup within the TTL and the
    # fleet recovered after SIGCONT.
    assert 0 < out["smoke_obs_flip_s"] <= out["smoke_obs_liveness_s"] + 1.5
    assert out["smoke_obs_recover_s"] > out["smoke_obs_flip_s"]
    # Overhead: modeled from measured per-event costs, gated < 2%.
    assert out["smoke_obs_spans"] > 0
    assert out["smoke_obs_publishes"] > 0
    assert 0 < out["smoke_obs_overhead_pct"] < 2.0


@pytest.mark.timeout(180)
def test_bench_smoke_filter_gate():
    """Filter leg (ISSUE 10): run_filter_smoke itself gates zero false
    negatives over the full included set of a fuzz-populated table,
    capture == drained report, measured FP ≤ 2× target on a disjoint
    probe corpus, and build determinism; here we pin that the leg ran
    with real work and the BENCHLOG numbers were recorded."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_filter_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_filter_smoke"
    assert out["value"] > 0
    assert out["smoke_filter_serials"] > 1000
    assert out["smoke_filter_groups"] >= 3
    assert out["smoke_filter_false_negatives"] == 0
    assert out["smoke_filter_fp_measured"] <= 2 * out["smoke_filter_fp_target"]
    assert out["smoke_filter_probes"] >= 10_000
    # Compactness: a cascade, not a serial dump — well under the 128
    # bits a raw fingerprint list would need per entry.
    assert 0 < out["smoke_filter_bits_per_entry"] < 64
    assert out["smoke_filter_max_layers"] >= 1
    # (Filter-over-a-grown-table is pinned by tests/test_filter.py's
    # rehash-mid-corpus fuzz; the smoke stays at the overlap leg's
    # compiled table shape to keep the tier-1 budget.)


@pytest.mark.timeout(180)
def test_bench_smoke_filter_scale_gate():
    """Scaled filter build leg (ISSUE 14): run_filter_scale_smoke
    itself gates byte identity across fused/per-group/streamed/NumPy
    build paths, the fused dispatch collapse, and spill-ring capture
    parity; here we pin that the leg ran with real work and the
    BENCHLOG numbers were recorded."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_filter_scale_smoke()  # raises BenchError on a miss
    assert out["metric"] == "ct_filter_scale_smoke"
    assert out["value"] > 0
    assert out["smoke_fscale_serials"] > 30_000
    assert out["smoke_fscale_groups"] >= 12
    assert out["smoke_fscale_byte_identity"] == 1
    # The collapse is the lever: dispatches well under the
    # per-(group, layer) count the round-15 path would issue.
    assert out["smoke_fscale_dispatches"] < out["smoke_fscale_layers"]
    assert out["smoke_fscale_groups_per_dispatch"] > 2.0
    assert out["smoke_fscale_device_dispatches"] > 0
    # The spill ring really spilled and changed nothing (parity is
    # gated inside the leg).
    assert out["smoke_fscale_spilled_bytes"] > 0
    assert out["smoke_fscale_spill_segments"] >= 1


@pytest.mark.timeout(180)
def test_bench_smoke_distrib_gate():
    """Distribution leg (ISSUE 13): run_distrib_smoke itself gates
    worker byte-identity (full + containers over HTTP), client-side
    delta-chain replay to the exact full filter, and delta+304 traffic
    ≪ full-pull bytes; here we pin that the leg ran every pull class
    with real work and the BENCHLOG numbers were recorded."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_distrib_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_distrib_smoke"
    assert out["value"] > 0
    assert out["smoke_distrib_parity"] == 1
    assert out["smoke_distrib_workers"] == 2
    assert out["smoke_distrib_clients"] >= 500
    assert out["smoke_distrib_ratio_304"] > 0.1
    assert out["smoke_distrib_delta_304_vs_full"] < 0.20
    assert out["smoke_distrib_wire_vs_counterfactual"] < 0.5
    assert out["smoke_distrib_pulls"]["304"] > 0
    assert out["smoke_distrib_pulls"]["delta"] > 0
    assert out["smoke_distrib_pulls"]["full"] > 0
    assert 0 < out["smoke_distrib_p50_ms"] <= out["smoke_distrib_p99_ms"]


@pytest.mark.timeout(180)
def test_bench_smoke_ckpt_gate():
    """Incremental checkpoint leg (ISSUE 18): run_ckpt_smoke itself
    gates a 1%-churn CTMRCK02 delta tick >= 5x faster than a full ck01
    save, digest parity between the chain restore, the writer, and the
    ck01 oracle restore, and a bounded chain (anchor observed at
    ckptMaxChain); here we pin that the leg ran with real work and the
    BENCHLOG numbers were recorded."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    # 50K entries is the smallest scale the gate accepts; the tier-1
    # wall rides the capped-run dot budget, so don't pay for more here
    # (the 10^7 headline lives in stagecost/BENCHLOG).
    os.environ.setdefault("CT_BENCH_SMOKE_CKPT_ENTRIES", "50000")
    out = bench.run_ckpt_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_ckpt_smoke"
    assert out["value"] >= 5.0
    assert out["smoke_ckpt_entries"] >= 50_000
    assert out["smoke_ckpt_tick_ms"] < out["smoke_ckpt_full_ms"]
    assert out["smoke_ckpt_parity"] == 1
    assert out["smoke_ckpt_chain_bounded"] == 1


@pytest.mark.timeout(240)
def test_bench_smoke_verify_gate():
    """Verify leg (ISSUE 8): run_verify_smoke itself gates verdict
    parity vs the host-recomputed truth, the span-counted device
    verify executions, and fallback == undecidable-lane count; here
    we pin that the leg ran with real work on every lane class."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_verify_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_verify_smoke"
    assert out["value"] > 0
    assert out["smoke_verify_device_lanes"] > 0
    assert out["smoke_verify_fallback_lanes"] > 0
    assert out["smoke_verify_no_sct"] > 0
    assert out["smoke_verify_no_key"] > 0
    assert out["smoke_verify_verified"] > 0
    assert out["smoke_verify_failed"] > 0
    assert out["smoke_verify_device_execs"] > 0
    assert out["smoke_verify_mean_batch_lanes"] > 1.0
    assert (out["smoke_verify_verified"] + out["smoke_verify_failed"]
            == out["smoke_verify_device_lanes"]
            + out["smoke_verify_fallback_lanes"])
    # Round 17: the windowed precompute engaged — qtable hits beyond
    # the one build per device log key, under the staged queue.
    assert out["smoke_verify_window"] > 0
    assert out["smoke_verify_qtable_misses"] == 2
    assert out["smoke_verify_qtable_hits"] > 0


@pytest.mark.timeout(240)
def test_bench_smoke_audit_gate():
    """Audit leg (round 24): run_audit_smoke itself gates every
    recorded-shard tally against the fixture's ground truth × tile,
    the per-issuer folds against a host-recomputed reference-verifier
    oracle, and quarantined == 0 PINNED on the real corpus; here we
    pin that the leg ran at tier-1 scale (>= 10^5 entries through the
    full decode+verify+aggregate path) with real work in every lane
    class and that divergence was actually measured."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_audit_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_audit_smoke"
    assert out["value"] > 0
    assert out["smoke_audit_entries"] >= 100_000
    assert out["smoke_audit_quarantined"] == 0
    assert out["smoke_audit_verified"] > 0
    assert out["smoke_audit_failed"] > 0
    assert out["smoke_audit_no_key"] > 0
    assert out["smoke_audit_retired"] > 0
    assert out["smoke_audit_out_of_interval"] > 0
    assert out["smoke_audit_device_lanes"] > 0
    assert out["smoke_audit_host_lanes"] > 0
    assert out["smoke_audit_per_issuer_groups"] == 8
    # The quarantine pin is only meaningful when the native scanner
    # actually ran against the mirror.
    from ct_mapreduce_tpu.native import load as load_native

    if (os.environ.get("CTMR_NATIVE", "1") != "0"
            and getattr(load_native(), "has_sct", False)):
        assert out["smoke_audit_divergence_measured"] == 1


@pytest.mark.timeout(300)
def test_bench_smoke_tune_gate(monkeypatch):
    """Autotune leg (round 21): run_tune_smoke itself gates a REAL
    scaled-down sweep (staging replay, open-loop serving, ECDSA
    lanes) through the coordinate-descent driver, profile emission
    (fingerprint + provenance), and the end-to-end load check —
    resolve_staging / resolve_serve / resolve_verify returning the
    tuned values through the profile layer alone. Here we pin that
    every sweep really evaluated points and the emitted knobs sit on
    the registry's declared surface. The winning points carry no
    performance claim on this 1-core box (rounds-11/14 convention);
    the real curves come from tools/campaign.py on a device host."""
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    from ct_mapreduce_tpu.tune.registry import SWEEPABLE

    out = bench.run_tune_smoke()  # raises BenchError on any miss
    assert out["metric"] == "ct_tune_smoke"
    assert out["value"] > 0
    assert out["smoke_tune_loaded"] == 1
    assert os.path.exists(out["smoke_tune_profile_path"])
    knobs = out["smoke_tune_knobs"]
    assert set(knobs) == {"staging", "serve", "verify"}
    for section, tuned in knobs.items():
        assert tuned, f"empty tuned section {section}"
        for name in tuned:
            assert name in SWEEPABLE[section]
    for name, st in out["smoke_tune_sweeps"].items():
        assert st["evals"] >= 2, f"{name}: sweep did not search"
        assert st["best_value"] > 0
