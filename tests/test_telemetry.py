"""The telemetry module itself: sink fanout topology, dumper output,
StatsD payload encoding (loopback UDP), sample ring bound, measure()
timing, and the snapshot percentiles (ISSUE 4 satellites)."""

import io
import socket
import time

from ct_mapreduce_tpu.telemetry import metrics
from ct_mapreduce_tpu.telemetry.metrics import (
    InMemSink,
    MetricsDumper,
    StatsdSink,
)


def _restore():
    metrics.set_sink(InMemSink())


def test_fanout_sinks_all_receive():
    primary, extra = InMemSink(), InMemSink()
    metrics.set_sink(primary, extra)
    try:
        metrics.incr_counter("fan", "c", value=2)
        metrics.set_gauge("fan", "g", value=7.0)
        metrics.add_sample("fan", "s", value=0.25)
        for s in (primary, extra):
            snap = s.snapshot()
            assert snap["counters"]["fan.c"] == 2
            assert snap["gauges"]["fan.g"] == 7.0
            assert snap["samples"]["fan.s"]["count"] == 1
        assert metrics.get_sink() is primary
        assert metrics.get_fanout() == [extra]
    finally:
        _restore()


def test_statsd_sink_demoted_to_fanout_keeps_snapshot():
    """set_sink(StatsdSink(...)) must NOT lose snapshot capability:
    an InMemSink stays primary and StatsD rides as fanout, so
    MetricsDumper, /metrics, and the flight recorder work in every
    configuration (the old code made StatsD the primary and
    ``snapshot()`` didn't exist on it)."""
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(5.0)
    port = recv.getsockname()[1]
    sd = StatsdSink("127.0.0.1", port, prefix="ct-fetch.")
    metrics.set_sink(sd)
    try:
        # Primary is snapshot-capable; statsd still receives as fanout.
        primary = metrics.get_sink()
        assert hasattr(primary, "snapshot")
        assert metrics.get_fanout() == [sd]
        metrics.incr_counter("k")  # default value 1.0
        assert recv.recv(512) == b"ct-fetch.k:1.0|c"
        assert primary.snapshot()["counters"]["k"] == 1
    finally:
        _restore()
        recv.close()


def test_statsd_payload_encoding_loopback():
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(5.0)
    port = recv.getsockname()[1]
    sink = StatsdSink("127.0.0.1", port, prefix="p.")
    try:
        sink.incr_counter("a.count", 2.0)
        assert recv.recv(512) == b"p.a.count:2.0|c"
        sink.set_gauge("a.gauge", 1.5)
        assert recv.recv(512) == b"p.a.gauge:1.5|g"
        sink.add_sample("a.time", 0.25)  # seconds -> ms
        assert recv.recv(512) == b"p.a.time:250.000|ms"
    finally:
        sink.close()
        recv.close()


def test_statsd_socket_closed_on_replacement():
    """Replacing a StatsD sink via set_sink closes its UDP socket
    (ISSUE 4 satellite: no fd leak across reconfigurations); sends
    after close are silent no-ops."""
    sd = StatsdSink("127.0.0.1", 1)  # never actually sent to
    metrics.set_sink(sd)
    try:
        assert not sd._closed
        metrics.set_sink(InMemSink())
        assert sd._closed
        assert sd._sock.fileno() == -1
        sd.incr_counter("after.close", 1)  # must not raise
        sd.close()  # idempotent
    finally:
        _restore()


def test_sample_ring_bound():
    sink = InMemSink()
    n = sink.SAMPLE_RING
    for i in range(n + 500):
        sink.add_sample("ring", float(i))
    s = sink.snapshot()["samples"]["ring"]
    assert s["count"] == n
    # The ring keeps the NEWEST window.
    assert s["min"] == 500.0
    assert s["max"] == float(n + 499)


def test_measure_times_the_block():
    sink = InMemSink()
    metrics.set_sink(sink)
    try:
        with metrics.measure("timed", "block"):
            time.sleep(0.02)
        s = sink.snapshot()["samples"]["timed.block"]
        assert s["count"] == 1
        assert 0.015 <= s["mean"] < 5.0
    finally:
        _restore()


def test_snapshot_percentiles():
    """p50/p95/p99 join min/mean/max (the mean hides the tail that
    matters for dispatchLockWait / decode_ns_per_entry)."""
    sink = InMemSink()
    for i in range(1, 101):
        sink.add_sample("lat", float(i))
    s = sink.snapshot()["samples"]["lat"]
    assert s["p50"] == 50.0
    assert s["p95"] == 95.0
    assert s["p99"] == 99.0
    assert s["min"] == 1.0 and s["max"] == 100.0
    # One-sample series: every percentile is that sample.
    sink.add_sample("one", 0.5)
    one = sink.snapshot()["samples"]["one"]
    assert one["p50"] == one["p95"] == one["p99"] == 0.5


def test_dumper_output_format_includes_percentiles():
    sink = InMemSink()
    sink.incr_counter("certIsFilteredOut.CA", 2)
    sink.set_gauge("entries_per_sec_per_chip", 1e7)
    for i in range(1, 21):
        sink.add_sample("store", float(i) / 100.0)
    out = io.StringIO()
    MetricsDumper(sink, period_s=3600, out=out).dump()
    text = out.getvalue()
    assert "[C] certIsFilteredOut.CA: 2" in text
    assert "[G] entries_per_sec_per_chip" in text
    assert "p50=0.100000s" in text
    assert "p95=0.190000s" in text
    assert "p99=0.200000s" in text


def test_dumper_on_snapshot_feeds_recorder():
    """The on_snapshot hook receives every dumped snapshot (the flight
    recorder's feed), and a hook failure never kills the dump."""
    sink = InMemSink()
    sink.incr_counter("c", 1)
    seen = []
    out = io.StringIO()
    MetricsDumper(sink, 3600, out=out, on_snapshot=seen.append).dump()
    assert seen and seen[0]["counters"]["c"] == 1

    def boom(snap):
        raise RuntimeError("recorder died")

    out2 = io.StringIO()
    MetricsDumper(sink, 3600, out=out2, on_snapshot=boom).dump()
    assert "c: 1" in out2.getvalue()  # dump survived the hook
