"""Round-21 autotuner: search driver, profile emission + loading,
campaign resume.

Everything here is device-free by design: the search is exercised on
synthetic surfaces, profile round-trips go through the real
config/profile.py loader, and campaign resume uses ``--stub``
subprocesses (deterministic synthetic evaluators, SIGKILL fault
injection). The real-measurement path is gated by
tests/test_bench_smoke.py::test_bench_smoke_tune_gate.
"""

import json
import os
import random
import signal
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.config import profile as platprofile  # noqa: E402
from ct_mapreduce_tpu.tune import emit, registry, search  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAMPAIGN = os.path.join(REPO, "tools", "campaign.py")

GRID = {
    "chunksPerDispatch": [1, 2, 4, 8],
    "stagingDepth": [1, 2, 3, 4],
    "batch": [256, 1024, 4096],
}
PLANTED = {"chunksPerDispatch": 4, "stagingDepth": 2, "batch": 1024}


def surface(point):
    """Separable bowl with the optimum at PLANTED."""
    s = 0.0
    for k, ladder in GRID.items():
        s -= 100.0 * abs(ladder.index(point[k])
                         - ladder.index(PLANTED[k]))
    return 1000.0 + s


# -- search driver --------------------------------------------------------


def test_search_finds_planted_optimum():
    calls = []

    def evaluate(point, reps):
        calls.append((dict(point), reps))
        return search.EvalResult(mean=surface(point), reps=reps)

    sr = search.coordinate_descent(GRID, evaluate, seed=7,
                                   budget_evals=60)
    assert sr.best == PLANTED
    assert sr.best_value == 1000.0
    assert not sr.budget_exhausted
    assert sr.evals_used <= 60
    # Coordinate descent beats exhaustive: 4*4*3 = 48 points, the
    # search confirmed the optimum on a fraction of the rep budget.
    assert len(calls) < 48
    # Provenance curves cover every axis with measured points through
    # the best.
    assert set(sr.curves) == set(GRID)
    for axis, curve in sr.curves.items():
        assert curve, f"empty curve for {axis}"
        vals = dict(curve)
        assert vals[PLANTED[axis]] == 1000.0


def test_search_finds_optimum_under_noise():
    noise = random.Random(1234)  # deterministic, independent of seed

    def evaluate(point, reps):
        # Noise well under the 100-per-rung separation; more reps
        # average it down like real reps would.
        vals = [surface(point) + noise.gauss(0.0, 8.0)
                for _ in range(reps)]
        m = sum(vals) / len(vals)
        return search.EvalResult(mean=m, reps=reps)

    sr = search.coordinate_descent(GRID, evaluate, seed=3,
                                   reps=(1, 5), budget_evals=200)
    assert sr.best == PLANTED


def test_search_deterministic_given_seed():
    def run(seed):
        order = []

        def evaluate(point, reps):
            order.append((tuple(sorted(point.items())), reps))
            return search.EvalResult(mean=surface(point), reps=reps)

        sr = search.coordinate_descent(GRID, evaluate, seed=seed)
        return order, sr.best, sr.best_value

    a = run(11)
    b = run(11)
    assert a == b  # identical evaluation sequence, not just best


def test_search_budget_exhaustion_returns_partial():
    def evaluate(point, reps):
        return search.EvalResult(mean=surface(point), reps=reps)

    sr = search.coordinate_descent(GRID, evaluate, seed=0,
                                   budget_evals=4, reps=(1, 3))
    assert sr.budget_exhausted
    assert sr.evals_used <= 4 + 3  # last eval may straddle the line
    assert sr.best  # never empty: the start point was confirmed


def test_search_low_rep_probe_cannot_win():
    """Successive halving: a point that looks great at the low-rep
    probe but bad at the high-rep confirm must not end up best."""
    decoy = {"chunksPerDispatch": 8, "stagingDepth": 4, "batch": 4096}

    def evaluate(point, reps):
        if dict(point) == decoy and reps < 3:
            return search.EvalResult(mean=1e9, reps=reps)  # lying probe
        return search.EvalResult(mean=surface(point), reps=reps)

    sr = search.coordinate_descent(GRID, evaluate, seed=5,
                                   reps=(1, 3))
    assert sr.best != decoy
    assert sr.best_value <= 1000.0


def test_search_infeasible_everywhere_is_nan():
    def evaluate(point, reps):
        return search.EvalResult(mean=surface(point), reps=reps,
                                 feasible=False)

    sr = search.coordinate_descent(GRID, evaluate, seed=0)
    assert sr.best_value != sr.best_value  # NaN: nothing confirmed
    # ...and emission refuses to tune from it (no knobs, no NaN bytes).
    m = _FakeMeasurement("staging")
    prof = emit.build_profile([(m, sr)], platform="t",
                              fingerprint={})
    assert prof["knobs"] == {}
    assert prof["provenance"]["staging"]["fake"]["best_value"] is None
    assert b"NaN" not in emit.profile_bytes(prof)


def test_search_rejects_bad_grid_and_start():
    def evaluate(point, reps):
        return search.EvalResult(mean=0.0, reps=reps)

    with pytest.raises(ValueError):
        search.coordinate_descent({}, evaluate)
    with pytest.raises(ValueError):
        search.coordinate_descent({"a": []}, evaluate)
    with pytest.raises(ValueError):
        search.coordinate_descent({"a": [1, 2]}, evaluate,
                                  start={"a": 99})


# -- profile emission + loading -------------------------------------------


class _FakeMeasurement:
    def __init__(self, section, name="fake", metric="rate",
                 unit="1/s"):
        self.section = section
        self.name = name
        self.metric = metric
        self.unit = unit


def _searched(best, value=123.0):
    sr = search.SearchResult(best=dict(best), best_value=value)
    sr.evaluations = [(dict(best), 3, None)]
    sr.curves = {k: [[v, value]] for k, v in best.items()}
    sr.wall_s = 1.5
    return sr


def test_profile_bytes_deterministic_and_knobs_filtered():
    m = _FakeMeasurement("staging")
    sr = _searched({"chunksPerDispatch": 4, "stagingDepth": 2,
                    "maxBatch": 64})  # maxBatch: swept, NOT a knob
    prof = emit.build_profile([(m, sr)], platform="test-host",
                              fingerprint={"device_kind": "x"})
    assert prof["knobs"] == {"staging": {"chunksPerDispatch": 4,
                                         "stagingDepth": 2}}
    prov = prof["provenance"]["staging"]["fake"]
    assert prov["best_point"]["maxBatch"] == 64  # provenance keeps it
    assert prov["evals"] == 1 and prov["reps"] == 3
    assert emit.profile_bytes(prof) == emit.profile_bytes(prof)
    # Same inputs -> same bytes (no timestamps, no env leakage).
    prof2 = emit.build_profile([(m, sr)], platform="test-host",
                               fingerprint={"device_kind": "x"})
    assert emit.profile_bytes(prof) == emit.profile_bytes(prof2)


def test_profile_roundtrip_through_loader(tmp_path):
    m = _FakeMeasurement("staging")
    sr = _searched({"chunksPerDispatch": 8, "stagingDepth": 3})
    prof = emit.build_profile([(m, sr)], fingerprint={})
    path = str(tmp_path / "p.json")
    emit.write_profile(path, prof)
    loaded = platprofile.load_profile(path)
    assert loaded is not None
    assert loaded["knobs"]["staging"]["chunksPerDispatch"] == 8
    assert loaded["version"] == platprofile.PROFILE_VERSION


def test_fingerprint_match_and_mismatch(tmp_path):
    base = {"version": 1, "knobs": {"staging": {"stagingDepth": 3}}}
    ok = str(tmp_path / "ok.json")
    with open(ok, "w") as fh:
        json.dump(dict(base, fingerprint={
            "host_cores": os.cpu_count() or 1}), fh)
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as fh:
        json.dump(dict(base, fingerprint={"host_cores": -1}), fh)
    legacy = str(tmp_path / "legacy.json")
    with open(legacy, "w") as fh:
        json.dump(base, fh)  # round-18 profile: no fingerprint block
    try:
        assert platprofile.load_profile(ok) is not None
        assert platprofile.load_profile(bad) is None  # warn + ignore
        assert platprofile.load_profile(legacy) is not None
        # Partial fingerprints compare only shared keys.
        assert platprofile.fingerprint_matches({})
        assert platprofile.fingerprint_matches(
            {"unknown_key": "whatever"})
        assert not platprofile.fingerprint_matches(
            {"host_cores": -1}, {"host_cores": 4})
    finally:
        platprofile.invalidate_cache()


def test_provenance_tolerant_load(tmp_path):
    base = {"version": 1, "knobs": {"staging": {"stagingDepth": 2}}}
    odd = str(tmp_path / "odd.json")
    with open(odd, "w") as fh:
        json.dump(dict(base, provenance={
            "future_section": {"future_measure": {"anything": [1]}}},
            extra_future_block=42), fh)
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as fh:
        json.dump(dict(base, provenance=["not", "a", "dict"]), fh)
    try:
        loaded = platprofile.load_profile(odd)
        assert loaded is not None  # unknown provenance content is fine
        assert loaded["knobs"]["staging"]["stagingDepth"] == 2
        assert platprofile.load_profile(bad) is None  # wrong shape
    finally:
        platprofile.invalidate_cache()


def test_explain_section_layers(tmp_path, monkeypatch):
    knobs = (
        platprofile.Knob(name="alpha", env="CTMR_TEST_ALPHA",
                         default=10,
                         is_set=platprofile.pos_int),
        platprofile.Knob(name="beta", env="", default=20,
                         is_set=platprofile.pos_int),
    )
    path = str(tmp_path / "prof.json")
    with open(path, "w") as fh:
        json.dump({"version": 1,
                   "knobs": {"testsec": {"alpha": 3, "beta": 4}}}, fh)
    monkeypatch.delenv("CTMR_TEST_ALPHA", raising=False)
    platprofile.set_active_profile(path)
    platprofile.invalidate_cache()
    try:
        rows = platprofile.explain_section("testsec", knobs)
        assert rows["alpha"] == {"value": 3, "layer": "profile"}
        assert rows["beta"] == {"value": 4, "layer": "profile"}
        monkeypatch.setenv("CTMR_TEST_ALPHA", "7")
        rows = platprofile.explain_section("testsec", knobs)
        assert rows["alpha"] == {"value": 7, "layer": "env"}
        rows = platprofile.explain_section("testsec", knobs,
                                           {"alpha": 9})
        assert rows["alpha"] == {"value": 9, "layer": "explicit"}
        platprofile.set_active_profile(None)
        monkeypatch.delenv("CTMR_PLATFORM_PROFILE", raising=False)
        monkeypatch.delenv("CTMR_TEST_ALPHA", raising=False)
        rows = platprofile.explain_section("testsec", knobs)
        assert rows["alpha"] == {"value": 10, "layer": "default"}
        assert rows["beta"] == {"value": 20, "layer": "default"}
        # explain and resolve agree (same ladder, one implementation).
        assert {k: r["value"] for k, r in rows.items()} == \
            platprofile.resolve_section("testsec", knobs, {})
    finally:
        platprofile.set_active_profile(None)
        platprofile.invalidate_cache()


# -- registry -------------------------------------------------------------


def test_registry_covers_every_knob():
    problems = registry.audit()
    assert problems == []


def test_registry_sections_match_measurements():
    from ct_mapreduce_tpu.tune import measure

    for name, m in measure.measurements().items():
        assert m.section in registry.SECTIONS, name
        grid = m.grid("smoke")
        for knob in grid:
            # Every swept PROFILE knob must be declared sweepable;
            # extra measurement axes (maxBatch...) must NOT collide
            # with any declared knob name of the section.
            if knob in registry.SWEEPABLE.get(m.section, {}):
                continue
            assert knob not in registry.EXCLUDED.get(m.section, {}), \
                f"{name} sweeps excluded knob {knob}"


# -- campaign resume ------------------------------------------------------


def _run_campaign(state, fault=None, timeout=120):
    env = dict(os.environ)
    env.pop("CTMR_CAMPAIGN_FAULT", None)
    if fault:
        env["CTMR_CAMPAIGN_FAULT"] = fault
    return subprocess.run(
        [sys.executable, CAMPAIGN, "--state", str(state), "--stub",
         "--scale", "smoke"],
        capture_output=True, text=True, env=env, timeout=timeout)


@pytest.mark.timeout(300)
def test_campaign_sigkill_resume(tmp_path):
    state = tmp_path / "state"
    # Kill mid-campaign: verify_sweep's work finishes but its
    # checkpoint never lands (the worst preemption instant).
    p = _run_campaign(state, fault="verify_sweep")
    assert p.returncode == -signal.SIGKILL
    done = sorted(f for f in os.listdir(state) if f.endswith(".json"))
    assert done == ["leg-serve_openloop.json", "leg-staged_e2e.json"]
    # Resume: completed legs skip, the killed leg reruns, the campaign
    # finishes and emits the profile.
    p = _run_campaign(state)
    assert p.returncode == 0, p.stderr
    assert p.stderr.count("checkpoint found") == 2
    out = json.loads(p.stdout)
    assert out["metric"] == "ct_device_campaign"
    legs = out["legs"]
    assert legs["staged_e2e"]["state"] == "resumed"
    assert legs["serve_openloop"]["state"] == "resumed"
    for leg in ("verify_sweep", "fleet_scale", "filter_device",
                "tuned_profile"):
        assert legs[leg]["state"] == "ran"
    prof_path = legs["tuned_profile"]["profile_path"]
    assert os.path.exists(prof_path)
    # The emitted profile loads through the real config loader (the
    # stub fingerprint has no host keys, so it matches everywhere).
    loaded = platprofile.load_profile(prof_path)
    platprofile.invalidate_cache()
    assert loaded is not None
    assert set(loaded["knobs"]) == {"staging", "serve", "verify",
                                    "fleet", "filter"}
    # A third run is a pure resume: every measurement leg skips.
    p = _run_campaign(state)
    assert p.returncode == 0, p.stderr
    assert p.stderr.count("checkpoint found") == 5


@pytest.mark.timeout(300)
def test_campaign_deterministic_and_torn_checkpoint(tmp_path):
    a = tmp_path / "a"
    b = tmp_path / "b"
    assert _run_campaign(a).returncode == 0
    assert _run_campaign(b).returncode == 0
    pa = open(a / "tuned_profile.json", "rb").read()
    pb = open(b / "tuned_profile.json", "rb").read()
    assert pa == pb  # stub campaign output is byte-deterministic
    # A torn checkpoint (truncated JSON) must rerun its leg, not
    # crash or be trusted.
    with open(a / "leg-fleet_scale.json", "w") as fh:
        fh.write('{"leg": "fleet_sc')
    p = _run_campaign(a)
    assert p.returncode == 0, p.stderr
    assert "leg fleet_scale: sweeping" in p.stderr


def test_campaign_legs_cover_the_five_device_runs():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import campaign
    finally:
        sys.path.pop(0)
    from ct_mapreduce_tpu.tune import measure

    # The consolidated campaign executes all five pending device runs
    # (ROADMAP item 1) + the profile leg, in this order.
    assert [n for n, _ in campaign.MEASURE_LEGS] == [
        "staged_e2e", "serve_openloop", "verify_sweep", "fleet_scale",
        "filter_device"]
    assert campaign.LEGS[-1] == "tuned_profile"
    have = measure.measurements()
    sections = set()
    for _leg, mname in campaign.MEASURE_LEGS:
        assert mname in have
        sections.add(have[mname].section)
    assert sections == {"staging", "serve", "verify", "fleet",
                        "filter"}


# -- CLI ------------------------------------------------------------------


def test_cli_show_renders_ladder(capsys):
    from ct_mapreduce_tpu.tune import cli

    rc = cli.main(["show"])
    out = capsys.readouterr().out
    assert rc == 0
    for section in registry.SECTIONS:
        assert f"[{section}]" in out
    assert "chunksPerDispatch" in out
    assert "(default; sweepable)" in out
    assert "(default; excluded)" in out
