"""Overlapped ingest scheduler (ingest/overlap.py): parity, ordering,
and failure semantics.

Fixtures come from ``ct_mapreduce_tpu.utils.minicert`` (hand-assembled
canonical DER) so this suite runs on hosts without the ``cryptography``
package — the ingest path parses and never verifies, so synthetic
signature bytes are within contract.
"""

import base64
import datetime
import threading
import time

import numpy as np
import pytest

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.ingest.overlap import OverlapError
from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
from ct_mapreduce_tpu.native import leafpack
from ct_mapreduce_tpu.utils import minicert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2025, 1, 1, tzinfo=UTC)

ISSUERS = [minicert.make_cert(serial=1, issuer_cn=f"Ovl CA {k}", is_ca=True)
           for k in range(2)]


def wire_batch(start: int, n: int, duplicate_of: int | None = None):
    """n wire entries alternating two issuers; serials start..start+n
    (or re-serials of an earlier window when ``duplicate_of`` is set,
    for cross-batch dedup coverage)."""
    lis, eds = [], []
    base = duplicate_of if duplicate_of is not None else start
    for j in range(n):
        k = j % 2
        leaf = minicert.make_cert(
            serial=base + j, issuer_cn=f"Ovl CA {k}",
            subject_cn="ovl.example", is_ca=False,
        )
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(leaf, 1000 + start + j)).decode())
        eds.append(base64.b64encode(
            leaflib.encode_extra_data([ISSUERS[k]])).decode())
    return RawBatch(lis, eds, start, "ovl-log")


def make_sink(overlap_workers: int, depth: int = 2, flush_size: int = 32):
    agg = TpuAggregator(capacity=1 << 12, batch_size=flush_size, now=NOW)
    sink = AggregatorSink(agg, flush_size=flush_size,
                          device_queue_depth=depth,
                          overlap_workers=overlap_workers)
    return agg, sink


def test_overlap_exact_parity_with_serial():
    """Same wire batches through the serial path and the overlap
    scheduler: (was_unknown totals, host_lane, table_count, per-issuer
    counts) must match EXACTLY — insertion order is preserved by the
    submit-stage reorder point, so even cross-batch duplicates
    attribute identically."""
    batches = [wire_batch(i * 64, 64) for i in range(4)]
    # Batch 4 duplicates batch 1's serials: dedup must attribute the
    # first sighting to batch 1 on both paths.
    batches.append(wire_batch(4 * 64, 64, duplicate_of=0))

    def run(overlap_workers):
        agg, sink = make_sink(overlap_workers)
        for rb in batches:
            sink.store_raw_batch(rb)
        sink.close()
        snap = agg.drain()
        return {
            "counts": snap.counts,
            "total": snap.total,
            "table_count": int(np.asarray(agg.table.count)),
            "host_lane": agg.metrics["host_lane"],
            "inserted": agg.metrics["inserted"],
            "known": agg.metrics["known"],
            "issuer_totals": agg.issuer_totals.copy(),
        }

    serial = run(0)
    over = run(2)
    assert serial["total"] == over["total"] == 4 * 64
    assert serial["table_count"] == over["table_count"]
    assert serial["host_lane"] == over["host_lane"] == 0
    assert serial["counts"] == over["counts"]
    assert serial["inserted"] == over["inserted"]
    assert serial["known"] == over["known"] == 64  # the duplicate batch
    np.testing.assert_array_equal(serial["issuer_totals"],
                                  over["issuer_totals"])


def test_overlap_ordered_drain_under_slow_consumer():
    """A slow drain consumer must not reorder completions (FIFO =
    submission order) nor stall submissions beyond the configured
    depth — batch N+1 submits while N still drains."""
    agg, sink = make_sink(overlap_workers=2, depth=2)
    events = []
    ev_lock = threading.Lock()
    orig_submit = sink._submit_chunk
    orig_complete = sink._complete_item

    def slow_complete(pending, der_of):
        time.sleep(0.05)
        with ev_lock:
            events.append(("complete", id(pending)))
        orig_complete(pending, der_of)

    def spy_submit(prep):
        items = orig_submit(prep)
        with ev_lock:
            for kind, payload, _ in items:
                if kind == "pending":
                    events.append(("submit", id(payload)))
        return items

    sink._complete_item = slow_complete
    sink._submit_chunk = spy_submit
    for i in range(5):
        sink.store_raw_batch(wire_batch(i * 32, 32))
    sink.close()
    assert agg.drain().total == 5 * 32

    sub_ids = [i for k, i in events if k == "submit"]
    com_ids = [i for k, i in events if k == "complete"]
    assert len(sub_ids) == len(com_ids) == 5
    # FIFO drain: completion order equals submission order.
    assert com_ids == sub_ids
    # Pipelining: at least one submit happened before the first
    # completion (the slow consumer did not serialize the stages).
    kinds = [k for k, _ in events]
    assert kinds.index("complete") >= 2


def test_overlap_decode_failure_surfaces_and_shuts_down():
    """A decode worker raising mid-epoch must neither hang the queues
    nor get swallowed: the failure latches, flush()/close() raise
    OverlapError with the original as __cause__, and work already
    submitted to the device is still completed (counts exact for it)."""
    agg, sink = make_sink(overlap_workers=2, depth=2)
    boom = RuntimeError("decoder exploded")
    orig_prepare = sink._prepare_chunk
    calls = {"n": 0}

    def failing_prepare(pairs):
        calls["n"] += 1
        if calls["n"] == 2:
            raise boom
        return orig_prepare(pairs)

    sink._prepare_chunk = failing_prepare
    deadline = time.monotonic() + 60.0  # the no-hang budget
    with pytest.raises(OverlapError) as exc_info:
        for i in range(4):
            sink.store_raw_batch(wire_batch(i * 32, 32))
        sink.flush()
    assert exc_info.value.__cause__ is boom
    # Subsequent submissions refuse immediately.
    with pytest.raises(OverlapError):
        sink.store_raw_batch(wire_batch(999 * 32, 32))
    with pytest.raises(OverlapError):
        sink.close()
    assert time.monotonic() < deadline, "shutdown hung"
    # Whatever reached the device before the failure folded exactly:
    # chunk 1 always did (ordered submit), chunk 2 died in decode.
    total = agg.drain().total
    assert total % 32 == 0 and 32 <= total <= 3 * 32


def test_overlap_flush_is_reusable_barrier():
    """flush() drains everything in flight but keeps the pipeline
    alive: a second wave of batches lands exactly on the same sink."""
    agg, sink = make_sink(overlap_workers=2)
    sink.store_raw_batch(wire_batch(0, 64))
    sink.flush()
    assert agg.drain().total == 64
    sink.store_raw_batch(wire_batch(64, 64))
    sink.close()
    assert agg.drain().total == 128


def test_issuer_too_long_status_skips_futile_redecode():
    """Satellite (ADVICE r05): a >=2 MiB issuer DER gets its own
    status (ISSUER_TOO_LONG) — the cert itself packed fine, so the
    batch must NOT pay a full-width redecode that cannot clear it —
    and the entry still lands via the exact host lane."""
    huge_issuer = minicert.make_cert(
        serial=1, issuer_cn="Huge CA", is_ca=True,
        extra_ext_bytes=(1 << 21) + 256,
    )
    assert len(huge_issuer) >= (1 << 21)
    small = [minicert.make_cert(serial=50 + i, issuer_cn="Ovl CA 0",
                                subject_cn="s.example", is_ca=False)
             for i in range(3)]
    victim = minicert.make_cert(serial=99, issuer_cn="Huge CA",
                                subject_cn="v.example", is_ca=False)

    lis = [base64.b64encode(leaflib.encode_leaf_input(d, i)).decode()
           for i, d in enumerate(small + [victim])]
    eds = ([base64.b64encode(
        leaflib.encode_extra_data([ISSUERS[0]])).decode()] * len(small)
        + [base64.b64encode(
            leaflib.encode_extra_data([huge_issuer])).decode()])

    # Decoder level: dedicated status on BOTH lanes of the fallback
    # matrix (native when a compiler exists, pure Python always).
    dec_py = leafpack._decode_python(lis, eds, 2048)
    assert dec_py.status[-1] == leafpack.ISSUER_TOO_LONG
    assert dec_py.length[-1] == len(victim)  # the cert row IS packed
    from ct_mapreduce_tpu.native import available
    if available():
        dec_nat = leafpack.decode_raw_batch(lis, eds, 2048)
        np.testing.assert_array_equal(dec_nat.status, dec_py.status)

    # Sink level: the narrow pre-decode stays a SINGLE decode (the old
    # overloaded TOO_LONG forced a futile full-width redecode here).
    pads_seen = []
    orig = leafpack.decode_raw_batch

    def spy(l, e, pad_len, workers=None, threads=None):
        pads_seen.append(pad_len)
        return orig(l, e, pad_len, workers=workers, threads=threads)

    agg, sink = make_sink(overlap_workers=0, flush_size=64)
    leafpack.decode_raw_batch = spy
    try:
        sink.store_raw_batch(RawBatch(lis, eds, 0, "log"))
        sink.flush()
    finally:
        leafpack.decode_raw_batch = orig
    assert pads_seen == [sink.PAD_LEN // 2], pads_seen
    # ... and the oversized-issuer entry still counted, exactly once.
    assert agg.drain().total == len(small) + 1


def test_overlap_queue_highwater_gauges():
    """The bounded-queue high-water marks (prepared window + drain
    queue) are tracked and exported as gauges — the smoke gate's
    handle for telling a decode-starved pipeline from a drain-starved
    one."""
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    agg, sink = make_sink(overlap_workers=2, depth=2)
    ovl = sink._overlap
    for i in range(6):
        sink.store_raw_batch(wire_batch(i * 32, 32))
    ovl.drain_all()
    hw = ovl.publish_highwater()
    cap_prepared, cap_drain = ovl._max_prepared, ovl.queue_depth
    sink.close()
    assert 1 <= hw["prepared"] <= cap_prepared
    assert 0 <= hw["drain_queue"] <= cap_drain
    # Gauges really were exported through the metrics API.
    sink_metrics2 = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink_metrics2)
    try:
        agg2, sink2 = make_sink(overlap_workers=2, depth=1)
        ovl2 = sink2._overlap
        for i in range(4):
            sink2.store_raw_batch(wire_batch(i * 32, 32))
        ovl2.drain_all()
        occ = ovl2.occupancy(1.0)
        sink2.close()
    finally:
        tmetrics.set_sink(prev)
    gauges = sink_metrics2.snapshot()["gauges"]
    for key in ("overlap.prepared_highwater", "overlap.prepared_capacity",
                "overlap.drain_queue_highwater",
                "overlap.drain_queue_capacity"):
        assert key in gauges, sorted(gauges)
    assert gauges["overlap.prepared_highwater"] >= 1
    assert "lock" in occ  # dispatch-lock wait is its own occupancy bucket


def test_overlap_lock_wait_sampled_outside_store_envelope():
    """dispatchLockWait is its own sample and the storeCertificate
    envelope opens only after the lock is held — the bench's submit
    budget must not fold lock contention into submit cost."""
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    sink_metrics = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink_metrics)
    try:
        agg, sink = make_sink(overlap_workers=2, depth=2)
        for i in range(4):
            sink.store_raw_batch(wire_batch(i * 32, 32))
        sink.close()
    finally:
        tmetrics.set_sink(prev)
    samples = sink_metrics.snapshot()["samples"]
    assert "ct-fetch.dispatchLockWait" in samples
    assert "ct-fetch.storeCertificate" in samples
    # One lock sample per submitted chunk (4 chunks + flush barrier
    # paths), all non-negative.
    assert samples["ct-fetch.dispatchLockWait"]["count"] >= 4
    assert samples["ct-fetch.dispatchLockWait"]["min"] >= 0.0
