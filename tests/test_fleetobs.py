"""Fleet observability plane (telemetry/fleetobs.py, round 23): obs
knob ladder, traceparent propagation, clock-skew correction math,
merged timelines, metrics fan-in parity, SLO threshold edges, and the
fleet health rollup — plus the promhttp fleet routes and the
batcher's trace-context adoption.

The live W=2 cross-process legs (one merged timeline across worker
pids, fleet-scrape parity against per-worker scrapes, SIGSTOP →
rollup 503) run in bench.run_obs_smoke, gated by
tests/test_bench_smoke.py; everything here is in-process."""

import http.server
import json
import threading
import urllib.error
import urllib.request

import pytest

from ct_mapreduce_tpu.telemetry import fleetobs, metrics, trace
from ct_mapreduce_tpu.telemetry.fleetobs import ObsKnobs
from ct_mapreduce_tpu.telemetry.metrics import InMemSink
from ct_mapreduce_tpu.telemetry.promhttp import MetricsServer


# -- obs knob ladder -----------------------------------------------------


def test_resolve_obs_defaults(monkeypatch):
    for var in ("CTMR_FLEET_METRICS", "CTMR_SLO_MAX_INGEST_LAG",
                "CTMR_SLO_MAX_CKPT_AGE_S", "CTMR_SLO_MAX_FILTER_LAG",
                "CTMR_SLO_MAX_SERVE_P99_MS"):
        monkeypatch.delenv(var, raising=False)
    knobs = fleetobs.resolve_obs()
    assert knobs.fleet_metrics is True
    assert knobs.max_ingest_lag == 0
    assert knobs.max_ckpt_age_s == 0.0
    assert knobs.max_filter_lag == 0
    assert knobs.max_serve_p99_ms == 0.0
    assert not knobs.any_slo()


def test_resolve_obs_env_and_explicit(monkeypatch):
    monkeypatch.setenv("CTMR_FLEET_METRICS", "0")
    monkeypatch.setenv("CTMR_SLO_MAX_INGEST_LAG", "5")
    monkeypatch.setenv("CTMR_SLO_MAX_SERVE_P99_MS", "12.5")
    knobs = fleetobs.resolve_obs()
    assert knobs.fleet_metrics is False
    assert knobs.max_ingest_lag == 5
    assert knobs.max_serve_p99_ms == 12.5
    assert knobs.any_slo()
    # Explicit (config directive) outranks env; an unset explicit
    # (0 / None) falls through to the env layer.
    knobs = fleetobs.resolve_obs(fleet_metrics=True, max_ingest_lag=9,
                                 max_serve_p99_ms=0.0)
    assert knobs.fleet_metrics is True
    assert knobs.max_ingest_lag == 9
    assert knobs.max_serve_p99_ms == 12.5


# -- traceparent ---------------------------------------------------------


def test_traceparent_roundtrip():
    header, trace_id, span_id = trace.mint_traceparent()
    assert trace.parse_traceparent(header) == (trace_id, span_id)
    assert len(trace_id) == 32 and len(span_id) == 16
    assert trace.format_traceparent(trace_id, span_id) == header


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-beef-01",
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",   # non-hex
    "00-" + "a" * 32 + "-" + "b" * 16,            # missing flags
    "0-" + "a" * 32 + "-" + "b" * 16 + "-01",     # bad version width
])
def test_traceparent_malformed(bad):
    assert trace.parse_traceparent(bad) is None


def test_trace_context_scoping_and_noop():
    assert trace.get_trace_context() is None
    with trace.trace_context("a" * 32, "b" * 16):
        assert trace.get_trace_context() == ("a" * 32, "b" * 16)
        # Falsy trace_id = no-op: the outer context survives.
        with trace.trace_context(None):
            assert trace.get_trace_context() == ("a" * 32, "b" * 16)
        with trace.trace_context("c" * 32, "d" * 16):
            assert trace.get_trace_context() == ("c" * 32, "d" * 16)
        assert trace.get_trace_context() == ("a" * 32, "b" * 16)
    assert trace.get_trace_context() is None


def test_span_args_carry_context_and_process_attrs():
    tracer = trace.SpanTracer(path=None, ring_size=64)
    trace.set_process_attrs(worker=3)
    try:
        with trace.trace_context("a" * 32, "b" * 16):
            with tracer.span("obs.test", cat="test", k=1):
                pass
        with tracer.span("obs.plain"):
            pass
    finally:
        trace.set_process_attrs(worker=None)
    evs = {e["name"]: e for e in tracer.events() if e.get("ph") == "X"}
    tagged = evs["obs.test"]["args"]
    assert tagged["trace_id"] == "a" * 32
    assert tagged["parent_id"] == "b" * 16
    assert tagged["worker"] == 3
    assert tagged["k"] == 1  # span-local args win, nothing dropped
    plain = evs["obs.plain"].get("args", {})
    assert "trace_id" not in plain and plain.get("worker") == 3


# -- clock skew + merge --------------------------------------------------


def test_clock_offset_and_correction():
    pair = {"wall": 1000.0, "mono": 100.0}
    assert fleetobs.clock_offset(pair) == 900.0
    # event at ts=5µs, tracer anchored at mono 10.0 → wall-epoch µs
    assert fleetobs.corrected_epoch_us(5.0, 10.0, 900.0) == 910e6 + 5.0


def _doc(worker, pid, wall_t0, mono_t0, events):
    return {
        "traceEvents": events,
        "otherData": {"wall_t0": wall_t0, "mono_t0": mono_t0,
                      "pid": pid, "process_attrs": {"worker": worker}},
    }


def test_merge_traces_rebases_and_corrects_skew():
    # Both workers started at mono=100; worker 1's wall clock reads
    # 0.5s fast. Its event really happened 100µs after worker 0's.
    d0 = _doc(0, 11, 1000.0, 100.0,
              [{"name": "a", "ph": "X", "ts": 0.0, "dur": 5.0,
                "pid": 11, "tid": 1}])
    d1 = _doc(1, 22, 1000.5, 100.0,
              [{"name": "b", "ph": "X", "ts": 100.0, "dur": 5.0,
                "pid": 22, "tid": 1}])

    # Without fabric pairs: each doc's own startup pair → worker 1's
    # wall skew leaks into the timeline (b lands 500100µs in).
    merged = fleetobs.merge_traces([d0, d1])
    by_name = {e["name"]: e for e in merged["traceEvents"]
               if e.get("ph") == "X"}
    assert by_name["a"]["ts"] == 0.0
    assert by_name["b"]["ts"] == pytest.approx(500100.0)
    assert merged["otherData"]["merged_from"] == 2
    assert merged["otherData"]["skew_corrected"] is False

    # Fabric pair for worker 1 carries its TRUE offset → corrected.
    pairs = {1: {"wall": 1000.0, "mono": 100.0}}
    merged = fleetobs.merge_traces([d0, d1], pairs=pairs)
    by_name = {e["name"]: e for e in merged["traceEvents"]
               if e.get("ph") == "X"}
    assert by_name["a"]["ts"] == 0.0
    assert by_name["b"]["ts"] == pytest.approx(100.0)
    assert merged["otherData"]["skew_corrected"] is True
    labels = {e["args"]["name"] for e in merged["traceEvents"]
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert labels == {"worker 0 (pid 11)", "worker 1 (pid 22)"}


# -- obs payloads --------------------------------------------------------


def _payload(worker, counters=None, gauges=None, samples=None,
             fleet=None, slo=None, wall=None):
    import time as _time

    sink = InMemSink()
    for k, v in (counters or {}).items():
        sink.incr_counter(k, v)
    for k, v in (gauges or {}).items():
        sink.set_gauge(k, v)
    for k, vals in (samples or {}).items():
        for v in vals:
            sink.add_sample(k, v)
    raw = fleetobs.build_obs_payload(worker, 2, fleet_stats=fleet,
                                     slo=slo, sink=sink)
    doc = fleetobs.parse_obs_payload(raw)
    assert doc is not None
    if wall is not None:
        doc["wall"] = wall
    return doc


def test_obs_payload_roundtrip_and_tolerant_parse():
    doc = _payload(1, counters={"a.b": 3},
                   fleet={"role": "leader"}, slo={"degraded": []})
    assert doc["worker"] == 1 and doc["num_workers"] == 2
    assert doc["metrics"]["counters"]["a.b"] == 3.0
    assert doc["fleet"]["role"] == "leader"
    assert "wall" in doc and "mono" in doc

    assert fleetobs.parse_obs_payload("not json {") is None
    assert fleetobs.parse_obs_payload(json.dumps([1, 2])) is None
    assert fleetobs.parse_obs_payload(
        json.dumps({"v": fleetobs.OBS_VERSION + 1, "metrics": {}})) is None

    raw = {0: json.dumps({"v": 1, "worker": 0, "metrics": {}}),
           1: "garbage"}
    got = fleetobs.collect_fleet_obs(raw)
    assert list(got) == [0]


def test_clock_pairs_from_obs():
    docs = {0: {"wall": 10.0, "mono": 2.0}, 1: {"wall": 11.0}}
    pairs = fleetobs.clock_pairs_from_obs(docs)
    assert pairs == {0: {"wall": 10.0, "mono": 2.0}}


# -- metrics fan-in ------------------------------------------------------


def test_render_fleet_metrics_parity_and_labels():
    payloads = {
        0: _payload(0, counters={"serve.requests": 3, "only.w0": 1},
                    gauges={"fleet.is_leader": 1.0},
                    samples={"serve.wait_s": [0.01, 0.02]}),
        1: _payload(1, counters={"serve.requests": 4.5}),
    }
    body = fleetobs.render_fleet_metrics(payloads)
    lines = body.splitlines()
    assert 'serve_requests{worker="0"} 3' in lines
    assert 'serve_requests{worker="1"} 4.5' in lines
    assert "serve_requests 7.5" in lines        # fleet-summed
    assert "only_w0 1" in lines                 # single-worker total
    # Gauges/samples render per-worker only — no unlabeled sum line.
    assert 'fleet_is_leader{worker="0"} 1' in lines
    assert not any(line.startswith("fleet_is_leader ") for line in lines)
    assert 'serve_wait_s_count{worker="0"} 2' in lines

    assert fleetobs.fleet_counter_parity(body) == []
    # A tampered total is caught (the smoke gate's assertion).
    broken = body.replace("\nserve_requests 7.5\n",
                          "\nserve_requests 9\n")
    assert fleetobs.fleet_counter_parity(broken) == ["serve_requests"]


# -- SLO rules -----------------------------------------------------------


class _FakeTracer:
    def __init__(self, durs_us):
        self._durs = durs_us

    def events(self):
        return [{"name": "serve.wait", "ph": "X", "ts": 0.0, "dur": d}
                for d in self._durs] + [{"name": "other", "ph": "X",
                                         "ts": 0.0, "dur": 1e9}]


def test_serve_p99_ms():
    durs = [1000.0 * (i + 1) for i in range(100)]  # 1ms..100ms
    assert fleetobs.serve_p99_ms(_FakeTracer(durs)) == \
        pytest.approx(99.0)
    assert fleetobs.serve_p99_ms(_FakeTracer([])) is None


def test_evaluate_slos_threshold_edges():
    knobs = ObsKnobs(fleet_metrics=True, max_ingest_lag=10,
                     max_ckpt_age_s=5.0, max_filter_lag=2,
                     max_serve_p99_ms=50.0)
    snap = {"gauges": {"ingest.lag_entries.log-a": 11.0,
                       "ingest.lag_entries.log-b": 3.0,
                       "unrelated.gauge": 99.0}}
    values, degraded = fleetobs.evaluate_slos(
        knobs, snap, now=100.0, last_checkpoint_wall=90.0,
        filter_epoch_lag=3, p99_ms=60.0)
    assert values["ingest_lag_entries"] == 11.0  # worst log wins
    assert values["checkpoint_age_s"] == 10.0
    assert values["filter_epoch_lag"] == 3.0
    assert values["serve_p99_ms"] == 60.0
    assert len(degraded) == 4

    # At-threshold values do NOT breach (strictly greater-than).
    snap = {"gauges": {"ingest.lag_entries.log-a": 10.0}}
    values, degraded = fleetobs.evaluate_slos(
        knobs, snap, now=100.0, last_checkpoint_wall=95.0,
        filter_epoch_lag=2, p99_ms=50.0)
    assert degraded == []

    # Checkpoint age grades against max(threshold, cadence): a 30s
    # cadence can't flap a 5s threshold.
    _, degraded = fleetobs.evaluate_slos(
        knobs, None, now=100.0, last_checkpoint_wall=90.0,
        checkpoint_period_s=30.0)
    assert degraded == []
    # ... but beyond the cadence it still breaches.
    _, degraded = fleetobs.evaluate_slos(
        knobs, None, now=131.0, last_checkpoint_wall=100.0,
        checkpoint_period_s=30.0)
    assert degraded and "checkpoint_age" in degraded[0]

    # No first checkpoint yet → no signal, no flapping at startup.
    values, degraded = fleetobs.evaluate_slos(
        knobs, None, now=100.0, last_checkpoint_wall=0.0)
    assert "checkpoint_age_s" not in values and degraded == []

    # Disabled thresholds record values but never degrade.
    off = ObsKnobs(fleet_metrics=True, max_ingest_lag=0,
                   max_ckpt_age_s=0.0, max_filter_lag=0,
                   max_serve_p99_ms=0.0)
    snap = {"gauges": {"ingest.lag_entries.log-a": 1e9}}
    values, degraded = fleetobs.evaluate_slos(
        off, snap, now=1e9, last_checkpoint_wall=1.0,
        filter_epoch_lag=1000, p99_ms=1e6)
    assert values and degraded == []


def test_publish_slo_gauges():
    fleetobs.publish_slo_gauges({"ingest_lag_entries": 11.0}, ["breach"])
    gauges = metrics.get_sink().snapshot()["gauges"]
    assert gauges["slo.ingest_lag_entries"] == 11.0
    assert gauges["slo.degraded"] == 1.0
    fleetobs.publish_slo_gauges({}, [])
    assert metrics.get_sink().snapshot()["gauges"]["slo.degraded"] == 0.0


# -- fleet health rollup -------------------------------------------------


def _health_payloads(now):
    p0 = _payload(0, gauges={"ckpt.chain_length": 3.0},
                  fleet={"role": "leader", "checkpoint_epoch": 5,
                         "claims": ["log-a"], "checkpoints_run": 2},
                  slo={"degraded": []}, wall=now)
    p1 = _payload(1, fleet={"role": "follower", "checkpoint_epoch": 5},
                  wall=now - 1.0)
    return p0, p1


def test_fleet_health_rollup():
    now = 1_000_000.0
    p0, p1 = _health_payloads(now)
    body = fleetobs.fleet_health({0: p0, 1: p1}, 2, 10.0, now=now)
    assert body["healthy"] is True
    assert body["workers_reporting"] == 2 and body["missing"] == []
    assert body["workers"]["0"]["role"] == "leader"
    assert body["leader_epoch_skew"] == 0
    assert body["ckpt_chain_depth"] == {"0": 3.0}

    # Missing worker → degraded.
    body = fleetobs.fleet_health({0: p0}, 2, 10.0, now=now)
    assert body["healthy"] is False
    assert any("worker 1 not reporting" in r for r in body["degraded"])

    # Stale heartbeat (TTL'd payload lingering) → degraded.
    p0s, p1s = _health_payloads(now)
    p1s["wall"] = now - 20.0
    body = fleetobs.fleet_health({0: p0s, 1: p1s}, 2, 10.0, now=now)
    assert body["healthy"] is False
    assert any("stale" in r for r in body["degraded"])

    # Epoch skew of 1 is normal propagation; 2+ degrades.
    p0a, p1a = _health_payloads(now)
    p1a["fleet"]["checkpoint_epoch"] = 4
    assert fleetobs.fleet_health(
        {0: p0a, 1: p1a}, 2, 10.0, now=now)["healthy"] is True
    p1a["fleet"]["checkpoint_epoch"] = 3
    body = fleetobs.fleet_health({0: p0a, 1: p1a}, 2, 10.0, now=now)
    assert body["healthy"] is False
    assert any("skew" in r for r in body["degraded"])

    # No leader reporting → degraded.
    p0b, p1b = _health_payloads(now)
    p0b["fleet"]["role"] = "follower"
    body = fleetobs.fleet_health({0: p0b, 1: p1b}, 2, 10.0, now=now)
    assert body["healthy"] is False
    assert any("no leader" in r for r in body["degraded"])

    # A worker's SLO breach surfaces in the rollup.
    p0c, p1c = _health_payloads(now)
    p1c["slo"] = {"degraded": ["ingest_lag 11 > 10"]}
    body = fleetobs.fleet_health({0: p0c, 1: p1c}, 2, 10.0, now=now)
    assert body["healthy"] is False
    assert any("worker 1 slo" in r for r in body["degraded"])


# -- promhttp fleet routes -----------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_metrics_server_fleet_routes():
    health = {"healthy": True, "workers_reporting": 2}
    srv = MetricsServer(
        0, host="127.0.0.1", sink=InMemSink(),
        fleet_metrics=lambda: 'x{worker="0"} 1\n',
        fleet_health=lambda: dict(health)).start()
    try:
        code, text = _get(f"http://127.0.0.1:{srv.port}/metrics/fleet")
        assert code == 200 and 'x{worker="0"} 1' in text
        code, text = _get(f"http://127.0.0.1:{srv.port}/healthz/fleet")
        assert code == 200
        assert json.loads(text)["workers_reporting"] == 2

        health["healthy"] = False
        health["degraded"] = ["worker 1 not reporting"]
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv.port}/healthz/fleet")
        assert err.value.code == 503
        assert json.loads(err.value.read().decode())["degraded"] == \
            ["worker 1 not reporting"]
    finally:
        srv.stop()


def test_metrics_server_fleet_routes_absent_and_failing():
    srv = MetricsServer(0, host="127.0.0.1", sink=InMemSink()).start()
    try:
        for route in ("/metrics/fleet", "/healthz/fleet"):
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"http://127.0.0.1:{srv.port}{route}")
            assert err.value.code == 404
    finally:
        srv.stop()

    def boom():
        raise RuntimeError("fabric down")

    srv2 = MetricsServer(0, host="127.0.0.1", sink=InMemSink(),
                         fleet_metrics=boom, fleet_health=boom).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv2.port}/metrics/fleet")
        assert err.value.code == 503
        assert "fabric down" in err.value.read().decode()
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv2.port}/healthz/fleet")
        assert err.value.code == 503
    finally:
        srv2.stop()


# -- batcher trace-context adoption --------------------------------------


def test_batcher_adopts_single_submitter_context():
    from ct_mapreduce_tpu.serve.batcher import MicroBatcher

    captured = []

    def run_batch(items):
        captured.append(trace.get_trace_context())
        return items

    mb = MicroBatcher(run_batch, max_batch=64, max_delay_s=0.001)
    try:
        with trace.trace_context("a" * 32, "b" * 16):
            mb.submit([1, 2])
        mb.submit([3])
    finally:
        mb.close()
    # Single-context batch adopts the submitter's ids on the worker
    # thread; a context-free batch stays untagged.
    assert captured[0] == ("a" * 32, "b" * 16)
    assert captured[1] is None


# -- query client propagation + query-plane SLO 503 ----------------------


def test_query_client_mints_and_sends_traceparent():
    from ct_mapreduce_tpu.serve.client import QueryClient

    seen = []

    class Recorder(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            seen.append(self.headers.get(trace.TRACEPARENT_HEADER))
            body = json.dumps({"healthy": True}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), Recorder)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    tracer = trace.enable(path=None)
    n_before = len(tracer.events())
    try:
        QueryClient(f"127.0.0.1:{httpd.server_port}").healthz()
    finally:
        trace.disable()
        httpd.shutdown()
        thread.join(timeout=5)
    assert len(seen) == 1
    ids = trace.parse_traceparent(seen[0])
    assert ids is not None
    spans = [e for e in tracer.events()[n_before:]
             if e.get("name") == "query.client"]
    assert spans, "client did not record a query.client span"
    # The span carries the SAME trace id the wire header carried — the
    # merge-time correlation key.
    assert spans[-1]["args"]["trace_id"] == ids[0]


def test_query_server_healthz_503_on_slo_breach():
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.serve.server import QueryServer

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    srv = QueryServer(agg, 0, host="127.0.0.1").start()
    try:
        code, text = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert code == 200 and json.loads(text)["healthy"] is True

        srv.slo_check = lambda: ["ingest_lag 11 > 10"]
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert err.value.code == 503
        body = json.loads(err.value.read().decode())
        assert body["healthy"] is False
        assert body["degraded"] == ["ingest_lag 11 > 10"]

        # A crashing probe degrades (the probe must answer, not 500).
        def boom():
            raise RuntimeError("rule layer exploded")

        srv.slo_check = boom
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert err.value.code == 503
        assert "rule layer exploded" in err.value.read().decode()
    finally:
        srv.stop()
