"""Filter distribution plane (round 18): epoch deltas, upstream
containers, CDN-grade serving.

Pins the acceptance contract of ISSUE 13:
- any epoch sequence's delta chain replays to bytes IDENTICAL to the
  full build — including across a table-growth event and a fleet
  merge — with truncated/corrupted/misordered links rejected loudly
  through the mandatory per-link SHA-256 checks;
- container encodings (mlbf, clubcard) answer every membership
  question exactly as the source artifact does, deterministically;
- the distribution store bounds chain length with full-snapshot
  anchors, evicts history, and ranks fleet-merged publishes above
  local builds;
- the HTTP tier: strong ETags, If-None-Match ⇒ 304, Accept-Encoding
  negotiation against pre-compressed caches, delta/manifest/container
  routes, and byte-identical serving across a 2-worker pair;
- platformProfile: one data file feeds every subsystem's knob ladder
  (explicit > env > profile > default).
"""

import gzip
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator  # noqa: E402
from ct_mapreduce_tpu.distrib import (  # noqa: E402
    ChainManifest,
    DeltaError,
    FilterDistributor,
    apply_chain,
    apply_delta,
    compute_delta,
    decode_container,
    encode_container,
    negotiate_encoding,
    resolve_distrib,
    split_bundle,
)
from ct_mapreduce_tpu.distrib import delta as delta_mod  # noqa: E402
from ct_mapreduce_tpu.distrib.container import ContainerError  # noqa: E402
from ct_mapreduce_tpu.filter import (  # noqa: E402
    FilterArtifact,
    build_artifact,
    build_from_aggregator,
)
from ct_mapreduce_tpu.utils import minicert  # noqa: E402

ISSUER_DER = minicert.make_cert(serial=1, issuer_cn="Distrib CA",
                                is_ca=True)
ISSUER_DER_B = minicert.make_cert(serial=2, issuer_cn="Distrib CA B",
                                  is_ca=True)


def corpus(n=60, issuer_cn="Distrib CA", issuer=ISSUER_DER, base=1000):
    return [
        (minicert.make_cert(serial=base + s, issuer_cn=issuer_cn,
                            subject_cn=f"d{s}.example"), issuer)
        for s in range(n)
    ]


def epoch_sets(rng, n_groups, per_group, salt):
    return {
        (f"issuer-{g}", 500_000 + 24 * g): {
            bytes([salt, g, s % 251, 7]) + bytes([int(x) for x in
                                                  rng.integers(0, 256, 2)])
            for s in range(per_group)
        }
        for g in range(n_groups)
    }


def build(sets):
    return build_artifact(sets, fp_rate=0.01, use_device=False).to_bytes()


# -- delta chain replay == full build (property) --------------------------


def test_delta_chain_replays_any_epoch_sequence():
    """Randomized epoch sequences — serials added, groups added,
    groups removed — always replay through the delta chain to bytes
    identical to the full build at every step."""
    rng = np.random.default_rng(20260805)
    for seq in range(3):
        sets = epoch_sets(rng, n_groups=6, per_group=25, salt=seq)
        blobs = [build(sets)]
        for _ in range(4):
            # Mutate: grow a couple of groups, sometimes add/remove one.
            for key in sorted(sets)[:2]:
                sets[key] = set(sets[key]) | {
                    bytes([int(x) for x in rng.integers(0, 256, 5)])
                    for _ in range(int(rng.integers(1, 6)))}
            if rng.integers(2):
                sets[(f"new-{seq}-{len(blobs)}", 700_000)] = {
                    bytes([int(x) for x in rng.integers(0, 256, 4)])}
            if rng.integers(2) and len(sets) > 3:
                del sets[sorted(sets)[-1]]
            blobs.append(build(sets))
        deltas = [compute_delta(blobs[i], blobs[i + 1], i, i + 1)
                  for i in range(len(blobs) - 1)]
        assert apply_chain(blobs[0], deltas) == blobs[-1]
        # And every intermediate prefix replays exactly too.
        for i in range(1, len(blobs)):
            assert apply_chain(blobs[0], deltas[:i]) == blobs[i]


def test_delta_chain_across_growth_and_fleet_merge(tmp_path):
    """The production epoch shapes: epoch 0 → 1 spans a table
    grow-and-rehash; epoch 1 → 2 lands on a MERGED fleet artifact
    (two worker checkpoints folded). The chain still replays to the
    exact merged-build bytes."""
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.filter import build_from_merged

    agg = TpuAggregator(capacity=1 << 8, batch_size=64, grow_at=0.5,
                        max_capacity=1 << 14)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=40, base=1000))
    e0 = build_from_aggregator(agg, fp_rate=0.01).to_bytes()
    agg.ingest(corpus(n=120, base=3000))  # drives growth past 2^8
    assert agg.capacity > (1 << 8), "growth never fired"
    e1 = build_from_aggregator(agg, fp_rate=0.01).to_bytes()

    # Epoch 2: this worker + a second worker's disjoint half, merged.
    p0 = str(tmp_path / "agg.w0.npz")
    agg.save_checkpoint(p0)
    other = TpuAggregator(capacity=1 << 10, batch_size=64)
    other.enable_filter_capture()
    other.ingest(corpus(n=50, issuer_cn="Distrib CA B",
                        issuer=ISSUER_DER_B, base=9000))
    p1 = str(tmp_path / "agg.w1.npz")
    other.save_checkpoint(p1)
    e2 = build_from_merged(merge.load_checkpoints([p0, p1]),
                           fp_rate=0.01).to_bytes()

    d01 = compute_delta(e0, e1, 0, 1)
    d12 = compute_delta(e1, e2, 1, 2)
    assert apply_chain(e0, [d01, d12]) == e2
    assert apply_delta(apply_delta(e0, d01), d12) == e2


def test_delta_rejects_corruption_and_misorder():
    rng = np.random.default_rng(7)
    s0 = epoch_sets(rng, 4, 20, salt=1)
    s1 = {k: set(v) | {b"\x01\x02\x03"} for k, v in s0.items()}
    b0, b1 = build(s0), build(s1)
    d = compute_delta(b0, b1, 0, 1)
    assert apply_delta(b0, d) == b1
    # Corrupted payload byte: the target-hash check trips.
    corrupt = bytearray(d)
    corrupt[-3] ^= 0x40
    with pytest.raises(DeltaError):
        apply_delta(b0, bytes(corrupt))
    # Truncated link: payloadBytes no longer matches.
    with pytest.raises(DeltaError):
        apply_delta(b0, d[:-5])
    # Wrong base (misordered chain): the base-hash check trips.
    with pytest.raises(DeltaError, match="base mismatch"):
        apply_delta(b1, d)
    # Garbage magic.
    with pytest.raises(DeltaError, match="magic"):
        apply_delta(b0, b"XXXXXXXX" + d[8:])


def test_chain_manifest_validates_links():
    rng = np.random.default_rng(11)
    sets = epoch_sets(rng, 3, 15, salt=2)
    blobs = [build(sets)]
    for i in range(3):
        sets[sorted(sets)[0]] = set(sets[sorted(sets)[0]]) | {bytes([i, 9])}
        blobs.append(build(sets))
    links, dblobs = [], []
    for i in range(3):
        db = compute_delta(blobs[i], blobs[i + 1], i, i + 1)
        dblobs.append(db)
        import hashlib

        links.append(delta_mod.ChainLink(
            from_epoch=i, to_epoch=i + 1,
            sha256=hashlib.sha256(db).hexdigest(),
            base_sha256=delta_mod.artifact_sha256(blobs[i]),
            target_sha256=delta_mod.artifact_sha256(blobs[i + 1]),
            n_bytes=len(db)))
    man = ChainManifest(latest_epoch=3,
                        latest_sha256=delta_mod.artifact_sha256(blobs[3]),
                        latest_bytes=len(blobs[3]), anchors=[0],
                        links=links)
    # JSON round trip preserves the manifest.
    back = ChainManifest.from_json(man.to_json())
    assert back.to_json() == man.to_json()
    # A valid chain validates; replay confirms.
    path = man.validate_chain(0, 3, dblobs)
    assert [li.from_epoch for li in path] == [0, 1, 2]
    assert apply_chain(blobs[0], dblobs) == blobs[3]
    # Corrupted download: rejected BEFORE replay.
    bad = dblobs[:1] + [dblobs[1][:-1] + b"\x00"] + dblobs[2:]
    with pytest.raises(DeltaError, match="hash mismatch"):
        man.validate_chain(0, 3, bad)
    # Wrong blob count (truncated chain).
    with pytest.raises(DeltaError, match="length mismatch"):
        man.validate_chain(0, 3, dblobs[:2])
    # No path outside the manifest's span.
    with pytest.raises(DeltaError, match="no delta path"):
        man.validate_chain(5, 9, [])
    assert man.link_path(2, 1) is None


def test_split_bundle_roundtrip():
    rng = np.random.default_rng(13)
    sets = epoch_sets(rng, 3, 10, salt=3)
    b0 = build(sets)
    sets[sorted(sets)[0]] = set(sets[sorted(sets)[0]]) | {b"\xaa"}
    b1 = build(sets)
    sets[sorted(sets)[1]] = set(sets[sorted(sets)[1]]) | {b"\xbb"}
    b2 = build(sets)
    d1 = compute_delta(b0, b1, 10, 11)
    d2 = compute_delta(b1, b2, 11, 12)
    assert split_bundle(d1 + d2) == [d1, d2]
    assert apply_chain(b0, split_bundle(d1 + d2)) == b2
    with pytest.raises(DeltaError):
        split_bundle(d1 + b"junk")


# -- containers -----------------------------------------------------------


def test_container_query_parity_and_determinism():
    """Both container encodings answer exactly what the source
    artifact answers — for every known serial AND for random probes
    (FP pattern included) — and encode deterministically."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=80, base=1000))
    art = build_from_aggregator(agg, fp_rate=0.01)
    blob = art.to_bytes()
    rng = np.random.default_rng(20260805)
    probes = [rng.integers(0, 256, 5, dtype=np.uint8).tobytes()
              for _ in range(150)]
    for kind in ("mlbf", "clubcard"):
        cb = encode_container(art, kind)
        assert encode_container(FilterArtifact.from_bytes(blob),
                                kind) == cb  # deterministic
        back = decode_container(cb)
        for (idx, eh), serials in sorted(agg.filter_capture.items()):
            iss = agg.registry.issuer_at(idx).id()
            for sb in sorted(serials)[:30]:
                assert back.query(iss, eh, sb)
            for p in probes:
                assert back.query(iss, eh, p) == art.query(iss, eh, p)
            # Cross-bucket exactness survives the container.
            sb = sorted(serials)[0]
            assert back.query(iss, eh + 24, sb) \
                == art.query(iss, eh + 24, sb)


def test_container_error_paths():
    with pytest.raises(ContainerError, match="magic"):
        decode_container(b"NOTAMAGICblahblah")
    art = build_artifact({("i", 1): {b"\x01"}}, 0.01, use_device=False)
    mlbf = encode_container(art, "mlbf")
    with pytest.raises(ContainerError):
        decode_container(mlbf[:-3])  # truncated
    with pytest.raises(ContainerError, match="kind"):
        encode_container(art, "bloom3000")


# -- the distributor ------------------------------------------------------


def epoch_blobs(n, rng=None, groups=8, per=20):
    rng = rng or np.random.default_rng(99)
    sets = epoch_sets(rng, groups, per, salt=9)
    out = [build(sets)]
    for i in range(n - 1):
        key = sorted(sets)[i % len(sets)]
        sets[key] = set(sets[key]) | {bytes([i, 77, j]) for j in range(3)}
        out.append(build(sets))
    return out


def test_distributor_chain_anchors_and_eviction():
    blobs = epoch_blobs(8)
    d = FilterDistributor(history=4, max_chain=2)
    for e, blob in enumerate(blobs):
        assert d.publish(e, blob)
    man = d.manifest()
    # History bound: only the newest 4 epochs held.
    assert man["epochsHeld"] == [4, 5, 6, 7]
    assert d.latest().epoch == 7 and d.latest().blob == blobs[7]
    # Anchors: every (max_chain+1)th epoch forces a full snapshot;
    # no delta bundle crosses one.
    assert man["maxDeltaChain"] == 2
    links = {(li["fromEpoch"], li["toEpoch"]) for li in man["links"]}
    for from_e, to_e in links:
        assert to_e == from_e + 1
    # A surviving adjacent pair replays exactly.
    replayable = [(f, t) for f, t in sorted(links) if f >= 4]
    assert replayable, links
    f, t = replayable[0]
    bundle = d.delta_bundle(f, t)
    assert bundle is not None
    assert apply_chain(blobs[f], split_bundle(bundle)) == blobs[t]
    # Evicted epoch: no chain.
    assert d.delta_bundle(0, 7) is None
    # Stale publish ignored.
    assert not d.publish(3, blobs[3])


def test_distributor_source_ranking():
    blobs = epoch_blobs(4)
    d = FilterDistributor()
    assert d.publish(100, blobs[0], source="local")
    assert d.publish(101, blobs[1], source="local")
    # Fleet takes over: its own epoch space, store restarts clean.
    assert d.publish(1, blobs[2], source="fleet")
    assert d.latest().epoch == 1 and d.latest().blob == blobs[2]
    # Local can no longer override the merged artifact.
    assert not d.publish(102, blobs[3], source="local")
    assert d.latest().blob == blobs[2]
    assert d.publish(2, blobs[3], source="fleet")
    assert d.latest().epoch == 2


def test_negotiate_encoding():
    from ct_mapreduce_tpu.distrib import zstd_available

    assert negotiate_encoding("gzip") == "gzip"
    assert negotiate_encoding("gzip;q=0") is None
    assert negotiate_encoding("") is None
    assert negotiate_encoding("identity") is None
    assert negotiate_encoding("br, gzip;q=0.5") == "gzip"
    if zstd_available():
        assert negotiate_encoding("zstd, gzip") == "zstd"
    else:
        assert negotiate_encoding("zstd") is None
        assert negotiate_encoding("zstd, gzip") == "gzip"
    # Wildcard accepts whatever the build offers.
    assert negotiate_encoding("*") in ("gzip", "zstd")


# -- HTTP tier ------------------------------------------------------------


@pytest.fixture
def served_pair():
    """Two QueryServers ('workers') whose distribution stores are fed
    the SAME artifact bytes — the fleet serving shape."""
    from ct_mapreduce_tpu.serve.server import QueryServer

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=70, base=1000))
    servers = [QueryServer(agg, 0, filter_first=True).start()
               for _ in range(2)]
    e0 = [s.oracle.distributor.latest().blob for s in servers]
    assert e0[0] == e0[1]  # deterministic build == same bytes
    agg.ingest(corpus(n=30, base=7000))
    blob1 = build_from_aggregator(
        agg, fp_rate=servers[0].oracle.filter_fp_rate).to_bytes()
    for s in servers:
        latest = s.oracle.distributor.latest().epoch
        assert s.oracle.distributor.publish(latest + 1, blob1,
                                            source="local")
    try:
        yield servers, e0[0], blob1
    finally:
        for s in servers:
            s.stop()


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    return urllib.request.urlopen(req)


def test_http_etag_304_encoding_delta_and_worker_parity(served_pair):
    servers, blob0, blob1 = served_pair
    bases = [f"http://127.0.0.1:{s.port}" for s in servers]

    # Every worker serves byte-identical artifacts with identical
    # strong ETags — full, containers, manifest.
    full, etags = [], []
    for base in bases:
        r = _get(base + "/filter")
        full.append(r.read())
        etags.append(r.headers["ETag"])
        assert r.headers["Cache-Control"].startswith("public")
        assert r.headers["Last-Modified"]
        assert r.headers["Vary"] == "Accept-Encoding"
    assert full[0] == full[1] == blob1
    assert etags[0] == etags[1]
    for kind in ("mlbf", "clubcard"):
        payloads = [_get(f"{b}/filter/container/{kind}").read()
                    for b in bases]
        assert payloads[0] == payloads[1]
        assert decode_container(payloads[0]).n_serials == 100

    # Conditional GET: warm client pays zero body bytes, from EITHER
    # worker (the ETag is fleet-global).
    for base in bases:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base + "/filter", {"If-None-Match": etags[0]})
        assert err.value.code == 304
        assert err.value.read() == b""
        assert err.value.headers["ETag"] == etags[0]
    # A stale ETag still gets the full body.
    r = _get(bases[0] + "/filter", {"If-None-Match": '"deadbeef"'})
    assert r.read() == blob1

    # Content negotiation: gzip round-trips to the identity bytes and
    # repeated requests hit the pre-compressed cache (same payload).
    r = _get(bases[0] + "/filter", {"Accept-Encoding": "gzip"})
    assert r.headers["Content-Encoding"] == "gzip"
    gz = r.read()
    assert gzip.decompress(gz) == blob1
    r2 = _get(bases[0] + "/filter", {"Accept-Encoding": "gzip;q=1.0"})
    assert r2.read() == gz
    # identity-only clients get identity.
    r3 = _get(bases[0] + "/filter", {"Accept-Encoding": "identity"})
    assert "Content-Encoding" not in r3.headers
    assert r3.read() == blob1

    # Delta route: a lagging client replays to the exact full bytes.
    man = json.loads(_get(bases[0] + "/filter/manifest").read())
    from_e, to_e = man["latestEpoch"] - 1, man["latestEpoch"]
    bundles = [_get(f"{b}/filter/delta/{from_e}/{to_e}").read()
               for b in bases]
    assert bundles[0] == bundles[1]
    links = split_bundle(bundles[0])
    ChainManifest.from_json(man).validate_chain(from_e, to_e, links)
    assert apply_chain(blob0, links) == blob1
    r = _get(f"{bases[0]}/filter/delta/{from_e}/{to_e}")
    assert "immutable" in r.headers["Cache-Control"]
    # Unknown spans 404 (client falls back to full-pull).
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(f"{bases[0]}/filter/delta/998/999")
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(f"{bases[0]}/filter/container/nope")
    assert err.value.code == 404
    # Manifest reports the serving inventory (fl02 default build →
    # the rev-2 delta wire).
    assert man["format"] == "CTMRDL02"
    assert man["containers"] == ["clubcard", "mlbf"]
    assert "gzip" in man["encodings"]
    # /healthz carries the distribution stats.
    stats = servers[0].oracle.stats()
    assert stats["distrib_latest_epoch"] == to_e
    assert stats["distrib_links"] >= 1


def test_publish_artifact_fleet_source_via_oracle():
    """The ct-fetch fan-out path: externally built (merged) bytes
    publish through the oracle and outrank the local build."""
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=30, base=1000))
    oracle = MembershipOracle(agg, filter_first=True,
                              max_delay_s=0.001)
    try:
        assert oracle.distributor.latest() is not None  # local build
        merged = build_from_aggregator(agg, fp_rate=0.02).to_bytes()
        assert oracle.publish_artifact(3, merged)
        assert oracle.distributor.latest().epoch == 3
        assert oracle.distributor.latest().blob == merged
        # A later local refresh cannot displace the fleet artifact.
        oracle.refresh_filter()
        assert oracle.distributor.latest().blob == merged
    finally:
        oracle.close()


# -- CLI ------------------------------------------------------------------


def test_ct_filter_cli_delta_apply_container(tmp_path):
    import io

    from ct_mapreduce_tpu.cmd import ct_filter

    rng = np.random.default_rng(17)
    s0 = epoch_sets(rng, 4, 15, salt=5)
    b0 = build(s0)
    s1 = {k: set(v) | {b"\x42\x42"} for k, v in s0.items()}
    b1 = build(s1)
    p0, p1 = str(tmp_path / "e0.filter"), str(tmp_path / "e1.filter")
    open(p0, "wb").write(b0)
    open(p1, "wb").write(b1)

    dpath = str(tmp_path / "e0-e1.delta")
    buf = io.StringIO()
    rc = ct_filter.main(["delta", "-base", p0, "-target", p1,
                         "-out", dpath, "-fromEpoch", "0",
                         "-toEpoch", "1"], out=buf)
    assert rc == 0
    meta = json.loads(buf.getvalue())
    assert meta["bytes"] == os.path.getsize(dpath)

    rpath = str(tmp_path / "replayed.filter")
    buf = io.StringIO()
    assert ct_filter.main(["apply", "-base", p0, "-delta", dpath,
                           "-out", rpath], out=buf) == 0
    assert open(rpath, "rb").read() == b1
    # A corrupted link exits 2, not a traceback.
    bad = str(tmp_path / "bad.delta")
    blob = bytearray(open(dpath, "rb").read())
    blob[-1] ^= 0xFF
    open(bad, "wb").write(bytes(blob))
    assert ct_filter.main(["apply", "-base", p0, "-delta", bad,
                           "-out", str(tmp_path / "x.filter")],
                          out=io.StringIO()) == 2

    for kind in ("mlbf", "clubcard"):
        cpath = str(tmp_path / f"run.{kind}")
        buf = io.StringIO()
        assert ct_filter.main(["container", "-artifact", p1,
                               "-kind", kind, "-out", cpath],
                              out=buf) == 0
        back = decode_container(open(cpath, "rb").read())
        assert back.n_serials == json.loads(buf.getvalue())["serials"]


# -- config surface -------------------------------------------------------


def test_resolve_distrib_layering(monkeypatch, tmp_path):
    monkeypatch.delenv("CTMR_DISTRIB_HISTORY", raising=False)
    monkeypatch.delenv("CTMR_MAX_DELTA_CHAIN", raising=False)
    monkeypatch.delenv("CTMR_PLATFORM_PROFILE", raising=False)
    assert resolve_distrib() == (8, 4)
    monkeypatch.setenv("CTMR_DISTRIB_HISTORY", "16")
    monkeypatch.setenv("CTMR_MAX_DELTA_CHAIN", "6")
    assert resolve_distrib() == (16, 6)
    # Explicit beats env.
    assert resolve_distrib(history=3, max_chain=2) == (3, 2)
    # Unparseable env falls through.
    monkeypatch.setenv("CTMR_DISTRIB_HISTORY", "lots")
    assert resolve_distrib()[0] == 8
    # Profile sits under env, above defaults.
    prof = tmp_path / "prof.json"
    prof.write_text(json.dumps({
        "version": 1, "platform": "test",
        "knobs": {"distrib": {"distribHistory": 12,
                              "maxDeltaChain": 9}}}))
    monkeypatch.setenv("CTMR_PLATFORM_PROFILE", str(prof))
    monkeypatch.delenv("CTMR_DISTRIB_HISTORY", raising=False)
    monkeypatch.delenv("CTMR_MAX_DELTA_CHAIN", raising=False)
    assert resolve_distrib() == (12, 9)
    monkeypatch.setenv("CTMR_MAX_DELTA_CHAIN", "5")
    assert resolve_distrib() == (12, 5)  # env beats profile
    assert resolve_distrib(max_chain=2) == (12, 2)  # explicit beats all


# -- zstd wire leg (ROADMAP 4(c): validated where the module exists) ------


def test_zstd_encoding_leg(served_pair):
    """Gated on the optional `zstandard` module (absent in the default
    CI image — skips cleanly there; ROADMAP 4(c) asks for validation
    on a host that has it): the fleet advertises zstd, serves full and
    delta pulls with Content-Encoding: zstd whose bodies decompress to
    the exact deterministic bytes, and the pre-compressed cache bytes
    are themselves deterministic across workers."""
    zstandard = pytest.importorskip("zstandard")
    from ct_mapreduce_tpu.distrib import zstd_available

    assert zstd_available()
    servers, blob0, blob1 = served_pair
    wire = []
    for s in servers:
        base = f"http://127.0.0.1:{s.port}"
        man = json.loads(_get(base + "/filter/manifest").read())
        assert "zstd" in man["encodings"]
        r = _get(base + "/filter",
                 headers={"Accept-Encoding": "zstd, gzip"})
        assert r.headers.get("Content-Encoding") == "zstd"
        body = r.read()
        assert zstandard.ZstdDecompressor().decompress(body) == blob1
        wire.append(body)
        latest = man["latestEpoch"]
        rd = _get(f"{base}/filter/delta/{latest - 1}/{latest}",
                  headers={"Accept-Encoding": "zstd"})
        if rd.headers.get("Content-Encoding") == "zstd":
            bundle = zstandard.ZstdDecompressor().decompress(rd.read())
        else:  # tiny deltas may not pay for compression
            bundle = rd.read()
        links = split_bundle(bundle)
        assert apply_chain(blob0, links) == blob1
    # Deterministic compressed bytes (gzip mtime=0 discipline applies
    # to zstd too): any worker's wire bytes are authoritative.
    assert wire[0] == wire[1]


def test_pullstorm_force_zstd_flag():
    """`tools/pullstorm.py --force-zstd` drives every compressible
    pull through zstd end to end (skips without the module; the flag
    itself must fail loudly in that case — asserted in the else arm)."""
    from tools import pullstorm

    try:
        import zstandard  # noqa: F401
        have = True
    except ImportError:
        have = False
    if not have:
        with pytest.raises(RuntimeError, match="zstd"):
            pullstorm.run_storm(clients=8, epochs=2, groups=3,
                                per_group=5, churn=1, workers=1,
                                threads=2, force_zstd=True)
        return
    report = pullstorm.run_storm(clients=24, epochs=3, groups=4,
                                 per_group=6, churn=1, workers=1,
                                 threads=4, force_zstd=True)
    assert report["zstd_available"]
    assert report["worker_parity"] == 1
