"""Dedup hash-table op: Redis SADD semantics on device.

Parity oracle is a plain Python set, mirroring how the reference's
MockRemoteCache stands in for Redis
(/root/reference/storage/mockcache.go)."""

import numpy as np
import pytest

from ct_mapreduce_tpu.ops import hashtable as ht


def rand_keys(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(n, 4), dtype=np.uint32)


def as_tuple(k):
    return tuple(int(x) for x in k)


def test_insert_then_reinsert():
    state = ht.make_table(256)
    keys = rand_keys(16)
    valid = np.ones(16, bool)
    meta = np.arange(16, dtype=np.uint32)
    state, unknown, overflow = ht.insert(state, keys, meta, valid)
    assert np.asarray(unknown).all()
    assert not np.asarray(overflow).any()
    assert int(state.count) == 16
    # Second insert of the same keys: all known.
    state, unknown2, overflow2 = ht.insert(state, keys, meta, valid)
    assert not np.asarray(unknown2).any()
    assert not np.asarray(overflow2).any()
    assert int(state.count) == 16


def test_within_batch_duplicates():
    state = ht.make_table(256)
    base = rand_keys(4, seed=1)
    keys = np.concatenate([base, base, base[:2]])  # lanes: 4 uniq + 4 dup + 2 dup
    valid = np.ones(len(keys), bool)
    meta = np.zeros(len(keys), np.uint32)
    state, unknown, _ = ht.insert(state, keys, meta, valid)
    unknown = np.asarray(unknown)
    # Exactly one lane per distinct key reports unknown.
    assert unknown.sum() == 4
    seen = set()
    for i, k in enumerate(keys):
        t = as_tuple(k)
        if unknown[i]:
            assert t not in seen
        seen.add(t)
    assert int(state.count) == 4


def test_invalid_lanes_ignored():
    state = ht.make_table(64)
    keys = rand_keys(8, seed=2)
    valid = np.array([True, False] * 4)
    meta = np.zeros(8, np.uint32)
    state, unknown, _ = ht.insert(state, keys, meta, valid)
    unknown = np.asarray(unknown)
    assert unknown[valid].all()
    assert not unknown[~valid].any()
    assert int(state.count) == 4


def test_invalid_then_valid_same_key():
    # An invalid lane must not "claim" a key for a later valid lane.
    state = ht.make_table(64)
    k = rand_keys(1, seed=3)
    keys = np.concatenate([k, k])
    valid = np.array([False, True])
    meta = np.zeros(2, np.uint32)
    state, unknown, _ = ht.insert(state, keys, meta, valid)
    assert list(np.asarray(unknown)) == [False, True]
    assert int(state.count) == 1


def test_collision_pressure_tiny_table():
    # 64-slot table, fill 48 slots across batches with forced probing.
    state = ht.make_table(64)
    oracle = set()
    rng = np.random.default_rng(7)
    for batch in range(6):
        keys = rng.integers(0, 4, size=(8, 4), dtype=np.uint32)  # heavy dups
        keys[:, 0] = rng.integers(0, 2**32, size=8, dtype=np.uint32)
        valid = np.ones(8, bool)
        meta = np.zeros(8, np.uint32)
        state, unknown, overflow = ht.insert(state, keys, meta, valid)
        unknown, overflow = np.asarray(unknown), np.asarray(overflow)
        batch_seen = set()
        for i, kk in enumerate(keys):
            t = as_tuple(kk)
            if overflow[i]:
                continue
            expect = t not in oracle and t not in batch_seen
            assert bool(unknown[i]) == expect, (batch, i)
            batch_seen.add(t)
        oracle |= batch_seen
    assert int(state.count) == len(
        [1 for _ in oracle]
    ) or int(state.count) <= len(oracle)  # overflowed reps may be missing


def test_contains():
    state = ht.make_table(128)
    keys = rand_keys(32, seed=5)
    state, _, _ = ht.insert(
        state, keys[:16], np.zeros(16, np.uint32), np.ones(16, bool)
    )
    got = np.asarray(ht.contains(state, keys))
    assert got[:16].all()
    assert not got[16:].any()


def test_meta_scattered_and_drain():
    state = ht.make_table(128)
    keys = rand_keys(10, seed=6)
    meta = (np.arange(10, dtype=np.uint32) << 8) | 7
    state, _, _ = ht.insert(state, keys, meta, np.ones(10, bool))
    got_keys, got_meta = ht.drain_np(state)
    assert got_keys.shape[0] == 10
    by_key = {as_tuple(k): int(m) for k, m in zip(got_keys, got_meta)}
    for k, m in zip(keys, meta):
        assert by_key[as_tuple(k)] == int(m)


def test_randomized_parity_vs_python_set():
    state = ht.make_table(1024)
    oracle = set()
    rng = np.random.default_rng(11)
    pool = rand_keys(300, seed=12)  # draw with replacement → cross-batch dups
    for _ in range(10):
        idx = rng.integers(0, len(pool), size=64)
        keys = pool[idx]
        valid = rng.random(64) > 0.1
        meta = np.zeros(64, np.uint32)
        state, unknown, overflow = ht.insert(state, keys, meta, valid)
        unknown, overflow = np.asarray(unknown), np.asarray(overflow)
        assert not overflow.any()  # plenty of capacity
        batch_first = {}
        for i in range(64):
            t = as_tuple(keys[i])
            if not valid[i]:
                assert not unknown[i]
                continue
            expect = t not in oracle and t not in batch_first
            assert bool(unknown[i]) == expect
            batch_first[t] = True
        oracle |= set(batch_first)
    assert int(state.count) == len(oracle)


def test_contains_np_matches_device_contains():
    """The NumPy membership mirror (host-only storage-statistics) agrees
    with the jitted `contains` on present, absent, and all-zero keys."""
    state = ht.make_table(512)
    keys = rand_keys(200, seed=21)
    meta = np.arange(200, dtype=np.uint32)
    state, _, overflow = ht.insert(state, keys, meta, np.ones(200, bool))
    assert not np.asarray(overflow).any()

    probe = np.concatenate([keys[:50], rand_keys(50, seed=22),
                            np.zeros((1, 4), np.uint32)])
    dev = np.asarray(ht.contains(state, probe))
    host = ht.contains_np(np.asarray(state.keys), probe)
    np.testing.assert_array_equal(host, dev)
    assert host[:50].all()


def test_lane_partition_invariant_under_pressure():
    """Every valid lane resolves to exactly one of {known, inserted,
    overflowed}; invalid lanes to none — across random batches driven
    into a tiny table with a tiny probe budget (overflow-heavy), with
    the table count equal to total insertions."""
    rng = np.random.default_rng(33)
    state = ht.make_table(128)
    pool = rand_keys(200, seed=34)
    oracle = set()  # keys the table really holds
    for _ in range(12):
        idx = rng.integers(0, len(pool), size=96)
        keys = pool[idx]
        valid = rng.random(96) > 0.15
        state, unknown, overflow = ht.insert(
            state, keys, np.zeros(96, np.uint32), valid, max_probes=3)
        unknown, overflow = np.asarray(unknown), np.asarray(overflow)
        known = valid & ~unknown & ~overflow
        # partition: one flag per valid lane, none for invalid
        assert not (unknown & overflow).any()
        assert not (unknown[~valid]).any()
        assert not (overflow[~valid]).any()
        for i in np.flatnonzero(valid & unknown):
            oracle.add(as_tuple(keys[i]))
        # a lane reported known must actually be present (table or
        # earlier in this batch — first-in-lane-order wins)
        for i in np.flatnonzero(known):
            assert as_tuple(keys[i]) in oracle
    assert int(state.count) == len(oracle)
    # every oracle key is findable; absent keys are not
    present = np.array([k for k in pool if as_tuple(k) in oracle])
    if present.size:
        assert np.asarray(ht.contains(state, present, max_probes=3)).all()
