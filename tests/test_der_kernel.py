"""Device DER walker vs the host reference lane (kernel-parity tier).

Every field the device kernel extracts is checked byte-for-byte against
:mod:`ct_mapreduce_tpu.core.der` on generated certificates spanning the
structural variations the walker must handle (serial lengths/leading
zeros, UTCTime vs GeneralizedTime, CA flags, CRL DPs, no-extensions)."""

import datetime

import numpy as np
import pytest

from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.ops import der_kernel

from certgen import make_cert

UTC = datetime.timezone.utc


def pack(ders, pad_to=None):
    maxlen = max(len(d) for d in ders)
    l = pad_to or ((maxlen + 127) // 128 * 128)
    data = np.zeros((len(ders), l), dtype=np.uint8)
    length = np.zeros((len(ders),), dtype=np.int32)
    for i, d in enumerate(ders):
        data[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
        length[i] = len(d)
    return data, length


def fixture_certs():
    certs = [
        make_cert(serial=0xDEADBEEF),
        make_cert(serial=0x00AA00BB, issuer_cn="Leading Zero CA"),  # leading-zero serial
        make_cert(serial=1),
        make_cert(serial=(1 << 152) - 5),  # 20-byte serial
        make_cert(is_ca=False, subject_cn="leaf.example.com"),
        make_cert(add_basic_constraints=False),
        make_cert(crl_dps=("http://crl.example.com/ca.crl",)),
        make_cert(
            crl_dps=("http://crl.example.com/a.crl", "https://crl2.example.com/b.crl")
        ),
        # GeneralizedTime: notAfter ≥ 2050 forces it (RFC 5280 §4.1.2.5)
        make_cert(not_after=datetime.datetime(2055, 6, 1, 13, 37, tzinfo=UTC)),
        # UTCTime upper range
        make_cert(not_after=datetime.datetime(2049, 12, 31, 23, 59, tzinfo=UTC)),
        make_cert(issuer_cn="日本語テストCA"),  # UTF8String CN
    ]
    return certs


def test_parity_with_host_lane():
    ders = fixture_certs()
    data, length = pack(ders)
    out = der_kernel.parse_certs(data, length)
    for i, der in enumerate(ders):
        ref = hostder.parse_cert(der)
        assert bool(out.ok[i]), f"lane {i} rejected"
        assert int(out.serial_off[i]) == ref.serial_off, i
        assert int(out.serial_len[i]) == ref.serial_len, i
        assert int(out.not_after_hour[i]) == ref.not_after_unix_hour, i
        assert bool(out.is_ca[i]) == ref.is_ca, i
        assert bool(out.has_crldp[i]) == bool(ref.crl_distribution_points), i
        assert int(out.spki_off[i]) == ref.spki_off, i
        assert int(out.spki_len[i]) == ref.spki_len, i
        # CN bytes
        cn = der[
            int(out.issuer_cn_off[i]) : int(out.issuer_cn_off[i])
            + int(out.issuer_cn_len[i])
        ].decode("utf-8")
        assert cn == ref.issuer_cn, i


def test_serial_gather():
    ders = fixture_certs()
    data, length = pack(ders)
    out = der_kernel.parse_certs(data, length)
    serials, fits = der_kernel.gather_serials(
        data, np.asarray(out.serial_off), np.asarray(out.serial_len)
    )
    serials, fits = np.asarray(serials), np.asarray(fits)
    for i, der in enumerate(ders):
        assert fits[i]
        want = hostder.raw_serial_bytes(der)
        got = serials[i, : int(out.serial_len[i])].tobytes()
        assert got == want, i
        assert not serials[i, int(out.serial_len[i]) :].any()


def test_garbage_rejected_not_crashed():
    rng = np.random.default_rng(3)
    garbage = [rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
               for n in (0, 1, 5, 100, 700)]
    # Prepend a plausible-but-truncated cert.
    real = make_cert()
    garbage.append(real[: len(real) // 2])
    data, length = pack(garbage, pad_to=1024)
    out = der_kernel.parse_certs(data, length)
    # No lane may claim ok on structural nonsense (prob. of a random
    # byte string forming a valid TBS prefix is negligible).
    assert not np.asarray(out.ok).any()


def test_mixed_good_and_bad_lanes():
    good = fixture_certs()[:3]
    bad = [b"\x30\x03\x01\x01\xff", b""]
    ders = [good[0], bad[0], good[1], bad[1], good[2]]
    data, length = pack(ders, pad_to=1024)
    out = der_kernel.parse_certs(data, length)
    ok = np.asarray(out.ok)
    assert list(ok) == [True, False, True, False, True]


def test_long_form_lengths():
    # A cert comfortably > 256 bytes exercises 0x82 long-form at the
    # outer SEQUENCE; all fixtures do. Also verify a tiny synthetic TLV
    # with 0x81 form passes the header reader via a real cert re-pack.
    der = make_cert()
    assert der[1] in (0x81, 0x82)
    data, length = pack([der])
    out = der_kernel.parse_certs(data, length)
    assert bool(out.ok[0])
