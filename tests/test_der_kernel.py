"""Device DER walker vs the host reference lane (kernel-parity tier).

Every field the device kernel extracts is checked byte-for-byte against
:mod:`ct_mapreduce_tpu.core.der` on generated certificates spanning the
structural variations the walker must handle (serial lengths/leading
zeros, UTCTime vs GeneralizedTime, CA flags, CRL DPs, no-extensions)."""

import datetime

import numpy as np
import pytest

from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.ops import der_kernel

from certgen import make_cert, requires_cryptography

UTC = datetime.timezone.utc


def pack(ders, pad_to=None):
    maxlen = max(len(d) for d in ders)
    l = pad_to or ((maxlen + 127) // 128 * 128)
    data = np.zeros((len(ders), l), dtype=np.uint8)
    length = np.zeros((len(ders),), dtype=np.int32)
    for i, d in enumerate(ders):
        data[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
        length[i] = len(d)
    return data, length


def fixture_certs():
    certs = [
        make_cert(serial=0xDEADBEEF),
        make_cert(serial=0x00AA00BB, issuer_cn="Leading Zero CA"),  # leading-zero serial
        make_cert(serial=1),
        make_cert(serial=(1 << 152) - 5),  # 20-byte serial
        make_cert(is_ca=False, subject_cn="leaf.example.com"),
        make_cert(add_basic_constraints=False),
        make_cert(crl_dps=("http://crl.example.com/ca.crl",)),
        make_cert(
            crl_dps=("http://crl.example.com/a.crl", "https://crl2.example.com/b.crl")
        ),
        # GeneralizedTime: notAfter ≥ 2050 forces it (RFC 5280 §4.1.2.5)
        make_cert(not_after=datetime.datetime(2055, 6, 1, 13, 37, tzinfo=UTC)),
        # UTCTime upper range
        make_cert(not_after=datetime.datetime(2049, 12, 31, 23, 59, tzinfo=UTC)),
        make_cert(issuer_cn="日本語テストCA"),  # UTF8String CN
    ]
    return certs


def test_parity_with_host_lane():
    ders = fixture_certs()
    data, length = pack(ders)
    out = der_kernel.parse_certs(data, length)
    for i, der in enumerate(ders):
        ref = hostder.parse_cert(der)
        assert bool(out.ok[i]), f"lane {i} rejected"
        assert int(out.serial_off[i]) == ref.serial_off, i
        assert int(out.serial_len[i]) == ref.serial_len, i
        assert int(out.not_after_hour[i]) == ref.not_after_unix_hour, i
        assert bool(out.is_ca[i]) == ref.is_ca, i
        assert bool(out.has_crldp[i]) == bool(ref.crl_distribution_points), i
        assert int(out.spki_off[i]) == ref.spki_off, i
        assert int(out.spki_len[i]) == ref.spki_len, i
        # CN bytes
        cn = der[
            int(out.issuer_cn_off[i]) : int(out.issuer_cn_off[i])
            + int(out.issuer_cn_len[i])
        ].decode("utf-8")
        assert cn == ref.issuer_cn, i


def test_cn_scan_gated_off():
    """scan_issuer_cn=False (no CN filter configured) must zero ONLY
    the cn fields; every other extracted field stays identical."""
    ders = fixture_certs()
    data, length = pack(ders)
    full = der_kernel.parse_certs(data, length)
    gated = der_kernel.parse_certs(data, length, scan_issuer_cn=False)
    assert not np.any(np.asarray(gated.issuer_cn_off))
    assert not np.any(np.asarray(gated.issuer_cn_len))
    for field in full._fields:
        if field.startswith("issuer_cn_"):
            continue
        assert np.array_equal(
            np.asarray(getattr(full, field)), np.asarray(getattr(gated, field))
        ), field


def test_extension_scan_superblock_stress():
    """The superblock extension scan must survive certificates whose
    extension lists span multiple 512-byte superblocks, skip huge
    opaque extensions via header arithmetic, and flag (not misparse)
    lanes that exceed the per-lane extension budget."""
    ders = [
        # 12 extensions of ~50 B each + BC LAST: ~600 B of extensions,
        # at least two superblock fetches, BC still found exactly.
        make_cert(serial=10, is_ca=True, extra_extensions=12,
                  extra_ext_size=40, extras_first=True),
        # One SCT-sized (600 B) opaque extension BEFORE BC(CA=true):
        # the frame is consumed by header arithmetic far past the
        # parse window — a mis-skip that misses BC would read CA=false.
        make_cert(serial=11, is_ca=True, extra_extensions=1,
                  extra_ext_size=600, extras_first=True),
        # Budget exhaustion: 30 extensions exceed MAX_EXTS — the lane
        # must come back not-ok (host lane), never silently wrong.
        make_cert(serial=12, is_ca=True, extra_extensions=30,
                  extra_ext_size=8, extras_first=True),
        # CRLDP after a long run of unknown extensions.
        make_cert(serial=13, is_ca=False, extras_first=True,
                  extra_extensions=10, extra_ext_size=60,
                  crl_dps=("http://crl.example.com/x.crl",)),
        # BC FIRST, then a long unknown tail: the scan must keep the
        # early CA verdict while walking (and budget-bounding) the rest.
        make_cert(serial=14, is_ca=True, extras_first=False,
                  extra_extensions=12, extra_ext_size=40),
    ]
    assert der_kernel.MAX_EXTS < 30 + 1  # fixture really exceeds budget
    data, length = pack(ders)
    out = der_kernel.parse_certs(data, length)
    # Lane 0: exact CA flag despite BC sitting ~600 B into the list.
    assert bool(out.ok[0]) and bool(out.is_ca[0])
    # Lane 1: huge opaque extension skipped, BC(CA=true) parsed after
    # it — a silent mis-skip would miss BC and report CA=false.
    assert bool(out.ok[1]) and bool(out.is_ca[1])
    # Lane 2: budget exceeded -> host lane, and the host parser (the
    # reference behavior) still classifies it fine.
    assert not bool(out.ok[2])
    assert hostder.parse_cert(ders[2]).is_ca
    # Lane 3: CRLDP found beyond the first superblock — decode the
    # device-reported extnValue window and require URL equality.
    assert bool(out.ok[3]) and bool(out.has_crldp[3])
    ref = hostder.parse_cert(ders[3])
    dev_urls = hostder._parse_crldp(ders[3], int(out.crldp_off[3]))
    assert sorted(dev_urls) == sorted(ref.crl_distribution_points)
    # Lane 4: BC before the unknown tail keeps its CA verdict.
    assert bool(out.ok[4]) and bool(out.is_ca[4])


@requires_cryptography
def test_rsassa_pss_on_device_path():
    """An RSASSA-PSS-signed certificate (~67-byte signature
    AlgorithmIdentifier frame) must stay ON the device path: the fixed
    walk reads only the alg HEADER (window 1) and skips the frame
    arithmetically, so alg size never forces a host fallback."""
    import datetime as _dt

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "PSS CA")])
    now = _dt.datetime(2024, 1, 1, tzinfo=_dt.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(0x00BEEF11)
        .not_valid_before(now)
        .not_valid_after(now + _dt.timedelta(days=900))
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256(),
              rsa_padding=padding.PSS(
                  mgf=padding.MGF1(hashes.SHA256()),
                  salt_length=32))
    )
    from cryptography.hazmat.primitives import serialization

    der = cert.public_bytes(serialization.Encoding.DER)
    data, length = pack([der])
    out = der_kernel.parse_certs(data, length)
    assert bool(out.ok[0]), "PSS cert fell off the device path"
    ref = hostder.parse_cert(der)
    assert int(out.serial_off[0]) == ref.serial_off
    assert int(out.serial_len[0]) == ref.serial_len
    assert int(out.not_after_hour[0]) == ref.not_after_unix_hour
    assert bool(out.is_ca[0]) == ref.is_ca
    assert int(out.spki_off[0]) == ref.spki_off


def _splice_serial(der: bytes, new_serial: bytes) -> bytes:
    """Replace the TBS serialNumber content with ``new_serial`` via raw
    DER surgery (signature becomes invalid — irrelevant, neither parser
    verifies it). cryptography caps builder serials at 20 octets, but
    real logs carry non-conforming certs; the device schema accepts up
    to MAX_SERIAL_BYTES = 46."""
    f = hostder.parse_cert(der)
    assert f.serial_len < 128
    tlv_start = f.serial_off - 2  # short-form INTEGER header
    assert der[tlv_start] == 0x02 and der[tlv_start + 1] == f.serial_len
    assert len(new_serial) < 128
    new_tlv = bytes([0x02, len(new_serial)]) + new_serial
    delta = len(new_tlv) - (2 + f.serial_len)
    # Fix the two enclosing long-form lengths (cert SEQ, TBS SEQ);
    # sizes stay in the 0x82 two-byte range for these fixtures.
    assert der[0] == 0x30 and der[1] == 0x82
    assert der[4] == 0x30 and der[5] == 0x82
    cert_len = int.from_bytes(der[2:4], "big") + delta
    tbs_len = int.from_bytes(der[6:8], "big") + delta
    return (bytes([0x30, 0x82]) + cert_len.to_bytes(2, "big")
            + bytes([0x30, 0x82]) + tbs_len.to_bytes(2, "big")
            + der[8:tlv_start] + new_tlv
            + der[tlv_start + 2 + f.serial_len:])


def test_serial_ceiling_46_bytes():
    """Non-conforming wide serials: 46 bytes (the device schema
    ceiling, and exactly window 1's 68-byte reach) must parse on
    device with exact raw bytes; 47 bytes must overflow the gather
    window (host lane), never truncate."""
    from ct_mapreduce_tpu.core import packing

    base = make_cert(serial=0xAB, subject_cn="wide.example.com", is_ca=False)
    wide46 = bytes([0x00, 0x7F]) + bytes(range(2, 46))  # leading zero kept
    der46 = _splice_serial(base, wide46)
    assert hostder.parse_cert(der46).serial_len == 46  # surgery sane
    der47 = _splice_serial(base, bytes(47))
    data, length = pack([der46, der47])
    out = der_kernel.parse_certs(data, length)
    assert bool(out.ok[0]) and int(out.serial_len[0]) == 46
    got = der46[int(out.serial_off[0]): int(out.serial_off[0]) + 46]
    assert got == wide46  # raw bytes incl. leading zero
    serials, fits = der_kernel.gather_serials(
        data, out.serial_off, out.serial_len, packing.MAX_SERIAL_BYTES
    )
    import numpy as _np

    assert bool(fits[0])
    assert bytes(_np.asarray(serials[0][:46], dtype=_np.uint8)) == wide46
    # 47-byte serial: the walker parses the TLV (ok, correct length),
    # but it cannot ride the packed schema -> fits=False (host lane).
    assert bool(out.ok[1]) and int(out.serial_len[1]) == 47
    assert not bool(fits[1])


def test_row_pass_budget():
    """Structural guard for the walker's row-pass economy: each
    ``_window`` / ``_sup_fetch`` call site costs ~one HBM row pass at
    production widths, so the trace-time call counts are pinned —
    a regression reintroducing per-header windows fails here long
    before a hardware benchmark would catch it."""
    import jax
    import jax.numpy as jnp

    calls = {"window": 0, "sup": 0}
    real_window, real_sup = der_kernel._window, der_kernel._sup_fetch

    def count_window(*a, **k):
        calls["window"] += 1
        return real_window(*a, **k)

    def count_sup(*a, **k):
        calls["sup"] += 1
        return real_sup(*a, **k)

    der_kernel._window, der_kernel._sup_fetch = count_window, count_sup
    try:
        data = jnp.zeros((8, 1024), jnp.uint8)
        length = jnp.full((8,), 1000, jnp.int32)
        jax.eval_shape(
            lambda d, l: der_kernel.parse_certs_rows(
                der_kernel.pack_rows(d), l, scan_issuer_cn=False
            ),
            data, length,
        )
        # Fixed walk: window 1 (cert..algHdr), issuer hdr, validity +
        # subject, SPKI hdr, UIDs + extensions = 5 windows; extension
        # scan = 1 superblock fetch site (re-executed, not re-traced,
        # per outer round).
        assert calls == {"window": 5, "sup": 1}, calls
        calls["window"] = calls["sup"] = 0
        jax.eval_shape(
            lambda d, l: der_kernel.parse_certs_rows(
                der_kernel.pack_rows(d), l, scan_issuer_cn=True
            ),
            data, length,
        )
        # + the RDN scan's one superblock fetch site.
        assert calls == {"window": 5, "sup": 2}, calls
    finally:
        der_kernel._window, der_kernel._sup_fetch = real_window, real_sup


def test_serial_gather():
    ders = fixture_certs()
    data, length = pack(ders)
    out = der_kernel.parse_certs(data, length)
    serials, fits = der_kernel.gather_serials(
        data, np.asarray(out.serial_off), np.asarray(out.serial_len)
    )
    serials, fits = np.asarray(serials), np.asarray(fits)
    for i, der in enumerate(ders):
        assert fits[i]
        want = hostder.raw_serial_bytes(der)
        got = serials[i, : int(out.serial_len[i])].tobytes()
        assert got == want, i
        assert not serials[i, int(out.serial_len[i]) :].any()


def test_garbage_rejected_not_crashed():
    rng = np.random.default_rng(3)
    garbage = [rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
               for n in (0, 1, 5, 100, 700)]
    # Prepend a plausible-but-truncated cert.
    real = make_cert()
    garbage.append(real[: len(real) // 2])
    data, length = pack(garbage, pad_to=1024)
    out = der_kernel.parse_certs(data, length)
    # No lane may claim ok on structural nonsense (prob. of a random
    # byte string forming a valid TBS prefix is negligible).
    assert not np.asarray(out.ok).any()


def test_mixed_good_and_bad_lanes():
    good = fixture_certs()[:3]
    bad = [b"\x30\x03\x01\x01\xff", b""]
    ders = [good[0], bad[0], good[1], bad[1], good[2]]
    data, length = pack(ders, pad_to=1024)
    out = der_kernel.parse_certs(data, length)
    ok = np.asarray(out.ok)
    assert list(ok) == [True, False, True, False, True]


def test_long_form_lengths():
    # A cert comfortably > 256 bytes exercises 0x82 long-form at the
    # outer SEQUENCE; all fixtures do. Also verify a tiny synthetic TLV
    # with 0x81 form passes the header reader via a real cert re-pack.
    der = make_cert()
    assert der[1] in (0x81, 0x82)
    data, length = pack([der])
    out = der_kernel.parse_certs(data, length)
    assert bool(out.ok[0])


def test_mutation_fuzz_walker_host_agreement():
    """Seeded single-byte mutation fuzz over valid certs, classified
    through the differential harness (core/divergence.py — ROADMAP
    5(a)'s standing buckets).

    Contract pinned here:
    - HARD: the verdict-mismatch bucket is EMPTY — when both sides
      parse, every identity-surface field is byte-identical (serial
      window, expiry hour, CA flag, SPKI window, issuer Name window,
      issuer-CN bytes, CRLDP presence and URLs). A mismatch silently
      corrupts identity keys.
    - BOUNDED: the device-accepts/host-rejects bucket (the walker's
      leniency — it skips subtrees outside the identity surface, akin
      to Go x509's non-fatal tolerance) stays below 25% of accepts.
    - When the native extractor is present, the sidecar-undecidable
      bucket is EMPTY too (the sidecar's ok bit is pinned bit-equal to
      the walker's by tests/test_preparsed.py; drift lands here
      first).
    - The `parse.device_accept_rate` metric is published and sane (the
      fuzz must actually exercise the accept path).
    Lanes the walker rejects take the exact host lane by contract."""
    from ct_mapreduce_tpu.core import divergence
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    rng = np.random.default_rng(20260730)
    bases = fixture_certs()
    mutants: list[bytes] = []
    muts: list[tuple] = []
    for _ in range(300):
        bi = int(rng.integers(len(bases)))
        base = bytearray(bases[bi])
        pos = int(rng.integers(len(base)))
        x = int(rng.integers(1, 256))
        base[pos] ^= x
        mutants.append(bytes(base))
        muts.append((bi, pos, x))

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        report = divergence.classify_corpus(mutants)
        divergence.publish(report)
        snap = sink.snapshot()
    finally:
        tmetrics.set_sink(prev)

    # Base certs are freshly generated per run; the report's detail
    # lines carry the full repro (mutation tuples below cover the
    # threshold assertions).
    for line in report.details:
        print(line)
    accepted = report.device_accepts
    assert accepted > 50, (accepted, muts[:20])
    assert report.verdict_mismatch == 0, report.details
    assert report.device_accept_host_reject < 0.25 * accepted, (
        report.device_accept_host_reject, accepted, muts[:20])
    from ct_mapreduce_tpu.native import available

    if available():
        assert report.sidecar_undecidable == 0, report.sidecar_undecidable
    # Bucket bookkeeping is internally consistent.
    assert (report.both_accept + report.device_accept_host_reject
            == accepted)
    assert (report.both_accept + report.host_accept_device_reject
            == report.host_accepts)
    # The tracked metric really published.
    rate = snap["gauges"]["parse.device_accept_rate"]
    assert 0 < rate <= 1 and rate == accepted / report.total
    assert snap["counters"]["parse.divergence_verdict_mismatch"] == 0.0


def test_divergence_trend_floor_gate():
    """ROADMAP 5(a) increment (round 22): the divergence harness's
    bucket counts persist to a trend file (DIVERGENCE_TREND.json,
    core/divergence.record_trend), and `parse.device_accept_rate`
    must never silently drop below the recorded floor — a walker
    change that rejects lanes it used to accept shows up here before
    it shows up as fleet-wide host-lane throughput loss."""
    import json
    import os

    from ct_mapreduce_tpu.core import divergence

    trend_path = os.path.join(os.path.dirname(__file__), "..",
                              "DIVERGENCE_TREND.json")
    floor = divergence.trend_floor(trend_path)
    assert floor is not None and 0 < floor <= 1, floor

    rng = np.random.default_rng(20260730)
    bases = fixture_certs()
    mutants = []
    for _ in range(300):
        bi = int(rng.integers(len(bases)))
        base = bytearray(bases[bi])
        pos = int(rng.integers(len(base)))
        base[pos] ^= int(rng.integers(1, 256))
        mutants.append(bytes(base))
    report = divergence.classify_corpus(mutants)
    assert report.device_accept_rate >= floor, (
        f"device_accept_rate {report.device_accept_rate:.4f} dropped "
        f"below the recorded floor {floor} (DIVERGENCE_TREND.json); "
        "a deliberate strictness change must re-baseline the floor "
        "explicitly, with the why in the commit")

    # record_trend round-trips: append to a copy, floor is a ratchet
    # the harness itself never moves.
    with open(trend_path, encoding="utf-8") as fh:
        before = json.load(fh)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        tmp = os.path.join(td, "trend.json")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(before, fh)
        doc = divergence.record_trend(report, tmp)
        assert doc["floorDeviceAcceptRate"] == before["floorDeviceAcceptRate"]
        assert len(doc["runs"]) == len(before["runs"]) + 1
        assert doc["runs"][-1]["run"] == len(doc["runs"])
        assert (doc["runs"][-1]["deviceAcceptRate"]
                == round(report.device_accept_rate, 6))
        # Fresh-file path: the first run pins the floor.
        fresh = os.path.join(td, "fresh.json")
        doc2 = divergence.record_trend(report, fresh)
        assert (doc2["floorDeviceAcceptRate"]
                == round(report.device_accept_rate, 6))
        # A real-corpus run pins its OWN floor, not the fuzz one.
        doc3 = divergence.record_trend(report, fresh, corpus="real")
        assert (doc3["floorRealAcceptRate"]
                == round(report.device_accept_rate, 6))
        assert doc3["runs"][-1]["corpus"] == "real"
        assert divergence.trend_floor(fresh, corpus="real") == (
            doc3["floorRealAcceptRate"])


def test_divergence_real_corpus_floor_gate():
    """Round 24: the recorded-shard corpus (tests/data/
    recorded_shard.json.gz — real-wire get-entries pages through the
    production leaf codec) classified through the same differential
    harness. A real shard must be accepted essentially wholesale:
    the rate is graded against `floorRealAcceptRate` (pinned by the
    checked-in first run, a separate ratchet from the fuzz floor,
    which grades corpora BUILT to be mostly rejected), and the hard
    buckets stay empty — both parsers agreeing to accept a real cert
    while extracting different identity fields would poison
    aggregates silently."""
    import os

    from ct_mapreduce_tpu.audit import fixture as auditfx
    from ct_mapreduce_tpu.core import divergence

    root = os.path.join(os.path.dirname(__file__), "..")
    floor = divergence.trend_floor(
        os.path.join(root, "DIVERGENCE_TREND.json"), corpus="real")
    assert floor is not None and 0 < floor <= 1, floor

    from ct_mapreduce_tpu.audit import driver as drvlib

    doc = drvlib.load_recorded(
        os.path.join(root, "tests", "data", "recorded_shard.json.gz"))
    ders = auditfx.shard_ders(doc)
    assert len(ders) >= 1000, len(ders)
    # The shard's DERs all fit the default 1024 pad — same compiled
    # walker shape every other gate in this file uses.
    report = divergence.classify_corpus(ders)
    assert report.device_accept_rate >= floor, (
        f"real-corpus accept rate {report.device_accept_rate:.4f} "
        f"dropped below the recorded floor {floor} "
        "(DIVERGENCE_TREND.json); a deliberate strictness change "
        "must re-baseline the floor explicitly")
    assert report.verdict_mismatch == 0, report.details
    from ct_mapreduce_tpu.native import available

    if available():
        assert report.sidecar_undecidable == 0, report.sidecar_undecidable


def test_grammar_mutation_fuzz_buckets():
    """ROADMAP 5(a) increment: the grammar-aware mutators (length-
    field surgery, nested-TLV truncation/extension per ParsEval's
    methodology, arxiv 2405.18993) produce STRUCTURALLY plausible
    disagreement-inducing corpora — valid TLV trees with one
    inconsistent length — instead of random byte noise. Contract:

    - the hard bucket stays EMPTY on the structured corpus too (both
      parsers accepting with a differing identity field would mean a
      length inconsistency silently moved an identity window);
    - `parse.device_accept_rate` is PUBLISHED by this fuzz (the
      standing campaign trends it; a silent drop of the gauge would
      hide a walker regression) and the buckets are consistent;
    - the mutators really perturb structure (mutants differ from
      their bases) and the corpus still exercises accept paths.

    Runs at the single-byte fuzz's exact corpus shape (300 lanes,
    pad 1024) so the device walker reuses the compiled shape."""
    from ct_mapreduce_tpu.core import divergence
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    rng = np.random.default_rng(20260805)
    bases = fixture_certs()
    mutants = divergence.grammar_mutants(bases, rng, 300)
    assert len(mutants) == 300
    assert sum(m not in bases for m in mutants) > 250, \
        "mutators barely perturbed the corpus"

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        report = divergence.classify_corpus(mutants)
        divergence.publish(report)
        snap = sink.snapshot()
    finally:
        tmetrics.set_sink(prev)

    for line in report.details:
        print(line)
    assert report.verdict_mismatch == 0, report.details
    assert (report.both_accept + report.device_accept_host_reject
            == report.device_accepts)
    assert (report.both_accept + report.host_accept_device_reject
            == report.host_accepts)
    # The trend gauge cannot silently drop out of the fuzz.
    rate = snap["gauges"]["parse.device_accept_rate"]
    assert rate == report.device_accepts / report.total
    assert snap["counters"]["parse.divergence_verdict_mismatch"] == 0.0
