"""CTMRFL02 per-group universes (round 20): decoupled deltas and the
dirty-group incremental build path.

Pins the acceptance contract of ISSUE 16:
- cross-format parity: the same corpus compiled as CTMRFL01 and
  CTMRFL02 answers identically over the observed universe (zero false
  negatives in both; fl01 keeps its cross-group exactness, fl02 trades
  it for decoupled bytes — pinned structurally here);
- dirty tracking stays exact across table growth, a fleet merge, and
  a spill-ring restart: the capture layer's incremental content
  hashes always equal a from-scratch recompute, and a warm
  GroupBuildCache reuses clean groups at the OBJECT level (``is``),
  with bytes identical to a from-scratch build;
- the CTMRDL02 delta plane: chain replay is byte-identical at every
  prefix, untouched groups ship zero bytes, mixed-format endpoints
  are refused, and a format rollover publishes a full-snapshot anchor
  instead of a broken delta;
- rev-2 container magics round-trip the format;
- the filterFormat knob ladder (explicit > env > default).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.agg import merge  # noqa: E402
from ct_mapreduce_tpu.agg.aggregator import (  # noqa: E402
    HostSnapshotAggregator,
    TpuAggregator,
)
from ct_mapreduce_tpu.distrib import (  # noqa: E402
    ChainManifest,
    DeltaError,
    FilterDistributor,
    apply_chain,
    compute_delta,
    decode_container,
    encode_container,
)
from ct_mapreduce_tpu.distrib import delta as delta_mod  # noqa: E402
from ct_mapreduce_tpu.filter import (  # noqa: E402
    FORMAT_FL01,
    FORMAT_FL02,
    GroupBuildCache,
    SpillCaptureRing,
    build_artifact,
    build_from_aggregator,
    build_from_merged,
    content_token,
    default_format,
    normalize_format,
    resolve_filter,
)
from ct_mapreduce_tpu.filter.cache import serial_hash  # noqa: E402
from ct_mapreduce_tpu.utils import minicert  # noqa: E402

ISSUER_DER = minicert.make_cert(serial=1, issuer_cn="Fmt CA",
                                is_ca=True)
ISSUER_DER_B = minicert.make_cert(serial=2, issuer_cn="Fmt CA B",
                                  is_ca=True)


def corpus(n=60, issuer_cn="Fmt CA", issuer=ISSUER_DER, base=1000):
    return [
        (minicert.make_cert(serial=base + s, issuer_cn=issuer_cn,
                            subject_cn=f"fmt{s}.example"), issuer)
        for s in range(n)
    ]


def group_sets(rng, n_groups=5, per_group=30, salt=1):
    return {
        (f"issuer-{g}", 500_000 + 24 * g): {
            bytes([salt, g, s % 251, 9]) + bytes(
                [int(x) for x in rng.integers(0, 256, 2)])
            for s in range(per_group)
        }
        for g in range(n_groups)
    }


def tokens_of(sets):
    return {key: content_token(serials) for key, serials in sets.items()}


# -- cross-format parity --------------------------------------------------


def test_cross_format_parity_over_observed_universe():
    """The same corpus in both formats: every observed (group, serial)
    pair answers True in both — the membership contract is
    format-independent. Structure differs exactly as specified: fl02
    groups hash under ordinal 0 and collapse to a single Bloom layer
    (empty excluded universe); fl01 keeps sorted-issuer ordinals and
    the global excluded universe."""
    sets = group_sets(np.random.default_rng(2026), n_groups=5)
    art01 = build_artifact(sets, fp_rate=0.01, use_device=False,
                           fmt="fl01")
    art02 = build_artifact(sets, fp_rate=0.01, use_device=False,
                           fmt="fl02")
    assert art01.fmt == FORMAT_FL01 and art02.fmt == FORMAT_FL02
    assert art01.to_bytes()[:8] == b"CTMRFL01"
    assert art02.to_bytes()[:8] == b"CTMRFL02"
    for (iss, eh), serials in sorted(sets.items()):
        probe = sorted(serials)
        g01 = art01.group_for(iss, eh)
        g02 = art02.group_for(iss, eh)
        assert art01.query_group(g01, probe).all()
        assert art02.query_group(g02, probe).all()
    ordinals01 = sorted(g.ordinal for g in art01.groups.values())
    assert ordinals01 == list(range(len(sets)))  # sorted-issuer table
    for g in art02.groups.values():
        assert g.ordinal == 0  # no cross-group issuer numbering
        assert len(g.cascade.layers) == 1  # empty excluded set
    # Round-trip preserves the format (and the answers).
    from ct_mapreduce_tpu.filter import FilterArtifact

    back = FilterArtifact.from_bytes(art02.to_bytes())
    assert back.fmt == FORMAT_FL02
    assert back.to_bytes() == art02.to_bytes()


def test_fl02_group_bytes_decoupled_across_corpus_churn():
    """The property the delta plane is built on: adding serials to one
    group AND a whole new first-sorting issuer leaves every other fl02
    group's serialized block byte-identical. Under fl01 the new issuer
    renumbers the sorted ordinal table, re-keying (and so re-building)
    every group."""
    rng = np.random.default_rng(7)
    sets = group_sets(rng, n_groups=4)
    churn_key = sorted(sets)[0]
    sets2 = {k: set(v) for k, v in sets.items()}
    sets2[churn_key] = set(sets2[churn_key]) | {b"\xfe\xed" * 3}
    sets2[("aa-new-issuer", 900_000)] = {b"\x01\x02\x03\x04"}
    for fmt, decoupled in (("fl02", True), ("fl01", False)):
        a1 = build_artifact(sets, fp_rate=0.01, use_device=False,
                            fmt=fmt)
        a2 = build_artifact(sets2, fp_rate=0.01, use_device=False,
                            fmt=fmt)
        moved = sum(
            a1.group_bytes(iss, eh) != a2.group_bytes(iss, eh)
            for (iss, eh) in sorted(sets) if (iss, eh) != churn_key)
        if decoupled:
            assert moved == 0
        else:
            assert moved == len(sets) - 1  # ordinal shift re-keys all


# -- dirty tracking: growth, fleet merge, spill restart -------------------


def test_capture_hashes_exact_across_growth_and_checkpoint(tmp_path):
    """The dict capture's incrementally-maintained per-group hashes
    equal a from-scratch recompute — through table growth (rehash
    mid-corpus) and a checkpoint round-trip."""
    agg = TpuAggregator(capacity=1 << 8, batch_size=64, grow_at=0.5,
                        max_capacity=1 << 14)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=150) + corpus(n=20, issuer_cn="Fmt CA B",
                                      issuer=ISSUER_DER_B,
                                      base=500_000))
    assert agg.capacity > (1 << 8), "growth never fired"
    hashes = agg.capture_content_hashes()
    assert hashes is not None
    for key, serials in sorted(agg.filter_capture.items()):
        assert hashes[key] == content_token(serials)[1]

    path = str(tmp_path / "agg.npz")
    agg.save_checkpoint(path)
    assert "filter_hashes" in np.load(path, allow_pickle=True)
    snap = HostSnapshotAggregator(capacity=1 << 10)
    snap.load_checkpoint(path)
    assert snap.capture_content_hashes() == hashes

    back = TpuAggregator(capacity=1 << 10, batch_size=64)
    back.load_checkpoint(path)
    assert back.capture_content_hashes() == hashes

    # ... and the restored state keeps maintaining them incrementally.
    back.ingest(corpus(n=5, base=9000))
    h2 = back.capture_content_hashes()
    for key, serials in sorted(back.filter_capture.items()):
        assert h2[key] == content_token(serials)[1]


def test_fleet_merge_and_serial_run_agree_on_tokens():
    """A warm cache primed by the MERGED fleet build satisfies the
    serial run's build wholesale (and vice versa): merged tokens
    recompute from union sets, the serial run's come from incremental
    capture hashes, and the two must be the same value — the
    XOR-combine shortcut across workers would cancel shared serials
    and is deliberately not taken."""
    # Overlapping halves: both workers see the first 20 certs.
    half_a = corpus(n=40)
    half_b = corpus(n=40)[:20] + corpus(n=25, issuer_cn="Fmt CA B",
                                        issuer=ISSUER_DER_B,
                                        base=600_000)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        paths = []
        for w, ents in enumerate((half_a, half_b)):
            agg = TpuAggregator(capacity=1 << 10, batch_size=64)
            agg.enable_filter_capture()
            agg.ingest(ents)
            p = os.path.join(td, f"agg.w{w}.npz")
            agg.save_checkpoint(p)
            paths.append(p)
        serial = TpuAggregator(capacity=1 << 10, batch_size=64)
        serial.enable_filter_capture()
        serial.ingest(half_a + half_b)

        cache = GroupBuildCache()
        art_m = build_from_merged(merge.load_checkpoints(paths),
                                  fp_rate=0.01, cache=cache)
        assert cache.hits == 0  # cold cache: everything built
        art_s = build_from_aggregator(serial, fp_rate=0.01, cache=cache)
        assert cache.hits == len(art_m.groups)  # full reuse
        assert art_s.to_bytes() == art_m.to_bytes()


def test_spill_ring_hash_exactness_contract(tmp_path):
    """Ring hashes are exact only while every captured serial is still
    in the memory tier: a flush (or pre-existing segments at
    construction — the restart case) permanently drops to None, and
    the build path recomputes tokens from the full sets instead."""
    ring = SpillCaptureRing(str(tmp_path / "r1"), mem_bytes=1 << 20)
    key = (1, 500_000)
    ring.add(key, b"\x01\x02")
    ring.add(key, b"\x03\x04")
    ring.add(key, b"\x01\x02")  # duplicate must not double-XOR
    assert ring.content_hashes() == {
        key: serial_hash(b"\x01\x02") ^ serial_hash(b"\x03\x04")}

    spilly = SpillCaptureRing(str(tmp_path / "r2"), mem_bytes=64)
    for j in range(40):
        spilly.add(key, bytes([j]) * 8)
    assert spilly.spilled_bytes > 0
    assert spilly.content_hashes() is None  # flushed → inexact
    del spilly
    resumed = SpillCaptureRing(str(tmp_path / "r2"), mem_bytes=1 << 20)
    assert resumed.content_hashes() is None  # restart → unknown prior


def test_spilled_capture_still_feeds_the_cache(tmp_path):
    """With a flushed ring the aggregator reports no incremental
    hashes, but build_from_aggregator recomputes tokens from the
    serial sets — the second epoch still reuses every clean group."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture(spill_dir=str(tmp_path / "ring"),
                              spill_mem_bytes=256)
    agg.ingest(corpus(n=60))
    assert isinstance(agg.filter_capture, SpillCaptureRing)
    assert agg.filter_capture.spilled_bytes > 0
    assert agg.capture_content_hashes() is None

    cache = GroupBuildCache()
    art1 = build_from_aggregator(agg, fp_rate=0.01, cache=cache)
    art2 = build_from_aggregator(agg, fp_rate=0.01, cache=cache)
    assert cache.hits == len(art1.groups)
    assert art2.to_bytes() == art1.to_bytes()


# -- clean-group reuse is object-level, bytes pinned ----------------------


def test_clean_groups_reused_verbatim_across_epochs():
    """The incremental epoch tick: every clean group in epoch 2 is the
    SAME FilterGroup object epoch 1 built (``is`` — zero rebuild
    work), the churned group rebuilds, and the incremental artifact's
    bytes are identical to a from-scratch fl02 build of epoch 2."""
    rng = np.random.default_rng(41)
    sets1 = group_sets(rng, n_groups=5)
    churn_key = sorted(sets1)[2]
    sets2 = {k: set(v) for k, v in sets1.items()}
    sets2[churn_key] = set(sets2[churn_key]) | {b"\xaa\xbb\xcc\xdd"}

    cache = GroupBuildCache()
    art1 = build_artifact(sets1, fp_rate=0.01, use_device=False,
                          fmt="fl02", cache=cache,
                          tokens=tokens_of(sets1))
    art2 = build_artifact(sets2, fp_rate=0.01, use_device=False,
                          fmt="fl02", cache=cache,
                          tokens=tokens_of(sets2))
    assert cache.hits == len(sets1) - 1
    for key in sorted(sets1):
        iss, eh = key
        same = art2.group_for(iss, eh) is art1.group_for(iss, eh)
        assert same == (key != churn_key)
    scratch = build_artifact(sets2, fp_rate=0.01, use_device=False,
                             fmt="fl02")
    assert art2.to_bytes() == scratch.to_bytes()


def test_cache_ignores_fl01_and_fp_rate_changes():
    """The cache arms only the fl02 path, and a changed target FP rate
    is a miss — a tuned rate must never resurrect stale blocks."""
    sets = group_sets(np.random.default_rng(3), n_groups=3)
    cache = GroupBuildCache()
    build_artifact(sets, fp_rate=0.01, use_device=False, fmt="fl01",
                   cache=cache, tokens=tokens_of(sets))
    assert cache.misses == 0  # fl01 never consulted the cache
    build_artifact(sets, fp_rate=0.01, use_device=False, fmt="fl02",
                   cache=cache, tokens=tokens_of(sets))
    assert cache.hits == 0
    build_artifact(sets, fp_rate=0.02, use_device=False, fmt="fl02",
                   cache=cache, tokens=tokens_of(sets))
    assert cache.hits == 0  # rate change: all dirty


# -- the CTMRDL02 delta plane ---------------------------------------------


def build02(sets):
    return build_artifact(sets, fp_rate=0.01, use_device=False,
                          fmt="fl02").to_bytes()


def test_dl02_chain_replays_every_prefix():
    rng = np.random.default_rng(20260807)
    sets = group_sets(rng, n_groups=6, per_group=25, salt=2)
    blobs = [build02(sets)]
    for step in range(4):
        for key in sorted(sets)[:2]:
            sets[key] = set(sets[key]) | {
                bytes([int(x) for x in rng.integers(0, 256, 5)])
                for _ in range(int(rng.integers(1, 6)))}
        if step == 1:
            sets[("new-issuer", 700_000)] = {b"\x05\x06\x07"}
        if step == 2:
            del sets[sorted(sets)[-1]]
        blobs.append(build02(sets))
    links = [compute_delta(blobs[i], blobs[i + 1], i, i + 1)
             for i in range(len(blobs) - 1)]
    for link in links:
        assert link[:8] == b"CTMRDL02"
        assert delta_mod.delta_format(link) == FORMAT_FL02
    for i in range(1, len(blobs)):
        assert apply_chain(blobs[0], links[:i]) == blobs[i]


def test_dl02_untouched_groups_ship_zero_bytes():
    """Single-group churn: the delta names ONLY the churned group —
    no sparse-XOR salvage, no cross-group patch records at all."""
    rng = np.random.default_rng(11)
    sets = group_sets(rng, n_groups=6)
    churn_key = sorted(sets)[1]
    sets2 = {k: set(v) for k, v in sets.items()}
    sets2[churn_key] = set(sets2[churn_key]) | {b"\x10\x20\x30"}
    b1, b2 = build02(sets), build02(sets2)
    link = compute_delta(b1, b2, 0, 1)
    header, _ = delta_mod.parse_delta(link)
    touched = ([(e["issuer"], e["expHour"]) for e in header["added"]]
               + [(e["issuer"], e["expHour"])
                  for e in header["patched"]])
    assert touched == [churn_key]
    assert header["removed"] == []
    # The wire cost is one group's block plus the JSON header; at
    # fixture scale the header dominates, so only pin that the link
    # undercuts the full artifact — the ≤3% ratio is measured at 10⁷
    # by tools/filtercost.py --delta (BENCHLOG round 20).
    assert header["payloadBytes"] < len(b2) / 3
    assert len(link) < len(b2)


def test_mixed_format_delta_refused_and_rollover_anchors():
    sets = group_sets(np.random.default_rng(5), n_groups=3)
    b01 = build_artifact(sets, fp_rate=0.01, use_device=False,
                         fmt="fl01").to_bytes()
    b02 = build02(sets)
    with pytest.raises(DeltaError):
        compute_delta(b01, b02, 0, 1)
    with pytest.raises(DeltaError):
        compute_delta(b02, b01, 0, 1)

    # A format rollover mid-stream publishes a full-snapshot anchor
    # (no delta spans the boundary); the chain resumes in rev 2.
    dist = FilterDistributor()
    assert dist.publish(1, b01)
    assert dist.publish(2, b02)
    man = dist.manifest()
    assert man["format"] == "CTMRDL02"
    assert 2 in man["anchors"]
    assert dist.delta_bundle(1, 2) is None  # anchor in the span
    sets[sorted(sets)[0]].add(b"\x77\x88")
    b3 = build02(sets)
    assert dist.publish(3, b3)
    bundle = dist.delta_bundle(2, 3)
    assert bundle is not None
    ChainManifest.from_json(dist.manifest()).validate_chain(
        2, 3, [bundle])
    assert apply_chain(b02, [bundle]) == b3


# -- rev-2 containers -----------------------------------------------------


def test_container_rev2_magics_round_trip_format():
    sets = group_sets(np.random.default_rng(9), n_groups=3)
    for fmt, mb_magic, cc_magic in (
            ("fl01", b"CTMRMB01", b"CTMRCC01"),
            ("fl02", b"CTMRMB02", b"CTMRCC02")):
        art = build_artifact(sets, fp_rate=0.01, use_device=False,
                             fmt=fmt)
        for kind, magic in (("mlbf", mb_magic), ("clubcard", cc_magic)):
            blob = encode_container(art, kind)
            assert blob[:8] == magic
            back = decode_container(blob)
            assert back.fmt == fmt
            assert back.to_bytes() == art.to_bytes()


# -- the filterFormat knob ladder -----------------------------------------


def test_format_knob_ladder(monkeypatch):
    monkeypatch.delenv("CTMR_FILTER_FORMAT", raising=False)
    assert default_format() == FORMAT_FL02
    assert resolve_filter().fmt == FORMAT_FL02
    monkeypatch.setenv("CTMR_FILTER_FORMAT", "CTMRFL01")
    assert default_format() == FORMAT_FL01
    assert resolve_filter().fmt == FORMAT_FL01
    # Explicit (config directive) outranks env.
    assert resolve_filter(fmt="fl02").fmt == FORMAT_FL02
    # Junk env is ignored by the ladder (config-layer tolerance) ...
    monkeypatch.setenv("CTMR_FILTER_FORMAT", "fl99")
    assert default_format() == FORMAT_FL02
    assert resolve_filter().fmt == FORMAT_FL02
    # ... but a junk explicit value fails loudly.
    with pytest.raises(ValueError):
        resolve_filter(fmt="fl99")
    with pytest.raises(ValueError):
        normalize_format("CTMRFL99")


def test_serve_refresh_reuses_clean_groups():
    """The serve plane's periodic refresh rides the oracle-lifetime
    cache: an unchanged capture rebuilds nothing, and /healthz
    reports the format and the reuse count."""
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=30))
    oracle = MembershipOracle(agg, filter_first=True,
                              max_delay_s=0.001)
    try:
        n_groups = len(oracle.filter_tier.artifact.groups)
        oracle.refresh_filter()
        stats = oracle.stats()
        assert stats["filter_format"] == FORMAT_FL02
        assert stats["filter_groups_reused"] >= n_groups
    finally:
        oracle.close()
