"""Merging per-worker fleet aggregates into one reduce view.

A multi-worker ingest fleet (ingest/fleet.py) leaves one aggregate
checkpoint per worker. Each worker's own :meth:`drain` already
resolves its internal host/device dedup overlap with a consistent
issuer indexing, so the fleet-level merge is the MapReduce reduce-side
union over those drained snapshots:

- per-(issuer, expDate) serial **counts sum** — partitions are
  disjoint over entries by the rendezvous partitioner, so no entry is
  counted twice;
- per-issuer CRL/DN metadata and host-lane serial bytes **set-union**
  (idempotent, so checkpoint-replayed tails merge cleanly);
- verify verdict counts sum.

Honest limit: a certificate *identity* cross-logged into two logs
owned by DIFFERENT workers counts once per owning worker here (their
device tables hold 128-bit fingerprints under worker-local issuer
indices — not comparable across workers), where the reference's single
global Redis SADD — and this repo's single-job mesh-sharded mode —
would count it once. Exact global dedup across partitions needs the
shared-table modes; the fleet trades that for N× feed throughput.
"""

from __future__ import annotations

import glob
import os

from ct_mapreduce_tpu.agg.aggregator import (
    AggregateSnapshot,
    HostSnapshotAggregator,
    IssuerRegistry,
)


def expand_state_paths(spec: str) -> list[str]:
    """``aggStatePath`` → concrete snapshot paths: comma-separated
    entries, each optionally a glob (``agg.w*.npz``). Non-glob entries
    pass through even when absent (the caller reports the miss); glob
    entries expand to what exists, sorted for determinism."""
    paths: list[str] = []
    for part in (p.strip() for p in spec.split(",")):
        if not part:
            continue
        if any(ch in part for ch in "*?["):
            paths.extend(sorted(glob.glob(part)))
        else:
            paths.append(part)
    return paths


def merge_snapshots(snaps) -> AggregateSnapshot:
    """Reduce-side union of drained per-worker snapshots: counter sum
    + metadata set-union."""
    counts: dict[tuple[str, str], int] = {}
    crls: dict[str, set[str]] = {}
    dns: dict[str, set[str]] = {}
    verified: dict[str, int] = {}
    failed: dict[str, int] = {}
    # Sorted folds: the merged dicts' insertion order (which reaches
    # serialized reports/checkpoints downstream) must be a function of
    # the CONTENT, not of each worker's fold arrival order
    # (ctmrlint: determinism).
    for snap in snaps:
        for key, n in sorted(snap.counts.items()):
            counts[key] = counts.get(key, 0) + n
        for iss, urls in sorted(snap.crls.items()):
            crls.setdefault(iss, set()).update(urls)
        for iss, names in sorted(snap.dns.items()):
            dns.setdefault(iss, set()).update(names)
        for iss, n in sorted(snap.verified.items()):
            verified[iss] = verified.get(iss, 0) + n
        for iss, n in sorted(snap.failed.items()):
            failed[iss] = failed.get(iss, 0) + n
    return AggregateSnapshot(
        counts=counts, crls=crls, dns=dns, total=sum(counts.values()),
        verified=verified, failed=failed,
    )


class MergedAggregate:
    """A fleet's worth of worker checkpoints presented through the
    surface ``storage-statistics`` reads from a single aggregator:
    ``drain()`` (the merged snapshot), ``registry`` (union issuer
    indexing), and ``host_serials`` (worker-local indices remapped
    into it, serial byte-sets unioned)."""

    def __init__(self) -> None:
        self.registry = IssuerRegistry()
        self.host_serials: dict[tuple[int, int], set[bytes]] = {}
        # Union of the workers' filter captures (round 15), remapped to
        # the merged issuer indexing — the seed of the merged fleet
        # filter artifact (filter/artifact.py::build_from_merged).
        self.filter_serials: dict[tuple[int, int], set[bytes]] = {}
        # Checkpoints folded WITHOUT a capture: a merged filter built
        # over these would silently miss their device-lane serials, so
        # the builder refuses unless explicitly allowed.
        self.capture_missing: list[str] = []
        self._snapshots: list[AggregateSnapshot] = []
        self.worker_paths: list[str] = []

    def fold_checkpoint(self, path: str) -> None:
        """Load one worker's ``.npz`` checkpoint, drain it through the
        worker's own exact fold path, and union the results in."""
        agg = HostSnapshotAggregator(capacity=1 << 10)
        agg.load_checkpoint(path)
        self._snapshots.append(agg.drain())
        self.worker_paths.append(path)
        remap = {
            idx: self.registry.assign_issuer(agg.registry.issuer_at(idx))
            for idx in range(len(agg.registry))
        }
        # Sorted for the same reason as merge_snapshots: merged-dict
        # insertion order must not encode worker fold order.
        for (idx, eh), serials in sorted(agg.host_serials.items()):
            key = (remap[idx], eh)
            self.host_serials.setdefault(key, set()).update(serials)
        if agg.filter_capture is None:
            self.capture_missing.append(path)
        else:
            for (idx, eh), serials in sorted(agg.filter_capture.items()):
                key = (remap[idx], eh)
                self.filter_serials.setdefault(key, set()).update(serials)

    def drain(self) -> AggregateSnapshot:
        return merge_snapshots(self._snapshots)


def load_checkpoints(paths) -> MergedAggregate:
    """Fold every worker checkpoint into one merged view."""
    merged = MergedAggregate()
    for path in paths:
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        merged.fold_checkpoint(path)
    return merged


__all__ = [
    "AggregateSnapshot",
    "MergedAggregate",
    "expand_state_paths",
    "load_checkpoints",
    "merge_snapshots",
]
