"""Multi-chip sharded dedup: the distributed reduce over a device mesh.

The reference scales out by pointing many processes at one Redis
(/root/reference/coordinator/coordinator.go); the shared SADD state is
the bottleneck every worker serializes on. Here the dedup table is
**sharded by key across the mesh** and batches are **sharded along the
batch axis** (DP), with an expert-parallel-style exchange in between —
the TPU-native layout SURVEY.md §2.2/§2.3 prescribes:

1. Each device parses/filters/fingerprints its local slice of the batch
   (pure data parallelism — no communication).
2. Each fingerprint's *home shard* is a hash of the key; lanes are
   routed to their home with a fixed-capacity dispatch + ``all_to_all``
   over ICI (exactly the MoE token-dispatch pattern, with certificates
   as tokens and table shards as experts).
3. Every device runs the insert-if-absent op against its local table
   shard — keys for one shard never touch another, so no cross-device
   races exist by construction.
4. Results ride the inverse ``all_to_all`` home and are scattered back
   to original lane order.

Dispatch capacity is ``factor × B_local / n_shards`` per
(source, destination) pair; lanes that overflow a full dispatch slot
are flagged and take the exact host lane, identically to probe
overflow — the parity contract never depends on capacity tuning.

Everything is a single ``shard_map``-wrapped jitted step over a 1-D
``jax.sharding.Mesh``; the same code runs on a virtual CPU mesh in
tests and on a TPU pod slice in production.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.ops import buckettable, hashtable, pipeline
from ct_mapreduce_tpu.utils.jax_compat import shard_map

AXIS = "shard"


def mesh_capacity(n_shards: int, capacity: int,
                  layout: str | None = None) -> int:
    """Smallest capacity ≥ ``capacity`` that divides over ``n_shards``
    with a power-of-two per-shard unit (slots for the open layout,
    buckets for the bucket layout — the hash mask requirement)."""
    if (layout or pipeline.table_layout()) == "bucket":
        per_slots = max(1, -(-capacity // n_shards))
        nb_loc = 1 << max(
            0, (per_slots + buckettable.SLOTS - 1) // buckettable.SLOTS - 1
        ).bit_length()
        return n_shards * nb_loc * buckettable.SLOTS
    per = max(1, -(-capacity // n_shards))  # ceil
    return n_shards * (1 << (per - 1).bit_length())


class ShardedStepOut(NamedTuple):
    was_unknown: jax.Array  # bool[B]
    host_lane: jax.Array  # bool[B] (parse/serial/meta/probe/dispatch overflow)
    filtered_ca: jax.Array  # bool[B]
    filtered_expired: jax.Array  # bool[B]
    filtered_cn: jax.Array  # bool[B]
    not_after_hour: jax.Array  # int32[B]
    serials: jax.Array  # uint8[B, MAX_SERIAL]
    serial_len: jax.Array  # int32[B]
    issuer_unknown_counts: jax.Array  # int32[num_issuers] (global, replicated)
    has_crldp: jax.Array
    crldp_off: jax.Array
    crldp_len: jax.Array
    issuer_name_off: jax.Array
    issuer_name_len: jax.Array
    probe_overflow: jax.Array  # bool[B] — shard-local insert exhausted
    # its probe chain (spills to the exact host lane; `overflow` metric)
    dispatch_dropped: jax.Array  # bool[B] — lane spilled past the
    # per-(src,dst) routing cap to the exact host lane (surfaced as the
    # aggregator's `dispatch_spill` metric so routing skew is observable)


def shard_of_np(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Host mirror of :func:`_shard_of` (uint32 wraparound arithmetic):
    home shard per fingerprint row ``uint32[n, 4]``. Shared by the
    checkpoint-restore router (`bulk_insert_np`) and the pre-parsed
    lane's host-side routing."""
    k = np.asarray(keys).astype(np.uint32)
    h = k[:, 2] ^ (k[:, 3] * np.uint32(0x85EBCA6B))
    return (h % np.uint32(n_shards)).astype(np.int32)


def _shard_of(keys: jax.Array, n_shards: int) -> jax.Array:
    """Home shard of each fingerprint — independent bits from the slot
    hash so shard routing doesn't correlate with in-shard probing.

    Routing is a function of the WHOLE fingerprint (expHour, issuerID,
    serial): because serials differ per certificate, even a single hot
    issuer (Zipfian reality of CT logs) spreads uniformly over shards —
    spills past the per-(src,dst) cap are binomial-tail events, not
    hot-key events (pinned by test_sharded_zipfian_issuer_skew)."""
    h = keys[:, 2] ^ (keys[:, 3] * np.uint32(0x85EBCA6B))
    return (h % np.uint32(n_shards)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n_shards", "max_probes"))
def _contains_global_bucket(
    table_rows: jax.Array, keys: jax.Array,
    n_shards: int, max_probes: int,
) -> jax.Array:
    """Membership over the globally-viewed bucket-sharded table:
    shard-of-key addressing + the local bucket-hop probe of
    ``buckettable.contains``, as one gather-only jit."""
    nb_total = table_rows.shape[0]
    nb_loc = nb_total // n_shards
    b = keys.shape[0]
    keys = buckettable._desentinel(keys.astype(jnp.uint32))
    dest = _shard_of(keys, n_shards)
    h0 = buckettable._home_bucket(keys, nb_loc)
    S = buckettable.SLOTS

    def cond(carry):
        hops, _h, open_, _found = carry
        return (hops < max_probes) & jnp.any(open_)

    def round_body(carry):
        hops, h, open_, found = carry
        row = table_rows[dest * nb_loc + h]  # [B, 128]
        match = jnp.zeros((b,), bool)
        has_empty = jnp.zeros((b,), bool)
        for s in range(S):
            w = [row[:, s * 5 + i] for i in range(4)]
            match = match | (
                (w[0] == keys[:, 0]) & (w[1] == keys[:, 1])
                & (w[2] == keys[:, 2]) & (w[3] == keys[:, 3]))
            has_empty = has_empty | ((w[0] | w[1] | w[2] | w[3]) == 0)
        found = found | (open_ & match)
        open_ = open_ & ~match & ~has_empty
        h = jnp.where(open_, (h + 1) & (nb_loc - 1), h)
        return hops + 1, h, open_, found

    _, _, _, found = jax.lax.while_loop(
        cond, round_body,
        (jnp.int32(0), h0, jnp.ones((b,), bool), jnp.zeros((b,), bool)))
    return found


@functools.partial(jax.jit, static_argnames=("n_shards", "max_probes"))
def _contains_global(
    table_rows: jax.Array, keys: jax.Array,
    n_shards: int, max_probes: int,
) -> jax.Array:
    """Membership over the globally-viewed sharded table: shard-of-key
    addressing + the local triangular probe, as one gather-only jit (no
    shard_map — XLA inserts any needed collectives for the gathers)."""
    capacity = table_rows.shape[0]
    cap_loc = capacity // n_shards
    keys = hashtable._desentinel(keys.astype(jnp.uint32))
    dest = _shard_of(keys, n_shards)
    home = hashtable._home_slot(keys, cap_loc)
    b = keys.shape[0]
    W = min(hashtable.PROBE_WIDTH, max_probes)

    def cond(carry):
        _r, _found, open_ = carry
        return jnp.any(open_)

    def round_body(carry):
        r, found, open_ = carry
        # Windowed early-exit scan (shared with hashtable.contains):
        # typically ONE table gather instead of max_probes of them.
        _slots, match_j, empty_j = hashtable._probe_window(
            table_rows, keys, home, r, W, max_probes, cap_loc,
            slot_base=dest * cap_loc,
        )
        found = found | (open_ & jnp.any(
            match_j & (jnp.cumsum(empty_j, axis=-1) == 0), axis=-1
        ))
        still = open_ & ~jnp.any(match_j | empty_j, axis=-1)
        r = jnp.where(still, r + W, r)
        open_ = still & (r < max_probes)
        return r, found, open_

    _, found, _ = jax.lax.while_loop(
        cond, round_body,
        (jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
         jnp.ones((b,), bool)),
    )
    return found


def _dispatch(
    payload: jax.Array, dest: jax.Array, active: jax.Array,
    n_shards: int, cap: int,
):
    """Route lanes to destination shards with fixed per-dest capacity.

    payload: [B_loc, W] uint32 rows; dest: int32[B_loc]; active: bool.
    Returns (send [n_shards, cap, W], send_valid [n_shards, cap],
    slot_of_lane int32[B_loc] (-1 ⇒ dropped), pos_of_lane int32[B_loc]).
    """
    b = dest.shape[0]
    dest_eff = jnp.where(active, dest, n_shards)  # inactive → dummy bin
    # Rank within destination (MoE position-in-expert). For the usual
    # narrow meshes, one cumsum per shard beats the stable lexsort
    # 3.8x on TPU (1.5 ms vs 5.9 ms at 131K lanes, n=8) and assigns
    # IDENTICAL ranks (both are lane-order-stable). Wide meshes fall
    # back to the sort, whose cost doesn't scale with shard count.
    if n_shards <= 32:
        rank = jnp.zeros((b,), jnp.int32)
        for d in range(n_shards):  # dummy-bin lanes never need a rank
            m = dest_eff == d
            rank = jnp.where(m, jnp.cumsum(m.astype(jnp.int32)) - 1, rank)
    else:
        order = jnp.lexsort((jnp.arange(b, dtype=jnp.int32), dest_eff))
        d_sorted = dest_eff[order]
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), d_sorted[1:] != d_sorted[:-1]]
        )
        pos = jnp.arange(b, dtype=jnp.int32)
        group_start = jnp.where(is_start, pos, 0)
        group_start = jax.lax.associative_scan(jnp.maximum, group_start)
        rank_sorted = pos - group_start
        rank = jnp.zeros((b,), jnp.int32).at[order].set(rank_sorted)

    fits = active & (rank < cap)
    flat = jnp.where(fits, dest_eff * cap + rank, n_shards * cap)  # OOB drops
    send = jnp.zeros((n_shards * cap, payload.shape[1]), payload.dtype)
    send = send.at[flat].set(payload, mode="drop")
    send_valid = jnp.zeros((n_shards * cap,), bool).at[flat].set(fits, mode="drop")
    return (
        send.reshape(n_shards, cap, payload.shape[1]),
        send_valid.reshape(n_shards, cap),
        jnp.where(fits, flat, -1),
        rank,
    )


def _local_step(
    table_rows, table_count,
    data, length, issuer_idx, valid,
    now_hour, base_hour, cn_prefixes, cn_prefix_lens,
    *, n_shards: int, cap: int, num_issuers: int, max_probes: int,
    bucket: bool = False, axis: str = AXIS,
):
    """Per-device body, run under shard_map over the 1-D mesh."""
    # --- stage 1: local parse / filter / fingerprint (pure DP) ----------
    lanes = pipeline.local_lanes(
        data, length, issuer_idx, valid, now_hour, base_hour,
        cn_prefixes, cn_prefix_lens, num_issuers,
    )
    parsed = lanes.parsed

    # --- stage 2: dispatch to home shards -------------------------------
    # Payload is 5 uint32 words: 4 fingerprint words + the meta word
    # (which already encodes issuer_idx in its high bits).
    dest = _shard_of(lanes.fps, n_shards)
    payload = jnp.concatenate([lanes.fps, lanes.meta[:, None]], axis=1)
    send, send_valid, slot_of_lane, _ = _dispatch(
        payload, dest, lanes.insertable, n_shards, cap
    )
    dispatch_dropped = lanes.insertable & (slot_of_lane < 0)

    recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0, tiled=True)
    recv_valid = jax.lax.all_to_all(
        send_valid, axis, split_axis=0, concat_axis=0, tiled=True
    )

    # --- stage 3: local insert ------------------------------------------
    rk = recv.reshape(n_shards * cap, 5)
    rvalid = recv_valid.reshape(n_shards * cap)
    rkeys, rmeta = rk[:, :4], rk[:, 4]
    if bucket:
        state = buckettable.BucketTable(table_rows, table_count)
    else:
        state = hashtable.TableState(table_rows, table_count)
    state, r_unknown, r_overflow = pipeline.table_insert(
        state, rkeys, rmeta, rvalid, max_probes=max_probes
    )

    # Per-issuer counts of fresh inserts, reduced across the mesh.
    r_issuer = (rmeta >> packing.META_HOUR_BITS).astype(jnp.int32)
    local_counts = jnp.zeros((num_issuers,), jnp.int32).at[r_issuer].add(
        r_unknown.astype(jnp.int32), mode="drop"
    )
    issuer_counts = jax.lax.psum(local_counts, axis)

    # --- stage 4: route results home (1 word: unknown | overflow<<1) ----
    back = (
        r_unknown.astype(jnp.uint32) | (r_overflow.astype(jnp.uint32) << 1)
    ).reshape(n_shards, cap, 1)
    back = jax.lax.all_to_all(back, axis, split_axis=0, concat_axis=0, tiled=True)
    back = back.reshape(n_shards * cap)

    flat_slot = jnp.where(slot_of_lane >= 0, slot_of_lane, 0)
    lane_res = back[flat_slot]
    sent = slot_of_lane >= 0
    was_unknown = sent & ((lane_res & 1) != 0)
    probe_overflow = sent & ((lane_res & 2) != 0)

    host_lane = (
        (valid & ~parsed.ok)
        | (lanes.passed & ~lanes.device_exact)
        | dispatch_dropped
        | probe_overflow
    )

    return (
        state.rows, state.count,
        ShardedStepOut(
            was_unknown=was_unknown,
            host_lane=host_lane,
            filtered_ca=lanes.filtered_ca,
            filtered_expired=lanes.filtered_expired,
            filtered_cn=lanes.filtered_cn,
            not_after_hour=parsed.not_after_hour,
            serials=lanes.serials,
            serial_len=parsed.serial_len,
            issuer_unknown_counts=issuer_counts,
            has_crldp=parsed.has_crldp,
            crldp_off=parsed.crldp_off,
            crldp_len=parsed.crldp_len,
            issuer_name_off=parsed.issuer_off,
            issuer_name_len=parsed.issuer_len,
            probe_overflow=probe_overflow,
            dispatch_dropped=dispatch_dropped,
        ),
    )


def _local_preparsed_step(
    table_rows, table_count,
    serials, serial_len, not_after_hour, issuer_idx, insertable,
    base_hour,
    *, num_issuers: int, max_probes: int, flag_cap: int,
    bucket: bool = False, axis: str = AXIS,
):
    """Per-device body of the PRE-PARSED sharded step.

    Lanes arrive ALREADY ROUTED: the host computed every lane's home
    shard from its fingerprint (`core.packing.fingerprints_np` +
    `shard_of_np` — the same hash `_shard_of` uses) and partitioned the
    compact sidecar fields per shard before H2D. So this body is pure
    shard-local work — fingerprint + insert + counts, no dispatch, no
    ``all_to_all`` — and the only collective is the `psum` on the
    per-issuer fresh-insert counts. The ~59 B/lane wire win of the
    pre-parsed lane survives intact (row bytes never ship; the walker
    path would have moved padded rows over the batch axis instead).

    Outputs mirror `pipeline.preparsed_core`'s compact readback, per
    shard: one int32 row [inserted, ovf_count, was-unknown bitmask,
    compacted overflow lane ids] + the full overflow bitmask (fetched
    only on a compacted-flag spill) + replicated psum'd counts.
    """
    c = serial_len.shape[0]  # per-shard lane slots
    nb = -(-c // 32)
    if bucket:
        state = buckettable.BucketTable(table_rows, table_count)
    else:
        state = hashtable.TableState(table_rows, table_count)
    fps = pipeline.fingerprints(issuer_idx, not_after_hour, serials,
                                serial_len)
    hour_off = not_after_hour - base_hour
    meta = (
        (issuer_idx.astype(jnp.uint32) << packing.META_HOUR_BITS)
        | jnp.clip(hour_off, 0, packing.META_HOUR_SPAN - 1).astype(
            jnp.uint32)
    )
    state, wu, ovf = pipeline.table_insert(
        state, fps, meta, insertable, max_probes=max_probes
    )
    local_counts = jnp.zeros((num_issuers,), jnp.int32).at[issuer_idx].add(
        wu.astype(jnp.int32), mode="drop"
    )
    counts = jax.lax.psum(local_counts, axis)
    iota = jnp.arange(c, dtype=jnp.int32)
    ovf_idx = jnp.sort(jnp.where(ovf, iota, c))[:flag_cap]
    if flag_cap > c:
        ovf_idx = jnp.pad(ovf_idx, (0, flag_cap - c), constant_values=c)
    row = jnp.concatenate([
        jnp.stack([wu.sum(dtype=jnp.int32), ovf.sum(dtype=jnp.int32)]),
        jax.lax.bitcast_convert_type(
            pipeline._pack_bits(wu, nb), jnp.int32),
        ovf_idx,
    ])
    return (
        state.rows, state.count,
        row[None],                          # → int32[n_shards, 2+nb+cap]
        pipeline._pack_bits(ovf, nb)[None],  # → uint32[n_shards, nb]
        counts,                              # replicated
    )


class ShardedDedup:
    """Mesh-wide dedup state + the compiled sharded step.

    Table rows are sharded over ``mesh`` axis 0; batches arrive sharded
    along the batch axis. One instance per process (multi-host runs use
    the same global mesh via ``jax.distributed``).
    """

    def __init__(
        self,
        mesh: Mesh,
        capacity: int,
        base_hour: int = packing.DEFAULT_BASE_HOUR,
        num_issuers: int = packing.MAX_ISSUERS,
        max_probes: int = 32,
        dispatch_factor: float = 2.0,
    ) -> None:
        if len(mesh.axis_names) != 1:
            raise ValueError(
                f"ShardedDedup needs a 1-D mesh, got axes {mesh.axis_names}; "
                "flatten the mesh first (models.build_aggregator does this)"
            )
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.layout = pipeline.table_layout()
        if capacity % self.n_shards:
            raise ValueError("capacity must divide evenly across the mesh")
        per_shard = capacity // self.n_shards
        row_sharded = NamedSharding(mesh, P(self.axis))
        if self.layout == "bucket":
            # The home-bucket mask operates on each LOCAL shard's
            # bucket array inside shard_map, so per-shard BUCKET count
            # must be a power of two — rounded UP here (capacity is a
            # floor, mirroring buckettable.make_table; the realized
            # slot count is ``self.capacity`` after this block).
            nb_loc = 1 << max(
                0, (per_shard + buckettable.SLOTS - 1) // buckettable.SLOTS
                - 1).bit_length()
            capacity = self.n_shards * nb_loc * buckettable.SLOTS
            # Bucket rows, row-sharded: shard i holds buckets
            # [i*nb_loc, (i+1)*nb_loc).
            self.rows = jax.device_put(
                jnp.zeros((self.n_shards * nb_loc, buckettable.ROW_WORDS),
                          jnp.uint32), row_sharded
            )
        else:
            # The triangular-probe mask operates on each LOCAL shard
            # inside shard_map, so per-shard SLOT count must be a
            # power of two.
            if per_shard & (per_shard - 1):
                raise ValueError("per-shard capacity must be a power of two")
            # Fused table rows (4 fp words + meta), row-sharded over
            # the mesh — same layout as the single-chip TableState.
            self.rows = jax.device_put(
                jnp.zeros((capacity, 5), jnp.uint32), row_sharded
            )
        self.capacity = capacity
        self.base_hour = base_hour
        self.num_issuers = num_issuers
        self.max_probes = max_probes
        self.dispatch_factor = dispatch_factor
        self.count = jax.device_put(
            jnp.zeros((self.n_shards,), jnp.int32), row_sharded
        )
        self._step_cache: dict = {}

    def _compiled(self, b: int, l: int, p: int, k: int):
        key = (b, l, p, k)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        n = self.n_shards
        if b % n:
            raise ValueError(f"batch size {b} must divide over {n} shards")
        # Per-(src,dst) dispatch quota: expected b_loc/n with headroom;
        # floored so tiny batches keep full capacity (no spurious
        # host-lane fallbacks in small runs/tests).
        b_loc = b // n
        cap = min(b_loc, max(8, int(self.dispatch_factor * b_loc / n)))

        local = functools.partial(
            _local_step,
            n_shards=n,
            cap=cap,
            num_issuers=self.num_issuers,
            max_probes=self.max_probes,
            bucket=self.layout == "bucket",
            axis=self.axis,
        )
        A = P(self.axis)
        mapped = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                A, A,  # fused table rows + per-shard counts
                A, A, A, A,  # batch
                P(), P(), P(), P(),  # scalars + prefixes (replicated)
            ),
            out_specs=(
                A, A,
                ShardedStepOut(
                    was_unknown=A, host_lane=A,
                    filtered_ca=A, filtered_expired=A,
                    filtered_cn=A, not_after_hour=A,
                    serials=A, serial_len=A,
                    issuer_unknown_counts=P(),
                    has_crldp=A, crldp_off=A, crldp_len=A,
                    issuer_name_off=A, issuer_name_len=A,
                    probe_overflow=A, dispatch_dropped=A,
                ),
            ),
            check_vma=False,
        )
        fn = jax.jit(mapped, donate_argnums=(0, 1))
        self._step_cache[key] = fn
        return fn

    def step(
        self,
        data: np.ndarray,
        length: np.ndarray,
        issuer_idx: np.ndarray,
        valid: np.ndarray,
        now_hour: int,
        cn_prefixes: np.ndarray | None = None,
        cn_prefix_lens: np.ndarray | None = None,
    ) -> ShardedStepOut:
        if cn_prefixes is None:
            cn_prefixes = np.zeros((0, 32), np.uint8)
            cn_prefix_lens = np.zeros((0, 2), np.int32)
        b, l = data.shape
        fn = self._compiled(b, l, cn_prefixes.shape[0], cn_prefixes.shape[1])
        batch_sharding = NamedSharding(self.mesh, P(self.axis))
        args = [
            jax.device_put(jnp.asarray(x), batch_sharding)
            for x in (data, length, issuer_idx, valid)
        ]
        self.rows, self.count, out = fn(
            self.rows, self.count,
            *args,
            jnp.int32(now_hour), jnp.int32(self.base_hour),
            jnp.asarray(cn_prefixes), jnp.asarray(cn_prefix_lens),
        )
        return out

    def _preparsed_fn(self, c: int, flag_cap: int):
        """Compiled pre-parsed step for per-shard width ``c`` (cached;
        the caller pads c to a power of two so shape churn is log-
        bounded)."""
        key = ("preparsed", c, flag_cap)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        local = functools.partial(
            _local_preparsed_step,
            num_issuers=self.num_issuers,
            max_probes=self.max_probes,
            flag_cap=flag_cap,
            bucket=self.layout == "bucket",
            axis=self.axis,
        )
        A = P(self.axis)
        mapped = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(A, A, A, A, A, A, A, P()),
            out_specs=(A, A, A, A, P()),
            check_vma=False,
        )
        fn = jax.jit(mapped, donate_argnums=(0, 1))
        self._step_cache[key] = fn
        return fn

    def step_preparsed(
        self,
        serials: np.ndarray,      # uint8[n_shards*C, MAX_SERIAL]
        serial_len: np.ndarray,   # int32[n_shards*C]
        not_after_hour: np.ndarray,
        issuer_idx: np.ndarray,
        insertable: np.ndarray,   # bool[n_shards*C]
        flag_cap: int,
    ):
        """Walker-free sharded step over HOST-ROUTED sidecar lanes:
        slot ``s*C + j`` belongs to shard ``s`` (the caller routed each
        lane to ``shard_of_np(fingerprints_np(...))`` and padded every
        shard's range to C with insertable=False slots). Returns
        ``(packed, overflow_bits, counts)`` device arrays — the
        per-shard compact readback of `_local_preparsed_step`."""
        ns = self.n_shards
        c = int(serial_len.shape[0]) // ns
        fn = self._preparsed_fn(c, flag_cap)
        sh = NamedSharding(self.mesh, P(self.axis))
        args = [
            jax.device_put(jnp.asarray(x), sh)
            for x in (serials, serial_len, not_after_hour,
                      issuer_idx, insertable)
        ]
        self.rows, self.count, packed, ovf_bits, counts = fn(
            self.rows, self.count, *args, jnp.int32(self.base_hour)
        )
        return packed, ovf_bits, counts

    def _bulk_insert_fn(self, width: int):
        cache_key = ("bulk", width)
        fn = self._step_cache.get(cache_key)
        if fn is not None:
            return fn

        bucket = self.layout == "bucket"

        def local(table_rows, table_count, send, meta, valid):
            if bucket:
                state = buckettable.BucketTable(table_rows, table_count)
            else:
                state = hashtable.TableState(table_rows, table_count)
            state, _, overflow = pipeline.table_insert(
                state, send[0], meta[0], valid[0], max_probes=self.max_probes
            )
            return (
                state.rows, state.count,
                jnp.sum(overflow, dtype=jnp.int32)[None],
            )

        mapped = shard_map(
            local,
            mesh=self.mesh,
            in_specs=tuple([P(self.axis)] * 5),
            out_specs=tuple([P(self.axis)] * 3),
            check_vma=False,
        )
        fn = jax.jit(mapped, donate_argnums=(0, 1))
        self._step_cache[cache_key] = fn
        return fn

    def bulk_insert_np(
        self, keys_np: np.ndarray, meta_np: np.ndarray, chunk: int = 65536
    ) -> int:
        """Reinsert pre-hashed (fingerprint, meta) rows — the
        topology-independent restore path. Rows are routed to their home
        shard on the host (this runs once per restore, not per batch),
        then inserted per-shard under shard_map. Returns the number of
        rows that overflowed probing (0 unless the table is undersized)."""
        n = self.n_shards
        if keys_np.size == 0:
            return 0
        dest = shard_of_np(keys_np, n).astype(np.int64)
        per_shard = [np.flatnonzero(dest == i) for i in range(n)]
        max_len = max(idx.size for idx in per_shard)
        overflowed = 0
        batch_sharding = NamedSharding(self.mesh, P(self.axis))
        for start in range(0, max_len, chunk):
            width = min(chunk, max_len - start)
            send = np.zeros((n, width, 4), np.uint32)
            meta = np.zeros((n, width), np.uint32)
            valid = np.zeros((n, width), bool)
            for i, idx in enumerate(per_shard):
                sl = idx[start : start + width]
                send[i, : sl.size] = keys_np[sl]
                meta[i, : sl.size] = meta_np[sl]
                valid[i, : sl.size] = True
            fn = self._bulk_insert_fn(width)
            self.rows, self.count, ovf = fn(
                self.rows, self.count,
                jax.device_put(jnp.asarray(send), batch_sharding),
                jax.device_put(jnp.asarray(meta), batch_sharding),
                jax.device_put(jnp.asarray(valid), batch_sharding),
            )
            overflowed += int(jnp.sum(ovf))
        return overflowed

    def total_count(self) -> int:
        return int(jnp.sum(self.count))

    def contains_np(self, fps_np: np.ndarray) -> np.ndarray:
        """Batched membership probe against the sharded table.

        Mirrors the sharded insert addressing exactly: home shard from
        `_shard_of`, then the local triangular probe within that
        shard's row block (each shard's `hashtable.insert` runs on its
        local slice, so local capacity masks the slot). Used by the
        host lane's cross-domain dedup guard."""
        if fps_np.size == 0:
            return np.zeros((0,), bool)
        fn = (_contains_global_bucket if self.layout == "bucket"
              else _contains_global)
        return np.asarray(fn(
            self.rows, jnp.asarray(fps_np.astype(np.uint32)),
            n_shards=self.n_shards, max_probes=self.max_probes,
        ))

    def drain_np(self) -> tuple[np.ndarray, np.ndarray]:
        if self.layout == "bucket":
            return buckettable.drain_np(
                buckettable.BucketTable(self.rows, self.count)
            )
        return hashtable.drain_np(
            hashtable.TableState(self.rows, self.count)
        )
