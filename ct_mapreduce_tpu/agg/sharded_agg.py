"""Exact-parity aggregator over the mesh-sharded dedup.

:class:`ShardedAggregator` is :class:`TpuAggregator` with the device
path swapped for :class:`~ct_mapreduce_tpu.agg.sharded.ShardedDedup`:
batches shard along the batch axis, keys route to their home table
shard over ICI ``all_to_all``, per-issuer counts come back ``psum``'d —
while the host-side exact lane, issuer registry, CRL/DN accumulation,
drain, and checkpoint contract stay identical. One process drives the
whole mesh (multi-host runs drive the global mesh via
``jax.distributed``; see ct_mapreduce_tpu.parallel.distributed).
"""

from __future__ import annotations

from datetime import datetime
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.agg.sharded import ShardedDedup
from ct_mapreduce_tpu.core import packing


class ShardedAggregator(TpuAggregator):
    def __init__(
        self,
        mesh,
        capacity: int = 1 << 22,
        batch_size: int = 4096,
        base_hour: int = packing.DEFAULT_BASE_HOUR,
        cn_prefixes: tuple[str, ...] = (),
        max_probes: int = 32,
        now: Optional[datetime] = None,
        dispatch_factor: float = 2.0,
        grow_at: float = 0.55,
        max_capacity: int = 1 << 28,
    ) -> None:
        self.mesh = mesh
        n = mesh.devices.size
        if batch_size % n:
            raise ValueError(f"batch_size {batch_size} must divide over {n} devices")
        # Auto-growth is a LOCKSTEP operation (every process must
        # rebuild + reinsert the same mesh-wide table at the same
        # point), but its trigger derives from per-process fill
        # estimates that diverge across hosts — a recipe for collective
        # deadlock. Until a replicated trigger exists, growth is
        # disabled when THIS mesh spans multiple processes (a
        # process-local mesh inside a multi-host job keeps growing
        # normally); probe overflow still spills to the exact host
        # lane, so counts stay exact.
        import jax

        mesh_procs = {d.process_index for d in mesh.devices.flat}
        if grow_at > 0 and len(mesh_procs) > 1:
            if jax.process_index() == min(mesh_procs):
                import sys

                print(
                    "ShardedAggregator: disabling table auto-growth — "
                    f"the mesh spans {len(mesh_procs)} processes; size "
                    "tableBits for the full run or re-shard via "
                    "checkpoint",
                    file=sys.stderr,
                )
            grow_at = 0.0
        from ct_mapreduce_tpu.agg.sharded import mesh_capacity

        self.dedup = ShardedDedup(
            mesh,
            capacity=mesh_capacity(n, capacity),
            base_hour=base_hour,
            max_probes=max_probes,
            dispatch_factor=dispatch_factor,
        )
        super().__init__(
            capacity=capacity,
            batch_size=batch_size,
            base_hour=base_hour,
            cn_prefixes=cn_prefixes,
            max_probes=max_probes,
            now=now,
            grow_at=grow_at,
            max_capacity=max_capacity,
        )
        # Load-factor arithmetic runs on the mesh-rounded slot count.
        self.capacity = self.dedup.capacity

    # -- hooks -----------------------------------------------------------
    def _layout_capacity_floor(self, cap: int) -> int:
        """Largest mesh-buildable capacity ≤ ``cap``: shards get
        power-of-two units, so halve the per-shard unit until the
        mesh-rounded total fits under the configured ceiling."""
        from ct_mapreduce_tpu.agg.sharded import mesh_capacity

        n = self.mesh.devices.size
        target = cap
        while target >= n:
            reach = mesh_capacity(n, target)
            if reach <= cap:
                return reach
            target //= 2
        return mesh_capacity(n, 1)

    def _make_table(self, capacity: int):
        return None  # state lives in self.dedup (sharded over the mesh)

    def _drain_table(self) -> tuple[np.ndarray, np.ndarray]:
        return self.dedup.drain_np()

    def _device_contains(self, fps: np.ndarray) -> np.ndarray:
        return self.dedup.contains_np(fps)

    def _table_fill_exact(self) -> int:
        return self.dedup.total_count()

    def _device_step_preparsed(self, *args, **kwargs):
        # The pre-parsed lane's fingerprint+insert step is single-chip
        # today; the mesh path needs its key-routed dispatch fused in
        # first. Fail loudly rather than insert into a mesh table with
        # single-chip addressing (silent key loss).
        raise NotImplementedError(
            "preparsedIngest is not supported with meshShape yet; "
            "unset one of them")

    def _save_table_state(self):
        return self.dedup

    def _restore_table_state(self, saved) -> None:
        self.dedup = saved

    def _rebuild_table(self, new_capacity: int) -> int:
        self.dedup = ShardedDedup(
            self.mesh,
            capacity=self._mesh_capacity(new_capacity),
            base_hour=self.base_hour,
            max_probes=self.max_probes,
            dispatch_factor=self.dedup.dispatch_factor,
        )
        return self.dedup.capacity

    def _bulk_reinsert(self, keys: np.ndarray, meta: np.ndarray) -> int:
        return self.dedup.bulk_insert_np(keys, meta)

    def _device_step_packed(self, batch):
        self._device_written = True
        return self.dedup.step(
            np.asarray(batch.data),
            np.asarray(batch.length),
            np.asarray(batch.issuer_idx),
            np.asarray(batch.valid),
            now_hour=self._now_hour(),
            cn_prefixes=self._prefix_arr,
            cn_prefix_lens=self._prefix_lens,
        )

    def _topology_shards(self) -> int:
        return self.dedup.n_shards

    # -- checkpoint ------------------------------------------------------
    def save_checkpoint(self, path: str) -> None:
        import jax.numpy as jnp

        from ct_mapreduce_tpu.ops import buckettable, hashtable

        # Gather the sharded table to host once, reuse the parent
        # format (the state type must match the dedup's layout so the
        # codec writes the right positional keys/meta + layout +
        # n_shards fields).
        state_cls = (buckettable.BucketTable
                     if self.dedup.layout == "bucket"
                     else hashtable.TableState)
        self.table = state_cls(
            rows=jnp.asarray(np.asarray(self.dedup.rows)),
            count=jnp.asarray(np.asarray(self.dedup.count)),
        )
        try:
            super().save_checkpoint(path)
        finally:
            self.table = None

    def _restore_table(self, keys, meta, count, layout: str,
                       ckpt_shards: int) -> None:
        # Restore by REINSERTION, not raw row copy: a checkpoint may come
        # from a different topology (single chip, another mesh size) or
        # layout, and a key's home shard, bucket, and probe sequence all
        # depend on both — only re-hashing every occupied row is always
        # correct. (A same-topology fast path could raw-copy, but
        # restores are rare and reinsertion keeps one code path.)
        occ = keys.any(axis=-1)
        ckpt_cap = int(keys.shape[0])
        target_cap = max(self.dedup.capacity, ckpt_cap)
        self.dedup = ShardedDedup(
            self.mesh,
            capacity=self._mesh_capacity(target_cap),
            base_hour=self.base_hour,
            max_probes=self.max_probes,
            dispatch_factor=self.dedup.dispatch_factor,
        )
        overflow = self.dedup.bulk_insert_np(keys[occ], meta[occ])
        if overflow:
            raise RuntimeError(
                f"checkpoint restore overflowed {overflow} rows; "
                f"increase tableBits (capacity {self.dedup.capacity})"
            )
        self.capacity = self.dedup.capacity
        self.table = None

    def _mesh_capacity(self, capacity: int) -> int:
        """Round capacity so each shard gets a power-of-two slice."""
        from ct_mapreduce_tpu.agg.sharded import mesh_capacity

        return mesh_capacity(self.mesh.devices.size, capacity)
