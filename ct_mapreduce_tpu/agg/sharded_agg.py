"""Exact-parity aggregator over the mesh-sharded dedup.

:class:`ShardedAggregator` is :class:`TpuAggregator` with the device
path swapped for :class:`~ct_mapreduce_tpu.agg.sharded.ShardedDedup`:
batches shard along the batch axis, keys route to their home table
shard over ICI ``all_to_all``, per-issuer counts come back ``psum``'d —
while the host-side exact lane, issuer registry, CRL/DN accumulation,
drain, and checkpoint contract stay identical. One process drives the
whole mesh (multi-host runs drive the global mesh via
``jax.distributed``; see ct_mapreduce_tpu.parallel.distributed).
"""

from __future__ import annotations

from datetime import datetime
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.agg.sharded import ShardedDedup, shard_of_np
from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.telemetry import trace


def _pack_bits_np(flags: np.ndarray, nb: int) -> np.ndarray:
    """bool[B] → uint32[nb] bitmask (bit i of word w = lane w*32+i) —
    host mirror of ``pipeline._pack_bits``."""
    b = flags.shape[0]
    padded = np.pad(flags.astype(bool), (0, nb * 32 - b)).reshape(nb, 32)
    weights = (np.uint64(1) << np.arange(32, dtype=np.uint64))[None, :]
    return np.where(padded, weights, 0).sum(axis=1).astype(np.uint32)


def _unpack_bits_np(words: np.ndarray, n: int) -> np.ndarray:
    """uint32[..., nb] bitmask → bool[..., n] lanes."""
    bits = (words[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.astype(bool).reshape(words.shape[:-1] + (-1,))[..., :n]


class _ShardedPreparsedOut:
    """Readback adapter: the sharded pre-parsed step's per-SHARD compact
    outputs, reassembled lazily into the per-CHUNK ``PreparsedStepOut``
    layout ``TpuAggregator._fold_preparsed`` consumes. Device arrays
    stay unmaterialized until ``.packed`` is first read (the fold), so
    the submit half remains fully asynchronous, exactly like the
    single-chip lane."""

    def __init__(self, packed_s, ovf_bits_s, counts, slot_of_orig,
                 c: int, k_chunks: int, chunk: int, flag_cap: int,
                 device_cap: int, num_issuers: int) -> None:
        self._packed_s = packed_s      # device int32[n_shards, 2+nbC+dcap]
        self._ovf_bits_s = ovf_bits_s  # device uint32[n_shards, nbC]
        self._counts = counts          # device int32[num_issuers]
        self._slot = slot_of_orig      # int64[n] original lane → shard slot
        self._c = c
        self._k = k_chunks
        self._b = chunk
        self._cap = flag_cap           # per-chunk cap of the fold layout
        self._dev_cap = device_cap     # per-shard cap of the device rows
        self._num_issuers = num_issuers
        self._built = None

    def _build(self):
        if self._built is not None:
            return self._built
        P = np.asarray(self._packed_s)
        counts = np.asarray(self._counts).astype(np.int32)
        n_shards = P.shape[0]
        c, cap, dcap = self._c, self._cap, self._dev_cap
        nb_c = -(-c // 32)
        wu_slots = _unpack_bits_np(
            P[:, 2:2 + nb_c].view(np.uint32), c).reshape(-1)
        ovf_slots = np.zeros((n_shards * c,), bool)
        spilled = False
        for s in range(n_shards):
            oc = int(P[s, 1])
            if oc == 0:
                continue
            if oc <= dcap:
                ids = P[s, 2 + nb_c:2 + nb_c + oc]
                ids = ids[ids < c]
                ovf_slots[s * c + ids] = True
            else:
                # Compacted-flag spill on this shard: decode its full
                # overflow bitmask (one extra fetch, all shards).
                if not spilled:
                    bits = np.asarray(self._ovf_bits_s).view(np.uint32)
                    spilled = True
                ovf_slots[s * c:(s + 1) * c] = _unpack_bits_np(
                    bits[s], c)
        # Back to original lane order, then into the [K, B]-chunked
        # packed rows the shared fold expects.
        wu = wu_slots[self._slot]
        ovf = ovf_slots[self._slot]
        k, b, nb = self._k, self._b, -(-self._b // 32)
        width = 2 + nb + cap + self._num_issuers
        packed = np.zeros((k, width), np.int32)
        over_bits = np.zeros((k, nb), np.uint32)
        for kk in range(k):
            w = wu[kk * b:(kk + 1) * b]
            o = ovf[kk * b:(kk + 1) * b]
            packed[kk, 0] = int(w.sum())
            oc = int(o.sum())
            packed[kk, 1] = oc
            packed[kk, 2:2 + nb] = _pack_bits_np(w, nb).view(np.int32)
            ids = np.full((cap,), b, np.int32)
            if 0 < oc <= cap:
                ids[:oc] = np.nonzero(o)[0][:cap]
            packed[kk, 2 + nb:2 + nb + cap] = ids
            over_bits[kk] = _pack_bits_np(o, nb)
        # psum'd per-issuer counts ride one chunk row (the fold sums
        # the count region across chunk rows).
        packed[0, 2 + nb + cap:] = counts[:self._num_issuers]
        self._built = (packed, over_bits)
        return self._built

    @property
    def packed(self) -> np.ndarray:
        return self._build()[0]

    @property
    def overflow_bits(self) -> np.ndarray:
        return self._build()[1]


class ShardedAggregator(TpuAggregator):
    def __init__(
        self,
        mesh,
        capacity: int = 1 << 22,
        batch_size: int = 4096,
        base_hour: int = packing.DEFAULT_BASE_HOUR,
        cn_prefixes: tuple[str, ...] = (),
        max_probes: int = 32,
        now: Optional[datetime] = None,
        dispatch_factor: float = 2.0,
        grow_at: float = 0.55,
        max_capacity: int = 1 << 28,
    ) -> None:
        self.mesh = mesh
        n = mesh.devices.size
        if batch_size % n:
            raise ValueError(f"batch_size {batch_size} must divide over {n} devices")
        # Auto-growth is a LOCKSTEP operation (every process must
        # rebuild + reinsert the same mesh-wide table at the same
        # point), but its trigger derives from per-process fill
        # estimates that diverge across hosts — a recipe for collective
        # deadlock. Until a replicated trigger exists, growth is
        # disabled when THIS mesh spans multiple processes (a
        # process-local mesh inside a multi-host job keeps growing
        # normally); probe overflow still spills to the exact host
        # lane, so counts stay exact.
        import jax

        mesh_procs = {d.process_index for d in mesh.devices.flat}
        if grow_at > 0 and len(mesh_procs) > 1:
            if jax.process_index() == min(mesh_procs):
                import sys

                print(
                    "ShardedAggregator: disabling table auto-growth — "
                    f"the mesh spans {len(mesh_procs)} processes; size "
                    "tableBits for the full run or re-shard via "
                    "checkpoint",
                    file=sys.stderr,
                )
            grow_at = 0.0
        from ct_mapreduce_tpu.agg.sharded import mesh_capacity

        self.dedup = ShardedDedup(
            mesh,
            capacity=mesh_capacity(n, capacity),
            base_hour=base_hour,
            max_probes=max_probes,
            dispatch_factor=dispatch_factor,
        )
        super().__init__(
            capacity=capacity,
            batch_size=batch_size,
            base_hour=base_hour,
            cn_prefixes=cn_prefixes,
            max_probes=max_probes,
            now=now,
            grow_at=grow_at,
            max_capacity=max_capacity,
        )
        # Load-factor arithmetic runs on the mesh-rounded slot count.
        self.capacity = self.dedup.capacity

    # -- hooks -----------------------------------------------------------
    def _layout_capacity_floor(self, cap: int) -> int:
        """Largest mesh-buildable capacity ≤ ``cap``: shards get
        power-of-two units, so halve the per-shard unit until the
        mesh-rounded total fits under the configured ceiling."""
        from ct_mapreduce_tpu.agg.sharded import mesh_capacity

        n = self.mesh.devices.size
        target = cap
        while target >= n:
            reach = mesh_capacity(n, target)
            if reach <= cap:
                return reach
            target //= 2
        return mesh_capacity(n, 1)

    def _make_table(self, capacity: int):
        return None  # state lives in self.dedup (sharded over the mesh)

    def _drain_table(self) -> tuple[np.ndarray, np.ndarray]:
        return self.dedup.drain_np()

    def _device_contains(self, fps: np.ndarray) -> np.ndarray:
        return self.dedup.contains_np(fps)

    def _table_fill_exact(self) -> int:
        return self.dedup.total_count()

    # The mesh step reads its rows host-side (shard routing is a
    # host-computed partition); the staging ring must not ship the
    # stacked buffer to one device.
    staged_h2d = False

    def ingest_staged_submit(self, data, length, issuer_idx, valid,
                             host_chunks):
        """Staged lane over the mesh: the fused single-chip envelope
        doesn't apply (the walker step here is a shard_map program with
        its own per-chunk dispatch), so the staging ring's K chunks
        flatten into ONE :meth:`ingest_packed_submit` — per-chunk mesh
        steps dispatched back to back with a single deferred fold, so
        the sink-side contract (one pending per staged flush, drain
        fully async) is identical across topologies."""
        k_chunks, b = np.asarray(length).shape
        flat = np.asarray(data).reshape(k_chunks * b, -1)
        return self.ingest_packed_submit(
            flat,
            np.asarray(length, np.int32).reshape(-1),
            np.asarray(issuer_idx, np.int32).reshape(-1),
            np.asarray(valid, bool).reshape(-1),
        )

    def _device_step_preparsed(self, serials, serial_len, nah,
                               issuer_idx, insertable, flag_cap: int):
        """Pre-parsed lane over the mesh, host-routed.

        The walker path routes on device (dispatch + ``all_to_all``)
        because fingerprints only exist after the on-device parse. The
        pre-parsed lane's fingerprints are computable on the HOST from
        the sidecar's compact fields, so every lane's home shard is
        known before anything ships: lanes stable-sort by home shard,
        partition into per-shard ranges (padded to a shared power-of-
        two width C so compiled shapes stay log-bounded), and the
        device step is pure shard-local fingerprint+insert+counts —
        the ``all_to_all`` disappears and the ~59 B/lane wire win
        survives. Stable sort preserves lane order within a shard, so
        same-fingerprint duplicates resolve first-wins exactly like
        the single-chip lane (mesh=1 parity is exact, pinned by
        tests/test_sharded_preparsed.py)."""
        self._device_written = True
        k, b = np.asarray(serial_len).shape
        n = k * b
        ns = self.dedup.n_shards

        def flat(a, dtype):
            a = np.asarray(a, dtype)
            return np.ascontiguousarray(a.reshape((n,) + a.shape[2:]))

        ser = flat(serials, np.uint8)
        slen = flat(serial_len, np.int32)
        nh = flat(nah, np.int32)
        ii = flat(issuer_idx, np.int32)
        ins = flat(insertable, bool)

        fps = packing.fingerprints_np(ii, nh, ser, slen)
        dest = shard_of_np(fps, ns)
        perm = np.argsort(dest, kind="stable")
        per_shard = np.bincount(dest, minlength=ns)
        c = max(8, int(per_shard.max()))
        c = 1 << (c - 1).bit_length()  # pad to 2^k: bounded shape churn
        starts = np.zeros((ns + 1,), np.int64)
        starts[1:] = np.cumsum(per_shard)
        dsort = dest[perm].astype(np.int64)
        slot_sorted = dsort * c + (np.arange(n) - starts[dsort])
        slot_of_orig = np.empty((n,), np.int64)
        slot_of_orig[perm] = slot_sorted

        def route(a):
            out = np.zeros((ns * c,) + a.shape[1:], a.dtype)
            out[slot_sorted] = a[perm]
            return out

        cap = min(int(flag_cap), c)
        with trace.span("mesh.step_preparsed", cat="device",
                        shards=int(ns)), self._table_lock:
            packed_s, ovf_bits_s, counts = self.dedup.step_preparsed(
                route(ser), route(slen), route(nh), route(ii),
                route(ins), flag_cap=cap,
            )
        return _ShardedPreparsedOut(
            packed_s, ovf_bits_s, counts, slot_of_orig,
            c=c, k_chunks=k, chunk=b, flag_cap=int(flag_cap),
            device_cap=cap, num_issuers=packing.MAX_ISSUERS,
        )

    def _save_table_state(self):
        return self.dedup

    def _restore_table_state(self, saved) -> None:
        self.dedup = saved

    def _rebuild_table(self, new_capacity: int) -> int:
        self.dedup = ShardedDedup(
            self.mesh,
            capacity=self._mesh_capacity(new_capacity),
            base_hour=self.base_hour,
            max_probes=self.max_probes,
            dispatch_factor=self.dedup.dispatch_factor,
        )
        return self.dedup.capacity

    def _bulk_reinsert(self, keys: np.ndarray, meta: np.ndarray) -> int:
        return self.dedup.bulk_insert_np(keys, meta)

    def _device_step_packed(self, batch):
        self._device_written = True
        with trace.span("mesh.step", cat="device",
                        shards=int(self.dedup.n_shards)):
            return self.dedup.step(
                np.asarray(batch.data),
                np.asarray(batch.length),
                np.asarray(batch.issuer_idx),
                np.asarray(batch.valid),
                now_hour=self._now_hour(),
                cn_prefixes=self._prefix_arr,
                cn_prefix_lens=self._prefix_lens,
            )

    def _topology_shards(self) -> int:
        return self.dedup.n_shards

    # -- checkpoint ------------------------------------------------------
    def _save_full(self, path: str, knobs, compacting: bool = False) -> None:
        import jax.numpy as jnp

        from ct_mapreduce_tpu.ops import buckettable, hashtable

        # Gather the sharded table to host once, reuse the parent
        # format (the state type must match the dedup's layout so the
        # codec writes the right positional keys/meta + layout +
        # n_shards fields). Only full (ck01 / CTMRCK02 base) saves
        # gather — a delta segment's rows come from the fold-time
        # dirty log, which is the whole point of the format.
        state_cls = (buckettable.BucketTable
                     if self.dedup.layout == "bucket"
                     else hashtable.TableState)
        self.table = state_cls(
            rows=jnp.asarray(np.asarray(self.dedup.rows)),
            count=jnp.asarray(np.asarray(self.dedup.count)),
        )
        try:
            super()._save_full(path, knobs, compacting=compacting)
        finally:
            self.table = None

    def _restore_table(self, keys, meta, count, layout: str,
                       ckpt_shards: int) -> None:
        # Restore by REINSERTION, not raw row copy: a checkpoint may come
        # from a different topology (single chip, another mesh size) or
        # layout, and a key's home shard, bucket, and probe sequence all
        # depend on both — only re-hashing every occupied row is always
        # correct. (A same-topology fast path could raw-copy, but
        # restores are rare and reinsertion keeps one code path.)
        occ = keys.any(axis=-1)
        ckpt_cap = int(keys.shape[0])
        target_cap = max(self.dedup.capacity, ckpt_cap)
        self.dedup = ShardedDedup(
            self.mesh,
            capacity=self._mesh_capacity(target_cap),
            base_hour=self.base_hour,
            max_probes=self.max_probes,
            dispatch_factor=self.dedup.dispatch_factor,
        )
        overflow = self.dedup.bulk_insert_np(keys[occ], meta[occ])
        if overflow:
            raise RuntimeError(
                f"checkpoint restore overflowed {overflow} rows; "
                f"increase tableBits (capacity {self.dedup.capacity})"
            )
        self.capacity = self.dedup.capacity
        self.table = None

    def _mesh_capacity(self, capacity: int) -> int:
        """Round capacity so each shard gets a power-of-two slice."""
        from ct_mapreduce_tpu.agg.sharded import mesh_capacity

        return mesh_capacity(self.mesh.devices.size, capacity)
