"""The on-device reduce state and its exact host fallback lane.

``TpuAggregator`` replaces the Redis-resident reduce state of the
reference (serial dedup sets, per-issuer CRL/DN sets,
/root/reference/storage/knowncertificates.go,
/root/reference/storage/issuermetadata.go) with:

- an HBM-resident dedup hash table (:mod:`ct_mapreduce_tpu.ops.hashtable`)
  driven by the fused ingest step (:mod:`ct_mapreduce_tpu.ops.pipeline`),
- a host-side issuer registry mapping SHA-256(SPKI) identities to the
  dense indices the device ops use,
- host-side CRL/DN string sets (tiny, string-typed — SURVEY.md §7
  layer 3 keeps them off-device), fed by device-extracted byte windows
  so the host never re-parses a certificate it has seen the shape of,
- an **exact host lane** for every lane the device flags
  (parse failure / oversized serial / meta range / probe overflow),
  preserving the reference's per-entry tolerance contract
  (/root/reference/cmd/ct-fetch/ct-fetch.go:206-225).

Determinism note: a certificate either always takes the device path or
always takes the host path for the routing predicates that are
functions of the cert alone. Probe overflow is the exception — an
overflowed key spills to the host lane, and after a grow-and-rehash
(load-factor policy) the same key may later insert on device — so the
two dedup domains can OVERLAP. Exactness rests on the cross-domain
guards: the host lane probes device membership before counting
(`_device_known_flags`), the device lane checks the host sets on
unknown lanes (cross-encoding guard in `_consume_out`), and `drain()`
subtracts the host∩device overlap in one batched probe.

``drain()`` reconstructs exactly what ``storage-statistics`` prints
(/root/reference/cmd/storage-statistics/storage-statistics.go:28-99):
per-(issuer, expDate) serial counts from the table's meta words plus
the host sets, and per-issuer CRL/DN sets.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from ct_mapreduce_tpu.agg import ckpt
from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.core.types import ExpDate, Issuer
from ct_mapreduce_tpu.filter.cache import content_token, serial_hash
from ct_mapreduce_tpu.filter.spill import SpillCaptureRing
from ct_mapreduce_tpu.ops import buckettable, der_kernel, hashtable, pipeline
from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import incr_counter, set_gauge


# Layout selection lives beside the insert dispatch (CTMR_TABLE,
# default bucket); re-exported here for the aggregator's callers.
_table_layout = pipeline.table_layout


class IssuerRegistry:
    """Dense issuer indexing for device ops.

    Maps issuer certificates (by raw DER, cached) to small integer
    indices; index → :class:`Issuer` (base64url(SHA-256(SPKI)),
    /root/reference/storage/types.go:104-141) for drains and reports.
    """

    def __init__(self) -> None:
        self._by_der: dict[bytes, int] = {}
        self._by_issuer_id: dict[str, int] = {}
        self._issuers: list[Issuer] = []
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._issuers)

    def get_or_assign(self, issuer_der: bytes) -> int:
        with self._lock:
            idx = self._by_der.get(issuer_der)
            if idx is not None:
                return idx
            fields = hostder.parse_cert(issuer_der)
            issuer = Issuer.from_spki(fields.spki)
            iid = issuer.id()
            idx = self._by_issuer_id.get(iid)
            if idx is None:
                # Indices are unbounded: only the DEVICE meta word packs
                # the issuer in META_ISSUER_BITS, and the pipeline's
                # idx_ok gate (ops/pipeline.py) already routes lanes
                # with idx >= MAX_ISSUERS to the exact host lane, which
                # keys by plain ints — so a full-log replay that blows
                # past 16,384 issuers degrades to host-exact counting
                # for the excess issuers instead of crashing ingest.
                idx = len(self._issuers)
                self._issuers.append(issuer)
                self._by_issuer_id[iid] = idx
            self._by_der[issuer_der] = idx
            return idx

    def assign_issuer(self, issuer: Issuer) -> int:
        """Index for an already-constructed :class:`Issuer` identity
        (no DER in hand — e.g. folding another worker's checkpointed
        registry into a merged view)."""
        with self._lock:
            iid = issuer.id()
            idx = self._by_issuer_id.get(iid)
            if idx is None:
                idx = len(self._issuers)
                self._issuers.append(issuer)
                self._by_issuer_id[iid] = idx
            return idx

    def index_of_issuer_id(self, issuer_id: str) -> Optional[int]:
        return self._by_issuer_id.get(issuer_id)

    def ids_from(self, start: int) -> list[str]:
        """Issuer-id strings for indices >= ``start``, in index order —
        the registry's append-only suffix since a shadow length was
        taken (CTMRCK02 segment diffs)."""
        with self._lock:
            return [iss.id() for iss in self._issuers[start:]]

    def issuer_at(self, idx: int) -> Issuer:
        return self._issuers[idx]

    def to_json(self) -> str:
        return json.dumps([iss.id() for iss in self._issuers])

    @classmethod
    def from_json(cls, raw: str) -> "IssuerRegistry":
        reg = cls()
        for iid in json.loads(raw):
            idx = len(reg._issuers)
            reg._issuers.append(Issuer.from_string(iid))
            reg._by_issuer_id[iid] = idx
        return reg


@dataclass
class IngestResult:
    """Per-batch outcome, aligned with the input entry order."""

    was_unknown: np.ndarray  # bool[n]
    filtered: np.ndarray  # bool[n] — CA / expired / CN filter
    exp_hours: np.ndarray  # int32[n] (0 where filtered/unparseable)
    serials: list[Optional[bytes]]  # raw serial bytes per entry
    issuer_idx: np.ndarray  # int32[n]
    host_lane_count: int = 0


class PendingIngest:
    """The async half of :meth:`TpuAggregator.ingest_packed`.

    Device work for every chunk has been DISPATCHED (JAX dispatch is
    asynchronous; the steps chain in submission order on the donated
    table state), but no result has been read back. ``complete()``
    performs the D2H reads and the exact host-lane work and returns the
    :class:`IngestResult`.

    This is the TPU analog of the reference's download→store pipeline
    overlap (goroutines + a 16,384-slot channel,
    /root/reference/cmd/ct-fetch/ct-fetch.go:132,398-488): while the
    device chews on batch N, the host decodes and packs batch N+1
    instead of blocking on N's readback.
    """

    def __init__(self, agg: "TpuAggregator", chunks, res: IngestResult,
                 data: np.ndarray, length: np.ndarray) -> None:
        self._agg = agg
        self._chunks = chunks  # [(batch, device_pos, lane_of, out)]
        self._res = res
        self._data = data
        self._length = length
        self._done = False
        # Overlapped ingest completes pendings from a drain consumer
        # thread while checkpoint/grow paths may concurrently call
        # complete_outstanding from the submit thread; the per-pending
        # lock makes the race a cheap no-op for whoever loses it.
        self._lock = threading.Lock()

    def complete(self) -> IngestResult:
        with self._lock:
            if self._done:
                return self._res
            # Claimed BEFORE the fold (matching the pre-overlap
            # semantics): a fold that raises must not be retried by a
            # later completer — a partial fold re-applied would
            # double-count.
            self._done = True
            agg = self._agg
            # All host-state fold-ins serialize on the aggregator-wide
            # fold lock (metrics, issuer_totals, host_serials, and the
            # cross-encoding guard are shared mutable state). FIFO order
            # is preserved because every completer — the drain consumer
            # and complete_outstanding alike — takes the OLDEST pending
            # first and blocks on its per-pending lock.
            with trace.span("device.fold", cat="device"), agg._fold_lock:
                with contextlib.suppress(ValueError):
                    agg._outstanding.remove(self)
                agg._inflight_lanes = max(
                    0, agg._inflight_lanes - len(self._res.was_unknown))
                res = self._res
                host_lane_total = 0
                for batch, device_pos, lane_of, out in self._chunks:
                    host_pos = agg._consume_out(batch, out, device_pos, res,
                                                lane_of, host_rows=self._data)
                    host_lane_total += agg._host_lanes(
                        host_pos,
                        lambda pos: self._data[
                            pos, : self._length[pos]].tobytes(),
                        res,
                    )
                agg.metrics["host_lane"] += host_lane_total
                res.host_lane_count = host_lane_total
                incr_counter("aggregator", "batches")
            return self._res


@dataclass
class _PreparsedPlan:
    """Host-evaluated routing for one pre-parsed submit: every filter
    and device-exactness predicate of ``pipeline.local_lanes``,
    computed from the sidecar with mirrored arithmetic. The device sees
    only ``insertable``; everything else folds host-side at complete()
    time without any per-lane D2H."""

    sidecar: object  # leafpack.Sidecar
    issuer_idx: np.ndarray  # int32[n]
    valid: np.ndarray  # bool[n]
    f_ca: np.ndarray  # bool[n]
    f_expired: np.ndarray
    f_cn: np.ndarray
    passed: np.ndarray
    insertable: np.ndarray
    static_host_lane: np.ndarray  # host-lane lanes known before insert
    serial_bytes: np.ndarray  # uint8[n, MAX_SERIAL_BYTES]
    host_rows: np.ndarray  # uint8[n, pad]
    length: np.ndarray  # int32[n]
    n: int
    chunk: int  # device chunk width (batch_size)
    flag_cap: int


class PendingPreparsed:
    """Async half of :meth:`TpuAggregator.ingest_preparsed_submit` —
    the pre-parsed lane's :class:`PendingIngest`: same FIFO /
    claim-before-fold / fold-lock contract, but the readback is the
    step's single packed array (plus the overflow-bitmask fallback on
    a compacted-flag spill) instead of twelve per-lane buffers."""

    def __init__(self, agg: "TpuAggregator", out, plan: _PreparsedPlan,
                 res: IngestResult) -> None:
        self._agg = agg
        self._out = out  # pipeline.PreparsedStepOut
        self._plan = plan
        self._res = res
        self._done = False
        self._lock = threading.Lock()

    def complete(self) -> IngestResult:
        with self._lock:
            if self._done:
                return self._res
            self._done = True
            agg = self._agg
            with trace.span("device.fold", cat="device"), agg._fold_lock:
                with contextlib.suppress(ValueError):
                    agg._outstanding.remove(self)
                agg._inflight_lanes = max(
                    0, agg._inflight_lanes - len(self._res.was_unknown))
                agg._fold_preparsed(self._out, self._plan, self._res)
                incr_counter("aggregator", "batches")
            return self._res


class _NpStagedChunkOut:
    """One chunk's slice of a staged envelope readback, shaped like a
    host-resident :class:`~ct_mapreduce_tpu.ops.pipeline.StepOut` so
    ``_consume_out``'s NumPy branch folds it through the exact same
    code path as the serial step (parity by construction). ``packed``
    is one ``int32[7, B]`` row of the envelope's ``[K, 7, B]`` packed
    readback — the bit layout of ``_pack_out``, assembled on device by
    ``pipeline.pack_lane_words``."""

    def __init__(self, packed_row: np.ndarray, serials: np.ndarray,
                 issuer_unknown_counts: np.ndarray) -> None:
        flags = packed_row[0]
        self.host_lane = (flags & 1) != 0
        self.was_unknown = ((flags >> 1) & 1) != 0
        self.filtered_ca = ((flags >> 2) & 1) != 0
        self.filtered_expired = ((flags >> 3) & 1) != 0
        self.filtered_cn = ((flags >> 4) & 1) != 0
        self.probe_overflow = ((flags >> 5) & 1) != 0
        self.not_after_hour = packed_row[1]
        self.serial_len = packed_row[2]
        self.crldp_off = packed_row[3]
        self.crldp_len = packed_row[4]
        self.issuer_name_off = packed_row[5]
        self.issuer_name_len = packed_row[6]
        self.serials = serials
        self.issuer_unknown_counts = issuer_unknown_counts


class PendingStaged:
    """Async half of :meth:`TpuAggregator.ingest_staged_submit` — one
    K-chunk walker envelope. Same FIFO / claim-before-fold / fold-lock
    contract as :class:`PendingIngest`, but the readback is the
    envelope's ONE packed ``[K, 7, B]`` array (+ the summed issuer
    counts, + the serial matrix only when the sink keeps PEMs) instead
    of a packing jit + readback per chunk — and the fold then walks the
    K chunks through the very same ``_consume_out``/``_host_lanes``
    code the serial path uses."""

    def __init__(self, agg: "TpuAggregator", out, chunks,
                 res: IngestResult, chunk_width: int) -> None:
        self._agg = agg
        self._out = out  # pipeline.StagedStepOut
        self._chunks = chunks  # [(batch, device_pos, lane_of)]
        self._res = res
        self._chunk_width = int(chunk_width)  # pos = k * width + lane
        self._done = False
        self._lock = threading.Lock()

    def complete(self) -> IngestResult:
        with self._lock:
            if self._done:
                return self._res
            self._done = True
            agg = self._agg
            with trace.span("device.fold", cat="device"), agg._fold_lock:
                with contextlib.suppress(ValueError):
                    agg._outstanding.remove(self)
                agg._inflight_lanes = max(
                    0, agg._inflight_lanes - len(self._res.was_unknown))
                res = self._res
                P = np.asarray(self._out.packed)  # the one packed read
                counts = np.asarray(self._out.issuer_unknown_counts)
                serials = (np.asarray(self._out.serials)
                           if agg.want_serials else None)
                nothing = np.zeros((0,), np.int32)
                host_lane_total = 0
                for k, (batch, device_pos, lane_of) in enumerate(
                        self._chunks):
                    out_k = _NpStagedChunkOut(
                        P[k],
                        serials[k] if serials is not None else P[k, 2:3],
                        # Counts are device-summed across the envelope;
                        # attribute them to the first chunk's fold (the
                        # running totals are order-insensitive sums).
                        counts if k == 0 else nothing,
                    )
                    host_pos = agg._consume_out(
                        batch, out_k, device_pos, res, lane_of)
                    host_lane_total += agg._host_lanes(
                        host_pos,
                        lambda pos, _b=batch, _k=k: _b.data[
                            pos - _k * self._chunk_width,
                            : _b.length[pos - _k * self._chunk_width],
                        ].tobytes(),
                        res,
                    )
                agg.metrics["host_lane"] += host_lane_total
                res.host_lane_count = host_lane_total
                incr_counter("aggregator", "batches")
            return self._res


@dataclass
class AggregateSnapshot:
    """Drained reduce state — the material of storage-statistics."""

    counts: dict[tuple[str, str], int]  # (issuerID, expDateID) → serials
    crls: dict[str, set[str]]  # issuerID → CRL DP URLs
    dns: dict[str, set[str]]  # issuerID → issuer DN strings
    total: int = 0
    # Signature-verification outcomes (round 13): per-issuer embedded-
    # SCT verdict counts. Empty when verifySignatures is off — every
    # pre-round-13 consumer sees byte-identical reports.
    verified: dict[str, int] = field(default_factory=dict)
    failed: dict[str, int] = field(default_factory=dict)

    def issuers(self) -> list[str]:
        out = {iss for iss, _ in self.counts}
        out.update(self.crls)
        out.update(self.dns)
        return sorted(out)


_pack_out_cache: dict = {}


def _pack_out(out):
    """Pack a step's small per-lane outputs into ONE int32[7, B] device
    array (bools as bit flags, the six int fields as rows).

    On the tunneled stack every separate device-buffer read pays its
    own round trip (measured via the e2e budget: ~12 reads per chunk
    made device_wait ~47 us/entry while the step itself costs ~0.2
    us/entry), so the consume path fetches one packed array instead of
    twelve buffers. Cached per output type (StepOut/ShardedStepOut
    carry different flag sets); jit itself caches per shape."""
    import jax
    import jax.numpy as jnp

    key = type(out)
    fn = _pack_out_cache.get(key)
    if fn is None:
        has_dropped = hasattr(out, "dispatch_dropped")

        @jax.jit
        def fn(o):
            flags = (
                o.host_lane.astype(jnp.int32)
                | (o.was_unknown.astype(jnp.int32) << 1)
                | (o.filtered_ca.astype(jnp.int32) << 2)
                | (o.filtered_expired.astype(jnp.int32) << 3)
                | (o.filtered_cn.astype(jnp.int32) << 4)
                | (o.probe_overflow.astype(jnp.int32) << 5)
                | ((o.dispatch_dropped.astype(jnp.int32) << 6)
                   if has_dropped else 0)
            )
            return jnp.stack(
                [flags, o.not_after_hour, o.serial_len,
                 o.crldp_off, o.crldp_len,
                 o.issuer_name_off, o.issuer_name_len], axis=0)

        _pack_out_cache[key] = fn
    return fn(out)


def _reinsert_chunks(table, keys, meta, valid, max_probes: int):
    """All reinsert chunks in ONE jitted execution; overflow count
    accumulates on device and is read back once by the caller."""
    import functools as _functools

    import jax
    import jax.numpy as jnp

    @_functools.partial(jax.jit, static_argnames=("max_probes",),
                        donate_argnums=(0,))
    def run(table, keys, meta, valid, max_probes):
        def body(i, carry):
            table, ovf = carry
            table, _wu, o = pipeline.table_insert(
                table, keys[i], meta[i], valid[i], max_probes=max_probes
            )
            return table, ovf + o.sum(dtype=jnp.int32)

        return jax.lax.fori_loop(
            0, keys.shape[0], body, (table, jnp.int32(0))
        )

    return run(table, keys, meta, valid, max_probes=max_probes)


class TpuAggregator:
    def __init__(
        self,
        capacity: int = 1 << 22,
        batch_size: int = 4096,
        base_hour: int = packing.DEFAULT_BASE_HOUR,
        cn_prefixes: tuple[str, ...] = (),
        max_probes: int = 32,
        now: Optional[datetime] = None,
        grow_at: float = 0.55,
        max_capacity: int = 1 << 28,
    ) -> None:
        # ADVICE r05 grow-livelock fix: round the ceiling DOWN to a
        # capacity the active layout can actually build — bucket
        # layouts only reach 24·2^k slots, open layouts powers of two;
        # neither ever reaches a ragged 2^m+r ceiling — so maybe_grow's
        # at-ceiling guard can fire. Without this, a table at the
        # clamped bucket capacity saw capacity < max_capacity forever
        # and re-ran a full drain+rebuild+reinsert on every batch past
        # the threshold — gaining zero slots each time. Set before the
        # table exists: _make_table clamps its round-up to this ceiling
        # (rows are 512 B/bucket; a silent 2x overshoot would double
        # HBM use).
        self.max_capacity = self._layout_capacity_floor(max_capacity)
        # Serializes host-state fold-ins (PendingIngest.complete /
        # _consume_out / _host_lanes) across threads — the overlapped
        # ingest path completes from a drain consumer thread.
        self._fold_lock = threading.Lock()
        # Guards self.table swaps vs concurrent reads: the donated step
        # invalidates the previous table buffer, so a contains probe or
        # checkpoint read racing a submit would touch a deleted array.
        # Lock order where both are held: _fold_lock, then _table_lock.
        self._table_lock = threading.RLock()
        # Serializes whole checkpoint writes: the fleet cadence thread
        # (ingest/fleet.py epoch ticks) and the run's own save path can
        # both reach save_checkpoint; interleaved writers are each
        # atomic (temp + rename) but doing the drain + serialize work
        # twice concurrently is waste and widens buffer-lifetime
        # exposure for no benefit.
        self._save_lock = threading.Lock()
        self.table = self._make_table(capacity)
        # Bucket tables round capacity up to whole buckets; load-factor
        # arithmetic must use the real slot count.
        self.capacity = getattr(self.table, "capacity", capacity)
        self.batch_size = batch_size
        self.base_hour = base_hour
        self.max_probes = max_probes
        # Load-factor policy: when the (estimated) fill would exceed
        # grow_at × capacity, the table grows-and-rehashes to the next
        # power of two (up to max_capacity; past the cap, probe
        # overflow spills lanes to the exact host lane with the
        # `overflow` metric — counts stay exact either way). grow_at
        # <= 0 disables growth. The default 0.55 sits just below the
        # measured knee of the bucket table's load curve (one v5e,
        # docs/load_sweep_r04_bucket.log: 3.58M entries/s at 25% load,
        # 2.20M at 50%, 0.63M at 75% — past ~55% the Poisson tail of
        # full 24-slot buckets forces hop rounds), so steady state
        # operates in the 27-55% band at 2.2-3.6M/s.
        self.grow_at = grow_at
        # Host-side running fill estimate: device inserts folded in at
        # complete() time, plus lanes currently in flight (upper
        # bound). Exact fill is read from the device only when the
        # estimate trips the threshold.
        self._table_fill = 0
        self._inflight_lanes = 0
        self.registry = IssuerRegistry()
        self._fixed_now = now
        # Host-exact lane state: (issuer_idx, exp_hour) → set of serial bytes.
        self.host_serials: dict[tuple[int, int], set[bytes]] = {}
        # Per-issuer metadata (strings stay host-side).
        self.crl_sets: dict[int, set[str]] = {}
        self.dn_sets: dict[int, set[str]] = {}
        self._crl_raw_seen: set[tuple[int, bytes]] = set()
        self._dn_raw_seen: set[tuple[int, bytes]] = set()
        # Device-side per-issuer unknown totals (running).
        self.issuer_totals = np.zeros((packing.MAX_ISSUERS,), np.int64)
        # Per-issuer embedded-SCT verdict counts (round 13), fed by the
        # verify lane (verify/lane.py) under the fold lock; all-zero
        # (and absent from reports) unless verifySignatures is on.
        self.verify_verified = np.zeros((packing.MAX_ISSUERS,), np.int64)
        self.verify_failed = np.zeros((packing.MAX_ISSUERS,), np.int64)
        # Submitted-but-not-completed pipelined ingests (FIFO).
        self._outstanding: list[PendingIngest] = []
        # False until the first device-step submit: lets the host lane
        # skip cross-domain membership probes entirely for host-only
        # usage (each probe is a device dispatch + synchronous read).
        self._device_written = False
        # Set False by a sink that never materializes PEMs: skips the
        # per-entry serial-bytes construction in `_consume_out`.
        self.want_serials = True
        # Filter capture (round 15): when enabled, every first-seen
        # serial's BYTES are retained per (issuer_idx, exp_hour) so the
        # reduce state can compile crlite-style filter artifacts — the
        # device table keeps only hashed fingerprints, which cannot
        # seed a cross-run-deterministic filter. None = off (default):
        # zero overhead and byte-identical checkpoints.
        self.filter_capture: Optional[dict[tuple[int, int],
                                           set[bytes]]] = None
        # Exact per-group XOR content hashes for the dict capture
        # (CTMRFL02 dirty tracking): maintained incrementally alongside
        # first-seen capture; None when capture is off, the ring owns
        # its own hashes, or exactness was lost (restored snapshot
        # without stored hashes). A missing/None value only costs a
        # token recomputation — never a wrong reuse.
        self.filter_capture_hashes: Optional[dict[tuple[int, int],
                                                  int]] = None
        # Checkpoint-time filter emission (configure_filter_emission):
        # empty path = no artifact written.
        self.emit_filter_path = ""
        self.filter_fp_rate = 0.01
        self.filter_fmt = ""  # "" = resolve_format default
        # Checkpoint-time incremental build cache (CTMRFL02): clean
        # groups' cascades carry over between emissions.
        self._filter_build_cache = None
        self.set_cn_prefixes(cn_prefixes)
        self.metrics: dict[str, int] = {
            "inserted": 0, "known": 0, "filtered_ca": 0, "filtered_expired": 0,
            "filtered_cn": 0, "host_lane": 0, "parse_errors": 0, "overflow": 0,
            "dispatch_spill": 0,
        }
        # Serializes checkpoint-time filter emission, which runs OUTSIDE
        # _save_lock (the checkpoint bytes land atomically before the
        # build starts; a multi-second scaled build must not block the
        # fleet-cadence save fan-out). GroupBuildCache is not
        # thread-safe, so overlapping emissions still serialize here.
        self._emit_lock = threading.Lock()
        # Incremental checkpoints (CTMRCK02, agg/ckpt.py): the per-tick
        # dirty log the fold paths append to under _fold_lock, armed
        # only after a save/load established a durable base at
        # _ckpt_path (non-checkpointing runs record nothing). The save
        # path turns the log into one delta segment; any event that
        # breaks O(churn) replayability (grow/rehash, serial-less
        # folds, a recorded/inserted count mismatch, segment budget)
        # poisons the log and forces the next save to anchor (fresh
        # full base).
        self._ckpt_knobs = None
        self._ckpt_track = False
        self._ckpt_dirty_lost = False
        self._ckpt_rows: list[tuple[int, int, bytes]] = []
        self._ckpt_host_adds: list[tuple[int, int, bytes]] = []
        self._ckpt_row_bytes = 0
        self._ckpt_dev_inserted = 0
        self._ckpt_path = ""
        self._ckpt_base_sha = ""
        self._ckpt_tip_token = ""
        self._ckpt_chain_len = 0
        # Snapshot-diff shadows from the last durable tick for the
        # small O(issuers) structures (registry length, totals/verify
        # vectors, CRL/DN sets).
        self._ckpt_shadow: Optional[dict] = None

    # -- state hooks (overridden by the mesh-sharded subclass) -----------
    def _layout_capacity_floor(self, cap: int) -> int:
        """Largest capacity ≤ ``cap`` the active layout can build.

        Bucket tables hold 24·2^k slots; open-addressed tables any
        power of two (growth doubles from either, so a floored ceiling
        stays exactly reachable). The growth ceiling is rounded THROUGH
        this at construction so ``capacity >= max_capacity`` is
        reachable and the at-ceiling guard can fire."""
        if _table_layout() == "bucket":
            return buckettable.bucket_count(cap, cap) * buckettable.SLOTS
        if cap & (cap - 1):
            cap = 1 << (cap.bit_length() - 1)
        return cap

    def _make_table(self, capacity: int):
        if _table_layout() == "bucket":
            return buckettable.make_table(
                capacity, max_capacity=self.max_capacity)
        return hashtable.make_table(capacity)

    def _topology_shards(self) -> int:
        """How many key-addressed shards this aggregator's table uses.

        Checkpoint slot positions are only meaningful under the
        topology that wrote them (a mesh-sharded writer addresses
        dest * nb_local + local hash); the value is recorded in every
        snapshot so a reader with a different topology re-hashes
        instead of trusting positions."""
        return 1

    def _drain_table(self) -> tuple[np.ndarray, np.ndarray]:
        if isinstance(self.table, buckettable.BucketTable):
            return buckettable.drain_np(self.table)
        return hashtable.drain_np(self.table)

    def _device_contains(self, fps: np.ndarray) -> np.ndarray:
        """bool[n]: are these fingerprints present in the device table?

        Dispatch AND materialization run under the table lock: the
        donated step invalidates the previous table buffer, so a probe
        racing a concurrent submit could read a deleted array.

        Probe batches are padded to the next power of two (min 16) so
        the jitted contains kernel compiles once per log bucket, not
        once per ragged host-lane count — the same log-bounded
        compile-shape rule the sharded dispatch uses (padding lanes'
        results are sliced off; a spurious hit on a zero key costs
        nothing because the lane is discarded)."""
        import jax.numpy as jnp

        n = int(fps.shape[0])
        if n == 0:
            return np.zeros((0,), bool)
        width = max(16, 1 << (n - 1).bit_length())
        if width != n:
            fps = np.pad(np.asarray(fps), ((0, width - n), (0, 0)))
        with self._table_lock:
            if isinstance(self.table, buckettable.BucketTable):
                out = np.asarray(
                    buckettable.contains(self.table, jnp.asarray(fps),
                                         max_probes=self.max_probes),
                )
            else:
                out = np.asarray(
                    hashtable.contains(self.table, jnp.asarray(fps),
                                       max_probes=self.max_probes),
                )
        return out[:n]

    # -- load-factor policy ---------------------------------------------
    def _table_fill_exact(self) -> int:
        """Occupied-slot count, synced from the device."""
        with self._table_lock:
            return int(np.asarray(self.table.count))

    def _rebuild_table(self, new_capacity: int) -> int:
        """Fresh empty table at ``new_capacity``; returns the actual
        capacity (bucket layouts round up to whole buckets,
        mesh-sharded subclasses round to the mesh)."""
        self.table = self._make_table(new_capacity)
        return getattr(self.table, "capacity", new_capacity)

    def _bulk_reinsert(self, keys: np.ndarray, meta: np.ndarray) -> int:
        """Re-hash drained rows into the (fresh) table; returns the
        number of rows that overflowed their probe chains.

        One device EXECUTION for the whole reinsert (fori_loop over
        chunk-shaped inserts) with one readback at the end: on the
        tunneled stack every execution charges ~0.2s on the next D2H
        read, so a per-chunk read loop would add minutes to a large
        grow (BENCHLOG.md platform notes)."""
        import jax.numpy as jnp

        n = len(keys)
        if n == 0:
            return 0
        chunk = min(1 << 16, max(1, n))
        n_chunks = -(-n // chunk)
        pad = n_chunks * chunk - n
        k = np.pad(keys, ((0, pad), (0, 0))).reshape(n_chunks, chunk, 4)
        m = np.pad(meta, (0, pad)).reshape(n_chunks, chunk)
        v = np.pad(np.ones((n,), bool), (0, pad)).reshape(n_chunks, chunk)
        self.table, ovf = _reinsert_chunks(
            self.table, jnp.asarray(k), jnp.asarray(m), jnp.asarray(v),
            max_probes=self.max_probes,
        )
        return int(np.asarray(ovf))

    def _grow_target(self, need: int) -> int:
        target = self.capacity
        while need > self.grow_at * target:
            target *= 2
        return min(target, self.max_capacity)

    def maybe_grow(self, incoming: int = 0) -> None:
        """Grow-and-rehash when the upper-bound fill estimate (folded
        inserts + in-flight lanes + the batch about to be submitted)
        crosses ``grow_at`` × capacity. Cheap host arithmetic on the
        common path; the exact device count is read only when the
        estimate trips."""
        if self.grow_at <= 0 or self.capacity >= self.max_capacity:
            return
        upper = self._table_fill + self._inflight_lanes + incoming
        if upper <= self.grow_at * self.capacity:
            return
        self.complete_outstanding()  # grow must not strand dispatches
        exact = self._table_fill_exact()
        self._table_fill = exact
        target = self._grow_target(exact + incoming)
        if target > self.capacity:
            self.grow(target)

    def _save_table_state(self):
        return self.table

    def _restore_table_state(self, saved) -> None:
        self.table = saved

    def grow(self, new_capacity: int) -> None:
        """Rebuild the table at ``new_capacity`` and re-hash every
        occupied row (key home slots and probe chains depend on
        capacity, so a raw row copy would be wrong — same reasoning as
        the cross-topology checkpoint restore).

        Crash-safe: the old table state is held until the reinsert
        succeeds. A reinsert that probe-overflows (pathological /
        adversarial key cluster) retries at double capacity up to the
        ceiling; if it still overflows, the ORIGINAL state is restored
        and the error raised — a caller that catches and continues
        keeps exact counts either way."""
        self.complete_outstanding()
        t0 = time.perf_counter()
        # Table lock taken only AFTER the completes above: a drain
        # consumer mid-complete holds the fold lock and may probe the
        # table, so grabbing the table lock first would deadlock
        # (fold → table is the global order).
        with self._table_lock:
            keys, meta = self._drain_table()
            old_capacity = self.capacity
            saved = self._save_table_state()
            cap = new_capacity
            while True:
                actual = self._rebuild_table(cap)
                overflow = self._bulk_reinsert(keys, meta)
                if not overflow:
                    break
                if cap >= self.max_capacity:
                    self._restore_table_state(saved)
                    raise RuntimeError(
                        f"table grow overflowed {overflow} rows even at "
                        f"the max capacity {cap}; original table restored "
                        "(pathological key distribution)"
                    )
                cap = min(cap * 2, self.max_capacity)
            self.capacity = actual
        self._table_fill = len(keys)
        # A rehash changes the table's capacity/topology: a delta chain
        # replayed onto the pre-grow base would restore the OLD
        # capacity, diverging from what a full save would record — the
        # next checkpoint must anchor.
        self._ckpt_mark_dirty_lost("table grow")
        incr_counter("aggregator", "table_grow")
        set_gauge("aggregator", "table_load",
                  value=self._table_fill / self.capacity)
        print(
            f"table grown {old_capacity} → {self.capacity} slots "
            f"({len(keys)} rows re-hashed in "
            f"{time.perf_counter() - t0:.2f}s)",
            file=sys.stderr,
        )

    # -- config ----------------------------------------------------------
    def set_cn_prefixes(self, prefixes: tuple[str, ...]) -> None:
        self.cn_prefixes = tuple(prefixes)
        encoded = [p.encode("utf-8") for p in prefixes]
        # Device window sized to the longest prefix, capped at what a
        # single fixed window can serve. Prefixes longer than the cap
        # are compared on their head; head-matching lanes route to the
        # exact host lane (pipeline._cn_prefix_match "undecidable"),
        # so the device never silently decides on a truncated prefix.
        cap = der_kernel.MAX_FIXED_WINDOW_BYTES
        k = max(1, min(cap, max((len(b) for b in encoded), default=1)))
        arr = np.zeros((len(prefixes), k), np.uint8)
        lens = np.zeros((len(prefixes), 2), np.int32)
        for i, b in enumerate(encoded):
            head = b[:k]
            arr[i, : len(head)] = np.frombuffer(head, np.uint8)
            lens[i] = (len(head), len(b))
        self._prefix_arr, self._prefix_lens = arr, lens

    def _now_hour(self) -> int:
        now = self._fixed_now or datetime.now(timezone.utc)
        return int(now.timestamp()) // 3600

    def grow_verify_totals(self, max_idx: int) -> None:
        """Ensure the verify vectors cover issuer index ``max_idx``
        (registry indices are unbounded; only the device meta word caps
        at MAX_ISSUERS — same policy as the issuer_totals growth in
        ``_host_dedup``). Caller holds the fold lock."""
        if max_idx < self.verify_verified.shape[0]:
            return
        size = max(max_idx + 1, 2 * self.verify_verified.shape[0])
        for name in ("verify_verified", "verify_failed"):
            grown = np.zeros((size,), np.int64)
            old = getattr(self, name)
            grown[: old.shape[0]] = old
            setattr(self, name, grown)

    def verify_counts(self) -> dict[str, tuple[int, int]]:
        """issuerID → (verified, failed), nonzero rows only."""
        out: dict[str, tuple[int, int]] = {}
        nz = np.nonzero(self.verify_verified | self.verify_failed)[0]
        for i in nz:
            i = int(i)
            if i < len(self.registry):
                out[self.registry.issuer_at(i).id()] = (
                    int(self.verify_verified[i]),
                    int(self.verify_failed[i]),
                )
        return out

    # -- filter capture (round 15; spill ring round 19) ------------------
    def enable_filter_capture(self, spill_dir: str = "",
                              spill_mem_bytes: int = 0) -> None:
        """Start retaining first-seen serial bytes per (issuer_idx,
        exp_hour) for filter compilation. Seeds from the host-lane
        sets (their bytes survive checkpoints); device-lane serials
        ingested BEFORE enabling are hashes only and cannot be
        recovered — enabling mid-life on a warm table yields a filter
        covering the capture window, and says so once on stderr.
        Forces ``want_serials`` (capture needs the bytes the count-only
        fast path skips).

        With ``spill_dir`` (the ``filterCaptureSpillDir`` directive)
        the capture is a :class:`SpillCaptureRing`: RSS bounded by
        ``spill_mem_bytes``, overflow spilled to durable segment files
        (checkpoint/merge/build surfaces unchanged — the ring's
        ``items()`` is the dict's). An existing dict capture (e.g. a
        restored checkpoint) is folded into the ring."""
        if spill_dir and not isinstance(self.filter_capture,
                                        SpillCaptureRing):
            ring = SpillCaptureRing(spill_dir,
                                    mem_bytes=spill_mem_bytes)
            seed = (self.filter_capture
                    if self.filter_capture is not None
                    else self.host_serials)
            for key, serials in sorted(seed.items()):
                ring.update(key, sorted(serials))
            self.filter_capture = ring
            # The ring owns content-hash tracking from here on.
            self.filter_capture_hashes = None
        if self.filter_capture is None:
            self.filter_capture = {
                key: set(serials)
                for key, serials in self.host_serials.items()
            }
            self.filter_capture_hashes = {
                key: content_token(serials)[1]
                for key, serials in self.filter_capture.items()
            }
            if self._device_written and self._table_fill_exact() > 0:
                print(
                    "filter capture enabled on a warm table: device-lane "
                    "serials ingested before this point are fingerprints "
                    "only and will be missing from emitted filters",
                    file=sys.stderr,
                )
        self.want_serials = True
        # Capture state changed out-of-band of the dirty log (seeding,
        # ring adoption): segments record capture *additions* only, so
        # the next checkpoint must anchor to carry the new baseline.
        self._ckpt_mark_dirty_lost("capture reconfigured")

    def configure_filter_emission(self, path: str,
                                  fp_rate: float = 0.01,
                                  spill_dir: str = "",
                                  spill_mem_bytes: int = 0,
                                  fmt: str = "") -> None:
        """Emit a filter artifact (``path``) on every checkpoint save,
        compiled from the capture at the target FP rate. ``fmt`` picks
        the artifact format ("" → the CTMR_FILTER_FORMAT default)."""
        self.emit_filter_path = path
        if fp_rate > 0:
            self.filter_fp_rate = float(fp_rate)
        self.filter_fmt = fmt or ""
        self.enable_filter_capture(spill_dir=spill_dir,
                                   spill_mem_bytes=spill_mem_bytes)

    def _capture_serial(self, issuer_idx: int, exp_hour: int,
                        serial: bytes) -> None:
        """Record one first-seen serial (fold paths call this under
        the fold lock; set semantics absorb cross-domain repeats)."""
        cap = self.filter_capture
        if cap is None:
            return
        if isinstance(cap, SpillCaptureRing):
            cap.add((issuer_idx, exp_hour), serial)
        else:
            key = (issuer_idx, exp_hour)
            s = cap.setdefault(key, set())
            if serial not in s:
                s.add(serial)
                h = self.filter_capture_hashes
                if h is not None:
                    h[key] = h.get(key, 0) ^ serial_hash(serial)

    def capture_content_hashes(self) -> Optional[dict]:
        """Exact per-(issuer_idx, expHour) XOR content hashes of the
        filter capture, or None when unavailable (capture off, spilled
        ring, or a restored snapshot that predates hash tracking).
        Callers hold the fold lock, as for the capture itself."""
        cap = self.filter_capture
        if cap is None:
            return None
        if isinstance(cap, SpillCaptureRing):
            return cap.content_hashes()
        if self.filter_capture_hashes is None:
            return None
        return dict(self.filter_capture_hashes)

    # -- incremental checkpoints (CTMRCK02, agg/ckpt.py) -----------------
    def configure_checkpointing(self, mode: str = "",
                                max_chain: int = 0,
                                segment_budget_mb: int = 0) -> None:
        """Pin the checkpoint-plane knobs explicitly (the
        ``checkpointMode``/``ckptMaxChain``/``ckptSegmentBudgetMB``
        directives). Unset values fall through the knob ladder
        (CTMR_* env > platformProfile > default), which also applies
        lazily at the first save when this is never called."""
        self._ckpt_knobs = ckpt.resolve_ckpt(
            mode=mode, max_chain=max_chain,
            segment_budget_mb=segment_budget_mb)

    def _ckpt_resolved(self) -> "ckpt.CkptKnobs":
        if self._ckpt_knobs is None:
            self._ckpt_knobs = ckpt.resolve_ckpt()
        return self._ckpt_knobs

    def _ckpt_record_row(self, issuer_idx: int, exp_hour: int,
                         serial: bytes) -> None:
        """Dirty-log one device-table insert (fold paths, under the
        fold lock). No-op until a save/load arms tracking."""
        if not self._ckpt_track or self._ckpt_dirty_lost:
            return
        self._ckpt_rows.append((issuer_idx, exp_hour, serial))
        self._ckpt_note_bytes(len(serial))

    def _ckpt_record_host(self, issuer_idx: int, exp_hour: int,
                          serial: bytes) -> None:
        """Dirty-log one host-lane first-seen serial (under the fold
        lock; _host_dedup already deduplicated it)."""
        if not self._ckpt_track or self._ckpt_dirty_lost:
            return
        self._ckpt_host_adds.append((issuer_idx, exp_hour, serial))
        self._ckpt_note_bytes(len(serial))

    def _ckpt_note_bytes(self, serial_len: int) -> None:
        self._ckpt_row_bytes += serial_len + ckpt.REC.size
        budget = self._ckpt_resolved().segment_budget_mb << 20
        if self._ckpt_row_bytes > budget:
            # A tick whose churn rivals the corpus gains nothing from
            # a delta; cap the log so memory stays bounded.
            self._ckpt_mark_dirty_lost("segment budget exceeded")

    def _ckpt_note_inserted(self, n: int) -> None:
        if self._ckpt_track and not self._ckpt_dirty_lost:
            self._ckpt_dev_inserted += n

    def _ckpt_mark_dirty_lost(self, why: str) -> None:
        """Poison the dirty log: the next save anchors (full base).
        Recording stops and the log drops immediately — correctness
        never depends on a poisoned log's contents."""
        if not self._ckpt_track or self._ckpt_dirty_lost:
            return
        self._ckpt_dirty_lost = True
        self._ckpt_clear_log()
        incr_counter("ckpt", "dirty_lost")
        print(f"checkpoint dirty log dropped ({why}): next save "
              "writes a full base", file=sys.stderr)

    def _ckpt_clear_log(self) -> None:
        self._ckpt_rows = []
        self._ckpt_host_adds = []
        self._ckpt_row_bytes = 0
        self._ckpt_dev_inserted = 0

    def _ckpt_take_shadow(self) -> dict:
        """Copies of the small O(issuers) structures at a durable
        tick, diffed against at the next segment save. Caller holds
        the fold lock (or is otherwise quiesced)."""
        return {
            "registry_len": len(self.registry),
            "issuer_totals": self.issuer_totals.copy(),
            "verify_verified": self.verify_verified.copy(),
            "verify_failed": self.verify_failed.copy(),
            "crl": {i: set(s) for i, s in sorted(self.crl_sets.items())},
            "dn": {i: set(s) for i, s in sorted(self.dn_sets.items())},
        }

    def _ckpt_arm(self, path: str, base_sha: str, tip_token: str,
                  chain_len: int) -> None:
        """Arm dirty tracking against a durable tick at ``path``."""
        self._ckpt_path = path
        self._ckpt_base_sha = base_sha
        self._ckpt_tip_token = tip_token
        self._ckpt_chain_len = chain_len
        self._ckpt_track = True
        self._ckpt_dirty_lost = False
        self._ckpt_clear_log()
        self._ckpt_shadow = self._ckpt_take_shadow()
        set_gauge("ckpt", "chain_length", value=float(chain_len))

    # -- ingest ----------------------------------------------------------
    def ingest(self, entries: list[tuple[bytes, bytes]]) -> IngestResult:
        """Process (leaf_der, issuer_der) pairs; any count, chunked
        internally to the device batch size."""
        n = len(entries)
        res = IngestResult(
            was_unknown=np.zeros((n,), bool),
            filtered=np.zeros((n,), bool),
            exp_hours=np.zeros((n,), np.int32),
            serials=[None] * n,
            issuer_idx=np.zeros((n,), np.int32),
        )
        for i, (_, issuer_der) in enumerate(entries):
            res.issuer_idx[i] = self.registry.get_or_assign(issuer_der)

        max_len = packing.LENGTH_BUCKETS[-1]
        host_lane_total = 0
        for start in range(0, n, self.batch_size):
            chunk = entries[start : start + self.batch_size]
            device_entries, device_pos, host_pos = [], [], []
            for j, (der, _) in enumerate(chunk):
                if len(der) <= max_len:
                    device_entries.append((der, int(res.issuer_idx[start + j])))
                    device_pos.append(start + j)
                else:
                    host_pos.append(start + j)
            if device_entries:
                self.maybe_grow(incoming=len(device_entries))
            # Fold lock taken AFTER maybe_grow: growth completes the
            # outstanding pendings, whose folds need the same lock.
            with self._fold_lock:
                if device_entries:
                    batch = packing.pack_entries(
                        device_entries, batch_size=self.batch_size
                    )
                    host_pos += self._consume_chunk(batch, device_pos, res)
                host_lane_total += self._host_lanes(
                    host_pos, lambda pos: entries[pos][0], res
                )
        self.metrics["host_lane"] += host_lane_total
        res.host_lane_count = host_lane_total
        incr_counter("aggregator", "batches")
        return res

    def ingest_packed(
        self,
        data: np.ndarray,
        length: np.ndarray,
        issuer_idx: np.ndarray,
        valid: np.ndarray,
    ) -> IngestResult:
        """The zero-copy fast path: pre-packed rows (e.g. from the
        native batch decoder) go straight to the device, no per-entry
        Python objects. ``issuer_idx`` are registry indices
        (:meth:`IssuerRegistry.get_or_assign`); invalid lanes are
        ignored. Host-lane fallbacks slice their DER from ``data``.

        Synchronous form: submit + immediate complete. Pipelined
        callers use :meth:`ingest_packed_submit` and defer
        ``complete()`` by ``deviceQueueDepth`` batches."""
        return self.ingest_packed_submit(data, length, issuer_idx,
                                         valid).complete()

    def ingest_packed_submit(
        self,
        data: np.ndarray,
        length: np.ndarray,
        issuer_idx: np.ndarray,
        valid: np.ndarray,
        host_data: Optional[np.ndarray] = None,
    ) -> PendingIngest:
        """Dispatch the device steps for a packed batch WITHOUT reading
        anything back. Returns a :class:`PendingIngest`; until its
        ``complete()`` runs, the device computes while the host is free
        to decode/pack the next batch (SURVEY §2.2 PP row).

        ``data`` may be a device array whose H2D transfer the caller
        already started (overlap with the previous step); pass the
        NumPy rows as ``host_data`` then, so rare host-lane fallbacks
        slice DER bytes without a per-entry D2H read."""
        n = int(data.shape[0])
        if host_data is None:
            host_data = data if isinstance(data, np.ndarray) else None
        if host_data is None:
            raise ValueError(
                "host_data is required when data is a device array"
            )
        self.maybe_grow(incoming=n)
        self._inflight_lanes += n
        res = IngestResult(
            was_unknown=np.zeros((n,), bool),
            filtered=np.zeros((n,), bool),
            exp_hours=np.zeros((n,), np.int32),
            serials=[None] * n,
            issuer_idx=np.asarray(issuer_idx, np.int32).copy(),
        )
        chunks = []
        for start in range(0, n, self.batch_size):
            end = min(start + self.batch_size, n)
            m = end - start
            if m == self.batch_size:
                batch = packing.PackedBatch(
                    data[start:end], length[start:end],
                    res.issuer_idx[start:end], valid[start:end],
                )
            else:  # pad the tail chunk to the compiled batch shape
                b = self.batch_size
                pdata = np.zeros((b, data.shape[1]), np.uint8)
                pdata[:m] = data[start:end]
                plen = np.zeros((b,), np.int32)
                plen[:m] = length[start:end]
                pidx = np.zeros((b,), np.int32)
                pidx[:m] = res.issuer_idx[start:end]
                pval = np.zeros((b,), bool)
                pval[:m] = valid[start:end]
                batch = packing.PackedBatch(pdata, plen, pidx, pval)
            device_pos = [start + j for j in range(m) if valid[start + j]]
            # lanes in the packed batch correspond 1:1 with positions
            # only when every lane is valid; map explicitly otherwise.
            if len(device_pos) != m:
                lane_of_pos = {start + j: j for j in range(m)}
                lane_of = lambda pos, _m=lane_of_pos: _m[pos]  # noqa: E731
            else:
                lane_of = None
            out = self._device_step_packed(batch)  # async dispatch
            chunks.append((batch, device_pos, lane_of, out))
        pending = PendingIngest(self, chunks, res, host_data, length)
        self._outstanding.append(pending)
        return pending

    def complete_outstanding(self) -> None:
        """Fold every un-completed submit into host state (FIFO). Any
        reader of aggregate state (drain, checkpoint) calls this first
        so pipelining can never lose in-flight results. Robust to a
        drain consumer thread completing (and removing) entries
        concurrently — whoever loses the per-pending race no-ops."""
        while True:
            try:
                pending = self._outstanding[0]
            except IndexError:
                return
            pending.complete()

    # -- staged device queue (K-chunk walker envelope) -------------------
    # True when the staged lane wants its row buffers shipped to the
    # device ahead of the dispatch (the sink's staging ring device_puts
    # the stacked [K, B, L] buffer at submit time so the transfer
    # overlaps the previous envelope's compute). The mesh-sharded
    # subclass routes rows host-side and overrides this to False.
    staged_h2d = True

    def ingest_staged_submit(
        self,
        data,  # uint8[K, B, L] — device array (H2D enqueued) or np
        length: np.ndarray,  # int32[K, B]
        issuer_idx: np.ndarray,  # int32[K, B]
        valid: np.ndarray,  # bool[K, B]
        host_chunks: list[np.ndarray],  # per REAL chunk: uint8[n_k, L]
    ) -> "PendingStaged":
        """Dispatch ONE resident K-chunk walker envelope
        (:func:`ct_mapreduce_tpu.ops.pipeline.staged_core`) without
        reading anything back. Chunk ``k``'s lanes land at result
        positions ``k * B + lane``; chunks past ``len(host_chunks)``
        are all-invalid padding (the staging ring flushed early).
        ``host_chunks`` keeps the caller's own host-resident rows alive
        for host-lane slices and PEM folds — the device buffer may be
        donated and the staging buffer recycled, so neither is read
        after this call."""
        k_chunks, b = length.shape
        n = k_chunks * b
        valid = np.asarray(valid, bool)
        length = np.asarray(length, np.int32)
        issuer_idx = np.asarray(issuer_idx, np.int32)
        # Growth estimate counts the REAL chunks' lanes, not the
        # all-invalid K-axis padding of a partial ring — a tail flush
        # claiming K×B incoming lanes grew tables 4× early.
        self.maybe_grow(incoming=sum(
            int(c.shape[0]) for c in host_chunks))
        self._inflight_lanes += n
        res = IngestResult(
            was_unknown=np.zeros((n,), bool),
            filtered=np.zeros((n,), bool),
            exp_hours=np.zeros((n,), np.int32),
            serials=[None] * n,
            issuer_idx=issuer_idx.reshape(n).copy(),
        )
        chunks = []
        for k, rows in enumerate(host_chunks):
            n_k = int(rows.shape[0])
            batch = packing.PackedBatch(
                rows, length[k, :n_k], issuer_idx[k, :n_k], valid[k, :n_k]
            )
            lanes = np.nonzero(valid[k])[0]
            device_pos = [k * b + int(j) for j in lanes]
            if len(device_pos) == b:
                lane_of = None  # contiguous full chunk: lane == index
            else:
                lane_of = lambda pos, _k=k, _b=b: pos - _k * _b  # noqa: E731
            chunks.append((batch, device_pos, lane_of))
        out = self._device_step_staged(data, length, issuer_idx, valid)
        pending = PendingStaged(self, out, chunks, res, chunk_width=b)
        self._outstanding.append(pending)
        return pending

    def _device_step_staged(self, data, length, issuer_idx, valid):
        self._device_written = True
        import jax

        # Donation picks by residency and backend exactly like the
        # walker pair: device-resident rows (the staging ring enqueued
        # their H2D) donate through the envelope so XLA recycles the
        # buffer HBM; NumPy rows and the CPU backend (whose XLA can't
        # alias these layouts and warns per dispatch) stay undonated.
        step = (pipeline.ingest_step_staged_donated
                if isinstance(data, jax.Array)
                and jax.default_backend() != "cpu"
                else pipeline.ingest_step_staged)
        with trace.span("device.step_staged", cat="device",
                        chunks=int(length.shape[0])), self._table_lock:
            self.table, out = step(
                self.table, data, length, issuer_idx, valid,
                np.int32(self._now_hour()), np.int32(self.base_hour),
                self._prefix_arr, self._prefix_lens,
                max_probes=self.max_probes,
            )
        return out

    # -- pre-parsed ingest lane ------------------------------------------
    def ingest_preparsed(self, sidecar, issuer_idx, valid, host_rows,
                         length) -> IngestResult:
        """Synchronous form of the pre-parsed lane: submit + complete."""
        return self.ingest_preparsed_submit(
            sidecar, issuer_idx, valid, host_rows, length).complete()

    def ingest_preparsed_submit(
        self,
        sidecar,
        issuer_idx: np.ndarray,
        valid: np.ndarray,
        host_rows: np.ndarray,
        length: np.ndarray,
    ) -> PendingPreparsed:
        """Dispatch the walker-free device step for host-extracted
        sidecars (:class:`ct_mapreduce_tpu.native.leafpack.Sidecar`).

        Filter and device-exactness predicates are evaluated HERE, with
        arithmetic mirroring ``pipeline.local_lanes`` line for line —
        they are pure functions of the sidecar, so the device step
        collapses to fingerprint + insert + counts on compact inputs
        (no row bytes ship to the device). ``valid`` lanes whose
        sidecar ``ok`` is 0 take the exact host lane here; the
        AggregatorSink instead strips them from ``valid`` and replays
        them through the device-walker path, which keeps the two lanes
        parity-exact on host-lane spill counts too."""
        from ct_mapreduce_tpu.ops.pipeline import N_PREPARSED_FLAG_CAP

        n = int(len(valid))
        valid = np.asarray(valid, bool)
        issuer_idx = np.asarray(issuer_idx, np.int32).copy()
        ok = sidecar.ok.astype(bool) & valid
        nah = sidecar.not_after_hour
        now_hour = np.int32(self._now_hour())

        # Reference filter precedence (pipeline.local_lanes mirror).
        f_ca = ok & sidecar.is_ca.astype(bool)
        f_expired = ok & ~f_ca & (nah < now_hour)
        if self.cn_prefixes:
            cn_hit, cn_undec0 = self._cn_verdict_np(
                host_rows, sidecar.cn_off, sidecar.cn_len)
            cn_undec = ok & ~f_ca & ~f_expired & ~cn_hit & cn_undec0
            f_cn = ok & ~f_ca & ~f_expired & ~cn_hit & ~cn_undec
        else:
            f_cn = cn_undec = np.zeros_like(ok)
        passed = ok & ~f_ca & ~f_expired & ~f_cn

        hour_off = nah.astype(np.int64) - self.base_hour
        meta_ok = (hour_off >= 0) & (hour_off < packing.META_HOUR_SPAN)
        idx_ok = (issuer_idx >= 0) & (issuer_idx < packing.MAX_ISSUERS)
        boundary_hour = nah == now_hour
        fits = sidecar.serial_len <= packing.MAX_SERIAL_BYTES
        device_exact = fits & meta_ok & idx_ok & ~boundary_hour & ~cn_undec
        insertable = passed & device_exact
        static_host_lane = (valid & ~ok) | (passed & ~device_exact)

        # Serial content window, host-gathered (mirrors
        # gather_serials_rows: bytes past serial_len are zero; lanes
        # whose serial exceeds the window are not insertable).
        s = packing.MAX_SERIAL_BYTES
        serial_bytes = np.zeros((n, s), np.uint8)
        if n:
            cols = sidecar.serial_off[:, None].astype(np.int64) + np.arange(s)
            oob = cols >= host_rows.shape[1]
            np.clip(cols, 0, host_rows.shape[1] - 1, out=cols)
            win = host_rows[np.arange(n)[:, None], cols]
            mask = (np.arange(s)[None, :] < sidecar.serial_len[:, None]) & ~oob
            serial_bytes = np.where(mask, win, 0).astype(np.uint8)

        self.maybe_grow(incoming=n)
        self._inflight_lanes += n
        res = IngestResult(
            was_unknown=np.zeros((n,), bool),
            filtered=np.zeros((n,), bool),
            exp_hours=np.zeros((n,), np.int32),
            serials=[None] * n,
            issuer_idx=issuer_idx,
        )

        # Stack into [K, B] resident chunks for the fused dispatch.
        b = min(self.batch_size, max(n, 1))
        k_chunks = max(1, -(-n // b))
        pad = k_chunks * b - n

        def stk(a, dtype):
            a = np.asarray(a, dtype)
            if pad:
                a = np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
            return a.reshape((k_chunks, b) + a.shape[1:])

        flag_cap = min(N_PREPARSED_FLAG_CAP, max(64, b // 64), max(b, 1))
        out = self._device_step_preparsed(
            stk(serial_bytes, np.uint8), stk(sidecar.serial_len, np.int32),
            stk(nah, np.int32), stk(issuer_idx, np.int32),
            stk(insertable, bool), flag_cap,
        )
        plan = _PreparsedPlan(
            sidecar=sidecar, issuer_idx=issuer_idx, valid=valid, f_ca=f_ca,
            f_expired=f_expired, f_cn=f_cn, passed=passed,
            insertable=insertable, static_host_lane=static_host_lane,
            serial_bytes=serial_bytes, host_rows=host_rows,
            length=np.asarray(length, np.int32), n=n, chunk=b,
            flag_cap=flag_cap,
        )
        pending = PendingPreparsed(self, out, plan, res)
        self._outstanding.append(pending)
        return pending

    def _cn_verdict_np(self, rows: np.ndarray, cn_off: np.ndarray,
                       cn_len: np.ndarray):
        """Host mirror of ``pipeline._cn_prefix_match`` — same K-byte
        device window, same truncated-prefix "undecidable" routing, so
        the pre-parsed lane spills exactly the lanes the walker lane
        spills (the host could decide long prefixes exactly, but then
        the two lanes would disagree on host-lane counts)."""
        prefixes, lens = self._prefix_arr, self._prefix_lens
        k = prefixes.shape[1]
        n = rows.shape[0]
        cols = cn_off[:, None].astype(np.int64) + np.arange(k)
        oob = cols >= rows.shape[1]
        np.clip(cols, 0, rows.shape[1] - 1, out=cols)
        window = rows[np.arange(n)[:, None], cols].astype(np.int64)
        inside = (np.arange(k)[None, :] < cn_len[:, None]) & ~oob
        window = np.where(inside, window, 0)
        dev_lens, true_lens = lens[:, 0], lens[:, 1]
        eq = window[:, None, :] == prefixes[None, :, :]
        care = np.arange(k)[None, None, :] < dev_lens[None, :, None]
        full = np.all(eq | ~care, axis=-1)
        truncated = (true_lens > dev_lens)[None, :]
        hit = np.any(
            full & (cn_len[:, None] >= dev_lens[None, :]) & ~truncated,
            axis=-1)
        undec = np.any(
            full & (cn_len[:, None] >= true_lens[None, :]) & truncated,
            axis=-1)
        return hit, undec

    def _device_step_preparsed(self, serials, serial_len, nah, issuer_idx,
                               insertable, flag_cap: int):
        self._device_written = True
        import jax

        step = (pipeline.ingest_step_preparsed
                if jax.default_backend() == "cpu"
                else pipeline.ingest_step_preparsed_donated)
        with trace.span("device.step_preparsed", cat="device"), \
                self._table_lock:
            self.table, out = step(
                self.table, serials, serial_len, nah, issuer_idx,
                insertable, np.int32(self.base_hour),
                max_probes=self.max_probes, flag_cap=flag_cap,
            )
        return out

    def _fold_preparsed(self, out, plan: _PreparsedPlan,
                        res: IngestResult) -> None:
        """Blocking half of the pre-parsed lane: ONE packed D2H read,
        then a host-side fold mirroring ``_consume_out`` semantics.
        Caller holds the fold lock."""
        n, b, cap = plan.n, plan.chunk, plan.flag_cap
        nb = -(-b // 32)
        sc = plan.sidecar
        P = np.asarray(out.packed)  # the one readback
        k_chunks = P.shape[0]
        # Flag-traffic accounting (the smoke gate asserts O(flagged)):
        # the per-chunk scalar counts + compacted overflow ids are the
        # flag bytes; the was-unknown bitmask and issuer-count vectors
        # are data readback, counted separately.
        incr_counter("ingest", "d2h_flag_bytes",
                     value=float(4 * (2 + cap) * k_chunks))
        incr_counter("ingest", "d2h_readback_bytes", value=float(P.nbytes))

        wu = np.zeros((n,), bool)
        ovf = np.zeros((n,), bool)
        dev_inserted = 0
        counts = np.zeros((P.shape[1] - 2 - nb - cap,), np.int64)
        spill_bits = None
        for k in range(k_chunks):
            row = P[k]
            lo, hi = k * b, min((k + 1) * b, n)
            dev_inserted += int(row[0])
            ovf_count = int(row[1])
            bits = row[2:2 + nb].view(np.uint32)
            lanes = (
                (bits[:, None] >> np.arange(32, dtype=np.uint32)) & 1
            ).astype(bool).reshape(-1)[: hi - lo]
            wu[lo:hi] = lanes
            if ovf_count:
                if ovf_count <= cap:
                    ids = row[2 + nb:2 + nb + ovf_count]
                    ids = ids[ids < (hi - lo)]
                    ovf[lo + ids] = True
                else:
                    # Compacted-flag spill: fall back to the full
                    # overflow bitmask (a second, rare readback).
                    if spill_bits is None:
                        spill_bits = np.asarray(out.overflow_bits)
                        incr_counter("ingest", "d2h_flag_bytes",
                                     value=float(spill_bits.nbytes))
                        incr_counter("ingest", "flag_cap_spill")
                    obits = spill_bits[k]
                    ovf[lo:hi] = (
                        (obits[:, None] >> np.arange(32, dtype=np.uint32))
                        & 1
                    ).astype(bool).reshape(-1)[: hi - lo]
            counts += row[2 + nb + cap:].astype(np.int64)

        f_any = plan.f_ca | plan.f_expired | plan.f_cn
        self.metrics["filtered_ca"] += int(plan.f_ca.sum())
        self.metrics["filtered_expired"] += int(plan.f_expired.sum())
        self.metrics["filtered_cn"] += int(plan.f_cn.sum())
        self.metrics["overflow"] += int(ovf.sum())
        self.issuer_totals[: counts.shape[0]] += counts

        hl = plan.static_host_lane | ovf
        keep = plan.passed & ~hl  # == valid & ~hl & ~filtered
        res.filtered[~hl] = f_any[~hl]
        res.exp_hours[keep] = sc.not_after_hour[keep]
        if self.want_serials:
            for p_ in np.nonzero(keep)[0]:
                sb = plan.serial_bytes[
                    p_, : sc.serial_len[p_]].tobytes()
                res.serials[p_] = sb
                if wu[p_]:
                    key = (int(plan.issuer_idx[p_]),
                           int(sc.not_after_hour[p_]))
                    # Dirty-log PRE-guard (see _consume_out): the
                    # device table holds this key either way.
                    self._ckpt_record_row(key[0], key[1], sb)
                    if sb in self.host_serials.get(key, ()):
                        # Cross-encoding guard (see module docstring).
                        wu[p_] = False
                        self.issuer_totals[int(plan.issuer_idx[p_])] -= 1
                    else:
                        res.was_unknown[p_] = True
                        self._capture_serial(key[0], key[1], sb)
        else:
            res.was_unknown[wu] = True
            if dev_inserted:
                self._ckpt_mark_dirty_lost("serial-less fold")
        ksel = np.nonzero(res.was_unknown[:n])[0]
        if ksel.size:
            self._accumulate_metadata_lanes(
                plan.host_rows, ksel, plan.issuer_idx[ksel],
                sc.crldp_off[ksel], sc.crldp_len[ksel],
                sc.issuer_off[ksel], sc.issuer_len[ksel],
            )
        n_valid = int(plan.valid.sum())
        dev_unknown = int(wu.sum())
        dev_known = n_valid - int(hl.sum()) - dev_unknown
        self.metrics["inserted"] += dev_unknown
        self.metrics["known"] += max(dev_known, 0)
        self._table_fill += dev_inserted
        self._ckpt_note_inserted(dev_inserted)
        set_gauge("aggregator", "table_load",
                  value=self._table_fill / self.capacity)

        host_pos = [int(p) for p in np.nonzero(hl)[0]]
        host_lane_total = self._host_lanes(
            host_pos,
            lambda pos: plan.host_rows[
                pos, : plan.length[pos]].tobytes(),
            res,
        )
        self.metrics["host_lane"] += host_lane_total
        res.host_lane_count = host_lane_total

    def _consume_chunk(self, batch, device_pos, res, lane_of=None):
        """Run one packed chunk on device and fold the outputs into
        ``res`` at the global positions ``device_pos``. Returns the
        positions that must take the exact host lane."""
        out = self._device_step_packed(batch)
        return self._consume_out(batch, out, device_pos, res, lane_of)

    def _consume_out(self, batch, out, device_pos, res, lane_of=None,
                     host_rows=None):
        """Read back one chunk's device outputs and fold them into
        ``res``; the blocking half of the step. ``host_rows`` is the
        host-resident copy of the full padded rows (by global
        position): metadata windows slice it instead of pulling the
        device batch back through the tunnel (~0.5 s per 64 MB chunk
        read on this stack)."""
        if isinstance(out.host_lane, np.ndarray):
            # Host-resident outputs (snapshot reader): direct views.
            hl = out.host_lane
            wu = np.array(out.was_unknown)
            nah = np.asarray(out.not_after_hour)
            slen = np.asarray(out.serial_len)
            f_ca = np.asarray(out.filtered_ca)
            f_exp = np.asarray(out.filtered_expired)
            f_cn = np.asarray(out.filtered_cn)
            ovf = np.asarray(out.probe_overflow)
            d = getattr(out, "dispatch_dropped", None)
            dropped = np.asarray(d) if d is not None else None
            dp_off = np.asarray(out.crldp_off)
            dp_len = np.asarray(out.crldp_len)
            in_off = np.asarray(out.issuer_name_off)
            in_len = np.asarray(out.issuer_name_len)
        else:
            # ONE device read for the twelve small fields (each
            # separate buffer read pays its own tunnel round trip —
            # see _pack_out). wu/etc. are fresh arrays, so the
            # cross-encoding guard below may flip lanes freely.
            P = np.asarray(_pack_out(out))
            flags = P[0]
            hl = (flags & 1) != 0
            wu = ((flags >> 1) & 1) != 0
            f_ca = ((flags >> 2) & 1) != 0
            f_exp = ((flags >> 3) & 1) != 0
            f_cn = ((flags >> 4) & 1) != 0
            ovf = ((flags >> 5) & 1) != 0
            dropped = (((flags >> 6) & 1) != 0
                       if hasattr(out, "dispatch_dropped") else None)
            nah, slen = P[1], P[2]
            dp_off, dp_len, in_off, in_len = P[3], P[4], P[5], P[6]
        f_any = f_ca | f_exp | f_cn
        self.metrics["filtered_ca"] += int(f_ca.sum())
        self.metrics["filtered_expired"] += int(f_exp.sum())
        self.metrics["filtered_cn"] += int(f_cn.sum())
        if dropped is not None:  # sharded path: routing-cap spill rate
            self.metrics["dispatch_spill"] += int(dropped.sum())
        self.metrics["overflow"] += int(ovf.sum())
        # Device counts are MAX_ISSUERS-long; the host array may have
        # grown past that for registry-overflow issuers (host-lane-only).
        counts = np.asarray(out.issuer_unknown_counts, np.int64)
        self.issuer_totals[: counts.shape[0]] += counts

        # Vectorized fold-in (the per-entry Python loop here was the e2e
        # ingest bottleneck): positions and lanes as index arrays, with
        # per-entry Python only where bytes objects are genuinely needed
        # (serial materialization for PEM trees / the cross-encoding
        # guard — skipped entirely for count-only sinks).
        # True table-fill delta: captured BEFORE the cross-encoding
        # guard below flips any was_unknown lane for reporting — the
        # device inserted those keys regardless, and the load-factor
        # estimate must track slots, not report semantics.
        dev_inserted = int(wu.sum())
        n = len(device_pos)
        pos_arr = np.asarray(device_pos, dtype=np.int64).reshape(n)
        if lane_of is None:
            lanes = np.arange(n, dtype=np.int64)
        else:
            lanes = np.array([lane_of(p) for p in device_pos], dtype=np.int64)
        hl_l = hl[lanes]
        host_pos = [int(p) for p in pos_arr[hl_l]]
        okm = ~hl_l
        f_l = f_any[lanes]
        res.filtered[pos_arr[okm]] = f_l[okm]
        keep = okm & ~f_l
        kp, kl = pos_arr[keep], lanes[keep]
        res.exp_hours[kp] = nah[kl]
        if self.want_serials:
            sarr = np.asarray(out.serials)  # the one big field, lazily
            for p_, l_ in zip(kp, kl):
                sb = sarr[l_, : slen[l_]].tobytes()
                res.serials[p_] = sb
                if wu[l_]:
                    # Cross-encoding guard (see module docstring).
                    key = (int(batch.issuer_idx[l_]), int(nah[l_]))
                    # Dirty-log the row PRE-guard: the device inserted
                    # this key whether or not the guard flips the
                    # report, and the delta segment mirrors table
                    # slots, not report semantics.
                    self._ckpt_record_row(key[0], key[1], sb)
                    if sb in self.host_serials.get(key, ()):
                        wu[l_] = False
                        # Keep the running per-issuer gauge consistent
                        # with the corrected report.
                        self.issuer_totals[int(batch.issuer_idx[l_])] -= 1
                    else:
                        res.was_unknown[p_] = True
                        self._capture_serial(key[0], key[1], sb)
        else:
            # Count-only sinks stay on the vectorized path permanently:
            # exact totals are guaranteed by drain()'s batched overlap
            # subtraction, so no per-entry guard (or serial bytes) are
            # needed here. was_unknown may over-report on the
            # pathological host-then-device duplicate; counts cannot.
            res.was_unknown[kp[wu[kl]]] = True
            if dev_inserted:
                # No serial bytes → those inserts cannot be dirty-
                # logged; the next checkpoint must anchor.
                self._ckpt_mark_dirty_lost("serial-less fold")
        ksel = np.where(res.was_unknown[pos_arr])[0]
        if ksel.size:
            lanes_arr = np.asarray(lanes)
            if host_rows is not None:
                rows2d = host_rows
                row_sel = pos_arr[ksel]
                issuers = res.issuer_idx[pos_arr[ksel]]
            else:
                rows2d = np.asarray(batch.data)
                row_sel = lanes_arr[ksel]
                issuers = np.asarray(batch.issuer_idx)[lanes_arr[ksel]]
            lsel = lanes_arr[ksel]
            self._accumulate_metadata_lanes(
                rows2d, row_sel, issuers,
                dp_off[lsel], dp_len[lsel], in_off[lsel], in_len[lsel],
            )
        dev_unknown = int(wu.sum())
        dev_known = len(device_pos) - int(hl.sum()) - dev_unknown
        self.metrics["inserted"] += dev_unknown
        self.metrics["known"] += max(dev_known, 0)
        self._table_fill += dev_inserted
        self._ckpt_note_inserted(dev_inserted)
        set_gauge("aggregator", "table_load",
                  value=self._table_fill / self.capacity)
        return host_pos

    def _host_lanes(self, host_pos, der_of, res) -> int:
        """Exact host path for flagged + oversized lanes.

        Two phases so the cross-domain device-membership guard is ONE
        batched ``contains`` probe per chunk (each probe pays the full
        per-execution readback toll on the tunneled stack — per-cert
        probing would erode the pipelining the sink provides)."""
        staged = []  # (pos, fields, eh) — lanes that reached dedup
        for pos in host_pos:
            fields, x = self._host_filter(der_of(pos), int(res.issuer_idx[pos]))
            if fields is None:
                u, f, eh, sb = x
                res.was_unknown[pos], res.filtered[pos] = u, f
                res.exp_hours[pos], res.serials[pos] = eh, sb
            else:
                staged.append((pos, fields, x))
        flags = self._device_known_flags(
            [(int(res.issuer_idx[pos]), eh, fields.serial)
             for pos, fields, eh in staged]
        )
        for (pos, fields, eh), dk in zip(staged, flags):
            u, f, eh2, sb = self._host_dedup(
                fields, int(res.issuer_idx[pos]), eh, device_known=dk
            )
            res.was_unknown[pos], res.filtered[pos] = u, f
            res.exp_hours[pos], res.serials[pos] = eh2, sb
        return len(host_pos)

    def _device_step_packed(self, batch):
        self._device_written = True
        import jax

        # Device-resident rows (the overlapped/pipelined ingest path
        # device_puts them ahead of the dispatch) are donated through
        # the step — the caller keeps a host copy for host-lane slices,
        # so the row buffer is dead weight after this dispatch and XLA
        # may reuse its HBM. NumPy rows keep the non-donating wrapper,
        # as does the CPU backend (its XLA can't alias this layout and
        # warns on every dispatch).
        step = (pipeline.ingest_step_donated
                if isinstance(batch.data, jax.Array)
                and jax.default_backend() != "cpu"
                else pipeline.ingest_step)
        with trace.span("device.step", cat="device"), self._table_lock:
            self.table, out = step(
                self.table,
                batch.data,
                batch.length,
                batch.issuer_idx,
                batch.valid,
                np.int32(self._now_hour()),
                np.int32(self.base_hour),
                self._prefix_arr,
                self._prefix_lens,
                max_probes=self.max_probes,
            )
        return out

    def _accumulate_metadata_lanes(self, rows2d, row_sel, issuers,
                                   dp_off, dp_len, in_off, in_len):
        """CRL/DN accumulation for device-unknown lanes, keyed by raw
        byte windows so each distinct encoding is parsed once.

        All arrays are pre-selected to the was-unknown lanes: ``rows2d``
        is a HOST-resident padded-row matrix, ``row_sel`` the row per
        lane, ``issuers``/offsets/lengths aligned with it. Work is
        reduced to UNIQUE byte windows first (np.unique over the
        extracted windows, C-speed) so per-chunk Python cost is
        O(#distinct issuers/CRL encodings), not O(batch)."""
        if row_sel.size == 0:
            return

        def rep_windows(o, ln):
            """Representative index (into the selection) per unique
            (issuer, window bytes)."""
            width = int(ln.max(initial=0))
            if width == 0:
                return np.zeros((0,), np.int64)
            k = row_sel.shape[0]
            cols = o[:, None] + np.arange(width, dtype=o.dtype)[None, :]
            cols = np.clip(cols, 0, rows2d.shape[1] - 1)
            wins = rows2d[row_sel[:, None], cols]
            wins[np.arange(width)[None, :] >= ln[:, None]] = 0
            # Row-wise unique via a contiguous byte-row void view —
            # ~an order of magnitude cheaper than np.unique(axis=0)'s
            # int64 lexsort at these shapes (measured on the e2e leg).
            tag8 = np.empty((k, width + 6), np.uint8)
            tag8[:, 0:4] = (
                issuers.astype(np.uint32).view(np.uint8).reshape(k, 4))
            tag8[:, 4:6] = ln.astype(np.uint16).view(np.uint8).reshape(k, 2)
            tag8[:, 6:] = wins
            v = np.ascontiguousarray(tag8).view(
                np.dtype((np.void, tag8.shape[1])))
            _, first = np.unique(v.ravel(), return_index=True)
            return first

        for i in rep_windows(in_off, in_len):
            idx = int(issuers[i])
            raw_name = rows2d[
                row_sel[i], in_off[i] : in_off[i] + in_len[i]].tobytes()
            if (idx, raw_name) not in self._dn_raw_seen:
                self._dn_raw_seen.add((idx, raw_name))
                try:
                    rdns, _ = hostder.parse_name(raw_name, 0)
                    dn = hostder.render_dn(rdns)
                    self.dn_sets.setdefault(idx, set()).add(dn)
                except Exception:
                    pass
        for i in rep_windows(dp_off, dp_len):
            if dp_len[i] <= 0:
                continue
            idx = int(issuers[i])
            raw_dp = rows2d[
                row_sel[i], dp_off[i] : dp_off[i] + dp_len[i]].tobytes()
            if (idx, raw_dp) not in self._crl_raw_seen:
                self._crl_raw_seen.add((idx, raw_dp))
                try:
                    urls = hostder._parse_crldp(raw_dp, 0)
                except Exception:
                    urls = []
                self._add_crls(idx, urls)

    def _add_crls(self, issuer_idx: int, urls: list[str]) -> None:
        """http/https only; ldap silently dropped
        (/root/reference/storage/issuermetadata.go:48-73)."""
        for u in urls:
            try:
                parsed = urlparse(u.strip())
            except ValueError:
                continue
            if parsed.scheme in ("http", "https"):
                self.crl_sets.setdefault(issuer_idx, set()).add(parsed.geturl())

    def _host_filter(self, der: bytes, issuer_idx: int):
        """Tolerant host parse + reference filters. Returns
        ``(fields, exp_hour)`` when the lane reaches dedup, else
        ``(None, (was_unknown, filtered, exp_hour, serial))``."""
        try:
            fields = hostder.parse_cert(der)
        except Exception:
            self.metrics["parse_errors"] += 1
            return None, (False, False, 0, None)
        if fields.is_ca:
            self.metrics["filtered_ca"] += 1
            return None, (False, True, 0, None)
        eh = fields.not_after_unix_hour
        # Exact instant compare, like the reference's NotAfter.Before(now)
        # (/root/reference/cmd/ct-fetch/ct-fetch.go:52-55). The device
        # lane handles whole-bucket cases and routes the boundary bucket
        # (expiring this hour) here, so this compare is what decides it.
        now = self._fixed_now or datetime.now(timezone.utc)
        if fields.not_after < now:
            self.metrics["filtered_expired"] += 1
            return None, (False, True, 0, None)
        if self.cn_prefixes and not any(
            fields.issuer_cn.startswith(p) for p in self.cn_prefixes
        ):
            self.metrics["filtered_cn"] += 1
            return None, (False, True, 0, None)
        return fields, eh

    def _device_known_flags(self, items) -> list[bool]:
        """Cross-domain guard, mirror of the device→host check in
        `_consume_out`: a lane can migrate into the host domain over
        time (a cert entering its expiry hour is boundary-routed here;
        a table filling up overflows here), so a serial already counted
        in the DEVICE table must not count again. One batched membership
        probe for the whole chunk, no mutation.

        items: [(issuer_idx, exp_hour, serial_bytes)] → bool per item.
        """
        flags = [False] * len(items)
        if not self._device_written:
            return flags
        cand, fps = [], []
        for j, (issuer_idx, eh, serial) in enumerate(items):
            if (
                len(serial) <= packing.MAX_SERIAL_BYTES
                and 0 <= issuer_idx < packing.MAX_ISSUERS
                and 0 <= eh - self.base_hour < packing.META_HOUR_SPAN
            ):
                cand.append(j)
                fps.append(packing.fingerprint_host(issuer_idx, eh, serial))
        if fps:
            known = self._device_contains(np.array(fps, np.uint32))
            for j, k in zip(cand, known):
                flags[j] = bool(k)
        return flags

    def _host_dedup(self, fields, issuer_idx: int, eh: int,
                    device_known: bool = False):
        """Host-set dedup + metadata accumulation for a filtered lane."""
        key = (issuer_idx, eh)
        bucket = self.host_serials.setdefault(key, set())
        if fields.serial in bucket or device_known:
            self.metrics["known"] += 1
            return False, False, eh, fields.serial
        bucket.add(fields.serial)
        self._ckpt_record_host(issuer_idx, eh, fields.serial)
        self._capture_serial(issuer_idx, eh, fields.serial)
        self.metrics["inserted"] += 1
        if issuer_idx >= self.issuer_totals.shape[0]:
            # Registry-overflow issuers (idx >= MAX_ISSUERS) only ever
            # count here; grow the per-issuer totals to fit them.
            grown = np.zeros(
                (max(issuer_idx + 1, 2 * self.issuer_totals.shape[0]),),
                np.int64)
            grown[: self.issuer_totals.shape[0]] = self.issuer_totals
            self.issuer_totals = grown
        self.issuer_totals[issuer_idx] += 1
        # Metadata for host-lane unknowns.
        self.dn_sets.setdefault(issuer_idx, set()).add(fields.issuer_dn)
        self._add_crls(issuer_idx, fields.crl_distribution_points)
        return True, False, eh, fields.serial

    def _host_exact(self, der: bytes, issuer_idx: int):
        """The exact lane for one cert: filter + batched-of-one guard +
        dedup. Returns (was_unknown, filtered, exp_hour, serial)."""
        fields, x = self._host_filter(der, issuer_idx)
        if fields is None:
            return x
        dk = self._device_known_flags([(issuer_idx, x, fields.serial)])[0]
        return self._host_dedup(fields, issuer_idx, x, device_known=dk)

    # -- drain / report --------------------------------------------------
    def drain(self) -> AggregateSnapshot:
        """Pull device state to host and merge with the host lane —
        the data storage-statistics prints
        (/root/reference/cmd/storage-statistics/storage-statistics.go:28-99)."""
        self.complete_outstanding()
        with self._table_lock:
            _, meta = self._drain_table()
        counts: dict[tuple[str, str], int] = {}
        if meta.size:
            uniq, cnt = np.unique(meta, return_counts=True)
            for m, c in zip(uniq, cnt):
                idx, eh = packing.unpack_meta(int(m), self.base_hour)
                key = self._count_key(idx, eh)
                counts[key] = counts.get(key, 0) + int(c)
        # Host-lane serials that ALSO landed in the device table would
        # double count (host-first-then-device duplicate encodings of
        # one (issuer, serial, expiry) identity — the reference's
        # single SADD set counts once). One batched membership probe
        # finds the overlap; overlapping serials count device-side only.
        items = [
            (idx, eh, sb)
            for (idx, eh), serials in self.host_serials.items()
            for sb in serials
        ]
        overlap: dict[tuple[int, int], int] = {}
        for (idx, eh, _sb), dup in zip(items, self._device_known_flags(items)):
            if dup:
                overlap[(idx, eh)] = overlap.get((idx, eh), 0) + 1
        for (idx, eh), serials in self.host_serials.items():
            n = len(serials) - overlap.get((idx, eh), 0)
            if n <= 0:
                continue
            key = self._count_key(idx, eh)
            counts[key] = counts.get(key, 0) + n
        crls = {
            self.registry.issuer_at(i).id(): set(s) for i, s in self.crl_sets.items()
        }
        dns = {
            self.registry.issuer_at(i).id(): set(s) for i, s in self.dn_sets.items()
        }
        vc = self.verify_counts()
        return AggregateSnapshot(
            counts=counts, crls=crls, dns=dns, total=sum(counts.values()),
            verified={k: v for k, (v, _) in vc.items() if v},
            failed={k: f for k, (_, f) in vc.items() if f},
        )

    def _count_key(self, issuer_idx: int, exp_hour: int) -> tuple[str, str]:
        return (
            self.registry.issuer_at(issuer_idx).id(),
            ExpDate.from_unix_hour(exp_hour).id(),
        )

    # -- checkpoint ------------------------------------------------------
    def save_checkpoint(self, path: str) -> None:
        """Durable aggregate state at ``path``.

        The log cursor itself is checkpointed separately (same contract
        as the reference, /root/reference/storage/types.go:25-42); this
        file makes device state restorable after preemption.

        Two modes (``checkpointMode`` knob, agg/ckpt.py):

        - ``ck01``: every save is the full ``.npz`` snapshot — the
          compatibility path and the restore oracle.
        - ``ck02`` (default): the first save (and any save after the
          dirty log was poisoned, or after ``ckptMaxChain`` segments)
          anchors with a full base; every other epoch tick appends one
          O(churn) CTMRCK02 delta segment and updates the chain
          manifest. Restore replays the chain to the exact state a
          full save would have written.

        Every file lands via temp + fsync + ``os.replace`` so a crash
        mid-write never corrupts the previous durable tick; segments
        land before the manifest that names them, so a torn tick is
        invisible to the loader.
        """
        with self._save_lock:
            self.complete_outstanding()
            knobs = self._ckpt_resolved()
            wrote_segment = False
            compacting = False
            if (knobs.mode == ckpt.MODE_INCREMENTAL and self._ckpt_track
                    and not self._ckpt_dirty_lost
                    and path == self._ckpt_path):
                if self._ckpt_chain_len >= knobs.max_chain:
                    compacting = True  # mandatory anchor
                else:
                    man = self._ckpt_manifest_for_extend(path, knobs)
                    if man is not None:
                        wrote_segment = self._save_segment(path, man)
            if not wrote_segment:
                self._save_full(path, knobs, compacting=compacting)
        # Filter emission runs OUTSIDE the save lock (the checkpoint
        # bytes above are already durable): a multi-second scaled
        # build must not block the fleet-cadence save fan-out or a
        # concurrent checkpoint_now. _emit_lock still serializes
        # overlapping emissions (the build cache is not thread-safe).
        if self.emit_filter_path:
            with self._emit_lock:
                self._emit_filter()

    def _save_full(self, path: str, knobs, compacting: bool = False) -> None:
        """One full ck01 base snapshot (+ fresh manifest in ck02 mode).
        Caller holds the save lock."""
        # Snapshot the host items AND cut the dirty generation under
        # the fold lock: rows folded after this cut stay in the (new)
        # log — they may also land in the .npz below, which is safe
        # because segment replay is insert-if-absent/set-union
        # idempotent; rows folded before the cut are fully inside the
        # .npz. Sorted so host_keys/host_vals land in content order,
        # not fold arrival order (ctmrlint: determinism).
        with self._fold_lock:
            host_items = sorted(
                (idx, eh, b";".join(s.hex().encode()
                                    for s in sorted(serials)))
                for (idx, eh), serials in self.host_serials.items()
            )
            # Arm tracking at the SAME cut: a fold landing during the
            # npz write below records into the (fresh) log, so it is
            # carried by the next segment even when the table readback
            # also caught it — replay is idempotent, omission is not.
            self._ckpt_shadow = self._ckpt_take_shadow()
            self._ckpt_clear_log()
            self._ckpt_track = knobs.mode == ckpt.MODE_INCREMENTAL
            self._ckpt_dirty_lost = False
        directory = os.path.dirname(os.path.abspath(path))
        fd, tmp_path = tempfile.mkstemp(
            prefix=os.path.basename(path) + ".tmp.", dir=directory
        )
        try:
            with os.fdopen(fd, "wb") as fh:
                self._write_npz(fh, host_items)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp_path, path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp_path)
            # Rows folded before the cut above exist nowhere durable
            # now; the next save must anchor, not extend.
            self._ckpt_mark_dirty_lost("base save failed")
            raise
        incr_counter("ckpt", "full_saves")
        if knobs.mode == ckpt.MODE_INCREMENTAL:
            ckpt.kill_point("base-post-rename")
            base_sha = ckpt.file_sha256(path)
            ckpt.write_manifest(path, {
                "format": ckpt.FORMAT,
                "baseSha256": base_sha,
                "maxChain": knobs.max_chain,
                "chain": [],
            })
            ckpt.cleanup_segments(path)
            self._ckpt_path = path
            self._ckpt_base_sha = base_sha
            self._ckpt_tip_token = base_sha
            self._ckpt_chain_len = 0
            set_gauge("ckpt", "chain_length", value=0.0)
            if compacting:
                incr_counter("ckpt", "compactions")
        else:
            # ck01 compatibility mode: a stale manifest from an earlier
            # ck02 run must never pair with this fresh base. The
            # loader's base-hash check already ignores it; the unlink
            # keeps the directory honest.
            with contextlib.suppress(OSError):
                os.unlink(ckpt.manifest_path(path))
            self._ckpt_track = False

    def _ckpt_manifest_for_extend(self, path: str, knobs):
        """The on-disk manifest this save may append to, or None when
        the durable tip is not the one in memory (files moved by
        another process / a ck01-mode save / a fresh path) — the
        caller anchors instead."""
        try:
            man = ckpt.read_manifest(path)
        except ckpt.CkptError:
            return None
        if man is None:
            # A plain ck01 base we ourselves loaded or wrote can grow
            # a chain: synthesize its empty manifest, provided the
            # bytes on disk really are the base we are tracking.
            if self._ckpt_chain_len:
                return None
            if (not os.path.exists(path)
                    or ckpt.file_sha256(path) != self._ckpt_base_sha):
                return None
            return {"format": ckpt.FORMAT,
                    "baseSha256": self._ckpt_base_sha,
                    "maxChain": knobs.max_chain, "chain": []}
        if man.get("baseSha256") != self._ckpt_base_sha:
            return None
        chain = man.get("chain", [])
        try:
            disk_tip = (chain[-1].get("targetSha256") if chain
                        else man.get("baseSha256"))
        except AttributeError:
            return None
        if (disk_tip != self._ckpt_tip_token
                or len(chain) != self._ckpt_chain_len):
            return None
        return man

    def _save_segment(self, path: str, man: dict) -> bool:
        """Append one CTMRCK02 delta segment for this tick and update
        the manifest. Returns False when the dirty log fails its
        self-check (the caller anchors with a full base instead).
        Caller holds the save lock."""
        with self._fold_lock:
            rows = self._ckpt_rows
            host_adds = self._ckpt_host_adds
            if len(rows) != self._ckpt_dev_inserted:
                self._ckpt_mark_dirty_lost(
                    f"recorded {len(rows)} rows, device inserted "
                    f"{self._ckpt_dev_inserted}")
                return False
            blob = self._ckpt_segment_blob(rows, host_adds)
            shadow = self._ckpt_take_shadow()
            self._ckpt_clear_log()
        if not rows and not host_adds and not self._ckpt_blob_nonempty(blob):
            # Nothing churned since the last durable tick: the chain
            # on disk already restores to exactly this state.
            self._ckpt_shadow = shadow
            return True
        seq = self._ckpt_chain_len + 1
        data, header = ckpt.encode_segment(
            seq, self._ckpt_tip_token, rows, host_adds, blob)
        try:
            ckpt.write_segment(path, seq, data)
            man = dict(man)
            man["chain"] = list(man.get("chain", [])) + [{
                "seq": seq,
                "file": os.path.basename(ckpt.segment_path(path, seq)),
                "targetSha256": header["targetSha256"],
                "payloadSha256": header["payloadSha256"],
                "bytes": len(data),
                "rows": len(rows) + len(host_adds),
            }]
            ckpt.write_manifest(path, man)
        except BaseException:
            # The log was already cut; its rows exist nowhere durable
            # if this tick didn't land. Anchor next time.
            self._ckpt_mark_dirty_lost("segment write failed")
            raise
        self._ckpt_tip_token = header["targetSha256"]
        self._ckpt_chain_len = seq
        self._ckpt_shadow = shadow
        incr_counter("ckpt", "segments_written")
        incr_counter("ckpt", "segment_bytes", value=float(len(data)))
        incr_counter("ckpt", "dirty_rows",
                     value=float(len(rows) + len(host_adds)))
        set_gauge("ckpt", "chain_length", value=float(seq))
        return True

    def _ckpt_segment_blob(self, rows, host_adds) -> dict:
        """The non-row diffs of this tick against the last durable
        shadow. Caller holds the fold lock — countAfter must be cut
        at the same instant as the dirty log."""
        sh = self._ckpt_shadow or {
            "registry_len": 0,
            "issuer_totals": np.zeros((0,), np.int64),
            "verify_verified": np.zeros((0,), np.int64),
            "verify_failed": np.zeros((0,), np.int64),
            "crl": {}, "dn": {},
        }

        def vec_diff(cur, old):
            padded = np.zeros((cur.shape[0],), np.int64)
            padded[: old.shape[0]] = old
            nz = np.nonzero(cur != padded)[0]
            return {"len": int(cur.shape[0]),
                    "set": [[int(i), int(cur[i])] for i in nz]}

        def set_adds(cur, old):
            out = []
            for i, s in sorted(cur.items()):
                fresh = s - old.get(i, set())
                if fresh:
                    out.append([int(i), sorted(fresh)])
            return out

        blob = {
            "baseHour": int(self.base_hour),
            "countAfter": int(self._table_fill),
            "registryAdds": self.registry.ids_from(sh["registry_len"]),
            "issuerTotals": vec_diff(self.issuer_totals,
                                     sh["issuer_totals"]),
            "verifyVerified": vec_diff(self.verify_verified,
                                       sh["verify_verified"]),
            "verifyFailed": vec_diff(self.verify_failed,
                                     sh["verify_failed"]),
            "crlAdds": set_adds(self.crl_sets, sh["crl"]),
            "dnAdds": set_adds(self.dn_sets, sh["dn"]),
        }
        tokens = self.capture_content_hashes()
        if tokens is not None:
            dirty = sorted({(int(i), int(e)) for i, e, _ in rows}
                           | {(int(i), int(e)) for i, e, _ in host_adds})
            # Round-20 content tokens for the groups this tick dirtied:
            # a restored run resumes dirty-group filter rebuild (and
            # the replay self-check) from these.
            blob["captureTokens"] = [
                [i, e, format(tokens.get((i, e), 0), "032x")]
                for i, e in dirty]
        return blob

    @staticmethod
    def _ckpt_blob_nonempty(blob: dict) -> bool:
        return bool(blob["registryAdds"] or blob["issuerTotals"]["set"]
                    or blob["verifyVerified"]["set"]
                    or blob["verifyFailed"]["set"]
                    or blob["crlAdds"] or blob["dnAdds"])

    def _chain_insert(self, keys: np.ndarray, meta: np.ndarray) -> int:
        """Insert chain-replayed rows into the CURRENT table. The
        device insert kernels are insert-if-absent with accumulating
        counts, so replay is idempotent against rows the base already
        holds (a fold racing a base save may land in both)."""
        return self._bulk_reinsert(keys, meta)

    def _ckpt_replay_segment(self, header, dev_rows, host_rows,
                             blob) -> None:
        """Apply one decoded delta segment on top of the current
        state (base or earlier segments)."""
        base_hour = int(blob.get("baseHour", self.base_hour))
        if base_hour != self.base_hour:
            raise ckpt.CkptError(
                f"segment baseHour {base_hour} != base {self.base_hour}")
        # Registry first: replayed rows may reference issuers the base
        # predates.
        for iid in blob.get("registryAdds", []):
            self.registry.assign_issuer(Issuer.from_string(iid))
        if dev_rows:
            n = len(dev_rows)
            idx = np.array([r[0] for r in dev_rows], np.int64)
            eh = np.array([r[1] for r in dev_rows], np.int64)
            slen = np.array([len(r[2]) for r in dev_rows], np.int32)
            sarr = np.zeros((n, packing.MAX_SERIAL_BYTES), np.uint8)
            for i, (_, _, sb) in enumerate(dev_rows):
                sarr[i, : len(sb)] = np.frombuffer(sb, np.uint8)
            keys = packing.fingerprints_np(idx, eh, sarr, slen)
            off = eh - self.base_hour
            if (off < 0).any() or (off >= packing.META_HOUR_SPAN).any():
                raise ckpt.CkptError("segment exp hour outside meta span")
            meta = ((idx << packing.META_HOUR_BITS) | off).astype(np.uint32)
            overflow = self._chain_insert(keys, meta)
            if overflow:
                raise ckpt.CkptError(
                    f"segment replay overflowed {overflow} rows "
                    f"(base capacity {self.capacity})")
            self._device_written = True
        for i_, e_, sb in dev_rows:
            self._capture_serial(int(i_), int(e_), sb)
        for i_, e_, sb in host_rows:
            key = (int(i_), int(e_))
            self.host_serials.setdefault(key, set()).add(sb)
            self._capture_serial(key[0], key[1], sb)
        for name, field in (("issuerTotals", "issuer_totals"),
                            ("verifyVerified", "verify_verified"),
                            ("verifyFailed", "verify_failed")):
            self._ckpt_apply_vec(field, blob.get(name))
        for i, urls in blob.get("crlAdds", []):
            self.crl_sets.setdefault(int(i), set()).update(urls)
        for i, names in blob.get("dnAdds", []):
            self.dn_sets.setdefault(int(i), set()).update(names)
        # Self-checks: the replayed table must hold exactly the row
        # count the writer saw at this tick, and the capture groups
        # must hash to the writer's round-20 content tokens.
        self._table_fill = self._table_fill_exact()
        want = blob.get("countAfter")
        if want is not None and int(want) != self._table_fill:
            raise ckpt.CkptError(
                f"segment replay count {self._table_fill} != "
                f"recorded {want}")
        tokens = self.capture_content_hashes()
        if tokens is not None:
            for i, e, hx in blob.get("captureTokens", []):
                got = format(tokens.get((int(i), int(e)), 0), "032x")
                if got != hx:
                    raise ckpt.CkptError(
                        f"capture content token mismatch for group "
                        f"({i}, {e}) after replay")

    def _ckpt_apply_vec(self, field: str, spec) -> None:
        """Apply one {len, set: [[idx, value], ...]} vector diff —
        absolute values at changed indices, so replay in chain order
        converges regardless of how many segments touch an index."""
        if not spec:
            return
        vec = getattr(self, field)
        m = int(spec.get("len", vec.shape[0]))
        if m > vec.shape[0]:
            grown = np.zeros((m,), np.int64)
            grown[: vec.shape[0]] = vec
            vec = grown
        for i, v in spec.get("set", []):
            vec[int(i)] = int(v)
        setattr(self, field, vec)

    def _emit_filter(self) -> None:
        """Checkpoint-time filter emission: compile the capture into
        the versioned artifact (filter/artifact.py) and write it
        atomically next to the snapshot. An emission failure must not
        poison the checkpoint that already landed — it is reported and
        counted, and the next checkpoint retries."""
        from ct_mapreduce_tpu.filter import artifact as fartifact
        from ct_mapreduce_tpu.filter.cache import GroupBuildCache

        try:
            if self._filter_build_cache is None:
                self._filter_build_cache = GroupBuildCache()
            art = fartifact.build_from_aggregator(
                self, fp_rate=self.filter_fp_rate,
                fmt=self.filter_fmt or None,
                cache=self._filter_build_cache)
            fartifact.write_artifact(self.emit_filter_path, art.to_bytes())
        except Exception as err:
            incr_counter("filter", "emit_error")
            print(f"filter emission failed ({self.emit_filter_path}): "
                  f"{type(err).__name__}: {err}", file=sys.stderr)

    def _write_npz(self, fh, host_items) -> None:
        layout = ("bucket" if isinstance(self.table, buckettable.BucketTable)
                  else "open")
        # ONE device fetch for the whole table: the .keys/.meta
        # properties each pull rows through the tunnel (~0.5s per
        # 64 MB D2H), so going through them would double checkpoint
        # readback cost for multi-GB tables. Materialized as a
        # HOST-OWNED copy under the table lock — np.asarray of a
        # CPU-backend jax array is a zero-copy VIEW of the XLA buffer,
        # and the long savez_compressed window below must not read
        # device memory whose lifetime it doesn't own (table swaps and
        # donation policies are backend-dependent); the copy bounds
        # the exposure to a memcpy made while swaps are locked out.
        with self._table_lock:
            rows = np.array(self.table.rows, copy=True)
        if layout == "bucket":
            slots = rows[:, : buckettable.SLOTS * 5].reshape(-1, 5)
        else:
            slots = rows
        extra = {}
        if self.filter_capture is not None:
            # Filter capture rides the checkpoint ONLY when the feature
            # is on (round-15 interplay contract: emitFilter off leaves
            # the .npz byte-identical to pre-round-15 writers). Same
            # hex-joined encoding as the host-lane sets; sorted keys so
            # identical captures serialize identically.
            f_items = sorted(
                (idx, eh, b";".join(s.hex().encode()
                                    for s in sorted(serials)))
                for (idx, eh), serials in self.filter_capture.items()
            )
            extra["filter_keys"] = np.array(
                [(i, e) for i, e, _ in f_items], dtype=np.int64
            ).reshape(-1, 2)
            extra["filter_vals"] = np.array(
                [v for _, _, v in f_items], dtype=object)
            # Exact content hashes ride along when the capture layer
            # has them (row-aligned with filter_keys) so a restored
            # run resumes incremental dirty tracking without an
            # O(capture) rehash. Absent when exactness was lost (e.g.
            # a spilled ring) — restore recomputes instead.
            hashes = self.capture_content_hashes()
            if hashes is not None:
                extra["filter_hashes"] = np.array(
                    [format(hashes.get((i, e), 0), "032x").encode()
                     for i, e, _ in f_items], dtype=object)
        np.savez_compressed(
            fh,
            # (keys, meta, count) stays the cross-version wire format;
            # `layout` records slot positioning (bucket i//SLOTS vs
            # open-addressed chains) and `n_shards` the key-routing
            # topology, so restore rebuilds the same structure — or
            # re-hashes via the reinsertion path (_restore_table /
            # ShardedDedup.bulk_insert_np) when either differs.
            layout=np.array(layout),
            n_shards=np.int64(self._topology_shards()),
            keys=slots[:, :4],
            meta=slots[:, 4],
            count=np.asarray(self.table.count),
            registry=np.frombuffer(
                self.registry.to_json().encode(), dtype=np.uint8
            ),
            base_hour=np.int64(self.base_hour),
            issuer_totals=self.issuer_totals,
            verify_verified=self.verify_verified,
            verify_failed=self.verify_failed,
            host_keys=np.array(
                [(i, e) for i, e, _ in host_items], dtype=np.int64
            ).reshape(-1, 2),
            host_vals=np.array([v for _, _, v in host_items], dtype=object),
            # json.dumps preserves dict insertion order, so the key
            # iteration must be sorted too or the serialized bytes
            # depend on fold arrival order (ctmrlint: determinism).
            crl_sets=np.frombuffer(
                json.dumps(
                    {str(k): sorted(v)
                     for k, v in sorted(self.crl_sets.items())}
                ).encode(),
                dtype=np.uint8,
            ),
            dn_sets=np.frombuffer(
                json.dumps(
                    {str(k): sorted(v)
                     for k, v in sorted(self.dn_sets.items())}
                ).encode(),
                dtype=np.uint8,
            ),
            allow_pickle=True,
            **extra,
        )

    def _asarray(self, arr: np.ndarray):
        """Checkpoint rows → table-state arrays (device put). The
        host-only snapshot reader overrides this to stay in NumPy."""
        import jax.numpy as jnp

        return jnp.asarray(arr)

    def _restore_table(self, keys, meta, count, layout: str,
                       ckpt_shards: int) -> None:
        """Rebuild table state from checkpoint (keys, meta) rows.

        Positions written by a matching topology restore as a raw row
        copy; a snapshot from a different shard count (its slot
        positions encode dest * nb_local + local hash, unreachable by
        this topology's hashes) re-hashes every occupied row through
        the reinsertion path instead — silent positional trust would
        make contains/insert miss those keys and double-count."""
        if ckpt_shards != self._topology_shards():
            occ = keys.any(axis=-1)
            self.capacity = self._rebuild_table(
                max(int(keys.shape[0]), 1))
            overflow = self._bulk_reinsert(keys[occ], meta[occ])
            if overflow:
                raise RuntimeError(
                    f"checkpoint restore overflowed {overflow} rows "
                    f"re-hashing a {ckpt_shards}-shard snapshot; "
                    f"increase tableBits (capacity {self.capacity})"
                )
            self._table_fill = int(occ.sum())
            return
        if layout == "bucket":
            slots = hashtable.fuse_rows(keys, meta)
            nb = slots.shape[0] // buckettable.SLOTS
            rows = np.zeros((nb, buckettable.ROW_WORDS), np.uint32)
            rows[:, : buckettable.SLOTS * 5] = slots.reshape(nb, -1)
            # The device insert trusts the cached fill word; positional
            # snapshots (and pre-round-5 ones especially) don't carry it.
            buckettable.fill_counts_np(rows)
            self.table = buckettable.BucketTable(
                rows=self._asarray(rows), count=self._asarray(count),
            )
            self.capacity = nb * buckettable.SLOTS
        else:
            self.table = hashtable.TableState(
                rows=self._asarray(hashtable.fuse_rows(keys, meta)),
                count=self._asarray(count),
            )
            self.capacity = int(keys.shape[0])

    def load_checkpoint(self, path: str) -> None:
        """Restore from ``path``: the base ``.npz`` plus whatever
        CTMRCK02 delta chain its manifest names. ``resolve_chain``
        hash-validates every link before anything is applied, so a
        torn tick (crash between segment and manifest renames) loads
        as the previous durable state, never a partial one."""
        chain = ckpt.resolve_chain(path)
        self._load_base(path)
        for header, dev_rows, host_rows, blob in chain.segments:
            self._ckpt_replay_segment(header, dev_rows, host_rows, blob)
        if chain.segments:
            incr_counter("ckpt", "restore_segments",
                         value=float(len(chain.segments)))
        self._ckpt_arm(path, chain.base_sha, chain.tip_token,
                       len(chain.segments))

    def _load_base(self, path: str) -> None:
        z = np.load(path, allow_pickle=True)
        # Checkpoint format stays (keys, meta, count) for cross-version
        # stability; `layout` (absent in pre-round-4 snapshots ⇒ open)
        # says how slot positions map back to a table structure, and
        # `n_shards` (absent in pre-round-5 snapshots ⇒ 1) which
        # key-routing topology wrote them. The snapshot's layout wins
        # over CTMR_TABLE: positions are only meaningful in the
        # structure that wrote them.
        layout = str(z["layout"]) if "layout" in z else "open"
        ckpt_shards = int(z["n_shards"]) if "n_shards" in z else 1
        self._restore_table(
            np.asarray(z["keys"]), np.asarray(z["meta"]),
            np.asarray(z["count"]), layout, ckpt_shards,
        )
        self._device_written = bool(np.asarray(z["count"]).sum() > 0)
        self._table_fill = int(np.asarray(z["count"]).sum())
        self._inflight_lanes = 0
        self.base_hour = int(z["base_hour"])
        self.registry = IssuerRegistry.from_json(z["registry"].tobytes().decode())
        self.issuer_totals = z["issuer_totals"].copy()
        # Verify vectors are absent in pre-round-13 snapshots → zeros.
        for name in ("verify_verified", "verify_failed"):
            setattr(self, name,
                    z[name].copy() if name in z
                    else np.zeros((packing.MAX_ISSUERS,), np.int64))
        self.host_serials = {}
        for (idx, eh), blob in zip(z["host_keys"], z["host_vals"]):
            serials = {
                bytes.fromhex(h.decode()) for h in blob.split(b";") if h
            }
            self.host_serials[(int(idx), int(eh))] = serials
        self.crl_sets = {
            int(k): set(v)
            for k, v in json.loads(z["crl_sets"].tobytes().decode()).items()
        }
        self.dn_sets = {
            int(k): set(v)
            for k, v in json.loads(z["dn_sets"].tobytes().decode()).items()
        }
        # Filter capture: absent in pre-round-15 snapshots (and any
        # emitFilter-off writer) → capture stays off; a later
        # enable_filter_capture() re-seeds from the restored host sets.
        self.filter_capture = None
        self.filter_capture_hashes = None
        if "filter_keys" in z:
            cap: dict[tuple[int, int], set[bytes]] = {}
            for (idx, eh), blob in zip(
                    z["filter_keys"].reshape(-1, 2), z["filter_vals"]):
                cap[(int(idx), int(eh))] = {
                    bytes.fromhex(h.decode()) for h in blob.split(b";") if h
                }
            self.filter_capture = cap
            if "filter_hashes" in z:
                self.filter_capture_hashes = {
                    (int(idx), int(eh)): int(hx.decode(), 16)
                    for (idx, eh), hx in zip(
                        z["filter_keys"].reshape(-1, 2),
                        z["filter_hashes"])
                }
            else:
                # Pre-hash snapshot (or a writer whose ring had lost
                # exactness): the restored dict IS the full content,
                # so recomputing here regains exact incremental
                # tracking for the rest of the run.
                self.filter_capture_hashes = {
                    key: content_token(serials)[1]
                    for key, serials in cap.items()
                }
            self.want_serials = True


class HostSnapshotAggregator(TpuAggregator):
    """Read-only snapshot consumer for ``storage-statistics --backend=tpu``.

    The report is pure host work (regroup + count + print,
    /root/reference/cmd/storage-statistics/storage-statistics.go:28-99),
    so this subclass keeps the whole table state in NumPy: constructing
    it never allocates device buffers, and a report can run while the
    TPU pool is unavailable. Drain, regroup, and the host/device
    overlap check share the parent's code paths bit for bit — only the
    array residency hooks change.
    """

    def _make_table(self, capacity: int):
        if _table_layout() == "bucket":
            nb = buckettable.bucket_count(
                capacity, max_capacity=self.max_capacity)
            return buckettable.BucketTable(
                rows=np.zeros((nb, buckettable.ROW_WORDS), np.uint32),
                count=np.zeros((), np.int32),
            )
        if capacity & (capacity - 1):
            raise ValueError(f"capacity must be a power of two, got {capacity}")
        return hashtable.TableState(
            rows=np.zeros((capacity, 5), np.uint32),
            count=np.zeros((), np.int32),
        )

    def _asarray(self, arr: np.ndarray):
        return np.asarray(arr)

    def _bulk_reinsert(self, keys: np.ndarray, meta: np.ndarray) -> int:
        """Host-only reinsertion (topology-mismatched snapshots must
        re-hash; a report process must not claim the device to do so)."""
        if not isinstance(self.table, buckettable.BucketTable):
            raise RuntimeError(
                "host-only restore of a topology-mismatched open-layout "
                "snapshot is not supported; restore through a device "
                "aggregator (TpuAggregator/ShardedAggregator) instead")
        rows = np.asarray(self.table.rows)
        ovf = buckettable.bulk_insert_np(
            rows, keys, meta, max_probes=self.max_probes)
        self.table = buckettable.BucketTable(
            rows=rows, count=np.int32(len(keys) - ovf))
        return ovf

    def _chain_insert(self, keys: np.ndarray, meta: np.ndarray) -> int:
        """Chain replay on a host-resident snapshot. bulk_insert_np is
        blind placement (its contract requires keys NOT already in the
        table), but a fold racing a base save can land a row in both
        the base and the following segment — so pre-filter to the
        genuinely-absent keys and accumulate the count instead of
        resetting it like _bulk_reinsert does."""
        if not isinstance(self.table, buckettable.BucketTable):
            raise RuntimeError(
                "host-only chain replay needs the bucket layout; "
                "restore through TpuAggregator/ShardedAggregator")
        rows = np.asarray(self.table.rows)
        _, first = np.unique(keys, axis=0, return_index=True)
        uniq = np.zeros((keys.shape[0],), bool)
        uniq[first] = True
        fresh = uniq & ~buckettable.contains_np(
            rows, keys, max_probes=self.max_probes)
        ovf = buckettable.bulk_insert_np(
            rows, keys[fresh], meta[fresh], max_probes=self.max_probes)
        self.table = buckettable.BucketTable(
            rows=rows,
            count=np.int32(int(np.asarray(self.table.count))
                           + int(fresh.sum()) - ovf))
        return ovf

    # _drain_table is inherited: both layouts' drain_np helpers are
    # pure NumPy over this subclass's host-resident arrays.

    def _device_contains(self, fps: np.ndarray) -> np.ndarray:
        if isinstance(self.table, buckettable.BucketTable):
            return buckettable.contains_np(
                np.asarray(self.table.rows), fps, max_probes=self.max_probes
            )
        return hashtable.contains_np(
            np.asarray(self.table.rows), fps, max_probes=self.max_probes
        )

    def _device_step_packed(self, batch):
        raise RuntimeError(
            "HostSnapshotAggregator is read-only (reports); "
            "use TpuAggregator/ShardedAggregator to ingest")

    def _device_step_preparsed(self, *args, **kwargs):
        raise RuntimeError(
            "HostSnapshotAggregator is read-only (reports); "
            "use TpuAggregator/ShardedAggregator to ingest")

    def _device_step_staged(self, *args, **kwargs):
        raise RuntimeError(
            "HostSnapshotAggregator is read-only (reports); "
            "use TpuAggregator/ShardedAggregator to ingest")
