"""CTMRCK02 — incremental epoch checkpoints for the aggregation state.

The durability contract (aggregate first, cursor second, resume at
cursor) used to pay O(corpus) per epoch tick: ``save_checkpoint``
re-read the whole device table and re-serialized every host set into a
fresh ``.npz`` even when the tick folded a few thousand entries. This
module is the wire layer of the O(churn) replacement:

- The full ``.npz`` snapshot (``ck01``, agg/aggregator.py::_write_npz)
  stays the **base** format and the restore oracle.
- Each epoch tick appends one self-delimiting **delta segment**
  (``<path>.ckseg-<seq>``) carrying only that tick's churn: the
  device-table rows the fold paths saw insert (the was-unknown
  readback mask), host-lane serial additions, registry/issuer-total/
  verify-counter diffs, and the per-group capture content tokens.
- A JSON **manifest** (``<path>.ckmanifest.json``) names the live
  chain. Like CTMRDL01 links, every segment is hash-chained:
  ``token_0`` is the SHA-256 of the base file's bytes and
  ``token_i = sha256(token_{i-1} + payloadSha_i)``, so a segment can
  never silently replay onto the wrong base or out of order.
- Chains are bounded: after ``ckptMaxChain`` segments the next save is
  a mandatory **anchor** (compaction — fresh base, chain dropped).

Crash ordering (tmp+fsync+rename for every file, segment before
manifest, base before manifest): a SIGKILL at any point leaves either
the previous durable tick (new segment orphaned — ignored, later
overwritten) or the new one. A base file whose hash does not match the
manifest's ``baseSha256`` is NEWER than the manifest (a compaction
died between the base rename and the manifest rename) and is complete
by construction, so the loader uses it alone.

The aggregator owns the dirty log and the replay; this module owns
bytes, hashing, chain validation, and the resolution of what to
replay. Everything here must be a pure function of its inputs — the
module is in the ctmrlint determinism scope.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import struct
import tempfile
import zlib
from typing import Any, NamedTuple, Optional

from ct_mapreduce_tpu.config.profile import (
    Knob,
    pos_int,
    resolve_section,
)

MAGIC = b"CTMRCK02"
FORMAT = "CTMRCK02"
MODE_FULL = "ck01"          # compatibility path: every save is a base
MODE_INCREMENTAL = "ck02"   # base + delta segments (the default)
DEFAULT_MAX_CHAIN = 8
DEFAULT_SEGMENT_BUDGET_MB = 256

# One dirty row: issuer index, expiry hour, serial byte length —
# followed by the serial bytes (the capture spill ring's framing).
REC = struct.Struct("<iqI")
_LEN = struct.Struct("<I")


class CkptError(ValueError):
    """A segment/manifest/chain that cannot be trusted."""


# -- knobs ----------------------------------------------------------------


def _parse_mode(raw: str) -> str:
    return raw.strip().lower()


def _valid_mode(v: Any) -> bool:
    return v in (MODE_FULL, MODE_INCREMENTAL)


_CKPT_KNOBS = (
    Knob(name="checkpointMode", env="CTMR_CHECKPOINT_MODE",
         default=MODE_INCREMENTAL, parse=_parse_mode, is_set=_valid_mode),
    Knob(name="ckptMaxChain", env="CTMR_CKPT_MAX_CHAIN",
         default=DEFAULT_MAX_CHAIN, parse=int, is_set=pos_int),
    Knob(name="ckptSegmentBudgetMB", env="CTMR_CKPT_SEGMENT_BUDGET_MB",
         default=DEFAULT_SEGMENT_BUDGET_MB, parse=int, is_set=pos_int),
)


class CkptKnobs(NamedTuple):
    mode: str
    max_chain: int
    segment_budget_mb: int


def resolve_ckpt(mode: str = "", max_chain: int = 0,
                 segment_budget_mb: int = 0) -> CkptKnobs:
    """The checkpoint plane's knob ladder (explicit > CTMR_* env >
    platformProfile > default). ``mode`` empty / ints <= 0 mean
    "unset" at the explicit layer."""
    r = resolve_section("ckpt", _CKPT_KNOBS, {
        "checkpointMode": _parse_mode(mode) if mode else None,
        "ckptMaxChain": max_chain,
        "ckptSegmentBudgetMB": segment_budget_mb,
    })
    return CkptKnobs(r["checkpointMode"], r["ckptMaxChain"],
                     r["ckptSegmentBudgetMB"])


# -- fault injection (kill-resume tests) ----------------------------------

KILL_ENV = "CTMR_CKPT_KILL"
# Named write points, in write order. "base-*" fire on full/anchor
# saves (compaction included), "seg-*"/"manifest-*" on segment ticks;
# manifest-pre-rename also fires for the fresh manifest a compaction
# writes after its base.
KILL_POINTS = ("seg-pre-rename", "seg-post-rename",
               "base-post-rename", "manifest-pre-rename")

_kill_hits: dict = {}


def kill_point(point: str) -> None:
    """SIGKILL this process when CTMR_CKPT_KILL names this write
    point — the kill-resume tests' way of dying at exactly the
    ordering boundaries the crash proofs argue about. The value is
    either a bare point name (die on the first hit) or "name:N" (die
    on the Nth hit — e.g. "base-post-rename:2" survives the initial
    base save and dies inside the first compaction's anchor write)."""
    spec = os.environ.get(KILL_ENV, "")
    if not spec:
        return
    name, _, nth = spec.partition(":")
    if name != point:
        return
    _kill_hits[name] = _kill_hits.get(name, 0) + 1
    if _kill_hits[name] >= (int(nth) if nth else 1):
        os.kill(os.getpid(), signal.SIGKILL)


# -- paths / hashing ------------------------------------------------------


def manifest_path(path: str) -> str:
    return path + ".ckmanifest.json"


def segment_path(path: str, seq: int) -> str:
    return f"{path}.ckseg-{seq:08d}"


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def chain_token(prev_token: str, payload_sha: str) -> str:
    """token_i from token_{i-1}: binding every segment to its exact
    predecessor (CTMRDL01's baseSha/targetSha discipline)."""
    return hashlib.sha256(
        (prev_token + payload_sha).encode("ascii")).hexdigest()


def _dumps(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


# -- segment codec --------------------------------------------------------


def encode_segment(seq: int, prev_token: str,
                   dev_rows: list, host_rows: list,
                   blob: dict) -> tuple[bytes, dict]:
    """One delta segment: MAGIC + u32 header length + sorted-key JSON
    header + payload. Payload = dev_rows then host_rows as REC-framed
    (issuer_idx, exp_hour, serial) records, then a zlib-compressed
    sorted-key JSON blob with the non-row diffs. Self-delimiting: the
    header carries every section's byte length."""
    body = bytearray()
    for idx, eh, sb in dev_rows:
        body += REC.pack(int(idx), int(eh), len(sb))
        body += sb
    rows_bytes = len(body)
    for idx, eh, sb in host_rows:
        body += REC.pack(int(idx), int(eh), len(sb))
        body += sb
    host_bytes = len(body) - rows_bytes
    zblob = zlib.compress(_dumps(blob), 6)
    body += zblob
    payload = bytes(body)
    payload_sha = hashlib.sha256(payload).hexdigest()
    header = {
        "format": FORMAT,
        "version": 1,
        "seq": int(seq),
        "devRows": len(dev_rows),
        "devRowBytes": rows_bytes,
        "hostRows": len(host_rows),
        "hostRowBytes": host_bytes,
        "blobBytes": len(zblob),
        "baseSha256": prev_token,
        "payloadSha256": payload_sha,
        "targetSha256": chain_token(prev_token, payload_sha),
    }
    hdr = _dumps(header)
    return MAGIC + _LEN.pack(len(hdr)) + hdr + payload, header


def _parse_records(buf: bytes, n: int) -> list:
    rows = []
    off = 0
    for _ in range(n):
        if off + REC.size > len(buf):
            raise CkptError("segment truncated inside a dirty row")
        idx, eh, slen = REC.unpack_from(buf, off)
        off += REC.size
        if off + slen > len(buf):
            raise CkptError("segment truncated inside serial bytes")
        rows.append((idx, eh, buf[off:off + slen]))
        off += slen
    if off != len(buf):
        raise CkptError("trailing bytes after dirty rows")
    return rows


def decode_segment(data: bytes) -> tuple[dict, list, list, dict]:
    """Validate + decode one segment's bytes →
    (header, dev_rows, host_rows, blob)."""
    if data[:len(MAGIC)] != MAGIC:
        raise CkptError("bad segment magic")
    off = len(MAGIC)
    if len(data) < off + _LEN.size:
        raise CkptError("segment truncated before header")
    (hlen,) = _LEN.unpack_from(data, off)
    off += _LEN.size
    if len(data) < off + hlen:
        raise CkptError("segment truncated inside header")
    try:
        header = json.loads(data[off:off + hlen].decode("utf-8"))
    except ValueError as err:
        raise CkptError(f"unparseable segment header: {err}") from err
    off += hlen
    payload = data[off:]
    want = (header.get("devRowBytes", -1) + header.get("hostRowBytes", -1)
            + header.get("blobBytes", -1))
    if header.get("format") != FORMAT or want != len(payload):
        raise CkptError("segment header does not match payload size")
    payload_sha = hashlib.sha256(payload).hexdigest()
    if payload_sha != header.get("payloadSha256"):
        raise CkptError("segment payload hash mismatch")
    if header.get("targetSha256") != chain_token(
            header.get("baseSha256", ""), payload_sha):
        raise CkptError("segment target token mismatch")
    db = header["devRowBytes"]
    hb = header["hostRowBytes"]
    dev_rows = _parse_records(payload[:db], header["devRows"])
    host_rows = _parse_records(payload[db:db + hb], header["hostRows"])
    try:
        blob = json.loads(zlib.decompress(
            payload[db + hb:]).decode("utf-8"))
    except (ValueError, zlib.error) as err:
        raise CkptError(f"unparseable segment blob: {err}") from err
    return header, dev_rows, host_rows, blob


# -- manifest / atomic writes ---------------------------------------------


def _atomic_write(target: str, data: bytes, pre_rename: str = "",
                  post_rename: str = "") -> None:
    d = os.path.dirname(os.path.abspath(target))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(target),
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        if pre_rename:
            kill_point(pre_rename)
        os.replace(tmp, target)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if post_rename:
        kill_point(post_rename)


def write_segment(path: str, seq: int, data: bytes) -> str:
    sp = segment_path(path, seq)
    _atomic_write(sp, data, pre_rename="seg-pre-rename",
                  post_rename="seg-post-rename")
    return sp


def write_manifest(path: str, manifest: dict) -> None:
    _atomic_write(manifest_path(path), _dumps(manifest) + b"\n",
                  pre_rename="manifest-pre-rename")


def read_manifest(path: str) -> Optional[dict]:
    mp = manifest_path(path)
    if not os.path.exists(mp):
        return None
    try:
        with open(mp, "rb") as fh:
            man = json.loads(fh.read().decode("utf-8"))
    except (OSError, ValueError) as err:
        # Manifests are written atomically: an unreadable one is real
        # damage, not a torn write.
        raise CkptError(f"unreadable checkpoint manifest {mp}: {err}")
    if not isinstance(man, dict) or man.get("format") != FORMAT:
        raise CkptError(f"bad checkpoint manifest format in {mp}")
    return man


def cleanup_segments(path: str, keep_seqs=()) -> None:
    """Best-effort removal of segment files not in ``keep_seqs`` (after
    a compaction dropped the chain). Failures are ignored — orphan
    segments are inert: never loaded unless a manifest names them, and
    overwritten via tmp+rename if their seq is ever reused."""
    import glob as _glob

    keep = {segment_path(path, s) for s in keep_seqs}
    for sp in sorted(_glob.glob(path + ".ckseg-*")):
        if sp not in keep:
            try:
                os.unlink(sp)
            except OSError:
                pass


# -- chain resolution -----------------------------------------------------


class ChainState(NamedTuple):
    base_sha: str        # sha256 of the base file actually on disk
    tip_token: str       # token of the newest durable tick
    segments: list       # [(header, dev_rows, host_rows, blob), ...]


def resolve_chain(path: str) -> ChainState:
    """What must be replayed on top of the base at ``path``.

    - No manifest → plain ck01 snapshot: base alone, tip == base sha.
    - Manifest whose baseSha256 != the base file's actual hash → the
      base is NEWER (a compaction's base landed but its manifest did
      not); the fresh base IS the tick's complete state, so it loads
      alone and the stale chain is ignored.
    - Otherwise every listed segment must exist, decode, and
      hash-chain from the base: the manifest is only ever renamed into
      place AFTER its newest segment, so a broken listed chain is
      damage, not a crash artifact → CkptError.
    """
    base_sha = file_sha256(path)
    man = read_manifest(path)
    if man is None or man.get("baseSha256") != base_sha:
        return ChainState(base_sha, base_sha, [])
    segments = []
    prev = base_sha
    chain = man.get("chain", [])
    if not isinstance(chain, list):
        raise CkptError("manifest chain is not a list")
    for link in chain:
        sp = segment_path(path, int(link["seq"]))
        try:
            with open(sp, "rb") as fh:
                data = fh.read()
        except OSError as err:
            raise CkptError(
                f"manifest names missing segment {sp}: {err}")
        header, dev_rows, host_rows, blob = decode_segment(data)
        if header["baseSha256"] != prev:
            raise CkptError(
                f"segment {sp} chains from {header['baseSha256'][:12]} "
                f"but the durable tip is {prev[:12]}")
        if header["targetSha256"] != link.get("targetSha256"):
            raise CkptError(f"segment {sp} target differs from manifest")
        prev = header["targetSha256"]
        segments.append((header, dev_rows, host_rows, blob))
    return ChainState(base_sha, prev, segments)
