from ct_mapreduce_tpu.agg.aggregator import (  # noqa: F401
    AggregateSnapshot,
    IngestResult,
    IssuerRegistry,
    TpuAggregator,
)
