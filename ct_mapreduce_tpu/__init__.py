"""ct-mapreduce-tpu: a TPU-native map/reduce framework over Certificate
Transparency logs.

Rebuilds the capabilities of the reference Go toolkit (jcjones/ct-mapreduce)
on JAX/XLA/Pallas/pjit: the per-entry hot loop (x509 field extraction,
SHA-256 identity fingerprinting, known-certificate dedup, per-issuer
aggregation) runs as batched device compute over HBM-resident entry
batches, sharded over a `jax.sharding.Mesh` for pod-scale reduce.

Layout:
  core/        identity & value types, DER parsing, batch schema
  ops/         device ops (SHA-256, DER field extraction, hash-set, histograms)
  agg/         on-device aggregate (reduce) state + drain
  models/      the end-to-end jitted pipeline ("flagship model")
  parallel/    mesh construction, shardings, multi-host init
  storage/     pluggable backends + CertDatabase facade (reference parity)
  ingest/      CT log HTTP client, entry decode, batching, checkpointing
  coordinator/ multi-process leader election / start barrier
  config/      layered ini < env < flags configuration
  telemetry/   metrics registry, dumper, StatsD sink, health endpoint
  cmd/         CLI entry points (ct-fetch, storage-statistics, ct-getcert)
"""

__version__ = "0.1.0"
