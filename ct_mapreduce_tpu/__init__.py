"""ct-mapreduce-tpu: a TPU-native map/reduce framework over Certificate
Transparency logs.

Rebuilds the capabilities of the reference Go toolkit (jcjones/ct-mapreduce)
on JAX/XLA/Pallas/pjit: the per-entry hot loop (x509 field extraction,
SHA-256 identity fingerprinting, known-certificate dedup, per-issuer
aggregation) runs as batched device compute over HBM-resident entry
batches, sharded over a `jax.sharding.Mesh` for pod-scale reduce.

Layout:
  core/        identity & value types, DER parsing, batch schema
  ops/         device ops (SHA-256 incl. Pallas kernel, DER field
               extraction, hash-set dedup, fused ingest step)
  agg/         aggregate (reduce) state: single-chip + mesh-sharded
               (all_to_all key routing), exact host lane, drain
  models/      config → mesh → aggregator composition root
  parallel/    mesh construction, multi-host init, TPU-native coordinator
  native/      C++ batch leaf decoder (ctypes; pure-Python fallback)
  storage/     pluggable backends + CertDatabase facade (reference parity)
  ingest/      CT log HTTP client, RFC 6962 leaf codec, sync engine,
               raw-batch fast path, health endpoint
  coordinator/ Redis-parity leader election / start barrier
  config/      layered ini < env < flags configuration
  telemetry/   metrics registry, dumper, StatsD sink
  cmd/         CLI entry points (ct-fetch, storage-statistics, ct-getcert)
"""

__version__ = "0.1.0"
