"""Runtime lock-order witness: the declared hierarchy, checked live.

Opt-in (``CTMR_LOCK_WITNESS=1``): :func:`install` replaces
``threading.Lock``/``RLock`` with factories that wrap locks **created
by package code** (caller-frame filtered; everything else gets a real
lock untouched) in a thin bookkeeping shell. Each wrapped acquisition
pushes onto a per-thread chain; first-time (held → acquired) pairs are
recorded into a global edge graph where two checks run:

- **order** — both locks declared and ranked in
  :mod:`.lockspec`: acquiring a rank ≤ the one held violates the
  hierarchy;
- **cycle** — any new edge closing a directed cycle in the observed
  graph is a deadlock shape, declared or not.

Locks are *named* by creation site: the spec's
:func:`~ct_mapreduce_tpu.analysis.lockspec.build_site_table` maps
``(file, line)`` of every declared ``threading.Lock()`` call to its
hierarchy name, so the witness needs no cooperation from the code it
observes. Same-name pairs are exempt (distinct instances of one role,
e.g. two aggregators' fold locks during a merge).

Findings surface three ways: :meth:`LockWitness.findings`, a
``lock_witness`` section in every flight-recorder dump
(:func:`ct_mapreduce_tpu.telemetry.flight.register_section`), and —
under the test suite — a session-failing report from
``tests/conftest.py``, which enables the witness for the whole tier-1
run so every concurrency test doubles as a race-order probe.

Bookkeeping is wait-free on the hot path (thread-local list + one
set lookup per held lock) and *must never raise*: a witness bug may
lose a finding, never break the program it watches.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

from ct_mapreduce_tpu.analysis import lockspec

# Real factories, captured before any patching.
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

MAX_FINDINGS = 100
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.realpath(__file__)))


class WitnessLock:
    """Duck-typed threading.Lock/RLock with acquisition bookkeeping."""

    __slots__ = ("_w", "_lock", "name", "rank", "uid")

    def __init__(self, witness: "LockWitness", lock, name: str,
                 rank: Optional[int], uid: int) -> None:
        self._w = witness
        self._lock = lock
        self.name = name
        self.rank = rank
        self.uid = uid

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            try:
                self._w._note_acquire(self)
            except Exception:
                self._w._internal_errors += 1
        return got

    def release(self) -> None:
        try:
            self._w._note_release(self)
        except Exception:
            self._w._internal_errors += 1
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<WitnessLock {self.name} rank={self.rank}>"


class LockWitness:
    """The edge graph + per-thread chains. One instance is installed
    process-wide by :func:`install`; tests build private instances
    around :meth:`wrap` to inject violations without polluting it."""

    def __init__(self, site_table: Optional[dict] = None,
                 ranks: Optional[dict] = None) -> None:
        self.site_table = site_table or {}
        self.ranks = dict(lockspec.RANKS if ranks is None else ranks)
        self._tl = threading.local()
        self._ilock = _ORIG_LOCK()  # guards graph + findings; REAL lock
        self._edge_seen: set[tuple[str, str]] = set()
        self._edges: dict[str, set[str]] = {}
        self._edge_where: dict[tuple[str, str], str] = {}
        self._violations: list[dict] = []
        self._uid = 0
        self._internal_errors = 0
        self.locks_wrapped = 0

    # -- wrapping --------------------------------------------------------
    def wrap(self, lock, name: str,
             rank: Optional[int] = None) -> WitnessLock:
        with self._ilock:
            self._uid += 1
            uid = self._uid
            self.locks_wrapped += 1
        if rank is None:
            rank = self.ranks.get(name)
        return WitnessLock(self, lock, name, rank, uid)

    # -- hot path --------------------------------------------------------
    def _note_acquire(self, wl: WitnessLock) -> None:
        tl = self._tl
        try:
            stack = tl.stack
            counts = tl.counts
        except AttributeError:
            stack = tl.stack = []
            counts = tl.counts = {}
        n = counts.get(wl.uid, 0)
        counts[wl.uid] = n + 1
        if n:  # reentrant re-acquire (RLock): chain position unchanged
            return
        if stack:
            seen = self._edge_seen
            for held in stack:
                if held.name != wl.name and (
                        held.name, wl.name) not in seen:
                    self._record_edge(held, wl)
        stack.append(wl)

    def _note_release(self, wl: WitnessLock) -> None:
        tl = self._tl
        try:
            counts = tl.counts
            stack = tl.stack
        except AttributeError:
            return  # release from a thread that never acquired: ignore
        n = counts.get(wl.uid, 0)
        if n > 1:
            counts[wl.uid] = n - 1
            return
        counts.pop(wl.uid, None)
        if stack and stack[-1] is wl:
            stack.pop()
        else:  # legal out-of-LIFO release
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is wl:
                    del stack[i]
                    break

    # -- slow path: first observation of a (held, acquired) pair ---------
    @staticmethod
    def _acquire_site() -> str:
        f = sys._getframe(2)
        here = os.path.abspath(__file__)
        while f is not None and os.path.abspath(
                f.f_code.co_filename) == here:
            f = f.f_back
        if f is None:  # pragma: no cover
            return "?"
        return f"{f.f_code.co_filename}:{f.f_lineno}"

    def _record_edge(self, held: WitnessLock, wl: WitnessLock) -> None:
        where = self._acquire_site()
        thread = threading.current_thread().name
        with self._ilock:
            key = (held.name, wl.name)
            if key in self._edge_seen:
                return
            self._edge_seen.add(key)
            self._edge_where[key] = where
            self._edges.setdefault(held.name, set()).add(wl.name)
            if (held.rank is not None and wl.rank is not None
                    and wl.rank <= held.rank):
                self._add_violation({
                    "kind": "order",
                    "held": held.name,
                    "held_rank": held.rank,
                    "acquiring": wl.name,
                    "acquiring_rank": wl.rank,
                    "thread": thread,
                    "where": where,
                })
            cycle = self._find_cycle(wl.name, held.name)
            if cycle is not None:
                self._add_violation({
                    "kind": "cycle",
                    "cycle": cycle + [wl.name],
                    "closing_edge": f"{held.name}->{wl.name}",
                    "thread": thread,
                    "where": where,
                })

    def _find_cycle(self, src: str, dst: str) -> Optional[list]:
        """Path src →* dst in the edge graph (the new edge dst←...→src
        already inserted closes it into a cycle). Iterative DFS."""
        stack = [(src, [src])]
        visited = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for succ in self._edges.get(node, ()):
                if succ not in visited:
                    visited.add(succ)
                    stack.append((succ, path + [succ]))
        return None

    def _add_violation(self, v: dict) -> None:
        if len(self._violations) < MAX_FINDINGS:
            self._violations.append(v)

    # -- reporting -------------------------------------------------------
    def findings(self) -> list[dict]:
        with self._ilock:
            return list(self._violations)

    def edges(self) -> dict[str, list[str]]:
        with self._ilock:
            return {k: sorted(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        with self._ilock:
            self._violations.clear()
            self._edges.clear()
            self._edge_seen.clear()
            self._edge_where.clear()

    def report(self) -> dict:
        """The flight-recorder section."""
        with self._ilock:
            return {
                "violations": list(self._violations),
                "edge_count": len(self._edge_seen),
                "edges": {a: sorted(bs)
                          for a, bs in sorted(self._edges.items())},
                "locks_wrapped": self.locks_wrapped,
                "internal_errors": self._internal_errors,
            }


# -- process-wide installation -------------------------------------------

_active: Optional[LockWitness] = None
_patched = False


def enabled_by_env(env: Optional[dict] = None) -> bool:
    env = os.environ if env is None else env
    return str(env.get("CTMR_LOCK_WITNESS", "")).strip().lower() in (
        "1", "t", "true")


def active() -> Optional[LockWitness]:
    return _active


def _resolve(fname: str, _cache: dict = {}) -> str:
    r = _cache.get(fname)
    if r is None:
        r = _cache[fname] = os.path.realpath(fname)
    return r


def _factory(kind: str):
    orig = _ORIG_LOCK if kind == "lock" else _ORIG_RLOCK

    def make_lock():
        real = orig()
        w = _active
        if w is None:
            return real
        try:
            f = sys._getframe(1)
            fname = _resolve(f.f_code.co_filename)
            if not fname.startswith(_PKG_DIR + os.sep):
                return real
            named = w.site_table.get((fname, f.f_lineno))
            if named is not None:
                name, rank = named
            else:
                rel = os.path.relpath(fname, os.path.dirname(_PKG_DIR))
                name, rank = f"{rel}:{f.f_lineno}", None
            return w.wrap(real, name, rank)
        except Exception:
            return real

    make_lock.__name__ = f"witness_{kind}_factory"
    return make_lock


def install(force: bool = False) -> Optional[LockWitness]:
    """Install the process-wide witness when ``CTMR_LOCK_WITNESS`` is
    truthy (or ``force``). Idempotent; returns the active witness (or
    None when disabled). Must run before the package modules whose
    locks it should observe create them — already-created locks simply
    go unwitnessed."""
    global _active, _patched
    if _active is not None:
        return _active
    if not force and not enabled_by_env():
        return None
    w = LockWitness(site_table=lockspec.build_site_table(_PKG_DIR))
    _active = w
    if not _patched:
        threading.Lock = _factory("lock")
        threading.RLock = _factory("rlock")
        _patched = True
    try:
        from ct_mapreduce_tpu.telemetry import flight

        flight.register_section("lock_witness", w.report)
    except Exception:  # flight recorder is optional here
        pass
    return w


def uninstall() -> None:
    """Restore the real factories (test hygiene). Locks already
    wrapped keep working — they hold real locks inside."""
    global _active, _patched
    _active = None
    if _patched:
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK
        _patched = False
    try:
        from ct_mapreduce_tpu.telemetry import flight

        flight.unregister_section("lock_witness")
    except Exception:
        pass
