"""lock-order: the declared hierarchy, statically enforced.

Two sub-rules over :mod:`.lockspec`:

- **undeclared-lock** — every ``threading.Lock()``/``RLock()`` bound
  to an attribute or module global must be declared in the spec. An
  ad-hoc lock with no rank is a hierarchy hole: nothing checks what
  it may nest under.
- **order** — a ``with`` statement acquiring lock B syntactically
  inside a ``with`` holding lock A must respect rank(A) < rank(B).
  Same-name nesting is exempt (distinct instances of one role, e.g.
  two aggregators' fold locks during a merge, are indistinguishable
  statically; the runtime witness sees those).

Resolution is name-based: ``self.X`` resolves against the spec entry
for (module, enclosing class, X); cross-object references like
``agg._fold_lock`` resolve when the attribute is unambiguous across
the whole spec. Unresolvable expressions (plain names, call results)
are skipped — the witness covers them at runtime.
"""

from __future__ import annotations

import ast
from typing import Optional

from ct_mapreduce_tpu.analysis import lockspec
from ct_mapreduce_tpu.analysis.engine import Checker, Ctx

_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def resolve_lock_expr(expr: ast.AST, relpath: str,
                      cls: Optional[str]) -> Optional[tuple[str, Optional[int]]]:
    """(hierarchy name, rank) when ``expr`` names a declared lock."""
    if not isinstance(expr, ast.Attribute):
        return None
    attr = expr.attr
    if isinstance(expr.value, ast.Name) and expr.value.id == "self":
        d = lockspec.decl_for(relpath, cls, attr)
        if d is not None:
            return d.name, d.rank
    name = lockspec.unique_attr_name(attr)
    if name is not None:
        return name, lockspec.rank_of(name)
    return None


class LockOrderChecker(Checker):
    name = "lock-order"

    # -- undeclared locks ------------------------------------------------
    def _check_binding(self, value: ast.AST, target: ast.AST,
                       ctx: Ctx) -> None:
        if lockspec._lock_ctor_kind(value) is None:
            return
        relpath = ctx.module.relpath
        if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name) and target.value.id == "self":
            cls, attr = ctx.cls, target.attr
        elif isinstance(target, ast.Name) and ctx.cls is None \
                and ctx.func is None:
            cls, attr = None, target.id
        else:
            return  # local temporary; the witness still graphs it
        if lockspec.decl_for(relpath, cls, attr) is None:
            where = f"{cls}.{attr}" if cls else attr
            self.report(
                relpath, value.lineno, where,
                f"threading lock {where} is not declared in "
                f"analysis/lockspec.py — add a LockDecl with a rank "
                f"(or None for an order-free leaf)")

    def visit_Assign(self, node: ast.Assign, ctx: Ctx) -> None:
        for t in node.targets:
            self._check_binding(node.value, t, ctx)

    def visit_AnnAssign(self, node: ast.AnnAssign, ctx: Ctx) -> None:
        if node.value is not None:
            self._check_binding(node.value, node.target, ctx)

    # -- with-nest order -------------------------------------------------
    def _enclosing_function(self, node: ast.AST, ctx: Ctx):
        n = node
        while n is not None:
            n = ctx.parent(n)
            if isinstance(n, _SCOPE_TYPES):
                return n
        return None

    def visit_With(self, node: ast.With, ctx: Ctx) -> None:
        relpath, cls = ctx.module.relpath, ctx.cls
        here = self._enclosing_function(node, ctx)
        held: list[tuple[str, Optional[int], int]] = []
        # Locks held by enclosing `with` blocks IN THE SAME function
        # (a closure's body does not run under its definition site's
        # locks).
        for outer in ctx.with_stack:
            if self._enclosing_function(outer, ctx) is not here:
                continue
            for item in outer.items:
                r = resolve_lock_expr(item.context_expr, relpath, cls)
                if r is not None:
                    held.append((r[0], r[1], outer.lineno))
        for item in node.items:
            r = resolve_lock_expr(item.context_expr, relpath, cls)
            if r is None:
                continue
            name, rank = r
            for h_name, h_rank, h_line in held:
                if h_name == name:
                    continue  # same hierarchy node: witness territory
                if h_rank is None or rank is None:
                    continue  # order-free leaf
                if rank <= h_rank:
                    self.report(
                        relpath, node.lineno, f"{h_name}->{name}",
                        f"acquires {name} (rank {rank}) while holding "
                        f"{h_name} (rank {h_rank}, line {h_line}) — "
                        f"against the declared hierarchy")
            held.append((name, rank, node.lineno))
