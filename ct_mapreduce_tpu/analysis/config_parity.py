"""config-parity: one directive, four surfaces, zero drift.

A configuration directive exists in four places that historically
drifted independently: the ``_DIRECTIVES`` parse table in
``config/config.py``, the self-documenting ``usage()`` text, the
``CTMR_*`` env layer inside the subsystem ``resolve_*`` functions,
and the operator-facing MIGRATING.md. This rule diffs them:

- every parsed directive must appear in ``usage()``;
- every ``name =`` line in ``usage()`` must be a parsed directive
  (no ghost documentation);
- every TPU-native directive (not inherited from the Go reference —
  those are covered by reference docs) must appear in MIGRATING.md;
- every ``CTMR_*`` env var consulted by a ``resolve_*`` function must
  appear in MIGRATING.md (the env layer is API).

Round 18 (the platformProfile refactor) adds two surfaces: knob specs
(``Knob(...)`` declarations in config/profile.py's engine) carry the
env names that used to live inline in ``resolve_*`` bodies — their
``CTMR_*`` strings are collected the same way — and every profile
section resolved via ``resolve_section("<name>", ...)`` must be
documented in MIGRATING.md as ``knobs.<name>`` (the profile file
format is operator API too).
"""

from __future__ import annotations

import ast
import re

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx, Project

CONFIG_RELPATH = "ct_mapreduce_tpu/config/config.py"
MIGRATING_RELPATH = "MIGRATING.md"

# Directives inherited 1:1 from the reference's config.go — their
# operator docs are the reference's; MIGRATING.md documents deltas.
REFERENCE_DIRECTIVES = frozenset({
    "offset", "limit", "logList", "numThreads", "logExpiredEntries",
    "runForever", "pollingDelayMean", "pollingDelayStdDev",
    "savePeriod", "issuerCNFilter", "certPath", "googleProjectId",
    "redisHost", "redisTimeout", "outputRefreshPeriod",
    "statsRefreshPeriod", "statsdHost", "statsdPort", "healthAddr",
})

_ENV_RE = re.compile(r"^CTMR_[A-Z0-9_]+$")


class ConfigParityChecker(Checker):
    name = "config-parity"

    def __init__(self) -> None:
        super().__init__()
        # env var -> first "path:line" inside a resolve_* function
        self.resolve_envs: dict[str, str] = {}
        # profile section -> first "path:line" of a resolve_section call
        self.profile_sections: dict[str, str] = {}
        self._resolve_stack = 0

    # -- collect CTMR_* envs inside resolve_* functions ------------------
    def visit_FunctionDef(self, node: ast.FunctionDef, ctx: Ctx) -> None:
        if not node.name.startswith("resolve_"):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str) and _ENV_RE.match(sub.value):
                self.resolve_envs.setdefault(
                    sub.value, f"{ctx.module.relpath}:{sub.lineno}")

    # -- collect CTMR_* envs from Knob specs + profile section names -----
    def visit_Call(self, node: ast.Call, ctx: Ctx) -> None:
        fn = node.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        if name == "Knob":
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str) and _ENV_RE.match(arg.value):
                    self.resolve_envs.setdefault(
                        arg.value, f"{ctx.module.relpath}:{arg.lineno}")
        elif name == "resolve_section":
            if node.args and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str):
                self.profile_sections.setdefault(
                    node.args[0].value,
                    f"{ctx.module.relpath}:{node.lineno}")

    # -- diff the four surfaces ------------------------------------------
    @staticmethod
    def _directives(tree: ast.AST) -> dict[str, int]:
        """directive -> lineno from the _DIRECTIVES dict literal."""
        out: dict[str, int] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_DIRECTIVES"
                    for t in node.targets):
                if isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            out[k.value] = k.lineno
        return out

    @staticmethod
    def _usage_text(tree: ast.AST) -> str:
        chunks: list[str] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == "usage":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str):
                        chunks.append(sub.value)
        return "\n".join(chunks)

    def finish(self, project: Project) -> None:
        cfg = project.module(CONFIG_RELPATH)
        if cfg is None:
            self.report(CONFIG_RELPATH, 0, "missing",
                        "config module not found under the scanned root")
            return
        directives = self._directives(cfg.tree)
        if not directives:
            self.report(CONFIG_RELPATH, 0, "no-directives",
                        "_DIRECTIVES dict literal not found — parser "
                        "refactor? update config_parity.py")
            return
        usage = self._usage_text(cfg.tree)
        migrating_path = project.repo_root / MIGRATING_RELPATH
        migrating = (migrating_path.read_text()
                     if migrating_path.exists() else "")

        for d, line in sorted(directives.items()):
            if d not in usage:
                self.report(CONFIG_RELPATH, line, f"usage:{d}",
                            f"directive {d} is parsed but absent from "
                            f"usage() — operators discover directives "
                            f"there")
            if d not in REFERENCE_DIRECTIVES and d not in migrating:
                self.report(CONFIG_RELPATH, line, f"migrating:{d}",
                            f"TPU-native directive {d} undocumented in "
                            f"MIGRATING.md")

        # Ghost documentation: usage() lines shaped like directives.
        for m in re.finditer(r"^(\w+) = ", usage, re.MULTILINE):
            token = m.group(1)
            if token not in directives:
                self.report(CONFIG_RELPATH, 0, f"usage-unknown:{token}",
                            f"usage() documents '{token}' but no such "
                            f"directive is parsed")

        if not migrating:
            self.report(MIGRATING_RELPATH, 0, "missing",
                        "MIGRATING.md not found")
            return
        for env, where in sorted(self.resolve_envs.items()):
            if env not in migrating:
                self.report(
                    where.rpartition(":")[0],
                    int(where.rpartition(":")[2]),
                    f"migrating-env:{env}",
                    f"env var {env} (consulted by a resolve_* layer, "
                    f"{where}) undocumented in MIGRATING.md")
        for section, where in sorted(self.profile_sections.items()):
            if f"knobs.{section}" not in migrating:
                self.report(
                    where.rpartition(":")[0],
                    int(where.rpartition(":")[2]),
                    f"migrating-profile:{section}",
                    f"platformProfile section knobs.{section} "
                    f"(resolved at {where}) undocumented in "
                    f"MIGRATING.md")
