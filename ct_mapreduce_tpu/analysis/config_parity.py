"""config-parity: one directive, four surfaces, zero drift.

A configuration directive exists in four places that historically
drifted independently: the ``_DIRECTIVES`` parse table in
``config/config.py``, the self-documenting ``usage()`` text, the
``CTMR_*`` env layer inside the subsystem ``resolve_*`` functions,
and the operator-facing MIGRATING.md. This rule diffs them:

- every parsed directive must appear in ``usage()``;
- every ``name =`` line in ``usage()`` must be a parsed directive
  (no ghost documentation);
- every TPU-native directive (not inherited from the Go reference —
  those are covered by reference docs) must appear in MIGRATING.md;
- every ``CTMR_*`` env var consulted by a ``resolve_*`` function must
  appear in MIGRATING.md (the env layer is API).

Round 18 (the platformProfile refactor) adds two surfaces: knob specs
(``Knob(...)`` declarations in config/profile.py's engine) carry the
env names that used to live inline in ``resolve_*`` bodies — their
``CTMR_*`` strings are collected the same way — and every profile
section resolved via ``resolve_section("<name>", ...)`` must be
documented in MIGRATING.md as ``knobs.<name>`` (the profile file
format is operator API too).

Round 21 (the autotuner) adds the tune registry as a surface: every
``Knob`` declared in a ``_*_KNOBS`` tuple that a ``resolve_section``
call consumes must appear in ``tune/registry.py`` — either with a
declared sweep ladder (``SWEEPABLE``) or with a justified exclusion
(``EXCLUDED``, justification >= 15 chars). A new knob that lands in
neither table would make the autotuner silently stale against the
knob surface; a registry entry naming no declared knob is ghost
configuration. The diff is pure AST (the registry is import-light and
both tables are literals), mirroring ``tune.registry.audit()`` which
re-derives the same diff at runtime for the tests.
"""

from __future__ import annotations

import ast
import re

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx, Project

CONFIG_RELPATH = "ct_mapreduce_tpu/config/config.py"
MIGRATING_RELPATH = "MIGRATING.md"
REGISTRY_RELPATH = "ct_mapreduce_tpu/tune/registry.py"
MIN_JUSTIFICATION = 15  # chars — the ctmrlint.baseline discipline

# Directives inherited 1:1 from the reference's config.go — their
# operator docs are the reference's; MIGRATING.md documents deltas.
REFERENCE_DIRECTIVES = frozenset({
    "offset", "limit", "logList", "numThreads", "logExpiredEntries",
    "runForever", "pollingDelayMean", "pollingDelayStdDev",
    "savePeriod", "issuerCNFilter", "certPath", "googleProjectId",
    "redisHost", "redisTimeout", "outputRefreshPeriod",
    "statsRefreshPeriod", "statsdHost", "statsdPort", "healthAddr",
})

_ENV_RE = re.compile(r"^CTMR_[A-Z0-9_]+$")


class ConfigParityChecker(Checker):
    name = "config-parity"

    def __init__(self) -> None:
        super().__init__()
        # env var -> first "path:line" inside a resolve_* function
        self.resolve_envs: dict[str, str] = {}
        # profile section -> first "path:line" of a resolve_section call
        self.profile_sections: dict[str, str] = {}
        # (module relpath, knob-tuple var) -> [(knob name, lineno)]
        self.knob_decls: dict[tuple, list] = {}
        # section -> (relpath, knob-tuple var, lineno) from the
        # resolve_section("<name>", <VAR>, ...) association
        self.section_vars: dict[str, tuple] = {}
        self._resolve_stack = 0

    # -- collect CTMR_* envs inside resolve_* functions ------------------
    def visit_FunctionDef(self, node: ast.FunctionDef, ctx: Ctx) -> None:
        if not node.name.startswith("resolve_"):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str) and _ENV_RE.match(sub.value):
                self.resolve_envs.setdefault(
                    sub.value, f"{ctx.module.relpath}:{sub.lineno}")

    # -- collect CTMR_* envs from Knob specs + profile section names -----
    def visit_Call(self, node: ast.Call, ctx: Ctx) -> None:
        fn = node.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        if name == "Knob":
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str) and _ENV_RE.match(arg.value):
                    self.resolve_envs.setdefault(
                        arg.value, f"{ctx.module.relpath}:{arg.lineno}")
        elif name == "resolve_section":
            if node.args and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str):
                self.profile_sections.setdefault(
                    node.args[0].value,
                    f"{ctx.module.relpath}:{node.lineno}")
                if len(node.args) > 1 and isinstance(
                        node.args[1], ast.Name):
                    self.section_vars.setdefault(
                        node.args[0].value,
                        (ctx.module.relpath, node.args[1].id,
                         node.lineno))

    # -- collect Knob names from _*_KNOBS tuple declarations -------------
    def visit_Assign(self, node: ast.Assign, ctx: Ctx) -> None:
        for t in node.targets:
            if not (isinstance(t, ast.Name) and t.id.startswith("_")
                    and t.id.endswith("_KNOBS")):
                continue
            decls: list = []
            for sub in ast.walk(node.value):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                cname = (fn.id if isinstance(fn, ast.Name)
                         else fn.attr if isinstance(fn, ast.Attribute)
                         else None)
                if cname != "Knob":
                    continue
                kname = None
                if sub.args and isinstance(
                        sub.args[0], ast.Constant) and isinstance(
                        sub.args[0].value, str):
                    kname = sub.args[0].value
                else:
                    for kw in sub.keywords:
                        if kw.arg == "name" and isinstance(
                                kw.value, ast.Constant) and isinstance(
                                kw.value.value, str):
                            kname = kw.value.value
                if kname is not None:
                    decls.append((kname, sub.lineno))
            self.knob_decls[(ctx.module.relpath, t.id)] = decls

    # -- diff the four surfaces ------------------------------------------
    @staticmethod
    def _directives(tree: ast.AST) -> dict[str, int]:
        """directive -> lineno from the _DIRECTIVES dict literal."""
        out: dict[str, int] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_DIRECTIVES"
                    for t in node.targets):
                if isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            out[k.value] = k.lineno
        return out

    @staticmethod
    def _usage_text(tree: ast.AST) -> str:
        chunks: list[str] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == "usage":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str):
                        chunks.append(sub.value)
        return "\n".join(chunks)

    # -- diff Knob declarations against the tune registry ----------------
    @staticmethod
    def _registry_tables(tree: ast.AST) -> dict:
        """{'SWEEPABLE'|'EXCLUDED': {section: {knob: (lineno, value)}}}
        from the registry's top-level dict literals."""
        out: dict = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name)
                    and t.id in ("SWEEPABLE", "EXCLUDED")
                    for t in node.targets)):
                continue
            name = node.targets[0].id
            table: dict = {}
            if isinstance(node.value, ast.Dict):
                for sk, sv in zip(node.value.keys, node.value.values):
                    if not (isinstance(sk, ast.Constant) and isinstance(
                            sk.value, str)
                            and isinstance(sv, ast.Dict)):
                        continue
                    entries: dict = {}
                    for kk, kv in zip(sv.keys, sv.values):
                        if isinstance(kk, ast.Constant) and isinstance(
                                kk.value, str):
                            try:
                                val = ast.literal_eval(kv)
                            except ValueError:
                                val = None
                            entries[kk.value] = (kk.lineno, val)
                    table[sk.value] = entries
            out[name] = table
        return out

    def _check_tune_registry(self, project: Project) -> None:
        reg = project.module(REGISTRY_RELPATH)
        if reg is None:
            self.report(REGISTRY_RELPATH, 0, "tune-registry-missing",
                        "tune registry module not found — the knob "
                        "inventory the autotuner sweeps")
            return
        tables = self._registry_tables(reg.tree)
        sweep = tables.get("SWEEPABLE", {})
        excl = tables.get("EXCLUDED", {})
        if not sweep and not excl:
            self.report(REGISTRY_RELPATH, 0, "tune-no-tables",
                        "SWEEPABLE/EXCLUDED dict literals not found — "
                        "registry refactor? update config_parity.py")
            return

        declared: dict[str, dict] = {}  # section -> {knob: "path:line"}
        for section, (relpath, var, lineno) in sorted(
                self.section_vars.items()):
            decls = self.knob_decls.get((relpath, var))
            if decls is None:
                self.report(relpath, lineno, f"tune-knobs-var:{section}",
                            f"resolve_section('{section}', {var}, ...) "
                            f"consumes {var} but no matching _*_KNOBS "
                            f"tuple declaration was found in {relpath}")
                continue
            declared[section] = {k: f"{relpath}:{ln}" for k, ln in decls}

        for section, knobs in sorted(declared.items()):
            s_tab = sweep.get(section, {})
            e_tab = excl.get(section, {})
            for knob, where in sorted(knobs.items()):
                relpath, _, line = where.rpartition(":")
                hit_s, hit_e = knob in s_tab, knob in e_tab
                if hit_s and hit_e:
                    self.report(
                        relpath, int(line),
                        f"tune-both:{section}.{knob}",
                        f"knob {section}.{knob} is both sweepable and "
                        f"excluded in the tune registry")
                elif not (hit_s or hit_e):
                    self.report(
                        relpath, int(line),
                        f"tune-unregistered:{section}.{knob}",
                        f"knob {section}.{knob} is in neither SWEEPABLE "
                        f"nor EXCLUDED in {REGISTRY_RELPATH} — declare "
                        f"a sweep ladder or a justified exclusion")
            for knob, (line, ladder) in sorted(s_tab.items()):
                if knob not in knobs:
                    self.report(
                        REGISTRY_RELPATH, line,
                        f"tune-ghost:{section}.{knob}",
                        f"registry sweeps {section}.{knob} but no such "
                        f"Knob is declared for the section")
                elif not (isinstance(ladder, list) and ladder):
                    self.report(
                        REGISTRY_RELPATH, line,
                        f"tune-ladder:{section}.{knob}",
                        f"sweep ladder for {section}.{knob} must be a "
                        f"non-empty list literal")
            for knob, (line, why) in sorted(e_tab.items()):
                if knob not in knobs:
                    self.report(
                        REGISTRY_RELPATH, line,
                        f"tune-ghost:{section}.{knob}",
                        f"registry excludes {section}.{knob} but no "
                        f"such Knob is declared for the section")
                elif not (isinstance(why, str)
                          and len(why) >= MIN_JUSTIFICATION):
                    self.report(
                        REGISTRY_RELPATH, line,
                        f"tune-justification:{section}.{knob}",
                        f"exclusion of {section}.{knob} needs a "
                        f">= {MIN_JUSTIFICATION} char justification")

        for section in sorted(set(sweep) | set(excl)):
            if section not in self.section_vars:
                self.report(
                    REGISTRY_RELPATH, 0, f"tune-section:{section}",
                    f"registry section {section} is never resolved via "
                    f"resolve_section() — stale inventory")

    def finish(self, project: Project) -> None:
        self._check_tune_registry(project)
        cfg = project.module(CONFIG_RELPATH)
        if cfg is None:
            self.report(CONFIG_RELPATH, 0, "missing",
                        "config module not found under the scanned root")
            return
        directives = self._directives(cfg.tree)
        if not directives:
            self.report(CONFIG_RELPATH, 0, "no-directives",
                        "_DIRECTIVES dict literal not found — parser "
                        "refactor? update config_parity.py")
            return
        usage = self._usage_text(cfg.tree)
        migrating_path = project.repo_root / MIGRATING_RELPATH
        migrating = (migrating_path.read_text()
                     if migrating_path.exists() else "")

        for d, line in sorted(directives.items()):
            if d not in usage:
                self.report(CONFIG_RELPATH, line, f"usage:{d}",
                            f"directive {d} is parsed but absent from "
                            f"usage() — operators discover directives "
                            f"there")
            if d not in REFERENCE_DIRECTIVES and d not in migrating:
                self.report(CONFIG_RELPATH, line, f"migrating:{d}",
                            f"TPU-native directive {d} undocumented in "
                            f"MIGRATING.md")

        # Ghost documentation: usage() lines shaped like directives.
        for m in re.finditer(r"^(\w+) = ", usage, re.MULTILINE):
            token = m.group(1)
            if token not in directives:
                self.report(CONFIG_RELPATH, 0, f"usage-unknown:{token}",
                            f"usage() documents '{token}' but no such "
                            f"directive is parsed")

        if not migrating:
            self.report(MIGRATING_RELPATH, 0, "missing",
                        "MIGRATING.md not found")
            return
        for env, where in sorted(self.resolve_envs.items()):
            if env not in migrating:
                self.report(
                    where.rpartition(":")[0],
                    int(where.rpartition(":")[2]),
                    f"migrating-env:{env}",
                    f"env var {env} (consulted by a resolve_* layer, "
                    f"{where}) undocumented in MIGRATING.md")
        for section, where in sorted(self.profile_sections.items()):
            if f"knobs.{section}" not in migrating:
                self.report(
                    where.rpartition(":")[0],
                    int(where.rpartition(":")[2]),
                    f"migrating-profile:{section}",
                    f"platformProfile section knobs.{section} "
                    f"(resolved at {where}) undocumented in "
                    f"MIGRATING.md")
