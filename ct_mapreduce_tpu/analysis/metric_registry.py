"""metric-registry: every emitted metric key is documented, every
documented key is emitted.

The framework generalization of what ``tests/test_metrics_doc.py``
used to do with its own AST walk (the walker now lives here; the test
is a thin wrapper): collect every ``incr_counter``/``set_gauge``/
``add_sample``/``measure`` call site in the package, turn literal
arguments into dotted keys (non-literal segments become ``*``), and
diff against the backtick-quoted bullet entries of
``docs/METRICS.md`` in both directions. Wildcards match either way —
a dynamic call segment satisfies a doc wildcard and vice versa.
"""

from __future__ import annotations

import ast
import re

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx, Project

EMIT_FUNCS = {"incr_counter", "set_gauge", "add_sample", "measure"}
DOC_RELPATH = "docs/METRICS.md"
# The emit API itself, not a call site.
EXCLUDE_MODULES = ("ct_mapreduce_tpu/telemetry/metrics.py",)


def key_matches(call_key: str, doc_key: str) -> bool:
    """Wildcards may sit on either side: a dynamic call segment (``*``
    from an f-string/variable) matches a doc wildcard, and a doc
    wildcard covers literal call keys."""
    call_re = re.escape(call_key).replace(r"\*", ".*")
    doc_re = re.escape(doc_key).replace(r"\*", ".*")
    return (re.fullmatch(call_re, doc_key) is not None
            or re.fullmatch(doc_re, call_key) is not None)


def bullet_keys(doc_text: str, span_sections: bool = False) -> set[str]:
    """Backtick-quoted keys from the registry's bullet lines.

    docs/METRICS.md holds TWO registries in one file: metric keys and
    — under headings containing "Trace spans" (round 23) — span names.
    ``span_sections`` selects which side's bullets to return, so the
    metric rule never flags a span bullet as a stale metric and the
    span rule (analysis/span_registry.py) never reads a counter."""
    keys = set()
    in_span = False
    for line in doc_text.splitlines():
        stripped = line.strip()
        if stripped.startswith("#"):
            in_span = "trace spans" in stripped.lower()
            continue
        m = re.match(r"- `([^`]+)`", stripped)
        if m and in_span == span_sections:
            keys.add(m.group(1))
    return keys


def documented_keys(doc_text: str) -> set[str]:
    """Backtick-quoted METRIC keys (the non-span sections)."""
    return bullet_keys(doc_text, span_sections=False)


class MetricRegistryChecker(Checker):
    name = "metric-registry"

    def __init__(self) -> None:
        super().__init__()
        # dotted key -> ["path:line", ...]
        self.call_sites: dict[str, list[str]] = {}

    def visit_Call(self, node: ast.Call, ctx: Ctx) -> None:
        if ctx.module.relpath in EXCLUDE_MODULES:
            return
        fn = node.func
        name = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None)
        if name not in EMIT_FUNCS or not node.args:
            return
        parts = [
            a.value
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
            else "*"
            for a in node.args
        ]
        where = f"{ctx.module.relpath}:{node.lineno}"
        self.call_sites.setdefault(".".join(parts), []).append(where)

    def finish(self, project: Project) -> None:
        doc_path = project.repo_root / DOC_RELPATH
        if not doc_path.exists():
            self.report(DOC_RELPATH, 0, "missing",
                        "docs/METRICS.md not found — the metric-name "
                        "registry is the dashboard stability contract")
            return
        docs = documented_keys(doc_path.read_text())
        if not docs:
            self.report(DOC_RELPATH, 0, "empty",
                        "docs/METRICS.md lists no keys — format changed?")
            return
        for key, sites in sorted(self.call_sites.items()):
            if not any(key_matches(key, d) for d in docs):
                path, _, line = sites[0].rpartition(":")
                self.report(
                    path, int(line), key,
                    f"metric key `{key}` emitted "
                    f"({', '.join(sites)}) but missing from "
                    f"docs/METRICS.md — dashboards key on these names")
        for d in sorted(docs):
            if not any(key_matches(key, d) for key in self.call_sites):
                self.report(
                    DOC_RELPATH, 0, f"stale:{d}",
                    f"docs/METRICS.md lists `{d}` but no call site "
                    f"emits it — deleting a metric must update the "
                    f"registry too")
