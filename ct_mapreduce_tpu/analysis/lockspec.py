"""The declared lock hierarchy — the package's lock-order contract as
data.

Every ``threading.Lock``/``RLock`` in ``ct_mapreduce_tpu`` is declared
here with a **rank** in the global partial order: a thread holding a
lock of rank R may only acquire locks of rank **strictly greater**
than R. Ranks are spaced so new locks slot in without renumbering.
Locks that can never be held together still get distinct ranks — the
rank then documents where they'd sit if composition ever nests them.

The chain the ISSUE names (``agg/aggregator.py:482-494``,
``ingest/sync.py:185-189``) is the trunk::

    serve.manager/pool_refresh/pool   (10-14)  snapshot capture wrappers
        ingest.dispatch               (20)     ONE device stream
            agg.save                  (24)     checkpoint writer
                agg.pending           (30)     per-pending claim
                    agg.fold          (40)     host-state fold-ins
                        agg.table     (44)     table-swap guard
                            ingest.pem(48)     PEM tree writes
                                storage.*     (52-62)  backend/caches
                                    ...innermost: telemetry (90-94)

Consumed by BOTH halves of the round-16 tooling: the static
``lock-order`` rule (flags ``with``-nests against the order and any
lock attribute not declared here) and the runtime witness
(``analysis/witness.py`` maps creation sites to these names via
:func:`build_site_table` and checks real acquisition chains).

jax-free on purpose (see package docstring).
"""

from __future__ import annotations

import ast
import fnmatch
import pathlib
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class LockDecl:
    name: str  # hierarchy name, e.g. "agg.fold"
    path: str  # repo-relative module path (fnmatch pattern)
    cls: Optional[str]  # enclosing class; None = module level
    attr: str  # attribute / module-variable name
    rank: Optional[int]  # position in the partial order; None = leaf
    # with no ordering constraints (witness still graphs it)
    doc: str = ""


# NOTE: several distinct per-item locks share one name on purpose
# (the three Pending* classes): they are the same hierarchy node, and
# same-name nesting is exempt from order checks (distinct instances
# of one role, e.g. two aggregators' fold locks in a merge, are not
# statically distinguishable).
LOCKS: tuple[LockDecl, ...] = (
    # -- serve plane (outermost: may wrap a full aggregate capture) -----
    LockDecl("serve.manager", "ct_mapreduce_tpu/serve/snapshot.py",
             "SnapshotManager", "_lock", 10,
             "view refresh; held across capture_view -> agg.fold"),
    LockDecl("serve.pool_refresh", "ct_mapreduce_tpu/serve/snapshot.py",
             "ReplicaPool", "_refresh_lock", 12,
             "one capture in flight; held across capture + pin"),
    LockDecl("serve.pool", "ct_mapreduce_tpu/serve/snapshot.py",
             "ReplicaPool", "_lock", 14, "replica list + epoch counter"),
    # -- ingest device stream -------------------------------------------
    LockDecl("ingest.pending_buf", "ct_mapreduce_tpu/ingest/sync.py",
             "AggregatorSink", "_lock", 16,
             "pending entry/raw buffers; released before dispatch"),
    LockDecl("ingest.dispatch", "ct_mapreduce_tpu/ingest/sync.py",
             "AggregatorSink", "_dispatch_lock", 20,
             "serializes the donated device stream (ONE stream per "
             "table, however many store workers feed it)"),
    LockDecl("ops.ecdsa_tables", "ct_mapreduce_tpu/ops/ecdsa.py",
             None, "_TABLE_LOCK", 22,
             "precompute-table build/LRU caches; the verify lane "
             "builds under ingest.dispatch"),
    LockDecl("agg.save", "ct_mapreduce_tpu/agg/aggregator.py",
             "TpuAggregator", "_save_lock", 24,
             "whole-checkpoint writes (fleet cadence vs run's own save)"),
    LockDecl("agg.emit", "ct_mapreduce_tpu/agg/aggregator.py",
             "TpuAggregator", "_emit_lock", 26,
             "filter emission after a save (outside agg.save since "
             "round 22 — a multi-second build must not block the "
             "fleet save fan-out); acquires agg.fold inside"),
    LockDecl("agg.pending", "ct_mapreduce_tpu/agg/aggregator.py",
             "PendingIngest", "_lock", 30,
             "claim-before-fold; acquires agg.fold inside"),
    LockDecl("agg.pending", "ct_mapreduce_tpu/agg/aggregator.py",
             "PendingPreparsed", "_lock", 30, "same role, preparsed lane"),
    LockDecl("agg.pending", "ct_mapreduce_tpu/agg/aggregator.py",
             "PendingStaged", "_lock", 30, "same role, staged lane"),
    LockDecl("verify.keys", "ct_mapreduce_tpu/verify/lane.py",
             "LogKeyRegistry", "_lock", 36, "trust-anchor map"),
    LockDecl("agg.fold", "ct_mapreduce_tpu/agg/aggregator.py",
             "TpuAggregator", "_fold_lock", 40,
             "host-state fold-ins; documented order: fold, then table"),
    LockDecl("agg.table", "ct_mapreduce_tpu/agg/aggregator.py",
             "TpuAggregator", "_table_lock", 44,
             "table swaps vs concurrent reads (RLock: grow re-enters)"),
    LockDecl("ingest.pem", "ct_mapreduce_tpu/ingest/sync.py",
             "AggregatorSink", "_pem_lock", 48,
             "durable PEM tree writes (overlap drain vs per-entry path)"),
    # -- storage backends (inside the ingest chain via _store_pems) ------
    LockDecl("storage.certdb_meta", "ct_mapreduce_tpu/storage/certdb.py",
             "FilesystemDatabase", "_meta_lock", 52,
             "issuer-metadata map (RLock)"),
    LockDecl("storage.known_lru", "ct_mapreduce_tpu/storage/certdb.py",
             "_LRU", "_lock", 54,
             "known-certs LRU; factory runs cache loads inside"),
    LockDecl("storage.issuer_meta",
             "ct_mapreduce_tpu/storage/issuermetadata.py",
             "IssuerMetadata", "_lock", 56, "per-issuer CRL/DN sets"),
    LockDecl("storage.redis", "ct_mapreduce_tpu/storage/rediscache.py",
             "RespClient", "_lock", 60, "one RESP2 connection"),
    LockDecl("storage.mock", "ct_mapreduce_tpu/storage/mockcache.py",
             "MockRemoteCache", "_lock", 62, "in-process cache fake"),
    LockDecl("agg.registry", "ct_mapreduce_tpu/agg/aggregator.py",
             "IssuerRegistry", "_lock", 64,
             "issuer indexing; called under agg.fold by merge paths"),
    # -- engine / fleet bookkeeping (leaf-ish, metrics inside) -----------
    LockDecl("ingest.engine_update", "ct_mapreduce_tpu/ingest/sync.py",
             "LogSyncEngine", "_last_update_lock", 70,
             "health-surface progress map"),
    LockDecl("ingest.engine_active", "ct_mapreduce_tpu/ingest/sync.py",
             "LogSyncEngine", "_active_lock", 72,
             "live LogWorker registry (checkpoint fan-out)"),
    LockDecl("fleet.service", "ct_mapreduce_tpu/ingest/fleet.py",
             "FleetService", "_lock", 74,
             "claims/partition/errors; released before fabric calls"),
    LockDecl("overlap.exc", "ct_mapreduce_tpu/ingest/overlap.py",
             "OverlapIngestPipeline", "_exc_lock", 76, "first-failure latch"),
    LockDecl("overlap.busy", "ct_mapreduce_tpu/ingest/overlap.py",
             "OverlapIngestPipeline", "_busy_lock", 78,
             "per-stage busy accounting"),
    LockDecl("overlap.highwater", "ct_mapreduce_tpu/ingest/overlap.py",
             "OverlapIngestPipeline", "_hw_lock", 80,
             "queue-depth high-water marks"),
    LockDecl("serve.cache", "ct_mapreduce_tpu/serve/cache.py",
             "HotSerialCache", "_lock", 82, "hot-serial LRU"),
    LockDecl("distrib.store", "ct_mapreduce_tpu/distrib/publish.py",
             "FilterDistributor", "_lock", 83,
             "published epochs + delta chain + compression cache "
             "(checkpoint publishes vs HTTP reads; only telemetry "
             "nests inside)"),
    LockDecl("native.build", "ct_mapreduce_tpu/native/__init__.py",
             None, "_LOCK", 84, "one native build at a time"),
    LockDecl("utils.miniredis", "ct_mapreduce_tpu/utils/miniredis.py",
             "MiniRedis", "_lock", 86,
             "server-side store (own accept threads; never nests "
             "client-side locks)"),
    # -- telemetry (innermost: emitted from under every other lock) ------
    LockDecl("telemetry.flight", "ct_mapreduce_tpu/telemetry/flight.py",
             "FlightRecorder", "_lock", 90, "dump serialization"),
    LockDecl("telemetry.metrics", "ct_mapreduce_tpu/telemetry/metrics.py",
             "InMemSink", "_lock", 92, "sink state; every emit"),
    LockDecl("telemetry.trace", "ct_mapreduce_tpu/telemetry/trace.py",
             "SpanTracer", "_threads_lock", 94, "thread-name registry"),
)

RANKS: dict[str, Optional[int]] = {}
for _d in LOCKS:
    # Same-name redeclarations must agree on rank (one hierarchy node).
    if _d.name in RANKS and RANKS[_d.name] != _d.rank:
        raise ValueError(f"lockspec rank conflict for {_d.name}")
    RANKS[_d.name] = _d.rank


def decl_for(relpath: str, cls: Optional[str],
             attr: str) -> Optional[LockDecl]:
    """Exact declaration for a lock defined at (module, class, attr)."""
    for d in LOCKS:
        if d.attr == attr and d.cls == cls and fnmatch.fnmatch(
                relpath, d.path):
            return d
    return None


_ATTR_NAMES: dict[str, set[str]] = {}
for _d in LOCKS:
    _ATTR_NAMES.setdefault(_d.attr, set()).add(_d.name)


def unique_attr_name(attr: str) -> Optional[str]:
    """Hierarchy name for a lock attribute that is unambiguous across
    the whole spec (e.g. ``_fold_lock``) — how cross-object references
    like ``agg._fold_lock`` resolve. ``_lock`` is ambiguous -> None."""
    names = _ATTR_NAMES.get(attr)
    return next(iter(names)) if names and len(names) == 1 else None


def rank_of(name: str) -> Optional[int]:
    return RANKS.get(name)


# -- creation-site table (runtime witness support) -----------------------

def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
    """'lock' / 'rlock' when ``node`` is a threading.Lock()/RLock()
    (or bare Lock()/RLock()) call, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name == "Lock":
        return "lock"
    if name == "RLock":
        return "rlock"
    return None


def iter_lock_sites(tree: ast.AST, relpath: str):
    """Yield (lineno, cls, attr, kind) for every lock construction
    bound to a ``self.X`` attribute or module-level name."""
    class_stack: list[str] = []

    def walk(node):
        is_cls = isinstance(node, ast.ClassDef)
        if is_cls:
            class_stack.append(node.name)
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            kind = _lock_ctor_kind(value) if value is not None else None
            if kind is not None:
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        yield (value.lineno,
                               class_stack[-1] if class_stack else None,
                               t.attr, kind)
                    elif isinstance(t, ast.Name) and not class_stack:
                        yield value.lineno, None, t.id, kind
        for child in ast.iter_child_nodes(node):
            yield from walk(child)
        if is_cls:
            class_stack.pop()

    yield from walk(tree)


def build_site_table(pkg_root) -> dict[tuple[str, int], tuple[str, int]]:
    """(absolute file path, lineno of the Lock() call) ->
    (hierarchy name, rank) for every DECLARED lock in the package —
    how the runtime witness names a lock from its creation frame.
    Pure AST scan; never imports the scanned modules."""
    pkg_root = pathlib.Path(pkg_root).resolve()
    repo_root = pkg_root.parent
    table: dict[tuple[str, int], tuple[str, int]] = {}
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        relpath = path.relative_to(repo_root).as_posix()
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except (SyntaxError, OSError):
            continue
        for lineno, cls, attr, _kind in iter_lock_sites(tree, relpath):
            d = decl_for(relpath, cls, attr)
            if d is not None and d.rank is not None:
                table[(str(path), lineno)] = (d.name, d.rank)
    return table
