"""Pluggable AST checker framework.

One parse + one walk of every package module per run, no matter how
many rules are active: the engine maintains the traversal context
(enclosing class/function/``with`` stacks, parent links) and
dispatches node events to whichever checkers subscribed to them via
``visit_<NodeType>`` methods — the same shape ``ast.NodeVisitor``
has, minus the per-checker walk.

Intentionally jax-free: the lint gate parses source, it never imports
the modules it checks, so it runs in seconds with no device/XLA
startup and can gate CI before anything heavyweight builds.

Baselines: a finding's :meth:`Finding.key` is stable across line-number
drift (``rule:path:symbol``); the baseline file maps keys to one-line
justifications so known, justified exceptions don't fail the gate —
and unused entries are surfaced so the file can only shrink.
"""

from __future__ import annotations

import ast
import fnmatch
import pathlib
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional


@dataclass
class Finding:
    """One rule violation at one site."""

    rule: str  # rule name, e.g. "lock-order"
    path: str  # repo-relative posix path
    line: int  # 1-based; 0 for whole-file/project findings
    symbol: str  # stable key component (lock name, metric key, ...)
    message: str

    def key(self) -> str:
        """Baseline key: stable across line-number drift."""
        return f"{self.rule}:{self.path}:{self.symbol}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: {self.rule}: {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "key": self.key(),
        }


@dataclass
class Module:
    """Per-file context handed to checkers."""

    path: pathlib.Path  # absolute
    relpath: str  # repo-relative posix
    tree: ast.AST
    source: str

    def matches(self, pattern: str) -> bool:
        return fnmatch.fnmatch(self.relpath, pattern)


@dataclass
class Project:
    """Cross-module context for ``finish()``-time checks."""

    root: pathlib.Path  # the scanned package directory
    repo_root: pathlib.Path  # its parent (docs/, MIGRATING.md live here)
    modules: list[Module] = field(default_factory=list)

    def module(self, relpath: str) -> Optional[Module]:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


class Ctx:
    """Traversal context: where the engine currently is."""

    def __init__(self, module: Module) -> None:
        self.module = module
        self.class_stack: list[ast.ClassDef] = []
        self.func_stack: list[ast.AST] = []  # FunctionDef | AsyncFunctionDef | Lambda
        self.with_stack: list[ast.With] = []
        self._parents: dict[int, ast.AST] = {}

    @property
    def cls(self) -> Optional[str]:
        return self.class_stack[-1].name if self.class_stack else None

    @property
    def func(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))


class Checker:
    """Base class: subclasses define ``name`` and any subset of
    ``visit_<NodeType>(node, ctx)`` / ``begin_module(ctx)`` /
    ``end_module(ctx)`` / ``finish(project)`` and report via
    :meth:`report`."""

    name = "checker"

    def __init__(self) -> None:
        self.findings: list[Finding] = []

    def report(self, relpath: str, line: int, symbol: str,
               message: str) -> None:
        self.findings.append(
            Finding(self.name, relpath, line, symbol, message))

    # Optional hooks (engine calls them when present):
    # begin_module(ctx) / end_module(ctx) / visit_<Type>(node, ctx)
    def finish(self, project: Project) -> None:  # pragma: no cover
        pass


_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class AnalysisEngine:
    """Walks the package once, dispatching node events to checkers."""

    def __init__(self, checkers: Iterable[Checker]) -> None:
        self.checkers = list(checkers)
        # node-type name -> [(checker, bound method)], built lazily so
        # only types someone subscribed to pay dispatch cost.
        self._dispatch: dict[str, list[Callable]] = {}
        for c in self.checkers:
            for attr in dir(c):
                if attr.startswith("visit_"):
                    self._dispatch.setdefault(
                        attr[len("visit_"):], []).append(getattr(c, attr))
        self.errors: list[Finding] = []

    # -- file set --------------------------------------------------------
    @staticmethod
    def package_files(root: pathlib.Path) -> list[pathlib.Path]:
        return sorted(
            p for p in root.rglob("*.py") if "__pycache__" not in p.parts
        )

    def run(self, root: pathlib.Path) -> list[Finding]:
        """Parse + walk every module under ``root``; returns all
        findings (parse failures surface as rule ``parse-error``)."""
        root = root.resolve()
        repo_root = root.parent
        project = Project(root=root, repo_root=repo_root)
        for path in self.package_files(root):
            relpath = path.relative_to(repo_root).as_posix()
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
            except (SyntaxError, UnicodeDecodeError, OSError) as err:
                self.errors.append(Finding(
                    "parse-error", relpath, getattr(err, "lineno", 0) or 0,
                    "parse", f"cannot analyze: {err}"))
                continue
            module = Module(path=path, relpath=relpath, tree=tree,
                            source=source)
            project.modules.append(module)
            self._walk_module(module)
        for c in self.checkers:
            c.finish(project)
        out: list[Finding] = list(self.errors)
        for c in self.checkers:
            out.extend(c.findings)
        out.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
        return out

    # -- traversal -------------------------------------------------------
    def _walk_module(self, module: Module) -> None:
        ctx = Ctx(module)
        for c in self.checkers:
            begin = getattr(c, "begin_module", None)
            if begin is not None:
                begin(ctx)
        self._visit(module.tree, ctx)
        for c in self.checkers:
            end = getattr(c, "end_module", None)
            if end is not None:
                end(ctx)

    def _visit(self, node: ast.AST, ctx: Ctx) -> None:
        handlers = self._dispatch.get(type(node).__name__)
        if handlers is not None:
            for h in handlers:
                h(node, ctx)
        is_class = isinstance(node, ast.ClassDef)
        is_scope = isinstance(node, _SCOPE_TYPES)
        is_with = isinstance(node, ast.With)
        if is_class:
            ctx.class_stack.append(node)
        if is_scope:
            ctx.func_stack.append(node)
        if is_with:
            ctx.with_stack.append(node)
        for child in ast.iter_child_nodes(node):
            ctx._parents[id(child)] = node
            self._visit(child, ctx)
        if is_with:
            ctx.with_stack.pop()
        if is_scope:
            ctx.func_stack.pop()
        if is_class:
            ctx.class_stack.pop()


# -- baseline ------------------------------------------------------------

def load_baseline(path) -> dict[str, str]:
    """``key | justification`` per line; ``#`` comments and blanks
    ignored. A key without a justification is invalid (the whole point
    is forcing the why next to the exception) and raises ValueError."""
    entries: dict[str, str] = {}
    text = pathlib.Path(path).read_text()
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, why = line.partition("|")
        key, why = key.strip(), why.strip()
        if not sep or not why:
            raise ValueError(
                f"{path}:{lineno}: baseline entry needs "
                f"'key | justification', got: {raw!r}")
        entries[key] = why
    return entries


def apply_baseline(
    findings: list[Finding], baseline: dict[str, str]
) -> tuple[list[Finding], list[Finding], list[str]]:
    """Split findings into (live, suppressed) and list baseline keys
    that matched nothing (stale entries — the file must only shrink)."""
    live: list[Finding] = []
    suppressed: list[Finding] = []
    used: set[str] = set()
    for f in findings:
        k = f.key()
        if k in baseline:
            used.add(k)
            suppressed.append(f)
        else:
            live.append(f)
    unused = [k for k in baseline if k not in used]
    return live, suppressed, unused


def default_checkers() -> list[Checker]:
    """The full project rule set (import here, not at module top, so
    ``engine`` stays dependency-free for checker unit tests)."""
    from ct_mapreduce_tpu.analysis.config_parity import ConfigParityChecker
    from ct_mapreduce_tpu.analysis.determinism import DeterminismChecker
    from ct_mapreduce_tpu.analysis.donation import DonationChecker
    from ct_mapreduce_tpu.analysis.jit_purity import JitPurityChecker
    from ct_mapreduce_tpu.analysis.lock_order import LockOrderChecker
    from ct_mapreduce_tpu.analysis.metric_registry import (
        MetricRegistryChecker,
    )
    from ct_mapreduce_tpu.analysis.span_registry import SpanRegistryChecker

    return [
        LockOrderChecker(),
        DonationChecker(),
        DeterminismChecker(),
        JitPurityChecker(),
        MetricRegistryChecker(),
        SpanRegistryChecker(),
        ConfigParityChecker(),
    ]


def run_analysis(
    root,
    checkers: Optional[Iterable[Checker]] = None,
    baseline_path=None,
) -> tuple[list[Finding], list[Finding], list[str]]:
    """Convenience wrapper: run the engine over ``root`` and apply the
    baseline. Returns (live findings, suppressed findings, unused
    baseline keys)."""
    engine = AnalysisEngine(
        default_checkers() if checkers is None else checkers)
    findings = engine.run(pathlib.Path(root))
    baseline = load_baseline(baseline_path) if baseline_path else {}
    return apply_baseline(findings, baseline)
