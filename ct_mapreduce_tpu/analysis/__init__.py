"""Project-invariant static analysis (`ctmrlint`) + runtime lock-order
witness.

The package's correctness contracts — the lock hierarchy (fold →
table, dispatch serializes the donated device stream), donation
discipline (a buffer passed to a ``*_donated`` entry point is dead),
byte-determinism of filter/checkpoint serialization, and jit-body
purity — lived in comments until round 16. This subpackage turns them
into machine-checked gates:

- :mod:`.engine` — pluggable AST checker framework: one walk of the
  package per run, checkers subscribe to node events; baseline file
  for justified exceptions.
- :mod:`.lockspec` — the DECLARED lock hierarchy (every lock in the
  package, with a rank in the partial order) shared by the static
  lock-order rule and the runtime witness.
- :mod:`.lock_order`, :mod:`.donation`, :mod:`.determinism`,
  :mod:`.jit_purity`, :mod:`.metric_registry`, :mod:`.config_parity`
  — the project-specific rules.
- :mod:`.witness` — instrumented lock wrapper (opt-in via
  ``CTMR_LOCK_WITNESS=1``) recording per-thread acquisition chains
  into a global edge graph; detects order violations and cycles live
  and dumps findings through the flight recorder.
- :mod:`.cli` — the ``ctmrlint`` console script (text/JSON, exit
  codes 0/1/2).

Nothing here imports jax (or any device code): the lint lane must run
in CI in seconds, and the witness must be installable before the
heavyweight imports it observes.
"""

from ct_mapreduce_tpu.analysis.engine import (  # noqa: F401
    AnalysisEngine,
    Checker,
    Finding,
    load_baseline,
    run_analysis,
)

__all__ = [
    "AnalysisEngine",
    "Checker",
    "Finding",
    "load_baseline",
    "run_analysis",
]
