"""jit-purity: no host side effects inside traced bodies.

A ``jax.jit``-ed function (or a ``fori_loop``/``scan``/``while_loop``
carrier) runs its Python body ONCE, at trace time, per compile-cache
shape. A metric emit, span, print, or lock acquisition inside one is
wrong twice over: it fires on compiles rather than executions (so the
telemetry lies), and under the persistent compile cache it may never
fire at all. Lock use at trace time is worse — the traced body can be
re-entered under different callers' locks, deadlocking on compile.

Detection is name-based and module-local: functions decorated with
``jit``/``jax.jit``/``partial(jax.jit, ...)``, functions wrapped via
``X = jax.jit(f)`` / ``functools.partial(jax.jit, ...)(f)``, and
local defs passed by name (or lambdas passed inline) to
``fori_loop``/``scan``/``while_loop``/``cond``/``switch``.
"""

from __future__ import annotations

import ast
from typing import Optional

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx

_LOOP_FUNCS = {"fori_loop", "scan", "while_loop", "cond", "switch"}
_METRIC_FUNCS = {"incr_counter", "set_gauge", "add_sample", "measure"}
_IMPURE_CALL_TAILS = {
    "print": "print at trace time (fires per compile, not per step)",
    "span": "span at trace time (telemetry would count compiles)",
}
_IMPURE_CHAINS = {
    ("time", "time"): "wall-clock at trace time",
    ("time", "monotonic"): "wall-clock at trace time",
    ("datetime", "now"): "wall-clock at trace time",
}


def _attr_chain(expr: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    parts.reverse()
    return parts


def _is_jit_expr(expr: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)`` /
    ``functools.partial(jax.jit, ...)``."""
    chain = _attr_chain(expr)
    if chain and chain[-1] == "jit":
        return True
    if isinstance(expr, ast.Call):
        c = _attr_chain(expr.func)
        if c and c[-1] == "partial":
            return any(_is_jit_expr(a) for a in expr.args)
    return False


class JitPurityChecker(Checker):
    name = "jit-purity"

    def begin_module(self, ctx: Ctx) -> None:
        self._defs: dict[str, list[ast.AST]] = {}
        self._jit_names: set[str] = set()
        self._inline_bodies: list[ast.AST] = []

    def _def_decorated_jit(self, node) -> bool:
        return any(_is_jit_expr(d) for d in node.decorator_list)

    def visit_FunctionDef(self, node: ast.FunctionDef, ctx: Ctx) -> None:
        self._defs.setdefault(node.name, []).append(node)
        if self._def_decorated_jit(node):
            self._jit_names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign, ctx: Ctx) -> None:
        # X = jax.jit(f)  /  X = functools.partial(jax.jit, ...)(f)
        v = node.value
        if not isinstance(v, ast.Call):
            return
        if _is_jit_expr(v.func):
            for a in v.args:
                if isinstance(a, ast.Name):
                    self._jit_names.add(a.id)

    def visit_Call(self, node: ast.Call, ctx: Ctx) -> None:
        chain = _attr_chain(node.func)
        if not chain or chain[-1] not in _LOOP_FUNCS:
            return
        for a in node.args:
            if isinstance(a, ast.Name):
                self._jit_names.add(a.id)
            elif isinstance(a, ast.Lambda):
                self._inline_bodies.append(a)

    # -- per-module evaluation -------------------------------------------
    def end_module(self, ctx: Ctx) -> None:
        bodies: list[tuple[str, ast.AST]] = []
        for name in sorted(self._jit_names):
            for node in self._defs.get(name, ()):
                bodies.append((name, node))
        for lam in self._inline_bodies:
            bodies.append(("<lambda>", lam))
        seen: set[int] = set()
        for name, node in bodies:
            if id(node) in seen:
                continue
            seen.add(id(node))
            self._check_body(name, node, ctx)

    def _impurity(self, node: ast.AST) -> Optional[tuple[str, str]]:
        """(symbol-suffix, message) for an impure node."""
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if not chain:
                return None
            tail = chain[-1]
            if tail in _METRIC_FUNCS:
                return (f"metric:{tail}",
                        f"metric emit {tail}() inside a jitted body — "
                        f"fires per compile, not per execution")
            if tail in _IMPURE_CALL_TAILS:
                return f"{tail}", (f"{'.'.join(chain)}() inside a jitted "
                                   f"body: {_IMPURE_CALL_TAILS[tail]}")
            if len(chain) >= 2 and (chain[-2], tail) in _IMPURE_CHAINS:
                return (f"clock:{'.'.join(chain)}",
                        f"{'.'.join(chain)}() inside a jitted body: "
                        f"{_IMPURE_CHAINS[(chain[-2], tail)]}")
        if isinstance(node, ast.With):
            for item in node.items:
                chain = _attr_chain(item.context_expr)
                tail = chain[-1] if chain else ""
                if "lock" in tail.lower():
                    return (f"lock:{tail}",
                            f"lock {'.'.join(chain)} acquired inside a "
                            f"jitted body — trace-time locking can "
                            f"deadlock a compile under callers' locks")
        return None

    def _check_body(self, name: str, fn: ast.AST, ctx: Ctx) -> None:
        for node in ast.walk(fn):
            hit = self._impurity(node)
            if hit is None:
                continue
            suffix, message = hit
            self.report(ctx.module.relpath, node.lineno,
                        f"{name}:{suffix}", f"{name}: {message}")
