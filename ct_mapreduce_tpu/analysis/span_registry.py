"""span-registry: every span name handed to the tracer is documented,
every documented span name has a call site.

The round-23 generalization of the ``metric-registry`` rule to the
tracer surface: collect every ``span(...)``/``instant(...)`` call site
in the package (literal first argument becomes the name, a dynamic one
becomes ``*``) and diff against the backtick-quoted bullets of the
``## Trace spans`` sections in ``docs/METRICS.md`` — the same file,
split by section so span names and metric keys each get exactly one
registry. Wildcards match both directions, same as metric keys:
``tools/traceview.py --merge`` timelines and the bench occupancy legs
key on these names, so an undocumented span is dashboard drift just
like an undocumented counter.
"""

from __future__ import annotations

import ast

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx, Project
from ct_mapreduce_tpu.analysis.metric_registry import (
    DOC_RELPATH,
    bullet_keys,
    key_matches,
)

EMIT_FUNCS = {"span", "instant"}
# The tracer API itself, not a call site.
EXCLUDE_MODULES = ("ct_mapreduce_tpu/telemetry/trace.py",)


def documented_spans(doc_text: str) -> set[str]:
    """Backtick-quoted names from the ``## Trace spans`` sections."""
    return bullet_keys(doc_text, span_sections=True)


class SpanRegistryChecker(Checker):
    name = "span-registry"

    def __init__(self) -> None:
        super().__init__()
        # span name -> ["path:line", ...]
        self.call_sites: dict[str, list[str]] = {}

    def visit_Call(self, node: ast.Call, ctx: Ctx) -> None:
        if ctx.module.relpath in EXCLUDE_MODULES:
            return
        fn = node.func
        name = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None)
        if name not in EMIT_FUNCS or not node.args:
            return
        arg = node.args[0]
        span_name = (arg.value
                     if isinstance(arg, ast.Constant)
                     and isinstance(arg.value, str)
                     else "*")
        where = f"{ctx.module.relpath}:{node.lineno}"
        self.call_sites.setdefault(span_name, []).append(where)

    def finish(self, project: Project) -> None:
        doc_path = project.repo_root / DOC_RELPATH
        if not doc_path.exists():
            self.report(DOC_RELPATH, 0, "missing",
                        "docs/METRICS.md not found — the span-name "
                        "registry shares the metric registry file")
            return
        docs = documented_spans(doc_path.read_text())
        if not docs:
            self.report(DOC_RELPATH, 0, "empty",
                        "docs/METRICS.md has no `## Trace spans` "
                        "bullets — section renamed?")
            return
        for name, sites in sorted(self.call_sites.items()):
            if not any(key_matches(name, d) for d in docs):
                path, _, line = sites[0].rpartition(":")
                self.report(
                    path, int(line), name,
                    f"span name `{name}` traced ({', '.join(sites)}) "
                    f"but missing from the `## Trace spans` sections "
                    f"of docs/METRICS.md — timelines and occupancy "
                    f"tooling key on these names")
        for d in sorted(docs):
            if not any(key_matches(name, d) for name in self.call_sites):
                self.report(
                    DOC_RELPATH, 0, f"stale:{d}",
                    f"docs/METRICS.md lists span `{d}` but no call "
                    f"site traces it — deleting a span must update "
                    f"the registry too")
