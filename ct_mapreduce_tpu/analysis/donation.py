"""donation-safety: use-after-donate is a bug even when CPU hides it.

A buffer passed at a donated position of a ``*_donated`` jit entry
point (``ops/pipeline.py``'s ``donate_argnums``) is dead the moment
the call dispatches: XLA may alias its memory into the step's outputs
on real devices. CPU's XLA cannot alias these layouts and silently
falls back to copies — which is exactly why a use-after-donate
survives the whole CPU test tier and detonates on hardware. This rule
flags any read of a binding after it was passed at a donated position,
unless the binding was reassigned first (the canonical
``self.table, out = step(self.table, ...)`` idiom reassigns in the
same statement and is safe).

Donating callables are recognized by name (``*_donated``), including
locals aliased from them — the aggregator's backend-conditional
``step = (pipeline.ingest_step_staged_donated if ... else
pipeline.ingest_step_staged)`` donates on real devices, so the alias
is treated as donating (the conservative branch is the one that
bites). Donated positions come from :data:`DONATED_ARGNUMS`; unknown
``*_donated`` names default to position 0 (the table-first
convention).
"""

from __future__ import annotations

import ast
from typing import Optional

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx

# Mirrors the donate_argnums of ops/pipeline.py's entry points. A new
# *_donated entry point not listed here is checked at position 0 only;
# list it to widen coverage.
DONATED_ARGNUMS: dict[str, tuple[int, ...]] = {
    "ingest_step_donated": (0, 1),
    "ingest_step_preparsed_donated": (0,),
    "ingest_step_staged_donated": (0, 1),
}
DEFAULT_ARGNUMS: tuple[int, ...] = (0,)


def _tail_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _donated_names_in(expr: ast.AST) -> list[str]:
    """Every ``*_donated`` name referenced anywhere in ``expr``."""
    out = []
    for node in ast.walk(expr):
        n = _tail_name(node)
        if n is not None and n.endswith("_donated"):
            out.append(n)
    return out


def _binding_key(expr: ast.AST) -> Optional[str]:
    """Trackable binding: a plain name or a ``self.X`` attribute."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name) and expr.value.id == "self":
        return f"self.{expr.attr}"
    return None


def _assigned_keys(target: ast.AST) -> set[str]:
    keys: set[str] = set()
    for node in ast.walk(target):
        k = _binding_key(node)
        if k is not None:
            keys.add(k)
    return keys


class DonationChecker(Checker):
    name = "donation-safety"

    def visit_FunctionDef(self, node: ast.FunctionDef, ctx: Ctx) -> None:
        self._check_function(node, ctx)

    def visit_AsyncFunctionDef(self, node, ctx: Ctx) -> None:
        self._check_function(node, ctx)

    def _check_function(self, fn, ctx: Ctx) -> None:
        # Local donating aliases: X = <expr referencing *_donated>.
        aliases: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and node.value is not None:
                donated = _donated_names_in(node.value)
                if not donated:
                    continue
                argnums: set[int] = set()
                for d in donated:
                    argnums.update(DONATED_ARGNUMS.get(d, DEFAULT_ARGNUMS))
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = tuple(sorted(argnums))

        # Reassignment and loop structure for the exemptions below.
        assigns: list[tuple[int, set[str]]] = []  # (line, keys)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                keys = set()
                for t in node.targets:
                    keys |= _assigned_keys(t)
                assigns.append((node.lineno, keys))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                assigns.append((node.lineno, _assigned_keys(node.target)))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                assigns.append((node.lineno, _assigned_keys(node.target)))

        loops = [n for n in ast.walk(fn)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]

        def loop_of(lineno: int):
            best = None
            for lp in loops:
                end = getattr(lp, "end_lineno", lp.lineno)
                if lp.lineno <= lineno <= end:
                    if best is None or lp.lineno > best.lineno:
                        best = lp  # innermost
            return best

        def reassigned_between(key: str, a: int, b: int) -> bool:
            return any(a < line <= b and key in keys
                       for line, keys in assigns)

        # Donating calls and their donated bindings.
        # (call line, call end line, key, callee)
        donations: list[tuple[int, int, str, str]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = _tail_name(node.func)
            if callee is None:
                continue
            if callee.endswith("_donated"):
                argnums = DONATED_ARGNUMS.get(callee, DEFAULT_ARGNUMS)
            elif callee in aliases:
                argnums = aliases[callee]
            else:
                continue
            end = getattr(node, "end_lineno", node.lineno)
            for i in argnums:
                if i < len(node.args):
                    key = _binding_key(node.args[i])
                    if key is not None:
                        donations.append((node.lineno, end, key, callee))

        if not donations:
            return

        relpath = ctx.module.relpath
        for call_line, call_end, key, callee in donations:
            # Reassigned in the very statement of the call (the
            # `self.table, out = step(self.table, ...)` idiom).
            if any(line == call_line and key in keys
                   for line, keys in assigns):
                continue
            call_loop = loop_of(call_line)
            if call_loop is not None:
                # Donation inside a loop whose body refreshes the
                # binding each iteration: textual order lies about
                # execution order; skip if any reassignment lives in
                # the same loop.
                end = getattr(call_loop, "end_lineno", call_loop.lineno)
                if any(call_loop.lineno <= line <= end and key in keys
                       for line, keys in assigns):
                    continue
            for node in ast.walk(fn):
                if node.__class__ is ast.Name:
                    if not (isinstance(node.ctx, ast.Load)
                            and node.id == key):
                        continue
                elif node.__class__ is ast.Attribute:
                    if not (isinstance(node.ctx, ast.Load)
                            and _binding_key(node) == key):
                        continue
                else:
                    continue
                read_line = node.lineno
                if read_line <= call_end:
                    continue  # the donating call's own argument lines
                if reassigned_between(key, call_line, read_line):
                    continue
                self.report(
                    relpath, read_line,
                    f"{fn.name}:{key}",
                    f"{key} read after being donated to {callee} "
                    f"(line {call_line}) without reassignment — "
                    f"use-after-donate aliases freed device memory "
                    f"on real hardware")
                break  # one finding per donation is enough
