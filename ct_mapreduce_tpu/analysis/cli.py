"""``ctmrlint`` — the project-invariant linter CLI.

Exit codes (scripting contract, pinned by tests/test_lint.py):
  0  clean (no non-baselined findings)
  1  violations found
  2  internal error / bad invocation

Never imports jax: an AST-only pass over the package in seconds.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from ct_mapreduce_tpu.analysis import engine as _engine

DEFAULT_BASELINE = "ctmrlint.baseline"


def find_default_baseline(root: pathlib.Path):
    """``ctmrlint.baseline`` next to the scanned package (repo root)."""
    candidate = root.resolve().parent / DEFAULT_BASELINE
    return candidate if candidate.exists() else None


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ctmrlint",
        description="ct-mapreduce-tpu project-invariant static analysis")
    p.add_argument("root", nargs="?", default="ct_mapreduce_tpu",
                   help="package directory to analyze "
                        "(default: ct_mapreduce_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file of justified exceptions "
                        f"(default: <root>/../{DEFAULT_BASELINE} when "
                        f"present; 'none' disables)")
    p.add_argument("--rules", default="",
                   help="comma-separated rule names to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule set and exit")
    return p


def main(argv=None) -> int:
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as err:  # argparse exits 2 on bad usage already
        return int(err.code or 0)
    try:
        checkers = _engine.default_checkers()
        if args.list_rules:
            for c in checkers:
                print(c.name)
            return 0
        if args.rules:
            wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
            unknown = wanted - {c.name for c in checkers}
            if unknown:
                print(f"ctmrlint: unknown rule(s): "
                      f"{', '.join(sorted(unknown))}", file=sys.stderr)
                return 2
            checkers = [c for c in checkers if c.name in wanted]
        root = pathlib.Path(args.root)
        if not root.is_dir():
            print(f"ctmrlint: not a directory: {root}", file=sys.stderr)
            return 2
        if args.baseline == "none":
            baseline_path = None
        elif args.baseline:
            baseline_path = pathlib.Path(args.baseline)
            if not baseline_path.exists():
                print(f"ctmrlint: baseline not found: {baseline_path}",
                      file=sys.stderr)
                return 2
        else:
            baseline_path = find_default_baseline(root)
        live, suppressed, unused = _engine.run_analysis(
            root, checkers=checkers, baseline_path=baseline_path)
        # A baseline entry for a rule that did not run this invocation
        # is not stale — it just wasn't exercised (--rules filtering).
        ran = {c.name for c in checkers}
        unused = [k for k in unused if k.split(":", 1)[0] in ran]
    except Exception as err:  # the tool must never die silently
        print(f"ctmrlint: error: {type(err).__name__}: {err}",
              file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in live],
            "suppressed": [f.to_dict() for f in suppressed],
            "unused_baseline": unused,
            "counts": {
                "findings": len(live),
                "suppressed": len(suppressed),
                "unused_baseline": len(unused),
            },
        }, indent=2))
    else:
        for f in live:
            print(f.render())
        if suppressed:
            print(f"ctmrlint: {len(suppressed)} baselined finding(s) "
                  f"suppressed")
        for k in unused:
            print(f"ctmrlint: warning: stale baseline entry (matched "
                  f"nothing): {k}")
        if not live:
            print("ctmrlint: clean")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
