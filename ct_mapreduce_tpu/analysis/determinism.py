"""determinism: serialized bytes may not depend on wall-clock, RNG, or
hash order.

The fleet-vs-serial byte-identity contract (docs/FILTER_FORMAT.md, the
round-15 merged-filter gate, checkpoint byte-parity when features are
off) holds only because every serialization path iterates in sorted
order and never reads a clock. This rule pins that: inside the scoped
modules/functions it flags

- wall-clock reads (``time.time``/``monotonic``/``strftime``,
  ``datetime.now``/``utcnow``),
- randomness (``random.*``, ``np.random.*``, ``os.urandom``,
  ``uuid.*``),
- iteration over ``.keys()``/``.values()``/``.items()`` or ``set()``
  results that is not wrapped in ``sorted(...)`` — dict/set order is
  insertion/hash order, which differs between a fleet merge and a
  serial run even when the contents are equal.

Scope is declared data (:data:`SCOPE_MODULES`,
:data:`SCOPE_FUNCTIONS`): the rule is for byte-producing paths, not a
style ban on clocks.
"""

from __future__ import annotations

import ast
from typing import Optional

from ct_mapreduce_tpu.analysis.engine import Checker, Ctx

# Whole modules whose job is producing deterministic bytes.
SCOPE_MODULES: tuple[str, ...] = (
    "ct_mapreduce_tpu/filter/artifact.py",
    "ct_mapreduce_tpu/filter/cascade.py",
    "ct_mapreduce_tpu/agg/merge.py",
    # Distribution plane (round 18): delta and container bytes must be
    # byte-identical on every worker of a fleet — their ETags ARE
    # their SHA-256, so a nondeterministic byte breaks conditional
    # GET fleet-wide. (distrib/publish.py is intentionally out of
    # scope: Last-Modified wall stamps are header state, not bytes.)
    "ct_mapreduce_tpu/distrib/delta.py",
    "ct_mapreduce_tpu/distrib/container.py",
    # Round 19 — the scaled build path: streamed key production, the
    # fused multi-group layer dispatcher, and the capture spill ring
    # all feed artifact bytes; none may read a clock or iterate in
    # hash order.
    "ct_mapreduce_tpu/filter/stream.py",
    "ct_mapreduce_tpu/filter/fused.py",
    "ct_mapreduce_tpu/filter/spill.py",
    # Round 20 — the dirty-group build cache decides which groups are
    # rebuilt vs reused verbatim; a hash-order walk here would make
    # "identical corpus" produce different artifact bytes per process.
    "ct_mapreduce_tpu/filter/cache.py",
    # Round 22 — CTMRCK02 segment/manifest bytes are content-hashed
    # into a chain (targetSha256 per link); a nondeterministic byte
    # breaks tip continuation across a restart.
    "ct_mapreduce_tpu/agg/ckpt.py",
    # Round 24 — quarantine spool records are content-addressed
    # (<sha256[:24]>.json) and replay feeds the differential harness;
    # a clock or hash-order byte would break the spool's dedup-by-
    # content contract and the replayed-vs-dropped identity test.
    "ct_mapreduce_tpu/audit/quarantine.py",
)

# (module pattern, function name): serialization paths inside
# otherwise-unscoped modules.
SCOPE_FUNCTIONS: tuple[tuple[str, str], ...] = (
    ("ct_mapreduce_tpu/agg/aggregator.py", "save_checkpoint"),
    ("ct_mapreduce_tpu/agg/aggregator.py", "_write_npz"),
    ("ct_mapreduce_tpu/agg/aggregator.py", "_save_full"),
    ("ct_mapreduce_tpu/agg/aggregator.py", "_save_segment"),
    ("ct_mapreduce_tpu/agg/aggregator.py", "_ckpt_segment_blob"),
    # Round 24 — the checked-in recorded-shard fixture must be
    # byte-stable across regenerations (mtime=0, sorted keys).
    ("ct_mapreduce_tpu/audit/driver.py", "write_recorded"),
)

_WALL_CLOCK = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "strftime"),
    ("datetime", "now"), ("datetime", "utcnow"), ("date", "today"),
}
_RANDOM_ROOTS = {"random", "uuid"}


def _attr_chain(expr: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    parts.reverse()
    return parts


class DeterminismChecker(Checker):
    name = "determinism"

    def _scoped(self, ctx: Ctx) -> bool:
        relpath = ctx.module.relpath
        if any(ctx.module.matches(p) for p in SCOPE_MODULES):
            return True
        import fnmatch
        for fn in ctx.func_stack:
            fname = getattr(fn, "name", None)
            if fname is None:
                continue
            for pat, scoped_fn in SCOPE_FUNCTIONS:
                if fname == scoped_fn and fnmatch.fnmatch(relpath, pat):
                    return True
        return False

    def _func_label(self, ctx: Ctx) -> str:
        for fn in reversed(ctx.func_stack):
            name = getattr(fn, "name", None)
            if name is not None:
                return name
        return "module"

    def visit_Call(self, node: ast.Call, ctx: Ctx) -> None:
        if not self._scoped(ctx):
            return
        chain = _attr_chain(node.func)
        if len(chain) < 2:
            return
        root, leaf = chain[0], chain[-1]
        pair = (chain[-2], leaf)
        relpath = ctx.module.relpath
        label = self._func_label(ctx)
        if pair in _WALL_CLOCK:
            self.report(
                relpath, node.lineno,
                f"{label}:clock:{'.'.join(chain)}",
                f"wall-clock read {'.'.join(chain)}() in a "
                f"serialization path — bytes must not depend on when "
                f"they were produced")
        elif root in _RANDOM_ROOTS or (
                root in ("np", "numpy") and "random" in chain):
            self.report(
                relpath, node.lineno,
                f"{label}:random:{'.'.join(chain)}",
                f"randomness {'.'.join(chain)}() in a serialization "
                f"path — bytes must be a pure function of the inputs")
        elif (chain[-2], leaf) == ("os", "urandom"):
            self.report(
                relpath, node.lineno, f"{label}:random:os.urandom",
                "os.urandom in a serialization path")

    # Wrapping calls for which iteration order cannot reach the output
    # bytes: full sorts and commutative/associative reductions.
    _ORDER_FREE_WRAPPERS = {"sorted", "sum", "min", "max", "any", "all",
                            "len", "set", "frozenset"}

    def _order_free_context(self, node: ast.AST, ctx: Ctx) -> bool:
        parent = ctx.parent(node)
        if isinstance(parent, ast.Call) and isinstance(
                parent.func, ast.Name):
            return parent.func.id in self._ORDER_FREE_WRAPPERS
        return False

    def _check_iter(self, iter_node: ast.AST, lineno: int,
                    ctx: Ctx) -> None:
        """Flag unsorted dict-view/set iteration feeding the loop."""
        bad: Optional[str] = None
        if isinstance(iter_node, ast.Call):
            fn = iter_node.func
            if isinstance(fn, ast.Attribute) and fn.attr in (
                    "keys", "values", "items"):
                bad = f".{fn.attr}()"
            elif isinstance(fn, ast.Name) and fn.id in ("set", "frozenset"):
                bad = f"{fn.id}()"
        elif isinstance(iter_node, ast.Set):
            bad = "set literal"
        if bad is None:
            return
        self.report(
            ctx.module.relpath, lineno,
            f"{self._func_label(ctx)}:unsorted:{bad}",
            f"iterating {bad} without sorted(...) in a serialization "
            f"path — hash/insertion order is not deterministic across "
            f"fleet merge vs serial runs")

    def visit_For(self, node: ast.For, ctx: Ctx) -> None:
        if self._scoped(ctx):
            self._check_iter(node.iter, node.lineno, ctx)

    def _comp(self, node, ctx: Ctx) -> None:
        if self._scoped(ctx) and not self._order_free_context(node, ctx):
            for gen in node.generators:
                self._check_iter(gen.iter, node.lineno, ctx)

    visit_ListComp = _comp
    visit_SetComp = _comp
    visit_GeneratorExp = _comp
    visit_DictComp = _comp
