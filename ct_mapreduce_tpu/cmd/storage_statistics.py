"""storage-statistics: the reduce-side report.

Reference (/root/reference/cmd/storage-statistics/storage-statistics.go:22-100):
enumerate issuers×dates from the cache keyspace, print per-issuer serial
counts, CRL counts, DN counts, overall totals, then per-log checkpoint
states. Verbosity tiers: -v 1 adds per-expDate serial counts, -v 2 adds
the serial list, -v 3 dumps PEMs from the backend.

``--backend=tpu`` (BASELINE.json's north star) drains the on-device
aggregate snapshot written by ``ct-fetch`` (``aggStatePath``) instead
of walking a Redis keyspace — same report, no per-key round trips.
"""

from __future__ import annotations

import sys
from typing import Optional

from ct_mapreduce_tpu.config import CTConfig
from ct_mapreduce_tpu.engine import get_configured_storage, prepare_telemetry


def _load_tpu_aggregate(config: CTConfig):
    """``aggStatePath`` → an aggregate view, or None when nothing is
    there. One path loads the host-only snapshot reader; several
    (comma list and/or glob — a fleet's per-worker ``agg.w*.npz``
    checkpoints, ingest/fleet.py) fold into a
    :class:`~ct_mapreduce_tpu.agg.merge.MergedAggregate`, so one
    storage-statistics run reports the whole fleet."""
    import os

    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.agg.aggregator import HostSnapshotAggregator

    paths = merge.expand_state_paths(config.agg_state_path)
    if not paths or any(not os.path.exists(p) for p in paths):
        return None
    if len(paths) == 1:
        agg = HostSnapshotAggregator(capacity=1 << 10)
        agg.load_checkpoint(paths[0])
        return agg
    return merge.load_checkpoints(paths)


def report_from_tpu_snapshot(config: CTConfig, out, verbosity: int = 0) -> int:
    """Drain path: aggregate snapshot → the same report shape.

    Verbosity parity with the database walk
    (/root/reference/cmd/storage-statistics/storage-statistics.go:28-99):
    -v 1 per-expDate counts; -v 2 additionally lists the serials that
    exist host-side — the exact host-lane serials carried in the
    snapshot, plus the PEM-tree filenames when ``certPath`` was set
    (the tree is keyed ``<exp>/<issuer>/<serialID>``,
    /root/reference/storage/localdiskbackend.go:194-199); -v 3 dumps
    those PEMs. Device-lane serials live in the dedup table as
    128-bit fingerprints + packed (issuer, hour) meta — count-exact
    but not serial-listable BY DESIGN (SURVEY §7 layer 2c); without a
    certPath tree they are reported as counts only.
    """
    from ct_mapreduce_tpu.core.types import ExpDate, Serial

    # Host-only snapshot reader: the report is pure host work, so it
    # must not allocate device buffers or wait on TPU acquisition
    # (reports must stay runnable during pool outages). A multi-path
    # aggStatePath (fleet) folds per-worker checkpoints into one view.
    agg = _load_tpu_aggregate(config)
    if agg is None:
        print(
            f"error: aggStatePath not found: {config.agg_state_path!r} "
            "(run ct-fetch with backend=tpu first)",
            file=out,
        )
        return 1
    snap = agg.drain()

    backend = None
    if config.cert_path:
        from ct_mapreduce_tpu.storage.localdisk import LocalDiskBackend

        backend = LocalDiskBackend(config.cert_path)

    # Regroup (issuer, expdate) → issuer.
    by_issuer: dict[str, dict[str, int]] = {}
    for (iss, exp), count in snap.counts.items():
        by_issuer.setdefault(iss, {})[exp] = count

    # Host-lane serial lists by (issuerID, expDateID): the exact-lane
    # component of each count, listable without any backend. Only built
    # when the verbosity will print it — the default report must not
    # pay an O(n log n) sort over millions of host-lane serials.
    host_lists: dict[tuple[str, str], list] = {}
    if verbosity >= 2:
        for (idx, eh), serials in agg.host_serials.items():
            if not serials:
                continue
            key = (agg.registry.issuer_at(idx).id(),
                   ExpDate.from_unix_hour(eh).id())
            host_lists[key] = sorted((Serial(s) for s in serials),
                                     key=lambda s: s.id())

    def listable_serials(iss: str, exp: str):
        """Serial objects visible host-side for one (issuer, expDate):
        host-lane snapshot serials + PEM-tree entries (deduped)."""
        merged = {s.id(): s for s in host_lists.get((iss, exp), [])}
        if backend is not None:
            idx = agg.registry.index_of_issuer_id(iss)
            if idx is not None:
                exp_date = ExpDate.parse(exp)
                for s in backend.list_serials_for_expiration_date_and_issuer(
                    exp_date, agg.registry.issuer_at(idx)
                ):
                    merged.setdefault(s.id(), s)
        return [merged[k] for k in sorted(merged)]

    total_serials = 0
    total_crls = 0
    for iss in snap.issuers():
        dates = by_issuer.get(iss, {})
        crls = sorted(snap.crls.get(iss, ()))
        dns = sorted(snap.dns.get(iss, ()))
        total_crls += len(crls)
        issuer_serials = sum(dates.values())
        total_serials += issuer_serials
        print(f"Issuer: {iss} ({dns})", file=out)
        idx = agg.registry.index_of_issuer_id(iss) if verbosity >= 2 else None
        for exp in sorted(dates):
            if verbosity >= 1:
                print(f"- {exp} ({dates[exp]} serials)", file=out)
            if verbosity >= 2:
                serial_objs = listable_serials(iss, exp)
                print(f"  Serials: {[s.id() for s in serial_objs]}", file=out)
                if len(serial_objs) < dates[exp]:
                    print(
                        f"  ({dates[exp] - len(serial_objs)} device-lane "
                        "serials are count-only; set certPath during "
                        "ct-fetch to retain listable PEMs)",
                        file=out,
                    )
                if verbosity >= 3:
                    exp_date = ExpDate.parse(exp)
                    for serial in serial_objs:
                        print(
                            f"Certificate serial={{{serial.hex_string()}}} / "
                            f"{{{serial.id()}}}",
                            file=out,
                        )
                        if backend is None or idx is None:
                            continue
                        try:
                            pem = backend.load_certificate_pem(
                                serial, exp_date,
                                agg.registry.issuer_at(idx),
                            )
                            out.write(pem if isinstance(pem, str)
                                      else pem.decode())
                        except Exception as err:
                            print(f"error: {err}", file=out)
        tail = ""
        if iss in snap.verified or iss in snap.failed:
            tail = (f", {snap.verified.get(iss, 0)} scts verified, "
                    f"{snap.failed.get(iss, 0)} scts failed")
        print(
            f" --> {len(dates)} hours, {issuer_serials} serials known, "
            f"{len(crls)} crls known, {len(dns)} issuerDNs known{tail}",
            file=out,
        )
    verify_tail = ""
    if snap.verified or snap.failed:
        verify_tail = (f", {sum(snap.verified.values())} scts verified, "
                       f"{sum(snap.failed.values())} scts failed")
    print(
        f"overall totals: {len(snap.issuers())} issuers, "
        f"{total_serials} serials, {total_crls} crls{verify_tail}",
        file=out,
    )
    # Per-log checkpoint states print in TPU mode too: ct-fetch
    # dual-writes the cursor through the same database facade
    # regardless of backend, so the walk is identical to database mode
    # (storage-statistics.go:86-98).
    database, _cache, _backend = get_configured_storage(config)
    print_log_status(config, database, out)
    return 0


def print_log_status(config: CTConfig, database, out) -> None:
    """The "Log status:" section, shared by both report paths
    (/root/reference/cmd/storage-statistics/storage-statistics.go:86-98).

    Headers print unconditionally; the URL walk is gated on the
    reference's string-length quirk (:86-90).
    """
    from ct_mapreduce_tpu.ingest.ctclient import short_url

    print("", file=out)
    print("Log status:", file=out)
    if config.log_url_list and len(config.log_url_list) > 5:
        for url in config.log_urls():
            state = database.get_log_state(short_url(url))
            print(str(state), file=out)


def _log_status_lines(config: CTConfig, database) -> list[str]:
    """The "Log status:" walk as data (shared by text and JSON modes;
    same string-length gate as the reference, :86-90)."""
    from ct_mapreduce_tpu.ingest.ctclient import short_url

    lines = []
    if config.log_url_list and len(config.log_url_list) > 5:
        for url in config.log_urls():
            lines.append(str(database.get_log_state(short_url(url))))
    return lines


def collect_tpu_report(config: CTConfig) -> Optional[dict]:
    """Machine-readable form of :func:`report_from_tpu_snapshot` —
    the same drain, the same numbers, as a JSON-serializable dict
    (text/JSON parity is pinned by tests/test_cmd.py). Returns None
    when the snapshot is missing (the text path's error case)."""
    agg = _load_tpu_aggregate(config)
    if agg is None:
        return None
    snap = agg.drain()

    by_issuer: dict[str, dict[str, int]] = {}
    for (iss, exp), count in snap.counts.items():
        by_issuer.setdefault(iss, {})[exp] = count

    issuers = []
    total_serials = 0
    total_crls = 0
    for iss in snap.issuers():
        dates = by_issuer.get(iss, {})
        crls = sorted(snap.crls.get(iss, ()))
        dns = sorted(snap.dns.get(iss, ()))
        n = sum(dates.values())
        total_serials += n
        total_crls += len(crls)
        row = {
            "id": iss,
            "dns": dns,
            "crls": crls,
            "serials": n,
            "expDates": {exp: dates[exp] for exp in sorted(dates)},
        }
        if iss in snap.verified or iss in snap.failed:
            row["sctsVerified"] = snap.verified.get(iss, 0)
            row["sctsFailed"] = snap.failed.get(iss, 0)
        issuers.append(row)
    database, _cache, _backend = get_configured_storage(config)
    totals = {
        "issuers": len(issuers),
        "serials": total_serials,
        "crls": total_crls,
    }
    if snap.verified or snap.failed:
        # Verify totals appear only when the lane ran — pre-round-13
        # consumers (and verifySignatures=off runs) see the exact same
        # document, keeping the text/JSON parity pin byte-stable.
        totals["sctsVerified"] = sum(snap.verified.values())
        totals["sctsFailed"] = sum(snap.failed.values())
    return {
        "issuers": issuers,
        "totals": totals,
        "logStatus": _log_status_lines(config, database),
    }


def collect_database_report(config: CTConfig) -> dict:
    """Machine-readable form of :func:`report_from_database` (cache
    walk), same shape as :func:`collect_tpu_report`."""
    database, _cache, _backend = get_configured_storage(config)
    issuers = []
    total_serials = 0
    total_crls = 0
    for issuer_obj in database.get_issuer_and_dates_from_cache():
        meta = database.get_issuer_metadata(issuer_obj.issuer)
        crls = sorted(meta.crls())
        dns = sorted(meta.issuers())
        exp_counts = {}
        for exp_date in issuer_obj.exp_dates:
            known = database.get_known_certificates(
                exp_date, issuer_obj.issuer)
            exp_counts[exp_date.id()] = known.count()
        n = sum(exp_counts.values())
        total_serials += n
        total_crls += len(crls)
        issuers.append({
            "id": issuer_obj.issuer.id(),
            "dns": dns,
            "crls": crls,
            "serials": n,
            "expDates": {exp: exp_counts[exp] for exp in sorted(exp_counts)},
        })
    return {
        "issuers": issuers,
        "totals": {
            "issuers": len(issuers),
            "serials": total_serials,
            "crls": total_crls,
        },
        "logStatus": _log_status_lines(config, database),
    }


def report_json(config: CTConfig, out) -> int:
    """``--json``: the report as one machine-readable document."""
    import json

    if config.backend == "tpu":
        report = collect_tpu_report(config)
        if report is None:
            print(
                json.dumps({"error": "aggStatePath not found: "
                            f"{config.agg_state_path!r}"}),
                file=out,
            )
            return 1
    else:
        report = collect_database_report(config)
    json.dump(report, out, indent=2)
    print(file=out)
    return 0


def report_from_database(config: CTConfig, out, verbosity: int = 0) -> int:
    """Cache-walk path (reference parity)."""
    database, _cache, backend = get_configured_storage(config)
    issuer_list = database.get_issuer_and_dates_from_cache()

    total_serials = 0
    total_crls = 0
    for issuer_obj in issuer_list:
        meta = database.get_issuer_metadata(issuer_obj.issuer)
        crl_list = meta.crls()
        total_crls += len(crl_list)
        dn_list = meta.issuers()
        count_issuer_serials = 0
        print(f"Issuer: {issuer_obj.issuer.id()} ({sorted(dn_list)})", file=out)
        for exp_date in issuer_obj.exp_dates:
            known = database.get_known_certificates(exp_date, issuer_obj.issuer)
            count = known.count()
            count_issuer_serials += count
            total_serials += count
            if verbosity >= 1:
                print(f"- {exp_date.id()} ({count} serials)", file=out)
            if verbosity >= 2:
                known_list = known.known()
                print(f"  Serials: {[s.id() for s in known_list]}", file=out)
                if verbosity >= 3:
                    for serial in known_list:
                        print(
                            f"Certificate serial={{{serial.hex_string()}}} / "
                            f"{{{serial.id()}}}",
                            file=out,
                        )
                        try:
                            pem = backend.load_certificate_pem(
                                serial, exp_date, issuer_obj.issuer
                            )
                            out.write(pem if isinstance(pem, str)
                                      else pem.decode())
                        except Exception as err:
                            print(f"error: {err}", file=out)
        print(
            f" --> {len(issuer_obj.exp_dates)} hours, "
            f"{count_issuer_serials} serials known, "
            f"{len(crl_list)} crls known, {len(dn_list)} issuerDNs known",
            file=out,
        )
    print(
        f"overall totals: {len(issuer_list)} issuers, {total_serials} serials, "
        f"{total_crls} crls",
        file=out,
    )

    print_log_status(config, database, out)
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # -json rides outside CTConfig (an output-format flag, not a
    # directive); strip it before the config parser sees the rest.
    json_mode = any(a in ("-json", "--json") for a in argv)
    argv = [a for a in argv if a not in ("-json", "--json")]
    config = CTConfig.load(argv)
    prepare_telemetry("storage-statistics", config)
    if json_mode:
        return report_json(config, sys.stdout)
    if config.backend == "tpu":
        return report_from_tpu_snapshot(config, sys.stdout, config.verbosity)
    return report_from_database(config, sys.stdout, config.verbosity)


if __name__ == "__main__":
    sys.exit(main())
