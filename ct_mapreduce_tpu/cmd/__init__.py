"""CLI entry points, mirroring the reference's three binaries
(/root/reference/cmd/): ct-fetch, storage-statistics, ct-getcert."""
