"""ct-filter: build, inspect, and query revocation-filter artifacts
offline from aggregate checkpoints — no running ct-fetch needed.

The CLI face of :mod:`ct_mapreduce_tpu.filter` (round 15) and the
distribution plane (round 18):

    ct-filter build -state agg.npz[,agg.w*.npz] -out run.filter \\
              [-fpRate 0.01] [-format fl01|fl02] [-allowPartial]
    ct-filter inspect -artifact run.filter [-json]
    ct-filter query -artifact run.filter -issuer <issuerID> \\
              -expDate 2031-06-15-14 -serial 4d0000002a [-serial ...]
    ct-filter delta -base e1.filter -target e2.filter -out e1-e2.delta \\
              [-fromEpoch 1 -toEpoch 2]
    ct-filter apply -base e1.filter -delta e1-e2.delta [-delta ...] \\
              -out replayed.filter
    ct-filter container -artifact run.filter -kind mlbf|clubcard \\
              -out run.mlbf

``build -format`` picks the artifact format: ``fl02`` (default —
per-group universes, ``CTMRFL02``) or ``fl01`` (the global-universe
compatibility path). ``delta`` computes the versioned stash/diff
between two epochs' artifacts — ``CTMRDL01`` or ``CTMRDL02`` follows
the endpoints' artifact format automatically (mixed endpoints are
refused); ``apply`` replays one or more delta links (bundles
split automatically) and writes bytes guaranteed identical to the
full build (the per-link SHA-256 checks fail loudly otherwise);
``container`` re-encodes an artifact into an upstream
clubcard/mlbf-style container (docs/FILTER_FORMAT.md).

``build`` folds one or many worker checkpoints (comma list and globs,
the ``aggStatePath`` spelling) through the fleet merge
(:mod:`ct_mapreduce_tpu.agg.merge`) so a single snapshot and a whole
fleet's worth compile identically — the merged artifact of a W-worker
fleet is byte-identical to the serial run's. Checkpoints written with
``emitFilter`` off carry no serial bytes for their device lanes and are
refused unless ``-allowPartial`` accepts a filter over the capturing
subset.

Exit status: ``build``/``inspect`` 0 on success; ``query`` 0 when every
serial is known, 1 when any is unknown, 2 on usage/format errors —
scriptable like ``ct-query``.
"""

from __future__ import annotations

import argparse
import json
import sys


def _build(args, out) -> int:
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.filter import (
        build_from_merged,
        write_artifact,
    )

    paths = merge.expand_state_paths(args.state)
    if not paths:
        print(f"error: no checkpoints match {args.state!r}",
              file=sys.stderr)
        return 2
    try:
        merged = merge.load_checkpoints(paths)
    except FileNotFoundError as err:
        print(f"error: checkpoint not found: {err}", file=sys.stderr)
        return 2
    try:
        art = build_from_merged(merged, fp_rate=args.fpRate,
                                allow_partial=args.allowPartial,
                                fmt=args.format or None)
    except ValueError as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    blob = art.to_bytes()
    write_artifact(args.out, blob)
    print(json.dumps({
        "out": args.out,
        "bytes": len(blob),
        "checkpoints": paths,
        "format": art.fmt,
        "serials": art.n_serials,
        "groups": len(art.groups),
        "max_layers": art.max_layers(),
        "bits_per_entry": round(art.bits_per_entry(), 3),
        "fp_rate": art.fp_rate,
    }, indent=2), file=out)
    return 0


def _inspect(args, out) -> int:
    from ct_mapreduce_tpu.filter import read_artifact

    art = read_artifact(args.artifact)
    groups = [
        {
            "issuer": g.issuer,
            "expDate": g.exp_id,
            "serials": g.n,
            "layers": [{"m": lyr.m, "k": lyr.k}
                       for lyr in g.cascade.layers],
            "bits_per_entry": round(g.cascade.bits_per_entry(), 3),
        }
        for _, g in sorted(art.groups.items())
    ]
    body = {
        "format": art.fmt,
        "fp_rate": art.fp_rate,
        "serials": art.n_serials,
        "groups": len(groups),
        "max_layers": art.max_layers(),
        "bits_per_entry": round(art.bits_per_entry(), 3),
    }
    if args.json:
        body["group_detail"] = groups
        print(json.dumps(body, indent=2), file=out)
        return 0
    print(json.dumps(body, indent=2), file=out)
    for g in groups:
        layers = "+".join(str(lyr["m"]) for lyr in g["layers"])
        print(f"{g['issuer']} {g['expDate']}: {g['serials']} serials, "
              f"{len(g['layers'])} layers ({layers} bits)", file=out)
    return 0


def _query(args, out) -> int:
    from ct_mapreduce_tpu.filter import read_artifact

    art = read_artifact(args.artifact)
    try:
        serials = [bytes.fromhex(s) for s in args.serial]
    except ValueError as err:
        print(f"error: serial is not hex: {err}", file=sys.stderr)
        return 2
    all_known = True
    for raw, sb in zip(args.serial, serials):
        known = art.query(args.issuer, args.expDate, sb)
        all_known &= known
        print(json.dumps({"issuer": args.issuer, "expDate": args.expDate,
                          "serial": raw, "known": known}), file=out)
    return 0 if all_known else 1


def _delta(args, out) -> int:
    from ct_mapreduce_tpu.distrib import compute_delta
    from ct_mapreduce_tpu.filter import write_artifact

    with open(args.base, "rb") as fh:
        base = fh.read()
    with open(args.target, "rb") as fh:
        target = fh.read()
    blob = compute_delta(base, target, args.fromEpoch, args.toEpoch)
    write_artifact(args.out, blob)
    print(json.dumps({
        "out": args.out, "bytes": len(blob),
        "fromEpoch": args.fromEpoch, "toEpoch": args.toEpoch,
        "baseBytes": len(base), "targetBytes": len(target),
        "ratio": round(len(blob) / max(1, len(target)), 4),
    }, indent=2), file=out)
    return 0


def _apply(args, out) -> int:
    from ct_mapreduce_tpu.distrib import (
        DeltaError,
        apply_chain,
        split_bundle,
    )
    from ct_mapreduce_tpu.filter import write_artifact

    with open(args.base, "rb") as fh:
        blob = fh.read()
    links = []
    for path in args.delta:
        with open(path, "rb") as fh:
            links.extend(split_bundle(fh.read()))
    try:
        result = apply_chain(blob, links)
    except DeltaError as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    write_artifact(args.out, result)
    print(json.dumps({"out": args.out, "bytes": len(result),
                      "links": len(links)}, indent=2), file=out)
    return 0


def _container(args, out) -> int:
    from ct_mapreduce_tpu.distrib import encode_container
    from ct_mapreduce_tpu.filter import read_artifact, write_artifact

    art = read_artifact(args.artifact)
    blob = encode_container(art, args.kind)
    write_artifact(args.out, blob)
    print(json.dumps({
        "out": args.out, "kind": args.kind, "bytes": len(blob),
        "serials": art.n_serials, "groups": len(art.groups),
    }, indent=2), file=out)
    return 0


def main(argv: list[str] | None = None, out=None) -> int:
    parser = argparse.ArgumentParser(prog="ct-filter")
    sub = parser.add_subparsers(dest="cmd")

    b = sub.add_parser("build", help="compile checkpoints → artifact")
    b.add_argument("-state", "--state", required=True,
                   help="checkpoint path(s): comma list, globs ok "
                        "(the aggStatePath spelling)")
    b.add_argument("-out", "--out", required=True,
                   help="artifact output path")
    b.add_argument("-fpRate", "--fpRate", type=float, default=0.01,
                   help="target layer-0 false-positive rate")
    b.add_argument("-allowPartial", "--allowPartial", action="store_true",
                   help="accept checkpoints without a filter capture "
                        "(their device-lane serials will be missing)")
    b.add_argument("-format", "--format", default="",
                   choices=("", "fl01", "fl02"),
                   help="artifact format (default: the "
                        "CTMR_FILTER_FORMAT ladder, fl02)")

    i = sub.add_parser("inspect", help="artifact → structure summary")
    i.add_argument("-artifact", "--artifact", required=True)
    i.add_argument("-json", "--json", action="store_true",
                   help="full per-group detail as JSON")

    q = sub.add_parser("query", help="offline membership question")
    q.add_argument("-artifact", "--artifact", required=True)
    q.add_argument("-issuer", "--issuer", required=True,
                   help="issuerID (base64url of SHA-256(SPKI))")
    q.add_argument("-expDate", "--expDate", required=True,
                   help="expiration bucket id, e.g. 2031-06-15-14")
    q.add_argument("-serial", "--serial", action="append", default=[],
                   help="serial content bytes as hex (repeatable)")

    d = sub.add_parser("delta",
                       help="CTMRDL01/CTMRDL02 diff between epochs "
                            "(magic follows the artifacts' format)")
    d.add_argument("-base", "--base", required=True,
                   help="the from-epoch full artifact")
    d.add_argument("-target", "--target", required=True,
                   help="the to-epoch full artifact")
    d.add_argument("-out", "--out", required=True)
    d.add_argument("-fromEpoch", "--fromEpoch", type=int, default=0)
    d.add_argument("-toEpoch", "--toEpoch", type=int, default=1)

    a = sub.add_parser("apply", help="replay delta link(s) onto a base")
    a.add_argument("-base", "--base", required=True)
    a.add_argument("-delta", "--delta", action="append", default=[],
                   required=True,
                   help="delta link or bundle (repeatable, in order)")
    a.add_argument("-out", "--out", required=True)

    c = sub.add_parser("container",
                       help="re-encode as an upstream container")
    c.add_argument("-artifact", "--artifact", required=True)
    c.add_argument("-kind", "--kind", required=True,
                   choices=("mlbf", "clubcard"))
    c.add_argument("-out", "--out", required=True)

    args = parser.parse_args(argv)
    out = out or sys.stdout
    if args.cmd == "build":
        return _build(args, out)
    if args.cmd == "inspect":
        try:
            return _inspect(args, out)
        except (OSError, ValueError) as err:
            print(f"error: {err}", file=sys.stderr)
            return 2
    if args.cmd == "query":
        if not args.serial:
            print("error: at least one -serial is required",
                  file=sys.stderr)
            return 2
        try:
            return _query(args, out)
        except (OSError, ValueError) as err:
            print(f"error: {err}", file=sys.stderr)
            return 2
    if args.cmd in ("delta", "apply", "container"):
        handler = {"delta": _delta, "apply": _apply,
                   "container": _container}[args.cmd]
        try:
            return handler(args, out)
        except (OSError, ValueError) as err:
            print(f"error: {err}", file=sys.stderr)
            return 2
    parser.print_usage(sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
