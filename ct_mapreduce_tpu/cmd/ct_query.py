"""ct-query: ask the live query plane whether a serial is known.

The client side of ``serve/server.py`` (``queryPort`` directive on a
running ``ct-fetch``): membership questions, per-issuer metadata, and
plane health, answered in milliseconds against the epoch-pinned view —
no snapshot drain, no Redis walk.

Usage:

    ct-query -addr :9090 -issuer <issuerID> -expDate 2031-06-15-14 \\
             -serial 4d0000002a
    ct-query -addr :9090 -issuerMeta <issuerID>
    ct-query -addr :9090 -health

Exit status: 0 when every queried serial is known (or the metadata /
health request succeeded), 1 when any serial is unknown, 2 on usage or
transport errors — scriptable like ``grep``.
"""

from __future__ import annotations

import argparse
import json
import sys

from ct_mapreduce_tpu.serve.client import QueryClient, QueryError


def main(argv: list[str] | None = None, out=None) -> int:
    parser = argparse.ArgumentParser(prog="ct-query")
    parser.add_argument("-addr", "--addr", required=True,
                        help="query plane address (host:port or :port)")
    parser.add_argument("-issuer", "--issuer", default="",
                        help="issuerID (base64url of SHA-256(SPKI))")
    parser.add_argument("-expDate", "--expDate", default="",
                        help="expiration bucket id, e.g. 2031-06-15-14")
    parser.add_argument("-serial", "--serial", action="append", default=[],
                        help="serial content bytes as hex (repeatable)")
    parser.add_argument("-issuerMeta", "--issuerMeta", default="",
                        help="fetch per-issuer metadata instead of querying")
    parser.add_argument("-health", "--health", action="store_true",
                        help="fetch query-plane health instead of querying")
    parser.add_argument("-timeoutMs", "--timeoutMs", type=int, default=0,
                        help="per-request deadline (0 = none)")
    args = parser.parse_args(argv)
    out = out or sys.stdout

    client = QueryClient(args.addr)
    try:
        if args.health:
            print(json.dumps(client.healthz(), indent=2), file=out)
            return 0
        if args.issuerMeta:
            print(json.dumps(client.issuer(args.issuerMeta), indent=2),
                  file=out)
            return 0
        if not (args.issuer and args.expDate and args.serial):
            parser.print_usage(sys.stderr)
            print("error: -issuer, -expDate and -serial are required "
                  "(or use -issuerMeta / -health)", file=sys.stderr)
            return 2
        queries = [{"issuer": args.issuer, "expDate": args.expDate,
                    "serial": s} for s in args.serial]
        resp = client.query(
            queries, timeout_ms=args.timeoutMs or None)
        print(json.dumps(resp, indent=2), file=out)
        return 0 if all(r["known"] for r in resp["results"]) else 1
    except QueryError as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    except OSError as err:
        print(f"error: query plane unreachable at {client.base_url}: {err}",
              file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
