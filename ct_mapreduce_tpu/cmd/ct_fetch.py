"""ct-fetch: continuous CT-log ingest.

The reference binary (/root/reference/cmd/ct-fetch/ct-fetch.go:490-638):
config init → storage wiring → telemetry → sync engine + store workers
→ one downloader per log → health endpoint → signal-driven shutdown →
optional runForever polling loop.

This build adds ``backend = tpu``: entries are packed into device
batches and reduced on-chip by :class:`TpuAggregator` instead of
per-entry Redis round-trips; device aggregates snapshot to
``aggStatePath`` for ``storage-statistics --backend=tpu``.
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
import time
from ct_mapreduce_tpu.config import CTConfig
from ct_mapreduce_tpu.engine import get_configured_storage, prepare_telemetry
from ct_mapreduce_tpu.ingest.fleet import (
    FleetService,
    build_coordinator,
    resolve_fleet,
    worker_state_path,
)
from ct_mapreduce_tpu.ingest.health import HealthServer
from ct_mapreduce_tpu.ingest.sync import (
    AggregatorSink,
    DatabaseSink,
    LogSyncEngine,
    polling_delay,
)
from ct_mapreduce_tpu.telemetry import flight, trace
from ct_mapreduce_tpu.telemetry.promhttp import MetricsServer
from ct_mapreduce_tpu.utils import parse_duration


class ProgressPrinter:
    """Textual stand-in for the reference's mpb progress bars
    (ct-fetch.go:317-330); disabled by -nobars."""

    def __init__(self, engine: LogSyncEngine, period_s: float):
        self.engine = engine
        self.period_s = max(period_s, 0.05)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last: dict[str, tuple[float, int]] = {}

    def _line(self) -> str:
        parts = []
        now = time.monotonic()
        for url, (pos, end) in sorted(self.engine.progress().items()):
            prev_t, prev_pos = self._last.get(url, (now, pos))
            rate = (pos - prev_pos) / (now - prev_t) if now > prev_t else 0.0
            self._last[url] = (now, pos)
            pct = 100.0 * pos / end if end else 100.0
            # ETA decorator parity with the reference's mpb bars
            # (ct-fetch.go:317-330).
            if rate > 0 and end > pos:
                secs = (end - pos) / rate
                eta = (f"{secs / 3600:.1f}h" if secs >= 3600
                       else f"{secs / 60:.0f}m" if secs >= 60
                       else f"{secs:.0f}s")
            else:
                eta = "--"
            parts.append(
                f"{url}: {pos}/{end} ({pct:.1f}%) {rate:,.0f}/s eta {eta}"
            )
        return " | ".join(parts)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            line = self._line()
            if line:
                print(f"\r{line}", end="", file=sys.stderr, flush=True)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="progress",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join()
        print(file=sys.stderr)


def build_sink(config: CTConfig, database, backend=None):
    """Pick the store path: per-entry host store (reference parity) or
    the batched device pipeline (single-chip or mesh-sharded per
    meshShape — see models.build_aggregator)."""
    if config.backend == "tpu":
        from ct_mapreduce_tpu.models import IngestModel

        model = IngestModel.from_config(config)
        # certPath keeps the reference's durable PEM tree even in TPU
        # mode; without it the backend is a no-op and is skipped.
        pem_backend = backend if config.cert_path else None
        return AggregatorSink(model.aggregator,
                              flush_size=config.batch_size,
                              backend=pem_backend,
                              device_queue_depth=config.device_queue_depth,
                              decode_workers=config.decode_workers,
                              decode_threads=config.decode_threads,
                              overlap_workers=config.overlap_workers,
                              preparsed=config.preparsed_ingest or None,
                              chunks_per_dispatch=config.chunks_per_dispatch,
                              staging_depth=config.staging_depth,
                              verify_signatures=(config.verify_signatures
                                                 or None),
                              verify_log_keys=(config.verify_log_keys
                                               or None),
                              verify_precomp_window=(
                                  config.verify_precomp_window
                                  if config.verify_precomp_window >= 0
                                  else None),
                              verify_qtable_size=config.verify_qtable_size,
                              ), model
    sink = DatabaseSink(
        database,
        cn_filters=tuple(config.issuer_cn_filters()),
        log_expired_entries=config.log_expired_entries,
    )
    return sink, None


def fleet_assignments(fleet, log_urls: list[str],
                      takeover: bool = False,
                      errors: list | None = None) -> list[tuple]:
    """This worker's share of the feed as (url, offset, limit,
    state_suffix) download assignments. Multi-log fleets partition
    whole logs by rendezvous hash, then take the per-log fetch lease
    on each — a log whose lease another worker still holds (takeover
    racing the owner's restart) is skipped this round and re-contended
    next round, so no log is ever fetched by two workers at once. A
    fleet pointed at ONE log stripes its entry-index space instead
    (one STH fetch resolves the tree size), each stripe with its own
    durable cursor key; an STH failure is recorded in ``errors`` and
    yields an empty round (retried on the next poll) instead of
    killing the worker."""
    if fleet is None:
        return [(u, None, None, "") for u in log_urls]
    if fleet.num_workers <= 1:
        # Degenerate fleet: worker 0 owns everything, but the map
        # still computes so /healthz surfaces it.
        return [(u, None, None, "") for u in fleet.partition(log_urls)]
    if len(log_urls) == 1:
        from ct_mapreduce_tpu.ingest.ctclient import CTLogClient

        url = log_urls[0]
        try:
            tree_size = CTLogClient(url).get_sth().tree_size
        except Exception as err:
            if errors is not None:
                errors.append(
                    f"{url}: STH fetch for stripe assignment failed: "
                    f"{err}")
            return []
        offset, limit = fleet.stripe(tree_size)
        fleet.note_stripe(url, offset, limit)
        if limit <= 0:
            return []  # more workers than entries: nothing for us
        return [(url, offset, limit, f"#w{fleet.worker_id}")]
    return [(u, None, None, "")
            for u in fleet.partition(log_urls, takeover=takeover)
            if fleet.claim(u)]


def main(argv: list[str] | None = None) -> int:
    config = CTConfig.load(argv)
    log_urls = config.log_urls()
    if not log_urls:
        print(config.usage(), file=sys.stderr)
        print("\nerror: logList is required", file=sys.stderr)
        return 2

    # Platform profile (round 18): pin the tuned-knob data file before
    # any subsystem resolves its knobs — every resolve_* from here on
    # reads the profile layer (explicit > env > profile > default).
    from ct_mapreduce_tpu.config import profile as platprofile

    platprofile.set_active_profile(config.platform_profile)

    # Fleet resolution before any state path is used: each worker of a
    # multi-worker ingest keeps its own aggregate snapshot
    # (agg.npz → agg.w<id>.npz); storage-statistics merges them
    # (aggStatePath glob) into one view.
    num_workers, fleet_worker_id, checkpoint_period, coord_backend = (
        resolve_fleet(config.num_workers, config.worker_id,
                      config.checkpoint_period, config.coordinator_backend))
    if fleet_worker_id >= num_workers:
        print(f"error: workerId {fleet_worker_id} outside "
              f"[0, numWorkers={num_workers})", file=sys.stderr)
        return 2
    base_state_path = config.agg_state_path
    config.agg_state_path = worker_state_path(
        config.agg_state_path, fleet_worker_id, num_workers)
    # A durable per-worker checkpoint on disk means this process is a
    # WARM RESTART rejoining a fleet that already crossed its start
    # barrier: it must not re-run the barrier (peers may have finished;
    # a stale leader lease would strand it polling a dead started key)
    # and its first round must partition against the LIVE membership so
    # logs a survivor took over aren't double-fetched.
    resuming = bool(config.agg_state_path
                    and os.path.exists(config.agg_state_path))

    database, _cache, _backend = get_configured_storage(config)  # noqa: F841
    dumper = prepare_telemetry("ct-fetch", config)
    # Span tracing: tracePath directive (CTMR_TRACE env auto-enables at
    # import). Near-zero cost when off; exported at shutdown.
    if config.trace_path:
        trace.enable(config.trace_path)
    # Cross-process correlation (round 23): every span this process
    # emits carries its fleet worker id; the leader-epoch attr joins in
    # FleetService._observe_epoch as epochs advance.
    trace.set_process_attrs(worker=fleet_worker_id)
    # Fleet observability knobs: fan-in on/off + the SLO thresholds
    # (directives > CTMR_SLO_* env > platform profile > disabled).
    from ct_mapreduce_tpu.telemetry import fleetobs
    from ct_mapreduce_tpu.telemetry import metrics as _metrics

    obs = fleetobs.resolve_obs(
        fleet_metrics=config.fleet_metrics,
        max_ingest_lag=config.slo_max_ingest_lag,
        max_ckpt_age_s=config.slo_max_checkpoint_age,
        max_filter_lag=config.slo_max_filter_lag,
        max_serve_p99_ms=config.slo_max_serve_p99_ms)
    # Flight recorder: a crash, SIGTERM/SIGUSR1, or wedged-pipeline
    # latch dumps the trace ring + last metric snapshots next to the
    # run (CTMR_FLIGHT_DIR overrides the directory). Signal dumps ride
    # this process's own handlers below; the unhandled-exception dump
    # is the except clause around the main loop (no sys.excepthook
    # mutation — main() must leave no global hooks behind, it is
    # re-entered by tests and runForever wrappers). Uninstalled in the
    # finally for the same reason.
    flight.install(signals=False, excepthook=False)
    # Lock-order witness (round 16): CTMR_LOCK_WITNESS=1 wraps every
    # lock the package creates from here on; order violations and
    # cycles land in this run's flight dumps as a `lock_witness`
    # section (docs/ANALYSIS.md). No-op unless the env opts in.
    from ct_mapreduce_tpu.analysis import witness as _witness

    _witness.install()
    if config.issuer_cn_filter:
        # The reference logs a stale "unsupported" warning here
        # (ct-fetch.go:498-499) but enforces the filter anyway; we just
        # enforce it.
        print(f"IssuerCNFilter enabled: {config.issuer_cn_filters()}",
              file=sys.stderr)

    run_stage = {"stage": "init"}
    sink, model = build_sink(config, database, _backend)

    # Filter emission (round 15): emitFilter compiles the aggregation
    # state's per-(issuer, expDate) serial sets into a crlite-style
    # filter-cascade artifact on every checkpoint save. Fleet workers
    # get per-worker artifact paths (like their snapshots); the leader
    # additionally emits the MERGED fleet filter each epoch below.
    from ct_mapreduce_tpu.filter import resolve_filter

    fknobs = resolve_filter(
        config.emit_filter or None, config.filter_path,
        config.filter_fp_rate, state_path=base_state_path,
        spill_dir=config.filter_capture_spill_dir,
        spill_mb=config.filter_capture_spill_mb,
        stream_chunk=config.filter_stream_chunk,
        fused_lanes=config.filter_fused_lanes,
        fmt=config.filter_format)
    emit_filter, base_filter_path, filter_fp = (
        fknobs.emit, fknobs.path, fknobs.fp_rate)
    if emit_filter and model is not None:
        model.aggregator.configure_filter_emission(
            worker_state_path(base_filter_path, fleet_worker_id,
                              num_workers),
            filter_fp,
            spill_dir=(worker_state_path(fknobs.spill_dir,
                                         fleet_worker_id, num_workers)
                       if fknobs.spill_dir else ""),
            spill_mem_bytes=fknobs.spill_mb << 20,
            fmt=fknobs.fmt)
    elif emit_filter:
        print("emitFilter ignored: filter emission needs backend = tpu",
              file=sys.stderr)
        emit_filter = False

    # Checkpoint plane (round 22): pin the CTMRCK02 knobs from the
    # directives; unset ones resolve through CTMR_* env and the
    # platform profile inside the aggregator.
    if model is not None:
        model.aggregator.configure_checkpointing(
            mode=config.checkpoint_mode,
            max_chain=config.ckpt_max_chain,
            segment_budget_mb=config.ckpt_segment_budget_mb)

    # Leader-side incremental build cache: across epoch ticks only
    # churned groups of the merged fleet filter rebuild (tokens always
    # recompute from the merged union sets — never worker hashes).
    from ct_mapreduce_tpu.filter import GroupBuildCache

    fleet_filter_cache = GroupBuildCache()

    def leader_fleet_filter() -> None:
        """Leader epoch-tick duty: fold every worker snapshot present
        on disk (agg/merge.py) and emit the merged fleet filter —
        best-effort per tick (a worker mid-checkpoint contributes its
        previous snapshot; the next epoch catches it up)."""
        if not emit_filter or num_workers <= 1:
            return
        if fleet is None or not fleet.is_leader:
            return
        from ct_mapreduce_tpu.agg import merge as aggmerge
        from ct_mapreduce_tpu.filter import artifact as fartifact
        from ct_mapreduce_tpu.telemetry.metrics import incr_counter

        paths = [
            p for p in (worker_state_path(base_state_path, w, num_workers)
                        for w in range(num_workers))
            if os.path.exists(p)
        ]
        if not paths:
            return
        try:
            merged = aggmerge.load_checkpoints(paths)
            art = fartifact.build_from_merged(
                merged, fp_rate=filter_fp, allow_partial=True,
                fmt=fknobs.fmt, cache=fleet_filter_cache)
            fartifact.write_artifact(base_filter_path, art.to_bytes())
            incr_counter("filter", "fleet_emit")
        except Exception as err:
            incr_counter("filter", "fleet_emit_error")
            print(f"fleet filter emission failed: "
                  f"{type(err).__name__}: {err}", file=sys.stderr)

    def refresh_serve_filter() -> None:
        """Re-arm the query plane's filter tier from the live capture
        on the same cadence the artifact is emitted (checkpoint time):
        the serve tier's cascade snapshot tracks the durable artifact,
        never drifts unboundedly behind ingest, and between refreshes
        its registry-snapshot guard forwards anything newer to the
        table-confirm tier."""
        if query_server is not None and query_server.oracle.filter_first:
            try:
                query_server.oracle.refresh_filter()
            except Exception:
                pass  # no capture yet / transient: tier stays as-is

    def publish_distribution(epoch: int) -> None:
        """Fleet-wide distribution (round 18): every epoch tick, THIS
        worker publishes the artifact at the fleet's shared path —
        the leader's merged fleet filter (written by
        leader_fleet_filter just above in the leader's own tick) —
        into its local distribution store. The bytes are
        byte-identical on every worker by the determinism contract,
        so every worker serves identical ETags/deltas/containers and
        any replica is authoritative. Best-effort per tick: a
        follower ticking before the leader's merged write lands
        publishes one epoch behind and catches up next tick."""
        if not emit_filter or query_server is None:
            return
        try:
            with open(base_filter_path, "rb") as fh:
                blob = fh.read()
        except OSError:
            return  # leader hasn't emitted yet; next epoch retries
        try:
            query_server.oracle.publish_artifact(
                epoch, blob, source="fleet")
        except Exception as err:
            print(f"filter distribution publish failed: "
                  f"{type(err).__name__}: {err}", file=sys.stderr)

    checkpoint_hook = None
    if model is not None and config.agg_state_path:
        # Snapshot device aggregates before every durable cursor write —
        # a crash must never leave the cursor ahead of aggregate state.
        def checkpoint_hook():
            sink.checkpointed_save(model.save)
            refresh_serve_filter()
    engine = LogSyncEngine(
        sink,
        database,
        num_threads=config.num_threads,
        offset=config.offset,
        limit=config.limit,
        save_period_s=parse_duration(config.save_period),
        checkpoint_hook=checkpoint_hook,
        # TPU mode streams whole responses to the native batch decoder.
        raw_batches=model is not None,
    )
    engine.start_store_threads()

    # Fleet lifecycle (ingest/fleet.py): leader election + start
    # barrier + heartbeats over the configured coordination fabric
    # (the RemoteCache for `redis`, jax.distributed for `jax`), with
    # the leader publishing checkpoint-cadence epochs every
    # `checkpointPeriod` — each worker checkpoints (aggregate snapshot
    # + cursors) when it observes the epoch advance — and a clean-
    # shutdown broadcast that stops every worker's downloaders.
    ckpt_period_s = (parse_duration(checkpoint_period)
                     if checkpoint_period else 0.0)

    def slo_state() -> tuple[dict, list]:
        """One SLO rule evaluation (telemetry/fleetobs.py): raw
        signals → (slo values, breach reasons), mirrored into the
        ``slo.*`` gauges. Cheap no-op until a threshold is set."""
        if not obs.any_slo():
            return {}, []
        snap = _metrics.get_sink().snapshot()
        ckpt_wall = fleet.last_checkpoint_wall if fleet is not None else 0.0
        f_lag = None
        if fleet is not None and query_server is not None:
            tier = getattr(query_server.oracle, "filter_tier", None)
            if tier is not None:
                f_lag = max(0, int(fleet.stats()["checkpoint_epoch"])
                            - int(tier.epoch))
        p99 = fleetobs.serve_p99_ms() if obs.max_serve_p99_ms else None
        values, degraded = fleetobs.evaluate_slos(
            obs, snap, last_checkpoint_wall=ckpt_wall,
            checkpoint_period_s=ckpt_period_s,
            filter_epoch_lag=f_lag, p99_ms=p99)
        fleetobs.publish_slo_gauges(values, degraded)
        return values, degraded

    def obs_payload() -> str:
        """The heartbeat-cadence fan-in unit: this worker's metrics
        snapshot + fleet stats + SLO state + a (wall, mono) clock
        pair, published through the coordinator fabric's TTL'd keys."""
        values, degraded = slo_state()
        return fleetobs.build_obs_payload(
            fleet_worker_id, num_workers,
            fleet_stats=fleet.stats() if fleet is not None else None,
            slo={"values": values, "degraded": degraded})

    fleet = None
    if num_workers > 1 or coord_backend or checkpoint_period:
        coordinator = build_coordinator(
            coord_backend, _cache, "ct-fetch", fleet_worker_id, num_workers)
        fleet = FleetService(
            coordinator,
            checkpoint_period_s=ckpt_period_s,
            on_checkpoint=lambda epoch: (engine.checkpoint_now(),
                                         leader_fleet_filter(),
                                         publish_distribution(epoch)),
            on_shutdown=lambda reason: (
                print(f"\nfleet shutdown broadcast: {reason}",
                      file=sys.stderr),
                engine.signal_stop(),
            ),
            obs_payload=obs_payload if obs.fleet_metrics else None,
        )

    # Flight-recorder fleet sections (round 23): a SIGUSR1/crash dump
    # from a wedged worker answers role/epoch/claims/heartbeat-age and
    # current checkpoint chain depth without a live process to query.
    def _flight_fleet() -> dict:
        return fleet.stats() if fleet is not None else {}

    def _flight_ckpt_chain() -> dict:
        agg = model.aggregator if model is not None else None
        if agg is None:
            return {}
        return {
            "chain_length": int(getattr(agg, "_ckpt_chain_len", 0)),
            "last_checkpoint_wall": (fleet.last_checkpoint_wall
                                     if fleet is not None else 0.0),
            "checkpoint_period_s": ckpt_period_s,
        }

    flight.register_section("fleet", _flight_fleet)
    flight.register_section("ckpt_chain", _flight_ckpt_chain)

    health = None
    if config.health_addr:
        try:
            health = HealthServer(
                engine, parse_duration(config.polling_delay_mean),
                addr=config.health_addr,
            )
            health.start()
        except OSError as err:
            print(f"health endpoint disabled: {err}", file=sys.stderr)
            health = None

    def healthz() -> dict:
        """The /healthz body: engine stage, last-progress timestamp,
        and the overlap pipeline's bounded-queue depths."""
        updates = engine.last_updates()
        last = max(updates.values()).isoformat() if updates else None
        body = {
            "stage": run_stage["stage"],
            "last_progress": last,
            "progress": {u: {"pos": p, "end": e}
                         for u, (p, e) in engine.progress().items()},
            "entry_queue_depth": engine.entry_queue.qsize(),
        }
        ovl = getattr(sink, "_overlap", None)
        if ovl is not None:
            body["overlap_queues"] = ovl.queue_depths()
        verifier = getattr(sink, "verifier", None)
        if verifier is not None:
            # Round 17: verify-lane knobs, outcome totals, and Q-table
            # occupancy (steady state: occupancy = live log keys,
            # qtable_misses flat).
            body["verify"] = verifier.health()
        if query_server is not None:
            body["serve"] = query_server.oracle.stats()
        if fleet is not None:
            body["fleet"] = fleet.stats()
        # SLO rules (round 23): any breach renders the same body under
        # HTTP 503 (promhttp's healthy-False contract).
        values, degraded = slo_state()
        if values:
            body["slo"] = values
        if degraded:
            body["healthy"] = False
            body["degraded"] = degraded
        return body

    # Query plane: the batched membership-oracle JSON API over the live
    # aggregator (serve/server.py). TPU backend only — the oracle pins
    # epochs of the device dedup table; the per-entry database path has
    # no device table to serve.
    query_server = None
    if config.query_port and model is not None:
        from ct_mapreduce_tpu.serve.server import QueryServer

        try:
            query_server = QueryServer(
                model.aggregator, config.query_port,
                device=config.serve_device,
                replicas=config.serve_replicas,
                cache_size=config.serve_cache_size,
                # emitFilter also arms the serve plane's filter-first
                # tier and the /filter download routes (env
                # CTMR_SERVE_FILTER_FIRST can still force either way).
                filter_first=(True if emit_filter else None),
                filter_fp_rate=filter_fp,
                distrib_history=config.distrib_history,
                max_delta_chain=config.max_delta_chain).start()
            # SLO degradation flips the query plane's /healthz to 503
            # too (same rules, same reasons — satellite of round 23).
            query_server.slo_check = lambda: slo_state()[1]
            print(f"query endpoint: :{query_server.port}/query "
                  f"+ /issuer + /getcert + /filter "
                  f"(+ /filter/delta + /filter/container + "
                  f"/filter/manifest)", file=sys.stderr)
        except OSError as err:
            print(f"query endpoint disabled: {err}", file=sys.stderr)
            query_server = None
    elif config.query_port:
        print("queryPort ignored: the query plane needs backend = tpu",
              file=sys.stderr)

    metrics_server = None
    if config.metrics_port:
        # Fleet fan-in routes (round 23): any worker answers for the
        # whole fleet from the fabric's TTL'd obs payloads.
        fleet_metrics_fn = fleet_health_fn = None
        if fleet is not None and obs.fleet_metrics:
            def fleet_metrics_fn() -> str:
                return fleetobs.render_fleet_metrics(
                    fleetobs.collect_fleet_obs(fleet.fleet_obs()))

            def fleet_health_fn() -> dict:
                return fleetobs.fleet_health(
                    fleetobs.collect_fleet_obs(fleet.fleet_obs()),
                    num_workers,
                    getattr(fleet.coordinator, "liveness_timeout_s",
                            15.0))
        try:
            metrics_server = MetricsServer(
                config.metrics_port, health=healthz,
                fleet_metrics=fleet_metrics_fn,
                fleet_health=fleet_health_fn).start()
            print(f"metrics endpoint: :{metrics_server.port}/metrics "
                  f"+ /healthz"
                  + (" + /metrics/fleet + /healthz/fleet"
                     if fleet_metrics_fn else ""), file=sys.stderr)
        except OSError as err:
            print(f"metrics endpoint disabled: {err}", file=sys.stderr)
            metrics_server = None

    def handle_signal(signum, frame):
        print(f"\nsignal {signum}: stopping after current batches...",
              file=sys.stderr)
        if signum == signal.SIGTERM:
            # Orchestrator kill: leave the post-mortem artifact before
            # draining (the drain itself may be what's wedged).
            flight.dump(f"signal {signum} (SIGTERM)")
        if fleet is not None and fleet.is_leader:
            # Leader-published clean shutdown: followers observe the
            # broadcast and drain too, so one signal stops the fleet.
            fleet.request_shutdown(f"leader signal {signum}")
        engine.signal_stop()

    def handle_dump_signal(signum, frame):
        path = flight.dump(f"signal {signum} (SIGUSR1)")
        print(f"\nsignal {signum}: flight record "
              f"{path or 'not written'}", file=sys.stderr)

    # Previous handlers are restored in the finally below — main() must
    # leave no global hooks behind (same contract as the flight
    # recorder's excepthook note above): tests and runForever wrappers
    # re-enter it, and a stale handler would swallow a later SIGTERM
    # meant for the host process.
    prev_handlers = {}
    for signum, handler in ((signal.SIGINT, handle_signal),
                            (signal.SIGTERM, handle_signal)):
        prev_handlers[signum] = signal.signal(signum, handler)
    try:
        prev_handlers[signal.SIGUSR1] = signal.signal(
            signal.SIGUSR1, handle_dump_signal)
    except (AttributeError, ValueError, OSError):
        pass  # platform without SIGUSR1 / non-main thread

    printer = None
    if not config.nobars:
        printer = ProgressPrinter(
            engine, parse_duration(config.output_refresh_period)
        )
        printer.start()

    profiling = False
    if config.profile_dir:
        # SURVEY.md §5 tracing analog: a jax.profiler trace of the run
        # (device steps + host phases) next to the metric timers.
        try:
            import jax

            jax.profiler.start_trace(config.profile_dir)
            profiling = True
        except Exception as err:
            print(f"profiling disabled: {err}", file=sys.stderr)

    final_round_errors = False
    sync_round = 0
    try:
        if fleet is not None:
            # Election + start barrier: every worker begins its
            # partition at once, like the reference's Redis barrier
            # (and nobody fetches before the fleet is fully present).
            run_stage["stage"] = "electing"
            role = fleet.start(timeout_s=600.0, rejoin=resuming)
            print(f"fleet worker {fleet.worker_id}/{num_workers} "
                  f"({'leader' if role else 'follower'}"
                  f"{', rejoined' if fleet.rejoined else ''}, "
                  f"coordinator={type(fleet.coordinator).__name__})",
                  file=sys.stderr)
        while True:
            run_stage["stage"] = "syncing"
            # Dead-owner takeover on later runForever rounds (the start
            # barrier guaranteed full membership for round 0) AND on a
            # rejoining worker's first round — its logs may be mid-
            # takeover by a survivor, so it must partition against the
            # live membership (the per-log lease arbitrates the races).
            takeover = sync_round > 0 or (
                fleet is not None and fleet.rejoined)
            for url, f_off, f_lim, f_sfx in fleet_assignments(
                    fleet, log_urls, takeover=takeover,
                    errors=engine.errors):
                engine.sync_log(url, offset=f_off, limit=f_lim,
                                state_suffix=f_sfx)
            sync_round += 1
            engine.wait_for_downloads()
            run_stage["stage"] = "draining"
            engine.stop()  # drain queue, flush sink
            if model is not None:
                run_stage["stage"] = "saving"
                model.save()
                refresh_serve_filter()
            if fleet is not None:
                # This round's entries are durably folded: drop the
                # fetch leases so next round's rightful owners (per the
                # then-current membership) can take them.
                fleet.release_claims()
            run_stage["stage"] = "idle"
            # Drain this round's errors so runForever doesn't re-print
            # (or unboundedly accumulate) them across polls.
            final_round_errors = bool(engine.errors)
            for e in engine.errors:
                print(f"error: {e}", file=sys.stderr)
            engine.errors.clear()
            if not config.run_forever or engine.stop_event.is_set():
                break
            if fleet is not None and fleet.shutdown_requested():
                break
            engine.start_store_threads()  # next round
            delay = polling_delay(
                parse_duration(config.polling_delay_mean),
                config.polling_delay_std_dev,
            )
            if engine.stop_event.wait(delay):
                break
    except BaseException as err:
        # The post-mortem artifact for a crashing run: spans + metric
        # snapshots as of the moment the main loop died.
        flight.dump(f"unhandled exception in ct-fetch: {err!r}")
        raise
    finally:
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as err:
                # Trace serialization failures must not mask the real
                # exception or skip the remaining shutdown steps.
                print(f"profiler stop failed: {err}", file=sys.stderr)
        run_stage["stage"] = "stopped"
        if printer:
            printer.stop()
        if health:
            health.stop()
        if metrics_server:
            metrics_server.stop()
        if query_server:
            query_server.stop()
        if fleet is not None:
            fleet.stop()
        if dumper:
            dumper.stop()
        if trace.enabled():
            path = trace.export()
            if path:
                print(f"trace written to {path}", file=sys.stderr)
        flight.unregister_section("fleet")
        flight.unregister_section("ckpt_chain")
        trace.set_process_attrs(worker=None, epoch=None)
        flight.uninstall()
        for signum, prev in prev_handlers.items():
            with contextlib.suppress(ValueError, OSError):
                signal.signal(signum, prev)
        engine.cleanup()
    return 1 if final_round_errors else 0


if __name__ == "__main__":
    sys.exit(main())
