"""ct-getcert: fetch one CT entry by index and print its PEM.

Reference: /root/reference/cmd/ct-getcert/ct-getcert.go:16-57 — flags
-log and -index, GetRawEntries(index, index), tolerate non-fatal parse
issues, PEM to stdout.

When a query plane is running (`queryPort` on ct-fetch), the fetch
routes through its ``/getcert`` proxy instead of hitting the log
directly — the serving plane already holds log credentials and rate
budgets, so edge clients need no log access of their own. Configure
via ``-queryAddr host:port`` or a ``-config`` ini whose ``queryPort``
is set (the plane is assumed local then). An unreachable plane falls
back to the direct transport, loudly.
"""

from __future__ import annotations

import argparse
import sys

from ct_mapreduce_tpu.core.der import der_to_pem
from ct_mapreduce_tpu.ingest.ctclient import CTLogClient
from ct_mapreduce_tpu.ingest.leaf import LeafDecodeError, decode_json_entry


def _query_addr(args) -> str:
    """Resolve the query-plane address: explicit flag first, then the
    config's queryPort (flag precedence mirrors CTConfig layering)."""
    if args.queryAddr:
        return args.queryAddr
    if args.config:
        from ct_mapreduce_tpu.config import CTConfig

        cfg = CTConfig.load(["-config", args.config])
        if cfg.query_port:
            return f"127.0.0.1:{cfg.query_port}"
    return ""


def main(argv: list[str] | None = None, transport=None, out=None) -> int:
    parser = argparse.ArgumentParser(prog="ct-getcert")
    parser.add_argument("-log", "--log", required=True, help="log URL")
    parser.add_argument("-index", "--index", type=int, default=0, help="index")
    parser.add_argument("-queryAddr", "--queryAddr", default="",
                        help="query-plane address (host:port); fetch via "
                        "its /getcert proxy instead of the log")
    parser.add_argument("-config", "--config", default="",
                        help="ini whose queryPort selects a local query "
                        "plane")
    args = parser.parse_args(argv)
    out = out or sys.stdout

    addr = _query_addr(args)
    if addr:
        from ct_mapreduce_tpu.serve.client import QueryClient, QueryError

        try:
            pem = QueryClient(addr).getcert(args.log, args.index)
            out.write(pem)
            return 0
        except QueryError as err:
            # The plane answered: its error is authoritative (the log
            # itself failed or has no such entry) — don't double-fetch.
            print(f"[{args.log}] query plane: {err}", file=sys.stderr)
            return 1
        except OSError as err:
            print(
                f"query plane unreachable at {addr} ({err}); "
                "falling back to direct log fetch",
                file=sys.stderr,
            )

    client = CTLogClient(args.log, transport=transport)
    entries = client.get_raw_entries(args.index, args.index)
    if not entries:
        print(f"[{args.log}] no entry at index {args.index}", file=sys.stderr)
        return 1
    for raw in entries:
        try:
            entry = decode_json_entry(
                raw.index,
                {"leaf_input": raw.leaf_input, "extra_data": raw.extra_data},
            )
        except LeafDecodeError as err:
            print(
                f"Erroneous certificate: log={args.log} index={raw.index} "
                f"err={err}",
                file=sys.stderr,
            )
            continue
        pem = der_to_pem(entry.cert_der)
        out.write(pem.decode() if isinstance(pem, bytes) else pem)
    return 0


if __name__ == "__main__":
    sys.exit(main())
