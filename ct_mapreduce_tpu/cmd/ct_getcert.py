"""ct-getcert: fetch one CT entry by index and print its PEM.

Reference: /root/reference/cmd/ct-getcert/ct-getcert.go:16-57 — flags
-log and -index, GetRawEntries(index, index), tolerate non-fatal parse
issues, PEM to stdout.
"""

from __future__ import annotations

import argparse
import sys

from ct_mapreduce_tpu.core.der import der_to_pem
from ct_mapreduce_tpu.ingest.ctclient import CTLogClient
from ct_mapreduce_tpu.ingest.leaf import LeafDecodeError, decode_json_entry


def main(argv: list[str] | None = None, transport=None, out=None) -> int:
    parser = argparse.ArgumentParser(prog="ct-getcert")
    parser.add_argument("-log", "--log", required=True, help="log URL")
    parser.add_argument("-index", "--index", type=int, default=0, help="index")
    args = parser.parse_args(argv)
    out = out or sys.stdout

    client = CTLogClient(args.log, transport=transport)
    entries = client.get_raw_entries(args.index, args.index)
    if not entries:
        print(f"[{args.log}] no entry at index {args.index}", file=sys.stderr)
        return 1
    for raw in entries:
        try:
            entry = decode_json_entry(
                raw.index,
                {"leaf_input": raw.leaf_input, "extra_data": raw.extra_data},
            )
        except LeafDecodeError as err:
            print(
                f"Erroneous certificate: log={args.log} index={raw.index} "
                f"err={err}",
                file=sys.stderr,
            )
            continue
        pem = der_to_pem(entry.cert_der)
        out.write(pem.decode() if isinstance(pem, bytes) else pem)
    return 0


if __name__ == "__main__":
    sys.exit(main())
