"""Dirty-group tracking for incremental ``CTMRFL02`` builds: opaque
per-group content tokens plus the prior-epoch group cache.

The per-group-universe format (docs/FILTER_FORMAT.md, CTMRFL02) makes
a group's serialized block a pure function of its OWN serial set and
the target FP rate — no other group's churn can move its bytes. That
is what makes verbatim reuse sound: if a group's content token is
unchanged since the previous build, the previous build's
:class:`~ct_mapreduce_tpu.filter.artifact.FilterGroup` (cascade arrays
included) serializes to identical block bytes, so the builder skips
key generation and the layer scatter for it entirely. Epoch-tick build
cost becomes O(churn), not O(corpus).

Tokens are OPAQUE to the cache: the only contract is that a group's
token changes whenever its serial set changes (a stale-token false
MISS costs a redundant rebuild — always safe; a false HIT would be a
correctness bug, which is why the capture layer only reports exact
hashes, see :meth:`SpillCaptureRing.content_hashes`). Two token
producers exist:

- :func:`content_token` — ``(n, XOR of sha256(serial)[:16])`` over a
  deduplicated serial set. XOR is commutative/associative, so the
  capture layer maintains it incrementally per new serial and a
  recomputation from the set agrees exactly. XOR of per-subset hashes
  is NOT a union hash (shared serials cancel) — fleet merges must
  recompute from the union set, never combine worker hashes.
- Analytic tokens (benches): any value that is a pure function of the
  group's logical content qualifies — ``tools/filtercost.py`` derives
  tokens from its synthetic corpus parameters without hashing.

Reuse is an optimization, never a semantic: the rebuilt artifact's
bytes are pinned identical to a from-scratch build by
tests/test_filter_format.py.

Deterministic throughout — no wall-clock, no RNG, no unsorted
iteration reaches any byte-producing path (ctmrlint: determinism).
"""

from __future__ import annotations

import hashlib
from typing import Optional


def serial_hash(serial: bytes) -> int:
    """One serial's 128-bit content hash (low 16 bytes of SHA-256),
    as an int so set hashes XOR-combine without numpy overflow."""
    return int.from_bytes(hashlib.sha256(serial).digest()[:16], "big")


def content_token(serials) -> tuple[int, int]:
    """``(n, xor-of-serial-hashes)`` over a DEDUPLICATED serial
    iterable (a set, or any iterable without repeats — a repeated
    serial would XOR-cancel). Pure function of the serial set."""
    h = 0
    n = 0
    for s in serials:
        h ^= serial_hash(s)
        n += 1
    return (n, h)


class GroupBuildCache:
    """Prior-epoch ``(issuer, expHour) → (token, fp_rate, group)``
    store for the CTMRFL02 incremental build path. ``get`` returns the
    cached :class:`FilterGroup` only on an exact (token, fp_rate)
    match; ``put`` records the groups a build produced; ``prune``
    drops groups absent from the current epoch so removed groups
    cannot resurrect from a stale entry."""

    def __init__(self) -> None:
        self._groups: dict = {}
        # Cumulative reuse accounting across builds (tests/tools).
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._groups)

    def get(self, issuer: str, exp_hour: int, token,
            fp_rate: float) -> Optional[object]:
        if token is None:
            self.misses += 1
            return None
        ent = self._groups.get((issuer, int(exp_hour)))
        if ent is None or ent[0] != token or ent[1] != float(fp_rate):
            self.misses += 1
            return None
        self.hits += 1
        return ent[2]

    def put(self, issuer: str, exp_hour: int, token,
            fp_rate: float, group) -> None:
        if token is None:
            return
        self._groups[(issuer, int(exp_hour))] = (
            token, float(fp_rate), group)

    def prune(self, live_keys) -> None:
        """Drop entries whose (issuer, expHour) is not in
        ``live_keys`` (the current build's group set)."""
        live = set(live_keys)
        for key in sorted(self._groups):
            if key not in live:
                del self._groups[key]
