"""Bloom filter-cascade primitives: the crlite-style exact-membership
structure compiled from the aggregation state (ROADMAP item 5(b)).

A cascade over an *included* key set I and an *excluded* key set X
(both ``uint32[n, 4]`` fingerprint rows, disjoint) is a list of Bloom
layers: layer 0 holds I at the target false-positive rate, layer 1
holds the members of X that layer 0 false-positives on, layer 2 the
members of I that layer 1 false-positives on, and so on until a layer
produces no false positives against its complement set. Querying walks
the layers; the index of the first missing layer decides (odd ⇒
included), and a key passing every layer is decided by the layer-count
parity. By construction every key of I ∪ X is answered EXACTLY —
included keys can never answer excluded — while keys outside both sets
see roughly the layer-0 false-positive rate (the serve plane's
table-confirm tier kills those).

Layer hashing reuses the pipeline's fingerprint discipline: element
keys are SHA-256 fingerprints (``core.packing.fingerprints_np`` host
mirror / the jitted ``ops.pipeline.fingerprints`` device path — see
:mod:`ct_mapreduce_tpu.filter.artifact`), and probe positions derive
from the key words by Kirsch-Mitzenmacher double hashing in wrapping
uint32 arithmetic, identical on device (jnp) and host (np) so the
device-built and host-built bitmaps are bit-equal. The device build
bit-scatters each layer into a bitmap in one jitted execution and the
bitmap is packed into little-endian ``uint32`` words host-side; small
layers (or ``CTMR_FILTER_DEVICE=0``) take the pure-NumPy lane — the
walker-fallback pattern applied to filter building.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

import numpy as np

from ct_mapreduce_tpu.telemetry import trace

# Knuth multiplicative-hash constants used to decorrelate layers: the
# key words are already uniform (SHA-256 output), the layer index is
# not — mixing it through these keeps layer ℓ's probes independent of
# layer ℓ+2's over the same keys.
_GOLD = np.uint32(0x9E3779B9)
_MIX = np.uint32(0x85EBCA6B)

# Below this many keys a layer builds on the host: the jit dispatch +
# readback overhead dwarfs the work (same threshold reasoning as the
# aggregator's padded contains probes).
DEVICE_BUILD_MIN = 4096

# A cascade that has not converged after this many layers indicates
# either non-disjoint inputs or a pathological fingerprint cluster;
# fail loudly rather than looping.
MAX_LAYERS = 64

# Stall escalation (round 19). The double-hash probe mixes the layer
# index into each key word LINEARLY, so two distinct keys agreeing in
# the low log2(m) bits of all four words probe the same positions at
# EVERY level of an m-bit layer — once the chase isolates such a
# "low-bit twin" pair in the 64-bit tail layers, the alternation
# ping-pongs forever (first observed at the 10⁶-serial scale legs;
# round-15 corpora were too small to isolate a pair). When every
# complement key false-positives on a layer (the stall signature),
# the layer deterministically rebuilds with doubled m (k recomputed
# from the same sizing formula) until the twins separate. Readers are
# unaffected — (m, k) are stored per layer in the artifact — and
# builds that never stall are byte-identical to round 15.
MAX_SIZE_ESCALATIONS = 32


def device_enabled() -> bool:
    """Filter layers may use the jitted build path (CTMR_FILTER_DEVICE:
    0 forces the host lane, 1 forces device even for tiny layers)."""
    v = os.environ.get("CTMR_FILTER_DEVICE", "").strip().lower()
    if v in ("0", "f", "false"):
        return False
    return True


def layer_k(m: int, n: int) -> int:
    """``k = (m/n) ln 2`` probes clamped to [1, 16] — split out so
    stall escalation recomputes k from the same formula it grew m
    under (byte-determinism: one sizing rule everywhere)."""
    return min(16, max(1, round((m / n) * math.log(2))))


def layer_params(n: int, p: float) -> tuple[int, int]:
    """Bloom sizing for ``n`` keys at false-positive rate ``p``:
    ``m = -n ln p / (ln 2)^2`` bits rounded up to whole uint32 words,
    ``k = (m/n) ln 2`` probes (clamped to [1, 16]). Pure integer
    output of a fixed float formula — part of the determinism contract
    (docs/FILTER_FORMAT.md): identical (n, p) always yields identical
    (m, k)."""
    if n <= 0:
        raise ValueError("layer over an empty key set")
    m = max(64, math.ceil(-n * math.log(p) / (math.log(2) ** 2)))
    m = ((m + 31) // 32) * 32
    return m, layer_k(m, n)


def _probe_np(keys: np.ndarray, m: int, k: int, layer: int) -> np.ndarray:
    """Probe positions ``int64[n, k]`` in [0, m) for uint32[n, 4] keys.
    Wrapping-uint32 double hashing; the jnp mirror below must stay
    arithmetically identical (bit-equal bitmaps are the device/host
    parity contract)."""
    keys = np.asarray(keys, np.uint32)
    # Layer-mix scalars wrapped in Python int space (numpy scalar
    # uint32 multiply warns on overflow; the array arithmetic below
    # wraps silently like the jnp mirror).
    lay_gold = np.uint32((layer * int(_GOLD)) & 0xFFFFFFFF)
    lay_mix = np.uint32((layer * int(_MIX)) & 0xFFFFFFFF)
    a = (keys[:, 0] ^ lay_gold) + keys[:, 2]
    b = ((keys[:, 1] ^ lay_mix) + keys[:, 3]) | np.uint32(1)
    i = np.arange(k, dtype=np.uint32)
    pos = a[:, None] + i[None, :] * b[:, None]
    return (pos % np.uint32(m)).astype(np.int64)


_jit_cache: dict = {}


def _layer_bits_jit():
    """Jitted device layer build: probe + bit-scatter in one execution.
    Scattering plain ``True`` values keeps the duplicate-index write
    deterministic (every colliding write stores the same value), so
    the readback equals the host lane's bitmap bit for bit."""
    fn = _jit_cache.get("bits")
    if fn is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("m", "k"))
        def fn(keys, valid, layer, m, k):
            keys = keys.astype(jnp.uint32)
            lay = layer.astype(jnp.uint32)
            a = (keys[:, 0] ^ (lay * jnp.uint32(0x9E3779B9))) + keys[:, 2]
            b = ((keys[:, 1] ^ (lay * jnp.uint32(0x85EBCA6B)))
                 + keys[:, 3]) | jnp.uint32(1)
            i = jnp.arange(k, dtype=jnp.uint32)
            pos = (a[:, None] + i[None, :] * b[:, None]) % jnp.uint32(m)
            # Padding lanes park at m and drop out of the scatter.
            pos = jnp.where(valid[:, None], pos.astype(jnp.int32), m)
            bits = jnp.zeros((m,), jnp.bool_)
            return bits.at[pos.reshape(-1)].set(True, mode="drop")

        _jit_cache["bits"] = fn
    return fn


def _pack_words(bits: np.ndarray) -> np.ndarray:
    """bool[m] (m % 32 == 0) → little-endian uint32[m/32] words; bit
    ``j`` of the bitmap is word ``j >> 5`` bit ``j & 31``."""
    return np.packbits(bits, bitorder="little").view("<u4")


def build_layer(keys: np.ndarray, m: int, k: int, layer: int,
                use_device: bool | None = None) -> np.ndarray:
    """One Bloom layer over ``keys``: uint32[m/32] bitmap words.

    Large layers scatter on device in one jitted execution (key count
    padded to the next power of two so compile shapes stay log-bounded,
    like the sharded dispatch); small layers or ``CTMR_FILTER_DEVICE=0``
    take the identical-by-construction NumPy lane."""
    n = int(keys.shape[0])
    if use_device is None:
        use_device = device_enabled() and n >= DEVICE_BUILD_MIN
    with trace.span("filter.layer", cat="filter", keys=n, m=m,
                    device=int(bool(use_device))):
        if use_device:
            import jax.numpy as jnp

            width = max(16, 1 << (max(n, 1) - 1).bit_length())
            padded = np.zeros((width, 4), np.uint32)
            padded[:n] = keys
            valid = np.zeros((width,), bool)
            valid[:n] = True
            bits = np.asarray(_layer_bits_jit()(
                jnp.asarray(padded), jnp.asarray(valid),
                np.uint32(layer), m, k))
        else:
            bits = np.zeros((m,), bool)
            if n:
                bits[_probe_np(keys, m, k, layer).reshape(-1)] = True
        return _pack_words(bits)


def layer_contains(words: np.ndarray, m: int, k: int, layer: int,
                   keys: np.ndarray) -> np.ndarray:
    """bool[n]: all ``k`` probe bits set for each key (vectorized
    host probe; the build's false-positive chase and every query path
    share this one implementation).

    Probes short-circuit (round 19): a lane leaves the working set at
    its first unset bit, so non-members — the overwhelming majority of
    the build's complement chase — cost ~1/(1-fill) probes instead of
    ``k``. Results are bit-identical to probing all ``k``."""
    n = int(keys.shape[0])
    if n == 0:
        return np.zeros((0,), bool)
    keys = np.asarray(keys, np.uint32)
    lay_gold = np.uint32((layer * int(_GOLD)) & 0xFFFFFFFF)
    lay_mix = np.uint32((layer * int(_MIX)) & 0xFFFFFFFF)
    a = (keys[:, 0] ^ lay_gold) + keys[:, 2]
    b = ((keys[:, 1] ^ lay_mix) + keys[:, 3]) | np.uint32(1)
    w = np.asarray(words, np.uint32)
    hit = np.ones((n,), bool)
    alive = np.arange(n, dtype=np.int64)
    for i in range(k):
        if alive.size == 0:
            break
        pos = ((a[alive] + np.uint32(i) * b[alive])
               % np.uint32(m)).astype(np.int64)
        ok = ((w[pos >> 5] >> (pos & 31).astype(np.uint32)) & 1) \
            .astype(bool)
        hit[alive[~ok]] = False
        alive = alive[ok]
    return hit


def _unique_rows(keys: np.ndarray) -> np.ndarray:
    """Sorted-unique uint32[n, 4] rows (deterministic set canon)."""
    if keys.shape[0] == 0:
        return keys.reshape(0, 4).astype(np.uint32)
    return np.unique(np.asarray(keys, np.uint32), axis=0)


@dataclass
class BloomLayer:
    m: int  # bits
    k: int  # probes per key
    words: np.ndarray  # uint32[m / 32]


@dataclass
class FilterCascade:
    """An exact-membership cascade over one included key set, relative
    to the excluded universe it was built against."""

    fp_rate: float
    n_included: int
    layers: list[BloomLayer] = field(default_factory=list)

    @classmethod
    def build(cls, included: np.ndarray, excluded: np.ndarray,
              fp_rate: float, use_device: bool | None = None
              ) -> "FilterCascade":
        """Build the cascade. ``included``/``excluded`` are
        ``uint32[n, 4]`` fingerprint rows; rows present in both sets
        (a 128-bit fingerprint collision between distinct identities —
        astronomically unlikely but cheap to guard) are dropped from
        the excluded side so the alternation converges."""
        inc = _unique_rows(np.asarray(included).reshape(-1, 4))
        exc = _unique_rows(np.asarray(excluded).reshape(-1, 4))
        if inc.shape[0] and exc.shape[0]:
            tag = lambda a: {bytes(r.tobytes()) for r in a}  # noqa: E731
            both = tag(inc) & tag(exc)
            if both:
                keep = np.array(
                    [bytes(r.tobytes()) not in both for r in exc], bool)
                exc = exc[keep]
        cascade = cls(fp_rate=float(fp_rate), n_included=int(inc.shape[0]))
        cur_in, cur_out = inc, exc
        level = 0
        while cur_in.shape[0]:
            if level >= MAX_LAYERS:
                raise RuntimeError(
                    f"filter cascade did not converge in {MAX_LAYERS} "
                    "layers (non-disjoint inputs?)")
            # Layer 0 carries the target rate; deeper layers hold tiny
            # FP sets where 0.5 (≈1.44 bits/entry) converges fastest —
            # the crlite sizing convention.
            p = fp_rate if level == 0 else 0.5
            m, k = layer_params(int(cur_in.shape[0]), p)
            words = build_layer(cur_in, m, k, level, use_device=use_device)
            if cur_out.shape[0] == 0:
                cascade.layers.append(BloomLayer(m=m, k=k, words=words))
                break
            hits = layer_contains(words, m, k, level, cur_out)
            esc = 0
            while bool(hits.all()):
                # Stall: every complement key false-positives (low-bit
                # twins — see MAX_SIZE_ESCALATIONS). Grow the layer
                # until they separate; identical keys never do.
                esc += 1
                if esc > MAX_SIZE_ESCALATIONS:
                    raise RuntimeError(
                        "filter cascade stalled: complement keys "
                        "false-positive at every layer size "
                        "(non-disjoint inputs?)")
                m *= 2
                k = layer_k(m, int(cur_in.shape[0]))
                words = build_layer(cur_in, m, k, level,
                                    use_device=use_device)
                hits = layer_contains(words, m, k, level, cur_out)
            cascade.layers.append(BloomLayer(m=m, k=k, words=words))
            cur_in, cur_out = cur_out[hits], cur_in
            level += 1
        return cascade

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """bool[n] membership verdicts. Exact for every key of the
        build's included ∪ excluded sets; probabilistic (≈ layer-0
        rate, to be table-confirmed) outside them."""
        keys = np.asarray(keys, np.uint32).reshape(-1, 4)
        n = keys.shape[0]
        ans = np.zeros((n,), bool)
        undecided = np.arange(n)
        depth = len(self.layers)
        for level, layer in enumerate(self.layers):
            if undecided.size == 0:
                return ans
            hit = layer_contains(layer.words, layer.m, layer.k, level,
                                 keys[undecided])
            ans[undecided[~hit]] = (level % 2) == 1
            undecided = undecided[hit]
        ans[undecided] = (depth % 2) == 1
        return ans

    def total_bits(self) -> int:
        return sum(layer.m for layer in self.layers)

    def bits_per_entry(self) -> float:
        return self.total_bits() / max(1, self.n_included)
