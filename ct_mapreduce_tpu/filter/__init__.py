"""Filter emission: crlite-style compact revocation-filter artifacts
compiled from the aggregation state (ROADMAP item 5(b), round 15).

- :mod:`ct_mapreduce_tpu.filter.cascade` — the Bloom filter-cascade
  primitive (exact membership over the observed universe, device-built
  layers with a host fallback lane).
- :mod:`ct_mapreduce_tpu.filter.artifact` — canonical keys, the
  versioned on-disk format (docs/FILTER_FORMAT.md), and the builders
  over live aggregators / merged fleet checkpoints.

``resolve_filter`` is the config surface: ``emitFilter`` /
``filterPath`` / ``filterFpRate`` directives with ``CTMR_EMIT_FILTER``
/ ``CTMR_FILTER_PATH`` / ``CTMR_FILTER_FP_RATE`` env equivalents.
"""

from __future__ import annotations

from typing import NamedTuple

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.filter.artifact import (  # noqa: F401
    DEFAULT_FP_RATE,
    FORMAT_FL01,
    FORMAT_FL02,
    FilterArtifact,
    build_artifact,
    build_artifact_from_sources,
    build_from_aggregator,
    build_from_merged,
    canonical_keys,
    default_format,
    normalize_format,
    read_artifact,
    write_artifact,
)
from ct_mapreduce_tpu.filter.cache import (  # noqa: F401
    GroupBuildCache,
    content_token,
)
from ct_mapreduce_tpu.filter.cascade import (  # noqa: F401
    BloomLayer,
    FilterCascade,
)
from ct_mapreduce_tpu.filter.spill import SpillCaptureRing  # noqa: F401
from ct_mapreduce_tpu.filter.stream import (  # noqa: F401
    ListGroupSource,
    PackedGroupSource,
)


_FILTER_KNOBS = (
    platprofile.Knob("emitFilter", "CTMR_EMIT_FILTER", False,
                     parse=platprofile.parse_bool_strict,
                     env_is_set=platprofile.any_set, post=bool),
    platprofile.Knob("filterPath", "CTMR_FILTER_PATH", "",
                     parse=str, is_set=platprofile.nonempty_str),
    platprofile.Knob("filterFpRate", "CTMR_FILTER_FP_RATE",
                     DEFAULT_FP_RATE, parse=float,
                     is_set=platprofile.pos_float,
                     post=lambda v: float(v)),
    # Round 19 — scaled builds: capture spill ring + streamed/fused
    # build shapes. 0/empty = built-in defaults (spill off).
    platprofile.Knob("filterCaptureSpillDir", "CTMR_FILTER_SPILL_DIR",
                     "", parse=str, is_set=platprofile.nonempty_str),
    platprofile.Knob("filterCaptureSpillMB", "CTMR_FILTER_SPILL_MB",
                     256, parse=int, is_set=platprofile.pos_int,
                     post=int),
    platprofile.Knob("filterStreamChunk", "CTMR_FILTER_STREAM_CHUNK",
                     0, parse=int, is_set=platprofile.pos_int,
                     post=int),
    platprofile.Knob("filterFusedLanes", "CTMR_FILTER_FUSED_LANES",
                     0, parse=int, is_set=platprofile.pos_int,
                     post=int),
    # Round 20 — artifact format: fl02 (per-group universes,
    # decoupled deltas, incremental rebuilds) is the default;
    # fl01 is the compatibility path. normalize_format raises on
    # junk, so a bad env value is ignored by the ladder and a bad
    # explicit/profile value fails loudly.
    platprofile.Knob("filterFormat", "CTMR_FILTER_FORMAT",
                     FORMAT_FL02, parse=normalize_format,
                     is_set=platprofile.nonempty_str,
                     post=normalize_format),
)


class FilterKnobs(NamedTuple):
    emit: bool
    path: str
    fp_rate: float
    spill_dir: str
    spill_mb: int
    stream_chunk: int  # 0 = stream.DEFAULT_STREAM_CHUNK
    fused_lanes: int  # 0 = fused.DEFAULT_MAX_LANES
    fmt: str = FORMAT_FL02  # artifact format ("fl01" | "fl02")


def resolve_filter(emit=None, path: str = "", fp_rate: float = 0.0,
                   state_path: str = "", spill_dir: str = "",
                   spill_mb: int = 0, stream_chunk: int = 0,
                   fused_lanes: int = 0, fmt: str = "") -> FilterKnobs:
    """Resolve the filter knobs through the shared platformProfile
    ladder (config/profile.py): explicit value (config directive /
    kwarg) > ``CTMR_EMIT_FILTER`` / ``CTMR_FILTER_PATH`` /
    ``CTMR_FILTER_FP_RATE`` / ``CTMR_FILTER_SPILL_DIR`` /
    ``CTMR_FILTER_SPILL_MB`` / ``CTMR_FILTER_STREAM_CHUNK`` /
    ``CTMR_FILTER_FUSED_LANES`` env > profile ``knobs.filter`` >
    defaults (off; ``<aggStatePath>.filter``; 0.01 target FP rate;
    spill off with a 256 MB memory tier; built-in stream/fused
    shapes; ``filterFormat`` / ``CTMR_FILTER_FORMAT`` → fl02).
    Unparseable env values are ignored, matching the config layer's
    tolerance."""
    r = platprofile.resolve_section("filter", _FILTER_KNOBS, {
        "emitFilter": emit,
        "filterPath": path or "",
        "filterFpRate": float(fp_rate or 0.0),
        "filterCaptureSpillDir": spill_dir or "",
        "filterCaptureSpillMB": int(spill_mb or 0),
        "filterStreamChunk": int(stream_chunk or 0),
        "filterFusedLanes": int(fused_lanes or 0),
        "filterFormat": fmt or "",
    })
    p = r["filterPath"]
    if not p and state_path:
        p = state_path + ".filter"
    return FilterKnobs(
        emit=r["emitFilter"], path=p, fp_rate=r["filterFpRate"],
        spill_dir=r["filterCaptureSpillDir"],
        spill_mb=r["filterCaptureSpillMB"],
        stream_chunk=r["filterStreamChunk"],
        fused_lanes=r["filterFusedLanes"],
        fmt=r["filterFormat"])


__all__ = [
    "DEFAULT_FP_RATE",
    "FORMAT_FL01",
    "FORMAT_FL02",
    "BloomLayer",
    "FilterArtifact",
    "FilterCascade",
    "FilterKnobs",
    "GroupBuildCache",
    "ListGroupSource",
    "PackedGroupSource",
    "SpillCaptureRing",
    "build_artifact",
    "build_artifact_from_sources",
    "build_from_aggregator",
    "build_from_merged",
    "canonical_keys",
    "content_token",
    "default_format",
    "normalize_format",
    "read_artifact",
    "resolve_filter",
    "write_artifact",
]
