"""Filter emission: crlite-style compact revocation-filter artifacts
compiled from the aggregation state (ROADMAP item 5(b), round 15).

- :mod:`ct_mapreduce_tpu.filter.cascade` — the Bloom filter-cascade
  primitive (exact membership over the observed universe, device-built
  layers with a host fallback lane).
- :mod:`ct_mapreduce_tpu.filter.artifact` — canonical keys, the
  versioned on-disk format (docs/FILTER_FORMAT.md), and the builders
  over live aggregators / merged fleet checkpoints.

``resolve_filter`` is the config surface: ``emitFilter`` /
``filterPath`` / ``filterFpRate`` directives with ``CTMR_EMIT_FILTER``
/ ``CTMR_FILTER_PATH`` / ``CTMR_FILTER_FP_RATE`` env equivalents.
"""

from __future__ import annotations

import os

from ct_mapreduce_tpu.filter.artifact import (  # noqa: F401
    DEFAULT_FP_RATE,
    FilterArtifact,
    build_artifact,
    build_from_aggregator,
    build_from_merged,
    canonical_keys,
    read_artifact,
    write_artifact,
)
from ct_mapreduce_tpu.filter.cascade import (  # noqa: F401
    BloomLayer,
    FilterCascade,
)


def resolve_filter(emit=None, path: str = "", fp_rate: float = 0.0,
                   state_path: str = "") -> tuple[bool, str, float]:
    """Resolve the filter-emission knobs: explicit value (config
    directive / kwarg) > ``CTMR_EMIT_FILTER`` / ``CTMR_FILTER_PATH`` /
    ``CTMR_FILTER_FP_RATE`` env > defaults (off; ``<aggStatePath>
    .filter``; 0.01 target FP rate). Unparseable env values are
    ignored, matching the config layer's tolerance."""
    if emit is None:
        ev = os.environ.get("CTMR_EMIT_FILTER", "").strip().lower()
        emit = ev in ("1", "t", "true")
    p = path or os.environ.get("CTMR_FILTER_PATH", "")
    if not p and state_path:
        p = state_path + ".filter"
    r = float(fp_rate or 0.0)
    if r <= 0:
        try:
            r = float(os.environ.get("CTMR_FILTER_FP_RATE", "") or 0.0)
        except ValueError:
            r = 0.0
    if r <= 0:
        r = DEFAULT_FP_RATE
    return bool(emit), p, r


__all__ = [
    "DEFAULT_FP_RATE",
    "BloomLayer",
    "FilterArtifact",
    "FilterCascade",
    "build_artifact",
    "build_from_aggregator",
    "build_from_merged",
    "canonical_keys",
    "read_artifact",
    "resolve_filter",
    "write_artifact",
]
