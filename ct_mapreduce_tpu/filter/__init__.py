"""Filter emission: crlite-style compact revocation-filter artifacts
compiled from the aggregation state (ROADMAP item 5(b), round 15).

- :mod:`ct_mapreduce_tpu.filter.cascade` — the Bloom filter-cascade
  primitive (exact membership over the observed universe, device-built
  layers with a host fallback lane).
- :mod:`ct_mapreduce_tpu.filter.artifact` — canonical keys, the
  versioned on-disk format (docs/FILTER_FORMAT.md), and the builders
  over live aggregators / merged fleet checkpoints.

``resolve_filter`` is the config surface: ``emitFilter`` /
``filterPath`` / ``filterFpRate`` directives with ``CTMR_EMIT_FILTER``
/ ``CTMR_FILTER_PATH`` / ``CTMR_FILTER_FP_RATE`` env equivalents.
"""

from __future__ import annotations

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.filter.artifact import (  # noqa: F401
    DEFAULT_FP_RATE,
    FilterArtifact,
    build_artifact,
    build_from_aggregator,
    build_from_merged,
    canonical_keys,
    read_artifact,
    write_artifact,
)
from ct_mapreduce_tpu.filter.cascade import (  # noqa: F401
    BloomLayer,
    FilterCascade,
)


_FILTER_KNOBS = (
    platprofile.Knob("emitFilter", "CTMR_EMIT_FILTER", False,
                     parse=platprofile.parse_bool_strict,
                     env_is_set=platprofile.any_set, post=bool),
    platprofile.Knob("filterPath", "CTMR_FILTER_PATH", "",
                     parse=str, is_set=platprofile.nonempty_str),
    platprofile.Knob("filterFpRate", "CTMR_FILTER_FP_RATE",
                     DEFAULT_FP_RATE, parse=float,
                     is_set=platprofile.pos_float,
                     post=lambda v: float(v)),
)


def resolve_filter(emit=None, path: str = "", fp_rate: float = 0.0,
                   state_path: str = "") -> tuple[bool, str, float]:
    """Resolve the filter-emission knobs through the shared
    platformProfile ladder (config/profile.py): explicit value (config
    directive / kwarg) > ``CTMR_EMIT_FILTER`` / ``CTMR_FILTER_PATH`` /
    ``CTMR_FILTER_FP_RATE`` env > profile ``knobs.filter`` > defaults
    (off; ``<aggStatePath>.filter``; 0.01 target FP rate). Unparseable
    env values are ignored, matching the config layer's tolerance."""
    r = platprofile.resolve_section("filter", _FILTER_KNOBS, {
        "emitFilter": emit,
        "filterPath": path or "",
        "filterFpRate": float(fp_rate or 0.0),
    })
    p = r["filterPath"]
    if not p and state_path:
        p = state_path + ".filter"
    return r["emitFilter"], p, r["filterFpRate"]


__all__ = [
    "DEFAULT_FP_RATE",
    "BloomLayer",
    "FilterArtifact",
    "FilterCascade",
    "build_artifact",
    "build_from_aggregator",
    "build_from_merged",
    "canonical_keys",
    "read_artifact",
    "resolve_filter",
    "write_artifact",
]
