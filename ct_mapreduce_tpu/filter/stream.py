"""Streamed canonical-key production for filter builds (round 19).

The round-15 builder materialized, for the whole corpus at once: the
per-serial Python ``bytes`` lists, one ``uint8[N, MAX_SERIAL_BYTES]``
message matrix, and the ``uint32[N, 4]`` key array. At 10⁸ serials the
first two alone are several GB of host RSS before a single layer is
built. This module bounds that: serial corpora flow as *group sources*
yielding fixed-size packed chunks, and canonical keys are computed one
chunk at a time through the jitted fingerprint kernel (or the
``fingerprints_np`` host mirror) — only the ``[N, 4]`` key arena (16
bytes/serial) is ever resident for the whole corpus.

Two source flavors:

- :class:`ListGroupSource` wraps the legacy ``{(issuer, expHour):
  serial iterable}`` shape and owns the round-15 semantics exactly
  (``sorted(set(serials))`` — the unique count is the group's ``n`` in
  the artifact header).
- :class:`PackedGroupSource` feeds pre-packed numpy chunks (length
  vector + zero-padded message matrix) so a synthetic or spill-drained
  corpus never mints per-serial Python objects at all. The provider
  CONTRACT is that serials within a group are unique; duplicates would
  inflate the header's ``n`` (the bitmap bits themselves are
  set-determined and immune).

Determinism: keys are a pure function of (ordinal, expHour, serial) —
chunk boundaries, device-vs-host lanes, and source flavor change no
bytes (pinned by the round-19 byte-identity property tests).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Iterator, Optional

import numpy as np

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.telemetry import trace

# Serials per streamed key block. Bounds the transient message matrix
# (chunk × MAX_SERIAL_BYTES bytes) and keeps the fingerprint kernel's
# compile shapes fixed; the resolve_filter knob filterStreamChunk /
# CTMR_FILTER_STREAM_CHUNK overrides. 2^16 measured fastest on the
# 1-core CI box (the 2^20-wide SHA dispatch is cache-hostile there:
# ~500K vs ~700K serials/s) and is shape-cheap everywhere.
DEFAULT_STREAM_CHUNK = 1 << 16


def oversized_key(ordinal: int, exp_hour: int, serial: bytes) -> np.ndarray:
    """The host-lane key for a serial past MAX_SERIAL_BYTES: a disjoint
    hashlib encoding no conforming fingerprint message can collide with
    (marker byte 0xFF > MAX_SERIAL_BYTES in the length position)."""
    msg = (
        int(exp_hour).to_bytes(4, "big", signed=True)
        + int(ordinal).to_bytes(4, "big")
        + b"\xff"
        + len(serial).to_bytes(4, "big")
        + serial
    )
    digest = hashlib.sha256(msg).digest()
    return np.array(
        [int.from_bytes(digest[16 + 4 * i: 20 + 4 * i], "big")
         for i in range(4)], np.uint32)


def pack_serials(serials: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized ``list[bytes]`` → (lens int64[c], mat uint8[c, MAX])
    for conforming serials (every len ≤ MAX_SERIAL_BYTES). One
    ``b"".join`` + two scatters instead of a per-serial Python loop."""
    c = len(serials)
    mat = np.zeros((c, packing.MAX_SERIAL_BYTES), np.uint8)
    if c == 0:
        return np.zeros((0,), np.int64), mat
    lens = np.fromiter((len(s) for s in serials), np.int64, c)
    joined = b"".join(serials)
    if joined:
        buf = np.frombuffer(joined, np.uint8)
        row = np.repeat(np.arange(c), lens)
        offs = np.concatenate(([0], np.cumsum(lens)[:-1]))
        col = np.arange(buf.size) - np.repeat(offs, lens)
        mat[row, col] = buf
    return lens, mat


class GroupSource:
    """One (issuerID, expHour) group's serials as packed chunks.

    ``chunks(chunk_size)`` yields ``(lens, mat, oversized)`` blocks:
    conforming serials as a packed matrix, oversized ones as raw bytes
    (the host-lane path). ``n`` is the group's UNIQUE serial count —
    it lands verbatim in the artifact header.

    ``content_token`` (optional, default None) is an opaque value that
    changes whenever the group's serial set changes — the dirty-group
    key of the CTMRFL02 incremental build path (filter/cache.py). None
    means "unknown": the group always rebuilds."""

    issuer: str
    exp_hour: int
    n: int
    content_token = None

    def chunks(self, chunk_size: int) -> Iterator[
            tuple[np.ndarray, np.ndarray, list[bytes]]]:
        raise NotImplementedError


class ListGroupSource(GroupSource):
    """Legacy serial-iterable shape; dedups at construction (the
    round-15 ``sorted(set(...))`` semantics — sorting is not needed for
    the bytes, which are set-determined, but keeps the walk order of
    the legacy path for debuggability)."""

    def __init__(self, issuer: str, exp_hour: int,
                 serials: Iterable[bytes], content_token=None):
        self.issuer = issuer
        self.exp_hour = int(exp_hour)
        self._serials = sorted(set(serials))
        self.n = len(self._serials)
        self.content_token = content_token

    def chunks(self, chunk_size: int):
        for start in range(0, self.n, chunk_size):
            block = self._serials[start: start + chunk_size]
            fit = [s for s in block
                   if len(s) <= packing.MAX_SERIAL_BYTES]
            oversized = [s for s in block
                         if len(s) > packing.MAX_SERIAL_BYTES]
            lens, mat = pack_serials(fit)
            yield lens, mat, oversized


class PackedGroupSource(GroupSource):
    """Pre-packed chunk provider: ``provider(chunk_size)`` must yield
    ``(lens, mat, oversized)`` blocks covering exactly ``n`` unique
    serials. Used by the scale driver (synthetic corpora generated
    chunk-by-chunk, never resident) and spill-drained captures."""

    def __init__(self, issuer: str, exp_hour: int, n: int, provider,
                 content_token=None):
        self.issuer = issuer
        self.exp_hour = int(exp_hour)
        self.n = int(n)
        self._provider = provider
        self.content_token = content_token

    def chunks(self, chunk_size: int):
        return self._provider(chunk_size)


def _rss_bytes() -> int:
    """Current RSS via /proc (linux; 0 elsewhere). Sampled at chunk
    and round boundaries by the builders — a sampled peak, honest
    about missing sub-chunk transients."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * 4096
    except (OSError, ValueError, IndexError):
        return 0


def key_blocks(source: GroupSource, ordinal: int, chunk_size: int,
               use_device: Optional[bool] = None
               ) -> Iterator[np.ndarray]:
    """Stream one group's canonical keys as ``uint32[c, 4]`` blocks.

    Conforming serials hash through the pipeline fingerprint kernels
    (device when the block is large, padded to the next power of two
    so compile shapes stay log-bounded; the ``fingerprints_np`` host
    mirror otherwise); oversized serials take the disjoint hashlib
    lane. Block boundaries change no bytes."""
    from ct_mapreduce_tpu.filter.cascade import (
        DEVICE_BUILD_MIN,
        device_enabled,
    )

    for lens, mat, oversized in source.chunks(chunk_size):
        c = int(lens.shape[0])
        out = np.zeros((c + len(oversized), 4), np.uint32)
        with trace.span("filter.stream_chunk", cat="filter",
                        lanes=c + len(oversized),
                        oversized=len(oversized)):
            if c:
                dev = use_device
                if dev is None:
                    dev = device_enabled() and c >= DEVICE_BUILD_MIN
                ords = np.full((c,), int(ordinal), np.int64)
                ehs = np.full((c,), source.exp_hour, np.int64)
                if dev:
                    out[:c] = _fingerprints_device(ords, ehs, mat, lens)
                else:
                    out[:c] = packing.fingerprints_np(ords, ehs, mat,
                                                      lens)
            for j, sb in enumerate(oversized):
                out[c + j] = oversized_key(ordinal, source.exp_hour, sb)
        yield out


def _fingerprints_device(ords: np.ndarray, ehs: np.ndarray,
                         mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Jitted fingerprint dispatch, block padded to the next power of
    two (min 16) — one compile per log bucket, not per ragged block.
    Padding lanes are sliced off; their garbage rows never escape."""
    from ct_mapreduce_tpu.filter.artifact import _fingerprints_jit

    import jax.numpy as jnp

    c = int(lens.shape[0])
    width = max(16, 1 << (c - 1).bit_length())
    if width != c:
        pmat = np.zeros((width, mat.shape[1]), np.uint8)
        pmat[:c] = mat
        pords = np.zeros((width,), np.int64)
        pords[:c] = ords
        pehs = np.zeros((width,), np.int64)
        pehs[:c] = ehs
        plens = np.zeros((width,), np.int64)
        plens[:c] = lens
        ords, ehs, mat, lens = pords, pehs, pmat, plens
    fps = np.asarray(_fingerprints_jit()(
        jnp.asarray(ords.astype(np.int32)),
        jnp.asarray(ehs.astype(np.int32)),
        jnp.asarray(mat),
        jnp.asarray(lens.astype(np.int32)),
    ))
    return fps[:c]


def collect_keys(source: GroupSource, ordinal: int, chunk_size: int,
                 use_device: Optional[bool] = None) -> np.ndarray:
    """All of one group's keys as ``uint32[n, 4]`` — streamed through
    :func:`key_blocks` so only the key arena is corpus-sized."""
    out = np.zeros((source.n, 4), np.uint32)
    pos = 0
    for block in key_blocks(source, ordinal, chunk_size, use_device):
        out[pos: pos + block.shape[0]] = block
        pos += block.shape[0]
    if pos != source.n:
        raise ValueError(
            f"group source ({source.issuer!r}, {source.exp_hour}) "
            f"yielded {pos} serials, declared n={source.n}")
    return out
