"""Fused multi-group cascade builds: one scatter dispatch per
layer-round, not one per (group, layer) (round 19).

The round-15 builder walked groups one at a time, and each group's
cascade issued one jitted bit-scatter per layer — at fleet scale that
is thousands of tiny device dispatches whose fixed toll dwarfs the
scatter work (the same amortize-many-small-problems-into-one-dispatch
discipline as the staged ingest queue and the batched ECDSA lane).
This module builds EVERY group's layer ``ℓ`` in lockstep: the active
groups' current key sets pack into padded ``[B, 4]`` lane batches with
a per-lane group id, and ONE jitted execution per batch scatters all
of them into a concatenated per-group bitmap arena (per-lane ``m``/
``k``/bit-offset gathered from group-indexed parameter vectors). The
false-positive chase re-probes each group's complement against the
same arena. Compile shapes stay log-bounded: lane widths and the
arena length pad to powers of two, the per-dispatch probe count is
the power-of-two ceiling of the round's largest ``k``.

**Byte identity is the contract.** For every group the emitted layers
``(m, k, words)`` equal :meth:`FilterCascade.build`'s exactly:

- sizing sees the same counts (per-group unique-key sets, the
  inc∩exc drop replicated through the global sorted-unique key table
  ``S`` — a group's excluded universe is precisely ``S`` minus its own
  rows);
- scatter positions are the same wrapping-uint32 double-hash math,
  offset into the group's arena slice (offsets are multiples of 32
  bits, so the packed words slice out exactly);
- the chase classifies the same key sets (order within a set is
  immaterial: bitmaps and counts are set-determined), so every deeper
  layer sees the same inputs.

The NumPy lane mirrors the device scatter bit for bit (the
walker-fallback pattern), so ``CTMR_FILTER_DEVICE=0`` builds the same
artifact. No wall-clock, no RNG, no unsorted iteration enters this
module (ctmrlint: determinism).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.filter.cascade import (
    _GOLD,
    _MIX,
    _pack_words,
    DEVICE_BUILD_MIN,
    MAX_LAYERS,
    BloomLayer,
    FilterCascade,
    device_enabled,
    layer_params,
)
from ct_mapreduce_tpu.telemetry import trace

# Lanes per fused scatter dispatch (resolve_filter: filterFusedLanes /
# CTMR_FILTER_FUSED_LANES). Bounds the per-dispatch key gather and the
# jitted program's probe tensor ([B, kmax]).
DEFAULT_MAX_LANES = 1 << 20

# Bits per arena segment. Bounds the (device) bitmap allocation AND
# keeps every scatter target inside int32 (offset + position < 2^31);
# a layer-round whose groups want more bits splits into segments.
DEFAULT_MAX_ARENA_BITS = 1 << 30

_INT32_BITS_CEIL = (1 << 31) - 1


@dataclass
class FusedStats:
    """What the fused build actually dispatched — the collapse the
    round-19 acceptance records (per-group equivalent vs fused)."""

    rounds: int = 0
    peak_rss: int = 0  # max sampled RSS at sort/round boundaries
    dispatches: int = 0  # fused scatter batch executions (device or np)
    device_dispatches: int = 0
    layers: int = 0  # per-(group, layer) count == legacy dispatch count
    scatter_lanes: int = 0
    probe_lanes: int = 0
    escalations: int = 0  # stall-escalation layer rebuilds (rare tail)
    groups_per_dispatch: list = field(default_factory=list)

    def mean_groups_per_dispatch(self) -> float:
        if not self.groups_per_dispatch:
            return 0.0
        return float(sum(self.groups_per_dispatch)
                     / len(self.groups_per_dispatch))


def _pow2(n: int, floor: int = 1) -> int:
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


def _rows_hilo(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint32[n, 4] rows → two uint64 sort keys (row order is only a
    canon for run detection; the artifact bytes are set-determined)."""
    r = np.asarray(rows, np.uint32)
    hi = (r[:, 0].astype(np.uint64) << np.uint64(32)) | r[:, 1]
    lo = (r[:, 2].astype(np.uint64) << np.uint64(32)) | r[:, 3]
    return hi, lo


def _unique_idx(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Indices of one representative per distinct (hi, lo) pair."""
    if hi.size == 0:
        return np.zeros((0,), np.int64)
    order = np.lexsort((lo, hi))
    shi, slo = hi[order], lo[order]
    new = np.ones(order.size, bool)
    new[1:] = (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])
    return order[new]


_jit_cache: dict = {}


def _fused_bits_jit():
    """One jitted scatter for a whole layer-round batch: per-lane
    group ids gather (m, k, offset) from group-parameter vectors, and
    every lane's probes land in its group's arena slice. Scattering
    plain ``True`` keeps duplicate-index writes deterministic, exactly
    like the per-group kernel."""
    fn = _jit_cache.get("fused")
    if fn is None:
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("kmax",),
                           donate_argnums=(0,))
        def fn(bits, keys, gid, valid, layer, offs, ms, ks, kmax):
            keys = keys.astype(jnp.uint32)
            lay = layer.astype(jnp.uint32)
            m = ms[gid].astype(jnp.uint32)
            off = offs[gid]
            kk = ks[gid].astype(jnp.uint32)
            a = (keys[:, 0] ^ (lay * jnp.uint32(0x9E3779B9))) + keys[:, 2]
            b = ((keys[:, 1] ^ (lay * jnp.uint32(0x85EBCA6B)))
                 + keys[:, 3]) | jnp.uint32(1)
            i = jnp.arange(kmax, dtype=jnp.uint32)
            pos = (a[:, None] + i[None, :] * b[:, None]) % m[:, None]
            tgt = off[:, None] + pos.astype(jnp.int32)
            live = valid[:, None] & (i[None, :] < kk[:, None])
            # Dead probe slots (padding lanes, i >= k) park past the
            # arena and drop out of the scatter.
            tgt = jnp.where(live, tgt, bits.shape[0])
            return bits.at[tgt.reshape(-1)].set(True, mode="drop")

        _jit_cache["fused"] = fn
    return fn


def _scatter_np(arena: np.ndarray, keys: np.ndarray, gid: np.ndarray,
                layer: int, offs: np.ndarray, ms: np.ndarray,
                ks: np.ndarray, kmax: int) -> None:
    """NumPy mirror of the fused scatter (bit-equal by the same
    arithmetic as cascade._probe_np, plus the group offset)."""
    keys = np.asarray(keys, np.uint32)
    lay_gold = np.uint32((layer * int(_GOLD)) & 0xFFFFFFFF)
    lay_mix = np.uint32((layer * int(_MIX)) & 0xFFFFFFFF)
    a = (keys[:, 0] ^ lay_gold) + keys[:, 2]
    b = ((keys[:, 1] ^ lay_mix) + keys[:, 3]) | np.uint32(1)
    i = np.arange(kmax, dtype=np.uint32)
    m = ms[gid].astype(np.uint32)
    pos = (a[:, None] + i[None, :] * b[:, None]) % m[:, None]
    tgt = offs[gid].astype(np.int64)[:, None] + pos.astype(np.int64)
    live = i[None, :] < ks[gid][:, None]
    arena[tgt[live]] = True


def fused_contains(words_all: np.ndarray, idx_chunks: list,
                   S: np.ndarray, layer: int, offs_words: np.ndarray,
                   ms: np.ndarray, ks: np.ndarray) -> list:
    """Probe mixed-group lanes against the round's packed arena in one
    vectorized pass: ``idx_chunks`` is ``[(local_gid, S-index array),
    ...]``; returns per entry the boolean hit vector. Bit-equal to
    per-group :func:`layer_contains` (same probe math; a group's
    32-bit-aligned arena offset shifts whole words)."""
    if not idx_chunks:
        return []
    lanes = np.concatenate([idx for _, idx in idx_chunks])
    gid = np.concatenate(
        [np.full((idx.size,), g, np.int32) for g, idx in idx_chunks])
    keys = S[lanes]
    lay_gold = np.uint32((layer * int(_GOLD)) & 0xFFFFFFFF)
    lay_mix = np.uint32((layer * int(_MIX)) & 0xFFFFFFFF)
    a = (keys[:, 0] ^ lay_gold) + keys[:, 2]
    b = ((keys[:, 1] ^ lay_mix) + keys[:, 3]) | np.uint32(1)
    kmax = int(ks.max()) if ks.size else 1
    m = ms[gid].astype(np.uint32)
    # Arena segments are int32-bounded by construction, so every
    # absolute bit position fits int32 — half the index traffic of
    # int64 on the gather-heavy chase.
    off_bits = (offs_words[gid] * 32).astype(np.int32)
    kk = ks[gid].astype(np.int32)
    w = np.asarray(words_all, np.uint32)
    n = lanes.size
    # Short-circuit probing (see layer_contains): a lane leaves the
    # working set at its first unset bit — bit-identical results,
    # ~1/(1-fill) probes per non-member instead of kmax.
    hit = np.ones((n,), bool)
    alive = np.arange(n, dtype=np.int32)
    for i in range(kmax):
        if alive.size == 0:
            break
        act = alive[kk[alive] > i]
        if act.size == 0:
            break
        pos = (a[act] + np.uint32(i) * b[act]) % m[act]
        abs_pos = off_bits[act] + pos.astype(np.int32)
        ok = ((w[abs_pos >> 5] >> (abs_pos & 31).astype(np.uint32))
              & 1).astype(bool)
        hit[act[~ok]] = False
        alive = act[ok]
    out = []
    pos0 = 0
    for _, idx in idx_chunks:
        out.append(hit[pos0: pos0 + idx.size])
        pos0 += idx.size
    return out


class _GroupState:
    __slots__ = ("inc", "cur_in", "cur_out", "active", "cascade")

    def __init__(self, inc: np.ndarray, fp_rate: float):
        self.inc = inc  # int32 S-indices, the group's unique keys
        self.cur_in = inc
        self.cur_out: Optional[np.ndarray] = None  # None ⇒ complement
        self.active = inc.size > 0
        self.cascade = FilterCascade(fp_rate=float(fp_rate),
                                     n_included=int(inc.size))


def _complement_chunks(U: int, inc: np.ndarray, chunk: int):
    """Stream S-indices NOT in the (sorted) ``inc`` index set — the
    group's excluded universe at layer 0, never materialized whole."""
    for s in range(0, U, chunk):
        e = min(U, s + chunk)
        idx = np.arange(s, e, dtype=np.int64)
        a, b = np.searchsorted(inc, [s, e])
        members = inc[a:b].astype(np.int64)
        if members.size:
            mask = np.ones(e - s, bool)
            mask[members - s] = False
            idx = idx[mask]
        if idx.size:
            yield idx


def build_cascades_fused(
        group_keys: list, fp_rate: float,
        use_device: Optional[bool] = None,
        max_lanes: int = 0,
        max_arena_bits: int = 0,
        consume: bool = False) -> tuple[list, FusedStats]:
    """Build every group's cascade in fused layer-rounds.

    ``group_keys`` is one ``uint32[n_g, 4]`` raw key array per group
    (duplicates tolerated, as in the per-group builder). Returns the
    per-group :class:`FilterCascade` list (same order) plus the
    dispatch statistics. Semantics mirror ``FilterCascade.build(keys_g,
    all_other_keys, fp_rate)`` per group, byte-identically.
    ``consume=True`` lets the builder free each raw key array as soon
    as its unique rows are extracted (the caller's list entries become
    None — the 10⁸-scale RSS lever)."""
    max_lanes = int(max_lanes) or DEFAULT_MAX_LANES
    max_arena_bits = int(max_arena_bits) or DEFAULT_MAX_ARENA_BITS
    G = len(group_keys)
    stats = FusedStats()
    if G == 0:
        return [], stats

    # Global sorted-unique key table S + per-group unique index sets.
    # A group's excluded universe (every OTHER group's keys, minus its
    # own — the inc∩exc drop) is exactly S minus its inc set: every S
    # row outside inc_g belongs to some other group by construction.
    per_group_idx = []
    cat_rows = []
    for g in range(G):
        rows = np.asarray(group_keys[g], np.uint32).reshape(-1, 4)
        if consume:
            # The raw key arrays are not needed once their unique rows
            # are extracted (at 10⁸ serials each copy is corpus-sized).
            group_keys[g] = None
        hi, lo = _rows_hilo(rows)
        cat_rows.append(rows[_unique_idx(hi, lo)])
        del rows
    from ct_mapreduce_tpu.filter.stream import _rss_bytes

    gid_all = np.concatenate(
        [np.full((cat_rows[g].shape[0],), g, np.int32)
         for g in range(G)]) if cat_rows else np.zeros((0,), np.int32)
    all_rows = (np.concatenate(cat_rows) if cat_rows
                else np.zeros((0, 4), np.uint32))
    del cat_rows
    hi, lo = _rows_hilo(all_rows)
    order = np.lexsort((lo, hi))
    # The global sort is the build's RSS high-water mark at scale —
    # sample it where it peaks, not just at round boundaries.
    stats.peak_rss = max(stats.peak_rss, _rss_bytes())
    shi, slo = hi[order], lo[order]
    del hi, lo
    new = np.ones(order.size, bool)
    if order.size:
        new[1:] = (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])
    del shi, slo
    u_of_sorted = np.cumsum(new, dtype=np.int64) - 1
    S = all_rows[order[new]]
    U = int(S.shape[0])
    gid_sorted = gid_all[order]
    del all_rows, gid_all, order, new
    by_group = np.argsort(gid_sorted, kind="stable")
    counts = np.bincount(gid_sorted, minlength=G)
    del gid_sorted
    u_by_group = u_of_sorted[by_group]
    del u_of_sorted, by_group
    pos0 = 0
    for g in range(G):
        per_group_idx.append(
            u_by_group[pos0: pos0 + counts[g]].astype(np.int32))
        pos0 += int(counts[g])
    del u_by_group

    states = [_GroupState(per_group_idx[g], fp_rate) for g in range(G)]
    del per_group_idx
    stats.peak_rss = max(stats.peak_rss, _rss_bytes())

    level = 0
    while True:
        actives = [g for g in range(G)
                   if states[g].active and states[g].cur_in.size > 0]
        if not actives:
            break
        if level >= MAX_LAYERS:
            raise RuntimeError(
                f"filter cascade did not converge in {MAX_LAYERS} "
                "layers (non-disjoint inputs?)")
        p = fp_rate if level == 0 else 0.5
        params = {g: layer_params(int(states[g].cur_in.size), p)
                  for g in actives}
        # Arena segments: greedy by bits, int32-safe by construction.
        segments: list[list[int]] = []
        seg: list[int] = []
        seg_bits = 0
        for g in actives:
            m = params[g][0]
            if m > _INT32_BITS_CEIL:
                raise ValueError(
                    f"layer of {m} bits exceeds the int32 scatter "
                    "range; raise the FP rate or shard the corpus")
            if seg and seg_bits + m > max_arena_bits:
                segments.append(seg)
                seg, seg_bits = [], 0
            seg.append(g)
            seg_bits += m
        if seg:
            segments.append(seg)

        for seg in segments:
            _build_segment(states, seg, params, S, U, level,
                           use_device, max_lanes, stats)
        stats.rounds += 1
        stats.peak_rss = max(stats.peak_rss, _rss_bytes())
        level += 1

    return [st.cascade for st in states], stats


def _build_segment(states, seg, params, S, U, level, use_device,
                   max_lanes, stats: FusedStats) -> None:
    offs = np.zeros((len(seg),), np.int64)
    total = 0
    for j, g in enumerate(seg):
        offs[j] = total
        total += params[g][0]
    ms = np.array([params[g][0] for g in seg], np.int64)
    ks = np.array([params[g][1] for g in seg], np.int64)
    kmax = _pow2(int(ks.max()))
    total_lanes = int(sum(states[g].cur_in.size for g in seg))
    dev = use_device
    if dev is None:
        dev = device_enabled() and total_lanes >= DEVICE_BUILD_MIN

    with trace.span("filter.fused_layer", cat="filter", level=level,
                    groups=len(seg), lanes=total_lanes,
                    bits=total, device=int(bool(dev))):
        # -- fused scatter, chunked to max_lanes per dispatch --------
        chunks = _lane_chunks(states, seg, max_lanes)
        if dev:
            arena = _scatter_device(chunks, S, offs, ms, ks, level,
                                    total, kmax, max_lanes, stats)
        else:
            arena = np.zeros((total,), bool)
            for lane_list in chunks:
                keys = np.concatenate([S[idx] for _, idx in lane_list])
                gid = np.concatenate(
                    [np.full((idx.size,), j, np.int32)
                     for j, idx in lane_list])
                _scatter_np(arena, keys, gid, level, offs, ms,
                            ks.astype(np.int64), kmax)
                stats.dispatches += 1
                stats.groups_per_dispatch.append(len(lane_list))
        stats.scatter_lanes += total_lanes
        stats.layers += len(seg)
        words_all = _pack_words(arena)
        del arena

        # -- record layers ------------------------------------------
        for j, g in enumerate(seg):
            w0 = int(offs[j]) // 32
            words = words_all[w0: w0 + int(ms[j]) // 32].copy()
            states[g].cascade.layers.append(
                BloomLayer(m=int(ms[j]), k=int(ks[j]), words=words))

        # -- false-positive chase: the complement re-probes in the
        # same fused mixed-group batches the scatter used ------------
        offs_words = (offs // 32).astype(np.int64)
        collectors: dict[int, list] = {}
        probed_n: dict[int, int] = {}
        pending: list = []
        pending_n = 0

        def flush_probes():
            nonlocal pending, pending_n
            if not pending:
                return
            hits = fused_contains(words_all, pending, S, level,
                                  offs_words, ms, ks)
            for (j, idx), hit in zip(pending, hits):
                collectors[j].append(idx[hit].astype(np.int32))
                probed_n[j] += int(idx.size)
                stats.probe_lanes += int(idx.size)
            pending, pending_n = [], 0

        def out_chunks(st):
            if st.cur_out is None:
                return _complement_chunks(U, st.inc, max_lanes)
            out = st.cur_out
            return (out[s: s + max_lanes]
                    for s in range(0, out.size, max_lanes))

        probing: list[int] = []
        for j, g in enumerate(seg):
            st = states[g]
            if st.cur_out is None and U - st.inc.size == 0:
                st.active = False  # single-group universe: no chase
                st.cur_in = np.zeros((0,), np.int32)
                continue
            if st.cur_out is not None and st.cur_out.size == 0:
                st.active = False  # reference: break after the layer
                st.cur_in = np.zeros((0,), np.int32)
                continue
            probing.append(j)
            collectors[j] = []
            probed_n[j] = 0
            for idx in out_chunks(st):
                pending.append((j, idx))
                pending_n += int(idx.size)
                if pending_n >= max_lanes:
                    flush_probes()
        flush_probes()
        for j in probing:
            st = states[seg[j]]
            hits = collectors[j]
            new_in = (np.concatenate(hits) if hits
                      else np.zeros((0,), np.int32))
            if new_in.size and new_in.size == probed_n[j]:
                # Stall: the group's whole complement false-positived
                # (low-bit twins — cascade.MAX_SIZE_ESCALATIONS). Same
                # deterministic escalation as the reference path: grow
                # THIS group's layer until the twins separate, then
                # replace its arena slice.
                new_in = _escalate_group(st, params[seg[j]], S, U,
                                         level, use_device, max_lanes,
                                         stats)
            st.cur_out = st.cur_in
            st.cur_in = new_in


def _escalate_group(st, params_jg, S, U, level, use_device,
                    max_lanes, stats: FusedStats) -> np.ndarray:
    """Reference-identical stall escalation for one group: double m
    (k recomputed by the shared sizing formula), rebuild the layer
    over the group's cur_in keys, and re-probe its complement until
    not every key hits. Replaces the group's last recorded layer."""
    from ct_mapreduce_tpu.filter.cascade import (
        MAX_SIZE_ESCALATIONS,
        build_layer,
        layer_contains,
        layer_k,
    )

    m, k = params_jg
    cur_keys = S[st.cur_in]
    esc = 0
    while True:
        esc += 1
        if esc > MAX_SIZE_ESCALATIONS:
            raise RuntimeError(
                "filter cascade stalled: complement keys "
                "false-positive at every layer size "
                "(non-disjoint inputs?)")
        m *= 2
        k = layer_k(m, int(st.cur_in.size))
        words = build_layer(cur_keys, m, k, level,
                            use_device=use_device)
        stats.escalations += 1
        hits = []
        probed = hit_total = 0
        if st.cur_out is None:
            chunk_iter = _complement_chunks(U, st.inc, max_lanes)
        else:
            out = st.cur_out
            chunk_iter = (out[s: s + max_lanes]
                          for s in range(0, out.size, max_lanes))
        for idx in chunk_iter:
            hit = layer_contains(words, m, k, level, S[idx])
            hits.append(idx[hit].astype(np.int32))
            probed += int(idx.size)
            hit_total += int(hit.sum())
            stats.probe_lanes += int(idx.size)
        if hit_total < probed:
            st.cascade.layers[-1] = BloomLayer(m=m, k=k, words=words)
            return (np.concatenate(hits) if hits
                    else np.zeros((0,), np.int32))


def build_cascades_grouped(
        group_keys: list, fp_rate: float,
        use_device: Optional[bool] = None,
        max_lanes: int = 0,
        max_arena_bits: int = 0,
        consume: bool = False) -> tuple[list, FusedStats]:
    """The ``CTMRFL02`` fused build: one Bloom layer per group over
    the group's OWN unique keys (empty excluded universe — per-group
    universes never consult other groups' keys), batched through the
    same arena scatter the cascade rounds use. With no excluded set
    there is no false-positive chase, no deeper layers, and no stall
    escalation: the whole build is one layer-round of scatters.

    Byte-identical per group to ``FilterCascade.build(keys_g,
    <empty>, fp_rate)`` — same unique-count sizing, same probe math,
    same word packing (32-bit-aligned arena offsets slice exactly)."""
    max_lanes = int(max_lanes) or DEFAULT_MAX_LANES
    max_arena_bits = int(max_arena_bits) or DEFAULT_MAX_ARENA_BITS
    G = len(group_keys)
    stats = FusedStats()
    if G == 0:
        return [], stats
    from ct_mapreduce_tpu.filter.stream import _rss_bytes

    uniq: list = []
    for g in range(G):
        rows = np.asarray(group_keys[g], np.uint32).reshape(-1, 4)
        if consume:
            group_keys[g] = None
        hi, lo = _rows_hilo(rows)
        uniq.append(rows[_unique_idx(hi, lo)])
        del rows
    stats.peak_rss = max(stats.peak_rss, _rss_bytes())
    cascades = [FilterCascade(fp_rate=float(fp_rate),
                              n_included=int(uniq[g].shape[0]))
                for g in range(G)]
    actives = [g for g in range(G) if uniq[g].shape[0] > 0]
    if not actives:
        return cascades, stats
    params = {g: layer_params(int(uniq[g].shape[0]), fp_rate)
              for g in actives}
    segments: list[list[int]] = []
    seg: list[int] = []
    seg_bits = 0
    for g in actives:
        m = params[g][0]
        if m > _INT32_BITS_CEIL:
            raise ValueError(
                f"layer of {m} bits exceeds the int32 scatter "
                "range; raise the FP rate or shard the corpus")
        if seg and seg_bits + m > max_arena_bits:
            segments.append(seg)
            seg, seg_bits = [], 0
        seg.append(g)
        seg_bits += m
    if seg:
        segments.append(seg)

    for seg in segments:
        offs = np.zeros((len(seg),), np.int64)
        total = 0
        for j, g in enumerate(seg):
            offs[j] = total
            total += params[g][0]
        ms = np.array([params[g][0] for g in seg], np.int64)
        ks = np.array([params[g][1] for g in seg], np.int64)
        kmax = _pow2(int(ks.max()))
        total_lanes = int(sum(uniq[g].shape[0] for g in seg))
        dev = use_device
        if dev is None:
            dev = device_enabled() and total_lanes >= DEVICE_BUILD_MIN
        with trace.span("filter.fused_layer", cat="filter", level=0,
                        groups=len(seg), lanes=total_lanes,
                        bits=total, device=int(bool(dev))):
            chunks = _row_chunks(uniq, seg, max_lanes)
            if dev:
                arena = _scatter_device_rows(chunks, offs, ms, ks,
                                             total, kmax, stats)
            else:
                arena = np.zeros((total,), bool)
                for lane_list in chunks:
                    keys = np.concatenate(
                        [rows for _, rows in lane_list])
                    gid = np.concatenate(
                        [np.full((rows.shape[0],), j, np.int32)
                         for j, rows in lane_list])
                    _scatter_np(arena, keys, gid, 0, offs, ms,
                                ks.astype(np.int64), kmax)
                    stats.dispatches += 1
                    stats.groups_per_dispatch.append(len(lane_list))
            stats.scatter_lanes += total_lanes
            stats.layers += len(seg)
            words_all = _pack_words(arena)
            del arena
            for j, g in enumerate(seg):
                w0 = int(offs[j]) // 32
                words = words_all[w0: w0 + int(ms[j]) // 32].copy()
                cascades[g].layers.append(
                    BloomLayer(m=int(ms[j]), k=int(ks[j]),
                               words=words))
                uniq[g] = None  # free as soon as the layer is cut
        stats.peak_rss = max(stats.peak_rss, _rss_bytes())
    stats.rounds = 1
    return cascades, stats


def _row_chunks(uniq, seg, max_lanes: int) -> list:
    """Pack the segment's per-group key rows into ≤max_lanes batches:
    ``[[(local_gid, uint32[n,4] row slice), ...], ...]`` — the
    grouped-build analogue of :func:`_lane_chunks` (rows direct, no
    global S table to index into)."""
    chunks = []
    cur: list = []
    cur_n = 0
    for j, g in enumerate(seg):
        rows = uniq[g]
        pos = 0
        while pos < rows.shape[0]:
            take = min(int(rows.shape[0]) - pos, max_lanes - cur_n)
            if take > 0:
                cur.append((j, rows[pos: pos + take]))
                cur_n += take
                pos += take
            if cur_n >= max_lanes:
                chunks.append(cur)
                cur, cur_n = [], 0
    if cur:
        chunks.append(cur)
    return chunks


def _scatter_device_rows(chunks, offs, ms, ks, total_bits, kmax,
                         stats: FusedStats):
    """Device lane of the grouped (single-layer) build: identical
    jitted scatter and shape discipline as :func:`_scatter_device`,
    with lane keys gathered from row slices instead of S-indices."""
    import jax.numpy as jnp

    fn = _fused_bits_jit()
    gp = _pow2(len(ms))
    offs_p = np.zeros((gp,), np.int32)
    offs_p[:len(ms)] = offs
    ms_p = np.ones((gp,), np.int32)
    ms_p[:len(ms)] = ms
    ks_p = np.zeros((gp,), np.int32)
    ks_p[:len(ms)] = ks
    arena_n = _pow2(total_bits, floor=1 << 20)
    if arena_n > _INT32_BITS_CEIL:
        arena_n = min(_INT32_BITS_CEIL,
                      ((total_bits + (1 << 20) - 1) >> 20) << 20)
    arena = jnp.zeros((arena_n,), jnp.bool_)
    offs_d, ms_d, ks_d = (jnp.asarray(a) for a in (offs_p, ms_p, ks_p))
    for lane_list in chunks:
        n = int(sum(rows.shape[0] for _, rows in lane_list))
        width = _pow2(n, floor=16)
        keys = np.zeros((width, 4), np.uint32)
        gid = np.zeros((width,), np.int32)
        valid = np.zeros((width,), bool)
        pos = 0
        for j, rows in lane_list:
            keys[pos: pos + rows.shape[0]] = rows
            gid[pos: pos + rows.shape[0]] = j
            pos += rows.shape[0]
        valid[:n] = True
        arena = fn(arena, jnp.asarray(keys), jnp.asarray(gid),
                   jnp.asarray(valid), np.uint32(0), offs_d,
                   ms_d, ks_d, kmax)
        stats.dispatches += 1
        stats.device_dispatches += 1
        stats.groups_per_dispatch.append(len(lane_list))
    return np.asarray(arena)[:total_bits]


def _lane_chunks(states, seg, max_lanes: int) -> list:
    """Pack the segment's cur_in index sets into ≤max_lanes batches:
    ``[[(local_gid, S-index slice), ...], ...]``."""
    chunks = []
    cur: list = []
    cur_n = 0
    for j, g in enumerate(seg):
        idx = states[g].cur_in
        pos = 0
        while pos < idx.size:
            take = min(int(idx.size) - pos, max_lanes - cur_n)
            if take > 0:
                cur.append((j, idx[pos: pos + take]))
                cur_n += take
                pos += take
            if cur_n >= max_lanes:
                chunks.append(cur)
                cur, cur_n = [], 0
    if cur:
        chunks.append(cur)
    return chunks


def _scatter_device(chunks, S, offs, ms, ks, level, total_bits, kmax,
                    max_lanes, stats: FusedStats):
    import jax.numpy as jnp

    fn = _fused_bits_jit()
    gp = _pow2(len(ms))
    offs_p = np.zeros((gp,), np.int32)
    offs_p[:len(ms)] = offs
    ms_p = np.ones((gp,), np.int32)  # pad 1: no %0 on dead lanes
    ms_p[:len(ms)] = ms
    ks_p = np.zeros((gp,), np.int32)
    ks_p[:len(ms)] = ks
    # Floor the device arena at 2^20 bits (128 KB): deep rounds have
    # tiny shrinking arenas, and flooring collapses their compile
    # shapes to one — the same log-bounded-shape discipline as the
    # lane widths, at negligible memory cost.
    arena_n = _pow2(total_bits, floor=1 << 20)
    if arena_n > _INT32_BITS_CEIL:
        # A >2^30-bit single-group layer: pad in 1M-bit steps instead
        # of doubling past the int32 park index (rare shape; the
        # builder already refuses layers past the int32 range).
        arena_n = min(_INT32_BITS_CEIL,
                      ((total_bits + (1 << 20) - 1) >> 20) << 20)
    arena = jnp.zeros((arena_n,), jnp.bool_)
    offs_d, ms_d, ks_d = (jnp.asarray(a) for a in (offs_p, ms_p, ks_p))
    for lane_list in chunks:
        n = int(sum(idx.size for _, idx in lane_list))
        width = _pow2(n, floor=16)
        keys = np.zeros((width, 4), np.uint32)
        gid = np.zeros((width,), np.int32)
        valid = np.zeros((width,), bool)
        pos = 0
        for j, idx in lane_list:
            keys[pos: pos + idx.size] = S[idx]
            gid[pos: pos + idx.size] = j
            pos += idx.size
        valid[:n] = True
        arena = fn(arena, jnp.asarray(keys), jnp.asarray(gid),
                   jnp.asarray(valid), np.uint32(level), offs_d,
                   ms_d, ks_d, kmax)
        stats.dispatches += 1
        stats.device_dispatches += 1
        stats.groups_per_dispatch.append(len(lane_list))
    return np.asarray(arena)[:total_bits]
