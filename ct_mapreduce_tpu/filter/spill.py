"""Memory-bounded filter capture: a spill-to-disk segment ring
(round 19, the ``filterCaptureSpillDir`` directive).

The round-15 capture retains every first-seen serial's bytes in host
RAM for the life of the run — at 10⁸ serials that is tens of GB of
Python ``set`` overhead exactly where the build needs its arena. The
ring bounds it: serials accumulate in in-memory per-group sets until a
configured byte budget, then the WHOLE in-memory state flushes to one
append-only segment file and memory resets. Capture RSS is bounded by
the knob; corpus size only grows the spill directory.

Contracts:

- **Checkpoint/merge/npz unchanged.** The ring exposes the same
  ``items()`` surface the dict capture has (``[(key, set), ...]``,
  merged across memory + every segment, deduped by set semantics), so
  ``_write_npz``'s ``filter_keys``/``filter_vals`` arrays, the fleet
  merge, and ``build_from_aggregator`` are byte-identical to a dict
  capture of the same content. (Materializing a full ``items()`` view
  costs the corpus back — that is the existing npz contract, paid at
  checkpoint time, not for the life of the run.)
- **Crash-restart resume.** Each flush writes one complete segment
  atomically (tmp + rename + fsync). A restart pointed at the same
  directory picks every durable segment back up; serials that were
  only in memory are re-captured by the resume-at-cursor re-fold (the
  same idempotence the checkpoint tail replay relies on).
- **Determinism.** ``items()`` sorts keys and returns sets — what
  downstream writers serialize is a function of content only.
- **Content hashes (CTMRFL02 dirty tracking).** While the ring has
  never spilled (and found no pre-existing segments at construction),
  it maintains exact per-group XOR content hashes incrementally — the
  memory set IS the full logical content, so first-seen dedup is
  exact. The first flush permanently invalidates them: a serial
  re-captured after its set spilled looks new to the memory tier and
  would double-XOR. ``content_hashes()`` returns None once inexact —
  callers fall back to recomputation or full rebuild (a false MISS is
  a redundant rebuild; a false HIT would be a correctness bug, so the
  ring never guesses).

Record framing (one segment = magic + records until EOF): ``<iq I``
issuer_idx int32, exp_hour int64, serial length uint32, serial bytes.
A truncated tail record (crash mid-write of the non-atomic path never
happens — segments are atomic — but a torn filesystem is cheap to
tolerate) is dropped with a warning.
"""

from __future__ import annotations

import os
import struct
import sys
import tempfile

from ct_mapreduce_tpu.telemetry.metrics import incr_counter, set_gauge

SEGMENT_MAGIC = b"CTMRSPL1"
_REC = struct.Struct("<iqI")
DEFAULT_MEM_BYTES = 256 << 20

# Per-serial bookkeeping estimate added to the raw byte length when
# charging the in-memory budget (bytes object + set slot overhead).
_SET_OVERHEAD = 64


class SpillCaptureRing:
    """Dict-capture drop-in with a byte-budgeted memory tier. Callers
    hold the aggregator's fold lock, exactly as for the dict."""

    def __init__(self, spill_dir: str,
                 mem_bytes: int = DEFAULT_MEM_BYTES):
        self.spill_dir = spill_dir
        self.mem_bytes = int(mem_bytes) if mem_bytes else DEFAULT_MEM_BYTES
        os.makedirs(spill_dir, exist_ok=True)
        self._mem: dict[tuple[int, int], set[bytes]] = {}
        self._mem_used = 0
        self.spilled_bytes = 0
        existing = self._segments()
        self._hashes: dict[tuple[int, int], int] = {}
        # Exact only while every captured serial is still in the memory
        # tier: pre-existing segments mean unknown prior content.
        self.hashes_exact = not existing
        self._next_seg = (max(
            (int(os.path.basename(p)[4:12]) for p in existing),
            default=-1) + 1)
        for p in existing:
            self.spilled_bytes += os.path.getsize(p)

    # -- capture surface (mirrors the dict) --------------------------
    def add(self, key: tuple[int, int], serial: bytes) -> None:
        s = self._mem.get(key)
        if s is None:
            s = self._mem[key] = set()
        if serial not in s:
            s.add(serial)
            if self.hashes_exact:
                from ct_mapreduce_tpu.filter.cache import serial_hash

                self._hashes[key] = (
                    self._hashes.get(key, 0) ^ serial_hash(serial))
            self._mem_used += len(serial) + _SET_OVERHEAD
            if self._mem_used >= self.mem_bytes:
                self.flush()

    def update(self, key: tuple[int, int], serials) -> None:
        for sb in serials:
            self.add(key, sb)

    def items(self) -> list[tuple[tuple[int, int], set[bytes]]]:
        """Merged (memory + every segment) capture, keys sorted —
        the same shape ``dict.items()`` hands the checkpoint writer."""
        merged: dict[tuple[int, int], set[bytes]] = {}
        for key, serials in sorted(self._mem.items()):
            merged.setdefault(key, set()).update(serials)
        for path in self._segments():
            self._fold_segment(path, merged)
        return sorted(merged.items())

    def values(self):
        merged = self.items()  # already key-sorted
        return [s for _, s in merged]

    def __len__(self) -> int:
        return len(self.items())

    def __iter__(self):
        merged = self.items()  # already key-sorted
        return iter([k for k, _ in merged])

    # -- spill machinery ----------------------------------------------
    def _segments(self) -> list[str]:
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return []
        return [os.path.join(self.spill_dir, n) for n in sorted(names)
                if n.startswith("seg-") and n.endswith(".spill")]

    def flush(self) -> None:
        """Durably spill the whole memory tier as one atomic segment."""
        if not self._mem:
            return
        fd, tmp = tempfile.mkstemp(prefix="seg.tmp.", dir=self.spill_dir)
        n_bytes = 0
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(SEGMENT_MAGIC)
                for key, serials in sorted(self._mem.items()):
                    idx, eh = key
                    for sb in sorted(serials):
                        fh.write(_REC.pack(int(idx), int(eh), len(sb)))
                        fh.write(sb)
                fh.flush()
                os.fsync(fh.fileno())
                n_bytes = fh.tell()
            final = os.path.join(self.spill_dir,
                                 f"seg-{self._next_seg:08d}.spill")
            os.replace(tmp, final)
        except BaseException:
            import contextlib

            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise
        self._next_seg += 1
        self._mem = {}
        self._mem_used = 0
        # Memory dedup no longer covers spilled serials — hashes can
        # never be trusted again for the life of this directory.
        self._hashes = {}
        self.hashes_exact = False
        self.spilled_bytes += n_bytes
        incr_counter("filter", "capture_spilled_bytes",
                     value=float(n_bytes))
        set_gauge("filter", "capture_spill_segments",
                  value=float(self._next_seg))

    def _fold_segment(self, path: str, merged: dict) -> None:
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as err:
            print(f"filter spill segment unreadable ({path}): {err}",
                  file=sys.stderr)
            return
        if blob[:8] != SEGMENT_MAGIC:
            print(f"filter spill segment bad magic ({path})",
                  file=sys.stderr)
            return
        pos = 8
        end = len(blob)
        while pos < end:
            if pos + _REC.size > end:
                print(f"filter spill segment truncated tail ({path})",
                      file=sys.stderr)
                break
            idx, eh, ln = _REC.unpack_from(blob, pos)
            pos += _REC.size
            if pos + ln > end:
                print(f"filter spill segment truncated tail ({path})",
                      file=sys.stderr)
                break
            merged.setdefault((idx, eh), set()).add(blob[pos: pos + ln])
            pos += ln

    def content_hashes(self) -> dict | None:
        """Exact per-group XOR content hashes ({(issuer_idx, expHour):
        int}) when the memory tier still holds the full capture; None
        once any spill (or a restart over prior segments) made them
        unverifiable."""
        if not self.hashes_exact:
            return None
        return dict(self._hashes)

    def stats(self) -> dict:
        return {
            "memBytes": self._mem_used,
            "memBudget": self.mem_bytes,
            "spilledBytes": self.spilled_bytes,
            "segments": len(self._segments()),
        }
