"""The on-disk revocation-filter artifact: versioned, deterministic,
one filter cascade per ``(issuer, expDate)`` known-serial set.

This is the second product of the reduce state (ROADMAP item 5(b)):
where ``storage-statistics`` prints the per-(issuer, expDate) serial
*counts*, the filter artifact compiles the serial *sets* — captured by
the aggregator's filter capture (:meth:`TpuAggregator.
enable_filter_capture`) — into compact crlite-style cascades a
downstream revocation pipeline can ship. Byte layout is specified in
docs/FILTER_FORMAT.md; the invariants that matter here:

- **Canonical keys.** Element keys are the pipeline's own fingerprint
  message (``expHour ‖ issuerOrdinal ‖ serialLen ‖ serial``, SHA-256,
  low 128 bits) with the issuer's run-local registry index replaced by
  its ORDINAL in the artifact's sorted issuerID list. Run-local
  indices differ between a fleet's workers and a serial run; sorted
  identities do not — this is what makes a merged fleet artifact
  byte-identical to the serial run's (tools/fleet.py --verify).
  Conforming serials (≤ MAX_SERIAL_BYTES) hash through the existing
  kernels — the jitted :func:`ops.pipeline.fingerprints` for large
  batches, the :func:`core.packing.fingerprints_np` host mirror
  otherwise; oversized serials take a host hashlib lane with a
  disjoint message encoding (the walker-fallback pattern).
- **Determinism.** Groups sort by (issuerID, expHour), serials sort
  bytewise, layer sizing is a fixed formula, headers are
  sorted-key/compact JSON, and no wall-clock enters the bytes: the
  same aggregation state always serializes to the same artifact.
- **Exactness.** In the ``CTMRFL01`` format each group's cascade is
  built with *every other group's keys* as its excluded universe, so
  any serial known to the aggregation state answers its (issuer,
  expDate) membership exactly; serials outside the state see ≈ the
  target FP rate and are killed by the serve plane's table-confirm
  tier.
- **Per-group universes (CTMRFL02, the default).** Each group's
  cascade builds against its OWN observed universe only: keys hash
  under ordinal 0 (no cross-group issuer numbering) and the excluded
  set is empty, so the cascade is a single Bloom layer at the target
  FP rate. One group's churn can never move another group's bytes —
  the property the delta plane (CTMRDL02) and the dirty-group
  incremental build path (filter/cache.py) are built on. The trade:
  a query against the WRONG group (a serial the state knows only
  under a different (issuer, expDate)) now false-positives at ≈ the
  target rate instead of answering exactly; the serve tier's
  table-confirm kills those exactly as it kills ordinary FPs.
"""

from __future__ import annotations

import json
import os
import struct
import tempfile
import time
from dataclasses import dataclass

import numpy as np

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.core.types import ExpDate
from ct_mapreduce_tpu.filter.cascade import (
    DEVICE_BUILD_MIN,
    BloomLayer,
    FilterCascade,
    device_enabled,
)
from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import incr_counter, measure, set_gauge

MAGIC = b"CTMRFL01"
MAGIC_FL02 = b"CTMRFL02"
VERSION = 1
DEFAULT_FP_RATE = 0.01

# Format names (the `filterFormat` directive / CTMR_FILTER_FORMAT
# values). fl02 — per-group universes — is the default; fl01 is the
# compatibility path for consumers pinned to the global-universe
# format (round-15/19 golden artifacts).
FORMAT_FL01 = "fl01"
FORMAT_FL02 = "fl02"
_FORMAT_MAGIC = {FORMAT_FL01: MAGIC, FORMAT_FL02: MAGIC_FL02}
_MAGIC_FORMAT = {MAGIC: FORMAT_FL01, MAGIC_FL02: FORMAT_FL02}


def normalize_format(fmt: str) -> str:
    """One canonical spelling per format; loud on unknown values."""
    f = str(fmt).strip().lower()
    if f in ("fl01", "ctmrfl01"):
        return FORMAT_FL01
    if f in ("fl02", "ctmrfl02"):
        return FORMAT_FL02
    raise ValueError(f"unknown filter format {fmt!r} "
                     f"(expected fl01 or fl02)")


def default_format() -> str:
    """The build-time artifact format: ``CTMR_FILTER_FORMAT`` env
    (``fl01`` | ``fl02``) when set and parseable, else fl02."""
    v = os.environ.get("CTMR_FILTER_FORMAT", "").strip()
    if v:
        try:
            return normalize_format(v)
        except ValueError:
            pass  # unparseable env ignored (config-layer tolerance)
    return FORMAT_FL02


def resolve_format(fmt) -> str:
    """None/empty → the default format; otherwise normalized."""
    if fmt is None or fmt == "":
        return default_format()
    return normalize_format(fmt)

_jit_cache: dict = {}

# Dispatch statistics of the most recent fused build (None after a
# per-group build) — a tool/test observability hook, not API.
LAST_BUILD_STATS = None


def _fingerprints_jit():
    fn = _jit_cache.get("fp")
    if fn is None:
        import jax

        from ct_mapreduce_tpu.ops import pipeline

        fn = jax.jit(pipeline.fingerprints)
        _jit_cache["fp"] = fn
    return fn


def canonical_keys(ordinals: np.ndarray, exp_hours: np.ndarray,
                   serials: list[bytes],
                   use_device: bool | None = None,
                   chunk: int = 0) -> np.ndarray:
    """uint32[n, 4] canonical filter keys for (ordinal, expHour,
    serial) triples. Conforming serials reuse the pipeline fingerprint
    kernels (device when the batch is large, the vectorized host
    mirror otherwise); oversized serials — host-lane-only identities —
    hash through a disjoint single-purpose encoding that no conforming
    message can collide with (marker byte 0xFF > MAX_SERIAL_BYTES in
    the length position).

    Chunked driver (round 19): the per-serial message matrix is built
    ``chunk`` rows at a time (default ``stream.DEFAULT_STREAM_CHUNK``),
    so only the [n, 4] key array is corpus-sized. Chunk boundaries
    change no bytes."""
    from ct_mapreduce_tpu.filter import stream

    n = len(serials)
    out = np.zeros((n, 4), np.uint32)
    if n == 0:
        return out
    chunk = int(chunk) or stream.DEFAULT_STREAM_CHUNK
    ordinals = np.asarray(ordinals, np.int64)
    exp_hours = np.asarray(exp_hours, np.int64)
    lens = np.fromiter((len(s) for s in serials), np.int64, n)
    fit = lens <= packing.MAX_SERIAL_BYTES
    sel = np.nonzero(fit)[0]
    for start in range(0, int(sel.size), chunk):
        part = sel[start: start + chunk]
        block = [serials[p] for p in part]
        blens, mat = stream.pack_serials(block)
        dev = use_device
        if dev is None:
            dev = device_enabled() and part.size >= DEVICE_BUILD_MIN
        with trace.span("filter.stream_chunk", cat="filter",
                        lanes=int(part.size), device=int(bool(dev))):
            if dev:
                fps = stream._fingerprints_device(
                    ordinals[part], exp_hours[part], mat, blens)
            else:
                fps = packing.fingerprints_np(
                    ordinals[part], exp_hours[part], mat, blens)
        out[part] = fps
    for p in np.nonzero(~fit)[0]:
        out[p] = stream.oversized_key(
            int(ordinals[p]), int(exp_hours[p]), serials[p])
    return out


@dataclass
class FilterGroup:
    issuer: str  # issuerID (base64url(SHA-256(SPKI)))
    exp_id: str  # expDate report id, e.g. "2031-06-15-14"
    exp_hour: int
    ordinal: int  # issuer ordinal the keys were hashed under
    n: int  # included serials
    cascade: FilterCascade


class FilterArtifact:
    """Parsed (or freshly built) artifact: group directory + cascades.

    ``fmt`` is the serialization format (``fl01`` | ``fl02``): it
    picks the magic ``to_bytes`` writes and round-trips through
    ``from_bytes``, so re-serializers (delta replay, group slices)
    preserve the source format."""

    def __init__(self, fp_rate: float, groups: list[FilterGroup],
                 fmt: str = FORMAT_FL01):
        self.fp_rate = float(fp_rate)
        self.fmt = normalize_format(fmt)
        self.groups = {(g.issuer, g.exp_id): g for g in groups}
        self._by_hour = {(g.issuer, g.exp_hour): g for g in groups}

    @property
    def n_serials(self) -> int:
        return sum(g.n for g in self.groups.values())

    def max_layers(self) -> int:
        return max((len(g.cascade.layers) for g in self.groups.values()),
                   default=0)

    def total_bits(self) -> int:
        return sum(g.cascade.total_bits() for g in self.groups.values())

    def bits_per_entry(self) -> float:
        return self.total_bits() / max(1, self.n_serials)

    # -- queries ---------------------------------------------------------
    def group_for(self, issuer: str, exp) -> FilterGroup | None:
        """Group lookup; ``exp`` is an expDate id string or epoch
        hour. String ids resolve through ExpDate.parse so day- and
        hour-form spellings of the same bucket both land."""
        if isinstance(exp, str):
            g = self.groups.get((issuer, exp))
            if g is not None:
                return g
            try:
                exp = ExpDate.parse(exp).unix_hour()
            except ValueError:
                return None
        return self._by_hour.get((issuer, int(exp)))

    def query(self, issuer: str, exp, serial: bytes) -> bool:
        """Is ``serial`` a known serial of (issuer, expDate)? Exact
        for every serial the source aggregation state knew; unknown
        serials see ≈ the target FP rate (confirm against the table
        before trusting a positive)."""
        g = self.group_for(issuer, exp)
        if g is None:
            return False
        keys = canonical_keys(
            np.array([g.ordinal]), np.array([g.exp_hour]), [serial])
        return bool(g.cascade.contains(keys)[0])

    def query_group(self, g: FilterGroup, serials: list[bytes]) -> np.ndarray:
        keys = canonical_keys(
            np.full((len(serials),), g.ordinal),
            np.full((len(serials),), g.exp_hour), serials)
        return g.cascade.contains(keys)

    # -- serialization ---------------------------------------------------
    def to_bytes(self) -> bytes:
        payload = bytearray()
        entries = []
        for (_, _), g in sorted(self.groups.items()):
            layers = []
            for layer in g.cascade.layers:
                raw = layer.words.astype("<u4").tobytes()
                layers.append({"k": layer.k, "m": layer.m,
                               "off": len(payload), "words": len(raw)})
                payload += raw
            entries.append({
                "expDate": g.exp_id, "expHour": g.exp_hour,
                "issuer": g.issuer, "layers": layers, "n": g.n,
                "ordinal": g.ordinal,
            })
        header = json.dumps(
            {"fpRate": self.fp_rate, "groups": entries,
             "nSerials": self.n_serials, "version": VERSION},
            sort_keys=True, separators=(",", ":")).encode()
        return (_FORMAT_MAGIC[self.fmt] + struct.pack("<I", len(header))
                + header + bytes(payload))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "FilterArtifact":
        fmt = _MAGIC_FORMAT.get(blob[:8])
        if fmt is None:
            raise ValueError("not a ct-mapreduce filter artifact "
                             f"(magic {blob[:8]!r})")
        (hlen,) = struct.unpack("<I", blob[8:12])
        header = json.loads(blob[12:12 + hlen].decode())
        if header.get("version") != VERSION:
            raise ValueError(
                f"unsupported filter artifact version "
                f"{header.get('version')!r} (this build reads {VERSION})")
        payload = blob[12 + hlen:]
        groups = []
        for e in header["groups"]:
            layers = []
            for lyr in e["layers"]:
                raw = payload[lyr["off"]: lyr["off"] + lyr["words"]]
                words = np.frombuffer(raw, dtype="<u4").astype(np.uint32)
                layers.append(BloomLayer(m=lyr["m"], k=lyr["k"],
                                         words=words))
            groups.append(FilterGroup(
                issuer=e["issuer"], exp_id=e["expDate"],
                exp_hour=int(e["expHour"]), ordinal=int(e["ordinal"]),
                n=int(e["n"]),
                cascade=FilterCascade(fp_rate=header["fpRate"],
                                      n_included=int(e["n"]),
                                      layers=layers)))
        return cls(fp_rate=header["fpRate"], groups=groups, fmt=fmt)

    def group_bytes(self, issuer: str, exp) -> bytes | None:
        """A standalone single-group artifact (same format) for the
        serve plane's per-(issuer, expDate) download route. The group
        keeps its full-artifact ordinal; a CTMRFL01 cascade was built
        against the GLOBAL excluded universe and a CTMRFL02 one
        against its own, so in both formats the slice answers exactly
        what the full artifact answers."""
        g = self.group_for(issuer, exp)
        if g is None:
            return None
        return FilterArtifact(self.fp_rate, [g], fmt=self.fmt).to_bytes()


def fused_enabled() -> bool:
    """Filter builds use the fused multi-group layer dispatcher by
    default (round 19); ``CTMR_FILTER_FUSED=0`` forces the round-15
    per-group path (byte-identical — the parity escape hatch)."""
    v = os.environ.get("CTMR_FILTER_FUSED", "").strip().lower()
    if v in ("0", "f", "false"):
        return False
    return True


def build_artifact(serial_sets: dict, fp_rate: float = DEFAULT_FP_RATE,
                   use_device: bool | None = None,
                   fused: bool | None = None,
                   stream_chunk: int = 0,
                   fused_lanes: int = 0,
                   fmt: str | None = None,
                   cache=None,
                   tokens: dict | None = None) -> FilterArtifact:
    """Compile ``{(issuerID, expHour): iterable of serial bytes}`` into
    a deterministic artifact: each group's cascade includes its own
    serials and (fl01 only) excludes every other group's keys.
    ``tokens`` optionally maps the same keys to per-group content
    tokens for the incremental ``cache`` (filter/cache.py)."""
    from ct_mapreduce_tpu.filter import stream

    sources = []
    for iss, eh in sorted(serial_sets):
        src = stream.ListGroupSource(iss, eh, serial_sets[(iss, eh)])
        if tokens is not None:
            src.content_token = tokens.get((iss, eh))
        sources.append(src)
    return build_artifact_from_sources(
        sources, fp_rate=fp_rate, use_device=use_device, fused=fused,
        stream_chunk=stream_chunk, fused_lanes=fused_lanes, fmt=fmt,
        cache=cache)


def build_artifact_from_sources(
        sources: list, fp_rate: float = DEFAULT_FP_RATE,
        use_device: bool | None = None,
        fused: bool | None = None,
        stream_chunk: int = 0,
        fused_lanes: int = 0,
        fmt: str | None = None,
        cache=None) -> FilterArtifact:
    """The round-19 build driver over :class:`stream.GroupSource`
    providers (packed chunks — the 10⁸-scale entry point; the dict
    wrapper above feeds it :class:`stream.ListGroupSource`).

    Serial data streams through the fingerprint kernels in fixed-size
    blocks, only the ``[N, 4]`` key arena is corpus-resident, and the
    cascades build through the fused multi-group layer dispatcher (one
    jitted scatter per layer-round batch, not per (group, layer) —
    ``fused=False`` / ``CTMR_FILTER_FUSED=0`` for the byte-identical
    per-group reference path). Streamed, fused, in-memory, and
    fleet-merged builds of the same logical state produce identical
    bytes in either format (the round-15 contract, property-tested).

    ``fmt`` picks the artifact format (``fl01`` global universes /
    ``fl02`` per-group universes; None → :func:`default_format`).
    ``cache`` (a :class:`filter.cache.GroupBuildCache`, fl02 only)
    arms the dirty-group incremental path: sources whose
    ``content_token`` matches the cache reuse the prior build's
    group VERBATIM — no key generation, no scatter — and only dirty
    groups rebuild, so the epoch tick costs O(churn)."""
    from ct_mapreduce_tpu.filter import fused as fused_mod
    from ct_mapreduce_tpu.filter import stream

    fmt = resolve_format(fmt)
    if fused is None:
        fused = fused_enabled()
    stream_chunk = int(stream_chunk) or stream.DEFAULT_STREAM_CHUNK
    t0 = time.perf_counter()
    peak_rss = stream._rss_bytes()
    with measure("filter", "build_s"), \
            trace.span("filter.build", cat="filter",
                       groups=len(sources)):
        sources = sorted(sources, key=lambda s: (s.issuer, s.exp_hour))
        issuers = sorted({s.issuer for s in sources})
        if fmt == FORMAT_FL01:
            ordinal = {iss: i for i, iss in enumerate(issuers)}
        else:
            # CTMRFL02: every group hashes under ordinal 0. A new
            # issuer appearing must not renumber — and thereby re-key —
            # every other issuer's groups; the issuerID in the
            # fingerprint's group identity lives in the (issuer,
            # expHour) directory key, not the hashed message.
            ordinal = {iss: 0 for iss in issuers}
        reused: dict = {}
        build_srcs = []
        for src in sources:
            if src.n == 0:
                continue
            hit = None
            if cache is not None and fmt == FORMAT_FL02:
                hit = cache.get(src.issuer, src.exp_hour,
                                getattr(src, "content_token", None),
                                fp_rate)
            if hit is not None:
                reused[(src.issuer, src.exp_hour)] = hit
            else:
                build_srcs.append(src)
        group_keys = []
        meta = []
        for src in build_srcs:
            keys = stream.collect_keys(
                src, ordinal[src.issuer], stream_chunk,
                use_device=use_device)
            group_keys.append(keys)
            meta.append(src)
            peak_rss = max(peak_rss, stream._rss_bytes())
        global LAST_BUILD_STATS
        if fmt == FORMAT_FL02:
            if fused:
                cascades, stats = fused_mod.build_cascades_grouped(
                    group_keys, fp_rate, use_device=use_device,
                    max_lanes=fused_lanes, consume=True)
                set_gauge("filter", "fused_groups_per_dispatch",
                          value=stats.mean_groups_per_dispatch())
                peak_rss = max(peak_rss, stats.peak_rss)
                LAST_BUILD_STATS = stats
            else:
                no_exc = np.zeros((0, 4), np.uint32)
                cascades = [FilterCascade.build(k, no_exc, fp_rate,
                                                use_device=use_device)
                            for k in group_keys]
                LAST_BUILD_STATS = None
        elif fused:
            cascades, stats = fused_mod.build_cascades_fused(
                group_keys, fp_rate, use_device=use_device,
                max_lanes=fused_lanes, consume=True)
            set_gauge("filter", "fused_groups_per_dispatch",
                      value=stats.mean_groups_per_dispatch())
            peak_rss = max(peak_rss, stats.peak_rss)
            LAST_BUILD_STATS = stats
        else:
            cascades = _build_cascades_per_group(
                group_keys, fp_rate, use_device)
            LAST_BUILD_STATS = None
        del group_keys
        groups = []
        for src, cascade in zip(meta, cascades):
            g = FilterGroup(
                issuer=src.issuer,
                exp_id=ExpDate.from_unix_hour(src.exp_hour).id(),
                exp_hour=src.exp_hour, ordinal=ordinal[src.issuer],
                n=src.n, cascade=cascade)
            groups.append(g)
            if cache is not None and fmt == FORMAT_FL02:
                cache.put(src.issuer, src.exp_hour,
                          getattr(src, "content_token", None),
                          fp_rate, g)
        for key in sorted(reused):
            groups.append(reused[key])
        if fmt == FORMAT_FL02:
            set_gauge("filter", "dirty_groups", value=float(len(meta)))
            set_gauge("filter", "groups_reused",
                      value=float(len(reused)))
            if cache is not None:
                cache.prune({(g.issuer, g.exp_hour) for g in groups})
        art = FilterArtifact(fp_rate=fp_rate, groups=groups, fmt=fmt)
        peak_rss = max(peak_rss, stream._rss_bytes())
    build_s = time.perf_counter() - t0
    set_gauge("filter", "serials", value=float(art.n_serials))
    set_gauge("filter", "groups", value=float(len(art.groups)))
    set_gauge("filter", "layers", value=float(art.max_layers()))
    set_gauge("filter", "bits_per_entry", value=art.bits_per_entry())
    set_gauge("filter", "build_rate",
              value=art.n_serials / max(build_s, 1e-9))
    set_gauge("filter", "build_rss_bytes", value=float(peak_rss))
    return art


def _build_cascades_per_group(group_keys: list, fp_rate: float,
                              use_device) -> list:
    """The round-15 reference path: one cascade at a time, each
    group's excluded universe the concatenation of every other
    group's keys. Kept as the byte-identity oracle for the fused
    dispatcher (CTMR_FILTER_FUSED=0 and the parity tests)."""
    if not group_keys:
        return []
    all_keys = np.concatenate(group_keys)
    bounds = np.cumsum([0] + [k.shape[0] for k in group_keys])
    cascades = []
    for i in range(len(group_keys)):
        mask = np.zeros((all_keys.shape[0],), bool)
        mask[bounds[i]: bounds[i + 1]] = True
        cascades.append(FilterCascade.build(
            all_keys[mask], all_keys[~mask], fp_rate,
            use_device=use_device))
    return cascades


def capture_by_identity(capture: dict, registry) -> dict:
    """Aggregator filter capture ({(issuer_idx, expHour): serial set})
    → identity-keyed serial sets ({(issuerID, expHour): set}). Indices
    past the registry (impossible in a consistent state) fail loudly —
    an artifact must never silently drop a group."""
    out: dict = {}
    # Sorted so the identity-keyed dict's insertion order is a function
    # of content, not capture fold order (ctmrlint: determinism).
    for (idx, eh), serials in sorted(capture.items()):
        if not serials:
            continue
        iss = registry.issuer_at(int(idx)).id()
        out.setdefault((iss, int(eh)), set()).update(serials)
    return out


def capture_tokens(capture: dict, hashes: dict | None,
                   registry) -> dict:
    """Identity-keyed per-group content tokens ({(issuerID, expHour):
    (n, xor-hash)}) for the incremental build cache. Exact
    incrementally-maintained hashes from the capture layer are used
    when available; otherwise the token recomputes from the serial
    set (sha256 per serial — far cheaper than the rebuild a missing
    token would force). A group fed by more than one registry index
    recomputes from its merged set: XOR-combining per-index hashes
    would cancel serials present under both indices."""
    from ct_mapreduce_tpu.filter.cache import content_token

    merged: dict = {}
    contrib: dict = {}
    for (idx, eh), serials in sorted(capture.items()):
        if not serials:
            continue
        iss = registry.issuer_at(int(idx)).id()
        key = (iss, int(eh))
        merged.setdefault(key, set()).update(serials)
        contrib.setdefault(key, []).append((int(idx), int(eh)))
    out = {}
    for key in sorted(merged):
        srcs = contrib[key]
        if hashes is not None and len(srcs) == 1 and srcs[0] in hashes:
            out[key] = (len(merged[key]), hashes[srcs[0]])
        else:
            out[key] = content_token(merged[key])
    return out


def build_from_aggregator(agg, fp_rate: float = DEFAULT_FP_RATE,
                          use_device: bool | None = None,
                          fmt: str | None = None,
                          cache=None) -> FilterArtifact:
    """Artifact from a live aggregator's filter capture. With a
    ``cache`` (fl02), per-group content tokens come from the capture
    layer's incrementally-maintained hashes where exact, so clean
    groups reuse the prior epoch's blocks verbatim."""
    if getattr(agg, "filter_capture", None) is None:
        raise ValueError(
            "aggregator has no filter capture; enable emitFilter (or "
            "call enable_filter_capture) before ingesting")
    # Snapshot under the fold lock: a live serve-plane refresh may run
    # while ingest folds mutate the capture dict/sets concurrently.
    import contextlib

    lock = getattr(agg, "_fold_lock", None)
    with (lock if lock is not None else contextlib.nullcontext()):
        capture = {key: set(serials)
                   for key, serials in sorted(agg.filter_capture.items())}
        hashes = (agg.capture_content_hashes()
                  if hasattr(agg, "capture_content_hashes") else None)
    tokens = (capture_tokens(capture, hashes, agg.registry)
              if cache is not None else None)
    return build_artifact(
        capture_by_identity(capture, agg.registry),
        fp_rate=fp_rate, use_device=use_device, fmt=fmt, cache=cache,
        tokens=tokens)


def build_from_merged(merged, fp_rate: float = DEFAULT_FP_RATE,
                      allow_partial: bool = False,
                      use_device: bool | None = None,
                      fmt: str | None = None,
                      cache=None) -> FilterArtifact:
    """Artifact from a fleet's merged checkpoints
    (:class:`ct_mapreduce_tpu.agg.merge.MergedAggregate`). Every folded
    checkpoint must carry a filter capture (a worker that ran with
    emitFilter off contributes device-lane serials only as hashes —
    unrecoverable), unless ``allow_partial`` explicitly accepts an
    artifact over the capturing subset. Cache tokens always recompute
    from the merged union sets — per-worker hashes must never
    XOR-combine (shared serials would cancel)."""
    missing = getattr(merged, "capture_missing", [])
    if missing and not allow_partial:
        raise ValueError(
            "merged checkpoints without a filter capture (run workers "
            f"with emitFilter=true): {missing}")
    tokens = (capture_tokens(merged.filter_serials, None,
                             merged.registry)
              if cache is not None else None)
    return build_artifact(
        capture_by_identity(merged.filter_serials, merged.registry),
        fp_rate=fp_rate, use_device=use_device, fmt=fmt, cache=cache,
        tokens=tokens)


def write_artifact(path: str, blob: bytes) -> None:
    """Atomic artifact write (temp + rename — the same durability
    contract as the aggregate checkpoint: a crash mid-write must not
    corrupt the previous good artifact)."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp_path = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.", dir=directory)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp_path, path)
    except BaseException:
        import contextlib

        with contextlib.suppress(OSError):
            os.unlink(tmp_path)
        raise
    incr_counter("filter", "emit")


def read_artifact(path: str) -> FilterArtifact:
    with open(path, "rb") as fh:
        return FilterArtifact.from_bytes(fh.read())
