"""The fused device ingest step: the reference's hot loop #2 as one op.

One jitted call does what ``insertCTWorker`` + ``FilesystemDatabase.Store``
do per certificate (/root/reference/cmd/ct-fetch/ct-fetch.go:180-246,
/root/reference/storage/filesystemdatabase.go:158-211), for a whole
batch at once and with no per-entry host round trips:

  parse DER → filter (CA / expired / issuer-CN prefix,
  /root/reference/cmd/ct-fetch/ct-fetch.go:44-70) → gather serial →
  build fingerprint block → SHA-256 → dedup-table insert-if-absent →
  per-issuer new-cert counts.

Lanes the device cannot handle exactly (parse failure, oversized
serial, meta-range overflow, probe overflow) come back in
``host_lane`` and are re-processed by the exact host path — the same
tolerate-and-redirect contract the reference applies to unparseable
entries (/root/reference/cmd/ct-fetch/ct-fetch.go:206-225).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.ops import buckettable, der_kernel, hashtable, sha256


def table_layout() -> str:
    """Dedup-table layout: ``bucket`` (default — the sort-based
    24-slot-bucket table the round-4 hardware measurements favor by
    ~an order of magnitude on the insert, ops/buckettable.py) or
    ``open`` (slot-granular open addressing, ops/hashtable.py)."""
    import os

    layout = os.environ.get("CTMR_TABLE", "bucket").strip().lower()
    if layout not in ("bucket", "open"):
        import warnings

        warnings.warn(
            f"ignoring CTMR_TABLE={layout!r} (want bucket|open); "
            "using bucket", stacklevel=2)
        return "bucket"
    return layout


def make_table(capacity: int, layout: str | None = None):
    """Fresh dedup table in the selected layout."""
    if (layout or table_layout()) == "bucket":
        return buckettable.make_table(capacity)
    return hashtable.make_table(capacity)


def table_insert(table, keys, meta, valid, max_probes: int = 32):
    """Insert-if-absent on either dedup-table layout.

    Dispatches on the state type at trace time (each layout is its own
    pytree, so jit caches separate programs): ``BucketTable`` takes the
    sort-based bucket path (ops/buckettable.py — the measured-fast
    layout), ``hashtable.TableState`` the slot-granular probe path."""
    if isinstance(table, buckettable.BucketTable):
        return buckettable.insert(table, keys, meta, valid,
                                  max_probes=max_probes)
    return hashtable.insert(table, keys, meta, valid, max_probes=max_probes)


class StepOut(NamedTuple):
    was_unknown: jax.Array  # bool[B] — device-confirmed first sighting
    host_lane: jax.Array  # bool[B] — lane needs the exact host path
    filtered_ca: jax.Array  # bool[B]
    filtered_expired: jax.Array  # bool[B]
    filtered_cn: jax.Array  # bool[B]
    stored: jax.Array  # bool[B] — passed filters, device-handled
    not_after_hour: jax.Array  # int32[B]
    serials: jax.Array  # uint8[B, MAX_SERIAL_BYTES] (for PEM/host use)
    serial_len: jax.Array  # int32[B]
    issuer_unknown_counts: jax.Array  # int32[num_issuers]
    has_crldp: jax.Array  # bool[B]
    crldp_off: jax.Array  # int32[B] — CRLDP extnValue window in `data`
    crldp_len: jax.Array  # int32[B]
    issuer_name_off: jax.Array  # int32[B] — issuer Name TLV window
    issuer_name_len: jax.Array  # int32[B]
    probe_overflow: jax.Array  # bool[B] — insert exhausted its probe
    # chain (spills to the exact host lane; `overflow` metric)


def fingerprints(
    issuer_idx: jax.Array, exp_hour: jax.Array, serials: jax.Array, serial_len: jax.Array
) -> jax.Array:
    """Build fingerprint blocks on device and hash them: uint32[B, 4].

    Message layout must match
    :func:`ct_mapreduce_tpu.core.packing.fingerprint_message`.
    """
    b = issuer_idx.shape[0]
    msg = jnp.zeros((b, 64), dtype=jnp.uint8)
    eh = exp_hour.astype(jnp.uint32)
    ii = issuer_idx.astype(jnp.uint32)
    head = jnp.stack(
        [
            (eh >> 24) & 0xFF, (eh >> 16) & 0xFF, (eh >> 8) & 0xFF, eh & 0xFF,
            (ii >> 24) & 0xFF, (ii >> 16) & 0xFF, (ii >> 8) & 0xFF, ii & 0xFF,
            serial_len.astype(jnp.uint32) & 0xFF,
        ],
        axis=1,
    ).astype(jnp.uint8)
    msg = msg.at[:, :9].set(head)
    msg = msg.at[:, 9 : 9 + packing.MAX_SERIAL_BYTES].set(serials)
    # FIPS padding: 0x80 right after the message, bit length in the
    # last two bytes (messages are < 2^13 bits).
    msg_len = 9 + serial_len
    pos = jnp.arange(64, dtype=jnp.int32)[None, :]
    msg = jnp.where(pos == msg_len[:, None], jnp.uint8(0x80), msg)
    bits = (msg_len * 8).astype(jnp.uint32)
    msg = msg.at[:, 62].set(((bits >> 8) & 0xFF).astype(jnp.uint8))
    msg = msg.at[:, 63].set((bits & 0xFF).astype(jnp.uint8))
    words = msg.reshape(b, 16, 4).astype(jnp.uint32)
    block = (
        (words[:, :, 0] << 24) | (words[:, :, 1] << 16)
        | (words[:, :, 2] << 8) | words[:, :, 3]
    )
    return sha256.sha256_fingerprint64(block)


def _cn_prefix_match(
    rows, cn_off: jax.Array, cn_len: jax.Array,
    prefixes: jax.Array, prefix_lens: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Does the issuer CN start with any configured prefix?

    prefixes: uint8[P, K] (first K bytes of each prefix, K ≤
    der_kernel.MAX_FIXED_WINDOW_BYTES); prefix_lens: int32[P, 2] —
    column 0 the device-comparable length (= min(len, K)), column 1
    the TRUE configured length. P == 0 handled by the caller (filter
    disabled). ``rows`` are the shared word-packed rows
    (:func:`der_kernel.window_bytes_rows` — gather-free).

    Returns ``(hit, undecidable)`` bool[B]: ``hit`` = definitely
    matches some prefix; ``undecidable`` = matches the K-byte head of
    a LONGER-than-K prefix and is long enough that the tail could
    match — the device cannot decide, so the lane must take the exact
    host lane (the reference compares full prefixes,
    /root/reference/cmd/ct-fetch/ct-fetch.go:56-62).
    """
    k = prefixes.shape[1]
    window = der_kernel.window_bytes_rows(rows, cn_off, k).astype(jnp.uint8)
    inside = jnp.arange(k, dtype=jnp.int32)[None, :] < cn_len[:, None]
    window = jnp.where(inside, window, 0)
    dev_lens = prefix_lens[:, 0]
    true_lens = prefix_lens[:, 1]
    # [B, P, K] compare, masked beyond each prefix's device length
    eq = window[:, None, :] == prefixes[None, :, :]
    care = jnp.arange(k, dtype=jnp.int32)[None, None, :] < dev_lens[None, :, None]
    full = jnp.all(eq | ~care, axis=-1)  # [B, P]
    truncated = (true_lens > dev_lens)[None, :]
    hit = jnp.any(
        full & (cn_len[:, None] >= dev_lens[None, :]) & ~truncated, axis=-1
    )
    undecidable = jnp.any(
        full & (cn_len[:, None] >= true_lens[None, :]) & truncated, axis=-1
    )
    return hit, undecidable


class LocalLanes(NamedTuple):
    """Per-lane results of the communication-free ingest stages."""

    parsed: "der_kernel.ParsedCerts"
    serials: jax.Array  # uint8[B, MAX_SERIAL_BYTES]
    filtered_ca: jax.Array
    filtered_expired: jax.Array
    filtered_cn: jax.Array
    passed: jax.Array  # survived all filters
    device_exact: jax.Array  # serial/meta/issuer fit the device schema
    insertable: jax.Array  # passed & device_exact
    fps: jax.Array  # uint32[B, 4] dedup fingerprints
    meta: jax.Array  # uint32[B] packed (issuer_idx, exp-hour offset)


def local_lanes(
    data: jax.Array,
    length: jax.Array,
    issuer_idx: jax.Array,
    valid: jax.Array,
    now_hour: jax.Array,
    base_hour: jax.Array,
    cn_prefixes: jax.Array,
    cn_prefix_lens: jax.Array,
    num_issuers: int,
) -> LocalLanes:
    """Parse → filter → fingerprint, shared by the single-chip step and
    the per-device body of the mesh-sharded step (no communication).

    Rows are word-packed ONCE and shared by the parse walker, the
    serial extraction, and the CN window — one pass over [B, L], not
    three (der_kernel's gather-free access path)."""
    rows = der_kernel.pack_rows(data)
    # The RDN scan only feeds the CN-prefix filter; with no prefixes
    # configured (static shape) it is dead work — skip it at trace time.
    parsed = der_kernel.parse_certs_rows(
        rows, length, scan_issuer_cn=cn_prefixes.shape[0] > 0
    )
    ok = parsed.ok & valid

    serials, fits = der_kernel.gather_serials_rows(
        rows, parsed.serial_off, parsed.serial_len, packing.MAX_SERIAL_BYTES
    )

    # Filters, in the reference's precedence order
    # (/root/reference/cmd/ct-fetch/ct-fetch.go:44-70).
    f_ca = ok & parsed.is_ca
    f_expired = ok & ~f_ca & (parsed.not_after_hour < now_hour)
    if cn_prefixes.shape[0] > 0:
        cn_hit, cn_undec = _cn_prefix_match(
            rows, parsed.issuer_cn_off, parsed.issuer_cn_len,
            cn_prefixes, cn_prefix_lens,
        )
        # A lane matching only the truncated head of an over-long
        # prefix is NOT filtered here — it routes to the exact host
        # lane below (device_exact), where full prefixes decide.
        cn_undec = ok & ~f_ca & ~f_expired & ~cn_hit & cn_undec
        f_cn = ok & ~f_ca & ~f_expired & ~cn_hit & ~cn_undec
    else:
        f_cn = cn_undec = jnp.zeros_like(ok)
    passed = ok & ~f_ca & ~f_expired & ~f_cn

    # Device-exactness gate: lanes outside the packed schema go host-side.
    # A cert expiring WITHIN the current hour is also routed to the
    # exact host lane: the device compares hour buckets, the reference
    # compares instants (`NotAfter.Before(now)`,
    # /root/reference/cmd/ct-fetch/ct-fetch.go:52-55); buckets strictly
    # before/after `now_hour` classify identically either way, and the
    # boundary bucket gets the exact instant compare on host
    # (TpuAggregator._host_exact), so the combined system matches the
    # reference exactly.
    hour_off = parsed.not_after_hour - base_hour
    meta_ok = (hour_off >= 0) & (hour_off < packing.META_HOUR_SPAN)
    idx_ok = (issuer_idx >= 0) & (issuer_idx < num_issuers)
    boundary_hour = parsed.not_after_hour == now_hour
    device_exact = fits & meta_ok & idx_ok & ~boundary_hour & ~cn_undec

    fps = fingerprints(issuer_idx, parsed.not_after_hour, serials, parsed.serial_len)
    meta = (
        (issuer_idx.astype(jnp.uint32) << packing.META_HOUR_BITS)
        | jnp.clip(hour_off, 0, packing.META_HOUR_SPAN - 1).astype(jnp.uint32)
    )
    return LocalLanes(
        parsed=parsed,
        serials=serials,
        filtered_ca=f_ca,
        filtered_expired=f_expired,
        filtered_cn=f_cn,
        passed=passed,
        device_exact=device_exact,
        insertable=passed & device_exact,
        fps=fps,
        meta=meta,
    )


def ingest_core(
    table: hashtable.TableState,
    data: jax.Array,
    length: jax.Array,
    issuer_idx: jax.Array,
    valid: jax.Array,
    now_hour: jax.Array,
    base_hour: jax.Array,
    cn_prefixes: jax.Array,
    cn_prefix_lens: jax.Array,
    num_issuers: int = packing.MAX_ISSUERS,
    max_probes: int = 32,
) -> tuple[hashtable.TableState, StepOut]:
    """Process one packed batch end-to-end on device.

    Args:
      table: dedup state (donated).
      data/length/issuer_idx/valid: the packed batch.
      now_hour: scalar int32 — "now" for the expiry filter (the
        reference filters ``NotAfter.Before(now)``).
      base_hour: scalar int32 — meta-word epoch base.
      cn_prefixes/cn_prefix_lens: uint8[P, K]/int32[P, 2]
        (device-comparable length, true length); P == 0 disables
        the CN filter (shape is static ⇒ config changes recompile once).
    """
    lanes = local_lanes(
        data, length, issuer_idx, valid, now_hour, base_hour,
        cn_prefixes, cn_prefix_lens, num_issuers,
    )
    parsed = lanes.parsed

    table, was_unknown, overflowed = table_insert(
        table, lanes.fps, lanes.meta, lanes.insertable, max_probes=max_probes
    )

    host_lane = (
        (valid & ~parsed.ok) | (lanes.passed & ~lanes.device_exact) | overflowed
    )

    issuer_counts = jnp.zeros((num_issuers,), jnp.int32).at[issuer_idx].add(
        was_unknown.astype(jnp.int32), mode="drop"
    )

    return table, StepOut(
        was_unknown=was_unknown,
        host_lane=host_lane,
        probe_overflow=overflowed,
        filtered_ca=lanes.filtered_ca,
        filtered_expired=lanes.filtered_expired,
        filtered_cn=lanes.filtered_cn,
        stored=lanes.insertable & ~overflowed,
        not_after_hour=parsed.not_after_hour,
        serials=lanes.serials,
        serial_len=parsed.serial_len,
        issuer_unknown_counts=issuer_counts,
        has_crldp=parsed.has_crldp,
        crldp_off=parsed.crldp_off,
        crldp_len=parsed.crldp_len,
        issuer_name_off=parsed.issuer_off,
        issuer_name_len=parsed.issuer_len,
    )


# -- pre-parsed ingest lane ---------------------------------------------
#
# When the native decoder has already extracted the identity fields on
# the host (native/ctmr_native.cpp ctmr_extract_sidecars — a scalar
# port of the device walker), the device step collapses to its
# arithmetic floor: fingerprint SHA-256 + dedup-table insert +
# per-issuer counts. No row bytes ship to the device at all (~59 B of
# compact inputs per lane instead of 1-2 KB of padded DER), the
# word-pack and the DER walker (~107 of the walker step's ~194
# ns/entry per the round-5 cost model) disappear, and the readback is
# COMPACT: a was-unknown bitmask (1 bit/lane), sort-compacted
# probe-overflow lane indices (O(flagged), not O(batch)), and the
# count vectors — packed into ONE int32 array so the tunneled stack's
# per-execution readback toll is paid once per dispatch.
#
# Every filter/routing predicate that doesn't depend on table state
# (CA/expired/CN filters, the device-exactness gates) is a pure
# function of the sidecar and is evaluated by the HOST
# (agg/aggregator.py ingest_preparsed_submit) with arithmetic
# mirroring local_lanes exactly; only `insertable` reaches the device.

N_PREPARSED_FLAG_CAP = 1024  # default compacted-overflow capacity


class PreparsedStepOut(NamedTuple):
    """Device outputs of the pre-parsed step, readback-oriented."""

    packed: jax.Array  # int32[K, 2 + nb + flag_cap + num_issuers] — the
    # ONE array the host reads per dispatch; per chunk row:
    #   [0] unknown_count, [1] overflow_count,
    #   [2 : 2+nb] was-unknown bitmask (bit i of word w = lane w*32+i),
    #   [2+nb : 2+nb+flag_cap] overflow lane ids ascending (B = none),
    #   [2+nb+flag_cap :] per-issuer fresh-insert counts.
    overflow_bits: jax.Array  # uint32[K, nb] — full overflow bitmask,
    # fetched ONLY when overflow_count exceeds flag_cap (spill).


def _pack_bits(flags: jax.Array, nb: int) -> jax.Array:
    """bool[B] → uint32[nb] bitmask (bit i of word w = lane w*32+i)."""
    b = flags.shape[0]
    padded = jnp.pad(flags, (0, nb * 32 - b)).reshape(nb, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    return jnp.sum(jnp.where(padded, weights, jnp.uint32(0)), axis=1)


def preparsed_core(
    table,
    serials: jax.Array,  # uint8[K, B, MAX_SERIAL_BYTES]
    serial_len: jax.Array,  # int32[K, B]
    not_after_hour: jax.Array,  # int32[K, B]
    issuer_idx: jax.Array,  # int32[K, B]
    insertable: jax.Array,  # bool[K, B] — host-computed gate
    base_hour: jax.Array,  # int32 scalar
    num_issuers: int = packing.MAX_ISSUERS,
    max_probes: int = 32,
    flag_cap: int = N_PREPARSED_FLAG_CAP,
):
    """Fused multi-chunk pre-parsed step: ONE device execution for K
    resident chunks (fori_loop, like the aggregator's reinsert path) —
    on the tunneled stack every execution charges ~0.2 s on its first
    later D2H read, so chunked dispatch loops would pay it K times."""
    k_chunks, b = serial_len.shape
    nb = -(-b // 32)
    width = 2 + nb + flag_cap + num_issuers
    packed0 = jnp.zeros((k_chunks, width), jnp.int32)
    ovf_bits0 = jnp.zeros((k_chunks, nb), jnp.uint32)

    def body(k, carry):
        table, packed, ovf_bits = carry
        fps = fingerprints(
            issuer_idx[k], not_after_hour[k], serials[k], serial_len[k]
        )
        hour_off = not_after_hour[k] - base_hour
        meta = (
            (issuer_idx[k].astype(jnp.uint32) << packing.META_HOUR_BITS)
            | jnp.clip(hour_off, 0, packing.META_HOUR_SPAN - 1).astype(
                jnp.uint32)
        )
        table, wu, ovf = table_insert(
            table, fps, meta, insertable[k], max_probes=max_probes
        )
        counts = jnp.zeros((num_issuers,), jnp.int32).at[issuer_idx[k]].add(
            wu.astype(jnp.int32), mode="drop"
        )
        iota = jnp.arange(b, dtype=jnp.int32)
        ovf_idx = jnp.sort(jnp.where(ovf, iota, b))[:flag_cap]
        if flag_cap > b:  # tiny chunks: keep the packed width static
            ovf_idx = jnp.pad(ovf_idx, (0, flag_cap - b),
                              constant_values=b)
        row = jnp.concatenate([
            jnp.stack([wu.sum(dtype=jnp.int32), ovf.sum(dtype=jnp.int32)]),
            jax.lax.bitcast_convert_type(_pack_bits(wu, nb), jnp.int32),
            ovf_idx,
            counts,
        ])
        return (
            table,
            packed.at[k].set(row),
            ovf_bits.at[k].set(_pack_bits(ovf, nb)),
        )

    table, packed, ovf_bits = jax.lax.fori_loop(
        0, k_chunks, body, (table, packed0, ovf_bits0)
    )
    return table, PreparsedStepOut(packed=packed, overflow_bits=ovf_bits)


# -- staged multi-chunk walker envelope ---------------------------------
#
# The resident device loop of the staged ingest queue (round 11): K
# walker chunks run in ONE jitted execution (fori_loop over
# `ingest_core`, extending the `preparsed_core` K-chunk pattern to the
# full walker path). Per-lane outputs come back PRE-PACKED — the same
# int32[7, B] layout the aggregator's `_pack_out` builds per chunk, but
# assembled on device inside the envelope, so the host reads ONE
# [K, 7, B] array per dispatch instead of running a packing jit + a
# readback per chunk. Per-issuer fresh-insert counts accumulate across
# the K chunks on device (one [num_issuers] vector per dispatch, not
# K). On the tunneled stack every execution charges ~0.2 s on its
# first later D2H read (BENCHLOG platform notes), so K chunks per
# dispatch divides that toll by K; on every stack it divides the
# Python dispatch overhead by K.

class StagedStepOut(NamedTuple):
    """Device outputs of the K-chunk walker envelope."""

    packed: jax.Array  # int32[K, 7, B] — per chunk: row 0 = lane flag
    # word (host_lane | was_unknown<<1 | filtered_ca<<2 |
    # filtered_expired<<3 | filtered_cn<<4 | probe_overflow<<5 — the
    # `_pack_out` bit layout), rows 1-6 = not_after_hour, serial_len,
    # crldp_off, crldp_len, issuer_name_off, issuer_name_len.
    issuer_unknown_counts: jax.Array  # int32[num_issuers] — summed
    # across the K chunks (fold order is insensitive to the split).
    serials: jax.Array  # uint8[K, B, MAX_SERIAL_BYTES]


def pack_lane_words(out: StepOut) -> jax.Array:
    """One chunk's per-lane outputs as int32[7, B] — the readback
    layout shared with the aggregator's host-side unpack (and its
    per-chunk `_pack_out` twin for the unstaged path)."""
    flags = (
        out.host_lane.astype(jnp.int32)
        | (out.was_unknown.astype(jnp.int32) << 1)
        | (out.filtered_ca.astype(jnp.int32) << 2)
        | (out.filtered_expired.astype(jnp.int32) << 3)
        | (out.filtered_cn.astype(jnp.int32) << 4)
        | (out.probe_overflow.astype(jnp.int32) << 5)
    )
    return jnp.stack(
        [flags, out.not_after_hour, out.serial_len,
         out.crldp_off, out.crldp_len,
         out.issuer_name_off, out.issuer_name_len], axis=0)


def staged_core(
    table,
    data: jax.Array,  # uint8[K, B, L]
    length: jax.Array,  # int32[K, B]
    issuer_idx: jax.Array,  # int32[K, B]
    valid: jax.Array,  # bool[K, B]
    now_hour: jax.Array,
    base_hour: jax.Array,
    cn_prefixes: jax.Array,
    cn_prefix_lens: jax.Array,
    num_issuers: int = packing.MAX_ISSUERS,
    max_probes: int = 32,
) -> tuple:
    """K full walker chunks in one resident loop. Each iteration is
    exactly `ingest_core` — parse, filter, fingerprint, insert — so the
    staged path is parity-identical with K sequential unstaged steps by
    construction (the dedup table threads through the loop carry in
    submission order)."""
    k_chunks, b = length.shape
    packed0 = jnp.zeros((k_chunks, 7, b), jnp.int32)
    serials0 = jnp.zeros((k_chunks, b, packing.MAX_SERIAL_BYTES), jnp.uint8)
    counts0 = jnp.zeros((num_issuers,), jnp.int32)

    def body(k, carry):
        table, packed, serials, counts = carry
        table, out = ingest_core(
            table, data[k], length[k], issuer_idx[k], valid[k],
            now_hour, base_hour, cn_prefixes, cn_prefix_lens,
            num_issuers=num_issuers, max_probes=max_probes,
        )
        return (
            table,
            packed.at[k].set(pack_lane_words(out)),
            serials.at[k].set(out.serials),
            counts + out.issuer_unknown_counts,
        )

    table, packed, serials, counts = jax.lax.fori_loop(
        0, k_chunks, body, (table, packed0, serials0, counts0)
    )
    return table, StagedStepOut(
        packed=packed, issuer_unknown_counts=counts, serials=serials)


# The production entry point: donated table state, cached per shape.
ingest_step = functools.partial(
    jax.jit,
    static_argnames=("num_issuers", "max_probes"),
    donate_argnums=(0,),
)(ingest_core)

# Pre-parsed lane entry points (donating and not: CPU's XLA can't
# alias the donated layouts and warns per dispatch, so the aggregator
# picks by backend exactly like the walker-lane pair below).
ingest_step_preparsed = functools.partial(
    jax.jit,
    static_argnames=("num_issuers", "max_probes", "flag_cap"),
)(preparsed_core)

ingest_step_preparsed_donated = functools.partial(
    jax.jit,
    static_argnames=("num_issuers", "max_probes", "flag_cap"),
    donate_argnums=(0,),
)(preparsed_core)

# Overlapped-ingest entry point: donates the packed row buffer too.
# The overlap scheduler hands the step a device-resident batch it will
# never touch again (host-lane fallbacks slice the separate host copy),
# so donating `data` lets XLA reuse ~batch-size HBM per in-flight batch
# instead of holding the input rows live alongside the step's
# intermediates — at deviceQueueDepth 2 that is two full batches of
# headroom. Callers that keep NumPy rows (tail chunks, the synchronous
# per-entry path) stay on `ingest_step`.
ingest_step_donated = functools.partial(
    jax.jit,
    static_argnames=("num_issuers", "max_probes"),
    donate_argnums=(0, 1),
)(ingest_core)

# Staged-envelope entry points (donating and not, picked by backend
# like the pairs above: CPU's XLA can't alias the donated layouts and
# warns per dispatch). The donating form donates the TABLE and the
# [K, B, L] row buffer — the staging ring keeps its own host copy for
# host-lane slices, so after the dispatch the device rows are dead
# weight and XLA reuses their HBM for the next staged buffer: that
# reuse is what makes the two cycled staging buffers a true double
# buffer instead of 2×K chunks of additional residency.
ingest_step_staged = functools.partial(
    jax.jit,
    static_argnames=("num_issuers", "max_probes"),
)(staged_core)

ingest_step_staged_donated = functools.partial(
    jax.jit,
    static_argnames=("num_issuers", "max_probes"),
    donate_argnums=(0, 1),
)(staged_core)
