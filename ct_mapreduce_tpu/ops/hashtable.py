"""Device-resident dedup set: open-addressed hash table in HBM.

NOTE: since round 4 this slot-granular layout is the FALLBACK
(``CTMR_TABLE=open``); the default is the bucketized table in
:mod:`ct_mapreduce_tpu.ops.buckettable`, whose measured insert is
~10x cheaper on v5e (709 vs ~68 ns/entry at 2^20 lanes — this
module's per-round 5-word row scatter alone prices at 86.5 ns/lane
from tile-misalignment; see tools/randacc.py and BENCHLOG round 4).
Kept for layout comparisons and pre-round-4 checkpoint compatibility.

This is the TPU-native replacement for the reference's per-certificate
Redis ``SADD`` round trip (`WasUnknown`,
/root/reference/storage/knowncertificates.go:38-55 →
/root/reference/storage/rediscache.go:57-65): a whole batch of
certificate fingerprints is inserted in one jitted op, returning the
per-lane "was unknown" bit with the same semantics Redis set-insert
gives (first writer wins; re-inserting a known key is a no-op).

Keys are 128-bit truncated SHA-256 fingerprints of
``(expHour, issuerDigest, serial)`` — see
:func:`ct_mapreduce_tpu.core.packing.fingerprint_block` — stored as
``uint32[capacity, 4]``. The all-zero key is the empty sentinel; real
fingerprints are remapped away from it (probability 2^-128 anyway).

Insertion algorithm (bounded trip count, jit/pjit-friendly — the probe
loop is a ``lax.while_loop`` that exits as soon as no lane is pending;
sort-free, gather-light):

Each lane carries its own probe index ``r`` (triangular probing over a
power-of-two capacity, guaranteed full-cycle). Per round, every
pending lane examines a WINDOW of ``PROBE_WIDTH`` consecutive chain
positions in one gather, and resolves at the first position that is
not an occupied mismatch:

- 4-word compare says "already present" → done, ``was_unknown=False``;
- first empty slot in the window → contend via a deterministic
  scatter-min election: contenders scatter their lane id into a claim
  scratch with ``.min`` (min is commutative — duplicate indices are
  safe and order-independent) and read it back; the surviving lane
  wins and writes key+meta (winners hold unique slots, so those
  scatters never see duplicate indices — XLA's duplicate-index
  scatter is specified per element, not per row, so a whole-row CAS
  could tear). Losers resolve IN the same round by comparing their key
  against the winner's — ``keys[claim[slot]]``, a batch-sized gather,
  never a second table-sized read: a match means a within-batch
  duplicate (done, ``was_unknown=False`` — first-in-lane-order wins,
  exactly Redis SADD semantics when the reference stores the same
  serial twice); a different key means the chain moved — probe on past
  the slot;
- all window positions occupied by other keys → ``r`` advances past
  the window.

Random-access ops (gather/scatter on the HBM-resident table) carry a
large fixed per-op cost on TPU, so the structure minimizes OP COUNT
per round (4 table-touching ops — the fused row commits key+meta in
one scatter — and no claim reset: a slot is contended
at most once per call) and ROUND COUNT (losers resolve in-round;
windows cover W chain positions per gather).

A key always lands at the FIRST empty slot of its probe chain (losers
never skip the contested slot), so ``contains``' probe-until-empty
lookup invariant holds.

Within-batch dedup therefore falls out of the probe loop itself — no
pre-pass needed (the previous design ran 33 full-batch lexsorts per
insert; this one runs zero sorts).

Lanes that exhaust ``max_probes`` (or the round budget) are reported
in ``overflowed``; the aggregator sends them down the exact host lane
(the same reject-to-host contract the reference uses for unparseable
entries, /root/reference/cmd/ct-fetch/ct-fetch.go:206-225).

Alongside each key a ``meta`` word (packed issuer index + expiry hour
offset, :mod:`ct_mapreduce_tpu.core.packing`) is stored so a drain can
reconstruct exact per-(issuer, expDate) serial counts without a second
device pass.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


# Chain positions examined per probe round (one gather). Wider windows
# resolve more lanes in round 1 (P(all W occupied) = load^W) at the
# price of a W-times-larger gather; env-tunable for hardware sweeps.
def _probe_width_from_env() -> int:
    raw = os.environ.get("CTMR_PROBE_WIDTH", "4")
    try:
        width = int(raw)
        if width < 1:
            raise ValueError
    except ValueError:
        # A malformed env var must not break `import ct_mapreduce_tpu`
        # for CLI paths that never probe; degrade to the default loudly.
        import warnings

        warnings.warn(
            f"ignoring CTMR_PROBE_WIDTH={raw!r} (want an int >= 1); "
            "using 4", stacklevel=2)
        return 4
    return width


PROBE_WIDTH = _probe_width_from_env()


class TableState(NamedTuple):
    """Dedup-set state living in HBM (donated through insert steps).

    One FUSED row per slot — 4 fingerprint words + the meta word —
    so a winning lane commits key AND meta in a single scatter
    (random-access table ops carry a large fixed cost on TPU; fusing
    the two writes cuts insert from 5 table-touching ops per probe
    round to 4). The all-zero KEY words mark an empty slot; meta of 0
    is legal data.
    """

    rows: jax.Array  # uint32[capacity, 5]: fp words 0..3, meta word 4
    count: jax.Array  # int32[]; occupied slots

    @property
    def keys(self) -> jax.Array:  # uint32[capacity, 4] view
        return self.rows[:, :4]

    @property
    def meta(self) -> jax.Array:  # uint32[capacity] view
        return self.rows[:, 4]


def make_table(capacity: int) -> TableState:
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    return TableState(
        rows=jnp.zeros((capacity, 5), dtype=jnp.uint32),
        count=jnp.zeros((), dtype=jnp.int32),
    )


def fuse_rows(keys, meta):
    """uint32[N, 4] + uint32[N] → fused uint32[N, 5] rows (works on
    NumPy and jax arrays alike)."""
    xp = jnp if isinstance(keys, jax.Array) else np
    return xp.concatenate(
        [keys.astype(xp.uint32), meta.astype(xp.uint32)[:, None]], axis=1
    )


def _home_slot(keys: jax.Array, capacity: int) -> jax.Array:
    """Initial probe slot from the fingerprint's first two words."""
    h = keys[:, 0] ^ (keys[:, 1] * np.uint32(0x9E3779B9))
    return (h & np.uint32(capacity - 1)).astype(jnp.int32)


def _probe_window(
    table_rows: jax.Array,
    keys: jax.Array,
    home: jax.Array,
    r: jax.Array,
    W: int,
    max_probes: int,
    chain_capacity: int,
    slot_base: jax.Array | int = 0,
):
    """One W-wide window of triangular-chain probes: the shared access
    pattern of ``insert``, ``contains`` and the sharded membership scan
    (``slot_base`` offsets into a shard's row block).

    ``table_rows`` is the fused uint32[capacity, 5] table (or any
    row array whose first 4 words are the key); matching and the
    empty-slot test look only at the key words.

    Returns ``(slots [B, W], match_j [B, W], empty_j [B, W])`` with
    positions past ``max_probes`` masked out of both match and empty.
    """
    rj = r[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]  # [B, W]
    chain = (home[:, None] + (rj * (rj + 1)) // 2) & (chain_capacity - 1)
    if isinstance(slot_base, int) and slot_base == 0:
        slots = chain
    else:
        slots = slot_base[:, None] + chain
    in_budget = rj < max_probes
    cur = table_rows[slots][..., :4]  # [B, W, 4] key words of each row
    match_j = jnp.all(cur == keys[:, None, :], axis=-1) & in_budget
    empty_j = jnp.all(cur == 0, axis=-1) & in_budget
    return slots, match_j, empty_j


def _desentinel(keys: jax.Array) -> jax.Array:
    """Remap the (astronomically unlikely) all-zero fingerprint."""
    is_zero = jnp.all(keys == 0, axis=-1, keepdims=True)
    bump = jnp.concatenate(
        [jnp.zeros(keys.shape[:-1] + (3,), jnp.uint32),
         jnp.ones(keys.shape[:-1] + (1,), jnp.uint32)], axis=-1)
    return jnp.where(is_zero, bump, keys)


@functools.partial(jax.jit, static_argnames=("max_probes",), donate_argnums=(0,))
def insert(
    state: TableState,
    keys: jax.Array,
    meta: jax.Array,
    valid: jax.Array,
    max_probes: int = 32,
):
    """Batch insert-if-absent.

    Args:
      state: the table (donated; updated in place in HBM).
      keys: uint32[B, 4] fingerprints.
      meta: uint32[B] per-lane metadata scattered on successful insert.
      valid: bool[B]; padding lanes are ignored entirely.
      max_probes: probe rounds before declaring overflow.

    Returns:
      (new_state, was_unknown bool[B], overflowed bool[B]).
    """
    capacity = state.rows.shape[0]
    b = keys.shape[0]
    keys = _desentinel(keys.astype(jnp.uint32))
    qrows = fuse_rows(keys, meta)  # [B, 5]: what a winner commits
    home = _home_slot(keys, capacity)

    lane = jnp.arange(b, dtype=jnp.int32)
    no_lane = jnp.int32(2**31 - 1)
    W = min(PROBE_WIDTH, max_probes)
    # Every pending lane advances its probe index by ≥1 per round
    # (losers resolve in-round and skip past the contested slot), so
    # max_probes + 1 rounds bound the loop; lanes that leave the loop
    # still pending are overflow → exact host lane.
    max_rounds = max_probes + 1

    def cond(carry):
        rounds, _r, _rows, _claim, pending, _found, _inserted, _ovf = carry
        return (rounds < max_rounds) & jnp.any(pending)

    def round_body(carry):
        (rounds, r, table_rows, claim,
         pending, found, inserted, ovf) = carry
        # Probe window: W consecutive triangular-chain positions
        # starting at each lane's r, fetched in ONE gather.
        slots, match_j, empty_j = _probe_window(
            table_rows, keys, home, r, W, max_probes, capacity
        )
        stop_j = match_j | empty_j
        any_stop = jnp.any(stop_j, axis=-1)
        jstar = jnp.argmax(stop_j, axis=-1).astype(jnp.int32)  # first stop
        sel = jnp.take_along_axis  # alias
        match = pending & any_stop & sel(match_j, jstar[:, None], 1)[:, 0]
        empty = pending & any_stop & ~match
        slot = sel(slots, jstar[:, None], 1)[:, 0]
        # Deterministic election at each lane's first-empty slot:
        # scatter-min lane ids (min commutes ⇒ duplicate indices are
        # safe), read back; the surviving lane id is the winner. No
        # reset pass is needed: a slot is contended at most once per
        # insert call — its election always produces a winner, who
        # occupies it, so no later round can see it empty again.
        cslot = jnp.where(empty, slot, capacity)  # OOB rows are dropped
        claim = claim.at[cslot].min(lane, mode="drop")
        wlane = claim[slot]  # winning lane id at each contested slot
        winner = empty & (wlane == lane)
        # Winners hold unique slots, so this scatter sees no duplicate
        # indices; the FUSED row commits key and meta in ONE op (the
        # whole point of the fused layout — one fewer table-sized
        # random-access op per round).
        wslot = jnp.where(winner, slot, capacity)
        table_rows = table_rows.at[wslot].set(qrows, mode="drop")
        # Resolve election losers IN-ROUND (random-access ops have a
        # large fixed cost on TPU, so resolving here is far cheaper
        # than an extra round): the winner's key is keys[wlane] — a
        # BATCH-sized gather, never a second table-sized one. Losers
        # whose key equals the winner's are within-batch duplicates
        # (done, known); distinct-key losers probe on past the slot.
        wkeys = jnp.take(keys, jnp.clip(wlane, 0, b - 1), axis=0)  # [B, 4]
        loser = empty & ~winner
        loser_match = loser & jnp.all(wkeys == keys, axis=-1)
        found = found | match | loser_match
        inserted = inserted | winner
        pending = pending & ~match & ~winner & ~loser_match
        # Remaining pending lanes continue past what they examined:
        # distinct-key losers past the contested position, miss-through
        # lanes past the whole window.
        r = jnp.where(pending, jnp.where(any_stop, r + jstar + 1, r + W), r)
        # A lane that exhausts its probe chain is overflow — record it
        # and drop it from pending so the loop can terminate early.
        exhausted = pending & (r >= max_probes)
        ovf = ovf | exhausted
        pending = pending & ~exhausted
        return (rounds + 1, r, table_rows, claim,
                pending, found, inserted, ovf)

    pending0 = valid
    zeros = jnp.zeros((b,), bool)
    r0 = jnp.zeros((b,), jnp.int32)
    # Fresh capacity-sized claim scratch per call: a single ~4B/slot
    # broadcast fill (≈0.3 ms at 2^26 on v5e HBM, against a multi-ms
    # step) buys an election that needs no persistent state — the
    # persistent TableState stays just (rows, count), which the
    # checkpoint codec splits back into keys/meta for format
    # stability. Revisit only if profiles show the fill on the flame
    # graph.
    claim0 = jnp.full((capacity,), no_lane, dtype=jnp.int32)
    (_, _, table_rows, _, pending, found,
     inserted, ovf) = jax.lax.while_loop(
        cond, round_body,
        (jnp.int32(0), r0, state.rows, claim0,
         pending0, zeros, zeros, zeros),
    )

    was_unknown = inserted  # lanes that claimed a slot
    # Never found a home: probe chain exhausted, or still pending when
    # the round budget ran out (pathological contention) — either way
    # the exact host lane takes over.
    overflowed = ovf | pending
    new_count = state.count + jnp.sum(inserted, dtype=jnp.int32)
    return TableState(table_rows, new_count), was_unknown, overflowed


@functools.partial(jax.jit, static_argnames=("max_probes",))
def contains(state: TableState, keys: jax.Array, max_probes: int = 32) -> jax.Array:
    """Batch membership query (no mutation): bool[B].

    Same access structure as :func:`insert`: a W-wide window of chain
    positions per gather, with a ``while_loop`` that exits as soon as
    every lane has hit a match or an empty slot — the common case is
    ONE table gather, not ``max_probes`` of them (random-access table
    ops are latency-priced per lane on TPU: ~13-15 ns/lane measured,
    tools/randacc.py)."""
    capacity = state.rows.shape[0]
    keys = _desentinel(keys.astype(jnp.uint32))
    home = _home_slot(keys, capacity)
    b = keys.shape[0]
    W = min(PROBE_WIDTH, max_probes)

    def cond(carry):
        r, found, open_ = carry
        return jnp.any(open_)

    def round_body(carry):
        r, found, open_ = carry
        _slots, match_j, empty_j = _probe_window(
            state.rows, keys, home, r, W, max_probes, capacity
        )
        found = found | (open_ & jnp.any(
            match_j & (jnp.cumsum(empty_j, axis=-1) == 0), axis=-1
        ))
        # A lane stays open only if every in-budget window position was
        # an occupied mismatch (chain continues past the window).
        still = open_ & ~jnp.any(match_j | empty_j, axis=-1)
        r = jnp.where(still, r + W, r)
        open_ = still & (r < max_probes)
        return r, found, open_

    _, found, _ = jax.lax.while_loop(
        cond, round_body,
        (jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
         jnp.ones((b,), bool)),
    )
    return found


def contains_np(table_rows: np.ndarray, keys: np.ndarray,
                max_probes: int = 32) -> np.ndarray:
    """NumPy mirror of :func:`contains` — same home slot, triangular
    chain, and match-before-first-empty invariant — for host-only
    snapshot reads (storage-statistics is pure host work and must not
    allocate device buffers or wait on TPU acquisition).

    ``table_rows`` may be the fused [capacity, 5] rows or a bare
    [capacity, 4] key array; only the key words are examined.

    Vectorized (drain probes every host-lane serial in one call), with
    the batch chunked to bound the [chunk, max_probes, 4] gather."""
    capacity = table_rows.shape[0]
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    keys = keys.astype(np.uint32, copy=True).reshape(-1, 4)
    zero = ~keys.any(axis=-1)
    keys[zero, :] = 0
    keys[zero, 3] = 1  # _desentinel
    mask = capacity - 1
    home = (keys[:, 0] ^ (keys[:, 1] * np.uint32(0x9E3779B9))).astype(np.int64)
    r = np.arange(max_probes, dtype=np.int64)
    tri = (r * (r + 1)) // 2
    out = np.zeros((keys.shape[0],), bool)
    table_keys = table_rows[:, :4]  # zero-copy view; gather keys only
    for start in range(0, keys.shape[0], 65536):
        sl = slice(start, start + 65536)
        slots = (home[sl, None] + tri[None, :]) & mask  # [b, P]
        rows = table_keys[slots]  # [b, P, 4] key words
        match = (rows == keys[sl, None, :]).all(axis=-1)
        empty = ~rows.any(axis=-1)
        out[sl] = (match & (np.cumsum(empty, axis=1) == 0)).any(axis=1)
    return out


def occupied(state: TableState) -> jax.Array:
    """bool[capacity] occupancy mask."""
    return jnp.any(state.keys != 0, axis=-1)


def drain_np(state: TableState) -> tuple[np.ndarray, np.ndarray]:
    """Pull (keys, meta) of occupied slots to host as NumPy arrays."""
    keys = np.asarray(state.keys)
    meta = np.asarray(state.meta)
    occ = keys.any(axis=-1)
    return keys[occ], meta[occ]
