"""Device-resident dedup set: open-addressed hash table in HBM.

This is the TPU-native replacement for the reference's per-certificate
Redis ``SADD`` round trip (`WasUnknown`,
/root/reference/storage/knowncertificates.go:38-55 →
/root/reference/storage/rediscache.go:57-65): a whole batch of
certificate fingerprints is inserted in one jitted op, returning the
per-lane "was unknown" bit with the same semantics Redis set-insert
gives (first writer wins; re-inserting a known key is a no-op).

Keys are 128-bit truncated SHA-256 fingerprints of
``(expHour, issuerDigest, serial)`` — see
:func:`ct_mapreduce_tpu.core.packing.fingerprint_block` — stored as
``uint32[capacity, 4]``. The all-zero key is the empty sentinel; real
fingerprints are remapped away from it (probability 2^-128 anyway).

Insertion algorithm (bounded trip count, jit/pjit-friendly — the probe
loop is a ``lax.while_loop`` that exits as soon as no lane is pending,
probing at most ``max_probes`` rounds):

1. *Within-batch dedup*: lexsort lanes by the 4 key words; a lane is a
   "representative" iff its key differs from its sorted predecessor.
   Duplicate lanes inside one batch report ``was_unknown=False`` for
   every occurrence after the first, matching Redis semantics when the
   reference stores the same serial twice in a row.
2. *Probe rounds* (triangular probing over a power-of-two capacity,
   guaranteed full-cycle): each pending representative gathers its
   slot; a 4-word compare detects "already present"; empty slots are
   claimed by a deterministic scatter-min election: contenders
   scatter their lane id into a claim scratch with ``.min`` (min is
   commutative — duplicate indices are safe and order-independent),
   read the slot back, and the lane whose id survived is the winner.
   Winners therefore hold **unique** slots, so the key/meta scatters
   never see duplicate indices (XLA's duplicate-index scatter is
   specified per element, NOT per row — a whole-row CAS via
   duplicate scatter could tear). This replaces the previous
   per-round sort-based election — 32 extra full-batch lexsorts per
   insert call — with three cheap scatters and two gathers per round.
3. Lanes that exhaust ``max_probes`` are reported in ``overflowed``;
   the aggregator sends them down the exact host lane (the same
   reject-to-host contract the reference uses for unparseable entries,
   /root/reference/cmd/ct-fetch/ct-fetch.go:206-225).

Alongside each key a ``meta`` word (packed issuer index + expiry hour
offset, :mod:`ct_mapreduce_tpu.core.packing`) is stored so a drain can
reconstruct exact per-(issuer, expDate) serial counts without a second
device pass.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class TableState(NamedTuple):
    """Dedup-set state living in HBM (donated through insert steps)."""

    keys: jax.Array  # uint32[capacity, 4]; all-zero row = empty
    meta: jax.Array  # uint32[capacity]; packed (issuer_idx, exp_hour_offset)
    count: jax.Array  # int32[]; occupied slots


def make_table(capacity: int) -> TableState:
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    return TableState(
        keys=jnp.zeros((capacity, 4), dtype=jnp.uint32),
        meta=jnp.zeros((capacity,), dtype=jnp.uint32),
        count=jnp.zeros((), dtype=jnp.int32),
    )


def _home_slot(keys: jax.Array, capacity: int) -> jax.Array:
    """Initial probe slot from the fingerprint's first two words."""
    h = keys[:, 0] ^ (keys[:, 1] * np.uint32(0x9E3779B9))
    return (h & np.uint32(capacity - 1)).astype(jnp.int32)


def _desentinel(keys: jax.Array) -> jax.Array:
    """Remap the (astronomically unlikely) all-zero fingerprint."""
    is_zero = jnp.all(keys == 0, axis=-1, keepdims=True)
    bump = jnp.concatenate(
        [jnp.zeros(keys.shape[:-1] + (3,), jnp.uint32),
         jnp.ones(keys.shape[:-1] + (1,), jnp.uint32)], axis=-1)
    return jnp.where(is_zero, bump, keys)


@functools.partial(jax.jit, static_argnames=("max_probes",), donate_argnums=(0,))
def insert(
    state: TableState,
    keys: jax.Array,
    meta: jax.Array,
    valid: jax.Array,
    max_probes: int = 32,
):
    """Batch insert-if-absent.

    Args:
      state: the table (donated; updated in place in HBM).
      keys: uint32[B, 4] fingerprints.
      meta: uint32[B] per-lane metadata scattered on successful insert.
      valid: bool[B]; padding lanes are ignored entirely.
      max_probes: probe rounds before declaring overflow.

    Returns:
      (new_state, was_unknown bool[B], overflowed bool[B]).
    """
    capacity = state.keys.shape[0]
    b = keys.shape[0]
    keys = _desentinel(keys.astype(jnp.uint32))

    # --- 1. within-batch first-occurrence detection ---------------------
    # lexsort: last key is primary. Invalid lanes sort with key 0 but are
    # masked out of representative status below.
    order = jnp.lexsort((keys[:, 3], keys[:, 2], keys[:, 1], keys[:, 0]))
    sk = keys[order]
    same_as_prev = jnp.concatenate(
        [jnp.zeros((1,), bool), jnp.all(sk[1:] == sk[:-1], axis=-1)]
    )
    sorted_valid = valid[order]
    # First *valid* lane of each equal-key run is the representative.
    # (Invalid lanes never represent; a run of [invalid, valid] with equal
    # keys must still elect the valid one, so walk with a scan max.)
    run_id = jnp.cumsum(~same_as_prev)  # 1-based run index per sorted lane
    # representative = first valid lane in its run
    first_valid_pos = jnp.full((b + 1,), b, dtype=jnp.int32)
    pos = jnp.arange(b, dtype=jnp.int32)
    first_valid_pos = first_valid_pos.at[run_id].min(
        jnp.where(sorted_valid, pos, b)
    )
    sorted_rep = sorted_valid & (pos == first_valid_pos[run_id])
    rep = jnp.zeros((b,), bool).at[order].set(sorted_rep)

    # --- 2. probe rounds ------------------------------------------------
    home = _home_slot(keys, capacity)

    lane = jnp.arange(b, dtype=jnp.int32)
    no_lane = jnp.int32(2**31 - 1)

    def cond(carry):
        r, _tk, _tm, _claim, pending, _found, _inserted = carry
        return (r < max_probes) & jnp.any(pending)

    def round_body(carry):
        r, table_keys, table_meta, claim, pending, found, inserted = carry
        # triangular probing: offset r(r+1)/2 cycles a power-of-two table
        slot = (home + (r * (r + 1)) // 2) & (capacity - 1)
        cur = table_keys[slot]  # [B, 4]
        match = jnp.all(cur == keys, axis=-1) & pending
        empty = jnp.all(cur == 0, axis=-1) & pending
        # Deterministic election: scatter-min lane ids at contested
        # empty slots (min commutes ⇒ duplicate indices are safe),
        # read back; the surviving lane id is the winner.
        cslot = jnp.where(empty, slot, capacity)  # OOB rows are dropped
        claim = claim.at[cslot].min(lane, mode="drop")
        winner = empty & (claim[slot] == lane)
        # Winners hold unique slots: key/meta scatters see no duplicates.
        wslot = jnp.where(winner, slot, capacity)
        table_keys = table_keys.at[wslot].set(keys, mode="drop")
        table_meta = table_meta.at[wslot].set(meta, mode="drop")
        # Reset only the touched claim slots for the next round.
        claim = claim.at[cslot].set(no_lane, mode="drop")
        found = found | match
        inserted = inserted | winner
        pending = pending & ~match & ~winner
        return r + 1, table_keys, table_meta, claim, pending, found, inserted

    pending0 = rep
    zeros = jnp.zeros((b,), bool)
    # Fresh capacity-sized claim scratch per call: a single ~4B/slot
    # broadcast fill (≈0.3 ms at 2^26 on v5e HBM, against a multi-ms
    # step) buys an election that needs no persistent state — keeping
    # TableState exactly (keys, meta, count) for checkpoints and the
    # sharded per-shard reconstruction. Revisit only if profiles show
    # the fill on the flame graph.
    claim0 = jnp.full((capacity,), no_lane, dtype=jnp.int32)
    _, table_keys, table_meta, _, pending, found, inserted = jax.lax.while_loop(
        cond, round_body,
        (jnp.int32(0), state.keys, state.meta, claim0, pending0, zeros, zeros),
    )

    was_unknown = inserted  # representatives that claimed a slot
    overflowed = pending  # representatives that never found a home
    new_count = state.count + jnp.sum(inserted, dtype=jnp.int32)
    return TableState(table_keys, table_meta, new_count), was_unknown, overflowed


@functools.partial(jax.jit, static_argnames=("max_probes",))
def contains(state: TableState, keys: jax.Array, max_probes: int = 32) -> jax.Array:
    """Batch membership query (no mutation): bool[B]."""
    capacity = state.keys.shape[0]
    keys = _desentinel(keys.astype(jnp.uint32))
    home = _home_slot(keys, capacity)

    def round_body(r, carry):
        found, open_ = carry
        slot = (home + (r * (r + 1)) // 2) & (capacity - 1)
        cur = state.keys[slot]
        match = jnp.all(cur == keys, axis=-1)
        empty = jnp.all(cur == 0, axis=-1)
        found = found | (match & open_)
        open_ = open_ & ~match & ~empty
        return found, open_

    b = keys.shape[0]
    found, _ = jax.lax.fori_loop(
        0, max_probes, round_body, (jnp.zeros((b,), bool), jnp.ones((b,), bool))
    )
    return found


def occupied(state: TableState) -> jax.Array:
    """bool[capacity] occupancy mask."""
    return jnp.any(state.keys != 0, axis=-1)


def drain_np(state: TableState) -> tuple[np.ndarray, np.ndarray]:
    """Pull (keys, meta) of occupied slots to host as NumPy arrays."""
    keys = np.asarray(state.keys)
    meta = np.asarray(state.meta)
    occ = keys.any(axis=-1)
    return keys[occ], meta[occ]
