"""Batched wide modular arithmetic on int32-limb lanes.

The signature-verification lane (ops/ecdsa.py) needs field arithmetic
over the P-256/P-384 primes and group orders, vectorized over a batch
axis the same way the SHA-256 kernel vectorizes lanes (ops/sha256.py):
every lane is an independent big integer, all uint32 lane arithmetic,
no cross-lane traffic — the shape the FPGA ECDSA engine (arxiv
2112.02229) and zkSpeed's big-integer datapath (arxiv 2504.06211)
exploit with wide limb lanes.

Representation: an n·16-bit value is ``uint32[..., n]`` — 16-bit
limbs, little-endian (n = 16 for the 256-bit curves, 24 for P-384;
the limb count is carried by the array shape and the :class:`Mod`
constants, so every function below is width-generic). 16-bit limbs
are the widest radix whose products and carry chains close over
uint32 without 64-bit temporaries (accelerator int ops are 32-bit):
a limb product is < 2^32, and the column accumulators below stay
< 2^24 even at 24 limbs.

Multiplication is Montgomery (REDC) with lazy column accumulation:
the schoolbook product accumulates split lo/hi half-products into
2n+1 columns (each column sums ≤ 2n+2 values < 2^16 — no overflow),
then the reduction walks the n low limbs in a ``fori_loop``,
deferring the m·N half-products into the same lazy columns, with one
carry normalization at the end.

Graph-size discipline: the ECDSA kernel runs ~20 of these per
double-and-add step inside a 256-iteration ``fori_loop``, so the
traced cost of ONE multiply bounds XLA compile time for the whole
verifier. Everything sequential over limbs is therefore a
``lax.scan``/``fori_loop`` (carry chains, borrow chains, REDC — one
traced iteration each) and the schoolbook columns are pad-and-add
(flat, fusible) rather than scatter updates; a fully unrolled
formulation compiled ~200 s on CPU, this one ~seconds.

Moduli are host-side constants (:class:`Mod`); the four instances the
verifier uses (P-256/P-384 field and order) are built at import. All
functions are shape-polymorphic over leading batch dims and jit-safe.

Round 17 adds :func:`batch_inv_mont` — Montgomery batch inversion
across the batch dimension (prefix-product scan → ONE Fermat
inversion → suffix unwind), so a batch pays one inversion where the
per-lane Fermat ladder paid ``16·n`` squarings+multiplies per lane —
and :func:`window_digit` for the windowed-precompute ladders.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

NLIMB = 16  # 16 x 16-bit limbs = 256 bits (the P-256 width)
NLIMB384 = 24  # 24 x 16-bit limbs = 384 bits (the P-384 width)
RADIX = 16
MASK = np.uint32(0xFFFF)


def limbs_from_int(v: int, nlimb: int = NLIMB) -> np.ndarray:
    """Python int → uint32[nlimb] little-endian 16-bit limbs."""
    return np.array(
        [(v >> (RADIX * k)) & 0xFFFF for k in range(nlimb)], np.uint32
    )


def int_from_limbs(a: np.ndarray) -> int:
    """uint32[..., n] limbs → python int (host-side, tests/debug)."""
    a = np.asarray(a)
    return sum(int(a[..., k]) << (RADIX * k)
               for k in range(a.shape[-1]))


@dataclass(frozen=True)
class Mod:
    """One modulus' Montgomery constants (host numpy, baked at trace)."""

    n: np.ndarray  # uint32[nlimb] — the modulus
    n0p: np.uint32  # -n^-1 mod 2^16 (REDC quotient multiplier)
    r2: np.ndarray  # uint32[nlimb] — R^2 mod n (R = 2^(16·nlimb))
    one: np.ndarray  # uint32[nlimb] — plain 1 (from-Montgomery mult)
    one_m: np.ndarray  # uint32[nlimb] — R mod n (Montgomery 1)
    exp_inv_bits: np.ndarray  # uint32[16·nlimb] — bits of n-2, MSB
    # first (Fermat inversion exponent; n must be prime)

    @property
    def nlimb(self) -> int:
        return int(self.n.shape[0])

    @classmethod
    def make(cls, n_int: int, nlimb: int = NLIMB) -> "Mod":
        bits_total = RADIX * nlimb
        r = 1 << bits_total
        n0p = (-pow(n_int, -1, 1 << RADIX)) % (1 << RADIX)
        e = n_int - 2
        bits = np.array(
            [(e >> (bits_total - 1 - i)) & 1 for i in range(bits_total)],
            np.uint32,
        )
        return cls(
            n=limbs_from_int(n_int, nlimb),
            n0p=np.uint32(n0p),
            r2=limbs_from_int(r * r % n_int, nlimb),
            one=limbs_from_int(1, nlimb),
            one_m=limbs_from_int(r % n_int, nlimb),
            exp_inv_bits=bits,
        )


# The field/order moduli of the P-256 and P-384 verifiers.
P256_P_INT = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
P256_N_INT = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
P384_P_INT = int(
    "fffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff"
    "effffffff0000000000000000ffffffff", 16)
P384_N_INT = int(
    "ffffffffffffffffffffffffffffffffffffffffffffffffc7634d81f4372dd"
    "f581a0db248b0a77aecec196accc52973", 16)

P256_P = Mod.make(P256_P_INT)
P256_N = Mod.make(P256_N_INT)
P384_P = Mod.make(P384_P_INT, NLIMB384)
P384_N = Mod.make(P384_N_INT, NLIMB384)


def bytes_to_limbs(b):
    """uint8[..., 2n] big-endian bytes → uint32[..., n] limbs."""
    b = b.astype(jnp.uint32)
    nl = b.shape[-1] // 2
    return jnp.stack(
        [(b[..., 2 * nl - 2 - 2 * k] << 8) | b[..., 2 * nl - 1 - 2 * k]
         for k in range(nl)],
        axis=-1,
    )


def is_zero(a) -> jnp.ndarray:
    """bool[...]: a == 0."""
    return jnp.all(a == 0, axis=-1)


def eq(a, b) -> jnp.ndarray:
    """bool[...]: a == b limbwise."""
    return jnp.all(a == b, axis=-1)


def _carry_norm(a, n_out: int):
    """Propagate carries over ``a`` (uint32[..., k], limbs < 2^31) into
    ``n_out`` normalized 16-bit limbs plus the final carry word. One
    traced iteration (lax.scan over the limb axis)."""
    k = a.shape[-1]
    if k < n_out:
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, n_out - k)])
    xs = jnp.moveaxis(a[..., :n_out], -1, 0)

    def step(c, x):
        s = x + c
        return s >> RADIX, s & MASK

    c, out = jax.lax.scan(step, jnp.zeros(a.shape[:-1], jnp.uint32), xs)
    # Residual columns past n_out sit at the carry's own position and
    # fold into it (mont_mul's column 2·NLIMB is always zero, but the
    # math stays total for any caller).
    for j in range(n_out, k):
        c = c + a[..., j]
    return jnp.moveaxis(out, 0, -1), c


def sub_raw(a, b):
    """(a - b) mod 2^256 with the final borrow: (limbs, borrow[...])."""
    xs = (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0))
    base = jnp.uint32(1 << RADIX)

    def step(borrow, ab):
        ak, bk = ab
        x = ak + base - bk - borrow
        return jnp.uint32(1) - (x >> RADIX), x & MASK

    borrow, out = jax.lax.scan(
        step, jnp.zeros(a.shape[:-1], jnp.uint32), xs
    )
    return jnp.moveaxis(out, 0, -1), borrow


def geq(a, b) -> jnp.ndarray:
    """bool[...]: a >= b."""
    _, borrow = sub_raw(a, b)
    return borrow == 0


def _cond_sub_n(a, carry, mod: Mod):
    """a (< 2n, possibly with a 2^256 carry bit) → canonical a mod n."""
    n = jnp.broadcast_to(jnp.asarray(mod.n), a.shape)
    sub, borrow = sub_raw(a, n)
    take = (carry != 0) | (borrow == 0)
    return jnp.where(take[..., None], sub, a)


def add_mod(a, b, mod: Mod):
    """(a + b) mod n for canonical a, b < n."""
    s, c = _carry_norm(a + b, a.shape[-1])
    return _cond_sub_n(s, c, mod)


def sub_mod(a, b, mod: Mod):
    """(a - b) mod n for canonical a, b < n."""
    d, borrow = sub_raw(a, b)
    dn, _ = _carry_norm(d + jnp.asarray(mod.n), a.shape[-1])
    return jnp.where((borrow != 0)[..., None], dn, d)


def mod_reduce_once(a, mod: Mod):
    """a mod n for a < 2n (one conditional subtract) — enough for a
    256-bit SHA digest against the P-256 order, and for x_R mod n
    (P-256: p < 2n)."""
    zero = jnp.zeros(a.shape[:-1], jnp.uint32)
    return _cond_sub_n(a, zero, mod)


def mont_mul(a, b, mod: Mod):
    """Montgomery product a·b·R^-1 mod n (R = 2^(16·nl)), canonical.

    Preconditions: b < n; a < R (any nl-limb value — the to-Montgomery
    conversion feeds raw digests through here against r2 < n).

    Bound sketch (nl ≤ 24): schoolbook columns take ≤ nl lo + nl hi
    terms (< 2^22); REDC adds ≤ 1 lo + 1 hi per outer step (< 2^23
    total); the running REDC carry stays < 2^8 — everything closes
    over uint32. The REDC output is < 2n, canonicalized by one
    conditional subtract.
    """
    nl = int(mod.n.shape[0])  # static limb count from the modulus
    shape = a.shape[:-1]
    pads = [(0, 0)] * len(shape)
    # Schoolbook columns: outer product split into half-words, rows
    # shifted into place with static pads (flat, fusible — no scatter).
    prod = a[..., :, None] * b[..., None, :]  # [..., nl, nl]
    lo = prod & MASK
    hi = prod >> RADIX
    t = jnp.zeros(shape + (2 * nl + 1,), jnp.uint32)
    for i in range(nl):
        t = t + jnp.pad(lo[..., i, :], pads + [(i, nl + 1 - i)])
        t = t + jnp.pad(hi[..., i, :], pads + [(i + 1, nl - i)])

    # REDC: finalize the nl low limbs in order; position i's true low
    # 16 bits are known once the carry from position i-1 arrives, the
    # m·N half-products for higher positions stay lazy in the columns.
    n = jnp.asarray(mod.n)
    axis = t.ndim - 1

    def body(i, carry_t):
        carry, t = carry_t
        ti = jax.lax.dynamic_index_in_dim(t, i, axis, keepdims=False)
        ti = ti + carry
        m = (ti * mod.n0p) & MASK
        p = m[..., None] * n  # [..., nl]
        x = ti + (p[..., 0] & MASK)  # ≡ 0 mod 2^16 by choice of m
        # Deferred adds for positions i+1..i+nl: element j of the
        # window gains lo(p[j+1]) (j < nl-1) and hi(p[j]).
        upd = jnp.pad(p[..., 1:] & MASK, pads + [(0, 1)]) + (p >> RADIX)
        win = jax.lax.dynamic_slice_in_dim(t, i + 1, nl, axis)
        t = jax.lax.dynamic_update_slice_in_dim(
            t, win + upd, i + 1, axis
        )
        return x >> RADIX, t

    carry, t = jax.lax.fori_loop(
        0, nl, body, (jnp.zeros(shape, jnp.uint32), t)
    )
    res, c = _carry_norm(t[..., nl:].at[..., 0].add(carry), nl)
    return _cond_sub_n(res, c, mod)


def to_mont(a, mod: Mod):
    """a → a·R mod n (a any 16-limb value < R)."""
    return mont_mul(a, jnp.asarray(mod.r2), mod)


def from_mont(a_m, mod: Mod):
    """a·R → a mod n."""
    return mont_mul(a_m, jnp.asarray(mod.one), mod)


def mont_sqr(a, mod: Mod):
    return mont_mul(a, a, mod)


def mont_inv(a_m, mod: Mod):
    """Montgomery-domain inverse via Fermat: a^(n-2) (n prime).

    Square-and-multiply over the fixed exponent bits with a
    ``fori_loop`` (one squaring + one masked multiply per iteration),
    so the traced graph is one step, not 16·nl. a_m == 0 → 0 (the
    ECDSA caller masks those lanes out via its own validity flags)."""
    bits = jnp.asarray(mod.exp_inv_bits)
    acc0 = jnp.broadcast_to(jnp.asarray(mod.one_m), a_m.shape)

    def body(i, acc):
        acc = mont_sqr(acc, mod)
        mul = mont_mul(acc, a_m, mod)
        return jnp.where((bits[i] != 0)[..., None], mul, acc)

    return jax.lax.fori_loop(0, int(bits.shape[0]), body, acc0)


def batch_inv_mont(a_m, mod: Mod):
    """Montgomery batch inversion across the batch dimension.

    ``a_m``: uint32[B, nl] canonical Montgomery-domain values. Returns
    the per-lane Montgomery-domain inverses (bit-identical to
    :func:`mont_inv` lane by lane — the inverse is unique) for ONE
    Fermat inversion per batch: an exclusive prefix-product
    ``lax.scan`` down the batch, one Fermat inversion of the total,
    and a reverse-scan suffix unwind. Each scan step is a single-lane
    mont_mul, so the whole thing costs ~3B narrow multiplies instead
    of the ladder's 2·16·nl batch-wide ones.

    Completeness: zero lanes are masked THROUGH the product — a zero
    denominator is replaced by 1 before the prefix product and its
    output is forced to 0 afterwards, so an adversarial lane (s = 0,
    point-at-infinity Z = 0) can never poison a neighboring lane's
    inverse. Matches mont_inv's 0 → 0 convention.
    """
    one_m = jnp.asarray(mod.one_m)
    zero_lane = is_zero(a_m)  # [B]
    safe = jnp.where(zero_lane[..., None], one_m[None, :], a_m)

    def fwd(c, x):
        return mont_mul(c, x, mod), c  # exclusive prefix product

    total, pre = jax.lax.scan(fwd, one_m, safe)
    tinv = mont_inv(total, mod)

    def bwd(c, x_pre):
        x, p = x_pre
        return mont_mul(c, x, mod), mont_mul(c, p, mod)

    _, inv = jax.lax.scan(bwd, tinv, (safe, pre), reverse=True)
    return jnp.where(zero_lane[..., None], jnp.zeros_like(a_m), inv)


def bit_at(a, k):
    """Bit ``k`` (traced scalar) of a limb value: uint32[...] ∈ {0,1}."""
    limb = jax.lax.dynamic_index_in_dim(
        a, k >> 4, axis=a.ndim - 1, keepdims=False
    )
    return (limb >> (k & 15).astype(jnp.uint32)) & 1


def window_digit(a, j, w: int):
    """Window ``j``'s w-bit digit of a limb value: uint32[...] in
    [0, 2^w). ``j`` is a traced scalar (the ladder's loop index); ``w``
    is static and must divide 16 so a digit never straddles limbs."""
    bit = j * w
    limb = jax.lax.dynamic_index_in_dim(
        a, bit >> 4, axis=a.ndim - 1, keepdims=False
    )
    return (limb >> (bit & 15).astype(jnp.uint32)) \
        & jnp.uint32((1 << w) - 1)
