"""Batched 256-bit modular arithmetic on int32-limb lanes.

The signature-verification lane (ops/ecdsa.py) needs field arithmetic
over the P-256 prime and group order, vectorized over a batch axis the
same way the SHA-256 kernel vectorizes lanes (ops/sha256.py): every
lane is an independent big integer, all uint32 lane arithmetic, no
cross-lane traffic — the shape the FPGA ECDSA engine (arxiv
2112.02229) and zkSpeed's big-integer datapath (arxiv 2504.06211)
exploit with wide limb lanes.

Representation: a 256-bit value is ``uint32[..., 16]`` — sixteen
16-bit limbs, little-endian. 16-bit limbs are the widest radix whose
products and carry chains close over uint32 without 64-bit temporaries
(accelerator int ops are 32-bit): a limb product is < 2^32, and the
column accumulators below stay < 2^23.

Multiplication is Montgomery (REDC) with lazy column accumulation:
the schoolbook product accumulates split lo/hi half-products into 33
columns (each column sums ≤ 64 values < 2^16 — no overflow), then the
reduction walks the 16 low limbs in a ``fori_loop``, deferring the
m·N half-products into the same lazy columns, with one carry
normalization at the end.

Graph-size discipline: the ECDSA kernel runs ~20 of these per
double-and-add step inside a 256-iteration ``fori_loop``, so the
traced cost of ONE multiply bounds XLA compile time for the whole
verifier. Everything sequential over limbs is therefore a
``lax.scan``/``fori_loop`` (carry chains, borrow chains, REDC — one
traced iteration each) and the schoolbook columns are pad-and-add
(flat, fusible) rather than scatter updates; a fully unrolled
formulation compiled ~200 s on CPU, this one ~seconds.

Moduli are host-side constants (:class:`Mod`); the two instances the
verifier uses (P-256 field ``P256_P`` and order ``P256_N``) are built
at import. All functions are shape-polymorphic over leading batch
dims and jit-safe.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

NLIMB = 16  # 16 x 16-bit limbs = 256 bits
RADIX = 16
MASK = np.uint32(0xFFFF)


def limbs_from_int(v: int) -> np.ndarray:
    """Python int → uint32[16] little-endian 16-bit limbs."""
    return np.array(
        [(v >> (RADIX * k)) & 0xFFFF for k in range(NLIMB)], np.uint32
    )


def int_from_limbs(a: np.ndarray) -> int:
    """uint32[..., 16] limbs → python int (host-side, tests/debug)."""
    a = np.asarray(a)
    return sum(int(a[..., k]) << (RADIX * k) for k in range(NLIMB))


@dataclass(frozen=True)
class Mod:
    """One modulus' Montgomery constants (host numpy, baked at trace)."""

    n: np.ndarray  # uint32[16] — the modulus
    n0p: np.uint32  # -n^-1 mod 2^16 (REDC quotient multiplier)
    r2: np.ndarray  # uint32[16] — R^2 mod n (R = 2^256): to-Montgomery
    one: np.ndarray  # uint32[16] — plain 1 (from-Montgomery multiplier)
    one_m: np.ndarray  # uint32[16] — R mod n (Montgomery 1)
    exp_inv_bits: np.ndarray  # uint32[256] — bits of n-2, MSB first
    # (Fermat inversion exponent; n must be prime)

    @classmethod
    def make(cls, n_int: int) -> "Mod":
        r = 1 << 256
        n0p = (-pow(n_int, -1, 1 << RADIX)) % (1 << RADIX)
        e = n_int - 2
        bits = np.array(
            [(e >> (255 - i)) & 1 for i in range(256)], np.uint32
        )
        return cls(
            n=limbs_from_int(n_int),
            n0p=np.uint32(n0p),
            r2=limbs_from_int(r * r % n_int),
            one=limbs_from_int(1),
            one_m=limbs_from_int(r % n_int),
            exp_inv_bits=bits,
        )


# The two moduli of the P-256 verifier.
P256_P_INT = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
P256_N_INT = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551

P256_P = Mod.make(P256_P_INT)
P256_N = Mod.make(P256_N_INT)


def bytes_to_limbs(b):
    """uint8[..., 32] big-endian bytes → uint32[..., 16] limbs."""
    b = b.astype(jnp.uint32)
    return jnp.stack(
        [(b[..., 30 - 2 * k] << 8) | b[..., 31 - 2 * k]
         for k in range(NLIMB)],
        axis=-1,
    )


def is_zero(a) -> jnp.ndarray:
    """bool[...]: a == 0."""
    return jnp.all(a == 0, axis=-1)


def eq(a, b) -> jnp.ndarray:
    """bool[...]: a == b limbwise."""
    return jnp.all(a == b, axis=-1)


def _carry_norm(a, n_out: int):
    """Propagate carries over ``a`` (uint32[..., k], limbs < 2^31) into
    ``n_out`` normalized 16-bit limbs plus the final carry word. One
    traced iteration (lax.scan over the limb axis)."""
    k = a.shape[-1]
    if k < n_out:
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, n_out - k)])
    xs = jnp.moveaxis(a[..., :n_out], -1, 0)

    def step(c, x):
        s = x + c
        return s >> RADIX, s & MASK

    c, out = jax.lax.scan(step, jnp.zeros(a.shape[:-1], jnp.uint32), xs)
    # Residual columns past n_out sit at the carry's own position and
    # fold into it (mont_mul's column 2·NLIMB is always zero, but the
    # math stays total for any caller).
    for j in range(n_out, k):
        c = c + a[..., j]
    return jnp.moveaxis(out, 0, -1), c


def sub_raw(a, b):
    """(a - b) mod 2^256 with the final borrow: (limbs, borrow[...])."""
    xs = (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0))
    base = jnp.uint32(1 << RADIX)

    def step(borrow, ab):
        ak, bk = ab
        x = ak + base - bk - borrow
        return jnp.uint32(1) - (x >> RADIX), x & MASK

    borrow, out = jax.lax.scan(
        step, jnp.zeros(a.shape[:-1], jnp.uint32), xs
    )
    return jnp.moveaxis(out, 0, -1), borrow


def geq(a, b) -> jnp.ndarray:
    """bool[...]: a >= b."""
    _, borrow = sub_raw(a, b)
    return borrow == 0


def _cond_sub_n(a, carry, mod: Mod):
    """a (< 2n, possibly with a 2^256 carry bit) → canonical a mod n."""
    n = jnp.broadcast_to(jnp.asarray(mod.n), a.shape)
    sub, borrow = sub_raw(a, n)
    take = (carry != 0) | (borrow == 0)
    return jnp.where(take[..., None], sub, a)


def add_mod(a, b, mod: Mod):
    """(a + b) mod n for canonical a, b < n."""
    s, c = _carry_norm(a + b, NLIMB)
    return _cond_sub_n(s, c, mod)


def sub_mod(a, b, mod: Mod):
    """(a - b) mod n for canonical a, b < n."""
    d, borrow = sub_raw(a, b)
    dn, _ = _carry_norm(d + jnp.asarray(mod.n), NLIMB)
    return jnp.where((borrow != 0)[..., None], dn, d)


def mod_reduce_once(a, mod: Mod):
    """a mod n for a < 2n (one conditional subtract) — enough for a
    256-bit SHA digest against the P-256 order, and for x_R mod n
    (P-256: p < 2n)."""
    zero = jnp.zeros(a.shape[:-1], jnp.uint32)
    return _cond_sub_n(a, zero, mod)


def mont_mul(a, b, mod: Mod):
    """Montgomery product a·b·R^-1 mod n (R = 2^256), canonical result.

    Preconditions: b < n; a < R (any 16-limb value — the to-Montgomery
    conversion feeds raw 256-bit digests through here against r2 < n).

    Bound sketch: schoolbook columns take ≤ 16 lo + 16 hi terms
    (< 2^21); REDC adds ≤ 1 lo + 1 hi per outer step (< 2^22 total);
    the running REDC carry stays < 2^7 — everything closes over
    uint32. The REDC output is < 2n, canonicalized by one conditional
    subtract.
    """
    shape = a.shape[:-1]
    pads = [(0, 0)] * len(shape)
    # Schoolbook columns: outer product split into half-words, rows
    # shifted into place with static pads (flat, fusible — no scatter).
    prod = a[..., :, None] * b[..., None, :]  # [..., 16, 16]
    lo = prod & MASK
    hi = prod >> RADIX
    t = jnp.zeros(shape + (2 * NLIMB + 1,), jnp.uint32)
    for i in range(NLIMB):
        t = t + jnp.pad(lo[..., i, :], pads + [(i, NLIMB + 1 - i)])
        t = t + jnp.pad(hi[..., i, :], pads + [(i + 1, NLIMB - i)])

    # REDC: finalize the 16 low limbs in order; position i's true low
    # 16 bits are known once the carry from position i-1 arrives, the
    # m·N half-products for higher positions stay lazy in the columns.
    n = jnp.asarray(mod.n)
    axis = t.ndim - 1

    def body(i, carry_t):
        carry, t = carry_t
        ti = jax.lax.dynamic_index_in_dim(t, i, axis, keepdims=False)
        ti = ti + carry
        m = (ti * mod.n0p) & MASK
        p = m[..., None] * n  # [..., 16]
        x = ti + (p[..., 0] & MASK)  # ≡ 0 mod 2^16 by choice of m
        # Deferred adds for positions i+1..i+16: element j of the
        # window gains lo(p[j+1]) (j < 15) and hi(p[j]).
        upd = jnp.pad(p[..., 1:] & MASK, pads + [(0, 1)]) + (p >> RADIX)
        win = jax.lax.dynamic_slice_in_dim(t, i + 1, NLIMB, axis)
        t = jax.lax.dynamic_update_slice_in_dim(
            t, win + upd, i + 1, axis
        )
        return x >> RADIX, t

    carry, t = jax.lax.fori_loop(
        0, NLIMB, body, (jnp.zeros(shape, jnp.uint32), t)
    )
    res, c = _carry_norm(t[..., NLIMB:].at[..., 0].add(carry), NLIMB)
    return _cond_sub_n(res, c, mod)


def to_mont(a, mod: Mod):
    """a → a·R mod n (a any 16-limb value < R)."""
    return mont_mul(a, jnp.asarray(mod.r2), mod)


def from_mont(a_m, mod: Mod):
    """a·R → a mod n."""
    return mont_mul(a_m, jnp.asarray(mod.one), mod)


def mont_sqr(a, mod: Mod):
    return mont_mul(a, a, mod)


def mont_inv(a_m, mod: Mod):
    """Montgomery-domain inverse via Fermat: a^(n-2) (n prime).

    Square-and-multiply over the fixed exponent bits with a
    ``fori_loop`` (one squaring + one masked multiply per iteration),
    so the traced graph is one step, not 256. a_m == 0 → 0 (the ECDSA
    caller masks those lanes out via its own validity flags)."""
    bits = jnp.asarray(mod.exp_inv_bits)
    acc0 = jnp.broadcast_to(jnp.asarray(mod.one_m), a_m.shape)

    def body(i, acc):
        acc = mont_sqr(acc, mod)
        mul = mont_mul(acc, a_m, mod)
        return jnp.where((bits[i] != 0)[..., None], mul, acc)

    return jax.lax.fori_loop(0, 256, body, acc0)


def bit_at(a, k):
    """Bit ``k`` (traced scalar) of a limb value: uint32[...] ∈ {0,1}."""
    limb = jax.lax.dynamic_index_in_dim(
        a, k >> 4, axis=a.ndim - 1, keepdims=False
    )
    return (limb >> (k & 15).astype(jnp.uint32)) & 1
