"""Batched SHA-256 as a pure-JAX op.

The reference computes SHA-256 on the CPU per certificate (issuer
identity = SHA-256(SPKI), /root/reference/storage/types.go:129-141).
Here the digest runs on-device, vectorized over the batch axis: every
lane is an independent message, all uint32 lane arithmetic, so XLA maps
it onto the VPU with no cross-lane traffic.

Two entry points:

- ``sha256_blocks(blocks)``: the general compression over pre-padded
  message blocks ``uint32[B, N, 16]`` → ``uint32[B, 8]``.
- ``sha256_fingerprint64(words)``: the dedup-key path — a single
  64-byte block per lane (enough for expHour ‖ issuerDigest ‖ serial,
  which is ≤ 57 bytes) → the low 128 bits of the digest as
  ``uint32[B, 4]``. Padding must already be applied by the packer.

Host-side packers live in :mod:`ct_mapreduce_tpu.core.packing`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# FIPS 180-4 round constants.
_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: jax.Array, block: jax.Array) -> jax.Array:
    """One SHA-256 compression. state: uint32[..., 8], block: uint32[..., 16].

    Implemented as a ``lax.scan`` over the 64 rounds with the classic
    rolling 16-word message schedule, so the traced graph is one round
    — full unrolling made XLA compile times explode on the SPMD paths
    while buying nothing at runtime (the body is pure VPU work).
    """
    w = jnp.moveaxis(block, -1, 0)  # [16, ...]
    av = jnp.moveaxis(state, -1, 0)  # [8, ...]
    kt_all = jnp.asarray(_K)

    def round_body(carry, t):
        av, w = carry
        a, b, c, d, e, f, g, h = (av[i] for i in range(8))
        i0 = t % 16
        wt = jax.lax.dynamic_index_in_dim(w, i0, 0, keepdims=False)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt_all[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        av = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g])
        # Rolling schedule: prepare W[t+16] in place of W[t].
        w1 = jax.lax.dynamic_index_in_dim(w, (t + 1) % 16, 0, keepdims=False)
        w9 = jax.lax.dynamic_index_in_dim(w, (t + 9) % 16, 0, keepdims=False)
        w14 = jax.lax.dynamic_index_in_dim(w, (t + 14) % 16, 0, keepdims=False)
        sg0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> np.uint32(3))
        sg1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> np.uint32(10))
        w = jax.lax.dynamic_update_index_in_dim(w, wt + sg0 + w9 + sg1, i0, 0)
        return (av, w), None

    (av, _), _ = jax.lax.scan(
        round_body, (av, w), jnp.arange(64, dtype=jnp.int32)
    )
    return state + jnp.moveaxis(av, 0, -1)


@functools.partial(jax.jit, static_argnames=())
def sha256_blocks(blocks: jax.Array) -> jax.Array:
    """Digest pre-padded messages.

    blocks: uint32[B, N, 16] big-endian message words, padding (0x80,
    zeros, 64-bit bit length) already applied. Returns uint32[B, 8].
    """
    blocks = blocks.astype(jnp.uint32)
    b = blocks.shape[0]
    state = jnp.broadcast_to(jnp.asarray(_H0), (b, 8))

    def step(st, blk):
        return _compress(st, blk), None

    state, _ = jax.lax.scan(step, state, jnp.swapaxes(blocks, 0, 1))
    return state


@jax.jit
def sha256_var_blocks(blocks: jax.Array, n_blocks: jax.Array) -> jax.Array:
    """Digest messages with per-lane block counts.

    blocks: uint32[B, N, 16] where each lane's message occupies its
    first ``n_blocks[lane]`` blocks (padding applied) and the remainder
    is ignored. n_blocks: int32[B]. Returns uint32[B, 8].
    """
    blocks = blocks.astype(jnp.uint32)
    b, n, _ = blocks.shape
    state = jnp.broadcast_to(jnp.asarray(_H0), (b, 8))
    n_blocks = n_blocks.astype(jnp.int32)

    def step(st, xs):
        i, blk = xs
        new = _compress(st, blk)
        keep = (i < n_blocks)[:, None]
        return jnp.where(keep, new, st), None

    idx = jnp.arange(n, dtype=jnp.int32)
    state, _ = jax.lax.scan(step, state, (idx, jnp.swapaxes(blocks, 0, 1)))
    return state


@jax.jit
def sha256_single_block(block: jax.Array) -> jax.Array:
    """Digest one pre-padded 64-byte block per lane.

    block: uint32[B, 16] → uint32[B, 8]. The hot path for dedup
    fingerprints (message ≤ 55 bytes fits one block with padding).
    """
    block = block.astype(jnp.uint32)
    state = jnp.broadcast_to(jnp.asarray(_H0), block.shape[:-1] + (8,))
    return _compress(state, block)


def _pallas_enabled(batch: int) -> bool:
    """Default-ON for TPU backends (recorded win: 0.50 ms vs 1.48 ms
    per 16,384-lane fingerprint batch on v5e, bit-exact); requires a
    batch the lane tiling divides (else the XLA path serves).
    ``CTMR_PALLAS=0`` opts out."""
    import os

    if os.environ.get("CTMR_PALLAS", "1") != "1":
        return False
    try:
        if jax.default_backend() != "tpu":
            return False
    except RuntimeError:
        return False
    from ct_mapreduce_tpu.ops import pallas_sha256

    if batch == 0:
        return False  # empty shard: the XLA path handles [0, 16] fine
    tile = min(pallas_sha256.lane_tile(), batch)
    return batch % tile == 0


def sha256_fingerprint64(block: jax.Array) -> jax.Array:
    """Low 128 bits (words 4..7) of the single-block digest: uint32[B, 4].

    Truncation keeps the dedup key compact; collision probability over a
    full CT log (~2^33 entries) is ≪ 2^-60, far below the
    issuer-count-parity gate (SURVEY.md §7 hard part #2).

    Dispatches to the VMEM-resident Pallas kernel
    (:mod:`ct_mapreduce_tpu.ops.pallas_sha256`) by default on TPU
    (``CTMR_PALLAS=0`` opts out); the XLA scan otherwise.
    """
    if _pallas_enabled(int(block.shape[0])):
        from ct_mapreduce_tpu.ops import pallas_sha256

        return pallas_sha256.sha256_fingerprint64_pallas(block)
    return sha256_single_block(block)[..., 4:]


def pad_message_np(msg: bytes, total_blocks: int | None = None) -> np.ndarray:
    """Host-side FIPS padding: bytes → uint32[N, 16] big-endian words."""
    bitlen = len(msg) * 8
    data = bytearray(msg)
    data.append(0x80)
    while len(data) % 64 != 56:
        data.append(0)
    data += bitlen.to_bytes(8, "big")
    arr = np.frombuffer(bytes(data), dtype=">u4").astype(np.uint32)
    arr = arr.reshape(-1, 16)
    if total_blocks is not None:
        if arr.shape[0] > total_blocks:
            raise ValueError(f"message needs {arr.shape[0]} blocks > {total_blocks}")
        pad = np.zeros((total_blocks - arr.shape[0], 16), dtype=np.uint32)
        arr = np.concatenate([arr, pad], axis=0)
    return arr


def digest_np(state: np.ndarray) -> bytes:
    """uint32[8] state → 32-byte big-endian digest."""
    return np.asarray(state, dtype=">u4").tobytes()
