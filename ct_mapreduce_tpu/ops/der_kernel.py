"""Vectorized X.509/DER field extraction on device.

Replaces the reference's per-entry CPU ``x509.ParseCertificate``
(/root/reference/cmd/ct-fetch/ct-fetch.go:198-226) for the fields the
map stage actually consumes:

- serial content offset/length (raw bytes incl. leading zeros,
  /root/reference/storage/types.go:165-178),
- notAfter as epoch-hours (the ExpDate bucket,
  /root/reference/storage/types.go:339-346),
- BasicConstraints CA flag and CRL-distribution-points presence
  (filter + metadata triggers, /root/reference/cmd/ct-fetch/ct-fetch.go:47-50,
  /root/reference/storage/issuermetadata.go:92-138),
- first CommonName of the issuer DN (the CN-prefix filter,
  /root/reference/cmd/ct-fetch/ct-fetch.go:56-62),
- SPKI TLV offset/length (issuer identity when a lane's cert is used
  as an issuer).

Because DER fixes the field order of TBSCertificate, the walk is a
straight-line program of vectorized header reads — identical control
flow for every lane, per-lane data only in the (tag, length, position)
registers. The two variable-count regions (issuer RDNs, extensions) are
early-exiting ``while_loop``s with active-lane masks. Any structural
surprise (unsupported long-form length, window overrun, loop budget
exhausted) clears the lane's ``ok`` bit; those lanes take the host
reference lane (:mod:`ct_mapreduce_tpu.core.der`), matching the
reference's tolerate-and-skip contract
(/root/reference/cmd/ct-fetch/ct-fetch.go:206-225).

Everything is shape-static and jit/pjit-friendly; the batch axis is the
sharding axis.

Access-path design (round-3 rework): TPU gathers are the enemy — a
single per-lane ``take_along_axis`` over the [B, L] byte buffer costs
~1 ms at B=16K, and the walker needs hundreds of byte reads, which is
where the original 170 ms/batch went. This version performs **zero
gathers**: rows are packed once into big-endian uint32 words (native
uint32 — no floating point), each walk step extracts a small byte WINDOW
at its per-lane position via one-hot × shifted-slice multiply-reduce
(pure elementwise + reduction, which XLA fuses into row passes; at
production row widths the one-hot is TWO-LEVEL — select two adjacent
_BLOCK_WORDS-word blocks in one pass over the row, then window within
the superblock — cutting per-window reduce work ~nw/_BLOCK_WORDS×), and
all byte reads inside a step are one-hot selects over that ≤68-byte
window (17 words — exactly the _PAD_WORDS+1 and _BLOCK_WORDS+1
ceiling). The fixed walk merges adjacent headers into 5 shared
windows; the variable-count scans (issuer RDNs, extensions) run as
superblock loops — fetch each lane 512 bytes in one row pass, walk
TLV elements inside it at VPU speed, refetch on crossing — so a
batch pays ~one row pass per ~468 bytes of scanned region instead of
one per TLV element.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

MAX_RDNS = 12  # RDN components scanned in the issuer Name
MAX_EXTS = 24  # extensions scanned in the TBS

_PAD_WORDS = 16  # slack words so shifted slices cover every window
# (every _window call asserts n_words <= _PAD_WORDS + 1; the binding
# consumer is window 1's 17 words = 68 bytes, which must reach the
# sigAlg HEADER past a maximum-width serial: 5+5+5+2+46+5 = 68
# exactly. 17 words also sits exactly at the _BLOCK_WORDS + 1
# two-level ceiling — there is NO slack left at this size.)

_BLOCK_WORDS = 16  # two-level window: block granularity (see _window)

_SUP_BLOCKS = 8  # superblock loops: blocks fetched per scan round
# (512 bytes — covers a typical whole extension list in ONE row pass)

# Largest content span `window_bytes_rows` can serve: its window needs
# (6 + n)//4 + 1 words, bounded by min(_PAD_WORDS, _BLOCK_WORDS) + 1.
MAX_FIXED_WINDOW_BYTES = min(_PAD_WORDS, _BLOCK_WORDS) * 4 + 3 - 6  # 61


class ParsedCerts(NamedTuple):
    """Per-lane extraction results (int32 unless noted)."""

    ok: jax.Array  # bool — False ⇒ use the host reference lane
    serial_off: jax.Array
    serial_len: jax.Array
    not_after_hour: jax.Array  # hours since Unix epoch, floor-truncated
    is_ca: jax.Array  # bool
    has_crldp: jax.Array  # bool
    issuer_cn_off: jax.Array
    issuer_cn_len: jax.Array  # 0 ⇒ no CN present
    issuer_off: jax.Array  # full issuer Name TLV (host DN-cache key)
    issuer_len: jax.Array
    spki_off: jax.Array  # offset of the full SPKI TLV
    spki_len: jax.Array  # header+content length
    crldp_off: jax.Array  # CRLDP extnValue content (host CRL-cache key)
    crldp_len: jax.Array  # 0 ⇒ extension absent


class _Rows(NamedTuple):
    """Word-packed rows: big-endian uint32 words, padded for slices.

    Width is max(NW + _PAD_WORDS, ceil(NW/_BLOCK_WORDS)*_BLOCK_WORDS)
    — enough for the flat path's shifted slices AND the two-level
    path's block reshape. Build via :func:`pack_rows`, not by hand.
    """

    words: jax.Array  # uint32[B, >= NW + _PAD_WORDS] (see docstring)
    n_words: int  # NW = ceil(L / 4)


def _pack_rows(data: jax.Array) -> _Rows:
    """uint8[B, L] → :class:`_Rows` (one elementwise pass, no gathers)."""
    b, l = data.shape
    if l % 4:
        data = jnp.pad(data, ((0, 0), (0, 4 - l % 4)))
    w = (
        (data[:, 0::4].astype(jnp.uint32) << 24)
        | (data[:, 1::4].astype(jnp.uint32) << 16)
        | (data[:, 2::4].astype(jnp.uint32) << 8)
        | data[:, 3::4].astype(jnp.uint32)
    )
    nw = w.shape[1]
    # Pad so BOTH window paths are in-bounds: the flat path's shifted
    # slices need nw + _PAD_WORDS; the two-level path reshapes the
    # first ceil(nw/_BLOCK_WORDS)*_BLOCK_WORDS columns into blocks.
    blocks = -(-nw // _BLOCK_WORDS) * _BLOCK_WORDS
    return _Rows(
        jnp.pad(w, ((0, 0), (0, max(nw + _PAD_WORDS, blocks) - nw))), nw
    )


# Public names for the shared-rows interface consumed by the fused
# step (pipeline.local_lanes): pack once, share across parse / serial
# extraction / CN window.
Rows = _Rows


def pack_rows(data: jax.Array) -> _Rows:
    """Public wrapper: word-pack a uint8[B, L] batch once for the
    ``*_rows`` entry points."""
    return _pack_rows(data.astype(jnp.uint8))


def _window(rows: _Rows, p: jax.Array, n_words: int):
    """Byte window anchored at per-lane position ``p``.

    Returns ``(win int32[B, n_words*4], a int32[B])`` where window byte
    ``a + d`` is row byte ``p + d`` (``a = p & 3`` is the alignment).
    No gather anywhere: short rows use one one-hot over the word axis
    plus ``n_words`` shifted-slice multiply-reduces; production-width
    rows (nw >= 4 * _BLOCK_WORDS) take the two-level block select
    below (same result, ~nw/_BLOCK_WORDS times less reduce work).

    Caveat: positions past the packed buffer CLAMP to the final word
    (window bytes then repeat trailing row bytes, not zeros) — every
    caller masks lanes whose positions failed the `limit` checks, and
    new callers must do the same.
    """
    nw = rows.n_words
    if n_words > _PAD_WORDS + 1:
        raise ValueError(
            f"window of {n_words} words exceeds _PAD_WORDS + 1 "
            f"({_PAD_WORDS + 1}); raise _PAD_WORDS"
        )
    if n_words > _BLOCK_WORDS + 1:
        # Two-level constraint: the superblock read is sup[loc + k]
        # with loc < _BLOCK_WORDS and k < n_words, which must stay
        # inside the 2*_BLOCK_WORDS superblock.
        raise ValueError(
            f"window of {n_words} words exceeds _BLOCK_WORDS + 1 "
            f"({_BLOCK_WORDS + 1}); raise _BLOCK_WORDS too"
        )
    base = jnp.clip(p, 0, (nw - 1) * 4) >> 2  # [B]
    b = p.shape[0]
    A = _BLOCK_WORDS
    if nw >= 4 * A:
        # Two-level block select: a flat one-hot costs n_words
        # reductions over ALL nw words (the dominant walker cost at
        # production row widths). Instead reshape the row into
        # [K, A]-word blocks, one-hot-select blocks bi and bi+1 (one
        # fused pass over the row, two tiny outputs), then run the
        # shifted-slice select inside the 2A-word superblock. Exact-
        # equivalent to the flat path for every position (including
        # the clamp-to-final-word caveat): superblock word j is row
        # word bi*A + j, and bi+1 == K one-hots to an all-zero block,
        # matching the zero padding the flat slices would read.
        K = -(-nw // A)
        blk = rows.words[:, : K * A].reshape(b, K, A)
        bi = base // A
        words = _two_level_words(blk, bi, base - bi * A, n_words)
        return _words_to_bytes(words), (jnp.maximum(p, 0) & 3)
    else:
        # Flat one-hot over the whole row — cheapest for short rows.
        # XLA fuses the iota comparison into the reduction, so each
        # word read streams only the word slice (exact by construction
        # — no dot, no floating point).
        src = rows.words
        oh = jax.lax.broadcasted_iota(jnp.int32, (b, nw), 1) == base[:, None]
        width = nw
    words = [
        jnp.sum(jnp.where(oh, src[:, k : k + width], jnp.uint32(0)), axis=1)
        for k in range(n_words)
    ]
    return _words_to_bytes(words), (jnp.maximum(p, 0) & 3)


def _two_level_words(
    blocks: jax.Array, bi: jax.Array, loc: jax.Array, n_words: int
) -> list[jax.Array]:
    """Two-level word-window select shared by :func:`_window` and
    :func:`_sup_window`: one-hot blocks ``bi`` and ``bi+1`` out of
    ``blocks`` uint32[B, K, A] (one fused pass, two tiny outputs),
    then the shifted-slice select of ``n_words`` words at word offset
    ``loc`` ∈ [0, A) within the 2A-word pair. ``bi+1 == K`` one-hots
    to an all-zero block (callers rely on it matching zero padding).
    Requires ``loc + n_words <= 2A`` (``n_words <= A + 1`` given
    ``loc < A`` — enforced by _window's _BLOCK_WORDS guard).
    """
    b, _k, A = blocks.shape
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (b, blocks.shape[1]), 1)
    lo = jnp.sum(
        jnp.where((iota_k == bi[:, None])[:, :, None], blocks, jnp.uint32(0)),
        axis=1,
    )
    hi = jnp.sum(
        jnp.where(
            (iota_k == bi[:, None] + 1)[:, :, None], blocks, jnp.uint32(0)
        ),
        axis=1,
    )
    pair = jnp.concatenate([lo, hi], axis=1)  # uint32[B, 2A]
    oh = jax.lax.broadcasted_iota(jnp.int32, (b, A), 1) == loc[:, None]
    return [
        jnp.sum(jnp.where(oh, pair[:, k : k + A], jnp.uint32(0)), axis=1)
        for k in range(n_words)
    ]


def _words_to_bytes(words: list[jax.Array]) -> jax.Array:
    """n_words per-lane uint32 words → int32[B, n_words*4] byte window."""
    ww = jnp.stack(words, axis=1)  # uint32[B, n_words]
    return jnp.stack(
        [(ww >> 24) & 0xFF, (ww >> 16) & 0xFF, (ww >> 8) & 0xFF, ww & 0xFF],
        axis=2,
    ).reshape(ww.shape[0], len(words) * 4).astype(jnp.int32)


def _sup_fetch(rows: _Rows, bi0: jax.Array) -> jax.Array:
    """Fetch a per-lane SUPERBLOCK: ``_SUP_BLOCKS`` consecutive
    ``_BLOCK_WORDS``-word blocks anchored at block index ``bi0``
    (superblock word ``j`` = row word ``bi0*_BLOCK_WORDS + j``).

    ONE fused pass over the row produces all ``_SUP_BLOCKS`` outputs —
    this is what lets the variable-count scans pay ~one HBM row pass
    per ~468 bytes of scanned region instead of one per TLV element.
    Blocks past the padded row one-hot to zero, matching the zero
    padding the flat path reads there.
    """
    b = bi0.shape[0]
    A = _BLOCK_WORDS
    K = -(-rows.n_words // A)
    blk = rows.words[:, : K * A].reshape(b, K, A)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (b, K), 1)
    parts = [
        jnp.sum(
            jnp.where((iota_k == bi0[:, None] + m)[:, :, None], blk,
                      jnp.uint32(0)),
            axis=1,
        )
        for m in range(_SUP_BLOCKS)
    ]
    return jnp.concatenate(parts, axis=1)  # uint32[B, _SUP_BLOCKS * A]


def _sup_window(sup: jax.Array, p: jax.Array, bi0: jax.Array, n_words: int):
    """:func:`_window`-contract byte window served FROM a superblock —
    pure VPU work on [B, 512] bytes, no row pass.

    ``p`` is the ROW byte position; the caller guarantees the window
    fits the superblock (``(p >> 2) - bi0*_BLOCK_WORDS + n_words <=
    _SUP_BLOCKS*_BLOCK_WORDS`` — the scan loops' ``can-process``
    condition). Returns ``(win int32[B, n_words*4], a int32[B])`` with
    window bytes identical to ``_window(rows, p, n_words)`` for every
    such position.
    """
    b = p.shape[0]
    A = _BLOCK_WORDS
    wloc = (p >> 2) - bi0 * A  # superblock word position
    sj = wloc // A
    words = _two_level_words(
        sup.reshape(b, _SUP_BLOCKS, A), sj, wloc - sj * A, n_words
    )
    return _words_to_bytes(words), (jnp.maximum(p, 0) & 3)


def _wbyte(win: jax.Array, rel: jax.Array) -> jax.Array:
    """Window byte at per-lane index ``rel``; 0 when out of range."""
    wb = win.shape[1]
    oh = jnp.arange(wb, dtype=jnp.int32)[None, :] == rel[:, None]
    return jnp.sum(jnp.where(oh, win, 0), axis=1)


def _read_header_w(win, a, delta, p, limit):
    """TLV header at row position ``p + delta`` read from ``win``
    (anchored at p) → (tag, content_len, header_len, ok).

    Supports short-form and long-form lengths up to 3 length octets
    (certificates are < 2^24 bytes). All int32[B].
    """
    rel = a + delta
    tag = _wbyte(win, rel)
    b0 = _wbyte(win, rel + 1)
    b1 = _wbyte(win, rel + 2)
    b2 = _wbyte(win, rel + 3)
    b3 = _wbyte(win, rel + 4)

    short = b0 < 0x80
    n_len = b0 - 0x80  # long-form octet count (valid when !short)
    long_ok = (b0 > 0x80) & (n_len <= 3)

    clen_long = jnp.where(
        n_len == 1, b1,
        jnp.where(n_len == 2, (b1 << 8) | b2, (b1 << 16) | (b2 << 8) | b3),
    )
    clen = jnp.where(short, b0, clen_long)
    hlen = jnp.where(short, 2, 2 + n_len)
    pos = p + delta
    ok = (short | long_ok) & (pos >= 0) & (pos + hlen + clen <= limit)
    return tag, clen, hlen, ok


def _header_at(rows: _Rows, p, limit):
    """Standalone header read: its own 3-word window at ``p``."""
    win, a = _window(rows, p, 3)
    return _read_header_w(win, a, jnp.zeros_like(p), p, limit)


def _parse_time_w(win, a, delta, p):
    """UTCTime/GeneralizedTime at row position ``p + delta`` (within the
    window anchored at p) → (epoch_hour, ok).

    UTCTime YYMMDDHHMMSSZ (RFC 5280 §4.1.2.5.1: 19YY if YY ≥ 50 else
    20YY); GeneralizedTime YYYYMMDDHHMMSSZ. Minutes/seconds are
    discarded — the ExpDate bucket truncates to the hour
    (/root/reference/storage/types.go:339-346).
    """
    tag, clen, hlen, hok = _read_header_w(win, a, delta, p, jnp.int32(2**30))
    is_utc = tag == 0x17
    is_gen = tag == 0x18
    ok = hok & (is_utc | is_gen) & jnp.where(is_utc, clen >= 11, clen >= 13)
    q = a + delta + hlen  # window-relative content start

    def digits2(off):
        return (_wbyte(win, off) - 0x30) * 10 + (_wbyte(win, off + 1) - 0x30)

    def is_digits2(off):
        b0 = _wbyte(win, off)
        b1 = _wbyte(win, off + 1)
        return ((b0 >= 0x30) & (b0 <= 0x39)
                & (b1 >= 0x30) & (b1 <= 0x39))

    yy = digits2(q)
    year_utc = jnp.where(yy >= 50, 1900 + yy, 2000 + yy)
    year_gen = yy * 100 + digits2(q + 2)
    year = jnp.where(is_utc, year_utc, year_gen)
    body = jnp.where(is_utc, q, q + 2)  # start of MMDDHH...
    month = digits2(body + 2)
    day = digits2(body + 4)
    hour = digits2(body + 6)
    # Every byte feeding the expiry bucket must be a genuine ASCII
    # digit — range checks alone let some mutated bytes alias into
    # plausible values, silently corrupting the (expDate, issuer,
    # serial) identity (caught by the walker/host mutation fuzz).
    # Minutes/seconds are not validated: the bucket truncates to the
    # hour (types.go:339-346), so they cannot affect identity.
    digits_ok = (is_digits2(q) & is_digits2(body + 2)
                 & is_digits2(body + 4) & is_digits2(body + 6)
                 & jnp.where(is_utc, True, is_digits2(q + 2)))
    ok = (ok & digits_ok
          & (month >= 1) & (month <= 12) & (day >= 1) & (day <= 31)
          & (hour <= 23))

    # Days-from-civil (Gregorian), valid for year ≥ 1583; all positive here.
    y = year - (month <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = jnp.where(month > 2, month - 3, month + 9)
    doy = (153 * mp + 2) // 5 + day - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    days = era * 146097 + doe - 719468
    return days * 24 + hour, ok


def _scan_issuer_cn(rows: _Rows, name_off, name_end, hdr_ok0):
    """First CN (OID 2.5.4.3) value inside the issuer Name.

    Name ::= SEQUENCE OF RelativeDistinguishedName;
    RDN ::= SET OF AttributeTypeAndValue;
    ATV ::= SEQUENCE { type OID, value ANY }.
    Returns (cn_off, cn_len) with len 0 when absent. Runs as a
    superblock loop (see _scan_extensions): one row pass fetches each
    lane 512 bytes; a typical issuer Name (3–6 RDNs, tens of bytes)
    scans in a single fetch.
    """
    b = name_off.shape[0]
    zero = jnp.zeros((b,), jnp.int32)
    supw = _SUP_BLOCKS * _BLOCK_WORDS
    stride = (supw - 8 - _BLOCK_WORDS) * 4
    outer_max = -(-(rows.n_words * 4) // stride) + 1

    def rdn_round(win, a, p, cn_off, cn_len, alive, cnt, active):
        d0 = jnp.zeros_like(p)
        tag, clen, hlen, hok = _read_header_w(win, a, d0, p, name_end)
        set_ok = active & hok & (tag == 0x31)
        # Only the first ATV of each RDN SET is examined (multi-valued
        # RDNs are vanishingly rare; such lanes simply find no CN here,
        # and the CN filter then falls back to the host lane decision).
        da = hlen
        atag, aclen, ahlen, aok = _read_header_w(win, a, da, p, name_end)
        do = da + ahlen
        otag, oclen, ohlen, ook = _read_header_w(win, a, do, p, name_end)
        ro = a + do + ohlen
        is_cn = (
            set_ok & aok & (atag == 0x30) & ook & (otag == 0x06) & (oclen == 3)
            & (_wbyte(win, ro) == 0x55)
            & (_wbyte(win, ro + 1) == 0x04)
            & (_wbyte(win, ro + 2) == 0x03)
        )
        dv = do + ohlen + oclen
        vtag, vclen, vhlen, vok = _read_header_w(win, a, dv, p, name_end)
        take = is_cn & vok & (cn_len == 0)
        cn_off = jnp.where(take, p + dv + vhlen, cn_off)
        cn_len = jnp.where(take, vclen, cn_len)
        p = jnp.where(active & hok, p + hlen + clen, p)
        cnt = cnt + (active & hok).astype(jnp.int32)
        alive = alive & jnp.where(active, hok, True)
        return p, cn_off, cn_len, alive, cnt

    # Superblock loops (see _scan_extensions — same structure, same
    # window bytes per round as the old one-row-pass-per-RDN loop):
    # one row pass fetches each lane 512 bytes; RDNs are a few tens of
    # bytes, so a typical issuer Name scans in ONE fetch.
    def outer_cond(carry):
        r_out, _p, _co, _cl, _alive, _cnt, live = carry
        return (r_out < outer_max) & jnp.any(live)

    def outer_body(carry):
        r_out, p, cn_off, cn_len, alive, cnt, live = carry
        bi0 = p >> (2 + 4)
        sup = _sup_fetch(rows, bi0)

        def inner_cond(c):
            return jnp.any(c[-1])

        def inner_body(c):
            p, cn_off, cn_len, alive, cnt, go = c
            win, a = _sup_window(sup, p, bi0, 8)
            p, cn_off, cn_len, alive, cnt = rdn_round(
                win, a, p, cn_off, cn_len, alive, cnt, go
            )
            wloc = (p >> 2) - bi0 * _BLOCK_WORDS
            go = (alive & (p < name_end) & (cnt < MAX_RDNS)
                  & (wloc <= supw - 8))
            return p, cn_off, cn_len, alive, cnt, go

        # `live` doubles as the first round's go: a lane freshly
        # anchored at bi0 = p >> 6 always has wloc0 in [0, 16), so the
        # fit guard is trivially true.
        p, cn_off, cn_len, alive, cnt, _go = jax.lax.while_loop(
            inner_cond, inner_body, (p, cn_off, cn_len, alive, cnt, live)
        )
        live = alive & (p < name_end) & (cnt < MAX_RDNS)
        return r_out + 1, p, cn_off, cn_len, alive, cnt, live

    live0 = hdr_ok0 & (name_off < name_end)
    (_r, _p, cn_off, cn_len, _alive, _cnt, _live) = jax.lax.while_loop(
        outer_cond, outer_body,
        (jnp.int32(0), name_off, zero, zero, hdr_ok0, zero, live0),
    )
    return cn_off, cn_len


def _scan_extensions(rows: _Rows, ext_off, ext_end, alive0):
    """Walk SEQUENCE OF Extension for BasicConstraints CA + CRLDP
    presence.

    Superblock structure (round-4 rework): at production batch widths
    the early-exit never fires (some lane in a 2^20-lane batch always
    has many extensions), so the OLD one-row-pass-per-extension loop
    paid ~MAX_EXTS full HBM passes per batch. Now an OUTER loop
    fetches each lane a 512-byte superblock anchored at its position
    (ONE row pass, :func:`_sup_fetch`) and an INNER loop walks
    extensions entirely inside the superblock (:func:`_sup_window` —
    VPU-only); a lane waits for the next outer refetch only when its
    11-word window would cross the superblock edge. Each outer round
    therefore advances every active lane ≥ ~404 bytes (or to
    completion), so the row-pass count drops from ~MAX_EXTS to
    ≤ ceil(row/404) — the window bytes each round body sees are
    IDENTICAL to the old per-round ``_window`` read, so per-lane
    semantics (including the overrun and budget contracts) are
    unchanged. The per-lane extension budget stays MAX_EXTS (the old
    global round count bounded exactly the same thing).
    """
    b = ext_off.shape[0]
    false = jnp.zeros((b,), bool)
    zero = jnp.zeros((b,), jnp.int32)
    supw = _SUP_BLOCKS * _BLOCK_WORDS  # superblock words
    # Bytes a lane is guaranteed to traverse per outer round before its
    # window can cross the superblock edge (used for the outer budget).
    stride = (supw - 11 - _BLOCK_WORDS) * 4
    outer_max = -(-(rows.n_words * 4) // stride) + 1

    def outer_cond(carry):
        r_out, _p, _ca, _dp, _dpo, _dpl, _alive, _cnt, live = carry
        return (r_out < outer_max) & jnp.any(live)

    def outer_body(carry):
        r_out, p, is_ca, has_crldp, dp_off, dp_len, alive, cnt, live = carry
        bi0 = p >> (2 + 4)  # anchor block: p // (4 bytes * 16 words)
        sup = _sup_fetch(rows, bi0)

        def inner_cond(c):
            (_p, _ca, _dp, _dpo, _dpl, _alive, _cnt, go) = c
            return jnp.any(go)

        def inner_body(c):
            p, is_ca, has_crldp, dp_off, dp_len, alive, cnt, go = c
            win, a = _sup_window(sup, p, bi0, 11)
            (p, is_ca, has_crldp, dp_off, dp_len, alive, cnt) = _ext_round(
                win, a, p, ext_end,
                is_ca, has_crldp, dp_off, dp_len, alive, cnt, go,
            )
            wloc = (p >> 2) - bi0 * _BLOCK_WORDS
            go = (alive & (p < ext_end) & (cnt < MAX_EXTS)
                  & (wloc <= supw - 11))
            return p, is_ca, has_crldp, dp_off, dp_len, alive, cnt, go

        # `live` doubles as the first round's go: a lane freshly
        # anchored at bi0 = p >> 6 always has wloc0 in [0, 16), so the
        # fit guard is trivially true.
        (p, is_ca, has_crldp, dp_off, dp_len, alive, cnt, _go) = (
            jax.lax.while_loop(
                inner_cond, inner_body,
                (p, is_ca, has_crldp, dp_off, dp_len, alive, cnt, live),
            )
        )
        live = alive & (p < ext_end) & (cnt < MAX_EXTS)
        return (r_out + 1, p, is_ca, has_crldp, dp_off, dp_len, alive,
                cnt, live)

    live0 = alive0 & (ext_off < ext_end)
    (_r, p, is_ca, has_crldp, dp_off, dp_len, alive, _cnt, _live) = (
        jax.lax.while_loop(
            outer_cond, outer_body,
            (jnp.int32(0), ext_off, false, false, zero, zero, alive0,
             zero, live0),
        )
    )
    # Lanes still inside the window after exhausting the extension
    # budget — flag them (host lane) rather than silently missing a
    # trailing basicConstraints.
    exhausted = alive & (p < ext_end)
    return is_ca, has_crldp, dp_off, dp_len, alive & ~exhausted


def _ext_round(win, a, p, ext_end, is_ca, has_crldp, dp_off, dp_len,
               alive, cnt, active):
    """One extension parse against a window anchored at ``p`` — the
    original per-round body, window source abstracted out."""
    d0 = jnp.zeros_like(p)
    tag, clen, hlen, hok = _read_header_w(win, a, d0, p, ext_end)
    ext_ok = active & hok & (tag == 0x30)
    di = hlen
    otag, oclen, ohlen, ook = _read_header_w(win, a, di, p, ext_end)
    oid_ok = ext_ok & ook & (otag == 0x06) & (oclen == 3)
    ro = a + di + ohlen
    o0 = _wbyte(win, ro)
    o1 = _wbyte(win, ro + 1)
    o2 = _wbyte(win, ro + 2)
    is_bc = oid_ok & (o0 == 0x55) & (o1 == 0x1D) & (o2 == 0x13)
    is_dp = oid_ok & (o0 == 0x55) & (o1 == 0x1D) & (o2 == 0x1F)
    # optional BOOLEAN critical
    dc = di + ohlen + oclen
    ctag, cclen, chlen, cok = _read_header_w(win, a, dc, p, ext_end)
    has_crit = cok & (ctag == 0x01)
    dv = jnp.where(has_crit, dc + chlen + cclen, dc)
    vtag, vclen, vhlen, vok = _read_header_w(win, a, dv, p, ext_end)
    # extnValue must fit INSIDE its Extension frame (hlen + clen),
    # not merely inside the extension list — an inflated value
    # length would otherwise window into the next extension's
    # bytes. The whole LANE is rejected (host-lane fallback), in
    # lockstep with the host parser's DerError on the same input
    # (pinned by the walker/host mutation fuzz). The overrun check
    # uses a limit-free header re-read: a value whose end ALSO
    # crosses ext_end makes vok itself False, which must still
    # count as an overrun, not a silent skip (the list bound is a
    # superset of the frame bound). Same window bytes — pure
    # arithmetic, no extra gather.
    _vt2, vclen2, vhlen2, vok2 = _read_header_w(
        win, a, dv, p, jnp.int32(2**30)
    )
    overrun = ext_ok & vok2 & (dv + vhlen2 + vclen2 > hlen + clen)
    val_ok = vok & (vtag == 0x04) & ~overrun
    # BasicConstraints ::= SEQUENCE { cA BOOLEAN DEFAULT FALSE, ... }
    db = dv + vhlen
    btag, bclen, bhlen, bok = _read_header_w(win, a, db, p, ext_end)
    bc_seq_ok = val_ok & bok & (btag == 0x30)
    df = db + bhlen
    ftag, fclen, fhlen, fok = _read_header_w(win, a, df, p, ext_end)
    ca_flag = (
        bc_seq_ok & (bclen > 0) & fok & (ftag == 0x01) & (fclen == 1)
        & (_wbyte(win, a + df + fhlen) != 0)
    )
    is_ca = is_ca | (is_bc & ca_flag)
    take_dp = is_dp & val_ok & (dp_len == 0)
    dp_off = jnp.where(take_dp, p + dv + vhlen, dp_off)
    dp_len = jnp.where(take_dp, vclen, dp_len)
    has_crldp = has_crldp | (is_dp & val_ok)
    p = jnp.where(active & hok, p + hlen + clen, p)
    cnt = cnt + (active & hok).astype(jnp.int32)
    alive = alive & jnp.where(active, hok & ~overrun, True)
    return p, is_ca, has_crldp, dp_off, dp_len, alive, cnt


@functools.partial(jax.jit, static_argnames=("scan_issuer_cn",))
def parse_certs(
    data: jax.Array, length: jax.Array, scan_issuer_cn: bool = True
) -> ParsedCerts:
    """Extract map-stage fields from a batch of DER certificates.

    Args:
      data: uint8[B, L] zero-padded DER.
      length: int32[B] true byte length per lane.
      scan_issuer_cn: static — False skips the RDN scan entirely
        (several window reads per round); callers with no CN-prefix
        filter configured pass False and get cn_off/cn_len of 0.

    Returns a :class:`ParsedCerts`; lanes with ``ok=False`` must be
    re-parsed on the host (reference lane).
    """
    return parse_certs_rows(
        _pack_rows(data.astype(jnp.uint8)), length.astype(jnp.int32),
        scan_issuer_cn=scan_issuer_cn,
    )


def parse_certs_rows(
    rows: _Rows, length: jax.Array, scan_issuer_cn: bool = True
) -> ParsedCerts:
    """:func:`parse_certs` over pre-packed rows — callers that also
    extract serials (the fused ingest step) pack once and share."""
    length = length.astype(jnp.int32)
    b = length.shape[0]
    limit = length

    ok = length > 4
    zero = jnp.zeros((b,), jnp.int32)
    d0 = zero

    # The fixed walk pays ~one HBM row pass per window, so adjacent
    # headers are MERGED into shared windows wherever the next header
    # sits within reach for every well-formed certificate (11 windows
    # → 5). Reads that an adversarial length field pushes past a
    # merged window see zeros — each merge carries an explicit
    # in-window guard that routes such lanes to the exact host lane
    # instead of decoding the zeros (real certificates sit well inside
    # every guard; the guards exist so a crafted length can only cost
    # a host parse, never mis-extract).

    # -- window 1 (17 words = 68 bytes, anchored at 0): Certificate
    # SEQUENCE + TBSCertificate SEQUENCE + [0] version OPTIONAL +
    # serial INTEGER + signature AlgorithmIdentifier HEADER. Only the
    # alg header is read here (its frame is then skipped
    # arithmetically), so any AlgorithmIdentifier size — including
    # RSASSA-PSS's ~67-byte frame — stays on the device path. 68
    # bytes reach the alg header even for the 46-byte serial ceiling
    # (the widest serial the device schema accepts at all).
    w1 = 17 * 4  # window bytes — guards below must use this bound
    win, a = _window(rows, zero, w1 // 4)
    tag, clen, hlen, hok = _read_header_w(win, a, d0, zero, limit)
    ok &= hok & (tag == 0x30)
    d_tbs = hlen  # header lengths are ≤ 6, so every delta through the
    tag, clen, hlen, hok = _read_header_w(win, a, d_tbs, zero, limit)
    ok &= hok & (tag == 0x30)
    tbs_end = d_tbs + hlen + clen
    d = d_tbs + hlen  # ... version header stays in-window by bound
    tag, clen, hlen, hok = _read_header_w(win, a, d, zero, tbs_end)
    has_version = hok & (tag == 0xA0)
    dser = d + jnp.where(has_version, hlen + clen, 0)
    tag, clen, hlen, hok = _read_header_w(win, a, dser, zero, tbs_end)
    # Guard: the serial header's 5 bytes must all be in-window (an
    # adversarial version frame pushes dser out of reach).
    ok &= hok & (tag == 0x02) & (a + dser + 5 <= w1)
    serial_off = dser + hlen
    serial_len = clen
    d_alg = dser + hlen + clen
    tag, clen, hlen, hok = _read_header_w(win, a, d_alg, zero, tbs_end)
    ok &= hok & (tag == 0x30) & (a + d_alg + 5 <= w1)
    p = d_alg + hlen + clen  # past the whole AlgorithmIdentifier

    # -- issuer Name header: own small window anchored right at it.
    tag, clen, hlen, hok = _header_at(rows, p, tbs_end)
    ok &= hok & (tag == 0x30)
    issuer_off = p
    issuer_len_out = hlen + clen
    issuer_inner = issuer_off + hlen
    issuer_end = issuer_off + hlen + clen
    if scan_issuer_cn:
        cn_off, cn_len = _scan_issuer_cn(rows, issuer_inner, issuer_end, ok)
    else:  # CN filter disabled (static) — skip the RDN scan entirely
        cn_off = cn_len = jnp.zeros((b,), jnp.int32)
    p = issuer_end

    # -- window 3 (13 words): validity SEQUENCE { notBefore, notAfter }
    # + subject Name header (validity is ≤ ~36 bytes; the time parser's
    # strict digit checks reject any out-of-window zero reads).
    w3 = 13 * 4
    win, a = _window(rows, p, w3 // 4)
    tag, clen, hlen, hok = _read_header_w(win, a, d0, p, tbs_end)
    ok &= hok & (tag == 0x30)
    dnb = hlen
    nb_tag, nb_clen, nb_hlen, nb_ok = _read_header_w(win, a, dnb, p, tbs_end)
    ok &= nb_ok
    not_after_hour, t_ok = _parse_time_w(
        win, a, dnb + nb_hlen + nb_clen, p
    )
    ok &= t_ok
    d_subj = hlen + clen
    tag, clen, hlen, hok = _read_header_w(win, a, d_subj, p, tbs_end)
    ok &= hok & (tag == 0x30) & (a + d_subj + 5 <= w3)
    p = p + d_subj + hlen + clen  # past the subject Name

    # -- subjectPublicKeyInfo header: own window (the subject Name
    # length is unbounded, so no merge is possible).
    tag, clen, hlen, hok = _header_at(rows, p, tbs_end)
    ok &= hok & (tag == 0x30)
    spki_off = p
    spki_len = hlen + clen
    p = p + hlen + clen

    # -- window 4 (13 words): optional [1]/[2] UniqueID frames + [3]
    # EXPLICIT Extensions header + inner SEQUENCE header.
    w4 = 13 * 4
    win, a = _window(rows, p, w4 // 4)
    d = zero
    for _ in range(2):
        tag, clen, hlen, hok = _read_header_w(win, a, d, p, tbs_end)
        is_uid = hok & ((tag == 0x81) | (tag == 0x82) | (tag == 0xA1) | (tag == 0xA2))
        d = jnp.where(is_uid, d + hlen + clen, d)
    # Both the [3] header and the inner SEQUENCE header (≤ 6 + 5
    # bytes) must decode in-window. UniqueID frames large enough to
    # push them out (absent from real CT certificates) go host-side —
    # reading zeros there would silently classify the lane as
    # "no extensions".
    in_win = a + d + 11 <= w4
    tag, clen, hlen, hok = _read_header_w(win, a, d, p, tbs_end)
    has_ext = hok & (tag == 0xA3) & ((p + d) < tbs_end) & in_win
    # ANY trailing TBS bytes that are not a well-formed in-window [3]
    # frame route the lane to the exact host lane: the host parser
    # scans PAST frames it doesn't recognize (and tolerates a [3]
    # frame whose length overruns the TBS while its inner list is
    # intact), so silently deciding "no extensions" here would
    # mis-extract is_ca/CRLDP on exactly those certs (caught by the
    # round-7 sidecar/host mutation fuzz).
    ok &= has_ext | ((p + d) >= tbs_end)
    de = d + hlen
    etag, eclen, ehlen, eok = _read_header_w(win, a, de, p, tbs_end)
    ext_listed = has_ext & eok & (etag == 0x30)
    ok &= jnp.where(has_ext, eok & (etag == 0x30), True)
    ext_off = p + de + ehlen
    ext_end = jnp.where(ext_listed, p + de + ehlen + eclen,
                        jnp.zeros((b,), jnp.int32))
    is_ca, has_crldp, dp_off, dp_len, ext_ok = _scan_extensions(
        rows, ext_off, ext_end, ok
    )
    ok &= ext_ok

    return ParsedCerts(
        ok=ok,
        serial_off=jnp.where(ok, serial_off, 0),
        serial_len=jnp.where(ok, serial_len, 0),
        not_after_hour=jnp.where(ok, not_after_hour, 0),
        is_ca=is_ca & ok,
        has_crldp=has_crldp & ok,
        issuer_cn_off=cn_off,
        issuer_cn_len=jnp.where(ok, cn_len, 0),
        issuer_off=jnp.where(ok, issuer_off, 0),
        issuer_len=jnp.where(ok, issuer_len_out, 0),
        spki_off=jnp.where(ok, spki_off, 0),
        spki_len=jnp.where(ok, spki_len, 0),
        crldp_off=jnp.where(ok, dp_off, 0),
        crldp_len=jnp.where(ok, dp_len, 0),
    )


@functools.partial(jax.jit, static_argnames=("max_serial_bytes",))
def gather_serials(
    data: jax.Array, off: jax.Array, ln: jax.Array, max_serial_bytes: int = 46
) -> tuple[jax.Array, jax.Array]:
    """Extract serial content bytes into a fixed window — gather-free:
    one one-hot word window at ``off``, then a 4-way alignment select
    of static slices.

    Returns (serial uint8[B, max_serial_bytes] zero-padded,
    fits bool[B]). Lanes whose serial exceeds the window must use the
    host lane (real-world serials are ≤ 20 bytes per CABF; the window
    leaves slack for non-conforming logs).
    """
    return gather_serials_rows(
        _pack_rows(data.astype(jnp.uint8)), off, ln, max_serial_bytes
    )


def gather_serials_rows(
    rows: _Rows, off: jax.Array, ln: jax.Array, max_serial_bytes: int = 46
) -> tuple[jax.Array, jax.Array]:
    """:func:`gather_serials` over pre-packed rows (shared with
    :func:`parse_certs_rows` by the fused step)."""
    got = window_bytes_rows(rows, off, max_serial_bytes)
    mask = jnp.arange(max_serial_bytes, dtype=jnp.int32)[None, :] < ln[:, None]
    return jnp.where(mask, got, 0).astype(jnp.uint8), ln <= max_serial_bytes


def _dealign(win: jax.Array, a: jax.Array, n: int) -> jax.Array:
    """Window bytes [a, a+n) as int32[B, n] via a 4-way static-slice
    select (a = alignment ∈ {0,1,2,3})."""
    outs = [win[:, s : s + n] for s in range(4)]
    return jnp.where(
        (a == 0)[:, None], outs[0],
        jnp.where((a == 1)[:, None], outs[1],
                  jnp.where((a == 2)[:, None], outs[2], outs[3])),
    )


def window_bytes_rows(rows: _Rows, off: jax.Array, n: int) -> jax.Array:
    """Fixed-width byte window at per-lane ``off`` as int32[B, n] —
    gather-free (used by the CN-prefix filter)."""
    n_words = (3 + n + 3) // 4 + 1
    win, a = _window(rows, off, n_words)
    return _dealign(win, a, n)
