"""Vectorized X.509/DER field extraction on device.

Replaces the reference's per-entry CPU ``x509.ParseCertificate``
(/root/reference/cmd/ct-fetch/ct-fetch.go:198-226) for the fields the
map stage actually consumes:

- serial content offset/length (raw bytes incl. leading zeros,
  /root/reference/storage/types.go:165-178),
- notAfter as epoch-hours (the ExpDate bucket,
  /root/reference/storage/types.go:339-346),
- BasicConstraints CA flag and CRL-distribution-points presence
  (filter + metadata triggers, /root/reference/cmd/ct-fetch/ct-fetch.go:47-50,
  /root/reference/storage/issuermetadata.go:92-138),
- first CommonName of the issuer DN (the CN-prefix filter,
  /root/reference/cmd/ct-fetch/ct-fetch.go:56-62),
- SPKI TLV offset/length (issuer identity when a lane's cert is used
  as an issuer).

Because DER fixes the field order of TBSCertificate, the walk is a
straight-line program of vectorized header reads — identical control
flow for every lane, per-lane data only in the (tag, length, position)
registers. The two variable-count regions (issuer RDNs, extensions) are
fixed-trip-count ``fori_loop``s with active-lane masks. Any structural
surprise (unsupported long-form length, window overrun, loop budget
exhausted) clears the lane's ``ok`` bit; those lanes take the host
reference lane (:mod:`ct_mapreduce_tpu.core.der`), matching the
reference's tolerate-and-skip contract
(/root/reference/cmd/ct-fetch/ct-fetch.go:206-225).

Everything is shape-static and jit/pjit-friendly; the batch axis is the
sharding axis.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

MAX_RDNS = 12  # RDN components scanned in the issuer Name
MAX_EXTS = 24  # extensions scanned in the TBS


class ParsedCerts(NamedTuple):
    """Per-lane extraction results (int32 unless noted)."""

    ok: jax.Array  # bool — False ⇒ use the host reference lane
    serial_off: jax.Array
    serial_len: jax.Array
    not_after_hour: jax.Array  # hours since Unix epoch, floor-truncated
    is_ca: jax.Array  # bool
    has_crldp: jax.Array  # bool
    issuer_cn_off: jax.Array
    issuer_cn_len: jax.Array  # 0 ⇒ no CN present
    issuer_off: jax.Array  # full issuer Name TLV (host DN-cache key)
    issuer_len: jax.Array
    spki_off: jax.Array  # offset of the full SPKI TLV
    spki_len: jax.Array  # header+content length
    crldp_off: jax.Array  # CRLDP extnValue content (host CRL-cache key)
    crldp_len: jax.Array  # 0 ⇒ extension absent


def _byte_at(data: jax.Array, p: jax.Array) -> jax.Array:
    """data: uint8[B, L], p: int32[B] → int32[B]; OOB reads clamp."""
    l = data.shape[1]
    idx = jnp.clip(p, 0, l - 1)
    return jnp.take_along_axis(data, idx[:, None], axis=1)[:, 0].astype(jnp.int32)


def _read_header(data, p, limit):
    """TLV header at p → (tag, content_len, header_len, ok).

    Supports short-form and long-form lengths up to 3 length octets
    (certificates are < 2^24 bytes). All int32[B].
    """
    tag = _byte_at(data, p)
    b0 = _byte_at(data, p + 1)
    b1 = _byte_at(data, p + 2)
    b2 = _byte_at(data, p + 3)
    b3 = _byte_at(data, p + 4)

    short = b0 < 0x80
    n_len = b0 - 0x80  # long-form octet count (valid when !short)
    long_ok = (b0 > 0x80) & (n_len <= 3)

    clen_long = jnp.where(
        n_len == 1, b1,
        jnp.where(n_len == 2, (b1 << 8) | b2, (b1 << 16) | (b2 << 8) | b3),
    )
    clen = jnp.where(short, b0, clen_long)
    hlen = jnp.where(short, 2, 2 + n_len)
    ok = (short | long_ok) & (p >= 0) & (p + hlen + clen <= limit)
    return tag, clen, hlen, ok


def _parse_time(data, p):
    """UTCTime/GeneralizedTime at TLV position p → (epoch_hour, ok).

    UTCTime YYMMDDHHMMSSZ (RFC 5280 §4.1.2.5.1: 19YY if YY ≥ 50 else
    20YY); GeneralizedTime YYYYMMDDHHMMSSZ. Minutes/seconds are
    discarded — the ExpDate bucket truncates to the hour
    (/root/reference/storage/types.go:339-346).
    """
    tag, clen, hlen, hok = _read_header(data, p, jnp.int32(2**30))
    is_utc = tag == 0x17
    is_gen = tag == 0x18
    ok = hok & (is_utc | is_gen) & jnp.where(is_utc, clen >= 11, clen >= 13)
    q = p + hlen

    def digits2(off):
        return (_byte_at(data, off) - 0x30) * 10 + (_byte_at(data, off + 1) - 0x30)

    yy = digits2(q)
    year_utc = jnp.where(yy >= 50, 1900 + yy, 2000 + yy)
    year_gen = yy * 100 + digits2(q + 2)
    year = jnp.where(is_utc, year_utc, year_gen)
    body = jnp.where(is_utc, q, q + 2)  # start of MMDDHH...
    month = digits2(body + 2)
    day = digits2(body + 4)
    hour = digits2(body + 6)
    ok = ok & (month >= 1) & (month <= 12) & (day >= 1) & (day <= 31) & (hour <= 23)

    # Days-from-civil (Gregorian), valid for year ≥ 1583; all positive here.
    y = year - (month <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = jnp.where(month > 2, month - 3, month + 9)
    doy = (153 * mp + 2) // 5 + day - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    days = era * 146097 + doe - 719468
    return days * 24 + hour, ok


def _scan_issuer_cn(data, name_off, name_end, hdr_ok0):
    """First CN (OID 2.5.4.3) value inside the issuer Name.

    Name ::= SEQUENCE OF RelativeDistinguishedName;
    RDN ::= SET OF AttributeTypeAndValue;
    ATV ::= SEQUENCE { type OID, value ANY }.
    Returns (cn_off, cn_len) with len 0 when absent.
    """
    b = data.shape[0]
    zero = jnp.zeros((b,), jnp.int32)

    def body(_, carry):
        p, cn_off, cn_len, alive = carry
        active = alive & (p < name_end)
        tag, clen, hlen, hok = _read_header(data, p, name_end)
        set_ok = active & hok & (tag == 0x31)
        # Only the first ATV of each RDN SET is examined (multi-valued
        # RDNs are vanishingly rare; such lanes simply find no CN here,
        # and the CN filter then falls back to the host lane decision).
        pa = p + hlen
        atag, aclen, ahlen, aok = _read_header(data, pa, name_end)
        po = pa + ahlen
        otag, oclen, ohlen, ook = _read_header(data, po, name_end)
        is_cn = (
            set_ok & aok & (atag == 0x30) & ook & (otag == 0x06) & (oclen == 3)
            & (_byte_at(data, po + ohlen) == 0x55)
            & (_byte_at(data, po + ohlen + 1) == 0x04)
            & (_byte_at(data, po + ohlen + 2) == 0x03)
        )
        pv = po + ohlen + oclen
        vtag, vclen, vhlen, vok = _read_header(data, pv, name_end)
        take = is_cn & vok & (cn_len == 0)
        cn_off = jnp.where(take, pv + vhlen, cn_off)
        cn_len = jnp.where(take, vclen, cn_len)
        p = jnp.where(active & hok, p + hlen + clen, p)
        alive = alive & jnp.where(active, hok, True)
        return p, cn_off, cn_len, alive

    p0 = name_off
    _, cn_off, cn_len, _ = jax.lax.fori_loop(
        0, MAX_RDNS, body, (p0, zero, zero, hdr_ok0)
    )
    return cn_off, cn_len


def _scan_extensions(data, ext_off, ext_end, alive0):
    """Walk SEQUENCE OF Extension for BasicConstraints CA + CRLDP presence."""
    b = data.shape[0]
    false = jnp.zeros((b,), bool)
    zero = jnp.zeros((b,), jnp.int32)

    def body(_, carry):
        p, is_ca, has_crldp, dp_off, dp_len, alive = carry
        active = alive & (p < ext_end)
        tag, clen, hlen, hok = _read_header(data, p, ext_end)
        ext_ok = active & hok & (tag == 0x30)
        pi = p + hlen
        otag, oclen, ohlen, ook = _read_header(data, pi, ext_end)
        oid_ok = ext_ok & ook & (otag == 0x06) & (oclen == 3)
        o0 = _byte_at(data, pi + ohlen)
        o1 = _byte_at(data, pi + ohlen + 1)
        o2 = _byte_at(data, pi + ohlen + 2)
        is_bc = oid_ok & (o0 == 0x55) & (o1 == 0x1D) & (o2 == 0x13)
        is_dp = oid_ok & (o0 == 0x55) & (o1 == 0x1D) & (o2 == 0x1F)
        # optional BOOLEAN critical
        pc = pi + ohlen + oclen
        ctag, cclen, chlen, cok = _read_header(data, pc, ext_end)
        has_crit = cok & (ctag == 0x01)
        pv = jnp.where(has_crit, pc + chlen + cclen, pc)
        vtag, vclen, vhlen, vok = _read_header(data, pv, ext_end)
        val_ok = vok & (vtag == 0x04)
        # BasicConstraints ::= SEQUENCE { cA BOOLEAN DEFAULT FALSE, ... }
        pb = pv + vhlen
        btag, bclen, bhlen, bok = _read_header(data, pb, ext_end)
        bc_seq_ok = val_ok & bok & (btag == 0x30)
        pflag = pb + bhlen
        ftag, fclen, fhlen, fok = _read_header(data, pflag, ext_end)
        ca_flag = (
            bc_seq_ok & (bclen > 0) & fok & (ftag == 0x01) & (fclen == 1)
            & (_byte_at(data, pflag + fhlen) != 0)
        )
        is_ca = is_ca | (is_bc & ca_flag)
        take_dp = is_dp & val_ok & (dp_len == 0)
        dp_off = jnp.where(take_dp, pv + vhlen, dp_off)
        dp_len = jnp.where(take_dp, vclen, dp_len)
        has_crldp = has_crldp | (is_dp & val_ok)
        p = jnp.where(active & hok, p + hlen + clen, p)
        alive = alive & jnp.where(active, hok, True)
        return p, is_ca, has_crldp, dp_off, dp_len, alive

    p, is_ca, has_crldp, dp_off, dp_len, alive = jax.lax.fori_loop(
        0, MAX_EXTS, body, (ext_off, false, false, zero, zero, alive0)
    )
    # Lanes still inside the window after MAX_EXTS rounds exhausted the
    # loop budget — flag them (host lane) rather than silently missing
    # a trailing basicConstraints.
    exhausted = alive & (p < ext_end)
    return is_ca, has_crldp, dp_off, dp_len, alive & ~exhausted


@jax.jit
def parse_certs(data: jax.Array, length: jax.Array) -> ParsedCerts:
    """Extract map-stage fields from a batch of DER certificates.

    Args:
      data: uint8[B, L] zero-padded DER.
      length: int32[B] true byte length per lane.

    Returns a :class:`ParsedCerts`; lanes with ``ok=False`` must be
    re-parsed on the host (reference lane).
    """
    data = data.astype(jnp.uint8)
    length = length.astype(jnp.int32)
    b = data.shape[0]
    limit = length

    ok = length > 4
    p = jnp.zeros((b,), jnp.int32)

    # Certificate ::= SEQUENCE { tbsCertificate, sigAlg, sig }
    tag, clen, hlen, hok = _read_header(data, p, limit)
    ok &= hok & (tag == 0x30)
    p = p + hlen

    # TBSCertificate ::= SEQUENCE { ... }
    tag, clen, hlen, hok = _read_header(data, p, limit)
    ok &= hok & (tag == 0x30)
    tbs_end = p + hlen + clen
    p = p + hlen

    # [0] EXPLICIT Version OPTIONAL
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    has_version = hok & (tag == 0xA0)
    p = jnp.where(has_version, p + hlen + clen, p)

    # serialNumber INTEGER — raw content bytes are the Serial
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    ok &= hok & (tag == 0x02)
    serial_off = p + hlen
    serial_len = clen
    p = p + hlen + clen

    # signature AlgorithmIdentifier
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    ok &= hok & (tag == 0x30)
    p = p + hlen + clen

    # issuer Name — scanned for the first CN
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    ok &= hok & (tag == 0x30)
    issuer_off = p
    issuer_len_out = hlen + clen
    issuer_inner = p + hlen
    issuer_end = p + hlen + clen
    cn_off, cn_len = _scan_issuer_cn(data, issuer_inner, issuer_end, ok)
    p = issuer_end

    # validity SEQUENCE { notBefore, notAfter }
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    ok &= hok & (tag == 0x30)
    pv = p + hlen
    nb_tag, nb_clen, nb_hlen, nb_ok = _read_header(data, pv, tbs_end)
    ok &= nb_ok
    not_after_hour, t_ok = _parse_time(data, pv + nb_hlen + nb_clen)
    ok &= t_ok
    p = p + hlen + clen

    # subject Name
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    ok &= hok & (tag == 0x30)
    p = p + hlen + clen

    # subjectPublicKeyInfo
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    ok &= hok & (tag == 0x30)
    spki_off = p
    spki_len = hlen + clen
    p = p + hlen + clen

    # optional [1] issuerUniqueID / [2] subjectUniqueID (primitive or
    # constructed context tags 1/2)
    for _ in range(2):
        tag, clen, hlen, hok = _read_header(data, p, tbs_end)
        is_uid = hok & ((tag == 0x81) | (tag == 0x82) | (tag == 0xA1) | (tag == 0xA2))
        p = jnp.where(is_uid, p + hlen + clen, p)

    # [3] EXPLICIT Extensions OPTIONAL
    tag, clen, hlen, hok = _read_header(data, p, tbs_end)
    has_ext = hok & (tag == 0xA3) & (p < tbs_end)
    pe = p + hlen
    etag, eclen, ehlen, eok = _read_header(data, pe, tbs_end)
    ext_listed = has_ext & eok & (etag == 0x30)
    ok &= jnp.where(has_ext, eok & (etag == 0x30), True)
    ext_off = pe + ehlen
    ext_end = jnp.where(ext_listed, pe + ehlen + eclen, jnp.zeros((b,), jnp.int32))
    is_ca, has_crldp, dp_off, dp_len, ext_ok = _scan_extensions(
        data, ext_off, ext_end, ok
    )
    ok &= ext_ok

    return ParsedCerts(
        ok=ok,
        serial_off=jnp.where(ok, serial_off, 0),
        serial_len=jnp.where(ok, serial_len, 0),
        not_after_hour=jnp.where(ok, not_after_hour, 0),
        is_ca=is_ca & ok,
        has_crldp=has_crldp & ok,
        issuer_cn_off=cn_off,
        issuer_cn_len=jnp.where(ok, cn_len, 0),
        issuer_off=jnp.where(ok, issuer_off, 0),
        issuer_len=jnp.where(ok, issuer_len_out, 0),
        spki_off=jnp.where(ok, spki_off, 0),
        spki_len=jnp.where(ok, spki_len, 0),
        crldp_off=jnp.where(ok, dp_off, 0),
        crldp_len=jnp.where(ok, dp_len, 0),
    )


@functools.partial(jax.jit, static_argnames=("max_serial_bytes",))
def gather_serials(
    data: jax.Array, off: jax.Array, ln: jax.Array, max_serial_bytes: int = 46
) -> tuple[jax.Array, jax.Array]:
    """Gather serial content bytes into a fixed window.

    Returns (serial uint8[B, max_serial_bytes] zero-padded,
    fits bool[B]). Lanes whose serial exceeds the window must use the
    host lane (real-world serials are ≤ 20 bytes per CABF; the window
    leaves slack for non-conforming logs).
    """
    b, l = data.shape
    idx = off[:, None] + jnp.arange(max_serial_bytes, dtype=jnp.int32)[None, :]
    mask = jnp.arange(max_serial_bytes, dtype=jnp.int32)[None, :] < ln[:, None]
    got = jnp.take_along_axis(data, jnp.clip(idx, 0, l - 1), axis=1)
    return jnp.where(mask, got, 0).astype(jnp.uint8), ln <= max_serial_bytes
