"""Bucketized device-resident dedup set: sort-based insert, tile-aligned rows.

Drop-in alternative to :mod:`ct_mapreduce_tpu.ops.hashtable` (same
Redis-SADD semantics as the reference's per-certificate ``WasUnknown``
round trip, /root/reference/storage/knowncertificates.go:38-55), built
from the primitives the hardware actually favors. Measured on one
v5e chip at 2^20 lanes (tools/randacc.py, docs/randacc_r04_run.log):

  gather/scatter of 5-word rows:   13.6 / 86.5 ns per lane
  gather/scatter of 128-word rows: 12.0 / 11.8 ns per lane
  full 128-bit lexsort + payload:   4.0 ns per lane

i.e. random access costs per-LANE latency, not bandwidth — a 512-byte
tile-aligned block moves for the price of one word, while a 5-word
row scatter pays a ~7x tile-misalignment penalty — and sorts are
nearly free. So:

- The table is an array of BUCKETS: ``rows: uint32[n_buckets, 128]``,
  each row holding 24 slots x 5 words (4 fingerprint words + meta;
  word 120 caches the fill count, 121..127 spare) — one gather
  fetches a whole bucket, one scatter commits it, both tile-aligned.
- Slots fill contiguously (0..fill-1); the count ALSO rides in the
  row's spare word 120 for `contains` and host-side restores. (The
  insert still recomputes it by scanning — reading the cached word
  instead measured 2x SLOWER; see the _FILL_MODE note below.)
- Within-batch coordination is a SORT, not a scatter election: lanes
  sort by (bucket, key words, lane). Same-bucket lanes become
  adjacent, same-key lanes become adjacent-with-deterministic-first
  (lane order = batch order, matching the reference's sequential
  first-writer-wins), and every per-bucket quantity (fill, rank,
  merge window) is a dense segmented scan.
- Each round, every bucket's first pending lane (the bucket head)
  composes the merged row — old slots plus up to ``WINDOW`` new keys
  from its adjacent lanes — and commits it in ONE 128-word scatter.
- A bucket that is full (all 24 slots occupied, no key match) spills
  at BUCKET granularity: the lane hops to the next bucket (linear
  probing over buckets), up to ``max_probes`` hops, then overflows to
  the exact host lane — the reference's tolerate-and-redirect
  contract (/root/reference/cmd/ct-fetch/ct-fetch.go:206-225).

The lookup invariant mirrors slot-level open addressing one level up:
a key lives in the first non-full bucket of its hop chain, so
``contains`` probes until it hits a key match or a bucket with an
empty slot. Inserts only hop past a bucket when the round leaves it
with all 24 slots occupied, which preserves that invariant.

Load behavior (measured, docs/load_sweep_r04_bucket.log): 3.58M
entries/s at 25% load, 2.20M at 50%, 0.63M at 75%, 0.28M at 85% (131K
lanes, cap 2^24, one v5e). Below ~55% load inserts stay one
gather/sort/scatter round; past it the Poisson tail of full 24-slot
buckets (at 75% load a bucket is full ~10% of the time) forces hop
rounds at full batch width. The aggregator's growth policy therefore
grows at 55% fill by default, keeping steady state in the flat part
of the curve; versus the slot-granular table the bucket layout is
~3x faster at every load point measured (open table: 1.21M at 25%,
0.77M at 50%, 0.51M at 75%).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

SLOTS = 24  # slots per bucket (24 * 5 = 120 of 128 row words)
ROW_WORDS = 128
#: Spare row word caching the bucket's occupied-slot count. Slots fill
#: contiguously, so the count used to be recomputed as a 24-iteration
#: occupancy scan over the gathered row every insert round (~5 ns/entry
#: of pure formulation cost, docs/profile_r04_step_ops.txt); caching it
#: here makes `fill` a single column read. Every code path that builds
#: rows outside `insert` (bulk_insert_np, checkpoint restore) must keep
#: this word consistent — `fill_counts_np` recomputes it from occupancy.
FILL_WORD = 120


def _window_from_env() -> int:
    # Default 6: measured best on v5e at 2^20 lanes (191.0/191.4
    # ns/entry full step on two runs, vs 196.5-196.9 at 8, 250 at 4,
    # 229 at 16 — 4 loses to extra rounds, 16 to compose width).
    raw = os.environ.get("CTMR_BUCKET_WINDOW", "6")
    try:
        w = int(raw)
        if not 1 <= w <= 32:
            raise ValueError
    except ValueError:
        import warnings

        warnings.warn(
            f"ignoring CTMR_BUCKET_WINDOW={raw!r} (want 1..32); using 6",
            stacklevel=2)
        return 6
    return w


#: New keys merged per bucket per round (adjacent-lane look-ahead).
WINDOW = _window_from_env()

#: Fill-count sourcing inside the insert round (perf bisect knob):
#:   scan      — recompute via the 24-slot occupancy scan (default;
#:               the fill word is still written, so the cache stays
#:               valid for `contains` and host-side restores)
#:   cache     — read row word FILL_WORD instead of scanning
#:   scan-only — occupancy scan AND skip the fill-word write (the
#:               exact round-4 program, for A/B timing)
#:
#: MEASURED (round 5, tools/insertcost.py at 2^20 lanes / cap 2^26 on
#: one v5e): scan-only 65.8, scan 66.5, cache 133 ns/entry. Writing
#: the cached count is free; READING it in place of the occupancy
#: scan — the "obvious" win — DOUBLES insert cost (the single-column
#: read replaces a reduce that XLA fused into the gather, and the
#: resulting schedule materializes extra [B, 128] traffic). The scan
#: stays the shipping formulation; the cache word exists for
#: `contains`' emptiness test and topology-mismatched restores.
def _fill_mode_from_env() -> str:
    raw = os.environ.get("CTMR_FILL_MODE", "scan").strip().lower()
    if raw not in ("scan", "cache", "scan-only"):
        import warnings

        warnings.warn(
            f"ignoring CTMR_FILL_MODE={raw!r} "
            "(want scan | cache | scan-only); using scan", stacklevel=2)
        return "scan"
    return raw


_FILL_MODE = _fill_mode_from_env()


class BucketTable(NamedTuple):
    """Dedup-set state in HBM (donated through insert steps).

    ``rows[b]`` is bucket ``b``: 24 slots x (4 fingerprint words +
    meta word), filled contiguously; all-zero KEY words mark an empty
    slot (meta 0 is legal data, exactly as in hashtable.TableState).
    Row word ``FILL_WORD`` caches the bucket's occupied-slot count.
    """

    rows: jax.Array  # uint32[n_buckets, 128]
    count: jax.Array  # int32[]; occupied slots

    @property
    def n_buckets(self) -> int:
        return self.rows.shape[0]

    @property
    def capacity(self) -> int:
        return self.rows.shape[0] * SLOTS

    # Positional slot views matching hashtable.TableState's properties,
    # so the checkpoint codec writes the same (keys, meta) format for
    # both layouts (slot i = bucket i // SLOTS, position i % SLOTS).
    # Computed on HOST: a device-side [N, 5] reshape would pad its
    # minor dim to 128 lanes (25.6x the table's HBM footprint).
    @property
    def keys(self):  # uint32[n_buckets * SLOTS, 4]
        rows = np.asarray(self.rows)
        return rows[:, : SLOTS * 5].reshape(-1, 5)[:, :4]

    @property
    def meta(self):  # uint32[n_buckets * SLOTS]
        rows = np.asarray(self.rows)
        return rows[:, : SLOTS * 5].reshape(-1, 5)[:, 4]


def bucket_count(capacity: int, max_capacity: int | None = None) -> int:
    """Power-of-two bucket count for ≥ ``capacity`` slots. When the
    rounded-up slot count would exceed ``max_capacity`` (rows are 512 B
    each, so a silent 2x round-up can double HBM use past the
    configured bound), rounds DOWN instead."""
    if capacity < 1:
        raise ValueError(f"capacity must be positive, got {capacity}")
    nb = 1 << max(0, (capacity + SLOTS - 1) // SLOTS - 1).bit_length()
    if max_capacity is not None and nb * SLOTS > max_capacity:
        while nb > 1 and nb * SLOTS > max_capacity:
            nb >>= 1
    return nb


def make_table(capacity: int, max_capacity: int | None = None) -> BucketTable:
    """Table with at least ``capacity`` slots (n_buckets rounds up to
    a power of two; real capacity is ``state.capacity``). Pass
    ``max_capacity`` to round down instead when the power-of-two
    round-up would overshoot a configured ceiling."""
    n_buckets = bucket_count(capacity, max_capacity)
    return BucketTable(
        rows=jnp.zeros((n_buckets, ROW_WORDS), dtype=jnp.uint32),
        count=jnp.zeros((), dtype=jnp.int32),
    )


def fill_counts_np(rows_np: np.ndarray) -> np.ndarray:
    """Recompute each bucket's occupied-slot count from key-word
    occupancy and write it into ``FILL_WORD`` in place. Call after any
    host-side row construction (checkpoint restore, bulk insert) so
    the device insert's cached-fill invariant holds."""
    slots = rows_np[:, : SLOTS * 5].reshape(rows_np.shape[0], SLOTS, 5)
    fills = slots[:, :, :4].any(axis=-1).sum(axis=-1).astype(np.uint32)
    rows_np[:, FILL_WORD] = fills
    return fills


def _desentinel(keys: jax.Array) -> jax.Array:
    """Remap the (2^-128-unlikely) all-zero fingerprint, mirroring
    hashtable._desentinel so both tables share key semantics."""
    is_zero = jnp.all(keys == 0, axis=-1, keepdims=True)
    bump = jnp.concatenate(
        [jnp.zeros(keys.shape[:-1] + (3,), jnp.uint32),
         jnp.ones(keys.shape[:-1] + (1,), jnp.uint32)], axis=-1)
    return jnp.where(is_zero, bump, keys)


def _home_bucket(keys: jax.Array, n_buckets: int) -> jax.Array:
    h = keys[:, 0] ^ (keys[:, 1] * np.uint32(0x9E3779B9))
    # Independent of the in-bucket layout; distinct from hashtable's
    # slot hash only through the modulus.
    return (h & np.uint32(n_buckets - 1)).astype(jnp.int32)


def _shift_up(a: jax.Array, j: int, fill) -> jax.Array:
    """a[i + j] with ``fill`` past the end (j >= 0 static)."""
    n = a.shape[0]
    if j == 0:
        return a
    if j >= n:
        return jnp.full_like(a, fill)
    pad = jnp.full((j,) + a.shape[1:], fill, dtype=a.dtype)
    return jnp.concatenate([a[j:], pad], axis=0)


@functools.partial(jax.jit, static_argnames=("max_probes",), donate_argnums=(0,))
def insert(
    state: BucketTable,
    keys: jax.Array,
    meta: jax.Array,
    valid: jax.Array,
    max_probes: int = 32,
):
    """Batch insert-if-absent. Same contract as ``hashtable.insert``:

    Returns ``(new_state, was_unknown bool[B], overflowed bool[B])``
    with ``was_unknown`` true for the first lane (in batch order) of
    each genuinely-new key, false for re-inserts and within-batch
    duplicates; ``overflowed`` lanes must take the exact host lane.
    ``max_probes`` bounds bucket HOPS (each hop skips a full bucket =
    24 slots, so chains are far shorter than slot-granular probing).
    """
    rows = state.rows
    nb = rows.shape[0]
    b = keys.shape[0]
    if b > 1 << 25:
        # The segment broadcast packs (sorted position, window count)
        # as idx * 64 + w into one int32 cummax; position 2^25 is where
        # that encoding would overflow and silently corrupt merges.
        raise ValueError(
            f"insert batch width {b} exceeds 2^25 lanes (the int32 "
            "segment-broadcast encoding); split the batch")
    keys = _desentinel(keys.astype(jnp.uint32))
    h0 = _home_bucket(keys, nb)
    lane = jnp.arange(b, dtype=jnp.int32)
    sentinel = jnp.int32(nb)  # resolved lanes sort past every bucket
    idx = lane  # alias: position index in sorted order

    # Per-lane flags packed into one sort payload word:
    # bit0 known (seen before), bit1 inserted, bits 8.. hop count.
    K_KNOWN = jnp.uint32(1)
    K_INS = jnp.uint32(2)
    HOP_1 = jnp.uint32(256)

    # Round budget: each round commits >= 1 new key per active bucket
    # (the bucket head is always in its own window), so only window
    # retries and hops consume rounds. Hops are bounded by max_probes;
    # a few extra rounds absorb window-limited retries on skewed
    # batches before the overflow contract hands lanes to the host.
    max_rounds = max_probes + 16

    def cond(carry):
        rounds = carry[0]
        h = carry[2]
        return (rounds < max_rounds) & jnp.any(h < sentinel)

    def round_body(carry):
        rounds, rows, h, k0, k1, k2, k3, mt, ln, flags = carry

        # Sort pending lanes by (bucket, key, lane): same-bucket lanes
        # adjacent, same-key lanes adjacent with batch-order-first;
        # resolved lanes (h == sentinel) sink to the end.
        h, k0, k1, k2, k3, ln, mt, flags = jax.lax.sort(
            (h, k0, k1, k2, k3, ln, mt, flags), num_keys=6)
        pend = h < sentinel
        kw = (k0, k1, k2, k3)

        # One tile-aligned gather per lane: the whole bucket.
        #
        # LAYOUT RULE for everything below: intermediates stay either
        # 1-D [B] or full-width [B, 128]. Any [B, small] array (a
        # stack/concat of columns, a [B, SLOTS, 5] reshape) pads its
        # minor dim to 128 lanes on TPU — measured 62 GB of padding at
        # 2^20 lanes for the stacked formulation of this very loop.
        row = rows[jnp.minimum(h, nb - 1)]  # [B, 128]

        # Occupancy: the cached fill word, or the 24-slot scan
        # (CTMR_FILL_MODE bisect knob). The match scan walks all 24
        # slots either way: empty slots are all-zero and keys are
        # desentineled nonzero, so matching against them is harmless.
        scan_fill = jnp.zeros((b,), jnp.int32)
        in_row = jnp.zeros((b,), bool)
        for s in range(SLOTS):
            w = [row[:, s * 5 + i] for i in range(4)]
            if _FILL_MODE != "cache":
                occ_s = (w[0] | w[1] | w[2] | w[3]) != 0
                scan_fill = scan_fill + occ_s.astype(jnp.int32)
            in_row = in_row | (
                (w[0] == k0) & (w[1] == k1) & (w[2] == k2) & (w[3] == k3))
        if _FILL_MODE == "cache":
            fill = row[:, FILL_WORD].astype(jnp.int32)
        else:
            fill = scan_fill
        in_row = pend & in_row

        # Segment structure over the sorted order (dense scans only).
        def prev(a, fillv):
            return jnp.concatenate(
                [jnp.full((1,), fillv, a.dtype), a[:-1]])

        bucket_head = pend & (h != prev(h, -1))
        key_diff = (
            (k0 != prev(k0, 0)) | (k1 != prev(k1, 0))
            | (k2 != prev(k2, 0)) | (k3 != prev(k3, 0)))
        key_head = pend & (bucket_head | key_diff)
        dup_lane = pend & ~key_head  # same key as an earlier lane
        new_head = key_head & ~in_row

        # Rank among new heads within my bucket segment (cumsum with a
        # cummax-propagated segment base — c is nondecreasing, so the
        # latest bucket head always wins the max).
        x = new_head.astype(jnp.int32)
        c = jnp.cumsum(x)
        base = jax.lax.cummax(jnp.where(bucket_head, c - x, -1))
        r = c - x - base  # 0-based new-key rank in segment

        # Head computes how many new keys land in its WINDOW-lane
        # look-ahead, then broadcasts (start index, count) down the
        # segment through one monotone cummax.
        same_seg_w = jnp.zeros((b,), jnp.int32)
        for j in range(WINDOW):
            nh_j = _shift_up(new_head, j, False)
            h_j = _shift_up(h, j, sentinel)
            same_seg_w = same_seg_w + (nh_j & (h_j == h)).astype(jnp.int32)
        enc = jnp.where(bucket_head, idx * 64 + jnp.minimum(same_seg_w, 63),
                        -1)
        cm = jax.lax.cummax(enc)
        bs = cm // 64  # my bucket head's sorted position
        w_seg = cm % 64  # new keys in the head's window
        pos = idx - bs  # my offset inside the segment

        # Merge decision, identical arithmetic for the head composing
        # the row and for each candidate judging itself: in-window new
        # heads hold consecutive ranks 0..w_seg-1, so `fill + r` is
        # exactly the slot a merged key occupies.
        space = SLOTS - fill
        merged = new_head & (pos < WINDOW) & (r < space)
        full_after = w_seg >= space  # bucket leaves this round full

        # Compose merged rows at bucket heads as ONE fused elementwise
        # expression over the [B, 128] row: candidate j of a head
        # writes its 5 words at columns tgt_j*5 .. tgt_j*5+4. Every
        # [B]-vector broadcasts along the lane axis inside the fusion
        # (no [B, 1] materialization — see the layout rule above), and
        # candidates hold distinct slots, so the wheres commute.
        #
        # NOTE (round-5 negative result, measured via tools/insertcost
        # A/B on one v5e): a "cheaper" two-pass variant — build each
        # lane's own candidate block once, then OR the WINDOW-1
        # following lanes' blocks into the head via [B, 128] row shifts
        # — DOUBLED insert cost (130 vs 66 ns/entry at 2^20 lanes).
        # Sublane-axis shifts of [B, 128] arrays are not tile-aligned,
        # so each shifted copy materializes and the big loop fusion
        # breaks. The WINDOW-unrolled select chain below stays the
        # shipping formulation.
        col = jnp.arange(ROW_WORDS, dtype=jnp.int32)[None, :]  # [1, 128]
        outrow = row
        for j in range(WINDOW):
            m_j = _shift_up(merged, j, False)
            bs_j = _shift_up(bs, j, -1)
            ok_j = m_j & (bs_j == idx)  # candidate belongs to MY segment
            r_j = _shift_up(r, j, 0)
            tgt = fill + r_j
            off = col - (tgt * 5)[:, None]  # [B, 128]
            val = jnp.where(
                off == 0, _shift_up(k0, j, jnp.uint32(0))[:, None],
                jnp.where(
                    off == 1, _shift_up(k1, j, jnp.uint32(0))[:, None],
                    jnp.where(
                        off == 2, _shift_up(k2, j, jnp.uint32(0))[:, None],
                        jnp.where(
                            off == 3,
                            _shift_up(k3, j, jnp.uint32(0))[:, None],
                            _shift_up(mt, j, jnp.uint32(0))[:, None]))))
            sel = ok_j[:, None] & (off >= 0) & (off < 5)
            outrow = jnp.where(sel, val, outrow)
        # The committed row also carries the updated fill count in its
        # spare word (all w_seg in-window new keys hold consecutive
        # ranks, so exactly min(w_seg, space) of them merge per round).
        if _FILL_MODE != "scan-only":
            new_fill = (fill + jnp.minimum(w_seg, space)).astype(jnp.uint32)
            outrow = jnp.where(col == FILL_WORD, new_fill[:, None], outrow)

        # One tile-aligned scatter per active bucket (heads hold
        # unique, sorted bucket ids — no duplicate indices).
        write = bucket_head & (w_seg > 0) & (space > 0)
        wslot = jnp.where(write, h, sentinel)
        rows = rows.at[wslot].set(outrow, mode="drop")

        # Resolve lanes. Duplicate lanes resolve as known even when
        # their key head is still pending: the head (or, on overflow,
        # the exact host lane) accounts for the single fresh insert.
        flags = jnp.where(pend & (in_row | dup_lane), flags | K_KNOWN, flags)
        flags = jnp.where(merged, flags | K_INS, flags)
        resolved = in_row | dup_lane | merged
        still = pend & ~resolved
        hop = still & full_after
        flags = jnp.where(hop, flags + HOP_1, flags)
        hops = (flags >> 8).astype(jnp.int32)
        ovf_now = hop & (hops >= max_probes)
        # Overflowed lanes resolve (host lane takes them); hopping
        # lanes advance one bucket; window-limited lanes retry.
        h = jnp.where(still & ~ovf_now,
                      jnp.where(hop, (h + 1) & (nb - 1), h), sentinel)
        # Mark terminal overflow in a flag bit (bit2).
        flags = jnp.where(ovf_now, flags | jnp.uint32(4), flags)
        return (rounds + 1, rows, h, k0, k1, k2, k3, mt, ln, flags)

    h_init = jnp.where(valid, h0, sentinel)
    flags0 = jnp.zeros((b,), jnp.uint32)
    carry = (jnp.int32(0), rows, h_init,
             keys[:, 0], keys[:, 1], keys[:, 2], keys[:, 3],
             meta.astype(jnp.uint32), lane, flags0)
    (_, rows, h_fin, _, _, _, _, _, ln_fin, flags_fin) = jax.lax.while_loop(
        cond, round_body, carry)

    # Unsort the per-lane outcome by SORTING on the carried lane ids
    # (a permutation of 0..b-1, so the sort reproduces lane order
    # exactly). A sort is the cheap primitive on this hardware — 2.6
    # vs 13 ns/lane for the equivalent scatter (tools/randacc.py).
    # Lanes that left the loop still pending (round budget) also
    # overflow.
    res_sorted = (
        flags_fin
        | jnp.where(h_fin < sentinel, jnp.uint32(4), jnp.uint32(0)))
    _, packed = jax.lax.sort((ln_fin, res_sorted), num_keys=1)
    was_unknown = (packed & 2) != 0
    overflowed = (packed & 4) != 0
    new_count = state.count + jnp.sum(was_unknown, dtype=jnp.int32)
    return BucketTable(rows, new_count), was_unknown, overflowed


@functools.partial(jax.jit, static_argnames=("max_probes",))
def contains(state: BucketTable, keys: jax.Array,
             max_probes: int = 32) -> jax.Array:
    """Batch membership query: bool[B]. One bucket gather resolves a
    lane unless the bucket is full-without-match (then it hops, like
    the insert's bucket-granular open addressing)."""
    rows = state.rows
    nb = rows.shape[0]
    b = keys.shape[0]
    keys = _desentinel(keys.astype(jnp.uint32))
    h0 = _home_bucket(keys, nb)

    def cond(carry):
        hops, _h, open_, _found = carry[0], carry[1], carry[2], carry[3]
        return (hops < max_probes) & jnp.any(open_)

    def round_body(carry):
        hops, h, open_, found = carry
        row = rows[h]  # [B, 128]
        # Per-column [B] slices, not a [B, SLOTS, 5] reshape — small
        # minor dims pad to 128 lanes on TPU (layout rule in insert).
        # Emptiness comes from the cached fill word, not a slot scan.
        match = jnp.zeros((b,), bool)
        has_empty = jnp.zeros((b,), bool)
        for s in range(SLOTS):
            w = [row[:, s * 5 + i] for i in range(4)]
            match = match | (
                (w[0] == keys[:, 0]) & (w[1] == keys[:, 1])
                & (w[2] == keys[:, 2]) & (w[3] == keys[:, 3]))
            if _FILL_MODE == "scan-only":
                has_empty = has_empty | ((w[0] | w[1] | w[2] | w[3]) == 0)
        if _FILL_MODE != "scan-only":
            has_empty = row[:, FILL_WORD].astype(jnp.int32) < SLOTS
        found = found | (open_ & match)
        open_ = open_ & ~match & ~has_empty
        h = jnp.where(open_, (h + 1) & (nb - 1), h)
        return hops + 1, h, open_, found

    _, _, _, found = jax.lax.while_loop(
        cond, round_body,
        (jnp.int32(0), h0, jnp.ones((b,), bool), jnp.zeros((b,), bool)))
    return found


def contains_np(rows_np: np.ndarray, keys: np.ndarray,
                max_probes: int = 32) -> np.ndarray:
    """NumPy mirror of :func:`contains` for host-only snapshot reads
    (storage-statistics must not touch the device)."""
    nb = rows_np.shape[0]
    keys = keys.astype(np.uint32, copy=True).reshape(-1, 4)
    zero = ~keys.any(axis=-1)
    keys[zero, 3] = 1  # _desentinel
    h = ((keys[:, 0] ^ (keys[:, 1] * np.uint32(0x9E3779B9)))
         & np.uint32(nb - 1)).astype(np.int64)
    out = np.zeros((keys.shape[0],), bool)
    open_ = np.ones((keys.shape[0],), bool)
    slots = rows_np[:, : SLOTS * 5].reshape(nb, SLOTS, 5)
    for _ in range(max_probes):
        if not open_.any():
            break
        rows = slots[h[open_]]  # [n, SLOTS, 5]
        match = (rows[:, :, :4] == keys[open_][:, None, :]).all(-1).any(-1)
        has_empty = (~rows[:, :, :4].any(-1)).any(-1)
        sub = np.where(open_)[0]
        out[sub[match]] = True
        still = ~match & ~has_empty
        open_[sub[~still]] = False
        h[sub[still]] = (h[sub[still]] + 1) & (nb - 1)
    return out


def drain_np(state: BucketTable) -> tuple[np.ndarray, np.ndarray]:
    """Pull (keys uint32[N, 4], meta uint32[N]) of occupied slots."""
    rows = np.asarray(state.rows)
    slots = rows[:, : SLOTS * 5].reshape(-1, 5)
    occ = slots[:, :4].any(axis=-1)
    return slots[occ, :4], slots[occ, 4]


def bulk_insert_np(rows_np: np.ndarray, keys: np.ndarray,
                   meta: np.ndarray, max_probes: int = 32) -> int:
    """Host-side rebuild: insert ``keys`` into ``rows_np`` in place
    (the topology-mismatched checkpoint-restore path). Returns the
    number of keys that could not be placed within ``max_probes``
    hops. Callers must pass DEDUPLICATED keys not already present in
    the table (drained dedup-set rows satisfy both by construction) —
    no membership check is performed.

    Vectorized by rounds: bucket fills via bincount, per-bucket ranks
    via argsort order, spillover hops to the next bucket. Maintains
    the ``FILL_WORD`` cache the device insert relies on.
    """
    nb = rows_np.shape[0]
    keys = keys.astype(np.uint32).reshape(-1, 4)
    meta = meta.astype(np.uint32).reshape(-1)
    zero = ~keys.any(axis=-1)
    if zero.any():
        keys = keys.copy()
        keys[zero, 3] = 1
    h = ((keys[:, 0] ^ (keys[:, 1] * np.uint32(0x9E3779B9)))
         & np.uint32(nb - 1)).astype(np.int64)
    slots = rows_np[:, : SLOTS * 5].reshape(nb, SLOTS, 5)
    fill = (slots[:, :, :4].any(axis=-1)).sum(axis=-1).astype(np.int64)
    alive = np.ones(keys.shape[0], bool)
    for _ in range(max_probes):
        if not alive.any():
            break
        sub = np.where(alive)[0]
        order = sub[np.argsort(h[sub], kind="stable")]
        hs = h[order]
        seg_start = np.r_[True, hs[1:] != hs[:-1]]
        seg_idx = np.cumsum(seg_start) - 1
        first = np.where(seg_start)[0]
        rank = np.arange(len(order)) - first[seg_idx]
        slot = fill[hs] + rank
        ok = slot < SLOTS
        tgt = order[ok]
        slots[hs[ok], slot[ok], :4] = keys[tgt]
        slots[hs[ok], slot[ok], 4] = meta[tgt]
        fill += np.bincount(hs[ok], minlength=nb)
        alive[tgt] = False
        h[order[~ok]] = (h[order[~ok]] + 1) & (nb - 1)
    rows_np[:, FILL_WORD] = fill.astype(np.uint32)
    return int(alive.sum())
