"""Device ops: the jitted/Pallas kernels of the ingest pipeline.

- :mod:`~ct_mapreduce_tpu.ops.der_kernel` — batched DER/TLV field
  extraction (the reference's per-cert x509 parse, done data-parallel).
- :mod:`~ct_mapreduce_tpu.ops.sha256` — jitted SHA-256 over packed
  blocks; :mod:`~ct_mapreduce_tpu.ops.pallas_sha256` — the Pallas
  variant.
- :mod:`~ct_mapreduce_tpu.ops.hashtable` — HBM-resident dedup set
  (insert-if-absent, the Redis SADD replacement).
- :mod:`~ct_mapreduce_tpu.ops.pipeline` — the fused ingest step.
"""

from ct_mapreduce_tpu.ops import (  # noqa: F401
    der_kernel,
    hashtable,
    pipeline,
    sha256,
)
