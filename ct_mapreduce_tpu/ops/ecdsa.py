"""Batched ECDSA verification (P-256 + P-384) as pure-JAX ops.

Two ladder formulations share one verdict contract — a lane's verdict
is the mathematical ECDSA verdict, bit-identical to the pure-python
reference verifier (:mod:`ct_mapreduce_tpu.verify.host`) on EVERY
input, adversarial ones included:

- **Windowed precompute ladder (round 17, the default).** Both scalar
  multiplications degenerate into table lookups: u1·G reads a
  device-resident fixed-base window table (G never changes — built
  once per process through the host reference, so the constants are
  independently derivable), and u2·Q reads a per-key window table the
  verify lane caches per log key (a CT workload verifies millions of
  signatures under <100 distinct log keys — the opposite regime from
  blockchain, so key-dependent precompute amortizes instantly). With
  w-bit windows the whole dual-scalar multiplication is 2·(bits/w)
  COMPLETE projective mixed additions (Renes–Costello–Batina 2015,
  a = -3 — no exceptional cases, no doubling fallback branch) and
  zero doublings. Inversions (s⁻¹ and the final x_R = X/Z
  normalization) run through :func:`bigint.batch_inv_mont` — one
  Fermat inversion per batch, zero denominators masked through the
  product so adversarial lanes cannot desync a neighbor's verdict.

- **Jacobian Shamir ladder (window = 0, the round-13 formulation).**
  Kept verbatim as the parity fallback: per-bit double + complete
  mixed add, per-lane Fermat inversions. `verifyPrecompWindow = 0`
  routes here; the KAT corpus pins windowed ≡ legacy ≡ host.

Graph-size discipline is load-bearing either way: ladders are
``fori_loop``s (one traced iteration), table lookups are gathers on a
loop-indexed window slice, and batches compile once per
(curve, window, width, table-slot) shape — pow2-padded so shapes stay
log-bounded.

The kernel never *decides* which lanes it should see — routing
(P-256 vs P-384 vs odd curves vs RSA) is the extractor's and key
registry's job, mirroring the walker-fallback pattern.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ct_mapreduce_tpu.ops import bigint
from ct_mapreduce_tpu.ops.bigint import (
    P256_N,
    P256_P,
    P384_N,
    P384_P,
    Mod,
    add_mod,
    bytes_to_limbs,
    eq,
    from_mont,
    geq,
    is_zero,
    mod_reduce_once,
    mont_inv,
    mont_mul,
    mont_sqr,
    sub_mod,
    to_mont,
)
from ct_mapreduce_tpu.verify import host as vhost

# Historical P-256 constants (kept for reference/tests).
P256_B_INT = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
P256_GX_INT = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
P256_GY_INT = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

DEFAULT_WINDOW = 8  # verifyPrecompWindow default; 0 = legacy ladder
VALID_WINDOWS = (0, 2, 4, 8)  # w must divide 16 (limb radix)
MIN_QTABLE_SLOTS = 32  # convenience-wrapper qtab slot floor — matches
# the lane's default qtable size so tier-1 compiles ONE shape


@dataclass(frozen=True)
class CurveOps:
    """One curve's device-side constants (host numpy, baked at trace).
    ``curve`` is the pure-python reference curve — table generation
    runs through it so every precomputed point is independently
    derivable from the reference implementation."""

    name: str
    curve: vhost.Curve
    mod_p: Mod
    mod_n: Mod
    b_m: np.ndarray  # curve b, Montgomery domain
    gx_m: np.ndarray  # generator, Montgomery domain
    gy_m: np.ndarray
    nbits: int
    byte_len: int


def _make_ops(curve: vhost.Curve, mod_p: Mod, mod_n: Mod) -> CurveOps:
    nl = mod_p.nlimb
    r = 1 << (bigint.RADIX * nl)
    p = curve.p
    return CurveOps(
        name=curve.name,
        curve=curve,
        mod_p=mod_p,
        mod_n=mod_n,
        b_m=bigint.limbs_from_int(curve.b * r % p, nl),
        gx_m=bigint.limbs_from_int(curve.gx * r % p, nl),
        gy_m=bigint.limbs_from_int(curve.gy * r % p, nl),
        nbits=bigint.RADIX * nl,
        byte_len=2 * nl,
    )


P256_OPS = _make_ops(vhost.P256, P256_P, P256_N)
P384_OPS = _make_ops(vhost.P384, P384_P, P384_N)
CURVE_OPS = {o.name: o for o in (P256_OPS, P384_OPS)}


def resolve_precomp_window(window: int | None = None) -> int:
    """The ``verifyPrecompWindow`` knob: explicit value (directive /
    kwarg, ≥ 0) > ``CTMR_VERIFY_PRECOMP_WINDOW`` env > default 8.
    0 selects the legacy Jacobian ladder; invalid values (window must
    divide 16) fall back to the default, matching the config layer's
    tolerance for unparseable values."""
    if window is None or window < 0:
        try:
            window = int(
                os.environ.get("CTMR_VERIFY_PRECOMP_WINDOW", "") or -1)
        except ValueError:
            window = -1
    if window < 0 or window not in VALID_WINDOWS:
        return DEFAULT_WINDOW if window != 0 else 0
    return window


# -- Jacobian ladder primitives (round 13, window = 0) -------------------

def _dbl(x1, y1, z1, mod: Mod):
    """Jacobian doubling, a = -3 (dbl-2001-b). Z = 0 stays Z = 0, so
    infinity is preserved without a select."""
    delta = mont_sqr(z1, mod)
    gamma = mont_sqr(y1, mod)
    beta = mont_mul(x1, gamma, mod)
    t0 = sub_mod(x1, delta, mod)
    t1 = add_mod(x1, delta, mod)
    alpha = mont_mul(t0, t1, mod)
    alpha = add_mod(add_mod(alpha, alpha, mod), alpha, mod)
    b2 = add_mod(beta, beta, mod)
    b4 = add_mod(b2, b2, mod)
    b8 = add_mod(b4, b4, mod)
    x3 = sub_mod(mont_sqr(alpha, mod), b8, mod)
    t2 = add_mod(y1, z1, mod)
    z3 = sub_mod(sub_mod(mont_sqr(t2, mod), gamma, mod), delta, mod)
    g2 = mont_sqr(gamma, mod)
    g8 = add_mod(add_mod(g2, g2, mod), add_mod(g2, g2, mod), mod)
    g8 = add_mod(g8, g8, mod)
    y3 = sub_mod(mont_mul(alpha, sub_mod(b4, x3, mod), mod), g8, mod)
    return x3, y3, z3


def _sel(c, a, b):
    """Per-lane limb select: c bool[...], a/b uint32[..., nl]."""
    return jnp.where(c[..., None], a, b)


def _add_mixed(x1, y1, z1, x2, y2, q_inf, mod: Mod):
    """Complete Jacobian + affine addition (the round-13 select-based
    formulation — kept verbatim for the window = 0 parity path).

    Handles every exceptional case by select: P at infinity → Q,
    Q at infinity → P, P == Q → double, P == -Q → infinity. The
    general madd formulas are evaluated unconditionally (vector lanes
    are free); the selects pick the right answer per lane."""
    p_inf = is_zero(z1)
    z1z1 = mont_sqr(z1, mod)
    u2 = mont_mul(x2, z1z1, mod)
    s2 = mont_mul(y2, mont_mul(z1, z1z1, mod), mod)
    h = sub_mod(u2, x1, mod)
    rr = sub_mod(s2, y1, mod)
    hh = mont_sqr(h, mod)
    hhh = mont_mul(h, hh, mod)
    v = mont_mul(x1, hh, mod)
    x3 = sub_mod(sub_mod(mont_sqr(rr, mod), hhh, mod),
                 add_mod(v, v, mod), mod)
    y3 = sub_mod(mont_mul(rr, sub_mod(v, x3, mod), mod),
                 mont_mul(y1, hhh, mod), mod)
    z3 = mont_mul(z1, h, mod)

    same_x = is_zero(h) & ~p_inf & ~q_inf
    dbl_case = same_x & is_zero(rr)
    neg_case = same_x & ~is_zero(rr)
    dx, dy, dz = _dbl(x1, y1, z1, mod)

    zero = jnp.zeros_like(x1)
    one_m = jnp.broadcast_to(jnp.asarray(mod.one_m), x1.shape)
    x3 = _sel(dbl_case, dx, x3)
    y3 = _sel(dbl_case, dy, y3)
    z3 = _sel(dbl_case, dz, z3)
    z3 = _sel(neg_case, zero, z3)
    # P at infinity: result is Q (as Jacobian with Z = 1), unless Q is
    # infinity too. Q at infinity: result is P.
    x3 = _sel(p_inf, x2, x3)
    y3 = _sel(p_inf, y2, y3)
    z3 = _sel(p_inf, _sel(q_inf, zero, one_m), z3)
    x3 = _sel(q_inf & ~p_inf, x1, x3)
    y3 = _sel(q_inf & ~p_inf, y1, y3)
    z3 = _sel(q_inf & ~p_inf, z1, z3)
    return x3, y3, z3


def _to_affine(x, y, z, mod: Mod):
    """Jacobian → affine (Montgomery domain); infinity → (0, 0, inf)."""
    inf = is_zero(z)
    zi = mont_inv(z, mod)
    zi2 = mont_sqr(zi, mod)
    ax = mont_mul(x, zi2, mod)
    ay = mont_mul(y, mont_mul(zi, zi2, mod), mod)
    return ax, ay, inf


def _on_curve(x_m, y_m, ops: CurveOps):
    """y² == x³ - 3x + b (Montgomery domain)."""
    mod = ops.mod_p
    lhs = mont_sqr(y_m, mod)
    x3 = mont_mul(mont_sqr(x_m, mod), x_m, mod)
    x_3 = add_mod(add_mod(x_m, x_m, mod), x_m, mod)
    rhs = add_mod(sub_mod(x3, x_3, mod),
                  jnp.broadcast_to(jnp.asarray(ops.b_m), x_m.shape), mod)
    return eq(lhs, rhs)


def _check_inputs(ops: CurveOps, digest, r, s, qx, qy, valid):
    """Shared validity prefix: limb conversion, range checks, on-curve
    check, and the u1/u2 ingredients. Returns (ok, limbs...)."""
    mod_n, mod_p = ops.mod_n, ops.mod_p
    r_l = bytes_to_limbs(r)
    s_l = bytes_to_limbs(s)
    e_l = bytes_to_limbs(digest)
    qx_l = bytes_to_limbs(qx)
    qy_l = bytes_to_limbs(qy)

    n_b = jnp.broadcast_to(jnp.asarray(mod_n.n), r_l.shape)
    p_b = jnp.broadcast_to(jnp.asarray(mod_p.n), r_l.shape)
    ok = (
        valid
        & ~is_zero(r_l) & ~geq(r_l, n_b)
        & ~is_zero(s_l) & ~geq(s_l, n_b)
        & ~geq(qx_l, p_b) & ~geq(qy_l, p_b)
        & ~(is_zero(qx_l) & is_zero(qy_l))
    )
    qx_m = to_mont(qx_l, mod_p)
    qy_m = to_mont(qy_l, mod_p)
    ok = ok & _on_curve(qx_m, qy_m, ops)
    return ok, r_l, s_l, e_l, qx_m, qy_m


def _scalars(ops: CurveOps, r_l, s_l, e_l, w_m):
    """u1 = e·s⁻¹, u2 = r·s⁻¹ (plain domain) from the Montgomery-
    domain inverse ``w_m``."""
    mod_n = ops.mod_n
    e_m = to_mont(mod_reduce_once(e_l, mod_n), mod_n)
    r_nm = to_mont(mod_reduce_once(r_l, mod_n), mod_n)
    u1 = from_mont(mont_mul(e_m, w_m, mod_n), mod_n)
    u2 = from_mont(mont_mul(r_nm, w_m, mod_n), mod_n)
    return u1, u2


def _verify_jacobian(ops: CurveOps, digest, r, s, qx, qy, valid):
    """The round-13 Shamir dual-scalar ladder, curve-parameterized.
    Bit-identical to the original P-256 formulation (same ops, same
    order) — the window = 0 parity fallback."""
    mod_p = ops.mod_p
    ok, r_l, s_l, e_l, qx_m, qy_m = _check_inputs(
        ops, digest, r, s, qx, qy, valid)

    # Scalars: w = s^-1 mod n; u1 = e·w; u2 = r·w (plain domain).
    # A zero s would make the inversion garbage — ok lanes exclude it,
    # and garbage scalars on dead lanes can't resurrect the verdict.
    s_m = to_mont(s_l, ops.mod_n)
    w_m = mont_inv(s_m, ops.mod_n)
    u1, u2 = _scalars(ops, r_l, s_l, e_l, w_m)

    # Shamir precompute: T = G + Q (affine, per lane). Complete add
    # handles Q == ±G; T can be infinity (Q == -G).
    gx_b = jnp.broadcast_to(jnp.asarray(ops.gx_m), qx_m.shape)
    gy_b = jnp.broadcast_to(jnp.asarray(ops.gy_m), qx_m.shape)
    one_m = jnp.broadcast_to(jnp.asarray(mod_p.one_m), qx_m.shape)
    q_inf = jnp.zeros(ok.shape, bool)
    tx_j, ty_j, tz_j = _add_mixed(
        gx_b, gy_b, one_m, qx_m, qy_m, q_inf, mod_p)
    tx, ty, t_inf = _to_affine(tx_j, ty_j, tz_j, mod_p)

    # Joint double-and-add, MSB first: R = 2R; R += [G | Q | G+Q].
    zero = jnp.zeros_like(qx_m)
    nbits = ops.nbits

    def body(i, carry):
        x, y, z = carry
        k = nbits - 1 - i
        b1 = bigint.bit_at(u1, k)
        b2 = bigint.bit_at(u2, k)
        sel = b1 + 2 * b2  # 0:none 1:G 2:Q 3:G+Q
        ax = _sel(sel == 1, gx_b, _sel(sel == 2, qx_m, tx))
        ay = _sel(sel == 1, gy_b, _sel(sel == 2, qy_m, ty))
        a_inf = jnp.where(sel == 3, t_inf, sel == 0)
        x, y, z = _dbl(x, y, z, mod_p)
        x, y, z = _add_mixed(x, y, z, ax, ay, a_inf, mod_p)
        return x, y, z

    rx, ry, rz = jax.lax.fori_loop(
        0, nbits, body, (zero, zero, jnp.zeros_like(qx_m))
    )

    r_inf = is_zero(rz)
    ax, _ay, _ = _to_affine(rx, ry, rz, mod_p)
    x_aff = from_mont(ax, mod_p)  # canonical x_R < p
    # x_R mod n: p < 2n for both NIST curves, one conditional subtract.
    v = mod_reduce_once(x_aff, ops.mod_n)
    return ok & ~r_inf & eq(v, bytes_to_limbs(r))


def verify_p256_core(digest, r, s, qx, qy, valid):
    """Batched legacy ECDSA-P256 verify over byte rows.

    digest/r/s/qx/qy: uint8[B, 32] big-endian; valid: bool[B] (invalid
    lanes short to False without influencing anything). → bool[B].
    """
    return _verify_jacobian(P256_OPS, digest, r, s, qx, qy, valid)


verify_p256_jit = jax.jit(verify_p256_core)

_JACOBIAN_JITS: dict[str, object] = {"p256": verify_p256_jit}


def jacobian_jit(ops: CurveOps):
    """The jitted window = 0 ladder for ``ops`` (cached per curve)."""
    f = _JACOBIAN_JITS.get(ops.name)
    if f is None:
        f = jax.jit(functools.partial(_verify_jacobian, ops))
        _JACOBIAN_JITS[ops.name] = f
    return f


# -- complete projective addition (round 17) -----------------------------

def _madd_complete(ops: CurveOps, x1, y1, z1, x2, y2):
    """COMPLETE projective mixed addition, a = -3 (Renes–Costello–
    Batina 2015, Alg. 5 — the formulas behind Go's crypto nistec
    P-256). P1 = (X:Y:Z) homogeneous projective, ANY point including
    the identity (0:1:0); P2 = (x2, y2) an affine curve point (never
    the identity — callers select zero digits away). No exceptional
    cases: P1 = ±P2 and P1 = ∞ all flow through the same 13
    multiplies, which is what lets the windowed ladder drop the
    per-add doubling fallback the Jacobian formulation pays."""
    mod = ops.mod_p

    def mul(a, b):
        return mont_mul(a, b, mod)

    def add(a, b):
        return add_mod(a, b, mod)

    def sub(a, b):
        return sub_mod(a, b, mod)

    b_c = jnp.broadcast_to(jnp.asarray(ops.b_m), x1.shape)
    t0 = mul(x1, x2)
    t1 = mul(y1, y2)
    t3 = sub(sub(mul(add(x2, y2), add(x1, y1)), t0), t1)  # x1y2+x2y1
    t4 = add(mul(y2, z1), y1)  # y1 + y2·z1
    ty = add(mul(x2, z1), x1)  # x1 + x2·z1
    bz = mul(b_c, z1)
    x3 = sub(ty, bz)
    x3 = add(x3, add(x3, x3))  # 3(ty - b·z1)
    z3t = sub(t1, x3)
    x3t = add(t1, x3)
    y3 = mul(b_c, ty)
    z1_3 = add(add(z1, z1), z1)
    y3 = sub(sub(y3, z1_3), t0)
    y3 = add(y3, add(y3, y3))  # 3(b·ty - 3z1 - t0)
    t0n = sub(add(add(t0, t0), t0), z1_3)  # 3t0 - 3z1
    xo = sub(mul(t3, x3t), mul(t4, y3))
    yo = add(mul(x3t, z3t), mul(t0n, y3))
    zo = add(mul(t4, z3t), mul(t3, t0n))
    return xo, yo, zo


# -- window tables (host-built through the reference curve math) ---------

_TABLE_LOCK = threading.Lock()  # one precompute-table build at a time
_GTABLES: dict[tuple[str, int], object] = {}  # (curve, w) → device tab
_QTABLES: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
_QTABLE_NP_CAP = 128  # host-side np table LRU bound (per process)


def _jac_add(p: int, P1, P2):
    """Host Jacobian addition over python ints; Z = 0 is infinity.
    General and total — doubling and cancellation resolve inline, so
    degenerate (off-curve) bases still produce well-defined output."""
    x1, y1, z1 = P1
    x2, y2, z2 = P2
    if z1 == 0:
        return P2
    if z2 == 0:
        return P1
    z1s = z1 * z1 % p
    z2s = z2 * z2 % p
    u1 = x1 * z2s % p
    u2 = x2 * z1s % p
    s1 = y1 * z2s * z2 % p
    s2 = y2 * z1s * z1 % p
    h = (u2 - u1) % p
    r = (s2 - s1) % p
    if h == 0:
        if r != 0:
            return (1, 1, 0)  # P = -Q
        # P = Q: double (a = -3)
        ys = y1 * y1 % p
        s = 4 * x1 * ys % p
        m = (3 * x1 * x1 - 3 * z1s * z1s) % p
        x3 = (m * m - 2 * s) % p
        return (x3, (m * (s - x3) - 8 * ys * ys) % p, 2 * y1 * z1 % p)
    hs = h * h % p
    hc = h * hs % p
    v = u1 * hs % p
    x3 = (r * r - hc - 2 * v) % p
    return (x3, (r * (v - x3) - s1 * hc) % p, z1 * z2 * h % p)


def _limbs_mont(v: int, nl: int) -> np.ndarray:
    """int (already Montgomery-reduced) → uint32[nl] 16-bit limbs —
    the builder's fast path (bytes view, no per-limb python loop)."""
    return np.frombuffer(
        v.to_bytes(2 * nl, "little"), "<u2").astype(np.uint32)


def point_table_np(curve: vhost.Curve, x: int, y: int,
                   window: int) -> np.ndarray:
    """Fixed-base window table for base point (x, y): entry [j][d] is
    the Montgomery-domain affine point d·2^(w·j)·(x, y), d ∈ [1, 2^w);
    entry [j][0] is zeros (the identity — kernels select it away).

    Built host-side from the reference curve constants with Jacobian
    accumulation and ONE batched inversion for the whole table (the
    same prefix-product→Fermat→unwind shape the device kernel uses),
    so builds stay a fraction of a second per key. Independent
    derivability is pinned by test: entries equal
    ``verify/host._point_mul(curve, d << (w·j), (x, y))`` — the
    pure-python reference scalar multiplication.

    Invalid bases (off-curve registry keys, coordinates ≥ p) produce
    well-defined garbage: the lanes that would read such a table
    already failed the kernel's on-curve check, so the verdict is
    False regardless of table contents — same fail-closed shape as
    the round-13 kernel."""
    nl = curve.byte_len // 2
    nbits = bigint.RADIX * nl
    nwin = nbits // window
    r_mont = 1 << nbits
    p = curve.p
    tab = np.zeros((nwin, 1 << window, 2, nl), np.uint32)
    base = (x % p, y % p, 1)
    jac: list[tuple[int, tuple[int, int, int]]] = []  # (flat idx, point)
    for j in range(nwin):
        acc = (1, 1, 0)
        for d in range(1, 1 << window):
            acc = _jac_add(p, acc, base)
            if acc[2] != 0:
                jac.append((j * (1 << window) + d, acc))
        for _ in range(window):
            base = _jac_add(p, base, base)
    # One inversion for every entry's Z: exclusive prefix products,
    # one Fermat inversion of the total, reverse unwind.
    prefix = []
    total = 1
    for _, (_x, _y, z) in jac:
        prefix.append(total)
        total = total * z % p
    tinv = pow(total, p - 2, p)
    for k in range(len(jac) - 1, -1, -1):
        flat, (xj, yj, zj) = jac[k]
        zi = tinv * prefix[k] % p
        tinv = tinv * zj % p
        zi2 = zi * zi % p
        tab[flat >> window, flat & ((1 << window) - 1), 0] = \
            _limbs_mont(xj * zi2 % p * r_mont % p, nl)
        tab[flat >> window, flat & ((1 << window) - 1), 1] = \
            _limbs_mont(yj * zi2 % p * zi % p * r_mont % p, nl)
    return tab


def fixed_base_table(ops: CurveOps, window: int):
    """The shared device-resident u1·G table for (curve, window):
    built once per process, then cached. Returns ``(table,
    build_seconds)`` — build_seconds is 0.0 on a cache hit (callers
    emit the verify.precomp_build_s metric only on real builds)."""
    key = (ops.name, window)
    with _TABLE_LOCK:
        tab = _GTABLES.get(key)
        if tab is not None:
            return tab, 0.0
        t0 = time.perf_counter()
        np_tab = point_table_np(
            ops.curve, ops.curve.gx, ops.curve.gy, window)
        tab = jax.device_put(np_tab)
        _GTABLES[key] = tab
        return tab, time.perf_counter() - t0


def point_table_cached(ops: CurveOps, window: int, x: int, y: int):
    """Host-side np window table for an arbitrary base point,
    LRU-cached per process and keyed by coordinates — two registries
    (or registry epochs) that agree on a key's coordinates share the
    build. Returns ``(np_table, build_seconds)``."""
    key = (ops.name, window, x, y)
    with _TABLE_LOCK:
        tab = _QTABLES.get(key)
        if tab is not None:
            _QTABLES.move_to_end(key)
            return tab, 0.0
        t0 = time.perf_counter()
        tab = point_table_np(ops.curve, x, y, window)
        _QTABLES[key] = tab
        while len(_QTABLES) > _QTABLE_NP_CAP:
            _QTABLES.popitem(last=False)
        return tab, time.perf_counter() - t0


def zero_qtable(slots: int, nwin: int, entries: int, nl: int):
    """Fresh device-resident Q-table slot array (all identity)."""
    return jnp.zeros((slots, nwin, entries, 2, nl), jnp.uint32)


qtable_slot_set = jax.jit(lambda tab, slot, val: tab.at[slot].set(val))
"""Ship ONE key's window table into its LRU slot (slot is traced, so
one compile serves every slot; only the new table crosses H2D)."""


# -- windowed verification kernel ----------------------------------------

def _verify_windowed(ops: CurveOps, digest, r, s, qx, qy, valid,
                     key_idx, gtab, qtab):
    """Batched windowed-precompute ECDSA verify.

    digest/r/s/qx/qy: uint8[B, byte_len] big-endian (digest left-
    padded for P-384); valid: bool[B]; key_idx: int32[B] slot of each
    lane's Q table in ``qtab``; gtab: uint32[nwin, 2^w, 2, nl] (the
    fixed-base G table); qtab: uint32[K, nwin, 2^w, 2, nl]. → bool[B].

    The window size is static from gtab's shape, so one jit serves
    every window at a given (width, K) — recompiles stay log-bounded.
    """
    mod_p, mod_n = ops.mod_p, ops.mod_n
    ok, r_l, s_l, e_l, qx_m, qy_m = _check_inputs(
        ops, digest, r, s, qx, qy, valid)

    # s⁻¹ by batch inversion: ONE Fermat chain per batch; s = 0 lanes
    # (already ok = False) are masked through the product.
    s_m = to_mont(s_l, mod_n)
    w_m = bigint.batch_inv_mont(s_m, mod_n)
    u1, u2 = _scalars(ops, r_l, s_l, e_l, w_m)

    nwin = int(gtab.shape[0])
    w_bits = (int(gtab.shape[1]) - 1).bit_length()
    zero = jnp.zeros_like(qx_m)
    one_m = jnp.broadcast_to(jnp.asarray(mod_p.one_m), qx_m.shape)

    def add_entry(carry, point, dig):
        x, y, z = carry
        px = point[..., 0, :]
        py = point[..., 1, :]
        xn, yn, zn = _madd_complete(ops, x, y, z, px, py)
        keep = dig == 0  # digit 0 = identity: keep the accumulator
        return (_sel(keep, x, xn), _sel(keep, y, yn),
                _sel(keep, z, zn))

    def body(j, carry):
        d1 = bigint.window_digit(u1, j, w_bits)
        d2 = bigint.window_digit(u2, j, w_bits)
        g_j = jax.lax.dynamic_index_in_dim(
            gtab, j, 0, keepdims=False)  # [2^w, 2, nl]
        carry = add_entry(carry, jnp.take(g_j, d1, axis=0), d1)
        q_j = jax.lax.dynamic_index_in_dim(
            qtab, j, 1, keepdims=False)  # [K, 2^w, 2, nl]
        carry = add_entry(carry, q_j[key_idx, d2], d2)
        return carry

    rx, _ry, rz = jax.lax.fori_loop(
        0, nwin, body, (zero, one_m, jnp.zeros_like(qx_m))
    )

    r_inf = is_zero(rz)
    # x_R = X/Z (homogeneous projective): one batched inversion, zero
    # Z (infinity results) masked through the product.
    z_inv = bigint.batch_inv_mont(rz, mod_p)
    x_aff = from_mont(mont_mul(rx, z_inv, mod_p), mod_p)
    v = mod_reduce_once(x_aff, mod_n)
    return ok & ~r_inf & eq(v, r_l)


_WINDOWED_JITS: dict[str, object] = {}


def windowed_jit(ops: CurveOps):
    """The jitted windowed kernel for ``ops`` (cached per curve; the
    window/width/slot shapes specialize per call shape as usual)."""
    f = _WINDOWED_JITS.get(ops.name)
    if f is None:
        f = jax.jit(functools.partial(_verify_windowed, ops))
        _WINDOWED_JITS[ops.name] = f
    return f


def pad_width(n: int, min_width: int = 32) -> int:
    """Pow2-padded batch width (log-bounded compile shapes, like the
    aggregator's contains probes)."""
    return max(min_width, 1 << max(0, (max(n, 1) - 1).bit_length()))


# -- numpy convenience wrappers ------------------------------------------

def _pad_rows(a, width: int, byte_len: int):
    a = np.ascontiguousarray(np.asarray(a, np.uint8))
    if a.shape[1] < byte_len:  # left-pad short digests (P-384 lanes)
        a = np.pad(a, ((0, 0), (byte_len - a.shape[1], 0)))
    if a.shape[0] != width:
        a = np.pad(a, ((0, width - a.shape[0]), (0, 0)))
    return a


def verify_batch(ops: CurveOps, digest, r, s, qx, qy,
                 valid=None, window: int | None = None) -> np.ndarray:
    """Synchronous convenience verify: numpy byte rows in (digest may
    be shorter than byte_len — left-padded), bool[n] out. window
    resolves via :func:`resolve_precomp_window`; window = 0 runs the
    legacy Jacobian ladder. The windowed path groups lanes by unique
    public key and builds/caches the per-key tables host-side — the
    ingest lane keeps its own persistent device-resident cache
    (verify/lane.py) instead of going through here."""
    window = resolve_precomp_window(window)
    n = int(digest.shape[0])
    width = pad_width(n)
    bl = ops.byte_len
    v = (np.ones((n,), bool) if valid is None
         else np.asarray(valid, bool))
    v = np.pad(v, (0, width - n))
    args = [_pad_rows(a, width, bl) for a in (digest, r, s, qx, qy)]
    if window == 0:
        out = jacobian_jit(ops)(*args, v)
        return np.asarray(out)[:n]

    gtab, _ = fixed_base_table(ops, window)
    qx_p, qy_p = args[3], args[4]
    slots: dict[tuple[int, int], int] = {}
    key_idx = np.zeros((width,), np.int32)
    tabs: list[np.ndarray] = []
    c = ops.curve
    for i in range(n):
        kx = int.from_bytes(qx_p[i].tobytes(), "big")
        ky = int.from_bytes(qy_p[i].tobytes(), "big")
        # Lanes whose key fails the kernel's own range/on-curve checks
        # are False regardless of ladder output — don't build tables
        # for them (mutation-fuzz corpora are mostly such keys).
        if not (kx < c.p and ky < c.p and (kx or ky)
                and (ky * ky - kx * kx * kx - c.a * kx - c.b) % c.p
                == 0):
            continue
        slot = slots.get((kx, ky))
        if slot is None:
            slot = len(tabs)
            slots[(kx, ky)] = slot
            tabs.append(point_table_cached(ops, window, kx, ky)[0])
        key_idx[i] = slot
    k_pad = max(MIN_QTABLE_SLOTS, pad_width(len(tabs), 1))
    nl = ops.mod_p.nlimb
    qtab = np.zeros((k_pad, ops.nbits // window, 1 << window, 2, nl),
                    np.uint32)
    if tabs:
        qtab[: len(tabs)] = np.stack(tabs)
    out = windowed_jit(ops)(*args, v, key_idx, gtab, qtab)
    return np.asarray(out)[:n]


def verify_p256(digest: np.ndarray, r: np.ndarray, s: np.ndarray,
                qx: np.ndarray, qy: np.ndarray,
                valid: np.ndarray | None = None,
                window: int | None = None) -> np.ndarray:
    """Batched ECDSA-P256 verify over 32-byte rows → bool[n]."""
    return verify_batch(P256_OPS, digest, r, s, qx, qy, valid, window)


def verify_p384(digest: np.ndarray, r: np.ndarray, s: np.ndarray,
                qx: np.ndarray, qy: np.ndarray,
                valid: np.ndarray | None = None,
                window: int | None = None) -> np.ndarray:
    """Batched ECDSA-P384 verify over 48-byte rows (the 32-byte
    SHA-256 digest is left-padded) → bool[n]."""
    return verify_batch(P384_OPS, digest, r, s, qx, qy, valid, window)


def verify_p256_submit(digest, r, s, qx, qy, valid=None):
    """Legacy-ladder dispatch WITHOUT readback: returns
    ``(device_verdicts, n)`` — the caller slices ``[:n]`` after the
    (blocking) ``np.asarray``. JAX dispatch is asynchronous, so the
    device chews on the batch while the host stages the next one (the
    pipelining contract of the ingest sink's pendings)."""
    n = int(digest.shape[0])
    width = pad_width(n)
    v = (np.ones((n,), bool) if valid is None
         else np.asarray(valid, bool))
    v = np.pad(v, (0, width - n))
    out = verify_p256_jit(
        *[_pad_rows(a, width, 32) for a in (digest, r, s, qx, qy)], v)
    return out, n
