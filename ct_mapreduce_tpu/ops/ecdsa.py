"""Batched ECDSA-P256 verification as a pure-JAX op.

``verify_p256`` checks one signature per lane — digests, signature
scalars and public keys as big-endian byte rows — entirely on device:
scalar inversion by Fermat, Shamir's double-scalar multiplication
u1·G + u2·Q in Jacobian coordinates over the Montgomery-domain field
ops of :mod:`ct_mapreduce_tpu.ops.bigint`, and the r ≡ x_R (mod n)
check. All uint32 lane arithmetic, vectorized over the batch axis like
the SHA-256 kernel — the batched-limb shape of the FPGA ECDSA engine
(arxiv 2112.02229).

Verdict contract: a lane's verdict is the mathematical ECDSA verdict —
bit-identical to the pure-python reference verifier
(:mod:`ct_mapreduce_tpu.verify.host`) on EVERY input, adversarial ones
included. Exceptional group-law cases (P = ±Q inside the ladder,
points at infinity) are handled by explicit selects, not assumed away;
invalid-range inputs (r/s ∉ [1, n-1], pubkey off-curve or out of
range) fail closed. The kernel never *decides* which lanes it should
see — routing (P-256 vs odd curves vs RSA) is the extractor's job,
mirroring the walker-fallback pattern.

The ladder is a ``fori_loop`` over the 256 scalar bits (one traced
iteration, like ``preparsed_core``'s chunk loop), so batches compile
once per width and per-lane cost amortizes the fixed per-op XLA
dispatch overhead across the batch — the whole point of the wide lane
formulation (tools/stagecost.py's ``verify`` stage records the curve).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ct_mapreduce_tpu.ops import bigint
from ct_mapreduce_tpu.ops.bigint import (
    P256_N,
    P256_P,
    add_mod,
    bytes_to_limbs,
    eq,
    from_mont,
    geq,
    is_zero,
    mod_reduce_once,
    mont_inv,
    mont_mul,
    mont_sqr,
    sub_mod,
    to_mont,
)

# Curve constants (b, G) as host limbs; Montgomery domain where used.
P256_B_INT = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
P256_GX_INT = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
P256_GY_INT = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

_R = 1 << 256
_B_M = bigint.limbs_from_int(P256_B_INT * _R % bigint.P256_P_INT)
_GX_M = bigint.limbs_from_int(P256_GX_INT * _R % bigint.P256_P_INT)
_GY_M = bigint.limbs_from_int(P256_GY_INT * _R % bigint.P256_P_INT)


def _mulp(a, b):
    return mont_mul(a, b, P256_P)


def _sqrp(a):
    return mont_sqr(a, P256_P)


def _addp(a, b):
    return add_mod(a, b, P256_P)


def _subp(a, b):
    return sub_mod(a, b, P256_P)


def _dbl(x1, y1, z1):
    """Jacobian doubling, a = -3 (dbl-2001-b). Z = 0 stays Z = 0, so
    infinity is preserved without a select."""
    delta = _sqrp(z1)
    gamma = _sqrp(y1)
    beta = _mulp(x1, gamma)
    t0 = _subp(x1, delta)
    t1 = _addp(x1, delta)
    alpha = _mulp(t0, t1)
    alpha = _addp(_addp(alpha, alpha), alpha)  # 3·(x-δ)(x+δ)
    b2 = _addp(beta, beta)
    b4 = _addp(b2, b2)
    b8 = _addp(b4, b4)
    x3 = _subp(_sqrp(alpha), b8)
    t2 = _addp(y1, z1)
    z3 = _subp(_subp(_sqrp(t2), gamma), delta)
    g2 = _sqrp(gamma)
    g8 = _addp(_addp(g2, g2), _addp(g2, g2))
    g8 = _addp(g8, g8)
    y3 = _subp(_mulp(alpha, _subp(b4, x3)), g8)
    return x3, y3, z3


def _sel(c, a, b):
    """Per-lane limb select: c bool[...], a/b uint32[..., 16]."""
    return jnp.where(c[..., None], a, b)


def _add_mixed(x1, y1, z1, x2, y2, q_inf):
    """Complete Jacobian + affine addition.

    Handles every exceptional case by select: P at infinity → Q,
    Q at infinity → P, P == Q → double, P == -Q → infinity. The
    general madd formulas are evaluated unconditionally (vector lanes
    are free); the selects pick the right answer per lane."""
    p_inf = is_zero(z1)
    z1z1 = _sqrp(z1)
    u2 = _mulp(x2, z1z1)
    s2 = _mulp(y2, _mulp(z1, z1z1))
    h = _subp(u2, x1)
    rr = _subp(s2, y1)
    hh = _sqrp(h)
    hhh = _mulp(h, hh)
    v = _mulp(x1, hh)
    x3 = _subp(_subp(_sqrp(rr), hhh), _addp(v, v))
    y3 = _subp(_mulp(rr, _subp(v, x3)), _mulp(y1, hhh))
    z3 = _mulp(z1, h)

    same_x = is_zero(h) & ~p_inf & ~q_inf
    dbl_case = same_x & is_zero(rr)
    neg_case = same_x & ~is_zero(rr)
    dx, dy, dz = _dbl(x1, y1, z1)

    zero = jnp.zeros_like(x1)
    one_m = jnp.broadcast_to(jnp.asarray(P256_P.one_m), x1.shape)
    x3 = _sel(dbl_case, dx, x3)
    y3 = _sel(dbl_case, dy, y3)
    z3 = _sel(dbl_case, dz, z3)
    z3 = _sel(neg_case, zero, z3)
    # P at infinity: result is Q (as Jacobian with Z = 1), unless Q is
    # infinity too. Q at infinity: result is P.
    x3 = _sel(p_inf, x2, x3)
    y3 = _sel(p_inf, y2, y3)
    z3 = _sel(p_inf, _sel(q_inf, zero, one_m), z3)
    x3 = _sel(q_inf & ~p_inf, x1, x3)
    y3 = _sel(q_inf & ~p_inf, y1, y3)
    z3 = _sel(q_inf & ~p_inf, z1, z3)
    return x3, y3, z3


def _to_affine(x, y, z):
    """Jacobian → affine (Montgomery domain); infinity → (0, 0, inf)."""
    inf = is_zero(z)
    zi = mont_inv(z, P256_P)
    zi2 = _sqrp(zi)
    ax = _mulp(x, zi2)
    ay = _mulp(y, _mulp(zi, zi2))
    return ax, ay, inf


def _on_curve(x_m, y_m):
    """y² == x³ - 3x + b (Montgomery domain)."""
    lhs = _sqrp(y_m)
    x3 = _mulp(_sqrp(x_m), x_m)
    x_3 = _addp(_addp(x_m, x_m), x_m)
    rhs = _addp(_subp(x3, x_3),
                jnp.broadcast_to(jnp.asarray(_B_M), x_m.shape))
    return eq(lhs, rhs)


def verify_p256_core(digest, r, s, qx, qy, valid):
    """Batched ECDSA-P256 verify over byte rows.

    digest/r/s/qx/qy: uint8[B, 32] big-endian; valid: bool[B] (invalid
    lanes short to False without influencing anything). → bool[B].
    """
    r_l = bytes_to_limbs(r)
    s_l = bytes_to_limbs(s)
    e_l = bytes_to_limbs(digest)
    qx_l = bytes_to_limbs(qx)
    qy_l = bytes_to_limbs(qy)

    n_b = jnp.broadcast_to(jnp.asarray(P256_N.n), r_l.shape)
    p_b = jnp.broadcast_to(jnp.asarray(P256_P.n), r_l.shape)
    ok = (
        valid
        & ~is_zero(r_l) & ~geq(r_l, n_b)
        & ~is_zero(s_l) & ~geq(s_l, n_b)
        & ~geq(qx_l, p_b) & ~geq(qy_l, p_b)
        & ~(is_zero(qx_l) & is_zero(qy_l))
    )
    qx_m = to_mont(qx_l, P256_P)
    qy_m = to_mont(qy_l, P256_P)
    ok = ok & _on_curve(qx_m, qy_m)

    # Scalars: w = s^-1 mod n; u1 = e·w; u2 = r·w (plain domain).
    # A zero s would make the inversion garbage — ok lanes exclude it,
    # and garbage scalars on dead lanes can't resurrect the verdict.
    s_m = to_mont(s_l, P256_N)
    w_m = mont_inv(s_m, P256_N)
    e_m = to_mont(mod_reduce_once(e_l, P256_N), P256_N)
    r_nm = to_mont(mod_reduce_once(r_l, P256_N), P256_N)
    u1 = from_mont(mont_mul(e_m, w_m, P256_N), P256_N)
    u2 = from_mont(mont_mul(r_nm, w_m, P256_N), P256_N)

    # Shamir precompute: T = G + Q (affine, per lane). Complete add
    # handles Q == ±G; T can be infinity (Q == -G).
    gx_b = jnp.broadcast_to(jnp.asarray(_GX_M), qx_m.shape)
    gy_b = jnp.broadcast_to(jnp.asarray(_GY_M), qy_m.shape)
    one_m = jnp.broadcast_to(jnp.asarray(P256_P.one_m), qx_m.shape)
    q_inf = jnp.zeros(ok.shape, bool)
    tx_j, ty_j, tz_j = _add_mixed(gx_b, gy_b, one_m, qx_m, qy_m, q_inf)
    tx, ty, t_inf = _to_affine(tx_j, ty_j, tz_j)

    # Joint double-and-add, MSB first: R = 2R; R += [G | Q | G+Q].
    zero = jnp.zeros_like(qx_m)

    def body(i, carry):
        x, y, z = carry
        k = 255 - i
        b1 = bigint.bit_at(u1, k)
        b2 = bigint.bit_at(u2, k)
        sel = b1 + 2 * b2  # 0:none 1:G 2:Q 3:G+Q
        ax = _sel(sel == 1, gx_b, _sel(sel == 2, qx_m, tx))
        ay = _sel(sel == 1, gy_b, _sel(sel == 2, qy_m, ty))
        a_inf = jnp.where(sel == 3, t_inf, sel == 0)
        x, y, z = _dbl(x, y, z)
        x, y, z = _add_mixed(x, y, z, ax, ay, a_inf)
        return x, y, z

    rx, ry, rz = jax.lax.fori_loop(
        0, 256, body, (zero, zero, jnp.zeros_like(qx_m))
    )

    r_inf = is_zero(rz)
    ax, _ay, _ = _to_affine(rx, ry, rz)
    x_aff = from_mont(ax, P256_P)  # canonical x_R < p
    # x_R mod n: p < 2n for P-256, one conditional subtract.
    v = mod_reduce_once(x_aff, P256_N)
    return ok & ~r_inf & eq(v, bytes_to_limbs(r))


verify_p256_jit = jax.jit(verify_p256_core)


def pad_width(n: int, min_width: int = 32) -> int:
    """Pow2-padded batch width (log-bounded compile shapes, like the
    aggregator's contains probes)."""
    return max(min_width, 1 << max(0, (max(n, 1) - 1).bit_length()))


def verify_p256(digest: np.ndarray, r: np.ndarray, s: np.ndarray,
                qx: np.ndarray, qy: np.ndarray,
                valid: np.ndarray | None = None) -> np.ndarray:
    """Synchronous convenience wrapper: numpy byte rows in, bool[n]
    out, padded to a pow2 width so compile shapes stay log-bounded.
    The ingest lane uses :func:`verify_p256_submit` instead (async
    dispatch, deferred readback)."""
    out, n = verify_p256_submit(digest, r, s, qx, qy, valid)
    return np.asarray(out)[:n]


def verify_p256_submit(digest, r, s, qx, qy, valid=None):
    """Dispatch the batched verify WITHOUT reading back: returns
    ``(device_verdicts, n)`` — the caller slices ``[:n]`` after the
    (blocking) ``np.asarray``. JAX dispatch is asynchronous, so the
    device chews on the batch while the host stages the next one (the
    pipelining contract of the ingest sink's pendings)."""
    n = int(digest.shape[0])
    width = pad_width(n)

    def prep(a):
        a = np.ascontiguousarray(np.asarray(a, np.uint8))
        if a.shape[0] != width:
            a = np.pad(a, ((0, width - a.shape[0]), (0, 0)))
        return a

    v = (np.ones((n,), bool) if valid is None
         else np.asarray(valid, bool))
    v = np.pad(v, (0, width - n))
    out = verify_p256_jit(
        prep(digest), prep(r), prep(s), prep(qx), prep(qy), v
    )
    return out, n
