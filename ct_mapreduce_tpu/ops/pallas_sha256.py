"""Pallas TPU kernel: batched single-block SHA-256 fingerprinting.

The XLA path (:mod:`ct_mapreduce_tpu.ops.sha256`) compiles the 64
compression rounds as a ``lax.scan`` with a rolling schedule — correct
and fast, but every round round-trips its [8, B] state through the
fusion boundary HBM traffic XLA chooses. This kernel keeps the entire
state and message schedule resident in VMEM for a tile of lanes and
runs all 64 rounds register-resident on the VPU: one HBM read of the
message block, one HBM write of the digest, nothing in between.

Layout: lanes ride the last (128-wide) axis. The [B, 16] message block
arrives transposed as [16, B]; per grid step the kernel sees a
[16, TILE] slice, state is an [8, TILE] VMEM scratch, and the rolling
16-entry schedule mutates the input tile in place.

Selection: :func:`ct_mapreduce_tpu.ops.sha256.sha256_fingerprint64`
dispatches here when ``CTMR_PALLAS=1`` and the backend is a TPU;
``interpret=True`` covers CPU tests (tests/test_pallas.py asserts
bit-equality with the XLA path and hashlib).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ct_mapreduce_tpu.ops.sha256 import _H0, _K

# Lanes per grid step. The r03 hardware number (0.50 ms @ 16,384 lanes)
# sits ~30x above the VPU's theoretical throughput for 64 unrolled
# rounds, which smells like per-grid-step overhead — CTMR_SHA_TILE
# exists so tools/sha_sweep.py can measure the tile curve on hardware
# (VMEM comfortably fits tiles up to ~16K: [16, T] block + [8, T] out
# + ~24 live [T] vectors ≈ 2.9 MB at T=8192).
LANE_TILE = 512  # shipped default: the r03-measured configuration


def lane_tile() -> int:
    """Effective lanes-per-grid-step: CTMR_SHA_TILE env override, else
    LANE_TILE (consumed by the sha256 dispatch gate too)."""
    import os

    raw = os.environ.get("CTMR_SHA_TILE", "")
    if not raw:
        return LANE_TILE
    try:
        tile = int(raw)
        if tile < 128 or tile % 128:
            raise ValueError
    except ValueError:
        import warnings

        warnings.warn(
            f"ignoring CTMR_SHA_TILE={raw!r} (want a multiple of 128); "
            f"using {LANE_TILE}", stacklevel=2)
        return LANE_TILE
    return tile


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _kernel(k_ref, h0_ref, block_ref, out_ref):
    """k_ref: uint32[64, 1] round constants; h0_ref: uint32[8, 1];
    block_ref: uint32[16, TILE]; out_ref: uint32[8, TILE].

    (Constants arrive as inputs — Pallas kernels cannot capture array
    constants from the enclosing trace.)

    The 64 rounds are UNROLLED in Python so every schedule access is a
    static index: Mosaic's TPU lowering has no dynamic_slice, which is
    what a fori_loop + dynamic_index_in_dim formulation requires (that
    variant lowers only in interpret mode — it is kept below as
    ``_kernel_looped`` because interpreting 64 unrolled rounds is
    orders of magnitude slower than interpreting one fori_loop). The
    rolling 16-entry schedule lives in a Python list of [TILE] vectors
    — all VMEM/VREG resident for the whole compression."""
    tile = block_ref.shape[1]
    w = [block_ref[i, :] for i in range(16)]
    a, b, c, d, e, f, g, h = (
        jnp.broadcast_to(h0_ref[i, :], (tile,)) for i in range(8)
    )
    for t in range(64):
        wt = w[t % 16]
        kt = k_ref[t, 0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g
        if t < 48:
            # Rolling schedule: W[t+16] replaces W[t] in place.
            w1, w9, w14 = w[(t + 1) % 16], w[(t + 9) % 16], w[(t + 14) % 16]
            sg0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> np.uint32(3))
            sg1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> np.uint32(10))
            w[t % 16] = wt + sg0 + w9 + sg1
    for i, v in enumerate((a, b, c, d, e, f, g, h)):
        out_ref[i, :] = v + jnp.broadcast_to(h0_ref[i, :], (tile,))


def _kernel_looped(k_ref, h0_ref, block_ref, out_ref):
    """fori_loop formulation — interpret-mode only (see `_kernel`)."""

    def round_body(t, carry):
        state, w = carry
        a, b, c, d, e, f, g, h = (state[i] for i in range(8))
        i0 = t % 16
        wt = jax.lax.dynamic_index_in_dim(w, i0, 0, keepdims=False)
        kt = jax.lax.dynamic_index_in_dim(
            k_ref[:], t, 0, keepdims=False
        )[0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        state = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g])
        w1 = jax.lax.dynamic_index_in_dim(w, (t + 1) % 16, 0, keepdims=False)
        w9 = jax.lax.dynamic_index_in_dim(w, (t + 9) % 16, 0, keepdims=False)
        w14 = jax.lax.dynamic_index_in_dim(w, (t + 14) % 16, 0, keepdims=False)
        sg0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> np.uint32(3))
        sg1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> np.uint32(10))
        w = jax.lax.dynamic_update_index_in_dim(w, wt + sg0 + w9 + sg1, i0, 0)
        return state, w

    w = block_ref[:]  # [16, TILE]
    tile = w.shape[1]
    init = jnp.broadcast_to(h0_ref[:], (8, tile))
    state, _ = jax.lax.fori_loop(0, 64, round_body, (init, w))
    out_ref[:] = init + state


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def _single_block_pallas(
    block: jax.Array, interpret: bool = False, tile: int = LANE_TILE
) -> jax.Array:
    b = block.shape[0]
    tile = min(tile, b)
    if b % tile:
        raise ValueError(f"batch {b} must divide by the lane tile {tile}")
    blk_t = block.astype(jnp.uint32).T  # [16, B]
    out = pl.pallas_call(
        _kernel_looped if interpret else _kernel,
        grid=(b // tile,),
        in_specs=[
            pl.BlockSpec((64, 1), lambda i: (0, 0)),  # K, replicated
            pl.BlockSpec((8, 1), lambda i: (0, 0)),  # H0, replicated
            pl.BlockSpec((16, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((8, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, b), jnp.uint32),
        interpret=interpret,
    )(
        jnp.asarray(_K).reshape(64, 1),
        jnp.asarray(_H0).reshape(8, 1),
        blk_t,
    )
    return out.T


def sha256_single_block_pallas(
    block: jax.Array, interpret: bool = False, tile: int | None = None
) -> jax.Array:
    """uint32[B, 16] pre-padded block → uint32[B, 8] digest.

    ``tile`` overrides the lanes-per-grid-step (default: CTMR_SHA_TILE
    env var, else LANE_TILE); must be a positive multiple of 128."""
    if tile is None:
        tile = lane_tile()
    elif tile < 128 or tile % 128:
        raise ValueError(f"tile must be a multiple of 128, got {tile}")
    return _single_block_pallas(block, interpret=interpret, tile=tile)


def sha256_fingerprint64_pallas(
    block: jax.Array, interpret: bool = False
) -> jax.Array:
    """Low 128 bits of the digest: uint32[B, 4] (dedup-key path)."""
    return sha256_single_block_pallas(block, interpret=interpret)[..., 4:]
