"""The flagship model: config → mesh → aggregator → ingest/drain.

This is the composition root for the device pipeline — the analog of
the reference's wired-up ``LogSyncEngine`` + ``FilesystemDatabase``
stack (/root/reference/engine/engine.go:19-48), but TPU-shaped: a
:class:`TpuAggregator` on one chip, a :class:`ShardedAggregator` over
a multi-device mesh, behind one interface the ingest sinks and CLIs
consume.
"""

from __future__ import annotations

import os
from datetime import datetime, timezone
from typing import Optional

from ct_mapreduce_tpu.agg.aggregator import AggregateSnapshot, TpuAggregator
from ct_mapreduce_tpu.config import CTConfig
from ct_mapreduce_tpu.parallel.mesh import make_mesh, parse_mesh_shape


def build_aggregator(config: CTConfig, mesh=None) -> TpuAggregator:
    """Pick the device path from config: a mesh with >1 device gets the
    sharded aggregator; otherwise single-chip. ``meshShape`` empty →
    all local devices on the ``shard`` axis."""
    import jax

    now = (
        datetime.fromtimestamp(0, tz=timezone.utc)
        if config.log_expired_entries
        else None
    )
    common = dict(
        capacity=1 << config.table_bits,
        batch_size=config.batch_size,
        cn_prefixes=tuple(config.issuer_cn_filters()),
        now=now,
        grow_at=config.table_grow_at,
        max_capacity=1 << config.table_max_bits,
    )
    if mesh is None:
        spec = parse_mesh_shape(config.mesh_shape)
        n_fixed = spec.fixed_size if -1 not in spec.axis_sizes else len(jax.devices())
        if n_fixed > 1:
            mesh = make_mesh(spec)
    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import Mesh

        from ct_mapreduce_tpu.agg.sharded import AXIS, mesh_capacity
        from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

        # The dedup's table/batch sharding is 1-D; flatten multi-axis
        # meshes (e.g. "data:4,expert:2") over the same devices.
        if len(mesh.axis_names) != 1:
            mesh = Mesh(mesh.devices.reshape(-1), (AXIS,))
        n = mesh.devices.size
        # Round capacity UP to a power-of-two-per-shard multiple, and
        # the batch up to a multiple of the mesh size.
        cap = mesh_capacity(n, 1 << config.table_bits)
        batch = -(-common["batch_size"] // n) * n
        return ShardedAggregator(
            mesh, **{**common, "capacity": cap, "batch_size": batch}
        )
    return TpuAggregator(**common)


class IngestModel:
    """Aggregator + snapshot lifecycle, as one object."""

    def __init__(self, aggregator: TpuAggregator, state_path: str = ""):
        self.aggregator = aggregator
        self.state_path = state_path

    @classmethod
    def from_config(cls, config: CTConfig, mesh=None) -> "IngestModel":
        agg = build_aggregator(config, mesh=mesh)
        model = cls(agg, state_path=config.agg_state_path)
        if model.state_path and os.path.exists(model.state_path):
            agg.load_checkpoint(model.state_path)
        return model

    def ingest(self, entries):
        return self.aggregator.ingest(entries)

    def drain(self) -> AggregateSnapshot:
        return self.aggregator.drain()

    def save(self) -> None:
        if self.state_path:
            self.aggregator.save_checkpoint(self.state_path)
