"""The flagship end-to-end pipeline ("model") assembled from config."""

from ct_mapreduce_tpu.models.ingest_model import IngestModel, build_aggregator

__all__ = ["IngestModel", "build_aggregator"]
