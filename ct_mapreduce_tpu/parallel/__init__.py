"""Mesh construction, shardings, and multi-host initialization.

The scale-out fabric of the framework: where the reference coordinates
many processes through Redis (/root/reference/coordinator/coordinator.go)
and serializes all shared state through one cache, this layer places
work on a ``jax.sharding.Mesh`` — batches sharded along the batch axis,
reduce state sharded by key — with XLA collectives over ICI doing the
communication, and ``jax.distributed`` + host-0 leadership replacing
the Redis election for multi-host runs.
"""

from ct_mapreduce_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    parse_mesh_shape,
)
from ct_mapreduce_tpu.parallel.distributed import (
    DistributedCoordinator,
    device_barrier,
    initialize_multihost,
    is_leader,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "parse_mesh_shape",
    "DistributedCoordinator",
    "device_barrier",
    "initialize_multihost",
    "is_leader",
]
