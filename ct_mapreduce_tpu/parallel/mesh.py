"""Device-mesh construction from configuration.

``meshShape`` config syntax: ``axis:size`` pairs, comma separated —
``"shard:8"``, ``"data:4,expert:2"``. Empty means one 1-D mesh named
``shard`` over every addressable device, matching
:data:`ct_mapreduce_tpu.agg.sharded.AXIS` (the dedup table's shard
axis). Sizes must multiply to ≤ the device count; a trailing ``:-1``
size means "whatever is left" (like a reshape wildcard).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

DEFAULT_AXIS = "shard"


@dataclass(frozen=True)
class MeshSpec:
    axis_names: tuple[str, ...]
    axis_sizes: tuple[int, ...]  # -1 = fill with remaining devices

    @property
    def fixed_size(self) -> int:
        return math.prod(s for s in self.axis_sizes if s > 0)

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        sizes = list(self.axis_sizes)
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one wildcard (-1) axis size")
        fixed = self.fixed_size
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed sizes {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed > n_devices:
            raise ValueError(
                f"mesh needs {fixed} devices, only {n_devices} available"
            )
        return tuple(sizes)


def parse_mesh_shape(spec: str) -> MeshSpec:
    if not spec.strip():
        return MeshSpec((DEFAULT_AXIS,), (-1,))
    names, sizes = [], []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"mesh axis {part!r} needs name:size")
        name, _, size = part.partition(":")
        names.append(name.strip())
        sizes.append(int(size))
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate mesh axis names in {spec!r}")
    return MeshSpec(tuple(names), tuple(sizes))


def make_mesh(spec: str | MeshSpec = "", devices=None):
    """Build the ``jax.sharding.Mesh`` for a config's ``meshShape``."""
    import jax
    from jax.sharding import Mesh

    if isinstance(spec, str):
        spec = parse_mesh_shape(spec)
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    n = math.prod(sizes)
    grid = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(grid, spec.axis_names)
