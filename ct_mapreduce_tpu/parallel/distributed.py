"""Multi-host initialization and TPU-native coordination.

The reference's scale-out control plane is Redis SETNX leader election
plus a polled start barrier (/root/reference/coordinator/
coordinator.go:44-138). The TPU-native equivalent (SURVEY.md §2.3 role
2):

- ``initialize_multihost`` wraps ``jax.distributed.initialize`` — the
  JAX runtime's coordination service IS the election (process 0 hosts
  the coordinator, everyone else connects to it over DCN);
- leadership is ``process_index == 0`` — deterministic, no contention,
  renewed implicitly by the runtime's health checks rather than a
  lease-renewal thread;
- the start barrier is a collective: an all-reduce over every
  addressable device rides ICI/DCN and unblocks all hosts at once,
  instead of followers polling Redis every 250 ms.

:class:`DistributedCoordinator` exposes the reference Coordinator's
interface (await_leader / await_start / send_start) on top of these so
callers can swap fabrics by construction alone.
"""

from __future__ import annotations

from typing import Optional


# Env vars whose presence signals a multi-host environment where
# argument-less jax.distributed.initialize() can autodetect peers.
_AUTODETECT_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "TPU_WORKER_HOSTNAMES",
)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up jax.distributed.

    Must run before any JAX computation (jax.distributed's own
    contract) — so this deliberately avoids jax.process_count() or any
    other backend-initializing call before initialize(). With explicit
    arguments it initializes directly; with none, it autodetects iff a
    multi-host environment variable is present, else stays local.
    No-ops when the distributed client already exists."""
    import os

    import jax

    if _already_initialized():
        return
    if coordinator_address is None and num_processes is None:
        if not any(os.environ.get(k) for k in _AUTODETECT_ENV):
            return  # single-host: no coordination service needed
        jax.distributed.initialize()
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _already_initialized() -> bool:
    from jax._src import distributed

    return distributed.global_state.client is not None


def kv_put(key: str, value: str) -> bool:
    """Publish a value on the jax.distributed coordination service's
    key-value store (the fleet coordinator's epoch/shutdown fabric on
    TPU pods). Returns False when no distributed client exists (single
    process) or the runtime lacks the KV API — callers degrade to
    local state. Overwrite is emulated by delete-then-set where the
    runtime forbids re-setting a key."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        return False
    try:
        delete = getattr(client, "key_value_delete", None)
        if delete is not None:
            try:
                delete(key)
            except Exception:
                pass  # absent key / runtime without delete semantics
        client.key_value_set(key, value)
        return True
    except Exception:
        return False


def kv_get(key: str) -> Optional[str]:
    """Non-blocking read of a coordination-service KV entry; None when
    absent, unreadable, or there is no distributed client."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        return None
    try_get = getattr(client, "key_value_try_get", None)
    if try_get is None:
        return None
    try:
        return try_get(key)
    except Exception:
        return None  # NotFound surfaces as an exception


def is_leader() -> bool:
    """Host-0 leadership — the fixed, contention-free analog of winning
    the SETNX election."""
    import jax

    return jax.process_index() == 0


def device_barrier(tag: str = "barrier") -> None:
    """Block until every process reaches the barrier: a 1-element
    psum over all devices forces a synchronizing collective."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    from ct_mapreduce_tpu.utils.jax_compat import shard_map

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("all",))

    @jax.jit
    def _reduce(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "all"),
            mesh=mesh,
            in_specs=P("all"),
            out_specs=P(),
        )(x)

    x = jax.device_put(
        jnp.ones((devices.size,), jnp.int32), NamedSharding(mesh, P("all"))
    )
    total = int(_reduce(x)[0])  # local slice is [1]; psum → replicated [1]
    if total != devices.size:
        raise RuntimeError(f"barrier psum returned {total} != {devices.size}")


class DistributedCoordinator:
    """Reference-Coordinator interface over jax.distributed.

    await_leader: returns host-0 status (no contention to win).
    send_start / await_start: both sides enter the device barrier — the
    leader's entry releases the followers, like publishing
    ``started-<id>`` does in the Redis protocol.
    """

    def __init__(self, name: str = "ct-fetch"):
        self.name = name
        self.is_leader = False
        self.identifier = ""

    def await_leader(self) -> bool:
        import jax

        self.is_leader = is_leader()
        self.identifier = f"jax-process-{jax.process_index()}"
        return self.is_leader

    def await_start(self, timeout_s: Optional[float] = None) -> None:
        if not self.identifier:
            raise RuntimeError("Must not call before await_leader completes")
        if self.is_leader:
            raise RuntimeError("Must not call unless we're a follower")
        if timeout_s is None:
            device_barrier(f"start-{self.name}")
            return
        # Collectives have no native timeout; honor the contract by
        # waiting on a worker thread. On expiry the thread (and its
        # pending collective) is abandoned — the caller is expected to
        # treat TimeoutError as fatal for this process, like the
        # reference's polled barrier timeout.
        import threading

        err: list[BaseException] = []

        def run():
            try:
                device_barrier(f"start-{self.name}")
            except BaseException as e:  # surfaced to the caller below
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            raise TimeoutError("start barrier")
        if err:
            raise err[0]

    def send_start(self) -> None:
        if not self.identifier:
            raise RuntimeError("Must not call before await_leader completes")
        if not self.is_leader:
            raise RuntimeError("Must not call unless we're leader")
        device_barrier(f"start-{self.name}")

    def close(self) -> None:
        pass
