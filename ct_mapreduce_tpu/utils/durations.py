"""Go-style duration strings ("15m", "125ms", "2h45m") ↔ seconds.

The reference's config directives take Go time.ParseDuration strings
(/root/reference/config/config.go:191-199, e.g. savePeriod "15m",
outputRefreshPeriod "125ms"); we accept the same syntax.
"""

from __future__ import annotations

import re

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_TOKEN = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")


def parse_duration(s: str) -> float:
    """Parse a Go duration string to seconds. Raises ValueError on junk."""
    s = s.strip()
    if not s:
        raise ValueError("empty duration")
    neg = s.startswith("-")
    if neg or s.startswith("+"):
        s = s[1:]
    if s == "0":
        return 0.0
    total = 0.0
    pos = 0
    for m in _TOKEN.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {s!r}")
    return -total if neg else total


def format_duration(seconds: float) -> str:
    """Render seconds as a compact Go-style duration string."""
    if seconds == 0:
        return "0s"
    neg = seconds < 0
    seconds = abs(seconds)
    parts = []
    for unit, size in (("h", 3600.0), ("m", 60.0)):
        if seconds >= size:
            n = int(seconds // size)
            parts.append(f"{n}{unit}")
            seconds -= n * size
    if seconds >= 1:
        s = f"{seconds:.9f}".rstrip("0").rstrip(".")
        parts.append(f"{s}s")
    elif seconds > 0:
        ms = seconds * 1000
        s = f"{ms:.6f}".rstrip("0").rstrip(".")
        parts.append(f"{s}ms")
    elif not parts:
        parts.append("0s")
    return ("-" if neg else "") + "".join(parts)
