"""A minimal in-process RESP2 server for exercising the socket client.

The reference gates its Redis tier on a real server being present
(/root/reference/storage/rediscache_test.go:16-28); this image has no
redis-server and no network egress, so that tier would never run. This
server implements exactly the command surface RedisCache
(ct_mapreduce_tpu/storage/rediscache.py) speaks — sets, TTLs, queues
with blocking pop, SET NX PX, SCAN/SSCAN cursors, INFO memory — with
REAL Redis semantics (BRPOPLPUSH pops the source tail and pushes the
destination head; SADD returns the number of new members; expiry is
lazy), so the live tier runs by default and a genuine server can still
be swapped in via ``RedisHost``.

Test-support knobs the real server can't offer:
- ``scan_duplicate=True`` replays one member per SSCAN page, modeling
  Redis's documented may-return-duplicates contract
  (/root/reference/storage/knowncertificates.go:66-68).
- ``set_oom(True)`` makes every allocating write return ``-OOM ...``,
  driving the client's fatal-on-OOM path (rediscache.go:57-65 parity).
- ``stop()``/``start()`` on the same port drives reconnect-after-kill.

NOT a Redis replacement: single-node, no persistence, no pub/sub, no
cluster, string-typed values only.
"""

from __future__ import annotations

import bisect
import fnmatch
import socket
import threading
import time


class _Conn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("client closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("client closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def read_command(self) -> list[str]:
        line = self.read_line()
        if not line.startswith(b"*"):
            raise ConnectionError(f"expected array, got {line!r}")
        n = int(line[1:])
        args = []
        for _ in range(n):
            hdr = self.read_line()
            if not hdr.startswith(b"$"):
                raise ConnectionError(f"expected bulk, got {hdr!r}")
            ln = int(hdr[1:])
            args.append(self.read_exact(ln).decode("latin-1"))
            self.read_exact(2)
        return args


def _bulk(s: str | None) -> bytes:
    if s is None:
        return b"$-1\r\n"
    raw = s.encode("latin-1")
    return b"$%d\r\n%s\r\n" % (len(raw), raw)


def _array(items: list[bytes]) -> bytes:
    return b"*%d\r\n%s" % (len(items), b"".join(items))


# Commands denied under OOM: real Redis only rejects commands flagged
# may-use-memory; memory-FREEING commands (DEL, SREM, LPOP, LREM,
# EXPIRE...) always succeed so clients can dig themselves out.
_OOM_DENIED = {"SADD", "RPUSH", "SET", "BRPOPLPUSH"}


class MiniRedis:
    def __init__(self, port: int = 0, scan_duplicate: bool = False,
                 maxmemory_policy: str = "noeviction"):
        self.port = port
        self.scan_duplicate = scan_duplicate
        self.maxmemory_policy = maxmemory_policy
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data: dict[str, object] = {}  # str | set[str] | list[str]
        self._expiry: dict[str, float] = {}
        self._oom = False
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._running = False

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "MiniRedis":
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(64)
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="miniredis-accept")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        """Kill the listener and every live connection (keeps data, so a
        later start() on the same port models a server restart)."""
        self._running = False
        if self._listener is not None:
            try:
                # Wake the thread blocked in accept() (plain close()
                # leaves it blocked and the port in LISTEN forever).
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        for sock in self._conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._conns.clear()
        with self._cond:
            self._cond.notify_all()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def set_oom(self, value: bool) -> None:
        self._oom = value

    # -- internals -------------------------------------------------------

    def _accept_loop(self) -> None:
        listener = self._listener
        while self._running and listener is not None:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            self._conns.append(sock)
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True, name="miniredis-conn")
            t.start()
            self._threads.append(t)

    def _serve(self, sock: socket.socket) -> None:
        conn = _Conn(sock)
        try:
            while self._running:
                args = conn.read_command()
                sock.sendall(self._dispatch(args))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _purge(self, key: str) -> None:
        exp = self._expiry.get(key)
        if exp is not None and time.time() >= exp:
            self._data.pop(key, None)
            self._expiry.pop(key, None)

    def _peek(self, key: str, kind: type) -> object:
        """Read without materializing (real Redis never creates a key
        on a read path): missing → fresh empty container NOT stored."""
        self._purge(key)
        val = self._data.get(key)
        if val is None:
            return kind()
        if not isinstance(val, kind):
            raise TypeError(key)
        return val

    def _mutate(self, key: str, kind: type) -> object:
        """Write path: materialize the container in _data."""
        self._purge(key)
        val = self._data.get(key)
        if val is None:
            val = kind()
            self._data[key] = val
        if not isinstance(val, kind):
            raise TypeError(key)
        return val

    def _drop_if_empty(self, key: str) -> None:
        """Real Redis deletes sets/lists that become empty."""
        val = self._data.get(key)
        if isinstance(val, (set, list)) and not val:
            del self._data[key]
            self._expiry.pop(key, None)

    def _dispatch(self, args: list[str]) -> bytes:
        cmd = args[0].upper()
        if self._oom and cmd in _OOM_DENIED:
            return (b"-OOM command not allowed when used memory > "
                    b"'maxmemory'.\r\n")
        with self._lock:
            try:
                return self._run(cmd, args[1:])
            except TypeError as err:
                return (b"-WRONGTYPE Operation against a key holding "
                        b"the wrong kind of value (%s)\r\n"
                        % str(err).encode("latin-1"))

    def _run(self, cmd: str, a: list[str]) -> bytes:  # noqa: C901
        if cmd == "PING":
            return b"+PONG\r\n"
        if cmd == "INFO":
            body = (f"# Memory\r\nused_memory:{len(self._data)}\r\n"
                    f"maxmemory_policy:{self.maxmemory_policy}\r\n")
            return _bulk(body)
        if cmd == "EXISTS":
            self._purge(a[0])
            return b":%d\r\n" % (1 if a[0] in self._data else 0)
        if cmd == "DEL":
            n = 0
            for key in a:
                self._purge(key)
                if self._data.pop(key, None) is not None:
                    n += 1
                self._expiry.pop(key, None)
            return b":%d\r\n" % n

        # -- sets --------------------------------------------------------
        if cmd == "SADD":
            s = self._mutate(a[0], set)
            added = sum(1 for m in a[1:] if m not in s)
            s.update(a[1:])
            return b":%d\r\n" % added
        if cmd == "SREM":
            s = self._peek(a[0], set)
            removed = sum(1 for m in a[1:] if m in s)
            s.difference_update(a[1:])
            self._drop_if_empty(a[0])
            return b":%d\r\n" % removed
        if cmd == "SISMEMBER":
            return b":%d\r\n" % (1 if a[1] in self._peek(a[0], set) else 0)
        if cmd == "SMEMBERS":
            return _array([_bulk(m) for m in sorted(self._peek(a[0], set))])
        if cmd == "SCARD":
            return b":%d\r\n" % len(self._peek(a[0], set))
        if cmd == "SSCAN":
            members = sorted(self._peek(a[0], set))
            return self._scan_page(members, a[1], a[2:])

        # -- TTLs --------------------------------------------------------
        if cmd == "EXPIRE":
            return self._set_expiry(a[0], time.time() + int(a[1]))
        if cmd == "EXPIREAT":
            return self._set_expiry(a[0], int(a[1]))

        # -- lists / queues ---------------------------------------------
        if cmd == "RPUSH":
            lst = self._mutate(a[0], list)
            lst.extend(a[1:])
            self._cond.notify_all()
            return b":%d\r\n" % len(lst)
        if cmd == "LPOP":
            lst = self._peek(a[0], list)
            if not lst:
                return _bulk(None)
            val = lst.pop(0)
            self._drop_if_empty(a[0])
            return _bulk(val)
        if cmd == "LLEN":
            return b":%d\r\n" % len(self._peek(a[0], list))
        if cmd == "LREM":
            lst = self._peek(a[0], list)
            # count 0: remove all occurrences (the only form the client uses)
            kept = [x for x in lst if x != a[2]]
            if a[0] in self._data:
                self._data[a[0]] = kept
            self._drop_if_empty(a[0])
            return b":%d\r\n" % (len(lst) - len(kept))
        if cmd == "BRPOPLPUSH":
            deadline = time.time() + float(a[2])
            while True:
                src = self._peek(a[0], list)
                if src:
                    # Real semantics: source TAIL → destination HEAD.
                    val = src.pop()
                    self._drop_if_empty(a[0])
                    self._mutate(a[1], list).insert(0, val)
                    return _bulk(val)
                if not self._running:
                    return _bulk(None)
                remaining = deadline - time.time()
                if remaining <= 0:
                    return _bulk(None)
                self._cond.wait(min(remaining, 0.25))

        # -- strings / SETNX / scan -------------------------------------
        if cmd == "SET":
            key, value, opts = a[0], a[1], [o.upper() for o in a[2:]]
            self._purge(key)
            if "NX" in opts and key in self._data:
                return _bulk(None)
            self._data[key] = value
            self._expiry.pop(key, None)
            for i, o in enumerate(opts):
                if o == "PX":
                    self._expiry[key] = time.time() + int(a[2 + i + 1]) / 1e3
                elif o == "EX":
                    self._expiry[key] = time.time() + int(a[2 + i + 1])
            return b"+OK\r\n"
        if cmd == "GET":
            self._purge(a[0])
            val = self._data.get(a[0])
            if val is not None and not isinstance(val, str):
                raise TypeError(a[0])
            return _bulk(val)
        if cmd == "SCAN":
            pattern = "*"
            rest = a[1:]
            for i, o in enumerate(rest):
                if o.upper() == "MATCH":
                    pattern = rest[i + 1]
            for key in list(self._data):
                self._purge(key)
            keys = sorted(k for k in self._data
                          if fnmatch.fnmatchcase(k, pattern))
            return self._scan_page(keys, a[0], a[1:])

        return b"-ERR unknown command '%s'\r\n" % cmd.encode("latin-1")

    def _set_expiry(self, key: str, when: float) -> bytes:
        self._purge(key)
        if key not in self._data:
            return b":0\r\n"
        self._expiry[key] = when
        return b":1\r\n"

    def _scan_page(self, items: list[str], cursor: str,
                   opts: list[str]) -> bytes:
        count = 10
        for i, o in enumerate(opts):
            if o.upper() == "COUNT":
                count = int(opts[i + 1])
        if self.scan_duplicate:
            # Force multi-page cursoring so the duplicate replay below
            # actually happens regardless of the client's COUNT hint
            # (COUNT is advisory in Redis anyway).
            count = min(count, 16)
        # The cursor names the last member returned ("1:<member>"), not a
        # numeric index: a deletion between pages must not shift later
        # members past the cursor (Redis guarantees elements present for
        # the whole scan are returned at least once). Clients treat the
        # cursor as opaque, comparing only against "0" — as real Redis
        # requires.
        if cursor == "0":
            start, prev = 0, None
        else:
            prev = cursor[2:]
            start = bisect.bisect_right(items, prev)
        page = items[start:start + count]
        next_cursor = "0" if not page or start + count >= len(items) \
            else "1:" + page[-1]
        if self.scan_duplicate and prev is not None and items:
            # Model Redis's may-return-duplicates contract: replay the
            # last member of the previous page at the head of this one.
            page = [prev] + page
        return _array([_bulk(next_cursor),
                       _array([_bulk(m) for m in page])])
