"""Small shared helpers: Go-style duration strings, jittered backoff."""

from ct_mapreduce_tpu.utils.durations import (  # noqa: F401
    format_duration,
    parse_duration,
)
from ct_mapreduce_tpu.utils.backoff import JitteredBackoff  # noqa: F401
