"""Version portability shims for jax APIs the pipeline depends on.

The sharded step is written against the stable ``jax.shard_map``
(jax >= 0.6); older runtimes (0.4.x, e.g. the CI container) only carry
``jax.experimental.shard_map.shard_map`` with the pre-rename
``check_rep`` keyword. One call-site-compatible wrapper keeps the agg
and parallel layers off version probes.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    import jax

    stable = getattr(jax, "shard_map", None)
    if stable is not None:
        return stable(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as experimental

    return experimental(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=check_vma)
