"""Synthetic certificate streams for benchmarks and dry runs.

The reference generates fixtures on the fly with Go's stdlib x509
(``makeCert``, /root/reference/storage/issuermetadata_test.go:62-98).
Signing a fresh key pair per certificate is far too slow for
millions-of-entries benchmark replays, so this module builds ONE real
signed template per issuer (via ``cryptography``) and then stamps out
arbitrarily many structurally-valid variants by patching the serial
INTEGER bytes in place — the parse/filter/fingerprint/dedup pipeline
never verifies signatures, exactly like the reference's ingest path
(/root/reference/cmd/ct-fetch/ct-fetch.go:198-226 parses, never
verifies chains).

Serials are fixed-length with a constant positive first byte, so DER
lengths never change and every variant remains canonical DER.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass

import numpy as np

from ct_mapreduce_tpu.core import der as hostder

SERIAL_LEN = 16  # bytes of DER INTEGER content in the template


@dataclass
class CertTemplate:
    """A signed leaf template whose serial window can be restamped."""

    leaf_der: bytes
    issuer_der: bytes
    serial_off: int  # offset of the serial content bytes in leaf_der
    serial_len: int


def _build_pair(
    issuer_cn: str,
    not_after: datetime.datetime,
    crl_dp: str | None,
    key_type: str = "ec",
    serial_len: int = SERIAL_LEN,
    rich_extensions: bool = False,
) -> tuple[bytes, bytes]:
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec, rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        # Hosts without the cryptography package (some CI containers)
        # fall back to the hand-assembled canonical-DER builder: same
        # parse/filter/fingerprint behavior, synthetic signature bytes
        # (nothing on the ingest path verifies). Row-size realism is
        # approximated with opaque extension padding.
        return _build_pair_minicert(
            issuer_cn, not_after, crl_dp, key_type=key_type,
            serial_len=serial_len, rich_extensions=rich_extensions)

    # Real CT logs are RSA-dominated (~1.2-1.9 KB DER vs ~0.8 KB for
    # ECDSA P-256): RSA templates exist so benchmarks can measure the
    # realistic row-bytes regime, not just the friendly one.
    if key_type == "rsa2048":
        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    elif key_type == "ec":
        key = ec.generate_private_key(ec.SECP256R1())
    else:
        raise ValueError(f"unknown key_type {key_type!r} (ec | rsa2048)")
    issuer_name = x509.Name(
        [
            x509.NameAttribute(NameOID.COUNTRY_NAME, "US"),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "Bench Org"),
            x509.NameAttribute(NameOID.COMMON_NAME, issuer_cn),
        ]
    )
    now = datetime.datetime(2024, 1, 1, tzinfo=datetime.timezone.utc)

    issuer_builder = (
        x509.CertificateBuilder()
        .subject_name(issuer_name)
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(1)
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
    )
    issuer_der = issuer_builder.sign(key, hashes.SHA256()).public_bytes(
        serialization.Encoding.DER
    )

    # Template serial: serial_len bytes, first byte 0x4D (positive, no
    # leading-zero trimming) so every restamp keeps identical DER shape.
    serial_int = int.from_bytes(b"\x4d" + b"\x00" * (serial_len - 1), "big")
    leaf_builder = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "bench.example.com")])
        )
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(serial_int)
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
    )
    if crl_dp:
        leaf_builder = leaf_builder.add_extension(
            x509.CRLDistributionPoints(
                [
                    x509.DistributionPoint(
                        full_name=[x509.UniformResourceIdentifier(crl_dp)],
                        relative_name=None,
                        reasons=None,
                        crl_issuer=None,
                    )
                ]
            ),
            critical=False,
        )
    if rich_extensions:
        # The production extension load (SAN, AIA, KU, EKU, SKI, AKI)
        # that puts real leaf certs in the 1.2-1.9 KB regime — the
        # walker's extension scan must be benchmarked against this
        # shape, not just the minimal template.
        leaf_builder = (
            leaf_builder
            .add_extension(
                x509.SubjectAlternativeName([
                    x509.DNSName("bench.example.com"),
                    x509.DNSName("www.bench.example.com"),
                    x509.DNSName("cdn.bench.example.com"),
                ]),
                critical=False,
            )
            .add_extension(
                x509.AuthorityInformationAccess([
                    x509.AccessDescription(
                        x509.oid.AuthorityInformationAccessOID.OCSP,
                        x509.UniformResourceIdentifier(
                            "http://ocsp.bench.example"),
                    ),
                    x509.AccessDescription(
                        x509.oid.AuthorityInformationAccessOID.CA_ISSUERS,
                        x509.UniformResourceIdentifier(
                            "http://ca.bench.example/issuer.crt"),
                    ),
                ]),
                critical=False,
            )
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True, key_encipherment=True,
                    content_commitment=False, data_encipherment=False,
                    key_agreement=False, key_cert_sign=False,
                    crl_sign=False, encipher_only=False,
                    decipher_only=False,
                ),
                critical=True,
            )
            .add_extension(
                x509.ExtendedKeyUsage([
                    x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                    x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH,
                ]),
                critical=False,
            )
            .add_extension(
                x509.SubjectKeyIdentifier.from_public_key(key.public_key()),
                critical=False,
            )
            .add_extension(
                x509.AuthorityKeyIdentifier.from_issuer_public_key(
                    key.public_key()),
                critical=False,
            )
            .add_extension(
                x509.CertificatePolicies([
                    x509.PolicyInformation(
                        x509.ObjectIdentifier("2.23.140.1.2.1"), None),
                ]),
                critical=False,
            )
            # Embedded SCT list stand-in (OID 1.3.6.1.4.1.11129.2.4.2):
            # CT leaves carry ~120 B per SCT; two logs' worth of opaque
            # bytes reproduces the real extension-scan workload.
            .add_extension(
                x509.UnrecognizedExtension(
                    x509.ObjectIdentifier("1.3.6.1.4.1.11129.2.4.2"),
                    bytes([0x04, 0xF6, 0x00, 0xF4]) + bytes(244),
                ),
                critical=False,
            )
        )
    leaf_der = leaf_builder.sign(key, hashes.SHA256()).public_bytes(
        serialization.Encoding.DER
    )
    return leaf_der, issuer_der


def _build_pair_minicert(
    issuer_cn: str,
    not_after: datetime.datetime,
    crl_dp: str | None,
    key_type: str = "ec",
    serial_len: int = SERIAL_LEN,
    rich_extensions: bool = False,
) -> tuple[bytes, bytes]:
    from ct_mapreduce_tpu.utils import minicert

    if key_type not in ("ec", "rsa2048"):
        raise ValueError(f"unknown key_type {key_type!r} (ec | rsa2048)")
    # Size realism without a signer: RSA-2048 leaves carry ~550 B more
    # key+signature DER than P-256; the production extension load adds
    # ~700 B (SAN/AIA/KU/EKU/SKI/AKI/policies/SCTs) — pad with one
    # opaque extension so row-byte-proportional code paths (narrow
    # pre-decode, H2D volume) see the same regime.
    extra = 0
    if key_type == "rsa2048":
        extra += 550
    if rich_extensions:
        extra += 700
    issuer_der = minicert.make_cert(
        serial=1, issuer_cn=issuer_cn, is_ca=True, not_after=not_after)
    leaf_der = minicert.make_cert(
        serial=0, issuer_cn=issuer_cn, subject_cn="bench.example.com",
        is_ca=False, not_after=not_after,
        crl_dps=(crl_dp,) if crl_dp else (),
        serial_len=serial_len, extra_ext_bytes=extra)
    return leaf_der, issuer_der


def make_template(
    issuer_cn: str = "Bench Issuer CA",
    not_after: datetime.datetime | None = None,
    crl_dp: str | None = "http://crl.bench.example/latest.crl",
    key_type: str = "ec",
    serial_len: int = SERIAL_LEN,
    rich_extensions: bool = False,
) -> CertTemplate:
    if not 8 <= serial_len <= 20:
        # < 8 leaves no room for the epoch+lane counter fields the
        # device stampers use; > 20 exceeds RFC 5280's serial bound.
        raise ValueError(f"serial_len {serial_len} outside 8..20")
    not_after = not_after or datetime.datetime(
        2031, 6, 15, tzinfo=datetime.timezone.utc
    )
    leaf_der, issuer_der = _build_pair(
        issuer_cn, not_after, crl_dp, key_type=key_type,
        serial_len=serial_len, rich_extensions=rich_extensions)
    fields = hostder.parse_cert(leaf_der)
    assert fields.serial_len == serial_len, fields.serial_len
    return CertTemplate(
        leaf_der=leaf_der,
        issuer_der=issuer_der,
        serial_off=fields.serial_off,
        serial_len=fields.serial_len,
    )


def stamp_serial(template: CertTemplate, counter: int) -> bytes:
    """One DER variant: template with serial content = 0x4D ‖ counter."""
    n = template.serial_len
    body = counter.to_bytes(n - 1, "big")
    der = bytearray(template.leaf_der)
    der[template.serial_off + 1 : template.serial_off + n] = body
    return bytes(der)


def stamp_batch_array(
    template: CertTemplate,
    start: int,
    batch: int,
    pad_len: int,
    rng_mix: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized restamp: uint8[batch, pad_len] data + int32 lengths.

    Serials are ``start..start+batch`` mixed with ``rng_mix`` so
    successive epochs produce disjoint serial spaces. This is the fast
    path for benchmark replay — no per-entry Python loop.
    """
    base = np.frombuffer(template.leaf_der, dtype=np.uint8)
    if base.size > pad_len:
        raise ValueError(f"template ({base.size}B) exceeds pad length {pad_len}")
    data = np.zeros((batch, pad_len), dtype=np.uint8)
    data[:, : base.size] = base[None, :]
    counters = (np.arange(start, start + batch, dtype=np.uint64)
                ^ np.uint64(rng_mix))
    # big-endian expansion of the counter into the low serial bytes
    # (8 of them, or serial_len - 1 for short serials — byte 0 stays
    # the fixed positive 0x4D either way)
    off = template.serial_off
    for i in range(min(8, template.serial_len - 1)):
        data[:, off + template.serial_len - 1 - i] = (
            (counters >> np.uint64(8 * i)) & np.uint64(0xFF)
        ).astype(np.uint8)
    lengths = np.full((batch,), base.size, dtype=np.int32)
    return data, lengths


def build_device_batches(
    template: CertTemplate,
    n_batches: int,
    batch: int,
    pad_len: int,
):
    """Synthesize resident batches ON DEVICE from the signed template.

    Returns ``(datas uint8[G, B, pad_len], lens int32[G, B])`` device
    arrays. A per-(batch, lane) uint32 counter (``g * batch + lane``,
    big-endian) is stamped into serial content bytes 12..16 — unique up
    to 2^32 lanes; bytes 4..8 are left zero for callers that restamp a
    per-sweep epoch on device (bench.py's mega_step). H2D traffic is
    one ~1 KB template row instead of gigabytes of host-stamped rows
    (on tunneled links the old upload took longer than the benchmark).
    """
    import jax
    import jax.numpy as jnp

    base = np.frombuffer(template.leaf_der, dtype=np.uint8)
    if base.size > pad_len:
        raise ValueError(f"template ({base.size}B) exceeds pad length {pad_len}")
    tlen = int(base.size)
    n = template.serial_len
    if n < 12:
        raise ValueError(
            f"serial_len {n} < 12: the lane counter (last 4 bytes) would "
            "collide with the epoch window (bytes 4..8); use the mixed "
            "builder for short serials")
    lane_cols = template.serial_off + np.arange(n - 4, n, dtype=np.int32)

    @jax.jit
    def build(base_row):
        row = jnp.zeros((pad_len,), jnp.uint8).at[:tlen].set(base_row)
        data = jnp.broadcast_to(row, (n_batches, batch, pad_len))
        cnt = (jnp.arange(n_batches, dtype=jnp.uint32)[:, None] * batch
               + jnp.arange(batch, dtype=jnp.uint32)[None, :])
        cb = jnp.stack(
            [(cnt >> 24) & 0xFF, (cnt >> 16) & 0xFF,
             (cnt >> 8) & 0xFF, cnt & 0xFF], axis=-1
        ).astype(jnp.uint8)
        return data.at[:, :, lane_cols].set(cb)

    datas = build(jax.device_put(base))
    lens = jnp.full((n_batches, batch), tlen, dtype=jnp.int32)
    return datas, lens


def make_wire_batch(
    templates: list[CertTemplate],
    start: int,
    n: int,
    ts_base: int = 1_700_000_000_000,
) -> tuple[list[str], list[str]]:
    """One get-entries response worth of RFC 6962 wire entries
    (base64 leaf_input / extra_data), entries alternating over
    ``templates`` with serials ``start..start+n``. Shared by the e2e
    benchmark leg and the decode-scaling probe so the two measure the
    SAME stream format.
    """
    import base64

    from ct_mapreduce_tpu.ingest import leaf as leaflib

    eds_cache = [
        base64.b64encode(
            leaflib.encode_extra_data([t.issuer_der])).decode()
        for t in templates
    ]
    lis, eds = [], []
    for j in range(n):
        k = j % len(templates)
        der = stamp_serial(templates[k], start + j)
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(der, ts_base + j)).decode())
        eds.append(eds_cache[k])
    return lis, eds


def zipf_weights(n: int, s: float = 1.1) -> np.ndarray:
    """Zipf issuer split (CT reality: a handful of CAs dominate)."""
    w = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** s
    return w / w.sum()


@dataclass
class MixedBatchSet:
    """Device-resident mixed-template batches + the per-lane stamping
    metadata benchmark steps need."""

    datas: "object"  # uint8[G, B, pad] device array
    lens: "object"  # int32[G, B] device array
    issuer_idx: np.ndarray  # int32[B] — registry index per lane
    epoch_cols: np.ndarray  # int32[B, 3] — serial bytes 1..4 per lane
    template_of: np.ndarray  # int32[B]
    templates: list  # list[CertTemplate]


def build_mixed_device_batches(
    templates: list[CertTemplate],
    weights: np.ndarray,
    n_batches: int,
    batch: int,
    pad_len: int,
    seed: int = 0,
) -> MixedBatchSet:
    """Resident batches mixing several templates (issuers, key types,
    serial lengths) in one device batch — the realistic-mix benchmark
    shape (real CT streams interleave RSA/ECDSA certs of many CAs,
    /root/reference/cmd/ct-fetch/ct-fetch.go:416-424).

    Stamping schema, uniform across serial lengths 8..20: serial
    content byte 0 stays the template's positive 0x4D; bytes 1..4 are
    the per-sweep epoch window (24 bits, restamped on device by the
    bench step via ``epoch_cols``); the LAST 4 bytes are the lane
    counter. Disjoint for every length >= 8.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    t_count = len(templates)
    if t_count < 1:
        raise ValueError("need at least one template")
    w = np.asarray(weights, np.float64)
    w = w / w.sum()
    template_of = rng.choice(t_count, size=batch, p=w).astype(np.int32)

    tpl_rows = np.zeros((t_count, pad_len), np.uint8)
    tpl_lens = np.zeros((t_count,), np.int32)
    ser_off = np.zeros((t_count,), np.int32)
    ser_len = np.zeros((t_count,), np.int32)
    for i, t in enumerate(templates):
        raw = np.frombuffer(t.leaf_der, dtype=np.uint8)
        if raw.size > pad_len:
            raise ValueError(
                f"template {i} ({raw.size}B) exceeds pad length {pad_len}")
        tpl_rows[i, : raw.size] = raw
        tpl_lens[i] = raw.size
        ser_off[i] = t.serial_off
        ser_len[i] = t.serial_len

    off_of = ser_off[template_of]  # int32[B]
    lane_cols = (off_of[:, None] + ser_len[template_of][:, None] - 4
                 + np.arange(4, dtype=np.int32)[None, :])  # [B, 4]
    epoch_cols = off_of[:, None] + np.arange(1, 4, dtype=np.int32)[None, :]

    @jax.jit
    def build(tpl_rows, template_of, lane_cols):
        data = tpl_rows[template_of]  # [B, pad] gather
        data = jnp.broadcast_to(data, (n_batches,) + data.shape)
        cnt = (jnp.arange(n_batches, dtype=jnp.uint32)[:, None] * batch
               + jnp.arange(batch, dtype=jnp.uint32)[None, :])
        cb = jnp.stack(
            [(cnt >> 24) & 0xFF, (cnt >> 16) & 0xFF,
             (cnt >> 8) & 0xFF, cnt & 0xFF], axis=-1
        ).astype(jnp.uint8)  # [G, B, 4]
        rows_ix = jnp.arange(batch, dtype=jnp.int32)[None, :, None]
        return data.at[
            jnp.arange(n_batches, dtype=jnp.int32)[:, None, None],
            rows_ix, lane_cols[None, :, :],
        ].set(cb)

    datas = build(jax.device_put(tpl_rows), jax.device_put(template_of),
                  jax.device_put(lane_cols))
    lens = jnp.broadcast_to(
        jnp.asarray(tpl_lens[template_of], dtype=jnp.int32)[None, :],
        (n_batches, batch))
    return MixedBatchSet(
        datas=datas,
        lens=lens,
        issuer_idx=template_of.copy(),
        epoch_cols=epoch_cols.astype(np.int32),
        template_of=template_of,
        templates=list(templates),
    )
