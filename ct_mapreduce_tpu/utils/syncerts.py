"""Synthetic certificate streams for benchmarks and dry runs.

The reference generates fixtures on the fly with Go's stdlib x509
(``makeCert``, /root/reference/storage/issuermetadata_test.go:62-98).
Signing a fresh key pair per certificate is far too slow for
millions-of-entries benchmark replays, so this module builds ONE real
signed template per issuer (via ``cryptography``) and then stamps out
arbitrarily many structurally-valid variants by patching the serial
INTEGER bytes in place — the parse/filter/fingerprint/dedup pipeline
never verifies signatures, exactly like the reference's ingest path
(/root/reference/cmd/ct-fetch/ct-fetch.go:198-226 parses, never
verifies chains).

Serials are fixed-length with a constant positive first byte, so DER
lengths never change and every variant remains canonical DER.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass

import numpy as np

from ct_mapreduce_tpu.core import der as hostder

SERIAL_LEN = 16  # bytes of DER INTEGER content in the template


@dataclass
class CertTemplate:
    """A signed leaf template whose serial window can be restamped."""

    leaf_der: bytes
    issuer_der: bytes
    serial_off: int  # offset of the serial content bytes in leaf_der
    serial_len: int


def _build_pair(
    issuer_cn: str,
    not_after: datetime.datetime,
    crl_dp: str | None,
) -> tuple[bytes, bytes]:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    issuer_name = x509.Name(
        [
            x509.NameAttribute(NameOID.COUNTRY_NAME, "US"),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "Bench Org"),
            x509.NameAttribute(NameOID.COMMON_NAME, issuer_cn),
        ]
    )
    now = datetime.datetime(2024, 1, 1, tzinfo=datetime.timezone.utc)

    issuer_builder = (
        x509.CertificateBuilder()
        .subject_name(issuer_name)
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(1)
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
    )
    issuer_der = issuer_builder.sign(key, hashes.SHA256()).public_bytes(
        serialization.Encoding.DER
    )

    # Template serial: SERIAL_LEN bytes, first byte 0x4D (positive, no
    # leading-zero trimming) so every restamp keeps identical DER shape.
    serial_int = int.from_bytes(b"\x4d" + b"\x00" * (SERIAL_LEN - 1), "big")
    leaf_builder = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "bench.example.com")])
        )
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(serial_int)
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
    )
    if crl_dp:
        leaf_builder = leaf_builder.add_extension(
            x509.CRLDistributionPoints(
                [
                    x509.DistributionPoint(
                        full_name=[x509.UniformResourceIdentifier(crl_dp)],
                        relative_name=None,
                        reasons=None,
                        crl_issuer=None,
                    )
                ]
            ),
            critical=False,
        )
    leaf_der = leaf_builder.sign(key, hashes.SHA256()).public_bytes(
        serialization.Encoding.DER
    )
    return leaf_der, issuer_der


def make_template(
    issuer_cn: str = "Bench Issuer CA",
    not_after: datetime.datetime | None = None,
    crl_dp: str | None = "http://crl.bench.example/latest.crl",
) -> CertTemplate:
    not_after = not_after or datetime.datetime(
        2031, 6, 15, tzinfo=datetime.timezone.utc
    )
    leaf_der, issuer_der = _build_pair(issuer_cn, not_after, crl_dp)
    fields = hostder.parse_cert(leaf_der)
    assert fields.serial_len == SERIAL_LEN, fields.serial_len
    return CertTemplate(
        leaf_der=leaf_der,
        issuer_der=issuer_der,
        serial_off=fields.serial_off,
        serial_len=fields.serial_len,
    )


def stamp_serial(template: CertTemplate, counter: int) -> bytes:
    """One DER variant: template with serial content = 0x4D ‖ counter."""
    body = counter.to_bytes(SERIAL_LEN - 1, "big")
    der = bytearray(template.leaf_der)
    der[template.serial_off + 1 : template.serial_off + SERIAL_LEN] = body
    return bytes(der)


def stamp_batch_array(
    template: CertTemplate,
    start: int,
    batch: int,
    pad_len: int,
    rng_mix: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized restamp: uint8[batch, pad_len] data + int32 lengths.

    Serials are ``start..start+batch`` mixed with ``rng_mix`` so
    successive epochs produce disjoint serial spaces. This is the fast
    path for benchmark replay — no per-entry Python loop.
    """
    base = np.frombuffer(template.leaf_der, dtype=np.uint8)
    if base.size > pad_len:
        raise ValueError(f"template ({base.size}B) exceeds pad length {pad_len}")
    data = np.zeros((batch, pad_len), dtype=np.uint8)
    data[:, : base.size] = base[None, :]
    counters = (np.arange(start, start + batch, dtype=np.uint64)
                ^ np.uint64(rng_mix))
    # big-endian expansion of the counter into the low 8 serial bytes
    off = template.serial_off
    for i in range(8):
        data[:, off + SERIAL_LEN - 1 - i] = (
            (counters >> np.uint64(8 * i)) & np.uint64(0xFF)
        ).astype(np.uint8)
    lengths = np.full((batch,), base.size, dtype=np.int32)
    return data, lengths


def build_device_batches(
    template: CertTemplate,
    n_batches: int,
    batch: int,
    pad_len: int,
):
    """Synthesize resident batches ON DEVICE from the signed template.

    Returns ``(datas uint8[G, B, pad_len], lens int32[G, B])`` device
    arrays. A per-(batch, lane) uint32 counter (``g * batch + lane``,
    big-endian) is stamped into serial content bytes 12..16 — unique up
    to 2^32 lanes; bytes 4..8 are left zero for callers that restamp a
    per-sweep epoch on device (bench.py's mega_step). H2D traffic is
    one ~1 KB template row instead of gigabytes of host-stamped rows
    (on tunneled links the old upload took longer than the benchmark).
    """
    import jax
    import jax.numpy as jnp

    base = np.frombuffer(template.leaf_der, dtype=np.uint8)
    if base.size > pad_len:
        raise ValueError(f"template ({base.size}B) exceeds pad length {pad_len}")
    tlen = int(base.size)
    lane_cols = template.serial_off + np.arange(12, 16, dtype=np.int32)

    @jax.jit
    def build(base_row):
        row = jnp.zeros((pad_len,), jnp.uint8).at[:tlen].set(base_row)
        data = jnp.broadcast_to(row, (n_batches, batch, pad_len))
        cnt = (jnp.arange(n_batches, dtype=jnp.uint32)[:, None] * batch
               + jnp.arange(batch, dtype=jnp.uint32)[None, :])
        cb = jnp.stack(
            [(cnt >> 24) & 0xFF, (cnt >> 16) & 0xFF,
             (cnt >> 8) & 0xFF, cnt & 0xFF], axis=-1
        ).astype(jnp.uint8)
        return data.at[:, :, lane_cols].set(cb)

    datas = build(jax.device_put(base))
    lens = jnp.full((n_batches, batch), tlen, dtype=jnp.int32)
    return datas, lens
