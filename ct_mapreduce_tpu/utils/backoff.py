"""Jittered exponential backoff for HTTP 429 handling.

Mirrors the reference's use of jpillora/backoff with Min=500ms,
Max=5min, jitter on (/root/reference/cmd/ct-fetch/ct-fetch.go:409-413).
"""

from __future__ import annotations

import random


class JitteredBackoff:
    def __init__(
        self,
        min_s: float = 0.5,
        max_s: float = 300.0,
        factor: float = 2.0,
        jitter: bool = True,
    ):
        self.min_s = min_s
        self.max_s = max_s
        self.factor = factor
        self.jitter = jitter
        self.attempt = 0

    def duration(self) -> float:
        """Next backoff delay in seconds; advances the attempt counter."""
        d = min(self.max_s, self.min_s * (self.factor**self.attempt))
        self.attempt += 1
        if self.jitter:
            d = random.uniform(self.min_s, d) if d > self.min_s else d
        return d

    def reset(self) -> None:
        self.attempt = 0
