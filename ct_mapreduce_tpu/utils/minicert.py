"""Dependency-free synthetic X.509: canonical DER, hand-assembled.

``syncerts`` signs one real template per issuer with the
``cryptography`` package — the right fixture for parity work, but a
hard dependency some deployment hosts (and the CI container) don't
carry. This module builds structurally-canonical certificates from
raw TLVs instead: every field the ingest pipeline reads (serial
INTEGER, issuer Name/CN, validity, SPKI bytes, BasicConstraints,
CRL distribution points) is real DER in the real places; only the
signature bytes are synthetic — which is exactly the contract of the
ingest path, which parses and never verifies
(/root/reference/cmd/ct-fetch/ct-fetch.go:198-226).

Used by the overlapped-ingest tests and bench.py's CPU smoke gate so
both run on any host; ``syncerts.make_template`` falls back to this
builder when ``cryptography`` is missing, keeping the e2e legs alive
there too. Issuer identity is SHA-256(SPKI), so each distinct
``issuer_cn`` gets a distinct deterministic SPKI point.
"""

from __future__ import annotations

import datetime
import hashlib

# OIDs (DER-encoded content bytes)
_OID_COUNTRY = bytes.fromhex("550406")
_OID_ORG = bytes.fromhex("55040a")
_OID_CN = bytes.fromhex("550403")
_OID_BASIC_CONSTRAINTS = bytes.fromhex("551d13")
_OID_CRLDP = bytes.fromhex("551d1f")
_OID_EC_PUBKEY = bytes.fromhex("2a8648ce3d0201")
_OID_P256 = bytes.fromhex("2a8648ce3d030107")
_OID_ECDSA_SHA256 = bytes.fromhex("2a8648ce3d040302")

SERIAL_FIRST_BYTE = 0x4D  # positive, no leading-zero trimming — stampable


def _oid(*arcs: int) -> bytes:
    """DER OID content bytes for an arbitrary arc sequence."""
    body = [bytes([40 * arcs[0] + arcs[1]])]
    for arc in arcs[2:]:
        groups = [arc & 0x7F]
        arc >>= 7
        while arc:
            groups.append((arc & 0x7F) | 0x80)
            arc >>= 7
        body.append(bytes(reversed(groups)))
    return b"".join(body)


def _tlv(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    if n < 0x100:
        return bytes([tag, 0x81, n]) + content
    if n < 0x10000:
        return bytes([tag, 0x82, n >> 8, n & 0xFF]) + content
    if n < 0x1000000:
        return bytes([tag, 0x83, n >> 16, (n >> 8) & 0xFF, n & 0xFF]) + content
    raise ValueError(f"TLV content too long: {n}")


def _name(cn: str, org: str = "Mini Cert Org", country: str = "US") -> bytes:
    # Same attribute order/types the cryptography-built fixtures use:
    # PrintableString country, UTF8String org/CN, one ATV per RDN.
    def atv(oid: bytes, value: str, string_tag: int) -> bytes:
        return _tlv(0x31, _tlv(0x30, _tlv(0x06, oid)
                               + _tlv(string_tag, value.encode("utf-8"))))

    return _tlv(0x30, atv(_OID_COUNTRY, country, 0x13)
                + atv(_OID_ORG, org, 0x0C) + atv(_OID_CN, cn, 0x0C))


def _time(dt: datetime.datetime) -> bytes:
    if dt.year < 2050:
        return _tlv(0x17, dt.strftime("%y%m%d%H%M%SZ").encode("ascii"))
    return _tlv(0x18, dt.strftime("%Y%m%d%H%M%SZ").encode("ascii"))


def _spki(seed: str) -> bytes:
    # A P-256-shaped uncompressed point with deterministic coordinate
    # bytes: SHA-256(SPKI) identity is stable per seed, distinct across
    # seeds. Never validated as a curve point (nothing verifies).
    point = (b"\x04"
             + hashlib.sha256(b"minicert-x:" + seed.encode()).digest()
             + hashlib.sha256(b"minicert-y:" + seed.encode()).digest())
    alg = _tlv(0x30, _tlv(0x06, _OID_EC_PUBKEY) + _tlv(0x06, _OID_P256))
    return _tlv(0x30, alg + _tlv(0x03, b"\x00" + point))


def _extension(oid: bytes, value_der: bytes, critical: bool = False) -> bytes:
    inner = _tlv(0x06, oid)
    if critical:
        inner += bytes([0x01, 0x01, 0xFF])
    inner += _tlv(0x04, value_der)
    return _tlv(0x30, inner)


def _basic_constraints(is_ca: bool) -> bytes:
    # cA DEFAULT FALSE is omitted in canonical DER.
    return _extension(
        _OID_BASIC_CONSTRAINTS,
        _tlv(0x30, bytes([0x01, 0x01, 0xFF]) if is_ca else b""),
        critical=True,
    )


def _crldp(urls: tuple[str, ...]) -> bytes:
    dps = b"".join(
        _tlv(0x30, _tlv(0xA0, _tlv(0xA0, _tlv(0x86, u.encode("ascii")))))
        for u in urls
    )
    return _extension(_OID_CRLDP, _tlv(0x30, dps))


def make_cert(
    serial: int = 1,
    issuer_cn: str = "Mini Issuer CA",
    subject_cn: str | None = None,
    org: str = "Mini Cert Org",
    country: str = "US",
    not_before: datetime.datetime | None = None,
    not_after: datetime.datetime | None = None,
    is_ca: bool = False,
    add_basic_constraints: bool = True,
    crl_dps: tuple[str, ...] = (),
    serial_len: int | None = 16,
    spki_seed: str | None = None,
    extra_ext_bytes: int = 0,
    extra_extensions: int = 0,
    extra_ext_size: int = 40,
    extras_first: bool = True,
) -> bytes:
    """One canonical-DER certificate.

    ``serial`` is stamped big-endian into ``serial_len - 1`` content
    bytes behind the fixed positive first byte, so every value keeps
    identical DER shape (the serial window is restampable, like
    syncerts templates); ``serial_len=None`` encodes it minimally
    instead, exactly as the ``cryptography`` builder does (leading
    0x00 pad iff the high bit is set). ``spki_seed`` defaults to the
    issuer CN — self-consistent chains fall out of using the same CN
    for leaf and issuer. ``extra_ext_bytes`` pads the extension list
    with one opaque private-arc extension (oversize fixtures, e.g. a
    >=2 MiB issuer); ``extra_extensions``/``extra_ext_size``/
    ``extras_first`` instead mirror tests/certgen.py's numbered
    UnrecognizedExtension padding (1.3.6.1.4.1.99999.i, payload
    verbatim as extnValue content, placed before or after
    BasicConstraints)."""
    utc = datetime.timezone.utc
    not_before = not_before or datetime.datetime(2024, 1, 1, tzinfo=utc)
    not_after = not_after or datetime.datetime(2031, 6, 15, tzinfo=utc)
    if serial_len is None:
        serial_body = serial.to_bytes(
            (serial.bit_length() + 8) // 8 or 1, "big")
    else:
        if not 2 <= serial_len <= 20:
            raise ValueError(f"serial_len {serial_len} outside 2..20")
        serial_body = bytes([SERIAL_FIRST_BYTE]) + serial.to_bytes(
            serial_len - 1, "big")

    sig_alg = _tlv(0x30, _tlv(0x06, _OID_ECDSA_SHA256))
    extras = b"".join(
        _extension(_oid(1, 3, 6, 1, 4, 1, 99999, i),
                   bytes([i & 0xFF]) * extra_ext_size)
        for i in range(extra_extensions)
    )
    exts = extras if extras_first else b""
    if add_basic_constraints:
        exts += _basic_constraints(is_ca)
    if not extras_first:
        exts += extras
    if crl_dps:
        exts += _crldp(tuple(crl_dps))
    if extra_ext_bytes:
        exts += _extension(
            bytes.fromhex("2b060104018f6501"),  # 1.3.6.1.4.1.2021.1-ish arc
            _tlv(0x04, b"\xeb" * extra_ext_bytes),
        )
    tbs = _tlv(0x30, b"".join([
        _tlv(0xA0, bytes([0x02, 0x01, 0x02])),  # [0] version v3
        _tlv(0x02, serial_body),
        sig_alg,
        _name(issuer_cn, org, country),
        _tlv(0x30, _time(not_before) + _time(not_after)),
        _name(subject_cn if subject_cn is not None else issuer_cn,
              org, country),
        _spki(spki_seed if spki_seed is not None else issuer_cn),
        # An empty extension list is omitted entirely (RFC 5280 wants
        # >= 1 entry; the cryptography builder omits it the same way).
        _tlv(0xA3, _tlv(0x30, exts)) if exts else b"",
    ]))
    # Synthetic ECDSA-SIG-shaped BIT STRING (never verified).
    sig = _tlv(0x03, b"\x00" + _tlv(0x30, _tlv(0x02, b"\x11" * 32)
                                    + _tlv(0x02, b"\x2f" * 32)))
    return _tlv(0x30, tbs + sig_alg + sig)


def make_sct_cert(
    serial: int = 1,
    issuer_cn: str = "Mini Issuer CA",
    subject_cn: str | None = None,
    sct_signer=None,
    sct_timestamp_ms: int = 1_700_000_000_000,
    sct_extensions: bytes = b"",
    corrupt_signature: bool = False,
    sct_issuer_der: bytes = b"",
    **kwargs,
) -> bytes:
    """A canonical-DER certificate with an embedded, genuinely-signed
    SCT (the round-13 verification fixtures). ``sct_signer`` defaults
    to a deterministic P-256 log key seeded by the issuer CN — same
    dependency-free contract as the rest of this module, so verify
    tests collect and pass on hosts without ``cryptography``.
    ``sct_issuer_der``: the issuing certificate whose SPKI hash the
    SCT signs (RFC 6962 issuer_key_hash); required when the cert will
    ride a pipeline lane that carries an issuer chain."""
    from ct_mapreduce_tpu.verify import sct as sctlib

    der = make_cert(serial=serial, issuer_cn=issuer_cn,
                    subject_cn=subject_cn, **kwargs)
    if sct_signer is None:
        sct_signer = sctlib.EcSctSigner(f"minicert-log:{issuer_cn}")
    return sctlib.attach_sct(
        der, sct_signer, sct_timestamp_ms, extensions=sct_extensions,
        corrupt_signature=corrupt_signature, issuer_der=sct_issuer_der,
    )


def make_ca_and_leaf(
    serial: int,
    issuer_cn: str = "Mini Issuer CA",
    subject_cn: str = "leaf.mini.example",
    crl_dps: tuple[str, ...] = (),
    serial_len: int = 16,
    not_after: datetime.datetime | None = None,
) -> tuple[bytes, bytes]:
    """(leaf_der, issuer_der) sharing the issuer's SPKI identity."""
    issuer = make_cert(serial=1, issuer_cn=issuer_cn, is_ca=True,
                       not_after=not_after)
    leaf = make_cert(serial=serial, issuer_cn=issuer_cn,
                     subject_cn=subject_cn, is_ca=False, crl_dps=crl_dps,
                     serial_len=serial_len, not_after=not_after)
    return leaf, issuer
