"""Batch leaf decoding + packing on top of the native library.

``decode_raw_batch`` takes one get-entries response worth of base64
strings and produces the packed device arrays plus per-entry issuer
DER — the whole-host fast path between the HTTP client and the device
pipeline. Falls back to the pure-Python leaf codec
(:mod:`ct_mapreduce_tpu.ingest.leaf`) entry by entry when the native
library is unavailable, with identical results (the conformance tests
assert byte equality).
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ct_mapreduce_tpu.native import load as load_native
from ct_mapreduce_tpu.telemetry import trace

# Status codes — keep in sync with ctmr_native.cpp.
OK = 0
BAD_B64 = 1
BAD_LEAF = 2
UNSUPPORTED = 3
NO_CHAIN = 4
TOO_LONG = 5  # cert exceeds pad_len — a wider redecode can clear it
ISSUER_TOO_LONG = 6  # issuer DER >= 2 MiB — cert packed fine; a wider
# redecode is futile, the entry goes straight to the exact host lane


@dataclass
class DecodedBatch:
    """Packed batch + per-entry metadata for one get-entries response."""

    data: np.ndarray  # uint8[n, pad_len]
    length: np.ndarray  # int32[n]
    timestamp_ms: np.ndarray  # int64[n]
    entry_type: np.ndarray  # int32[n]
    _issuers: Optional[list]  # chain[0] DER per entry; None = lazy
    status: np.ndarray  # int32[n]
    # Issuer grouping (vectorized sink bookkeeping): entries with the
    # same chain[0] DER share a group id; group_issuers[g] is that DER.
    # -1 = no issuer. None when the producer didn't compute groups.
    issuer_group: Optional[np.ndarray] = None  # int32[n]
    group_issuers: Optional[list] = None  # list[bytes]

    @property
    def issuers(self) -> list:
        """Per-entry issuer DER list (duplicates share one bytes
        object). Materialized lazily — the vectorized sink path works
        from ``issuer_group``/``group_issuers`` and never pays the
        per-entry list build."""
        if self._issuers is None:
            self._issuers = [
                self.group_issuers[g] if g >= 0 else None
                for g in self.issuer_group.tolist()
            ]
        return self._issuers

    def ok_mask(self) -> np.ndarray:
        return self.status == OK


@dataclass
class Sidecar:
    """Per-lane pre-parsed identity fields for a packed batch — the
    host half of the pre-parsed ingest lane.

    Extracted by the native scalar port of the device DER walker
    (``ctmr_extract_sidecars``), so semantics are bit-exact with
    :func:`ct_mapreduce_tpu.ops.der_kernel.parse_certs` on every lane:
    ``ok == 0`` means the walker itself would reject the lane (it
    falls back to the device-walker path), and on ``ok`` lanes every
    field equals the walker's output (pinned by
    tests/test_preparsed.py's mutation fuzz). All arrays length n;
    offsets index into the packed row (cert DER at offset 0).
    """

    ok: np.ndarray  # uint8[n] — 0: route through the device walker
    serial_off: np.ndarray  # int32[n]
    serial_len: np.ndarray  # int32[n]
    not_after_hour: np.ndarray  # int32[n] epoch-hour bucket
    is_ca: np.ndarray  # uint8[n]
    has_crldp: np.ndarray  # uint8[n]
    cn_off: np.ndarray  # int32[n] — first issuer-CN value window
    cn_len: np.ndarray  # int32[n] (0 = no CN found)
    issuer_off: np.ndarray  # int32[n] — full issuer Name TLV
    issuer_len: np.ndarray  # int32[n]
    spki_off: np.ndarray  # int32[n]
    spki_len: np.ndarray  # int32[n]
    crldp_off: np.ndarray  # int32[n] — CRLDP extnValue content window
    crldp_len: np.ndarray  # int32[n]


def resolve_threads(n: int, threads: Optional[int] = None) -> int:
    """Effective intra-chunk native thread count for an ``n``-lane call.

    An explicit ``threads`` > 0 is honored as given, clamped only to
    the lane count (tests exercise the threaded stitch on tiny
    batches). Otherwise: ``CTMR_DECODE_THREADS`` env, then the legacy
    ``CTMR_DECODE_WORKERS``, then ``os.cpu_count()`` — auto-sized so
    every chunk keeps >= 2048 lanes (below that the split overhead
    exceeds the decode it parallelizes).
    """
    import os

    if threads is not None and int(threads) > 0:
        return max(1, min(int(threads), max(int(n), 1)))
    t = int(os.environ.get("CTMR_DECODE_THREADS", "0") or 0)
    if t <= 0:
        t = int(os.environ.get("CTMR_DECODE_WORKERS", "0") or 0)
    if t <= 0:
        t = os.cpu_count() or 1
    t = max(1, min(t, n // 2048)) if n >= 4096 else 1
    return max(1, min(t, 256))


def extract_sidecars(data: np.ndarray, length: np.ndarray,
                     threads: Optional[int] = None) -> Optional[Sidecar]:
    with trace.span("native.extract_sidecars", cat="native",
                    entries=int(data.shape[0])):
        return _extract_sidecars(data, length, threads)


def _extract_sidecars(data: np.ndarray, length: np.ndarray,
                      threads: Optional[int] = None) -> Optional[Sidecar]:
    """Pre-parsed sidecars for packed rows ``uint8[n, pad]`` +
    ``int32[n]`` lengths, or None when the native library is
    unavailable (callers then stay on the device-walker lane —
    there is deliberately no Python fallback: the contract is
    walker-exactness, and the walker itself is always available).

    ``threads`` > 1 splits the lane range across the native worker
    pool; every lane's outputs are written by exactly one chunk, so
    results are byte-identical to the serial pass."""
    import os

    if os.environ.get("CTMR_NATIVE", "1") == "0":
        return None
    lib = load_native()
    if lib is None:
        return None
    n = int(data.shape[0])
    data = np.ascontiguousarray(data, np.uint8)
    length = np.ascontiguousarray(length, np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    out_u8 = [np.zeros((n,), np.uint8) for _ in range(3)]
    out_i32 = [np.zeros((n,), np.int32) for _ in range(11)]
    ok, is_ca, has_crldp = out_u8
    (serial_off, serial_len, not_after_hour, cn_off, cn_len,
     issuer_off, issuer_len, spki_off, spki_len,
     crldp_off, crldp_len) = out_i32
    t = resolve_threads(n, threads)
    fn, extra = lib.ctmr_extract_sidecars, ()
    if t > 1 and getattr(lib, "has_mt", False):
        fn, extra = lib.ctmr_extract_sidecars_mt, (t,)
    fn(
        n, data.ctypes.data_as(u8p), data.shape[1],
        length.ctypes.data_as(i32p),
        ok.ctypes.data_as(u8p),
        serial_off.ctypes.data_as(i32p), serial_len.ctypes.data_as(i32p),
        not_after_hour.ctypes.data_as(i32p),
        is_ca.ctypes.data_as(u8p), has_crldp.ctypes.data_as(u8p),
        cn_off.ctypes.data_as(i32p), cn_len.ctypes.data_as(i32p),
        issuer_off.ctypes.data_as(i32p), issuer_len.ctypes.data_as(i32p),
        spki_off.ctypes.data_as(i32p), spki_len.ctypes.data_as(i32p),
        crldp_off.ctypes.data_as(i32p), crldp_len.ctypes.data_as(i32p),
        *extra,
    )
    return Sidecar(
        ok=ok, serial_off=serial_off, serial_len=serial_len,
        not_after_hour=not_after_hour, is_ca=is_ca, has_crldp=has_crldp,
        cn_off=cn_off, cn_len=cn_len,
        issuer_off=issuer_off, issuer_len=issuer_len,
        spki_off=spki_off, spki_len=spki_len,
        crldp_off=crldp_off, crldp_len=crldp_len,
    )


def extract_scts(data: np.ndarray, length: np.ndarray,
                 threads: Optional[int] = None,
                 issuer_key_hash: Optional[np.ndarray] = None):
    """Embedded-SCT tuples for packed rows: a
    :class:`ct_mapreduce_tpu.verify.sct.SctBatch` — the host half of
    the signature-verification lane (status / RFC 6962 precert digest /
    log id / r / s per lane). ``issuer_key_hash``: uint8[n, 32]
    per-lane SHA-256(issuer SPKI) signed into each digest (None →
    all-zero lanes, no issuer chain). Native scanner when available
    (``ctmr_extract_scts_v2``, lane-range threaded like the sidecar
    pass), else the bit-identical pure-python mirror — unlike the
    sidecar extractor there IS a python fallback, because the verify
    lane has no device walker to fall back onto."""
    from ct_mapreduce_tpu.verify.sct import SctBatch, extract_scts_np

    with trace.span("native.extract_scts", cat="native",
                    entries=int(data.shape[0])):
        import os

        lib = (None if os.environ.get("CTMR_NATIVE", "1") == "0"
               else load_native())
        if lib is None or not getattr(lib, "has_sct", False):
            return extract_scts_np(data, length, issuer_key_hash)
        n = int(data.shape[0])
        data = np.ascontiguousarray(data, np.uint8)
        length = np.ascontiguousarray(length, np.int32)
        out = SctBatch.empty(n)
        if n == 0:
            return out
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        if issuer_key_hash is None:
            ikh_ptr = ctypes.cast(None, u8p)
        else:
            issuer_key_hash = np.ascontiguousarray(
                issuer_key_hash, np.uint8)
            if issuer_key_hash.shape != (n, 32):
                raise ValueError(
                    f"issuer_key_hash must be uint8[{n}, 32], got "
                    f"{issuer_key_hash.shape}")
            ikh_ptr = issuer_key_hash.ctypes.data_as(u8p)
        t = resolve_threads(n, threads)
        fn, extra = lib.ctmr_extract_scts_v2, ()
        if t > 1 and getattr(lib, "has_mt", False):
            fn, extra = lib.ctmr_extract_scts_v2_mt, (t,)
        fn(
            n, data.ctypes.data_as(u8p), data.shape[1],
            length.ctypes.data_as(i32p),
            ikh_ptr,
            out.ok.ctypes.data_as(u8p),
            out.digest.ctypes.data_as(u8p),
            out.log_id.ctypes.data_as(u8p),
            out.timestamp_ms.ctypes.data_as(i64p),
            out.r.ctypes.data_as(u8p),
            out.s.ctypes.data_as(u8p),
            out.hash_alg.ctypes.data_as(u8p),
            out.sig_alg.ctypes.data_as(u8p),
            *extra,
        )
        return out


def _assign_gid(gid_of: dict, group_issuers: list, der: bytes) -> int:
    """Accumulating DER→group-id assignment (shared by every producer
    that merges issuer groups)."""
    gid = gid_of.get(der)
    if gid is None:
        gid = gid_of[der] = len(group_issuers)
        group_issuers.append(der)
    return gid


def _concat_b64(strings: Sequence[str]) -> tuple[bytes, np.ndarray]:
    offs = np.zeros((len(strings) + 1,), np.int64)
    parts = []
    pos = 0
    for i, s in enumerate(strings):
        b = s.encode("ascii") if isinstance(s, str) else s
        parts.append(b)
        pos += len(b)
        offs[i + 1] = pos
    return b"".join(parts), offs


def decode_raw_batch(
    leaf_inputs: Sequence[str],
    extra_datas: Sequence[str],
    pad_len: int,
    workers: Optional[int] = None,
    threads: Optional[int] = None,
) -> DecodedBatch:
    with trace.span("native.decode_batch", cat="native",
                    entries=len(leaf_inputs), pad=int(pad_len)):
        return _decode_raw_batch(leaf_inputs, extra_datas, pad_len,
                                 workers=workers, threads=threads)


def _decode_raw_batch(
    leaf_inputs: Sequence[str],
    extra_datas: Sequence[str],
    pad_len: int,
    workers: Optional[int] = None,
    threads: Optional[int] = None,
) -> DecodedBatch:
    """Decode one get-entries response into packed device arrays.

    ``threads`` > 1 splits the batch across the native library's
    persistent worker pool — one ctypes call, lane ranges decoded in
    parallel inside C++ with the GIL released — so on multi-core TPU
    hosts decode scales with cores (it is the e2e ingest bottleneck at
    ~200k entries/s per core; a 10M entries/s chip needs tens of
    decode cores feeding it). ``workers`` is the legacy alias for the
    same knob (used when ``threads`` is unset). Default: the
    :func:`resolve_threads` policy (``CTMR_DECODE_THREADS`` env →
    ``CTMR_DECODE_WORKERS`` → ``os.cpu_count()``, bounded so each
    chunk keeps >= 2048 entries).

    Determinism: per-lane outputs are written by exactly one chunk
    into disjoint ranges, and per-chunk issuer groups merge by DER
    bytes in chunk (= lane) order, so the returned
    :class:`DecodedBatch` is byte-identical across thread counts
    (pinned by tests/test_decode_threads.py).
    """
    import os

    n = len(leaf_inputs)
    # CTMR_NATIVE=0 forces the pure-Python lane (read per call, not at
    # load: the bench's CPU smoke flips it mid-process to rebalance the
    # decode stage; results are byte-identical by the conformance suite).
    lib = (None if os.environ.get("CTMR_NATIVE", "1") == "0"
           else load_native())
    if lib is None:
        return _decode_python(leaf_inputs, extra_datas, pad_len)

    t = resolve_threads(n, threads if threads else workers)
    if not getattr(lib, "has_mt", False):
        t = 1  # stale prebuilt library without the pool entry points

    data = np.zeros((n, pad_len), np.uint8)
    length = np.zeros((n,), np.int32)
    ts = np.zeros((n,), np.int64)
    ety = np.zeros((n,), np.int32)
    status = np.zeros((n,), np.int32)
    out = (data, length, ts, ety, status)

    if t > 1:
        spans = _decode_native_mt(
            lib, leaf_inputs, extra_datas, pad_len, out, t)
        if spans is not None:
            # Merge per-chunk issuer groups by DER bytes in chunk
            # order (a handful per chunk — per-group work, never
            # per-entry). Chunks are contiguous lane ranges in lane
            # order, so the merged group order equals the serial
            # pass's first-appearance order.
            group = np.full((n,), -1, np.int32)
            group_issuers: list = []
            gid_of: dict = {}
            for (lo, hi), span in spans:
                c_group, c_issuers = _issuer_groups(hi - lo, *span)
                remap = np.full((len(c_issuers) + 1,), -1, np.int32)
                for g, der in enumerate(c_issuers):
                    remap[g] = _assign_gid(gid_of, group_issuers, der)
                group[lo:hi] = remap[c_group]
            return DecodedBatch(data, length, ts, ety, None, status,
                                issuer_group=group,
                                group_issuers=group_issuers)
        # A chunk's issuer slice overflowed (pathologically skewed
        # extra_data) — retry serial with the undivided buffer.

    span = _decode_native_into(lib, leaf_inputs, extra_datas, pad_len, out)
    if span is None:  # issuer scratch overflow — impossible by sizing
        return _decode_python(leaf_inputs, extra_datas, pad_len)
    group, group_issuers = _issuer_groups(n, *span)
    return DecodedBatch(data, length, ts, ety, None, status,
                        issuer_group=group, group_issuers=group_issuers)


def _issuer_groups(
    n: int,
    issuer_off: np.ndarray,
    issuer_len: np.ndarray,
    issuer_buf: np.ndarray,
) -> tuple:
    """Vectorized grouping of entries by issuer span.

    The native decoder dedups identical issuer DERs into shared
    (off, len) spans, so grouping is a numpy unique over the span ids
    — no per-entry byte hashing in Python."""
    has = issuer_len > 0
    # off < issuer_cap (< 2^42), len < 2^21 (pad-scale certs): the
    # combined key fits int64 losslessly.
    combo = issuer_off * (1 << 21) + issuer_len
    group = np.full((n,), -1, np.int32)
    if not has.any():
        return group, []
    uniq, inverse = np.unique(combo[has], return_inverse=True)
    group[has] = inverse.astype(np.int32)
    buf = issuer_buf.tobytes()
    group_issuers = [
        buf[int(c) >> 21 : (int(c) >> 21) + (int(c) & ((1 << 21) - 1))]
        for c in uniq
    ]
    return group, group_issuers



def _decode_native_into(
    lib,
    leaf_inputs: Sequence[str],
    extra_datas: Sequence[str],
    pad_len: int,
    out: tuple,
) -> Optional[tuple]:
    """Run the native decoder writing into caller-provided row views
    ``out = (data, length, ts, ety, status)``; returns the issuer span
    arrays ``(issuer_off, issuer_len, issuer_buf)`` (identical DERs
    share one span), or None on native scratch overflow."""
    n = len(leaf_inputs)
    data, length, ts, ety, status = out
    li_buf, li_off = _concat_b64(leaf_inputs)
    ed_buf, ed_off = _concat_b64(extra_datas)
    issuer_off = np.zeros((n,), np.int64)
    issuer_len = np.zeros((n,), np.int32)
    # Issuer chain certs are ~1-2 KB; extra_data is an upper bound.
    issuer_cap = max(len(ed_buf), 4096)
    issuer_buf = np.zeros((issuer_cap,), np.uint8)
    # Scratch must hold one decoded leaf_input + extra_data.
    max_li = int(np.max(np.diff(li_off))) if n else 0
    max_ed = int(np.max(np.diff(ed_off))) if n else 0
    scratch = np.zeros((max(max_li + max_ed + 64, 4096),), np.uint8)

    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    used = lib.ctmr_decode_entries(
        n,
        li_buf, li_off.ctypes.data_as(i64p),
        ed_buf, ed_off.ctypes.data_as(i64p),
        pad_len,
        data.ctypes.data_as(u8p), length.ctypes.data_as(i32p),
        ts.ctypes.data_as(i64p), ety.ctypes.data_as(i32p),
        issuer_buf.ctypes.data_as(u8p), issuer_cap,
        issuer_off.ctypes.data_as(i64p), issuer_len.ctypes.data_as(i32p),
        status.ctypes.data_as(i32p),
        scratch.ctypes.data_as(u8p), scratch.shape[0],
    )
    if used < 0:
        return None
    return issuer_off, issuer_len, issuer_buf[:used]


def _decode_native_mt(
    lib,
    leaf_inputs: Sequence[str],
    extra_datas: Sequence[str],
    pad_len: int,
    out: tuple,
    threads: int,
) -> Optional[list]:
    """One ``ctmr_decode_entries_mt`` call decoding ``threads``
    contiguous lane ranges in parallel on the native worker pool.
    Returns ``[((lo, hi), (issuer_off, issuer_len, issuer_buf))]`` per
    chunk (spans carry GLOBAL offsets into the shared buffer), or None
    when a chunk's issuer slice overflowed (caller retries serial)."""
    n = len(leaf_inputs)
    data, length, ts, ety, status = out
    li_buf, li_off = _concat_b64(leaf_inputs)
    ed_buf, ed_off = _concat_b64(extra_datas)
    issuer_off = np.zeros((n,), np.int64)
    issuer_len = np.zeros((n,), np.int32)
    # Chunk bounds mirror the C split exactly: lane [n*t//T, n*(t+1)//T).
    bounds = [(n * t) // threads for t in range(threads + 1)]
    # Each chunk's issuer slice must hold that chunk's chain bytes;
    # its base64 extra_data length is a safe upper bound on them.
    iss_each = max(
        4096,
        max(int(ed_off[bounds[t + 1]] - ed_off[bounds[t]])
            for t in range(threads)),
    )
    issuer_buf = np.zeros((threads * iss_each,), np.uint8)
    max_li = int(np.max(np.diff(li_off))) if n else 0
    max_ed = int(np.max(np.diff(ed_off))) if n else 0
    scratch_each = max(max_li + max_ed + 64, 4096)
    scratch = np.zeros((threads * scratch_each,), np.uint8)
    chunk_used = np.zeros((threads,), np.int64)

    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    rc = lib.ctmr_decode_entries_mt(
        n,
        li_buf, li_off.ctypes.data_as(i64p),
        ed_buf, ed_off.ctypes.data_as(i64p),
        pad_len,
        data.ctypes.data_as(u8p), length.ctypes.data_as(i32p),
        ts.ctypes.data_as(i64p), ety.ctypes.data_as(i32p),
        issuer_buf.ctypes.data_as(u8p), issuer_buf.shape[0],
        issuer_off.ctypes.data_as(i64p), issuer_len.ctypes.data_as(i32p),
        status.ctypes.data_as(i32p),
        scratch.ctypes.data_as(u8p), scratch_each,
        threads, chunk_used.ctypes.data_as(i64p),
    )
    if rc < 0:
        return None
    return [
        ((bounds[t], bounds[t + 1]),
         (issuer_off[bounds[t]:bounds[t + 1]],
          issuer_len[bounds[t]:bounds[t + 1]],
          issuer_buf))
        for t in range(threads)
        if bounds[t + 1] > bounds[t]
    ]


def pack_ders(ders: Sequence[bytes], pad_len: int,
              threads: Optional[int] = None):
    with trace.span("native.pack_ders", cat="native", entries=len(ders)):
        return _pack_ders(ders, pad_len, threads)


def _pack_ders(ders: Sequence[bytes], pad_len: int,
               threads: Optional[int] = None):
    """Pack pre-decoded DER blobs into the ``[n, pad_len]`` device
    layout via the native packer (parallel over lane ranges when
    ``threads`` > 1); returns ``(data, length, ok, packed_count)`` or
    None when the native library is unavailable."""
    import os

    if os.environ.get("CTMR_NATIVE", "1") == "0":
        return None
    lib = load_native()
    if lib is None:
        return None
    n = len(ders)
    blob = np.frombuffer(b"".join(ders) or b"\x00", np.uint8)
    off = np.zeros((n + 1,), np.int64)
    if n:
        off[1:] = np.cumsum([len(d) for d in ders])
    data = np.zeros((n, pad_len), np.uint8)
    length = np.zeros((n,), np.int32)
    ok = np.zeros((n,), np.uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    t = resolve_threads(n, threads)
    if t > 1 and getattr(lib, "has_mt", False):
        packed = lib.ctmr_pack_ders_mt(
            n, blob.ctypes.data_as(u8p), off.ctypes.data_as(i64p),
            pad_len,
            data.ctypes.data_as(u8p), length.ctypes.data_as(i32p),
            ok.ctypes.data_as(u8p), t,
        )
    else:
        packed = lib.ctmr_pack_ders(
            n, blob.ctypes.data_as(u8p), off.ctypes.data_as(i64p),
            pad_len,
            data.ctypes.data_as(u8p), length.ctypes.data_as(i32p),
            ok.ctypes.data_as(u8p),
        )
    return data, length, ok, int(packed)


def _decode_python(
    leaf_inputs: Sequence[str], extra_datas: Sequence[str], pad_len: int
) -> DecodedBatch:
    """Pure-Python fallback with identical semantics."""
    import base64
    import binascii

    from ct_mapreduce_tpu.ingest import leaf as leaflib

    n = len(leaf_inputs)
    data = np.zeros((n, pad_len), np.uint8)
    length = np.zeros((n,), np.int32)
    ts = np.zeros((n,), np.int64)
    ety = np.zeros((n,), np.int32)
    status = np.zeros((n,), np.int32)
    issuers: list[Optional[bytes]] = [None] * n
    for i in range(n):
        try:
            li = base64.b64decode(leaf_inputs[i], validate=True)
            ed = base64.b64decode(extra_datas[i] or "", validate=True)
        except (binascii.Error, ValueError):
            status[i] = BAD_B64
            continue
        try:
            e = leaflib.decode_entry(i, li, ed)
        except leaflib.LeafDecodeError as err:
            status[i] = (
                UNSUPPORTED if "unsupported" in str(err)
                or "unknown entry_type" in str(err) else BAD_LEAF
            )
            continue
        ts[i] = e.timestamp_ms
        ety[i] = e.entry_type
        if len(e.cert_der) > pad_len:
            status[i] = TOO_LONG
            continue
        data[i, : len(e.cert_der)] = np.frombuffer(e.cert_der, np.uint8)
        length[i] = len(e.cert_der)
        if not e.issuer_der:  # absent OR zero-length chain[0]
            status[i] = NO_CHAIN
        elif len(e.issuer_der) >= (1 << 21):
            # Native-path parity: pathological >=2 MiB issuer DERs are
            # routed down the exact host lane (span-packing bound). The
            # cert row stays packed, exactly like the native decoder
            # (which packs before its issuer-length check) — hence the
            # dedicated status: callers must not redecode wider for it.
            status[i] = ISSUER_TOO_LONG
        else:
            issuers[i] = e.issuer_der
    # Grouping for the vectorized sink path (dict-based — this is the
    # no-native fallback, already per-entry Python).
    group = np.full((n,), -1, np.int32)
    group_issuers: list = []
    gid_of: dict = {}
    for i, der in enumerate(issuers):
        if der is not None:
            group[i] = _assign_gid(gid_of, group_issuers, der)
    return DecodedBatch(data, length, ts, ety, issuers, status,
                        issuer_group=group, group_issuers=group_issuers)
